"""Live trading adapters (hard-gated; simulation never imports these).

The only implementation is the OANDA v20 REST broker
(`gymfx_tpu.live.oanda`), the working twin of the reference's
`bt.stores.OandaStore` broker (reference broker_plugins/oanda_broker.py:58-63).
"""
from gymfx_tpu.live.oanda import (
    DecisionRecord,
    FeedStaleError,
    OandaLiveBroker,
    PolicyDecisionService,
    TargetOrderRouter,
)

__all__ = [
    "DecisionRecord",
    "FeedStaleError",
    "OandaLiveBroker",
    "PolicyDecisionService",
    "TargetOrderRouter",
]
