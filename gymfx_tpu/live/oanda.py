"""Live OANDA order routing over the v20 REST API.

The reference's gated broker hands live trading to backtrader's
``OandaStore`` (reference broker_plugins/oanda_broker.py:58-63).  This
framework has no backtrader engine to hand anything to, so the live
surface is built the framework's way instead: the strategy kernels
already express every decision as a *pending target* (signed units +
optional bracket prices — the decision stream the replay engine
re-executes, simulation/crosscheck.py), and ``TargetOrderRouter`` maps
exactly that stream onto OANDA order payloads.  One adapter serves
every strategy kernel, like the crosscheck does.

``OandaLiveBroker`` is a dependency-free v20 client (urllib; the image
has no ``requests``).  The HTTP transport is injectable so the whole
surface is testable offline — tests drive it with a fake transport and
assert the exact payloads (tests/test_live_oanda.py); nothing here is
imported by the simulation path.

Endpoints used (OANDA v20 public API):
  GET  /v3/accounts/{id}/summary
  GET  /v3/accounts/{id}/openPositions
  GET  /v3/accounts/{id}/pricing?instruments=...
  POST /v3/accounts/{id}/orders                  (MARKET + brackets)
  PUT  /v3/accounts/{id}/positions/{inst}/close
"""
from __future__ import annotations

import json
import time
from collections import deque
from typing import Any, Callable, Dict, NamedTuple, Optional

import numpy as np

PRACTICE_HOST = "https://api-fxpractice.oanda.com"
LIVE_HOST = "https://api-fxtrade.oanda.com"

# transport: (method, url, headers, body-or-None) -> (status, response body)
Transport = Callable[[str, str, Dict[str, str], Optional[bytes]], Any]


def _urllib_transport(method: str, url: str, headers: Dict[str, str],
                      body: Optional[bytes], timeout: float = 30.0):
    import urllib.error
    import urllib.request

    req = urllib.request.Request(url, data=body, headers=headers,
                                 method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:  # nosec B310
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        # non-2xx must flow back as (status, body) so _request raises the
        # module's own OandaApiError with OANDA's errorMessage attached
        return e.code, e.read()


class OandaApiError(RuntimeError):
    def __init__(self, status: int, body: str):
        super().__init__(f"OANDA API error {status}: {body[:500]}")
        self.status = status
        self.body = body


class OandaTransportError(RuntimeError):
    """The venue's response was unusable (e.g. truncated JSON) — the
    request MAY have been processed, so callers must treat this like a
    timeout: retry only through an idempotency-checked path."""


def _is_transient(exc: BaseException) -> bool:
    """Failures worth retrying / counting toward the circuit breaker:
    5xx (venue-side), timeouts and connection drops (OSError covers
    socket.timeout, ConnectionError and urllib's URLError), and
    unusable response bodies.  4xx are the caller's bug — retrying
    cannot fix them and they must not trip the breaker."""
    if isinstance(exc, OandaApiError):
        return exc.status >= 500
    return isinstance(exc, (OSError, TimeoutError, OandaTransportError))


class OandaLiveBroker:
    """Minimal v20 REST trading client.

    Quantities follow OANDA conventions: signed integer units (positive
    buys, negative sells); prices are decimal strings at the
    instrument's precision.

    Resilience (all optional, default off so the bare client behaves
    exactly as before):

      ``retry_policy``  transient failures (5xx, timeout, connection
          drop, truncated body) on IDEMPOTENT calls (GET) retry with
          exponential backoff + jitter.  Non-idempotent calls (POST
          orders, PUT close) are NEVER retried here — a lost response
          does not mean an unprocessed order, so their retry belongs in
          :class:`TargetOrderRouter`, whose per-attempt client-id lookup
          makes the resubmit dedup-safe.
      ``breaker``  a :class:`~gymfx_tpu.resilience.retry.CircuitBreaker`
          gating every call; transient failures count toward the trip
          threshold, 4xx do not (they are the caller's bug).  Emergency
          calls (the router's flatten-and-halt) bypass it entirely.
      ``retry_budget``  shared cross-call retry cap.
    """

    def __init__(self, token: str, account_id: str, *,
                 practice: bool = True,
                 transport: Optional[Transport] = None,
                 retry_policy: Optional[Any] = None,
                 breaker: Optional[Any] = None,
                 retry_budget: Optional[Any] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 rng: Optional[Any] = None):
        if not token or not account_id:
            raise ValueError("OandaLiveBroker requires token and account_id")
        self.account_id = account_id
        self._base = (PRACTICE_HOST if practice else LIVE_HOST)
        self._headers = {
            "Authorization": f"Bearer {token}",
            "Content-Type": "application/json",
        }
        if transport is None:
            timeout = float(getattr(retry_policy, "timeout", 30.0) or 30.0)
            transport = lambda m, u, h, b: _urllib_transport(  # noqa: E731
                m, u, h, b, timeout=timeout
            )
        self._transport = transport
        self.retry_policy = retry_policy
        self.breaker = breaker
        self.retry_budget = retry_budget
        self._sleep = sleep
        self._rng = rng

    # ------------------------------------------------------------------
    def _request(self, method: str, path: str,
                 payload: Optional[Dict[str, Any]] = None, *,
                 emergency: bool = False) -> Dict[str, Any]:
        """One API call under the resilience wrappers.  ``emergency``
        (the router's flatten-and-halt close) skips the circuit breaker
        in BOTH directions — an open breaker must not block the flatten,
        and the flatten's own failure must not re-trip it."""
        body = json.dumps(payload).encode() if payload is not None else None
        url = f"{self._base}{path}"

        def attempt() -> Dict[str, Any]:
            status, raw = self._transport(
                method, url, dict(self._headers), body
            )
            text = (
                raw.decode() if isinstance(raw, (bytes, bytearray)) else str(raw)
            )
            if not 200 <= int(status) < 300:
                raise OandaApiError(int(status), text)
            try:
                return json.loads(text) if text else {}
            except json.JSONDecodeError as e:
                raise OandaTransportError(
                    f"unusable response body from {method} {path}: {e}"
                ) from e

        breaker = None if emergency else self.breaker
        if breaker is not None:
            breaker.allow()
        try:
            if self.retry_policy is not None and method == "GET":
                from gymfx_tpu.resilience.retry import RetryError, retry_call

                try:
                    result = retry_call(
                        attempt, policy=self.retry_policy,
                        retry_on_exc=_is_transient,
                        budget=self.retry_budget,
                        sleep=self._sleep, rng=self._rng,
                    )
                except RetryError as e:
                    # surface the final underlying failure, same type
                    # the unretried path would raise
                    raise e.last from e
            else:
                result = attempt()
        except BaseException as exc:
            if breaker is not None and _is_transient(exc):
                breaker.record_failure()
            raise
        if breaker is not None:
            breaker.record_success()
        return result

    # ------------------------------------------------------------------
    def account_summary(self) -> Dict[str, Any]:
        return self._request(
            "GET", f"/v3/accounts/{self.account_id}/summary"
        )["account"]

    def open_positions(self) -> Dict[str, float]:
        """{instrument: net signed units} for every open position."""
        data = self._request(
            "GET", f"/v3/accounts/{self.account_id}/openPositions"
        )
        out: Dict[str, float] = {}
        for pos in data.get("positions", []):
            units = float(pos.get("long", {}).get("units", 0) or 0) + float(
                pos.get("short", {}).get("units", 0) or 0
            )
            out[pos["instrument"]] = units
        return out

    def pricing(self, instrument: str) -> Dict[str, float]:
        data = self._request(
            "GET",
            f"/v3/accounts/{self.account_id}/pricing?instruments={instrument}",
        )
        price = data["prices"][0]
        return {
            "bid": float(price["bids"][0]["price"]),
            "ask": float(price["asks"][0]["price"]),
        }

    def market_order(self, instrument: str, units: float, *,
                     stop_loss: Optional[float] = None,
                     take_profit: Optional[float] = None,
                     price_precision: int = 5,
                     client_id: Optional[str] = None) -> Dict[str, Any]:
        """Market order for signed ``units``; brackets attach as
        on-fill orders (the scan engine's entry-with-brackets flow).

        ``units`` is rounded to the nearest integer (OANDA units are
        integral); an order that rounds to zero is refused loudly rather
        than silently dropped.  ``client_id`` becomes the order's
        ``clientExtensions.id`` — OANDA rejects a duplicate client id,
        so a deterministic id per decision makes a retry after a
        transport timeout surface as an API error instead of a second
        fill."""
        int_units = int(round(float(units)))
        if int_units == 0:
            raise ValueError(
                f"market_order units {units!r} round to zero — OANDA "
                "units are integral; refuse rather than silently no-op"
            )
        order: Dict[str, Any] = {
            "type": "MARKET",
            "instrument": instrument,
            "units": str(int_units),
            "timeInForce": "FOK",
            "positionFill": "DEFAULT",
        }
        if client_id:
            order["clientExtensions"] = {"id": str(client_id)}
        if stop_loss:
            order["stopLossOnFill"] = {
                "price": f"{stop_loss:.{price_precision}f}"
            }
        if take_profit:
            order["takeProfitOnFill"] = {
                "price": f"{take_profit:.{price_precision}f}"
            }
        return self._request(
            "POST", f"/v3/accounts/{self.account_id}/orders",
            {"order": order},
        )

    def order_by_client_id(self, client_id: str) -> Optional[Dict[str, Any]]:
        """The order previously submitted with ``clientExtensions.id``
        ``client_id`` in ANY state (pending, filled, cancelled), or
        ``None`` when the account has never seen that id — OANDA's
        ``@``-prefixed orderSpecifier lookup, with the transactions
        stream as the 404 fallback (some v20 builds 404 the @-lookup
        for market orders that filled and left the order book; the
        transaction log is the ground truth)."""
        from urllib.parse import quote

        try:
            return self._request(
                "GET",
                f"/v3/accounts/{self.account_id}/orders/"
                f"@{quote(str(client_id), safe='')}",
            ).get("order")
        except OandaApiError as e:
            if e.status != 404:
                raise
        return self._order_from_transactions(str(client_id))

    def _order_from_transactions(self, client_id: str) -> Optional[Dict[str, Any]]:
        """Best-effort reconstruction of an order's state from
        ``GET .../transactions/sinceid``: matching MARKET_ORDER /
        ORDER_FILL / ORDER_CANCEL transactions collapse into the
        ``state`` field the router's dedup check reads.  Returns None
        when the stream shows no trace of the id (never submitted) or
        the fallback itself fails (the router then treats the decision
        as unsubmitted — the same conclusion a plain 404 produced before
        this fallback existed)."""
        try:
            data = self._request(
                "GET",
                f"/v3/accounts/{self.account_id}/transactions/sinceid?id=1",
            )
        except (OandaApiError, OandaTransportError):
            return None
        matches = []
        for txn in data.get("transactions", []) or []:
            ext_id = (txn.get("clientExtensions") or {}).get("id")
            if client_id in (ext_id, txn.get("clientOrderID")):
                matches.append(txn)
        if not matches:
            return None
        types = {t.get("type") for t in matches}
        if "ORDER_FILL" in types:
            state = "FILLED"
        elif "ORDER_CANCEL" in types:
            state = "CANCELLED"
        else:
            state = "PENDING"
        return {
            "state": state,
            "clientExtensions": {"id": client_id},
            "transactions": matches,
        }

    def close_position(self, instrument: str, *,
                       client_id: Optional[str] = None,
                       emergency: bool = False) -> Dict[str, Any]:
        """Flatten the instrument (both sides, like the scan engine's
        force-flat).  ``client_id`` attaches to the venue-generated
        market order(s) so a retried flatten decision is discoverable
        via :meth:`order_by_client_id` (net positions only ever hold one
        side, so the shared id cannot collide with itself).
        ``emergency`` bypasses the circuit breaker — the router's
        flatten-and-halt must go out even when the breaker is open."""
        payload: Dict[str, Any] = {"longUnits": "ALL", "shortUnits": "ALL"}
        if client_id:
            ext = {"id": str(client_id)}
            payload["longClientExtensions"] = ext
            payload["shortClientExtensions"] = ext
        return self._request(
            "PUT",
            f"/v3/accounts/{self.account_id}/positions/{instrument}/close",
            payload, emergency=emergency,
        )


class RouterHaltedError(RuntimeError):
    """The router is in flatten-and-halt degraded mode (circuit breaker
    tripped): it flattened the book (best-effort) and refuses further
    submissions until a human (or supervisor process) resets it."""


class TargetOrderRouter:
    """Bridge from the framework's decision stream to live orders.

    The strategy kernels emit ``(pending_active, pending_target,
    pending_sl, pending_tp)`` each bar — the same stream the replay
    engine re-executes.  ``submit_target`` turns one decision into the
    minimal OANDA action: the units DELTA as a market order (with
    brackets on opening orders), or a position close when the target is
    flat.  Idempotent on no-ops (target == current).

    Retry safety: positions are reconciled (re-read) on every call, so
    a retry after the server accepted the previous order recomputes a
    zero delta once the fill is visible.  For the window before it is
    visible, every order carries a ``clientExtensions`` id, and when
    the caller supplies a ``decision_id`` (the bar index / timestamp of
    the decision) the router LOOKS UP that id on the account before
    submitting — OANDA's ``@client-id`` orderSpecifier finds the order
    in any state, including already-filled FOK market orders, so a
    blind resubmit of the same decision returns the original order
    instead of double-filling.  (The id alone is not enough: OANDA only
    enforces client-id uniqueness among PENDING orders, and a filled
    market order is no longer pending.)  Without an explicit
    ``decision_id`` the router falls back to a session-unique uuid-
    salted sequence — unique, but NOT retry-safe across callers:
    duplicate-order protection requires the caller's ``decision_id``.

    Units contract: live OANDA units are integral.  A fractional
    ``target_units`` (beyond float noise) is refused loudly — sizing
    kernels that emit sub-unit targets must be scaled before routing
    live, never silently under-traded."""

    def __init__(self, broker: OandaLiveBroker, instrument: str, *,
                 price_precision: int = 5,
                 client_id_prefix: str = "gymfx",
                 retry_policy: Optional[Any] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 rng: Optional[Any] = None):
        self.broker = broker
        self.instrument = instrument
        self.price_precision = int(price_precision)
        self.client_id_prefix = str(client_id_prefix)
        self.retry_policy = retry_policy
        self._sleep = sleep
        self._rng = rng
        self.halted = False
        self.halt_reason: Optional[str] = None
        self.flatten_error: Optional[BaseException] = None
        import uuid

        self._session_tag = uuid.uuid4().hex[:8]
        self._decision_seq = 0
        # the breaker (when the broker carries one) trips the router
        # into flatten-and-halt; attach AFTER construction so the
        # breaker can be shared/configured independently
        if getattr(broker, "breaker", None) is not None:
            broker.breaker.on_trip = self._flatten_and_halt

    # ------------------------------------------------------------------
    def _flatten_and_halt(self) -> None:
        """Degraded mode: one best-effort emergency flatten (bypassing
        the now-open breaker — it would refuse the flatten itself),
        then refuse every further submission.  The flatten's own
        failure is recorded, not raised: halting must always succeed."""
        if self.halted:
            return
        self.halted = True
        self.halt_reason = "circuit breaker tripped"
        try:
            self.broker.close_position(
                self.instrument,
                client_id=(
                    f"{self.client_id_prefix}-{self.instrument}-halt-"
                    f"{self._session_tag}"
                ),
                emergency=True,
            )
        except Exception as exc:  # noqa: BLE001 - recorded for operators
            self.flatten_error = exc

    def reset_halt(self) -> None:
        """Operator acknowledgment: leave degraded mode (the breaker
        still governs whether calls actually go through)."""
        self.halted = False
        self.halt_reason = None
        self.flatten_error = None

    # ------------------------------------------------------------------
    def submit_target(self, target_units: float, *,
                      stop_loss: Optional[float] = None,
                      take_profit: Optional[float] = None,
                      decision_id: Optional[str] = None) -> Optional[Dict[str, Any]]:
        if self.halted:
            raise RouterHaltedError(
                f"order router halted ({self.halt_reason}); book was "
                "flattened — reset_halt() after resolving the outage"
            )
        rounded_target = round(float(target_units))
        if abs(float(target_units) - rounded_target) > 1e-6:
            raise ValueError(
                f"target_units {target_units!r} is fractional — live "
                "OANDA units are integral; scale the kernel's sizing "
                "before routing live (integral-units contract)"
            )
        explicit_decision = decision_id is not None
        if decision_id is None:
            self._decision_seq += 1
            decision_id = f"{self._session_tag}-{self._decision_seq}"
            # under a retry policy the generated id is promoted to a
            # real decision id: it is minted ONCE per call, so the
            # retry attempts dedup against each other via the lookup
            explicit_decision = self.retry_policy is not None
        client_id = f"{self.client_id_prefix}-{self.instrument}-{decision_id}"

        def attempt() -> Optional[Dict[str, Any]]:
            return self._submit_once(
                rounded_target, client_id, explicit_decision,
                stop_loss=stop_loss, take_profit=take_profit,
            )

        from gymfx_tpu.resilience.retry import CircuitOpenError

        try:
            if self.retry_policy is None:
                return attempt()
            from gymfx_tpu.resilience.retry import RetryError, retry_call

            try:
                # the WHOLE reconcile -> lookup -> submit sequence is
                # the retry unit: re-reading positions and looking up
                # the client id first is what makes resubmitting a
                # non-idempotent order safe (a fill that happened but
                # whose response was lost is found, not repeated)
                return retry_call(
                    attempt, policy=self.retry_policy,
                    retry_on_exc=_is_transient,
                    sleep=self._sleep, rng=self._rng,
                )
            except RetryError as e:
                raise e.last from e
        except CircuitOpenError as exc:
            # the breaker's on_trip already flattened; a call landing
            # on an ALREADY-open breaker still needs to surface halt
            self._flatten_and_halt()
            raise RouterHaltedError(
                f"order router halted ({exc}); book was flattened — "
                "reset_halt() after resolving the outage"
            ) from exc

    def _submit_once(self, rounded_target: int, client_id: str,
                     explicit_decision: bool, *,
                     stop_loss: Optional[float],
                     take_profit: Optional[float]) -> Optional[Dict[str, Any]]:
        current = self.broker.open_positions().get(self.instrument, 0.0)
        delta = rounded_target - current
        if abs(delta) < 0.5:
            return None
        if explicit_decision:
            prior = self.broker.order_by_client_id(client_id)
            # a CANCELLED prior (FOK orders cancel routinely on missed
            # liquidity) never traded and releases its client id on
            # OANDA's side, so the decision is retried; any other state
            # (pending / triggered / filled) means the decision reached
            # the book — return it instead of double-submitting.  The
            # lookup runs for FLATTEN decisions too: close_position's
            # venue-generated market orders carry the same id.
            if prior is not None and prior.get("state") != "CANCELLED":
                return {"already_submitted": prior}
        if rounded_target == 0:
            return self.broker.close_position(
                self.instrument, client_id=client_id
            )
        return self.broker.market_order(
            self.instrument, delta,
            stop_loss=stop_loss, take_profit=take_profit,
            price_precision=self.price_precision,
            client_id=client_id,
        )


class FeedStaleError(RuntimeError):
    """The live bar feed went stale: the gap since the previous bar
    exceeded ``feed_stale_after_s``, so a decision on the current
    observation window would act on old data."""

    def __init__(self, age_s: float, threshold_s: float):
        super().__init__(
            f"bar feed stale: {age_s:.1f}s since the previous bar "
            f"(feed_stale_after_s={threshold_s:g})"
        )
        self.age_s = float(age_s)
        self.threshold_s = float(threshold_s)


class DecisionRecord(NamedTuple):
    """Audit row for one serve decision.  ``source`` is ``"model"`` for
    real engine output or ``"fallback"`` for a synthetic degraded-mode
    decision; fallback rows carry the ``reason`` (``shed`` / ``deadline``
    / ``breaker_open`` / ``batcher_closed`` / ``dispatch_error`` /
    ``stale_feed``) so downstream reconciliation can tell a routed
    target that came from the policy apart from one the overload
    machinery synthesized."""

    seq: int              # 1-based decide() counter
    bar: int              # session bar cursor at decision time
    action: int           # the env action that was (or would be) routed
    source: str           # "model" | "fallback"
    reason: Optional[str]  # None for model decisions


def _overload_reason(exc: BaseException) -> str:
    from gymfx_tpu.resilience.retry import CircuitOpenError
    from gymfx_tpu.serve.overload import (
        BatcherClosedError,
        DeadlineExceeded,
        ShedError,
    )

    if isinstance(exc, ShedError):
        return "shed"
    if isinstance(exc, DeadlineExceeded):
        return "deadline"
    if isinstance(exc, CircuitOpenError):
        return "breaker_open"
    if isinstance(exc, BatcherClosedError):
        return "batcher_closed"
    return "dispatch_error"


class PolicyDecisionService:
    """Warm policy serving glued to a :class:`TargetOrderRouter`.

    The pre-engine live loop would have jit-traced the policy on the
    FIRST market tick — a multi-second stall exactly when latency
    matters most.  This service instead boots the serving stack at
    router construction time:

      * the AOT-compiled bucket ladder (serve/engine.py) compiles and
        executes every bucket during ``__init__`` — after boot the
        decision path never traces (``engine.late_compiles`` stays 0,
        asserted by tests/test_live_serve.py);
      * each bar is featurized on the host through the O(1) scaler path
        (serve/features.py), producing observations bit-identical to
        the training env's;
      * the greedy decision is mapped to a pending target (signed
        units) and routed through ``router.submit_target`` with a
        per-bar decision id, inheriting the router's idempotent-resubmit
        and halt semantics.

    Action mapping (the env's discrete action set, core/env.py):
    1 -> long ``+units``, 2 -> short ``-units``, 3 -> flat 0,
    0 -> hold (keep the current target; nothing is routed).
    Continuous policies are already thresholded to {0, 1, 2} by the
    engine with the env's own coercion threshold.

    Overload resilience (docs/serving.md, "Overload behavior"): engine
    dispatch runs behind a serving :class:`CircuitBreaker` (or through
    an admission-controlled ``batcher``), and when the serving path
    sheds, misses a deadline, trips the breaker, or the bar feed goes
    stale (``feed_stale_after_s``), the configured ``serve_fallback``
    policy produces a SYNTHETIC decision instead — ``hold`` keeps the
    current pending target (no venue traffic), ``flat`` routes to flat,
    ``reject`` re-raises the typed error.  Every decision (model or
    fallback) appends a tagged :class:`DecisionRecord`, so downstream
    reconciliation always knows which routed targets were synthetic.
    """

    def __init__(
        self,
        config: Dict[str, Any],
        router: "TargetOrderRouter",
        *,
        bundle: Any = None,
        params: Any = None,
        env: Any = None,
        units: Optional[float] = None,
        batcher: Any = None,
        breaker: Any = None,
        clock: Callable[[], float] = time.monotonic,
        telemetry: Any = None,
    ):
        from gymfx_tpu.serve.config import serve_config_from
        from gymfx_tpu.serve.engine import engine_from_config
        from gymfx_tpu.serve.features import BarFeaturizer, make_host_encoder

        if bundle is None:
            # warm boot: every ladder bucket AOT-compiles and runs once
            # here, before the first market tick exists
            bundle = engine_from_config(
                config, params=params, env=env, warmup=True
            )
        self.bundle = bundle
        self.engine = bundle.engine
        self.router = router
        self.featurizer = BarFeaturizer.from_environment(bundle.env)
        self.session = self.featurizer.new_session()
        self._encode = make_host_encoder(
            bundle.policy_name, bundle.env.cfg.window_size, bundle.obs_spec
        )
        self._carry = (
            self.engine.initial_carry() if self.engine.recurrent else None
        )
        self.units = float(
            units if units is not None else bundle.env.params.position_size
        )
        self.target_units = 0.0  # last routed pending target
        self.decisions = 0

        scfg = serve_config_from(config)
        self.fallback_policy = scfg.fallback
        self.deadline_ms = scfg.deadline_ms
        self.feed_stale_after_s = scfg.feed_stale_after_s
        # dispatch path: an injected admission-controlled MicroBatcher
        # (multi-session serving; it carries its own breaker), else
        # direct engine dispatch behind the serving breaker
        self.batcher = batcher
        if breaker is None and batcher is None and scfg.breaker_threshold:
            from gymfx_tpu.resilience.retry import CircuitBreaker

            breaker = CircuitBreaker(
                scfg.breaker_threshold, scfg.breaker_recovery_s
            )
        self.breaker = breaker
        self._clock = clock
        self._last_bar_at: Optional[float] = None
        self.fallback_count = 0
        self.feed_stale_count = 0
        self.last_fallback_reason: Optional[str] = None
        self.decision_records = deque(maxlen=100_000)
        # telemetry (gymfx_tpu.telemetry.Telemetry, None = off): decision
        # counters by source/reason, a span per engine dispatch, the
        # service breaker bound as registry callback gauges, and —
        # when telemetry_http_port is configured — the /metrics +
        # /healthz endpoint over this service's health()
        self.telemetry = telemetry
        from gymfx_tpu.telemetry import null_tracer

        self._tracer = (
            telemetry.tracer if telemetry is not None else null_tracer()
        )
        self._decisions_ctr = self._fallback_ctr = None
        if telemetry is not None:
            reg = telemetry.registry
            self._decisions_ctr = reg.counter(
                "gymfx_live_decisions_total",
                "Serve decisions by source (model vs synthetic fallback)",
                labels=("source",),
            )
            self._fallback_ctr = reg.counter(
                "gymfx_live_fallback_total",
                "Degraded-mode decisions by fallback reason",
                labels=("reason",),
            )
            if self.breaker is not None:
                from gymfx_tpu.telemetry import register_resilience

                register_resilience(reg, breaker=self.breaker, name="live")
            telemetry.start_http(health_fn=self.health)

    # ------------------------------------------------------------------
    def feed_age_s(self, now: Optional[float] = None) -> Optional[float]:
        """Seconds since the previous bar arrived (None before the
        first bar)."""
        if self._last_bar_at is None:
            return None
        return (self._clock() if now is None else now) - self._last_bar_at

    def health(self) -> Dict[str, Any]:
        """One consistent health view across the service, its batcher
        and the registry-bound resilience objects — the /healthz payload
        when telemetry runs the HTTP endpoint."""
        out: Dict[str, Any] = {
            "status": "ok",
            "decisions": self.decisions,
            "fallback_count": self.fallback_count,
            "feed_stale_count": self.feed_stale_count,
            "last_fallback_reason": self.last_fallback_reason,
            "feed_age_s": self.feed_age_s(),
            "breaker_state": (
                None if self.breaker is None else self.breaker.state
            ),
        }
        if self.batcher is not None and hasattr(self.batcher, "health"):
            out["batcher"] = self.batcher.health()
        if self.telemetry is not None:
            from gymfx_tpu.telemetry import resilience_snapshot

            out["resilience"] = resilience_snapshot(self.telemetry.registry)
        return out

    def _model_decide(self, row):
        """One engine dispatch through the configured path; raises the
        typed overload errors (serve/overload.py) on the brownout
        paths."""
        if self.batcher is not None:
            fut = self.batcher.submit(
                row, self._carry, deadline_ms=self.deadline_ms
            )
            # the deadline machinery resolves the future; the extra
            # slack only guards against a wedged worker thread
            timeout = (
                None
                if self.deadline_ms is None
                else self.deadline_ms / 1e3 + 30.0
            )
            return fut.result(timeout=timeout)
        if self.breaker is not None:
            self.breaker.allow()  # raises CircuitOpenError while open
        try:
            decision = self.engine.decide(row, self._carry)
        except Exception:
            if self.breaker is not None:
                self.breaker.record_failure()
            raise
        if self.breaker is not None:
            self.breaker.record_success()
        return decision

    def _fallback_decision(self, reason: str, exc: BaseException):
        """Synthesize the degraded-mode decision (or re-raise under the
        ``reject`` policy).  The recurrent carry is left untouched —
        the model never saw this bar."""
        self.last_fallback_reason = reason
        if self.fallback_policy == "reject":
            raise exc
        self.fallback_count += 1
        from gymfx_tpu.serve.engine import Decision

        action = 0 if self.fallback_policy == "hold" else 3
        # NaN value/actor_out: a synthetic decision has no model output
        # to audit, and NaN is loud in any downstream aggregation
        return Decision(
            np.int32(action),
            np.float32(np.nan),
            np.float32(np.nan),
            self._carry,
        )

    def decide(
        self,
        close: float,
        features: Any = None,
        *,
        equity_delta: float = 0.0,
    ):
        """Featurize one bar and run the warm engine on it (no routing).

        Returns the serve Decision row; recurrent carry streams in the
        service between calls.  On a stale feed or a serving-path
        overload error the decision comes from the fallback policy and
        is tagged in :attr:`decision_records`."""
        now = self._clock()
        stale_age = (
            None
            if (self.feed_stale_after_s is None or self._last_bar_at is None)
            else now - self._last_bar_at
        )
        stale = (
            stale_age is not None and stale_age > self.feed_stale_after_s
        )
        self._last_bar_at = now
        self.session.push(close, features)
        obs = self.session.obs(
            pos_sign=float(
                (self.target_units > 0) - (self.target_units < 0)
            ),
            equity_delta=equity_delta,
        )
        row = self._encode(obs)
        source, reason = "model", None
        if stale:
            # the window behind this bar has a gap the policy never
            # trained on — decide via the fallback, not the model
            self.feed_stale_count += 1
            source, reason = "fallback", "stale_feed"
            decision = self._fallback_decision(
                reason, FeedStaleError(stale_age, self.feed_stale_after_s)
            )
        else:
            from gymfx_tpu.serve.overload import OVERLOAD_ERRORS

            try:
                with self._tracer.span(
                    "serve/dispatch",
                    path="batcher" if self.batcher is not None else "direct",
                ):
                    decision = self._model_decide(row)
                if self.engine.recurrent:
                    self._carry = decision.carry
            except OVERLOAD_ERRORS as exc:
                source, reason = "fallback", _overload_reason(exc)
                decision = self._fallback_decision(reason, exc)
            except Exception as exc:  # dispatch fault before the breaker opens
                source, reason = "fallback", "dispatch_error"
                decision = self._fallback_decision(reason, exc)
        self.decisions += 1
        self.decision_records.append(
            DecisionRecord(
                seq=self.decisions,
                bar=int(self.session.bars_seen),
                action=int(decision.action),
                source=source,
                reason=reason,
            )
        )
        if self._decisions_ctr is not None:
            self._decisions_ctr.inc(source=source)
            if reason is not None:
                self._fallback_ctr.inc(reason=reason)
        return decision

    def decide_and_route(
        self,
        close: float,
        features: Any = None,
        *,
        equity_delta: float = 0.0,
        stop_loss: Optional[float] = None,
        take_profit: Optional[float] = None,
        decision_id: Optional[str] = None,
    ):
        """One live tick: featurize -> decide -> route the new target.

        Returns ``(decision, order)``; ``order`` is None when the
        decision holds the current target (nothing to route) or the
        router found the book already at target."""
        decision = self.decide(close, features, equity_delta=equity_delta)
        action = int(decision.action)
        if action == 1:
            target = self.units
        elif action == 2:
            target = -self.units
        elif action == 3:
            target = 0.0
        else:  # hold: keep the current pending target, no order traffic
            return decision, None
        if decision_id is None:
            # bar cursor is unique per session, so resubmits of the same
            # decision dedup through the router's client-id lookup
            decision_id = f"bar{self.session.bars_seen}"
        order = self.router.submit_target(
            target,
            stop_loss=stop_loss,
            take_profit=take_profit,
            decision_id=decision_id,
        )
        self.target_units = target
        return decision, order
