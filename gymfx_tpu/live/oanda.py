"""Live OANDA order routing over the v20 REST API.

The reference's gated broker hands live trading to backtrader's
``OandaStore`` (reference broker_plugins/oanda_broker.py:58-63).  This
framework has no backtrader engine to hand anything to, so the live
surface is built the framework's way instead: the strategy kernels
already express every decision as a *pending target* (signed units +
optional bracket prices — the decision stream the replay engine
re-executes, simulation/crosscheck.py), and ``TargetOrderRouter`` maps
exactly that stream onto OANDA order payloads.  One adapter serves
every strategy kernel, like the crosscheck does.

``OandaLiveBroker`` is a dependency-free v20 client (urllib; the image
has no ``requests``).  The HTTP transport is injectable so the whole
surface is testable offline — tests drive it with a fake transport and
assert the exact payloads (tests/test_live_oanda.py); nothing here is
imported by the simulation path.

Endpoints used (OANDA v20 public API):
  GET  /v3/accounts/{id}/summary
  GET  /v3/accounts/{id}/openPositions
  GET  /v3/accounts/{id}/pricing?instruments=...
  POST /v3/accounts/{id}/orders                  (MARKET + brackets)
  PUT  /v3/accounts/{id}/positions/{inst}/close
"""
from __future__ import annotations

import json
from typing import Any, Callable, Dict, Optional

PRACTICE_HOST = "https://api-fxpractice.oanda.com"
LIVE_HOST = "https://api-fxtrade.oanda.com"

# transport: (method, url, headers, body-or-None) -> (status, response body)
Transport = Callable[[str, str, Dict[str, str], Optional[bytes]], Any]


def _urllib_transport(method: str, url: str, headers: Dict[str, str],
                      body: Optional[bytes]):
    import urllib.error
    import urllib.request

    req = urllib.request.Request(url, data=body, headers=headers,
                                 method=method)
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:  # nosec B310
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        # non-2xx must flow back as (status, body) so _request raises the
        # module's own OandaApiError with OANDA's errorMessage attached
        return e.code, e.read()


class OandaApiError(RuntimeError):
    def __init__(self, status: int, body: str):
        super().__init__(f"OANDA API error {status}: {body[:500]}")
        self.status = status
        self.body = body


class OandaLiveBroker:
    """Minimal v20 REST trading client.

    Quantities follow OANDA conventions: signed integer units (positive
    buys, negative sells); prices are decimal strings at the
    instrument's precision.
    """

    def __init__(self, token: str, account_id: str, *,
                 practice: bool = True,
                 transport: Optional[Transport] = None):
        if not token or not account_id:
            raise ValueError("OandaLiveBroker requires token and account_id")
        self.account_id = account_id
        self._base = (PRACTICE_HOST if practice else LIVE_HOST)
        self._headers = {
            "Authorization": f"Bearer {token}",
            "Content-Type": "application/json",
        }
        self._transport = transport or _urllib_transport

    # ------------------------------------------------------------------
    def _request(self, method: str, path: str,
                 payload: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        body = json.dumps(payload).encode() if payload is not None else None
        status, raw = self._transport(
            method, f"{self._base}{path}", dict(self._headers), body
        )
        text = raw.decode() if isinstance(raw, (bytes, bytearray)) else str(raw)
        if not 200 <= int(status) < 300:
            raise OandaApiError(int(status), text)
        return json.loads(text) if text else {}

    # ------------------------------------------------------------------
    def account_summary(self) -> Dict[str, Any]:
        return self._request(
            "GET", f"/v3/accounts/{self.account_id}/summary"
        )["account"]

    def open_positions(self) -> Dict[str, float]:
        """{instrument: net signed units} for every open position."""
        data = self._request(
            "GET", f"/v3/accounts/{self.account_id}/openPositions"
        )
        out: Dict[str, float] = {}
        for pos in data.get("positions", []):
            units = float(pos.get("long", {}).get("units", 0) or 0) + float(
                pos.get("short", {}).get("units", 0) or 0
            )
            out[pos["instrument"]] = units
        return out

    def pricing(self, instrument: str) -> Dict[str, float]:
        data = self._request(
            "GET",
            f"/v3/accounts/{self.account_id}/pricing?instruments={instrument}",
        )
        price = data["prices"][0]
        return {
            "bid": float(price["bids"][0]["price"]),
            "ask": float(price["asks"][0]["price"]),
        }

    def market_order(self, instrument: str, units: float, *,
                     stop_loss: Optional[float] = None,
                     take_profit: Optional[float] = None,
                     price_precision: int = 5) -> Dict[str, Any]:
        """Market order for signed ``units``; brackets attach as
        on-fill orders (the scan engine's entry-with-brackets flow)."""
        if units == 0:
            raise ValueError("market_order requires nonzero units")
        order: Dict[str, Any] = {
            "type": "MARKET",
            "instrument": instrument,
            "units": str(int(units)),
            "timeInForce": "FOK",
            "positionFill": "DEFAULT",
        }
        if stop_loss:
            order["stopLossOnFill"] = {
                "price": f"{stop_loss:.{price_precision}f}"
            }
        if take_profit:
            order["takeProfitOnFill"] = {
                "price": f"{take_profit:.{price_precision}f}"
            }
        return self._request(
            "POST", f"/v3/accounts/{self.account_id}/orders",
            {"order": order},
        )

    def close_position(self, instrument: str) -> Dict[str, Any]:
        """Flatten the instrument (both sides, like the scan engine's
        force-flat)."""
        return self._request(
            "PUT",
            f"/v3/accounts/{self.account_id}/positions/{instrument}/close",
            {"longUnits": "ALL", "shortUnits": "ALL"},
        )


class TargetOrderRouter:
    """Bridge from the framework's decision stream to live orders.

    The strategy kernels emit ``(pending_active, pending_target,
    pending_sl, pending_tp)`` each bar — the same stream the replay
    engine re-executes.  ``submit_target`` turns one decision into the
    minimal OANDA action: the units DELTA as a market order (with
    brackets on opening orders), or a position close when the target is
    flat.  Idempotent on no-ops (target == current)."""

    def __init__(self, broker: OandaLiveBroker, instrument: str, *,
                 price_precision: int = 5):
        self.broker = broker
        self.instrument = instrument
        self.price_precision = int(price_precision)

    def submit_target(self, target_units: float, *,
                      stop_loss: Optional[float] = None,
                      take_profit: Optional[float] = None) -> Optional[Dict[str, Any]]:
        current = self.broker.open_positions().get(self.instrument, 0.0)
        delta = float(target_units) - current
        if abs(delta) < 1.0:  # sub-unit residual: OANDA units are integral
            return None
        if target_units == 0:
            return self.broker.close_position(self.instrument)
        return self.broker.market_order(
            self.instrument, delta,
            stop_loss=stop_loss, take_profit=take_profit,
            price_precision=self.price_precision,
        )
