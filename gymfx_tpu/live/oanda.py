"""Live OANDA order routing over the v20 REST API.

The reference's gated broker hands live trading to backtrader's
``OandaStore`` (reference broker_plugins/oanda_broker.py:58-63).  This
framework has no backtrader engine to hand anything to, so the live
surface is built the framework's way instead: the strategy kernels
already express every decision as a *pending target* (signed units +
optional bracket prices — the decision stream the replay engine
re-executes, simulation/crosscheck.py), and ``TargetOrderRouter`` maps
exactly that stream onto OANDA order payloads.  One adapter serves
every strategy kernel, like the crosscheck does.

``OandaLiveBroker`` is a dependency-free v20 client (urllib; the image
has no ``requests``).  The HTTP transport is injectable so the whole
surface is testable offline — tests drive it with a fake transport and
assert the exact payloads (tests/test_live_oanda.py); nothing here is
imported by the simulation path.

Endpoints used (OANDA v20 public API):
  GET  /v3/accounts/{id}/summary
  GET  /v3/accounts/{id}/openPositions
  GET  /v3/accounts/{id}/pricing?instruments=...
  POST /v3/accounts/{id}/orders                  (MARKET + brackets)
  PUT  /v3/accounts/{id}/positions/{inst}/close
"""
from __future__ import annotations

import json
from typing import Any, Callable, Dict, Optional

PRACTICE_HOST = "https://api-fxpractice.oanda.com"
LIVE_HOST = "https://api-fxtrade.oanda.com"

# transport: (method, url, headers, body-or-None) -> (status, response body)
Transport = Callable[[str, str, Dict[str, str], Optional[bytes]], Any]


def _urllib_transport(method: str, url: str, headers: Dict[str, str],
                      body: Optional[bytes]):
    import urllib.error
    import urllib.request

    req = urllib.request.Request(url, data=body, headers=headers,
                                 method=method)
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:  # nosec B310
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        # non-2xx must flow back as (status, body) so _request raises the
        # module's own OandaApiError with OANDA's errorMessage attached
        return e.code, e.read()


class OandaApiError(RuntimeError):
    def __init__(self, status: int, body: str):
        super().__init__(f"OANDA API error {status}: {body[:500]}")
        self.status = status
        self.body = body


class OandaLiveBroker:
    """Minimal v20 REST trading client.

    Quantities follow OANDA conventions: signed integer units (positive
    buys, negative sells); prices are decimal strings at the
    instrument's precision.
    """

    def __init__(self, token: str, account_id: str, *,
                 practice: bool = True,
                 transport: Optional[Transport] = None):
        if not token or not account_id:
            raise ValueError("OandaLiveBroker requires token and account_id")
        self.account_id = account_id
        self._base = (PRACTICE_HOST if practice else LIVE_HOST)
        self._headers = {
            "Authorization": f"Bearer {token}",
            "Content-Type": "application/json",
        }
        self._transport = transport or _urllib_transport

    # ------------------------------------------------------------------
    def _request(self, method: str, path: str,
                 payload: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        body = json.dumps(payload).encode() if payload is not None else None
        status, raw = self._transport(
            method, f"{self._base}{path}", dict(self._headers), body
        )
        text = raw.decode() if isinstance(raw, (bytes, bytearray)) else str(raw)
        if not 200 <= int(status) < 300:
            raise OandaApiError(int(status), text)
        return json.loads(text) if text else {}

    # ------------------------------------------------------------------
    def account_summary(self) -> Dict[str, Any]:
        return self._request(
            "GET", f"/v3/accounts/{self.account_id}/summary"
        )["account"]

    def open_positions(self) -> Dict[str, float]:
        """{instrument: net signed units} for every open position."""
        data = self._request(
            "GET", f"/v3/accounts/{self.account_id}/openPositions"
        )
        out: Dict[str, float] = {}
        for pos in data.get("positions", []):
            units = float(pos.get("long", {}).get("units", 0) or 0) + float(
                pos.get("short", {}).get("units", 0) or 0
            )
            out[pos["instrument"]] = units
        return out

    def pricing(self, instrument: str) -> Dict[str, float]:
        data = self._request(
            "GET",
            f"/v3/accounts/{self.account_id}/pricing?instruments={instrument}",
        )
        price = data["prices"][0]
        return {
            "bid": float(price["bids"][0]["price"]),
            "ask": float(price["asks"][0]["price"]),
        }

    def market_order(self, instrument: str, units: float, *,
                     stop_loss: Optional[float] = None,
                     take_profit: Optional[float] = None,
                     price_precision: int = 5,
                     client_id: Optional[str] = None) -> Dict[str, Any]:
        """Market order for signed ``units``; brackets attach as
        on-fill orders (the scan engine's entry-with-brackets flow).

        ``units`` is rounded to the nearest integer (OANDA units are
        integral); an order that rounds to zero is refused loudly rather
        than silently dropped.  ``client_id`` becomes the order's
        ``clientExtensions.id`` — OANDA rejects a duplicate client id,
        so a deterministic id per decision makes a retry after a
        transport timeout surface as an API error instead of a second
        fill."""
        int_units = int(round(float(units)))
        if int_units == 0:
            raise ValueError(
                f"market_order units {units!r} round to zero — OANDA "
                "units are integral; refuse rather than silently no-op"
            )
        order: Dict[str, Any] = {
            "type": "MARKET",
            "instrument": instrument,
            "units": str(int_units),
            "timeInForce": "FOK",
            "positionFill": "DEFAULT",
        }
        if client_id:
            order["clientExtensions"] = {"id": str(client_id)}
        if stop_loss:
            order["stopLossOnFill"] = {
                "price": f"{stop_loss:.{price_precision}f}"
            }
        if take_profit:
            order["takeProfitOnFill"] = {
                "price": f"{take_profit:.{price_precision}f}"
            }
        return self._request(
            "POST", f"/v3/accounts/{self.account_id}/orders",
            {"order": order},
        )

    def order_by_client_id(self, client_id: str) -> Optional[Dict[str, Any]]:
        """The order previously submitted with ``clientExtensions.id``
        ``client_id`` in ANY state (pending, filled, cancelled), or
        ``None`` when the account has never seen that id — OANDA's
        ``@``-prefixed orderSpecifier lookup."""
        from urllib.parse import quote

        try:
            return self._request(
                "GET",
                f"/v3/accounts/{self.account_id}/orders/"
                f"@{quote(str(client_id), safe='')}",
            ).get("order")
        except OandaApiError as e:
            if e.status == 404:
                return None
            raise

    def close_position(self, instrument: str, *,
                       client_id: Optional[str] = None) -> Dict[str, Any]:
        """Flatten the instrument (both sides, like the scan engine's
        force-flat).  ``client_id`` attaches to the venue-generated
        market order(s) so a retried flatten decision is discoverable
        via :meth:`order_by_client_id` (net positions only ever hold one
        side, so the shared id cannot collide with itself)."""
        payload: Dict[str, Any] = {"longUnits": "ALL", "shortUnits": "ALL"}
        if client_id:
            ext = {"id": str(client_id)}
            payload["longClientExtensions"] = ext
            payload["shortClientExtensions"] = ext
        return self._request(
            "PUT",
            f"/v3/accounts/{self.account_id}/positions/{instrument}/close",
            payload,
        )


class TargetOrderRouter:
    """Bridge from the framework's decision stream to live orders.

    The strategy kernels emit ``(pending_active, pending_target,
    pending_sl, pending_tp)`` each bar — the same stream the replay
    engine re-executes.  ``submit_target`` turns one decision into the
    minimal OANDA action: the units DELTA as a market order (with
    brackets on opening orders), or a position close when the target is
    flat.  Idempotent on no-ops (target == current).

    Retry safety: positions are reconciled (re-read) on every call, so
    a retry after the server accepted the previous order recomputes a
    zero delta once the fill is visible.  For the window before it is
    visible, every order carries a ``clientExtensions`` id, and when
    the caller supplies a ``decision_id`` (the bar index / timestamp of
    the decision) the router LOOKS UP that id on the account before
    submitting — OANDA's ``@client-id`` orderSpecifier finds the order
    in any state, including already-filled FOK market orders, so a
    blind resubmit of the same decision returns the original order
    instead of double-filling.  (The id alone is not enough: OANDA only
    enforces client-id uniqueness among PENDING orders, and a filled
    market order is no longer pending.)  Without an explicit
    ``decision_id`` the router falls back to a session-unique uuid-
    salted sequence — unique, but NOT retry-safe across callers:
    duplicate-order protection requires the caller's ``decision_id``.

    Units contract: live OANDA units are integral.  A fractional
    ``target_units`` (beyond float noise) is refused loudly — sizing
    kernels that emit sub-unit targets must be scaled before routing
    live, never silently under-traded."""

    def __init__(self, broker: OandaLiveBroker, instrument: str, *,
                 price_precision: int = 5,
                 client_id_prefix: str = "gymfx"):
        self.broker = broker
        self.instrument = instrument
        self.price_precision = int(price_precision)
        self.client_id_prefix = str(client_id_prefix)
        import uuid

        self._session_tag = uuid.uuid4().hex[:8]
        self._decision_seq = 0

    def submit_target(self, target_units: float, *,
                      stop_loss: Optional[float] = None,
                      take_profit: Optional[float] = None,
                      decision_id: Optional[str] = None) -> Optional[Dict[str, Any]]:
        rounded_target = round(float(target_units))
        if abs(float(target_units) - rounded_target) > 1e-6:
            raise ValueError(
                f"target_units {target_units!r} is fractional — live "
                "OANDA units are integral; scale the kernel's sizing "
                "before routing live (integral-units contract)"
            )
        current = self.broker.open_positions().get(self.instrument, 0.0)
        delta = rounded_target - current
        if abs(delta) < 0.5:
            return None
        explicit_decision = decision_id is not None
        if decision_id is None:
            self._decision_seq += 1
            decision_id = f"{self._session_tag}-{self._decision_seq}"
        client_id = f"{self.client_id_prefix}-{self.instrument}-{decision_id}"
        if explicit_decision:
            prior = self.broker.order_by_client_id(client_id)
            # a CANCELLED prior (FOK orders cancel routinely on missed
            # liquidity) never traded and releases its client id on
            # OANDA's side, so the decision is retried; any other state
            # (pending / triggered / filled) means the decision reached
            # the book — return it instead of double-submitting.  The
            # lookup runs for FLATTEN decisions too: close_position's
            # venue-generated market orders carry the same id.
            if prior is not None and prior.get("state") != "CANCELLED":
                return {"already_submitted": prior}
        if rounded_target == 0:
            return self.broker.close_position(
                self.instrument, client_id=client_id
            )
        return self.broker.market_order(
            self.instrument, delta,
            stop_loss=stop_loss, take_profit=take_profit,
            price_precision=self.price_precision,
            client_id=client_id,
        )
