"""Dataset-of-tapes registry + mixed real/scengen curriculum sampler.

Many CSV files and/or scengen presets become ONE logical dataset
(Jumanji's registry-of-environments pattern applied to market tapes):
every tape is resolved through the same ``build_market_data`` pipeline
with the environment's exact feature/calendar kwargs, so each carries
its own calendar, and all tapes must agree on the bar count — static
shapes mean one compiled train step serves every tape.

``feed=curriculum`` draws a weighted, seed-deterministic tape per
superstep boundary (numpy PCG64 — bitwise-stable across processes) and
ledgers each draw as a ``curriculum_pick`` row.  With ``data_compress``
on, the tape *library* is held compressed on device (data/compress.py)
and each pick materializes its f32 view through the fused decode —
bitwise-identical to the uncompressed tape, so a curriculum over one
tape reproduces plain replay training exactly.

Tape grammar (the ``tapes`` config key):

- compact string: ``file:PATH[@WEIGHT]`` / ``scengen:PRESET[@WEIGHT]``
  entries joined by commas, e.g.
  ``"file:eurusd.csv@3,scengen:crash@1,scengen:regime_mix"``
- JSON list of dicts (also accepted as a Python list):
  ``[{"file": "eurusd.csv", "weight": 3},
  {"scengen": "crash", "scengen_seed": 7}]`` — extra keys overlay the
  base config for that tape only (per-tape seeds, bar counts, ...).
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

TAPE_KINDS = ("file", "scengen")


class TapeSpec(NamedTuple):
    kind: str                       # "file" | "scengen"
    source: str                     # CSV path | preset name
    weight: float
    label: str
    overrides: Tuple[Tuple[str, Any], ...] = ()


def _spec_from_entry(entry: Any, idx: int) -> TapeSpec:
    if isinstance(entry, str):
        body = entry.strip()
        weight = 1.0
        if "@" in body:
            body, _, w = body.rpartition("@")
            try:
                weight = float(w)
            except ValueError:
                raise ValueError(
                    f"tapes entry {entry!r}: weight after '@' must be a "
                    f"number, got {w!r}"
                ) from None
        kind, sep, source = body.partition(":")
        if not sep or kind not in TAPE_KINDS or not source:
            raise ValueError(
                f"tapes entry {entry!r} must look like "
                "'file:PATH[@WEIGHT]' or 'scengen:PRESET[@WEIGHT]'"
            )
        overrides: Dict[str, Any] = {}
    elif isinstance(entry, dict):
        entry = dict(entry)
        kinds = [k for k in TAPE_KINDS if k in entry]
        if len(kinds) != 1:
            raise ValueError(
                f"tapes entry {entry!r} must have exactly one of "
                f"{TAPE_KINDS} as a key"
            )
        kind = kinds[0]
        source = str(entry.pop(kind))
        weight = float(entry.pop("weight", 1.0))
        overrides = entry  # remaining keys overlay the base config
    else:
        raise ValueError(
            f"tapes entry #{idx} must be a 'kind:source' string or a "
            f"dict, got {type(entry).__name__}"
        )
    if not (np.isfinite(weight) and weight > 0):
        raise ValueError(
            f"tapes entry {source!r}: weight must be a finite positive "
            f"number, got {weight!r}"
        )
    label = f"{kind}:{source}"
    return TapeSpec(kind, source, float(weight), label,
                    tuple(sorted(overrides.items())))


def parse_tape_specs(config: Dict[str, Any]) -> Tuple[TapeSpec, ...]:
    """The ``tapes`` config key -> validated specs (honor-or-reject)."""
    raw = config.get("tapes")
    if raw is None or raw == "" or raw == []:
        raise ValueError(
            "feed=curriculum requires the 'tapes' config key: a "
            "'file:PATH[@W],scengen:PRESET[@W]' string or a JSON list "
            "of {file|scengen, weight, ...} dicts"
        )
    if isinstance(raw, str):
        s = raw.strip()
        if s.startswith("["):
            try:
                raw = json.loads(s)
            except json.JSONDecodeError as e:
                raise ValueError(
                    f"tapes looks like JSON but does not parse: {e}"
                ) from e
        else:
            raw = [part for part in s.split(",") if part.strip()]
    if not isinstance(raw, (list, tuple)) or not raw:
        raise ValueError(
            f"tapes must be a non-empty list of tape entries, got {raw!r}"
        )
    specs = tuple(_spec_from_entry(e, i) for i, e in enumerate(raw))
    labels = [s.label for s in specs]
    dupes = {x for x in labels if labels.count(x) > 1}
    if dupes:
        raise ValueError(
            f"tapes lists the same tape more than once: {sorted(dupes)}; "
            "merge the weights instead"
        )
    return specs


def overlay_config(config: Dict[str, Any], spec: TapeSpec) -> Dict[str, Any]:
    """Base config overlaid for ONE tape: the spec's source + overrides,
    with the curriculum keys stripped so nested dataset builds cannot
    recurse."""
    overlay = dict(config)
    overlay.pop("tapes", None)
    overlay.update(dict(spec.overrides))
    if spec.kind == "file":
        overlay["feed"] = "replay"
        overlay["input_data_file"] = spec.source
    else:
        overlay["feed"] = "scengen"
        overlay["scengen_preset"] = spec.source
    return overlay


def dataset_for_spec(config: Dict[str, Any], spec: TapeSpec):
    """Resolve one tape spec into a MarketDataset (replay or scengen)."""
    overlay = overlay_config(config, spec)
    if spec.kind == "file":
        from gymfx_tpu.data.feed import load_market_dataset

        return load_market_dataset(overlay)
    from gymfx_tpu.scengen.feed import ScenGenDataset

    return ScenGenDataset(overlay)


class _TapePickerBase:
    """Weighted, seed-deterministic draws + ``curriculum_pick`` ledger
    rows — shared by the single-pair and portfolio samplers.  Draws use
    ``np.random.default_rng(curriculum_seed)`` (PCG64), bitwise-stable
    across processes and platforms."""

    def _init_picker(self, config: Dict[str, Any],
                     specs: Sequence[TapeSpec]) -> None:
        self.specs = tuple(specs)
        w = np.asarray([s.weight for s in self.specs], np.float64)
        self.weights = w / w.sum()
        seed = config.get("curriculum_seed")
        if seed is None:
            seed = config.get("seed", 0)
        self.seed = int(seed or 0)
        self.rng = np.random.default_rng(self.seed)
        self.picks: List[Tuple[int, int]] = []  # (it_start, tape_index)

    @property
    def num_tapes(self) -> int:
        return len(self.specs)

    def _tape_data(self, i: int):
        raise NotImplementedError

    def pick(self, it_start: int):
        """Draw the tape for the superstep starting at ``it_start`` ->
        ``(index, label, device data)``; ledgers the draw."""
        i = int(self.rng.choice(len(self.specs), p=self.weights))
        self.picks.append((int(it_start), i))
        from gymfx_tpu.telemetry.ledger import get_active_ledger

        ledger = get_active_ledger()
        if ledger is not None:
            ledger.record(
                "curriculum_pick",
                it_start=int(it_start),
                tape=self.specs[i].label,
                tape_index=i,
                seed=self.seed,
            )
        return i, self.specs[i].label, self._tape_data(i)


class CurriculumSampler(_TapePickerBase):
    """Seed-deterministic weighted tape sampler over the registry.

    Tape 0 is the Environment's own dataset (its device MarketData is
    used as-is, so a single-tape curriculum is bitwise plain replay);
    the remaining tapes are built host-side with the SAME
    ``build_market_data`` kwargs and either parked on device f32
    (``data_compress=off``) or held as compressed tapes whose f32 view
    is decoded per pick (``on``/``interpret`` — 4x+ more tapes per GB,
    decode bitwise-verified at encode time).

    Draws use ``np.random.default_rng(curriculum_seed)`` (PCG64):
    bitwise-reproducible across processes and platforms, which the
    subprocess-determinism test pins.  Every draw is ledgered as a
    ``curriculum_pick`` row when a run ledger is active.
    """

    def __init__(
        self,
        config: Dict[str, Any],
        specs: Sequence[TapeSpec],
        *,
        base_dataset,
        base_data,
        md_kwargs: Dict[str, Any],
        compress: str = "off",
        tick_size: float = 1e-5,
    ):
        from gymfx_tpu.data import compress as C
        from gymfx_tpu.data.feed import market_data_nbytes

        self._init_picker(config, specs)
        self.compress = C.validate_compress_mode(compress)

        n0 = int(np.asarray(base_data.close).shape[0])
        self._decoded_nbytes = market_data_nbytes(base_data)
        self._compressed_nbytes: Optional[int] = None
        self._device: Dict[int, Any] = {0: base_data}
        self._tapes: Dict[int, Any] = {}
        self._decoder = None
        if self.compress != "off":
            self._compressed_nbytes = 0
        md_kwargs = dict(md_kwargs, device=False)
        for i, spec in enumerate(self.specs[1:], start=1):
            ds = dataset_for_spec(config, spec)
            host = ds.build_market_data(**md_kwargs)
            n = int(np.asarray(host.close).shape[0])
            if n != n0:
                raise ValueError(
                    "curriculum tapes must all have the same bar count "
                    "(one compiled train step serves every tape): tape "
                    f"{i} {spec.label!r} has {n} bars, tape 0 "
                    f"{self.specs[0].label!r} has {n0}; trim the files "
                    "or set scengen_bars to match"
                )
            if self.compress == "off":
                import jax

                self._device[i] = jax.tree.map(jax.device_put, host)
            else:
                tape = C.encode_tape(
                    host,
                    window_size=int(md_kwargs["window_size"]),
                    tick_size=float(tick_size),
                    what=f" (curriculum tape {spec.label})",
                )
                self._tapes[i] = C.device_tape(tape)
                self._compressed_nbytes += tape.nbytes
                if self._decoder is None:
                    self._decoder = C.make_shard_decoder(tape, self.compress)

    def nbytes_report(self) -> Dict[str, Any]:
        """Decoded vs compressed library accounting (tape 0 is always
        resident f32 — it is the Environment's own dataset)."""
        n = self.num_tapes
        return {
            "decoded": self._decoded_nbytes * n,
            "compressed": self._compressed_nbytes,
            "ratio": None if not self._compressed_nbytes else (
                self._decoded_nbytes * (n - 1) / self._compressed_nbytes
            ),
        }

    def _tape_data(self, i: int):
        if i in self._device:
            return self._device[i]
        from gymfx_tpu.data import compress as C

        return self._decoder(C.shard_arrays(self._tapes[i], 0))


class PortfolioCurriculumSampler(_TapePickerBase):
    """Curriculum over whole portfolio books.  Each non-base tape is
    built by a throwaway ``PortfolioEnvironment`` on the overlaid config
    (one level deep only — the overlay strips the curriculum keys), so
    every tape carries its own aligned multi-pair data AND conversion
    factors.  A ``file:`` tape is a single CSV, not a book, so portfolio
    tapes are either scengen presets or dict entries with a
    ``portfolio_files`` override.  Portfolio tapes are ``PortfolioData``
    pytrees (stacked pair leaves + a conversion matrix), not single-pair
    ``MarketData`` — ``data_compress`` does not apply to them
    (core/portfolio.py rejects the combination loudly)."""

    def __init__(self, config: Dict[str, Any], specs: Sequence[TapeSpec],
                 *, base_env):
        self._init_picker(config, specs)
        n0 = int(base_env.cfg.n_bars)
        self._device: Dict[int, Any] = {0: base_env.data}
        from gymfx_tpu.core.portfolio import PortfolioEnvironment

        for i, spec in enumerate(self.specs[1:], start=1):
            if (spec.kind == "file"
                    and "portfolio_files" not in dict(spec.overrides)):
                raise ValueError(
                    f"portfolio curriculum tape {spec.label!r}: a 'file:' "
                    "tape is a single CSV, not a multi-pair book; use the "
                    "dict form with a 'portfolio_files' override, or a "
                    "scengen preset"
                )
            env_i = PortfolioEnvironment(overlay_config(config, spec))
            if int(env_i.cfg.n_bars) != n0:
                raise ValueError(
                    "curriculum tapes must all have the same bar count "
                    "(one compiled train step serves every tape): tape "
                    f"{i} {spec.label!r} has {env_i.cfg.n_bars} aligned "
                    f"bars, tape 0 {self.specs[0].label!r} has {n0}; "
                    "trim the books or set scengen_bars to match"
                )
            self._device[i] = env_i.data

    def _tape_data(self, i: int):
        return self._device[i]
