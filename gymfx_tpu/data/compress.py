"""int16 tick-delta compression for MarketData tapes.

The LOB already prices everything on an integer tick grid
(``lob_tick_size``); this module makes that grid the *wire format* for
device-resident market data.  Each OHLC/padded_close column is stored as
int16 deltas against a per-shard int32 base (a scale sidecar carries the
grid), the event/calendar blocks narrow to int16 quantities / packed
bits / whole-tape constants, and the f32 view is materialized on device
per shard by a fused decode (ops/tape_decode.py, XLA oracle in
:func:`decode_q16_ref`).

The contract is bitwise, enforced at ENCODE time: every column's codec
simulates the exact device decode arithmetic in numpy and compares the
result against the f32 target *by bit pattern*.  Columns that cannot
round-trip fall back to raw f32 storage — except prices, which are the
honor-or-reject surface (same discipline as ``validate_lob_venue``):
off-grid prices or a per-shard tick span beyond int16 raise loudly
instead of degrading silently.

Decode arithmetic (pinned): ``f32 = (base_i32 + delta_i16→i32)→f32 /
inv_f32`` where ``inv = 1 / scale``.  Division (not multiplication by
the scale) is what makes on-grid prices round-trip: ``ticks / 1e5`` is a
single correctly-rounded f32 operation, while ``ticks * f32(1e-5)``
compounds the representation error of the scale.  See DIVERGENCES.md
for the dtype-narrowing bounds this implies.
"""
from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

COMPRESS_MODES = ("off", "on", "interpret")

# fields whose codec is mandatory (honor-or-reject): the int-tick grid
# IS the contract for prices
PRICE_FIELDS = ("open", "high", "low", "close", "padded_close")

# q16 divisor candidates for non-price f32 columns, tried in order: raw
# integer quantities (volume, M1 bar counts), hours-from-minutes
# (calendar hours_to_* = minutes / 60), minutes/days grids
Q16_CANDIDATE_INVS = (1.0, 60.0, 24.0, 1440.0, 10080.0)

# feature-pipeline tensors stay raw f32: their values are f64-derived
# rolling moments with no grid to quantize against
RAW_FIELDS = ("padded_features", "feat_mean", "feat_std", "feat_neutral")

_I16_SPAN = 65535  # full int16 delta range once the base is centered


class ColumnSpec(NamedTuple):
    """One stored column of a compressed tape.

    ``kind``:
      q16      int16 delta + per-shard i32 base; f32 = (base+delta)→f32/inv
      i16      int16 delta + per-shard i32 base; i32 = base + delta
      u8       uint8 delta + per-shard i32 base; i32 = base + delta
      bits     bit ``bit`` of a packed uint8 mask column; f32 = (m>>b)&1
      const    whole-tape constant ``value``
      iperiodic whole-tape lookup table gathered by the GLOBAL bar index
               modulo the table length (regular bar grids repeat weekly:
               calendar/session blocks and minute_of_week itself store
               ONE week of slots, not one value per bar)
      periodic whole-tape f32 lookup table gathered by the decoded
               ``minute_of_week`` — the fallback for weekly-periodic
               values on IRREGULAR grids (gap-y CSV replays), where the
               bar index is not congruent to the week.  Both table
               kinds copy stored bits on decode, so the round-trip is
               exact by construction and still verified at encode time.
      raw      original-dtype passthrough slab
    ``src`` indexes ``CompressedTape.slabs`` (q16/i16/bits), ``.raws``
    (raw) or ``.tables`` (periodic); identical delta slabs are
    content-deduplicated, so several columns may share one ``src`` with
    different ``inv`` (e.g. the calendar's hours-to-break and M1
    bars-to-break both decode from one stored minutes column).
    """

    field: str
    col: int          # column index inside a 2-D field; -1 for 1-D
    kind: str
    src: int = -1
    inv: float = 1.0
    bit: int = 0
    value: float = 0.0


class CompressedTape(NamedTuple):
    """Stacked per-shard slabs for one logical tape.

    ``slabs[i]`` is ``(S, rows)`` int16 (q16/i16) or uint8 (bits) with
    ``bases[i]`` the aligned ``(S,)`` int32 base sidecar; ``raws[i]`` is
    ``(S, rows[, C])`` in the original dtype.  Shard ``k``'s decode is
    bitwise-identical to ``shard_market_data(host, starts[k],
    shard_bars, window_size)`` — verified at encode time.
    """

    columns: Tuple[ColumnSpec, ...]
    slabs: Tuple[Any, ...]
    bases: Tuple[Any, ...]
    raws: Tuple[Any, ...]
    tables: Tuple[Any, ...]   # (period,) f32 minute-of-week lookups
    starts: Any               # (S,) int32 global shard starts
    shard_bars: int
    window_size: int
    n_bars: int
    decoded_shard_nbytes: int  # exact f32 bytes of ONE decoded shard

    @property
    def num_shards(self) -> int:
        return int(np.asarray(self.starts).shape[0])

    @property
    def nbytes(self) -> int:
        """Total compressed bytes (slabs + base sidecars + raw slabs +
        periodic lookup tables)."""
        total = 0
        for arr in (*self.slabs, *self.bases, *self.raws, *self.tables):
            total += int(arr.nbytes)
        return total

    @property
    def shard_nbytes(self) -> int:
        """Compressed bytes of one shard (slabs are uniformly stacked)."""
        return -(-self.nbytes // max(1, self.num_shards))

    @property
    def compression_ratio(self) -> float:
        """Decoded f32 bytes / compressed bytes over the shard set."""
        return (self.decoded_shard_nbytes * self.num_shards) / max(
            1, self.nbytes
        )

    def codec_report(self) -> Dict[str, str]:
        """{column: kind} — observability for tests and docs."""
        out = {}
        for c in self.columns:
            name = c.field if c.col < 0 else f"{c.field}:{c.col}"
            out[name] = c.kind
        return out


def validate_compress_mode(mode: Any) -> str:
    """Honor-or-reject the ``data_compress`` knob."""
    m = str(mode or "off").lower()
    if m not in COMPRESS_MODES:
        raise ValueError(
            f"data_compress must be one of {COMPRESS_MODES}, got {mode!r}"
        )
    return m


# ---------------------------------------------------------------------------
# encode


def _bitview(a: np.ndarray) -> np.ndarray:
    """Reinterpret as unsigned bits for exact (NaN-safe) comparison."""
    if a.dtype == np.float32:
        return a.view(np.uint32)
    return a


def _bit_equal(a: np.ndarray, b: np.ndarray) -> bool:
    return bool(np.array_equal(_bitview(np.ascontiguousarray(a)),
                               _bitview(np.ascontiguousarray(b))))


def _try_q16(col: np.ndarray, inv: float):
    """Fit (S, rows) f32 -> per-shard base + int16 delta under divisor
    ``inv``; returns (bases_i32, delta_i16) when the simulated decode is
    bitwise-exact, else None."""
    t = np.rint(col.astype(np.float64) * inv)
    if not np.all(np.isfinite(t)):
        return None
    lo = t.min(axis=1)
    span = t.max(axis=1) - lo
    if span.max() > _I16_SPAN:
        return None
    base = lo + np.where(span > 32767, 32768.0, 0.0)
    if np.any(np.abs(base) > 2**31 - 1):
        return None
    base = base.astype(np.int32)
    delta = (t - base[:, None].astype(np.float64)).astype(np.int16)
    dec = (
        base[:, None] + delta.astype(np.int32)
    ).astype(np.float32) / np.float32(inv)
    if not _bit_equal(dec, col):
        return None
    return base, delta


def _try_i16(col: np.ndarray):
    """(S, rows) int32 -> per-shard base + int16 delta (exact)."""
    t = col.astype(np.int64)
    lo = t.min(axis=1)
    span = t.max(axis=1) - lo
    if span.max() > _I16_SPAN:
        return None
    base = (lo + np.where(span > 32767, 32768, 0)).astype(np.int32)
    delta = (t - base[:, None]).astype(np.int16)
    if not np.array_equal(base[:, None] + delta.astype(np.int32), t):
        return None
    return base, delta


def _try_u8(col: np.ndarray):
    """(S, rows) int32 -> per-shard base + uint8 delta (exact): half the
    int16 bytes for narrow-span int columns (scenario flags)."""
    t = col.astype(np.int64)
    lo = t.min(axis=1)
    if (t.max(axis=1) - lo).max() > 255:
        return None
    base = lo.astype(np.int32)
    delta = (t - lo[:, None]).astype(np.uint8)
    if not np.array_equal(base[:, None] + delta.astype(np.int32), t):
        return None
    return base, delta


def _is_binary(col: np.ndarray) -> bool:
    dec = (col.view(np.uint32) != 0).astype(np.float32)
    return _bit_equal(dec, col)


# one FX week of minutes — the largest period a minute-of-week lookup
# table can need; anything indexing past it is not weekly-periodic
_MINUTES_PER_WEEK = 10080


def _try_index_periodic(
    col: np.ndarray, gidx: Optional[np.ndarray], period: Optional[int]
):
    """Fit (S, rows) values as a pure function of the GLOBAL bar index
    modulo ``period`` (bars-per-week on a regular grid): one (period,)
    table in the column's own dtype replaces per-bar storage.  Returns
    the table when the gather round-trips bitwise — irregular grids
    (gap-y replays) and non-periodic columns fail the consistency check
    — else None."""
    if gidx is None or period is None or period <= 0:
        return None
    if gidx.shape != col.shape:
        return None
    # only worth it when the table is smaller than the int16/uint8 slab
    # it replaces — short tapes keep delta codecs, long tapes amortize
    # one stored week over millions of bars
    if col.dtype.itemsize * period >= 2 * col.size:
        return None
    m = gidx.reshape(-1) % period
    v = col.reshape(-1)
    table = np.zeros(period, col.dtype)
    table[m] = v  # last write wins; the verify catches inconsistency
    if not _bit_equal(table[m], v):
        return None
    return table


def _try_periodic(col: np.ndarray, minutes: Optional[np.ndarray]):
    """Fit (S, rows) f32 as a pure function of minute_of_week: one
    (period,) f32 table replaces per-bar storage for weekly-periodic
    calendar/session columns.  Returns the table when the gather
    round-trips bitwise (DST-shifted or date-specific columns fail the
    consistency check and fall through to q16), else None."""
    if minutes is None or minutes.shape != col.shape:
        return None
    m = minutes.reshape(-1)
    if m.size == 0 or m.min() < 0 or m.max() >= _MINUTES_PER_WEEK:
        return None
    # only worth it when the (period,) f32 table is smaller than the
    # (n,) int16 q16 slab it replaces — short tapes keep q16, long tapes
    # amortize one stored week over millions of bars
    if 4 * (int(m.max()) + 1) >= 2 * m.size:
        return None
    v = col.reshape(-1)
    table = np.zeros(int(m.max()) + 1, np.float32)
    table[m] = v  # last write wins; the verify catches inconsistency
    if not _bit_equal(table[m], v):
        return None
    return table


class _TableStore:
    """Content-deduplicated periodic lookup tables."""

    def __init__(self):
        self.tables: List[np.ndarray] = []
        self._index: Dict[bytes, int] = {}

    def add(self, table: np.ndarray) -> int:
        key = str(table.dtype).encode() + b"|" + table.tobytes()
        src = self._index.get(key)
        if src is None:
            src = len(self.tables)
            self._index[key] = src
            self.tables.append(np.ascontiguousarray(table))
        return src


def _first_offgrid(col: np.ndarray, inv: float) -> Tuple[int, int, float]:
    """(shard, row, value) of the first element that fails the q16
    round-trip — for the honor-or-reject message."""
    t = np.rint(col.astype(np.float64) * inv)
    dec = (t / np.float64(inv)).astype(np.float32)
    bad = _bitview(dec) != _bitview(col)
    if not bad.any():
        # round-trips elementwise, so the failure was the delta span
        return -1, -1, float("nan")
    k, r = np.argwhere(bad)[0]
    return int(k), int(r), float(col[k, r])


class _SlabStore:
    """Content-deduplicated slab registry (the hours/bars calendar pair
    and OHLC columns of flat synthetic tapes collapse to one slab)."""

    def __init__(self):
        self.slabs: List[np.ndarray] = []
        self.bases: List[np.ndarray] = []
        self._index: Dict[bytes, int] = {}

    def add(self, slab: np.ndarray, base: Optional[np.ndarray]) -> int:
        if base is None:
            base = np.zeros(slab.shape[0], np.int32)
        key = (
            str(slab.dtype).encode() + b"|" + slab.tobytes()
            + b"|" + base.tobytes()
        )
        src = self._index.get(key)
        if src is None:
            src = len(self.slabs)
            self._index[key] = src
            self.slabs.append(np.ascontiguousarray(slab))
            self.bases.append(np.ascontiguousarray(base))
        return src


def _encode_f32_column(
    field: str, col_idx: int, col: np.ndarray, store: _SlabStore,
    *, tick_inv: float, tick_size: float, what: str,
    minutes: Optional[np.ndarray] = None,
    tstore: Optional["_TableStore"] = None,
    gidx: Optional[np.ndarray] = None,
    period: Optional[int] = None,
) -> ColumnSpec:
    """Codec selection for one stacked (S, rows) f32 column."""
    first = col.flat[0]
    if _bit_equal(np.broadcast_to(first, col.shape), col):
        return ColumnSpec(field, col_idx, "const", value=float(first))
    if field in PRICE_FIELDS:
        fit = _try_q16(col, tick_inv)
        if fit is None:
            k, r, v = _first_offgrid(col, tick_inv)
            if k >= 0:
                raise ValueError(
                    f"data_compress{what}: price column {field!r} is off "
                    f"the {tick_size!r} tick grid at shard {k} row {r} "
                    f"(value {v!r}); compressed tapes require on-grid "
                    "prices (same discipline as validate_lob_venue) — "
                    "snap the data to the LOB tick grid or set "
                    "data_compress=off"
                )
            raise ValueError(
                f"data_compress{what}: price column {field!r} spans more "
                f"than {_I16_SPAN} ticks ({_I16_SPAN * tick_size:g} price "
                "units) within one shard — beyond the int16 delta range; "
                "use smaller shards (lower stream_hbm_budget_mb) or set "
                "data_compress=off"
            )
        base, delta = fit
        return ColumnSpec(field, col_idx, "q16",
                          src=store.add(delta, base), inv=tick_inv)
    if _is_binary(col):
        # packed later by the caller (one uint8 mask per 2-D field)
        return ColumnSpec(field, col_idx, "bits")
    if tstore is not None:
        # index-periodic first: its table is one week of BAR slots (the
        # weekend rows never exist), smaller than the minute-of-week
        # table and independent of the minute decode
        table = _try_index_periodic(col, gidx, period)
        if table is not None:
            return ColumnSpec(field, col_idx, "iperiodic",
                              src=tstore.add(table))
        table = _try_periodic(col, minutes)
        if table is not None:
            return ColumnSpec(field, col_idx, "periodic",
                              src=tstore.add(table))
    for inv in Q16_CANDIDATE_INVS + (tick_inv,):
        fit = _try_q16(col, inv)
        if fit is not None:
            base, delta = fit
            return ColumnSpec(field, col_idx, "q16",
                              src=store.add(delta, base), inv=inv)
    return ColumnSpec(field, col_idx, "raw")


def encode_market_data(
    host: Any,
    *,
    starts: Sequence[int],
    shard_bars: int,
    window_size: int,
    tick_size: float,
    what: str = "",
) -> CompressedTape:
    """Compress a host MarketData into per-shard slabs aligned with the
    given shard ``starts`` (the BarStreamer grid, or ``[0]`` with
    ``shard_bars = n - 1`` for a whole-tape single slab).

    Every column's decode is simulated in numpy and verified bitwise
    against ``shard_market_data(host, start, ...)`` before the codec is
    accepted; prices reject loudly on failure, everything else falls
    back to raw f32.
    """
    from gymfx_tpu.data.feed import market_data_nbytes, shard_market_data

    close = np.asarray(host.close)
    if close.dtype != np.float32:
        raise ValueError(
            f"data_compress{what} requires compute_dtype float32 "
            f"(tapes are {close.dtype}); narrow the compute dtype or "
            "set data_compress=off"
        )
    if float(tick_size) <= 0.0:
        raise ValueError(
            f"data_compress{what}: lob_tick_size must be > 0, got "
            f"{tick_size!r}"
        )
    tick_inv = float(np.float32(1.0 / float(tick_size)))
    starts = [int(s) for s in starts]
    shards = [
        shard_market_data(host, s, int(shard_bars), int(window_size))
        for s in starts
    ]
    decoded_shard_nbytes = market_data_nbytes(shards[0])

    store = _SlabStore()
    tstore = _TableStore()
    raws: List[np.ndarray] = []
    columns: List[ColumnSpec] = []

    # weekly-periodic candidates gather by minute_of_week; the minute
    # block is stacked once up front so any f32 column with matching
    # geometry can try the table codec
    minutes = np.stack(
        [np.asarray(sh.minute_of_week) for sh in shards]
    ).astype(np.int64)
    # index-periodic candidates gather by GLOBAL bar index mod the
    # bars-per-week period; the distinct minute slots count the period
    # (self-validating — a wrong guess fails the bitwise check)
    gidx = (
        np.asarray(starts, np.int64)[:, None]
        + np.arange(minutes.shape[1], dtype=np.int64)[None, :]
    )
    period = int(np.unique(minutes).size)

    for field in type(host)._fields:
        if field == "row0":
            continue
        target = np.stack([np.asarray(getattr(sh, field)) for sh in shards])
        if field in RAW_FIELDS:
            columns.append(ColumnSpec(field, -1, "raw", src=len(raws)))
            raws.append(np.ascontiguousarray(target))
            continue
        if target.dtype == np.int32:
            first = target.flat[0]
            if np.array_equal(np.broadcast_to(first, target.shape), target):
                columns.append(
                    ColumnSpec(field, -1, "const", value=float(first))
                )
                continue
            table = _try_index_periodic(target, gidx, period)
            if table is not None:
                columns.append(ColumnSpec(field, -1, "iperiodic",
                                          src=tstore.add(table)))
                continue
            fit = _try_u8(target)
            if fit is not None:
                base, delta = fit
                columns.append(ColumnSpec(field, -1, "u8",
                                          src=store.add(delta, base)))
                continue
            fit = _try_i16(target)
            if fit is not None:
                base, delta = fit
                columns.append(ColumnSpec(field, -1, "i16",
                                          src=store.add(delta, base)))
            else:
                columns.append(ColumnSpec(field, -1, "raw",
                                          src=len(raws)))
                raws.append(np.ascontiguousarray(target))
            continue
        # f32 columns: 1-D fields directly, 2-D fields per column with
        # the binary columns packed into one uint8 mask slab per field
        if target.ndim == 2:
            cols = [(-1, target)]
        else:
            cols = [(j, target[:, :, j]) for j in range(target.shape[2])]
        pending_bits: List[Tuple[int, np.ndarray]] = []
        for j, col in cols:
            spec = _encode_f32_column(
                field, j, col, store,
                tick_inv=tick_inv, tick_size=float(tick_size), what=what,
                minutes=minutes, tstore=tstore, gidx=gidx, period=period,
            )
            if spec.kind == "bits":
                pending_bits.append((j, col))
                columns.append(spec)  # placeholder; patched below
            elif spec.kind == "raw":
                columns.append(spec._replace(src=len(raws)))
                raws.append(np.ascontiguousarray(col))
            else:
                columns.append(spec)
        if pending_bits:
            if len(pending_bits) > 8:
                raise ValueError(
                    f"data_compress{what}: field {field!r} has "
                    f"{len(pending_bits)} binary columns — more than one "
                    "uint8 mask can pack"
                )
            mask = np.zeros(pending_bits[0][1].shape, np.uint8)
            for bit, (_, col) in enumerate(pending_bits):
                mask |= ((col.view(np.uint32) != 0).astype(np.uint8) << bit)
            src = store.add(mask, None)
            bit_iter = iter(range(len(pending_bits)))
            for i, spec in enumerate(columns):
                if spec.field == field and spec.kind == "bits":
                    columns[i] = spec._replace(src=src, bit=next(bit_iter))

    return CompressedTape(
        columns=tuple(columns),
        slabs=tuple(store.slabs),
        bases=tuple(store.bases),
        raws=tuple(raws),
        tables=tuple(tstore.tables),
        starts=np.asarray(starts, np.int32),
        shard_bars=int(shard_bars),
        window_size=int(window_size),
        n_bars=int(close.shape[0]),
        decoded_shard_nbytes=int(decoded_shard_nbytes),
    )


def encode_tape(host: Any, *, window_size: int, tick_size: float,
                what: str = "") -> CompressedTape:
    """Whole-tape single-slab encoding: shard 0 anchored at row 0 with
    ``shard_bars = n - 1`` decodes to the full MarketData bitwise
    (curriculum tape libraries, ops/tape_decode parity tests)."""
    n = int(np.asarray(host.close).shape[0])
    return encode_market_data(
        host, starts=(0,), shard_bars=n - 1, window_size=window_size,
        tick_size=tick_size, what=what,
    )


# ---------------------------------------------------------------------------
# decode


def decode_q16_ref(delta, base, inv):
    """XLA parity oracle for the fused q16 decode: (C, rows) int16 +
    (C,) int32 + (C,) f32 -> (C, rows) f32.  The Pallas kernel
    (ops/tape_decode.py) must match this bitwise."""
    import jax.numpy as jnp

    return (
        base[:, None] + delta.astype(jnp.int32)
    ).astype(jnp.float32) / inv[:, None]


def _q16_groups(
    columns: Tuple[ColumnSpec, ...], row_counts: Sequence[int]
) -> List[List[Tuple[int, float]]]:
    """Deterministic fused-decode grouping: unique (slab, inv) q16 pairs
    bucketed by row count, sorted — shared by ``shard_arrays`` and
    ``_decode_shard_impl`` so the runtime divisor arrays line up."""
    q16_pairs = sorted({(c.src, c.inv) for c in columns if c.kind == "q16"})
    by_rows: Dict[int, List[Tuple[int, float]]] = {}
    for src, inv in q16_pairs:
        by_rows.setdefault(int(row_counts[src]), []).append((src, inv))
    return [items for _, items in sorted(by_rows.items())]


def shard_arrays(tape: CompressedTape, k: int) -> Dict[str, Any]:
    """Host-side pytree of shard ``k``'s compressed arrays — the traced
    argument of the jitted decoder (slabs/bases/raws sliced at ``k``,
    plus the shard's global ``row0``).

    The q16 divisors ride along as runtime f32 arrays (one per fused
    group) rather than being baked into the trace: XLA strength-reduces
    division by a compile-time constant into multiplication by its
    reciprocal, which costs a ULP and breaks the bitwise contract.
    """
    groups = _q16_groups(
        tape.columns, [int(np.asarray(s).shape[1]) for s in tape.slabs]
    )
    return {
        "slabs": tuple(s[k] for s in tape.slabs),
        "bases": tuple(b[k] for b in tape.bases),
        "raws": tuple(r[k] for r in tape.raws),
        # periodic lookup tables are whole-tape (not per-shard); they
        # ride every slab dict so the gather stays a traced operand
        "tables": tuple(tape.tables),
        "invs": tuple(
            np.asarray([iv for _, iv in g], np.float32) for g in groups
        ),
        "row0": np.int32(int(np.asarray(tape.starts)[k])),
    }


def _decode_shard_impl(columns: Tuple[ColumnSpec, ...], shard_bars: int,
                       window_size: int, mode: str, slab: Dict[str, Any]):
    """Traceable decode of one shard's arrays into a MarketData.

    q16 f32 sources are decoded FUSED: all unique (slab, inv) pairs of
    equal row count go through one kernel launch (Pallas when
    ``mode != "off"`` permits, ops/tape_decode.py; the pure-XLA
    :func:`decode_q16_ref` is the bitwise oracle).
    """
    import jax.numpy as jnp

    from gymfx_tpu.data.feed import MarketData

    slabs, bases, raws = slab["slabs"], slab["bases"], slab["raws"]
    R = int(shard_bars) + 1

    # fused decode of every unique q16 f32 source, grouped by row count;
    # divisors come in as runtime arrays (slab["invs"]) — constants
    # would let XLA rewrite the division as a reciprocal multiply
    groups = _q16_groups(columns, [s.shape[0] for s in slabs])
    decoded_q16: Dict[Tuple[int, float], Any] = {}
    for gi, items in enumerate(groups):
        delta = jnp.stack([slabs[s] for s, _ in items])
        base = jnp.stack([bases[s] for s, _ in items])
        inv = slab["invs"][gi]
        if mode == "off":
            out = decode_q16_ref(delta, base, inv)
        else:
            from gymfx_tpu.ops.tape_decode import decode_q16_block

            out = decode_q16_block(
                delta, base, inv,
                interpret=True if mode == "interpret" else None,
            )
        for i, key in enumerate(items):
            decoded_q16[key] = out[i]

    def column_rows(field: str) -> int:
        if field in ("padded_close", "padded_features"):
            return R + int(window_size)
        if field in ("feat_mean", "feat_std", "feat_neutral"):
            return R + 1
        return R

    def decode_column(c: ColumnSpec, int_field: bool):
        if c.kind == "q16":
            return decoded_q16[(c.src, c.inv)]
        if c.kind in ("i16", "u8"):
            return bases[c.src] + slabs[c.src].astype(jnp.int32)
        if c.kind == "iperiodic":
            t = slab["tables"][c.src]
            rows = column_rows(c.field)
            idx = (
                slab["row0"] + jnp.arange(rows, dtype=jnp.int32)
            ) % t.shape[0]
            return t[idx]
        if c.kind == "periodic":
            return slab["tables"][c.src][minute_idx]
        if c.kind == "bits":
            return (
                (slabs[c.src] >> np.uint8(c.bit)) & np.uint8(1)
            ).astype(jnp.float32)
        if c.kind == "const":
            rows = column_rows(c.field)
            if int_field:
                return jnp.full((rows,), np.int32(c.value), jnp.int32)
            return jnp.full((rows,), np.float32(c.value), jnp.float32)
        return raws[c.src]

    # periodic columns gather by the decoded minute_of_week — decode it
    # once up front (a gather of stored bits is exact by construction)
    minute_idx = None
    if any(c.kind == "periodic" for c in columns):
        mspec = next(c for c in columns if c.field == "minute_of_week")
        minute_idx = decode_column(mspec, True)

    by_field: Dict[str, List[ColumnSpec]] = {}
    for c in columns:
        by_field.setdefault(c.field, []).append(c)

    fields: Dict[str, Any] = {"row0": slab["row0"]}
    for field, specs in by_field.items():
        int_field = field in ("minute_of_week", "scen_flags")
        if len(specs) == 1 and specs[0].col < 0:
            fields[field] = decode_column(specs[0], int_field)
        else:
            cols = [
                decode_column(c, int_field)
                for c in sorted(specs, key=lambda c: c.col)
            ]
            fields[field] = jnp.stack(cols, axis=1)
    return MarketData(**fields)


def make_shard_decoder(tape: CompressedTape, mode: str):
    """Jitted ``slab_dict -> MarketData`` decoder for one tape geometry
    (all shards share it — static shapes, one executable)."""
    import functools

    import jax

    fn = functools.partial(
        _decode_shard_impl, tape.columns, tape.shard_bars,
        tape.window_size, validate_compress_mode(mode),
    )
    return jax.jit(fn)


def decode_shard_ref(tape: CompressedTape, k: int):
    """Pure-XLA decode of shard ``k`` (the parity/bit-identity oracle in
    tests; not jitted — convenience wrapper)."""
    return _decode_shard_impl(
        tape.columns, tape.shard_bars, tape.window_size, "off",
        shard_arrays(tape, k),
    )


def device_tape(tape: CompressedTape, placement=None) -> CompressedTape:
    """device_put every compressed slab (optionally with an explicit
    sharding — ShardedRuntime passes its replicated placement)."""
    import jax

    if placement is not None:
        put = lambda x: jax.device_put(x, placement)  # noqa: E731
    else:
        put = jax.device_put
    return tape._replace(
        slabs=tuple(put(s) for s in tape.slabs),
        bases=tuple(put(b) for b in tape.bases),
        raws=tuple(put(r) for r in tape.raws),
        tables=tuple(put(t) for t in tape.tables),
    )
