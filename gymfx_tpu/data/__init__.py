from gymfx_tpu.data.feed import MarketDataset, load_market_dataset  # noqa: F401
