"""OANDA FX calendar — DST-aware America/New_York policy, precomputed.

Zoneinfo/timezone logic cannot (and should not) run inside an XLA
program, so the calendar is resolved host-side ONCE per dataset into
per-bar feature columns that ship to the device as part of the market
tensor.  The policy constants, window predicates and feature semantics
match the reference pure-function library (reference
app/oanda_calendar.py:30-240); the scalar predicates below are kept for
API parity and for DST proof tests with paired summer/winter timestamps
(reference tests/test_oanda_calendar.py:44-63).
"""
from __future__ import annotations

import datetime as _dt
from typing import Any, Dict, Mapping, Optional

import numpy as np
import pandas as pd
from zoneinfo import ZoneInfo

OANDA_FX_TIMEZONE = "America/New_York"
CALENDAR_POLICY_ID = "oanda_us_fx_ny_v1"

# Policy times (New York local). Mon=0 .. Sun=6.
WEEKLY_OPEN_DOW = 6          # Sunday
WEEKLY_OPEN_HM = (17, 5)
WEEKLY_CLOSE_DOW = 4         # Friday
WEEKLY_CLOSE_HM = (16, 59)
DAILY_BREAK_START_HM = (16, 59)
DAILY_BREAK_END_HM = (17, 5)
NO_TRADE_WINDOW_START_HM = (16, 50)
NO_TRADE_WINDOW_END_HM = (17, 10)
FRIDAY_NO_NEW_POSITION_HM = (14, 0)
FRIDAY_RISK_REDUCTION_HM = (15, 0)
FRIDAY_FORCE_FLAT_HM = (15, 45)
FRIDAY_LAST_EXIT_HM = (15, 55)
BROKER_DAILY_BREAK_NEAR_MINUTES = 30

_NY = ZoneInfo(OANDA_FX_TIMEZONE)

CALENDAR_FEATURE_KEYS = (
    "hours_to_fx_daily_break",
    "bars_to_fx_daily_break",
    "hours_to_friday_close",
    "bars_to_friday_close",
    "is_friday_risk_reduction_window",
    "is_no_new_position_window",
    "is_force_flat_window",
    "is_broker_daily_break_near",
    "broker_market_open",
    "is_no_trade_window",
)

FORCE_CLOSE_FEATURE_KEYS = (
    "bars_to_force_close",
    "hours_to_force_close",
    "is_force_close_zone",
    "is_monday_entry_window",
)


def _hm_minutes(hm) -> int:
    return hm[0] * 60 + hm[1]


# ----------------------------------------------------------------------
# Scalar API (host-side; parity with the reference predicate surface)
# ----------------------------------------------------------------------
def to_ny(ts: Any) -> Optional[_dt.datetime]:
    """Coerce a timestamp-like value into an aware NY datetime.

    Naive inputs are treated as UTC.  Returns None when unparseable.
    """
    if ts is None:
        return None
    if isinstance(ts, pd.Timestamp):
        if ts is pd.NaT:
            return None
        # Plain datetime, not pd.Timestamp: wall-clock (not absolute)
        # timedelta arithmetic is required for next-break/next-close math
        # to match the reference's datetime-based policy across DST.
        dt = ts.to_pydatetime()
    elif isinstance(ts, _dt.datetime):
        dt = ts
    else:
        try:
            parsed = pd.to_datetime(str(ts).strip(), errors="coerce")
        except (TypeError, ValueError):
            return None
        if parsed is None or parsed is pd.NaT:
            return None
        dt = parsed.to_pydatetime()
    if dt.tzinfo is None:
        dt = dt.replace(tzinfo=_dt.timezone.utc)
    return dt.astimezone(_NY)


def _minute_of_day(dt: _dt.datetime) -> int:
    return dt.hour * 60 + dt.minute


def is_no_new_position_window(dt_ny: _dt.datetime) -> bool:
    """True from Friday 14:00 NY through weekly close."""
    if dt_ny.weekday() != WEEKLY_CLOSE_DOW:
        return False
    mod = _minute_of_day(dt_ny)
    return _hm_minutes(FRIDAY_NO_NEW_POSITION_HM) <= mod < _hm_minutes(WEEKLY_CLOSE_HM)


def is_friday_risk_reduction_window(dt_ny: _dt.datetime) -> bool:
    """True from Friday 15:00 NY through weekly close."""
    if dt_ny.weekday() != WEEKLY_CLOSE_DOW:
        return False
    mod = _minute_of_day(dt_ny)
    return _hm_minutes(FRIDAY_RISK_REDUCTION_HM) <= mod < _hm_minutes(WEEKLY_CLOSE_HM)


def is_force_flat_window(dt_ny: _dt.datetime) -> bool:
    """True from Friday 15:45 NY through weekly close."""
    if dt_ny.weekday() != WEEKLY_CLOSE_DOW:
        return False
    mod = _minute_of_day(dt_ny)
    return _hm_minutes(FRIDAY_FORCE_FLAT_HM) <= mod < _hm_minutes(WEEKLY_CLOSE_HM)


def is_broker_daily_break_near(
    dt_ny: _dt.datetime, *, near_minutes: int = BROKER_DAILY_BREAK_NEAR_MINUTES
) -> bool:
    """True within ``near_minutes`` before, or inside, the 16:59-17:05 break."""
    mod = _minute_of_day(dt_ny)
    start = _hm_minutes(DAILY_BREAK_START_HM)
    end = _hm_minutes(DAILY_BREAK_END_HM)
    if start <= mod < end:
        return True
    return start - near_minutes < mod < start


def is_no_trade_window(dt_ny: _dt.datetime) -> bool:
    """Project no-trade window: 16:50-17:10 NY (covers the FX break)."""
    mod = _minute_of_day(dt_ny)
    return _hm_minutes(NO_TRADE_WINDOW_START_HM) <= mod < _hm_minutes(NO_TRADE_WINDOW_END_HM)


def broker_market_open(dt_ny: _dt.datetime) -> bool:
    """True between Sun 17:05 NY and Fri 16:59 NY, excluding the daily break."""
    mod = _minute_of_day(dt_ny)
    dow = dt_ny.weekday()
    if dow == 5:  # Saturday
        return False
    if dow == WEEKLY_OPEN_DOW:
        return mod >= _hm_minutes(WEEKLY_OPEN_HM)
    if dow == WEEKLY_CLOSE_DOW and mod >= _hm_minutes(WEEKLY_CLOSE_HM):
        return False
    if _hm_minutes(DAILY_BREAK_START_HM) <= mod < _hm_minutes(DAILY_BREAK_END_HM):
        return False
    return True


def compute_fx_calendar_features(
    ts: Any, *, timeframe_hours: float = 4.0
) -> Dict[str, float]:
    """Single-timestamp calendar feature dict (neutral zeros on failure)."""
    neutral = {k: 0.0 for k in CALENDAR_FEATURE_KEYS}
    dt_ny = to_ny(ts)
    if dt_ny is None:
        return neutral
    tf_h = max(float(timeframe_hours or 0.0), 1e-9)

    hours_to_break = (_next_daily_break(dt_ny) - dt_ny).total_seconds() / 3600.0
    hours_to_close = (_next_friday_close(dt_ny) - dt_ny).total_seconds() / 3600.0
    return {
        "hours_to_fx_daily_break": float(max(hours_to_break, 0.0)),
        "bars_to_fx_daily_break": float(max(hours_to_break, 0.0) / tf_h),
        "hours_to_friday_close": float(max(hours_to_close, 0.0)),
        "bars_to_friday_close": float(max(hours_to_close, 0.0) / tf_h),
        "is_friday_risk_reduction_window": float(is_friday_risk_reduction_window(dt_ny)),
        "is_no_new_position_window": float(is_no_new_position_window(dt_ny)),
        "is_force_flat_window": float(is_force_flat_window(dt_ny)),
        "is_broker_daily_break_near": float(is_broker_daily_break_near(dt_ny)),
        "broker_market_open": float(broker_market_open(dt_ny)),
        "is_no_trade_window": float(is_no_trade_window(dt_ny)),
    }


def _next_daily_break(now_ny: _dt.datetime) -> _dt.datetime:
    """Next 16:59 NY (wall clock) at or after ``now_ny``."""
    today = now_ny.replace(
        hour=DAILY_BREAK_START_HM[0],
        minute=DAILY_BREAK_START_HM[1],
        second=0,
        microsecond=0,
    )
    if today <= now_ny:
        today += _dt.timedelta(days=1)
    return today


def _next_friday_close(now_ny: _dt.datetime) -> _dt.datetime:
    """Next Friday 16:59 NY (wall clock) at or after ``now_ny``."""
    days_ahead = (WEEKLY_CLOSE_DOW - now_ny.weekday()) % 7
    candidate = now_ny.replace(
        hour=WEEKLY_CLOSE_HM[0],
        minute=WEEKLY_CLOSE_HM[1],
        second=0,
        microsecond=0,
    ) + _dt.timedelta(days=days_ahead)
    if candidate < now_ny:
        candidate += _dt.timedelta(days=7)
    return candidate


def resolve_broker_metadata(config: Mapping[str, Any]) -> Dict[str, Optional[str]]:
    return {
        "broker_profile": config.get("broker_profile"),
        "market_type": config.get("market_type"),
        "trade_rate_band_id": config.get("trade_rate_band_id"),
        "calendar_policy_id": config.get("calendar_policy_id"),
    }


# ----------------------------------------------------------------------
# Vectorized precompute (the TPU path): timestamps -> per-bar columns
# ----------------------------------------------------------------------
def _as_ny_index(timestamps: pd.Series | pd.DatetimeIndex) -> pd.DatetimeIndex:
    idx = pd.DatetimeIndex(pd.to_datetime(pd.Series(np.asarray(timestamps)), errors="coerce"))
    if idx.tz is None:
        idx = idx.tz_localize("UTC")
    return idx.tz_convert(OANDA_FX_TIMEZONE)


def precompute_fx_calendar_features(
    timestamps, *, timeframe_hours: float = 4.0
) -> np.ndarray:
    """Vectorized calendar features: (n, 10) float32 in CALENDAR_FEATURE_KEYS order.

    Wall-clock "next break / next Friday close" arithmetic is done in NY
    local time and differenced in UTC, so hours-to-X correctly spans DST
    transitions exactly like the scalar reference semantics.
    Unparseable timestamps produce an all-zero (neutral) row.
    """
    tf_h = max(float(timeframe_hours or 0.0), 1e-9)
    ny = _as_ny_index(timestamps)
    n = len(ny)
    out = np.zeros((n, len(CALENDAR_FEATURE_KEYS)), dtype=np.float32)
    valid = ~ny.isna()
    if not valid.any():
        return out
    nyv = ny[valid]

    dow = nyv.weekday.to_numpy()
    mod = (nyv.hour * 60 + nyv.minute).to_numpy()

    # Wall-clock differences in NY local time.  The reference subtracts
    # two datetimes sharing one ZoneInfo, which Python defines as naive
    # wall-clock subtraction (reference app/oanda_calendar.py:229-230) —
    # so hours-to-X are NY wall-clock hours, not absolute elapsed hours,
    # on DST transition days.  Reproduced here deliberately.
    naive = nyv.tz_localize(None)
    floor_day = naive.normalize()

    # -- next daily break (16:59 NY wall clock, today or tomorrow) --------
    break_minutes = _hm_minutes(DAILY_BREAK_START_HM)
    today_break = floor_day + pd.Timedelta(minutes=break_minutes)
    need_tomorrow = today_break <= naive
    next_break_wall = today_break + pd.to_timedelta(np.where(need_tomorrow, 1, 0), unit="D")
    hours_to_break = ((next_break_wall - naive).total_seconds() / 3600.0).to_numpy()

    # -- next Friday 16:59 NY wall clock ----------------------------------
    close_minutes = _hm_minutes(WEEKLY_CLOSE_HM)
    days_ahead = (WEEKLY_CLOSE_DOW - dow) % 7
    candidate_wall = floor_day + pd.to_timedelta(days_ahead, unit="D") + pd.Timedelta(
        minutes=close_minutes
    )
    passed = candidate_wall < naive
    candidate_wall = candidate_wall + pd.to_timedelta(np.where(passed, 7, 0), unit="D")
    hours_to_close = ((candidate_wall - naive).total_seconds() / 3600.0).to_numpy()

    # -- window predicates (pure minute-of-day/dow arithmetic) ------------
    is_friday = dow == WEEKLY_CLOSE_DOW
    before_close = mod < close_minutes
    risk_red = is_friday & (mod >= _hm_minutes(FRIDAY_RISK_REDUCTION_HM)) & before_close
    no_new = is_friday & (mod >= _hm_minutes(FRIDAY_NO_NEW_POSITION_HM)) & before_close
    force_flat = is_friday & (mod >= _hm_minutes(FRIDAY_FORCE_FLAT_HM)) & before_close

    brk_start = _hm_minutes(DAILY_BREAK_START_HM)
    brk_end = _hm_minutes(DAILY_BREAK_END_HM)
    in_break = (mod >= brk_start) & (mod < brk_end)
    break_near = in_break | ((mod > brk_start - BROKER_DAILY_BREAK_NEAR_MINUTES) & (mod < brk_start))

    no_trade = (mod >= _hm_minutes(NO_TRADE_WINDOW_START_HM)) & (
        mod < _hm_minutes(NO_TRADE_WINDOW_END_HM)
    )

    open_mask = np.ones(len(nyv), dtype=bool)
    open_mask &= dow != 5  # Saturday closed
    sunday = dow == WEEKLY_OPEN_DOW
    open_mask &= ~sunday | (mod >= _hm_minutes(WEEKLY_OPEN_HM))
    open_mask &= ~(is_friday & (mod >= close_minutes))
    open_mask &= ~(~sunday & in_break)  # Mon-Fri daily break (Sunday handled above)

    block = np.stack(
        [
            np.maximum(hours_to_break, 0.0),
            np.maximum(hours_to_break, 0.0) / tf_h,
            np.maximum(hours_to_close, 0.0),
            np.maximum(hours_to_close, 0.0) / tf_h,
            risk_red.astype(np.float64),
            no_new.astype(np.float64),
            force_flat.astype(np.float64),
            break_near.astype(np.float64),
            open_mask.astype(np.float64),
            no_trade.astype(np.float64),
        ],
        axis=1,
    ).astype(np.float32)
    out[np.asarray(valid)] = block
    return out


def precompute_force_close_features(
    timestamps,
    *,
    timeframe_hours: float,
    force_close_dow: int = 4,
    force_close_hour: int = 20,
    force_close_window_hours: int = 4,
    monday_entry_window_hours: int = 4,
) -> np.ndarray:
    """Stage-B force-close features: (n, 4) float32 in FORCE_CLOSE_FEATURE_KEYS order.

    Matches the reference semantics (reference app/env.py:530-584): raw
    (naive/UTC) weekday+hour arithmetic at hour granularity, no timezone
    conversion; unparseable timestamps yield neutral zeros.
    """
    tf_hours = float(timeframe_hours) or 1.0
    idx = pd.DatetimeIndex(pd.to_datetime(pd.Series(np.asarray(timestamps)), errors="coerce"))
    if idx.tz is not None:
        idx = idx.tz_localize(None)
    n = len(idx)
    out = np.zeros((n, 4), dtype=np.float32)
    valid = ~idx.isna()
    if not valid.any():
        return out
    v = idx[valid]
    dow = v.weekday.to_numpy()
    hour = v.hour.to_numpy()

    days_ahead = (force_close_dow - dow) % 7
    target_hours = days_ahead * 24 + (force_close_hour - hour)
    target_hours = np.where(target_hours < 0, target_hours + 7 * 24, target_hours)
    hours_to_fc = target_hours.astype(np.float64)
    bars_to_fc = hours_to_fc / max(tf_hours, 1e-9)
    in_zone = (dow == force_close_dow) & (hour >= force_close_hour) & (
        hour < force_close_hour + force_close_window_hours
    )
    in_monday = (dow == 0) & (hour < monday_entry_window_hours)

    out[np.asarray(valid)] = np.stack(
        [
            bars_to_fc,
            hours_to_fc,
            in_zone.astype(np.float64),
            in_monday.astype(np.float64),
        ],
        axis=1,
    ).astype(np.float32)
    return out


def precompute_minute_of_week(timestamps) -> np.ndarray:
    """Raw-timestamp minute-of-week (Mon 00:00 = 0), int32; -1 when invalid.

    Used by the session/weekend filter, which in the reference compares
    raw bar datetimes at minute-of-week granularity
    (reference strategy_plugins/direct_atr_sltp.py:320-342).
    """
    idx = pd.DatetimeIndex(pd.to_datetime(pd.Series(np.asarray(timestamps)), errors="coerce"))
    if idx.tz is not None:
        idx = idx.tz_localize(None)
    out = np.full(len(idx), -1, dtype=np.int32)
    valid = ~idx.isna()
    if valid.any():
        v = idx[valid]
        mow = v.weekday.to_numpy() * 24 * 60 + v.hour.to_numpy() * 60 + v.minute.to_numpy()
        out[np.asarray(valid)] = mow.astype(np.int32)
    return out
