"""Market data pipeline: CSV -> host dataset -> columnar device arrays.

Load semantics match the reference default data feed (reference
data_feed_plugins/default_data_feed.py:36-56): CSV via pandas, datetime
index from ``date_column`` with unparseable rows dropped, missing
OHLC columns backfilled from ``price_column``, VOLUME defaulted to 0.

Instead of wrapping rows in a backtrader feed object, the dataset is
resolved ONCE into static-shaped device arrays (``MarketData``): prices,
padded window sources, per-bar NY-calendar/force-close feature columns
and leakage-safe scaling moments.  Every per-step computation inside
``jit`` is then a ``dynamic_slice`` + fused elementwise math — no pandas,
no Python objects, no data-dependent shapes.
"""
from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

from gymfx_tpu.data import calendar as fxcal

OHLC_COLUMNS = ("OPEN", "HIGH", "LOW", "CLOSE")


class MarketData(NamedTuple):
    """Static-shaped per-dataset device arrays consumed by the env kernel.

    All arrays are time-major over ``n`` bars.  ``padded_close`` /
    ``padded_features`` are front-padded with the first row so the obs
    window at step ``t`` is a pure ``dynamic_slice`` at offset ``t``
    (reference front-pad semantics:
    preprocessor_plugins/default_preprocessor.py:47-52).
    """

    open: Any          # (n,) compute dtype
    high: Any          # (n,)
    low: Any           # (n,)
    close: Any         # (n,)
    volume: Any        # (n,)
    padded_close: Any  # (n + window_size,)
    minute_of_week: Any  # (n,) int32, -1 when timestamp invalid
    calendar: Any      # (n, 10) float32 — fxcal.CALENDAR_FEATURE_KEYS order
    force_close: Any   # (n, 4) float32 — fxcal.FORCE_CLOSE_FEATURE_KEYS order
    ev_no_trade: Any   # (n,) float32
    ev_spread_mult: Any  # (n,) float32
    ev_slip_mult: Any  # (n,) float32
    rollover_accrual: Any  # (n,) compute dtype — daily financing rate on
                           # rollover bars, 0 elsewhere (data/financing.py)
    padded_features: Any  # (n + window_size, F) float32 (F may be 0)
    feat_mean: Any     # (n + 1, F) float32 — scaler mean fit on strictly-past rows
    feat_std: Any      # (n + 1, F) float32
    feat_neutral: Any  # (n + 1,) bool — True => neutral zero warm-up window

    @property
    def n_bars(self) -> int:
        return int(self.close.shape[0])


def _infer_timeframe_hours(config: Dict[str, Any]) -> float:
    """Timeframe label ('M1', 'h4', 'xx_15m', ...) -> hours (reference app/env.py:510-528)."""
    raw = str(
        config.get("timeframe")
        or config.get("timeframe_label")
        or config.get("bar_timeframe")
        or ""
    ).strip().lower()
    if "_" in raw:
        raw = raw.rsplit("_", 1)[-1]
    try:
        if raw.endswith("m") and raw[:-1].isdigit():
            return max(0.0, int(raw[:-1]) / 60.0)
        if raw.endswith("h") and raw[:-1].isdigit():
            return float(int(raw[:-1]))
        if raw.endswith("d") and raw[:-1].isdigit():
            return float(int(raw[:-1]) * 24)
        # leading-letter style: M1 / H4 / D1
        if raw[:1] == "m" and raw[1:].isdigit():
            return max(0.0, int(raw[1:]) / 60.0)
        if raw[:1] == "h" and raw[1:].isdigit():
            return float(int(raw[1:]))
        if raw[:1] == "d" and raw[1:].isdigit():
            return float(int(raw[1:]) * 24)
    except ValueError:
        return 0.0
    return 0.0


class MarketDataset:
    """Host-side dataset: the loaded dataframe + device-array builders."""

    def __init__(self, dataframe: pd.DataFrame, config: Dict[str, Any]):
        self.dataframe = dataframe
        self.config = dict(config)
        self.date_column = str(config.get("date_column", "DATE_TIME"))
        self.price_column = str(config.get("price_column", "CLOSE"))
        self.timeframe_hours = _infer_timeframe_hours(config)
        if isinstance(dataframe.index, pd.DatetimeIndex):
            self.timestamps = pd.Series(dataframe.index)
        elif self.date_column in dataframe.columns:
            self.timestamps = pd.to_datetime(
                dataframe[self.date_column], errors="coerce"
            ).reset_index(drop=True)
        else:
            self.timestamps = pd.Series(pd.DatetimeIndex([pd.NaT] * len(dataframe)))

    def __len__(self) -> int:
        return len(self.dataframe)

    def bar_interval_ms(self) -> Optional[float]:
        """Milliseconds per bar: from the timeframe label when present,
        else the median spacing of valid timestamps; None when neither
        is available (callers that need it must reject, not guess)."""
        if self.timeframe_hours:
            return self.timeframe_hours * 3_600_000.0
        ts = pd.to_datetime(self.timestamps, errors="coerce").dropna()
        if len(ts) < 2:
            return None
        deltas = ts.diff().dropna().dt.total_seconds()
        median = float(deltas.median())
        return median * 1000.0 if median > 0 else None

    # ------------------------------------------------------------------
    def build_market_data(
        self,
        *,
        window_size: int,
        feature_columns: Sequence[str] = (),
        feature_scaling: str = "rolling_zscore",
        feature_scaling_window: int = 256,
        dtype: Any = np.float32,
        event_context_no_trade_column: str = "event_no_trade_window_active",
        event_context_spread_stress_column: str = "event_spread_stress_multiplier",
        event_context_slippage_stress_column: str = "event_slippage_stress_multiplier",
        force_close_dow: int = 4,
        force_close_hour: int = 20,
        force_close_window_hours: int = 4,
        monday_entry_window_hours: int = 4,
        financing_rate_data: Any = None,
        instrument: str = "EUR_USD",
    ) -> MarketData:
        df = self.dataframe
        n = len(df)
        if n < window_size + 2:
            raise ValueError("input data is empty or too short for the configured window")

        close = df[self.price_column].to_numpy(dtype=np.float64, copy=False)

        def col(name: str, fallback) -> np.ndarray:
            if name in df.columns:
                return df[name].to_numpy(dtype=np.float64, copy=False)
            if np.isscalar(fallback):
                return np.full(n, float(fallback), dtype=np.float64)
            return fallback

        o = col("OPEN", close)
        h = col("HIGH", close)
        l = col("LOW", close)
        c = col("CLOSE", close)
        v = col("VOLUME", 0.0)

        padded_close = np.concatenate([np.full(window_size, close[0]), close])

        tf_h = self.timeframe_hours or 1.0
        cal = fxcal.precompute_fx_calendar_features(
            self.timestamps, timeframe_hours=tf_h
        )
        fcz = fxcal.precompute_force_close_features(
            self.timestamps,
            timeframe_hours=self.timeframe_hours,
            force_close_dow=force_close_dow,
            force_close_hour=force_close_hour,
            force_close_window_hours=force_close_window_hours,
            monday_entry_window_hours=monday_entry_window_hours,
        )
        mow = fxcal.precompute_minute_of_week(self.timestamps)

        ev_no_trade = col(event_context_no_trade_column, 0.0).astype(np.float32)
        ev_spread = col(event_context_spread_stress_column, 1.0).astype(np.float32)
        ev_slip = col(event_context_slippage_stress_column, 1.0).astype(np.float32)

        if financing_rate_data is not None:
            from gymfx_tpu.data import financing as fxfin

            base_ccy, quote_ccy = fxfin.split_pair(instrument)
            accrual = fxfin.precompute_rollover_accrual(
                self.timestamps, financing_rate_data, base_ccy, quote_ccy
            )
        else:
            accrual = np.zeros(n, dtype=np.float64)

        padded_features, feat_mean, feat_std, feat_neutral = _build_feature_tensors(
            df,
            feature_columns=tuple(feature_columns),
            window_size=window_size,
            scaling=feature_scaling,
            scaling_window=feature_scaling_window,
        )

        import jax.numpy as jnp

        f32 = np.float32
        return MarketData(
            open=jnp.asarray(o, dtype=dtype),
            high=jnp.asarray(h, dtype=dtype),
            low=jnp.asarray(l, dtype=dtype),
            close=jnp.asarray(c, dtype=dtype),
            volume=jnp.asarray(v, dtype=dtype),
            padded_close=jnp.asarray(padded_close, dtype=dtype),
            minute_of_week=jnp.asarray(mow, dtype=jnp.int32),
            calendar=jnp.asarray(cal, dtype=f32),
            force_close=jnp.asarray(fcz, dtype=f32),
            ev_no_trade=jnp.asarray(ev_no_trade, dtype=f32),
            ev_spread_mult=jnp.asarray(ev_spread, dtype=f32),
            ev_slip_mult=jnp.asarray(ev_slip, dtype=f32),
            rollover_accrual=jnp.asarray(accrual, dtype=dtype),
            padded_features=jnp.asarray(padded_features, dtype=f32),
            feat_mean=jnp.asarray(feat_mean, dtype=f32),
            feat_std=jnp.asarray(feat_std, dtype=f32),
            feat_neutral=jnp.asarray(feat_neutral, dtype=bool),
        )


def _build_feature_tensors(
    df: pd.DataFrame,
    *,
    feature_columns: Tuple[str, ...],
    window_size: int,
    scaling: str,
    scaling_window: int,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Feature matrix + per-step leakage-safe scaler moments.

    The reference re-fits a z-score over up to ``feature_scaling_window``
    strictly-past rows per step per env (reference
    preprocessor_plugins/feature_window_preprocessor.py:174-191) — the
    obs hot spot.  Here the mean/std for every possible step are derived
    once from f64 cumulative moments: O(n·F) precompute, O(1) lookup per
    step in-graph.  Windows with <2 history rows are flagged neutral
    (zero warm-up, reference :112-117).
    """
    n = len(df)
    f = len(feature_columns)
    if f == 0:
        return (
            np.zeros((n + window_size, 0), np.float32),
            np.zeros((n + 1, 0), np.float32),
            np.ones((n + 1, 0), np.float32),
            np.zeros((n + 1,), bool),
        )
    missing = [cname for cname in feature_columns if cname not in df.columns]
    if missing:
        raise ValueError(
            "feature_window preprocessor: configured feature_columns "
            f"missing from dataframe: {missing[:5]}{'...' if len(missing) > 5 else ''}"
        )
    values = df[list(feature_columns)].to_numpy(dtype=np.float64)
    padded = np.concatenate([np.tile(values[0], (window_size, 1)), values], axis=0)

    if scaling == "none":
        mean = np.zeros((n + 1, f), np.float64)
        std = np.ones((n + 1, f), np.float64)
        neutral = np.zeros((n + 1,), bool)
        return padded.astype(np.float32), mean.astype(np.float32), std.astype(np.float32), neutral

    s1 = np.concatenate([np.zeros((1, f)), np.cumsum(values, axis=0)], axis=0)
    s2 = np.concatenate([np.zeros((1, f)), np.cumsum(values**2, axis=0)], axis=0)
    t = np.arange(n + 1)
    if scaling == "rolling_zscore":
        lo = np.maximum(0, t - int(scaling_window))
    elif scaling == "expanding_zscore":
        lo = np.zeros(n + 1, dtype=np.int64)
    else:
        raise ValueError(
            "feature_scaling must be one of ('none', 'rolling_zscore', "
            f"'expanding_zscore'); got {scaling!r}"
        )
    count = (t - lo).astype(np.float64)
    safe_count = np.maximum(count, 1.0)[:, None]
    mean = (s1[t] - s1[lo]) / safe_count
    var = (s2[t] - s2[lo]) / safe_count - mean**2
    std = np.sqrt(np.maximum(var, 0.0))
    std = np.where(std < 1e-8, 1.0, std)
    neutral = count < 2
    mean = np.where(neutral[:, None], 0.0, mean)
    std = np.where(neutral[:, None], 1.0, std)
    return (
        padded.astype(np.float32),
        mean.astype(np.float32),
        std.astype(np.float32),
        neutral,
    )


def load_dataframe(config: Dict[str, Any]) -> pd.DataFrame:
    """CSV -> dataframe with datetime index and OHLCV backfill.

    Canonical bar files (exactly the DATE_TIME,OHLCV schema) go through
    the native C++ columnar parser when it is available; anything else —
    extra feature columns, custom date column, headerless files — takes
    the pandas path with identical semantics."""
    file_path = config.get("input_data_file")
    if not file_path:
        raise ValueError("config key 'input_data_file' is required")
    headers = bool(config.get("headers", True))
    max_rows = config.get("max_rows")

    if (
        headers
        and max_rows is None  # pandas' nrows stops early; native would not
        and str(config.get("date_column", "DATE_TIME")) == "DATE_TIME"
        and str(config.get("price_column", "CLOSE")) == "CLOSE"
    ):
        from gymfx_tpu.data.native_loader import load_ohlcv_csv

        native = load_ohlcv_csv(str(file_path))
        if native is not None:
            return native

    df = pd.read_csv(file_path, header=0 if headers else None, nrows=max_rows)

    date_col = str(config.get("date_column", "DATE_TIME"))
    if date_col in df.columns:
        df[date_col] = pd.to_datetime(df[date_col], errors="coerce")
        df = df.dropna(subset=[date_col]).set_index(date_col)

    price_col = str(config.get("price_column", "CLOSE"))
    if price_col not in df.columns:
        raise ValueError(f"price_column '{price_col}' not found in data")
    for column in OHLC_COLUMNS:
        if column not in df.columns:
            df[column] = df[price_col]
    if "VOLUME" not in df.columns:
        df["VOLUME"] = 0
    return df


def load_market_dataset(config: Dict[str, Any]) -> MarketDataset:
    return MarketDataset(load_dataframe(config), config)
