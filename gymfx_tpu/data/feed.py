"""Market data pipeline: CSV -> host dataset -> columnar device arrays.

Load semantics match the reference default data feed (reference
data_feed_plugins/default_data_feed.py:36-56): CSV via pandas, datetime
index from ``date_column`` with unparseable rows dropped, missing
OHLC columns backfilled from ``price_column``, VOLUME defaulted to 0.

Instead of wrapping rows in a backtrader feed object, the dataset is
resolved ONCE into static-shaped device arrays (``MarketData``): prices,
padded window sources, per-bar NY-calendar/force-close feature columns
and leakage-safe scaling moments.  Every per-step computation inside
``jit`` is then a ``dynamic_slice`` + fused elementwise math — no pandas,
no Python objects, no data-dependent shapes.
"""
from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

from gymfx_tpu.data import calendar as fxcal

OHLC_COLUMNS = ("OPEN", "HIGH", "LOW", "CLOSE")


class MarketData(NamedTuple):
    """Static-shaped per-dataset device arrays consumed by the env kernel.

    All arrays are time-major over ``n`` bars.  ``padded_close`` /
    ``padded_features`` are front-padded with the first row so the obs
    window at step ``t`` is a pure ``dynamic_slice`` at offset ``t``
    (reference front-pad semantics:
    preprocessor_plugins/default_preprocessor.py:47-52).
    """

    open: Any          # (n,) compute dtype
    high: Any          # (n,)
    low: Any           # (n,)
    close: Any         # (n,)
    volume: Any        # (n,)
    padded_close: Any  # (n + window_size,)
    minute_of_week: Any  # (n,) int32, -1 when timestamp invalid
    calendar: Any      # (n, 10) float32 — fxcal.CALENDAR_FEATURE_KEYS order
    force_close: Any   # (n, 4) float32 — fxcal.FORCE_CLOSE_FEATURE_KEYS order
    ev_no_trade: Any   # (n,) float32
    ev_spread_mult: Any  # (n,) float32
    ev_slip_mult: Any  # (n,) float32
    rollover_accrual: Any  # (n,) compute dtype — daily financing rate on
                           # rollover bars, 0 elsewhere (data/financing.py)
    padded_features: Any  # (n + window_size, F) float32 (F may be 0)
    feat_mean: Any     # (n + 1, F) float32 — scaler mean fit on strictly-past rows
    feat_std: Any      # (n + 1, F) float32
    feat_neutral: Any  # (n + 1,) bool — True => neutral zero warm-up window
    # global bar row of local array index 0.  Always 0 for a fully
    # resident dataset; a streamed shard (shard_market_data) carries the
    # shard's start row here so the env kernel can keep GLOBAL bar
    # cursors (state.t) and rebase every array read by -row0 — one
    # compiled program serves every shard.
    row0: Any = 0
    # (n,) int32 per-bar scenario bitmask (scengen/params.py FLAG_*):
    # zeros on every replayed feed; generated feeds carry the active
    # regime/overlay so venue=lob can thin its flow with the tape.
    # Reads are gated behind the static lob_flow_from_scengen config
    # flag, so replay-path programs never trace this leaf.
    scen_flags: Any = 0

    @property
    def n_bars(self) -> int:
        return int(self.close.shape[0])


def _infer_timeframe_hours(config: Dict[str, Any]) -> float:
    """Timeframe label ('M1', 'h4', 'xx_15m', ...) -> hours (reference app/env.py:510-528)."""
    raw = str(
        config.get("timeframe")
        or config.get("timeframe_label")
        or config.get("bar_timeframe")
        or ""
    ).strip().lower()
    if "_" in raw:
        raw = raw.rsplit("_", 1)[-1]
    try:
        if raw.endswith("m") and raw[:-1].isdigit():
            return max(0.0, int(raw[:-1]) / 60.0)
        if raw.endswith("h") and raw[:-1].isdigit():
            return float(int(raw[:-1]))
        if raw.endswith("d") and raw[:-1].isdigit():
            return float(int(raw[:-1]) * 24)
        # leading-letter style: M1 / H4 / D1
        if raw[:1] == "m" and raw[1:].isdigit():
            return max(0.0, int(raw[1:]) / 60.0)
        if raw[:1] == "h" and raw[1:].isdigit():
            return float(int(raw[1:]))
        if raw[:1] == "d" and raw[1:].isdigit():
            return float(int(raw[1:]) * 24)
    except ValueError:
        return 0.0
    return 0.0


class MarketDataset:
    """Host-side dataset: the loaded dataframe + device-array builders."""

    def __init__(self, dataframe: pd.DataFrame, config: Dict[str, Any]):
        self.dataframe = dataframe
        self.config = dict(config)
        self.date_column = str(config.get("date_column", "DATE_TIME"))
        self.price_column = str(config.get("price_column", "CLOSE"))
        self.timeframe_hours = _infer_timeframe_hours(config)
        if isinstance(dataframe.index, pd.DatetimeIndex):
            self.timestamps = pd.Series(dataframe.index)
        elif self.date_column in dataframe.columns:
            self.timestamps = pd.to_datetime(
                dataframe[self.date_column], errors="coerce"
            ).reset_index(drop=True)
        else:
            self.timestamps = pd.Series(pd.DatetimeIndex([pd.NaT] * len(dataframe)))

    def __len__(self) -> int:
        if self.dataframe is None:
            return self._released_len
        return len(self.dataframe)

    def release_frame(self) -> None:
        """Drop the host dataframe once the device tensors exist.

        Large generated feeds (feed=scengen at big ``scengen_bars``)
        otherwise hold the f64 frame AND its encoded device form at the
        same time; timestamps and length survive so latency validation
        and ``len()`` keep working.  Building market data again after a
        release fails loudly."""
        if self.dataframe is not None:
            self._released_len = len(self.dataframe)
            self.dataframe = None

    def bar_interval_ms(self) -> Optional[float]:
        """Milliseconds per bar: from the timeframe label when present,
        else the median spacing of valid timestamps; None when neither
        is available (callers that need it must reject, not guess)."""
        if self.timeframe_hours:
            return self.timeframe_hours * 3_600_000.0
        ts = pd.to_datetime(self.timestamps, errors="coerce").dropna()
        if len(ts) < 2:
            return None
        deltas = ts.diff().dropna().dt.total_seconds()
        median = float(deltas.median())
        return median * 1000.0 if median > 0 else None

    # ------------------------------------------------------------------
    def build_market_data(
        self,
        *,
        window_size: int,
        feature_columns: Sequence[str] = (),
        feature_scaling: str = "rolling_zscore",
        feature_scaling_window: int = 256,
        dtype: Any = np.float32,
        event_context_no_trade_column: str = "event_no_trade_window_active",
        event_context_spread_stress_column: str = "event_spread_stress_multiplier",
        event_context_slippage_stress_column: str = "event_slippage_stress_multiplier",
        force_close_dow: int = 4,
        force_close_hour: int = 20,
        force_close_window_hours: int = 4,
        monday_entry_window_hours: int = 4,
        financing_rate_data: Any = None,
        instrument: str = "EUR_USD",
        device: bool = True,
    ) -> MarketData:
        df = self.dataframe
        if df is None:
            raise ValueError(
                "this dataset's frame was released (release_frame) after "
                "its device tensors were built — market data cannot be "
                "rebuilt from it"
            )
        n = len(df)
        if n < window_size + 2:
            raise ValueError("input data is empty or too short for the configured window")

        close = df[self.price_column].to_numpy(dtype=np.float64, copy=False)

        def col(name: str, fallback) -> np.ndarray:
            if name in df.columns:
                return df[name].to_numpy(dtype=np.float64, copy=False)
            if np.isscalar(fallback):
                return np.full(n, float(fallback), dtype=np.float64)
            return fallback

        o = col("OPEN", close)
        h = col("HIGH", close)
        l = col("LOW", close)
        c = col("CLOSE", close)
        v = col("VOLUME", 0.0)

        padded_close = np.concatenate([np.full(window_size, close[0]), close])

        tf_h = self.timeframe_hours or 1.0
        cal = fxcal.precompute_fx_calendar_features(
            self.timestamps, timeframe_hours=tf_h
        )
        fcz = fxcal.precompute_force_close_features(
            self.timestamps,
            timeframe_hours=self.timeframe_hours,
            force_close_dow=force_close_dow,
            force_close_hour=force_close_hour,
            force_close_window_hours=force_close_window_hours,
            monday_entry_window_hours=monday_entry_window_hours,
        )
        mow = fxcal.precompute_minute_of_week(self.timestamps)

        ev_no_trade = col(event_context_no_trade_column, 0.0).astype(np.float32)
        ev_spread = col(event_context_spread_stress_column, 1.0).astype(np.float32)
        ev_slip = col(event_context_slippage_stress_column, 1.0).astype(np.float32)

        if financing_rate_data is not None:
            from gymfx_tpu.data import financing as fxfin

            base_ccy, quote_ccy = fxfin.split_pair(instrument)
            accrual = fxfin.precompute_rollover_accrual(
                self.timestamps, financing_rate_data, base_ccy, quote_ccy
            )
        else:
            accrual = np.zeros(n, dtype=np.float64)

        padded_features, feat_mean, feat_std, feat_neutral = _build_feature_tensors(
            df,
            feature_columns=tuple(feature_columns),
            window_size=window_size,
            scaling=feature_scaling,
            scaling_window=feature_scaling_window,
        )

        import jax.numpy as jnp

        # device=False keeps every array on the host (numpy, same final
        # dtypes) so streaming callers can slice shards cheaply and
        # device_put them on their own schedule (BarStreamer).
        if device:
            def A(x, dt):
                return jnp.asarray(x, dtype=dt)
        else:
            def A(x, dt):
                return np.asarray(x, dtype=dt)

        f32 = np.float32
        return MarketData(
            open=A(o, dtype),
            high=A(h, dtype),
            low=A(l, dtype),
            close=A(c, dtype),
            volume=A(v, dtype),
            padded_close=A(padded_close, dtype),
            minute_of_week=A(mow, np.int32),
            calendar=A(cal, f32),
            force_close=A(fcz, f32),
            ev_no_trade=A(ev_no_trade, f32),
            ev_spread_mult=A(ev_spread, f32),
            ev_slip_mult=A(ev_slip, f32),
            rollover_accrual=A(accrual, dtype),
            padded_features=A(padded_features, f32),
            feat_mean=A(feat_mean, f32),
            feat_std=A(feat_std, f32),
            feat_neutral=A(feat_neutral, bool),
            row0=np.int32(0),
            scen_flags=A(np.zeros(n, np.int32), np.int32),
        )


def _build_feature_tensors(
    df: pd.DataFrame,
    *,
    feature_columns: Tuple[str, ...],
    window_size: int,
    scaling: str,
    scaling_window: int,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Feature matrix + per-step leakage-safe scaler moments.

    The reference re-fits a z-score over up to ``feature_scaling_window``
    strictly-past rows per step per env (reference
    preprocessor_plugins/feature_window_preprocessor.py:174-191) — the
    obs hot spot.  Here the mean/std for every possible step are derived
    once from f64 cumulative moments: O(n·F) precompute, O(1) lookup per
    step in-graph.  Windows with <2 history rows are flagged neutral
    (zero warm-up, reference :112-117).
    """
    n = len(df)
    f = len(feature_columns)
    if f == 0:
        return (
            np.zeros((n + window_size, 0), np.float32),
            np.zeros((n + 1, 0), np.float32),
            np.ones((n + 1, 0), np.float32),
            np.zeros((n + 1,), bool),
        )
    missing = [cname for cname in feature_columns if cname not in df.columns]
    if missing:
        raise ValueError(
            "feature_window preprocessor: configured feature_columns "
            f"missing from dataframe: {missing[:5]}{'...' if len(missing) > 5 else ''}"
        )
    values = df[list(feature_columns)].to_numpy(dtype=np.float64)
    padded = np.concatenate([np.tile(values[0], (window_size, 1)), values], axis=0)

    if scaling == "none":
        mean = np.zeros((n + 1, f), np.float64)
        std = np.ones((n + 1, f), np.float64)
        neutral = np.zeros((n + 1,), bool)
        return padded.astype(np.float32), mean.astype(np.float32), std.astype(np.float32), neutral

    s1 = np.concatenate([np.zeros((1, f)), np.cumsum(values, axis=0)], axis=0)
    s2 = np.concatenate([np.zeros((1, f)), np.cumsum(values**2, axis=0)], axis=0)
    t = np.arange(n + 1)
    if scaling == "rolling_zscore":
        lo = np.maximum(0, t - int(scaling_window))
    elif scaling == "expanding_zscore":
        lo = np.zeros(n + 1, dtype=np.int64)
    else:
        raise ValueError(
            "feature_scaling must be one of ('none', 'rolling_zscore', "
            f"'expanding_zscore'); got {scaling!r}"
        )
    count = (t - lo).astype(np.float64)
    safe_count = np.maximum(count, 1.0)[:, None]
    mean = (s1[t] - s1[lo]) / safe_count
    var = (s2[t] - s2[lo]) / safe_count - mean**2
    std = np.sqrt(np.maximum(var, 0.0))
    std = np.where(std < 1e-8, 1.0, std)
    neutral = count < 2
    mean = np.where(neutral[:, None], 0.0, mean)
    std = np.where(neutral[:, None], 1.0, std)
    return (
        padded.astype(np.float32),
        mean.astype(np.float32),
        std.astype(np.float32),
        neutral,
    )


def market_data_nbytes(data: MarketData) -> int:
    """Total array bytes of a MarketData pytree (host or device)."""
    total = 0
    for leaf in data:
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is not None:
            total += int(nbytes)
    return total


def market_data_nbytes_report(data: MarketData, tape=None) -> Dict[str, Any]:
    """Decoded vs compressed byte accounting for one tape.

    ``decoded`` is the full-width f32 footprint of ``data``;
    ``compressed`` is the int16/packed footprint of its
    :class:`~gymfx_tpu.data.compress.CompressedTape` (None when the tape
    is not compressed), with ``ratio = decoded_per_shard * num_shards /
    compressed`` as defined by the tape."""
    decoded = market_data_nbytes(data) if data is not None else None
    if tape is None:
        return {"decoded": decoded, "compressed": None, "ratio": None}
    return {
        "decoded": decoded if decoded is not None
        else tape.decoded_shard_nbytes * tape.num_shards,
        "compressed": tape.nbytes,
        "ratio": tape.compression_ratio,
    }


def shard_market_data(data: MarketData, start: int, shard_bars: int,
                      window_size: int) -> MarketData:
    """Slice one streaming shard out of a (host) MarketData.

    A shard anchored at global row ``start`` serves env steps whose bar
    cursor lands in ``[start, start + shard_bars)``; a step at cursor
    ``t`` also reads row ``t + 1`` (next-bar fills, event overlay), so
    the bar arrays carry one row of lookahead and the front-padded
    window sources carry ``window_size`` extra rows.  ``row0 = start``
    lets the env kernel keep its GLOBAL cursor and rebase each read —
    every shard has identical shapes, so one compiled program serves
    them all.
    """
    hi = start + int(shard_bars) + 1
    if hi > int(np.asarray(data.close).shape[0]):
        raise ValueError(
            f"shard [{start}, {hi}) exceeds dataset of "
            f"{np.asarray(data.close).shape[0]} bars"
        )
    bar = slice(start, hi)
    padded = slice(start, hi + int(window_size))
    # scaler moments are (n + 1)-row tables indexed at min(t + 1, n):
    # one more row of lookahead than the bar arrays
    feat = slice(start, hi + 1)
    return data._replace(
        open=data.open[bar],
        high=data.high[bar],
        low=data.low[bar],
        close=data.close[bar],
        volume=data.volume[bar],
        padded_close=data.padded_close[padded],
        minute_of_week=data.minute_of_week[bar],
        calendar=data.calendar[bar],
        force_close=data.force_close[bar],
        ev_no_trade=data.ev_no_trade[bar],
        ev_spread_mult=data.ev_spread_mult[bar],
        ev_slip_mult=data.ev_slip_mult[bar],
        rollover_accrual=data.rollover_accrual[bar],
        padded_features=data.padded_features[padded],
        feat_mean=data.feat_mean[feat],
        feat_std=data.feat_std[feat],
        feat_neutral=data.feat_neutral[feat],
        row0=np.int32(start),
        scen_flags=data.scen_flags[bar],
    )


class BarStreamer:
    """Double-buffered host→device streaming of a long bar history.

    When the resident dataset would blow the HBM budget, the bar history
    is cut into fixed-size shards (identical static shapes — every shard
    reuses ONE compiled rollout executable) and each shard's
    ``jax.device_put`` is issued BEFORE compute is dispatched on the
    previous one, so the host→device DMA of shard ``t+1`` overlaps the
    device compute on shard ``t``.  At most two shards are resident at
    any time, which is why each shard targets half the budget.

    ``compress != "off"`` switches the wire format to int16 tick-deltas
    (data/compress.py): the planner then budgets on the COMPRESSED
    resident size plus two decoded shards (the double buffer), the whole
    compressed tape stays device-resident when the ring capacity allows,
    and ``_device_shard`` materializes each f32 shard with the fused
    decode — bitwise-identical to the uncompressed slice, verified at
    encode time.  The host f32 tape is dropped after encoding so large
    generated feeds never hold both representations at once.
    """

    def __init__(self, host_data: MarketData, *, window_size: int,
                 budget_mb: float, min_shard_bars: int = 64,
                 placement=None, compress: str = "off",
                 tick_size: float = 1e-5, what: str = ""):
        from gymfx_tpu.data import compress as C

        self.compress = C.validate_compress_mode(compress)
        self.window_size = int(window_size)
        # optional jax.sharding.Sharding for each shard's device_put —
        # on a mesh the ShardedRuntime passes its replicated sharding so
        # streamed bars land on EVERY mesh device (a bare device_put
        # targets device 0 only, forcing an implicit transfer inside the
        # sharded rollout program); None keeps the single-device path
        self.placement = placement
        n = int(np.asarray(host_data.close).shape[0])
        total = market_data_nbytes(host_data)
        per_bar = max(1.0, total / max(1, n))
        budget_bytes = float(budget_mb) * 2**20
        if self.compress == "off":
            shard_bars = (
                int(budget_bytes / 2.0 / per_bar) - self.window_size - 1
            )
        else:
            # two DECODED f32 buffers take an eighth of the budget; the
            # rest holds the compressed resident ring (checked below
            # once the actual compressed size is known)
            shard_bars = (
                int(budget_bytes * 0.125 / 2.0 / per_bar)
                - self.window_size - 1
            )
        shard_bars = max(int(min_shard_bars), shard_bars)
        if shard_bars >= n - 1:
            raise ValueError(
                f"dataset ({n} bars, {total / 2**20:.1f} MiB) fits the "
                f"{budget_mb} MiB streaming budget — streaming is not "
                "needed; unset stream_hbm_budget_mb"
            )
        self.n_bars = n
        self.shard_bars = shard_bars
        # regular starts every shard_bars; the final shard is anchored so
        # its lookahead row is the last bar — it overlaps the previous
        # shard, keeping every shard the same static shape.
        starts = list(range(0, n - shard_bars - 1, shard_bars))
        last = n - shard_bars - 1
        if not starts or starts[-1] != last:
            starts.append(last)
        self.starts = starts

        self.tape = None
        self._decoder = None
        self.ring_shards = 2  # uncompressed: the double buffer
        if self.compress == "off":
            self.host_data = host_data
            return
        import jax

        tape = C.encode_market_data(
            host_data, starts=starts, shard_bars=shard_bars,
            window_size=self.window_size, tick_size=tick_size, what=what,
        )
        ring_bytes = budget_bytes - 2.0 * tape.decoded_shard_nbytes
        ring = int(ring_bytes // max(1, tape.shard_nbytes))
        if ring < 2:
            raise ValueError(
                f"stream_hbm_budget_mb={budget_mb} cannot hold two "
                f"decoded shards ({2 * tape.decoded_shard_nbytes / 2**20:.1f}"
                " MiB) plus two compressed shards "
                f"({tape.shard_nbytes / 2**20:.2f} MiB each, "
                f"{tape.nbytes / 2**20:.1f} MiB total compressed) — raise "
                "the budget or set data_compress=off"
            )
        self.ring_shards = min(ring, len(starts))
        # full compressed tape fits the ring: park it on device once and
        # decode shards from resident slabs (no steady-state host DMA);
        # otherwise stream the (4x smaller) compressed shards from host
        self.tape_resident = ring >= len(starts)
        if self.tape_resident:
            tape = C.device_tape(tape, placement)
        self.tape = tape
        self._decoder = C.make_shard_decoder(tape, self.compress)
        # drop the host f32 reference: compressed mode never holds the
        # full-width tape and its compressed form at the same time
        self.host_data = None

    @property
    def num_shards(self) -> int:
        return len(self.starts)

    @property
    def resident_bars(self) -> int:
        """Bar capacity resident on device under the budget: the ring of
        compressed shards (plus decode buffers) when compressed, the
        double buffer otherwise."""
        return self.ring_shards * self.shard_bars

    @property
    def compression_ratio(self) -> Optional[float]:
        return None if self.tape is None else self.tape.compression_ratio

    def nbytes_report(self) -> Dict[str, Any]:
        """Compressed vs decoded byte accounting (see
        :func:`market_data_nbytes_report`)."""
        return market_data_nbytes_report(self.host_data, self.tape)

    def serve_ranges(self):
        """[(lo, hi_or_None), ...]: shard k serves bar cursors in
        [lo, hi); the final shard serves to the end (hi=None)."""
        out = []
        for k, lo in enumerate(self.starts):
            hi = self.starts[k + 1] if k + 1 < len(self.starts) else None
            out.append((lo, hi))
        return out

    def _device_shard(self, k: int) -> MarketData:
        import jax

        if self.tape is not None:
            from gymfx_tpu.data import compress as C

            arrs = C.shard_arrays(self.tape, k)
            if not self.tape_resident:
                # stream the compressed shard (4x+ smaller DMA), decode
                # on device into the f32 double buffer
                if self.placement is not None:
                    arrs = jax.tree.map(
                        lambda x: jax.device_put(x, self.placement), arrs
                    )
                else:
                    arrs = jax.tree.map(jax.device_put, arrs)
            shard = self._decoder(arrs)
            if self.placement is not None:
                shard = jax.tree.map(
                    lambda x: jax.device_put(x, self.placement), shard
                )
            return shard
        shard = shard_market_data(
            self.host_data, self.starts[k], self.shard_bars, self.window_size
        )
        # device_put on host numpy is async: it enqueues the transfer
        # and returns immediately — the double buffer.
        if self.placement is not None:
            return jax.tree.map(
                lambda x: jax.device_put(x, self.placement), shard
            )
        return jax.tree.map(jax.device_put, shard)

    def iter_shards(self):
        """Yield ``(serve_lo, serve_hi_or_None, device_shard)`` in
        order, with shard ``k+1``'s transfer already enqueued before
        shard ``k`` is handed to the caller for compute."""
        nxt = self._device_shard(0)
        for k in range(len(self.starts)):
            cur = nxt
            if k + 1 < len(self.starts):
                nxt = self._device_shard(k + 1)
            hi = self.starts[k + 1] if k + 1 < len(self.starts) else None
            yield self.starts[k], hi, cur


def load_dataframe(config: Dict[str, Any]) -> pd.DataFrame:
    """CSV -> dataframe with datetime index and OHLCV backfill.

    Canonical bar files (exactly the DATE_TIME,OHLCV schema) go through
    the native C++ columnar parser when it is available; anything else —
    extra feature columns, custom date column, headerless files — takes
    the pandas path with identical semantics."""
    file_path = config.get("input_data_file")
    if not file_path:
        raise ValueError("config key 'input_data_file' is required")
    headers = bool(config.get("headers", True))
    max_rows = config.get("max_rows")

    if (
        headers
        and max_rows is None  # pandas' nrows stops early; native would not
        and str(config.get("date_column", "DATE_TIME")) == "DATE_TIME"
        and str(config.get("price_column", "CLOSE")) == "CLOSE"
    ):
        from gymfx_tpu.data.native_loader import load_ohlcv_csv

        native = load_ohlcv_csv(str(file_path))
        if native is not None:
            return native

    df = pd.read_csv(file_path, header=0 if headers else None, nrows=max_rows)

    date_col = str(config.get("date_column", "DATE_TIME"))
    if date_col in df.columns:
        df[date_col] = pd.to_datetime(df[date_col], errors="coerce")
        df = df.dropna(subset=[date_col]).set_index(date_col)

    price_col = str(config.get("price_column", "CLOSE"))
    if price_col not in df.columns:
        raise ValueError(f"price_column '{price_col}' not found in data")
    for column in OHLC_COLUMNS:
        if column not in df.columns:
            df[column] = df[price_col]
    if "VOLUME" not in df.columns:
        df["VOLUME"] = 0
    return df


def load_market_dataset(config: Dict[str, Any]) -> MarketDataset:
    return MarketDataset(load_dataframe(config), config)
