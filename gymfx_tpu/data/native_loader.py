"""ctypes bindings for the native CSV loader.

Replaces the pandas parse on the hot data path (the reference loads
with pd.read_csv — data_feed_plugins/default_data_feed.py:40) with the
C++ columnar parser.  Strictness contract: the native parser handles
the canonical bar schema (DATE_TIME,OPEN,HIGH,LOW,CLOSE,VOLUME with
fixed-format timestamps) and REFUSES anything else, in which case the
caller silently falls back to pandas — exotic files behave exactly as
before, canonical files load several times faster.

Set GYMFX_NATIVE_LOADER=0 to disable, =require to hard-fail when the
native path cannot serve a file (for tests/benchmarks).
"""
from __future__ import annotations

import ctypes
import os
import pathlib
from typing import Optional

import numpy as np
import pandas as pd

_LIB_PATH = pathlib.Path(__file__).resolve().parent.parent / "native" / "libgymfx_csv.so"
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False


def _load_lib() -> Optional[ctypes.CDLL]:
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    try:
        import subprocess
        import sys

        build = pathlib.Path(__file__).resolve().parents[2] / "tools" / "build_native.py"
        # build_native handles staleness (mtime) and concurrency (lock +
        # atomic rename), so it is safe and cheap to invoke every time
        subprocess.run([sys.executable, str(build)], check=True,
                       capture_output=True)
        lib = ctypes.CDLL(str(_LIB_PATH))
        lib.gymfx_csv_parse.restype = ctypes.c_void_p
        lib.gymfx_csv_parse.argtypes = [ctypes.c_char_p,
                                        ctypes.POINTER(ctypes.c_int64)]
        lib.gymfx_csv_fill.restype = None
        lib.gymfx_csv_fill.argtypes = [ctypes.c_void_p] + [
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        ] + [np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")] * 5
        lib.gymfx_csv_free.restype = None
        lib.gymfx_csv_free.argtypes = [ctypes.c_void_p]
        _lib = lib
    except Exception:
        _lib_failed = True
        _lib = None
    return _lib


def native_enabled() -> bool:
    return os.environ.get("GYMFX_NATIVE_LOADER", "1") != "0"


_CANONICAL = {"DATE_TIME", "OPEN", "HIGH", "LOW", "CLOSE", "VOLUME"}


def _header_is_canonical(path: str) -> bool:
    """Only the exact bar schema qualifies — files with extra engineered
    feature columns must go through pandas, which preserves them."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            header = fh.readline().strip()
    except OSError:
        return False
    cols = {c.strip().upper() for c in header.split(",")}
    # require the FULL schema: with any column absent, the pandas path's
    # price_column-driven backfill semantics apply and could diverge
    return cols == _CANONICAL


def load_ohlcv_csv(path: str) -> Optional[pd.DataFrame]:
    """Native parse -> dataframe with DatetimeIndex, or None when the
    file is not canonical / the library is unavailable."""
    if not native_enabled():
        return None
    if not _header_is_canonical(path):
        if os.environ.get("GYMFX_NATIVE_LOADER") == "require":
            raise RuntimeError(f"native loader: non-canonical header in {path}")
        return None
    lib = _load_lib()
    if lib is None:
        if os.environ.get("GYMFX_NATIVE_LOADER") == "require":
            raise RuntimeError("native loader required but unavailable")
        return None
    n = ctypes.c_int64(0)
    handle = lib.gymfx_csv_parse(str(path).encode(), ctypes.byref(n))
    if not handle:
        if os.environ.get("GYMFX_NATIVE_LOADER") == "require":
            raise RuntimeError(f"native loader could not parse {path}")
        return None
    try:
        rows = int(n.value)
        epoch = np.empty(rows, np.int64)
        o = np.empty(rows, np.float64)
        h = np.empty(rows, np.float64)
        l = np.empty(rows, np.float64)
        c = np.empty(rows, np.float64)
        v = np.empty(rows, np.float64)
        lib.gymfx_csv_fill(handle, epoch, o, h, l, c, v)
    finally:
        lib.gymfx_csv_free(handle)
    index = pd.DatetimeIndex(epoch.view("datetime64[s]"), name="DATE_TIME")
    return pd.DataFrame(
        {"OPEN": o, "HIGH": h, "LOW": l, "CLOSE": c, "VOLUME": v}, index=index
    )
