"""FX rollover financing: rate table parsing + per-bar accrual precompute.

The reference accrues rollover interest through NautilusTrader's
FXRolloverInterestModule, fed by a monthly short-rate CSV with
LOCATION/TIME/Value rows (reference simulation_engines/nautilus_gym.py:276-290,
rate schema examples/data/fx_rollover_rates_smoke.csv).  This module is
the single source of rate semantics for BOTH engines of this framework:

  * the replay engine (simulation/replay.py) looks rates up per event
    timestamp while walking frames;
  * the scan engine precomputes ONE accrual-rate column here — zero
    everywhere except the first bar at/after 22:00 UTC of each calendar
    day, where it carries the pair's daily rate differential — so the
    jitted step applies financing as a single fused multiply-add
    (core/env.py), with no calendar logic in-graph.

Accrual model (matching the replay engine): a position held across the
22:00 UTC rollover earns/pays  units * mid * (base_rate - quote_rate)
/ 100 / 365  in quote currency, using the annualized short rates of the
bar's month (the latest table month at or before the bar; bars before
the first table month use the earliest entry).
"""
from __future__ import annotations

import bisect
from typing import Any, Dict, List, Tuple

import numpy as np
import pandas as pd

ROLLOVER_UTC_SECONDS = 22 * 3600  # 17:00 New York standard time

# OECD-style location codes used by the reference's rate fixtures.
CURRENCY_LOCATION = {"EUR": "EA19", "USD": "USA", "JPY": "JPN", "GBP": "GBR"}
_LOCATION_CURRENCY = {v: k for k, v in CURRENCY_LOCATION.items()}

RateTable = Dict[str, List[Tuple[int, float]]]


def parse_rate_table(rate_data: Any) -> RateTable:
    """LOCATION/TIME/Value rows -> currency -> sorted [(month_start_ns, pct)].

    ``TIME`` is a month label (YYYY-MM).  Rows with unknown locations or
    unparseable months are skipped.
    """
    if rate_data is None:
        return {}
    try:
        rows = rate_data.to_dict("records")  # pandas DataFrame
    except AttributeError:
        rows = list(rate_data)
    table: RateTable = {}
    for row in rows:
        ccy = _LOCATION_CURRENCY.get(str(row.get("LOCATION")))
        if not ccy:
            continue
        ts = pd.to_datetime(str(row.get("TIME")), errors="coerce", utc=True)
        if ts is pd.NaT:
            continue
        table.setdefault(ccy, []).append((int(ts.value), float(row.get("Value", 0.0))))
    for entries in table.values():
        entries.sort()
    return table


def rate_at(table: RateTable, currency: str, ts_ns: int) -> float:
    """Annualized short rate (%) applicable at ``ts_ns``: the latest table
    month at or before the timestamp; the earliest entry for timestamps
    before the table starts; 0.0 for unknown currencies."""
    entries = table.get(currency)
    if not entries:
        return 0.0
    idx = bisect.bisect_right(entries, (int(ts_ns), float("inf"))) - 1
    return entries[max(idx, 0)][1]


def daily_differential(
    table: RateTable, base_currency: str, quote_currency: str, ts_ns: int
) -> float:
    """Per-day accrual rate for one unit-notional of the pair: long base
    earns the base rate and pays the quote rate (annualized %)."""
    base = rate_at(table, base_currency, ts_ns)
    quote = rate_at(table, quote_currency, ts_ns)
    return (base - quote) / 100.0 / 365.0


def _to_utc_ns(timestamps: pd.Series) -> Tuple[np.ndarray, np.ndarray]:
    """(valid_mask, ns_since_epoch) — naive timestamps treated as UTC.
    The cast goes through datetime64[ns, UTC] explicitly: pandas 3.0
    keeps datetimes at microsecond resolution, where a bare
    ``astype(int64)`` would yield microseconds."""
    ts = pd.to_datetime(timestamps, errors="coerce")
    try:
        ts = ts.dt.tz_convert("UTC")
    except TypeError:
        ts = ts.dt.tz_localize("UTC")
    valid = ts.notna().to_numpy()
    ns = ts.astype("datetime64[ns, UTC]").astype("int64").to_numpy()
    return valid, ns


def rollover_mask(timestamps: pd.Series) -> np.ndarray:
    """(n,) bool — True on the FIRST bar at/after 22:00 UTC of each
    calendar day (naive timestamps are treated as UTC, matching the
    calendar precompute).  Invalid timestamps never roll over."""
    valid, ns = _to_utc_ns(timestamps)
    day = ns // 86_400_000_000_000
    second_of_day = (ns // 1_000_000_000) % 86_400
    eligible = valid & (second_of_day >= ROLLOVER_UTC_SECONDS)
    mask = np.zeros(len(ns), dtype=bool)
    seen: set = set()
    for i in np.flatnonzero(eligible):
        key = int(day[i])
        if key not in seen:
            seen.add(key)
            mask[i] = True
    return mask


def precompute_rollover_accrual(
    timestamps: pd.Series,
    rate_data: Any,
    base_currency: str,
    quote_currency: str,
) -> np.ndarray:
    """(n,) float64 — per-bar accrual rate column for the scan engine:
    the pair's daily differential on rollover bars, 0 elsewhere.  The
    step's financing credit is  pos * close * accrual[t]  in quote
    currency (core/env.py), matching the replay engine's
    units * mid * differential."""
    table = parse_rate_table(rate_data)
    mask = rollover_mask(timestamps)
    out = np.zeros(len(mask), dtype=np.float64)
    if not table:
        return out
    _, ns = _to_utc_ns(timestamps)
    for i in np.flatnonzero(mask):
        out[i] = daily_differential(table, base_currency, quote_currency, int(ns[i]))
    return out


def split_pair(instrument: str) -> Tuple[str, str]:
    """'EUR_USD' / 'EUR/USD' / 'EURUSD' -> ('EUR', 'USD')."""
    raw = str(instrument).upper().replace("/", "").replace("_", "").replace("-", "")
    if len(raw) != 6 or not raw.isalpha():
        raise ValueError(
            f"cannot derive base/quote currencies from instrument {instrument!r}"
        )
    return raw[:3], raw[3:]
