"""Episode metrics: analyzer equivalents + summary plugins.

Replaces the five backtrader analyzers the reference wires into cerebro
(TradeAnalyzer, SharpeRatio(Days), DrawDown, SQN, TimeReturn —
reference app/bt_bridge.py:277-281) with host-side computation over the
scanned equity stream and the trade statistics carried in ``EnvState``.
The summarize functions reproduce the reference metric plugins key for
key (reference metrics_plugins/default_metrics.py:22-60,
trading_metrics.py:24-62).
"""
from __future__ import annotations

import math
from typing import Any, Dict, Optional

import numpy as np

# backtrader SharpeRatio defaults: riskfreerate=0.01 (annual),
# timeframe=Days, factor=252, annualize=False, convertrate=True.
_SHARPE_ANNUAL_RF = 0.01
_SHARPE_FACTOR = 252.0


def compute_analyzers(
    *,
    equity: np.ndarray,
    done: Optional[np.ndarray],
    state,
    timestamps=None,
) -> Dict[str, Any]:
    """Build backtrader-shaped analyzer dicts from rollout outputs.

    ``equity`` is the per-step equity curve (f64), ``done`` the per-step
    termination flags; post-termination steps are excluded.  ``state``
    is the final EnvState (trade statistics, drawdown extrema).
    ``timestamps`` (optional, aligned with bars) drives the daily
    grouping of the Sharpe analyzer; without it, each step counts as
    one return sample.
    """
    equity = np.asarray(equity, dtype=np.float64)
    if done is not None:
        done = np.asarray(done, dtype=bool)
        if done.any():
            equity = equity[: int(np.argmax(done)) + 1]

    # --- trades (reference TradeAnalyzer surface) ----------------------
    total = int(state.trade_count)
    won = int(state.trades_won)
    lost = int(state.trades_lost)
    pnl_sum = float(state.trade_pnl_sum)
    avg = pnl_sum / total if total else None
    trades = {
        "total": {"total": total},
        "won": {"total": won},
        "lost": {"total": lost},
        "pnl": {"net": {"average": avg, "total": pnl_sum}},
    }

    # --- sharpe (daily returns, rf-adjusted, ddof=1, not annualized) ---
    returns = _periodic_returns(equity, timestamps)
    sharpe = None
    if returns.size >= 2:
        daily_rf = (1.0 + _SHARPE_ANNUAL_RF) ** (1.0 / _SHARPE_FACTOR) - 1.0
        excess = returns - daily_rf
        std = excess.std(ddof=1)
        if std > 0:
            sharpe = float(excess.mean() / std)

    # --- drawdown ------------------------------------------------------
    drawdown = {
        "max": {
            "drawdown": float(state.max_drawdown_pct),
            "moneydown": float(state.max_drawdown_money),
        }
    }

    # --- SQN (sqrt(n) * mean(trade pnl) / std(trade pnl), ddof=1) ------
    sqn = None
    if total >= 2:
        mean = pnl_sum / total
        var = (float(state.trade_pnl_sumsq) - total * mean**2) / (total - 1)
        std = math.sqrt(max(var, 0.0))
        if std > 0:
            sqn = float(math.sqrt(total) * mean / std)

    # --- time_return (per-period returns keyed by period index) --------
    time_return = {int(i): float(r) for i, r in enumerate(returns)}

    return {
        "trades": trades,
        "sharpe": {"sharperatio": sharpe},
        "drawdown": drawdown,
        "sqn": {"sqn": sqn},
        "time_return": time_return,
    }


def _periodic_returns(equity: np.ndarray, timestamps) -> np.ndarray:
    """Equity -> per-day returns when timestamps are supplied, else
    per-step returns (reference analyzer runs on the Days timeframe)."""
    if equity.size < 2:
        return np.empty(0)
    if timestamps is not None:
        import pandas as pd

        ts = pd.DatetimeIndex(pd.to_datetime(np.asarray(timestamps), errors="coerce"))
        ts = ts[: equity.size]
        day = np.asarray(ts.normalize().asi8)
        # last equity of each day
        boundaries = np.nonzero(np.diff(day) != 0)[0]
        idx = np.concatenate([boundaries, [equity.size - 1]])
        series = equity[idx]
    else:
        series = equity
    if series.size < 2:
        return np.empty(0)
    prev = series[:-1]
    with np.errstate(divide="ignore", invalid="ignore"):
        rets = np.where(prev != 0, series[1:] / prev - 1.0, 0.0)
    return rets


def _get(d: Any, *path: str, default: Any = None) -> Any:
    cur: Any = d
    for k in path:
        if cur is None:
            return default
        if hasattr(cur, "get"):
            cur = cur.get(k, None)
        else:
            return default
    return cur if cur is not None else default


def summarize_default(
    *,
    initial_cash: float,
    final_equity: float,
    analyzers: Dict[str, Any],
    config: Dict[str, Any],
) -> Dict[str, Any]:
    trades = analyzers.get("trades") or {}
    sharpe = analyzers.get("sharpe") or {}
    drawdown = analyzers.get("drawdown") or {}
    sqn = analyzers.get("sqn") or {}
    total_return = (
        (float(final_equity) / float(initial_cash) - 1.0) if initial_cash else 0.0
    )
    return {
        "initial_cash": float(initial_cash),
        "final_equity": float(final_equity),
        "total_return": float(total_return),
        "max_drawdown_pct": _get(drawdown, "max", "drawdown"),
        "max_drawdown_money": _get(drawdown, "max", "moneydown"),
        "sharpe_ratio": _get(sharpe, "sharperatio"),
        "sqn": _get(sqn, "sqn"),
        "trades_total": _get(trades, "total", "total", default=0),
        "trades_won": _get(trades, "won", "total", default=0),
        "trades_lost": _get(trades, "lost", "total", default=0),
        "avg_trade_pnl": _get(trades, "pnl", "net", "average"),
    }


def _finite_or_zero(value: Any) -> float:
    try:
        result = float(value)
    except (TypeError, ValueError):
        return 0.0
    return result if math.isfinite(result) else 0.0


def summarize_trading(
    *,
    initial_cash: float,
    final_equity: float,
    analyzers: Dict[str, Any],
    config: Dict[str, Any],
) -> Dict[str, Any]:
    """Risk-adjusted extension (rap, annualization) of the default summary."""
    summary = summarize_default(
        initial_cash=initial_cash,
        final_equity=final_equity,
        analyzers=analyzers,
        config=config,
    )
    drawdown_pct = _finite_or_zero(summary.get("max_drawdown_pct"))
    total_return = _finite_or_zero(summary.get("total_return"))
    risk_lambda = float(
        config.get("risk_lambda", config.get("risk_penalty_lambda", 1.0))
    )
    drawdown_fraction = max(0.0, drawdown_pct / 100.0)
    rap = total_return - risk_lambda * drawdown_fraction
    summary.update(
        {
            "metric_schema": str(config.get("metric_schema", "trading.metrics.v1")),
            "max_drawdown_fraction": drawdown_fraction,
            "risk_penalty_lambda": risk_lambda,
            "risk_adjusted_total_return": rap,
            "rap": rap,
        }
    )
    years = config.get("evaluation_years")
    if years is not None and float(years) > 0:
        summary["annual_return"] = (1.0 + total_return) ** (1.0 / float(years)) - 1.0
        summary["annual_rap"] = rap / float(years)
    return summary
