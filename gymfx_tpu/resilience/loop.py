"""Host-side per-iteration resilience hooks shared by the trainer loops.

The jitted train steps carry the in-graph guards (guards.py); this
module is the thin host loop around them:

  * the SkipMonitor divergence watchdog, run ONE STEP DELAYED — the
    guard counters for iteration ``i`` are fetched only after iteration
    ``i + 1`` has been dispatched, so the async device pipeline never
    stalls on the watchdog's host sync;
  * periodic preemption-safe auto-checkpointing (every
    ``checkpoint_every`` iterations), with the cumulative step count so
    a resumed run keeps advancing past the loaded step;
  * the simulated-preemption kill for checkpoint/resume drills
    (``fault_profile`` ``preempt_at`` clause).

One definition so the PPO and IMPALA loops cannot drift.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

from gymfx_tpu.resilience.faults import (
    DeviceLossError,
    SimulatedPreemptionError,
)
from gymfx_tpu.resilience.guards import (
    NonFiniteDivergenceError,
    SkipMonitor,
)

GUARD_METRIC_KEYS = ("nonfinite_skips", "guard_updates", "poisoned_env_resets")

# state_dict_fn: () -> (full state dict to checkpoint, params tree)
StateFn = Callable[[], Tuple[Dict[str, Any], Any]]


class ResilientLoop:
    """Call :meth:`after_step` once per train iteration and
    :meth:`finish` after the loop; raises
    :class:`~gymfx_tpu.resilience.guards.NonFiniteDivergenceError` on
    sustained divergence (after saving a diagnostic checkpoint when a
    checkpoint dir is configured) and
    :class:`~gymfx_tpu.resilience.faults.SimulatedPreemptionError` at
    the injected kill point (after the iteration's checkpoint, so the
    drill resumes from it)."""

    def __init__(
        self,
        *,
        steps_per_iter: int,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 0,
        step_offset: int = 0,
        checkpoint_metadata: Optional[Dict[str, Any]] = None,
        max_consecutive_skips: int = 10,
        preempt_at: Optional[int] = None,
        loggers: Tuple[Any, ...] = (),
        ledger: Any = None,
        recorder: Any = None,
        profiler: Any = None,
        mesh_faults: Tuple[Dict[str, Any], ...] = (),
        supervisor: Any = None,
        checkpoint_keep: int = 0,
    ):
        self.steps_per_iter = int(steps_per_iter)
        self.checkpoint_dir = str(checkpoint_dir) if checkpoint_dir else None
        self.checkpoint_every = int(checkpoint_every or 0)
        self.step_offset = int(step_offset or 0)
        self.checkpoint_metadata = checkpoint_metadata
        self.preempt_at = None if preempt_at is None else int(preempt_at)
        self.monitor = (
            SkipMonitor(max_consecutive_skips)
            if int(max_consecutive_skips or 0) > 0
            else None
        )
        # delayed metric drains (DelayedLogger / DeviceMetricStream)
        # tied to this loop's lifetime: they hold their newest snapshot
        # one dispatch behind, so every abort path below must flush them
        # or the final superstep's metrics are silently dropped
        self.loggers = tuple(loggers)
        # run-forensics taps (both optional, both never-raises by
        # contract): the ledger records lifecycle events, the flight
        # recorder dumps its postmortem bundle on the abort paths
        self.ledger = ledger
        self.recorder = recorder
        # managed jax.profiler capture (telemetry/profiler.py): the loop
        # owns the cadence — begin_superstep opens the trace window,
        # after_superstep closes it and writes the capture bundle
        self.profiler = profiler
        # simulated device loss (fault grammar ``mesh=`` clause,
        # docs/resilience.md "Elastic training"): each event fires at
        # the first superstep boundary reaching its ``at`` iteration —
        # ledger mesh_degrade, flight-recorder dump, then
        # DeviceLossError for the elastic controller to classify
        self._mesh_faults = sorted(
            (dict(f) for f in mesh_faults), key=lambda f: int(f["at"])
        )
        # MeshSupervisor (parallel/elastic.py): told about scripted
        # losses so the gymfx_mesh_devices{state} gauges and the degrade
        # counter move even on CPU virtual meshes where probes still pass
        self.supervisor = supervisor
        # newest-N checkpoint retention (0 = keep everything); the
        # resume-entry step is always protected
        self.checkpoint_keep = int(checkpoint_keep or 0)
        self.last_checkpoint_step: Optional[int] = None
        # (it_start, k, guard metrics) — scalars for k == 1, stacked
        # (k,) arrays for a fused superstep
        self._pending: Optional[Tuple[int, int, Dict[str, Any]]] = None

    def _flush_loggers(self) -> None:
        for logger in self.loggers:
            try:
                logger.finish()
            except Exception:
                # a telemetry drain failure must not mask the abort
                # (or break a clean finish)
                pass

    # ------------------------------------------------------------------
    def _save(self, state_fn: StateFn, step: int) -> None:
        from gymfx_tpu.train.checkpoint import save_checkpoint

        state_dict, params = state_fn()
        save_checkpoint(
            self.checkpoint_dir, state_dict, step=step,
            metadata=self.checkpoint_metadata, params=params,
            keep=self.checkpoint_keep, protect=(self.step_offset,),
        )
        self.last_checkpoint_step = step
        if self.ledger is not None:
            self.ledger.record("checkpoint_write", step=int(step))

    def _check_pending(self, state_fn: StateFn) -> None:
        if self.monitor is None or self._pending is None:
            return
        import jax
        import numpy as np

        it_start, k, guard_metrics = self._pending
        self._pending = None
        # ONE host fetch per superstep: each guard counter arrives as a
        # stacked (k,) array ((1,) for the per-step path) and the
        # monitor replays the per-iteration deltas from it — fetched as
        # one device_get of the whole tree so mesh-sharded counters do
        # not gather per leaf
        host = {
            key: np.ravel(np.asarray(value))
            for key, value in jax.device_get(guard_metrics).items()
        }
        try:
            for j in range(k):
                self.monitor.update(
                    {key: arr[j] for key, arr in host.items()},
                    step=it_start + j,
                )
        except NonFiniteDivergenceError:
            # params are still the last finite values (the in-graph
            # guard kept them) — persist them for the post-mortem/resume
            if self.checkpoint_dir:
                self._save(
                    state_fn,
                    self.step_offset + (it_start + k) * self.steps_per_iter,
                )
            self._flush_loggers()
            if self.ledger is not None:
                self.ledger.record("divergence", it=int(it_start + k))
            if self.recorder is not None:
                self.recorder.dump("divergence",
                                   extra={"it": int(it_start + k)})
            raise

    # ------------------------------------------------------------------
    def begin_superstep(self, it_start: int, k: int = 1) -> bool:
        """Open a profiler capture window when the configured cadence
        says this dispatch is due; returns whether a capture is now
        active — the caller must block the dispatch result before
        :meth:`after_superstep` so the trace covers the device work.
        A no-op (False) without a profiler, so the fast path is one
        attribute check."""
        if self.profiler is None:
            return False
        return self.profiler.start_capture(it_start, k)

    def after_superstep(self, it_start: int, k: int, metrics: Dict[str, Any],
                        state_fn: StateFn) -> None:
        """Superstep-aware hook: call once after dispatching iterations
        ``[it_start, it_start + k)`` as one fused dispatch.  ``metrics``
        carries the per-iteration guard counters stacked on a leading
        ``(k,)`` axis (plain scalars are fine when ``k == 1``).
        ``after_step(it, m, fn)`` is exactly
        ``after_superstep(it, 1, m, fn)``.

        With ``k > 1`` checkpoints land on the first superstep boundary
        at/after each ``checkpoint_every`` multiple (only boundary
        states exist on the host), and the simulated preemption fires on
        the first boundary reaching ``preempt_at``.
        """
        it_end = it_start + k
        if self.ledger is not None:
            self.ledger.record("superstep_dispatch",
                               it_start=int(it_start), k=int(k))
        if self.profiler is not None and self.profiler.capturing:
            # close the window begin_superstep opened (never raises);
            # runs before the watchdog so an abort still gets its bundle
            self.profiler.finish_capture()
        if self.monitor is not None:
            self._check_pending(state_fn)
            self._pending = (
                it_start,
                k,
                {key: metrics[key] for key in GUARD_METRIC_KEYS if key in metrics},
            )
        if (
            self.checkpoint_dir
            and self.checkpoint_every > 0
            and it_end // self.checkpoint_every > it_start // self.checkpoint_every
        ):
            self._save(state_fn, self.step_offset + it_end * self.steps_per_iter)
        if self._mesh_faults and int(self._mesh_faults[0]["at"]) <= it_end:
            due = [f for f in self._mesh_faults if int(f["at"]) <= it_end]
            self._mesh_faults = [
                f for f in self._mesh_faults if int(f["at"]) > it_end
            ]
            lost = sorted({int(f["device"]) for f in due})
            self._flush_loggers()
            if self.supervisor is not None:
                try:
                    self.supervisor.mark_lost(lost)
                except Exception:
                    pass
            if self.ledger is not None:
                self.ledger.record(
                    "mesh_degrade", lost=lost, at=int(it_end),
                    checkpoint_step=self.last_checkpoint_step,
                )
            if self.recorder is not None:
                self.recorder.dump(
                    "device_loss", extra={"lost": lost, "at": int(it_end)}
                )
            raise DeviceLossError(
                lost, at=int(it_end),
                checkpoint_step=self.last_checkpoint_step,
                step_offset=self.step_offset,
            )
        if self.preempt_at is not None and it_end >= self.preempt_at:
            self._flush_loggers()
            if self.ledger is not None:
                self.ledger.record("preemption", it=int(it_end))
            if self.recorder is not None:
                self.recorder.dump("preemption", extra={"it": int(it_end)})
            raise SimulatedPreemptionError(it_end)

    def after_step(self, it: int, metrics: Dict[str, Any],
                   state_fn: StateFn) -> None:
        self.after_superstep(it, 1, metrics, state_fn)

    def finish(self, state_fn: StateFn) -> None:
        """Flush the one-step-delayed watchdog — and any attached
        delayed loggers — after the loop ends (the watchdog may still
        raise, so loggers flush first)."""
        self._flush_loggers()
        self._check_pending(state_fn)
