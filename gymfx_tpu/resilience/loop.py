"""Host-side per-iteration resilience hooks shared by the trainer loops.

The jitted train steps carry the in-graph guards (guards.py); this
module is the thin host loop around them:

  * the SkipMonitor divergence watchdog, run ONE STEP DELAYED — the
    guard counters for iteration ``i`` are fetched only after iteration
    ``i + 1`` has been dispatched, so the async device pipeline never
    stalls on the watchdog's host sync;
  * periodic preemption-safe auto-checkpointing (every
    ``checkpoint_every`` iterations), with the cumulative step count so
    a resumed run keeps advancing past the loaded step;
  * the simulated-preemption kill for checkpoint/resume drills
    (``fault_profile`` ``preempt_at`` clause).

One definition so the PPO and IMPALA loops cannot drift.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

from gymfx_tpu.resilience.faults import SimulatedPreemptionError
from gymfx_tpu.resilience.guards import (
    NonFiniteDivergenceError,
    SkipMonitor,
)

GUARD_METRIC_KEYS = ("nonfinite_skips", "guard_updates", "poisoned_env_resets")

# state_dict_fn: () -> (full state dict to checkpoint, params tree)
StateFn = Callable[[], Tuple[Dict[str, Any], Any]]


class ResilientLoop:
    """Call :meth:`after_step` once per train iteration and
    :meth:`finish` after the loop; raises
    :class:`~gymfx_tpu.resilience.guards.NonFiniteDivergenceError` on
    sustained divergence (after saving a diagnostic checkpoint when a
    checkpoint dir is configured) and
    :class:`~gymfx_tpu.resilience.faults.SimulatedPreemptionError` at
    the injected kill point (after the iteration's checkpoint, so the
    drill resumes from it)."""

    def __init__(
        self,
        *,
        steps_per_iter: int,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 0,
        step_offset: int = 0,
        checkpoint_metadata: Optional[Dict[str, Any]] = None,
        max_consecutive_skips: int = 10,
        preempt_at: Optional[int] = None,
    ):
        self.steps_per_iter = int(steps_per_iter)
        self.checkpoint_dir = str(checkpoint_dir) if checkpoint_dir else None
        self.checkpoint_every = int(checkpoint_every or 0)
        self.step_offset = int(step_offset or 0)
        self.checkpoint_metadata = checkpoint_metadata
        self.preempt_at = None if preempt_at is None else int(preempt_at)
        self.monitor = (
            SkipMonitor(max_consecutive_skips)
            if int(max_consecutive_skips or 0) > 0
            else None
        )
        self.last_checkpoint_step: Optional[int] = None
        self._pending: Optional[Tuple[int, Dict[str, Any]]] = None

    # ------------------------------------------------------------------
    def _save(self, state_fn: StateFn, step: int) -> None:
        from gymfx_tpu.train.checkpoint import save_checkpoint

        state_dict, params = state_fn()
        save_checkpoint(
            self.checkpoint_dir, state_dict, step=step,
            metadata=self.checkpoint_metadata, params=params,
        )
        self.last_checkpoint_step = step

    def _check_pending(self, state_fn: StateFn) -> None:
        if self.monitor is None or self._pending is None:
            return
        it, guard_metrics = self._pending
        self._pending = None
        try:
            self.monitor.update(guard_metrics, step=it)
        except NonFiniteDivergenceError:
            # params are still the last finite values (the in-graph
            # guard kept them) — persist them for the post-mortem/resume
            if self.checkpoint_dir:
                self._save(
                    state_fn, self.step_offset + (it + 1) * self.steps_per_iter
                )
            raise

    # ------------------------------------------------------------------
    def after_step(self, it: int, metrics: Dict[str, Any],
                   state_fn: StateFn) -> None:
        if self.monitor is not None:
            self._check_pending(state_fn)
            self._pending = (
                it,
                {k: metrics[k] for k in GUARD_METRIC_KEYS if k in metrics},
            )
        if (
            self.checkpoint_dir
            and self.checkpoint_every > 0
            and (it + 1) % self.checkpoint_every == 0
        ):
            self._save(
                state_fn, self.step_offset + (it + 1) * self.steps_per_iter
            )
        if self.preempt_at is not None and it + 1 >= self.preempt_at:
            raise SimulatedPreemptionError(it + 1)

    def finish(self, state_fn: StateFn) -> None:
        """Flush the one-step-delayed watchdog after the loop ends."""
        self._check_pending(state_fn)
