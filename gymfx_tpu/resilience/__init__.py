"""Resilience layer: non-finite train-step guards, retry/backoff +
circuit breaker for the live path, and a deterministic fault-injection
harness.

Three pillars (ISSUE 1):

  guards   in-graph ``jnp.isfinite`` reductions that skip a poisoned
           update (keep last-good params/opt-state), quarantine-reset
           contaminated envs, and abort loudly after N consecutive
           fully-skipped steps (train/ppo.py, train/impala.py);
  retry    generic exponential-backoff retry policy with jitter, retry
           budget and per-call timeout, plus a circuit breaker that
           trips the live order router into a flatten-and-halt
           degraded mode (live/oanda.py);
  faults   seeded injectors — flaky transports (timeouts, 5xx, partial
           responses), NaN/inf feed contamination, simulated
           preemption — usable in tests and via the ``fault_profile``
           config knob for chaos runs.
"""
from gymfx_tpu.resilience.guards import (
    NonFiniteDivergenceError,
    SkipMonitor,
    quarantine_mask,
    select_tree,
    tree_all_finite,
)
from gymfx_tpu.resilience.retry import (
    CircuitBreaker,
    CircuitOpenError,
    RetryBudget,
    RetryError,
    RetryPolicy,
    retry_call,
)
from gymfx_tpu.resilience.loop import ResilientLoop
from gymfx_tpu.resilience.faults import (
    FlakyEngine,
    FlakyTransport,
    InjectedDispatchError,
    SimulatedPreemptionError,
    apply_fault_profile_to_market_data,
    contaminate_market_data,
    flaky_engine_from_profile,
    nonfinite_report,
    parse_fault_profile,
)

__all__ = [
    "NonFiniteDivergenceError",
    "SkipMonitor",
    "quarantine_mask",
    "select_tree",
    "tree_all_finite",
    "CircuitBreaker",
    "CircuitOpenError",
    "RetryBudget",
    "RetryError",
    "RetryPolicy",
    "retry_call",
    "ResilientLoop",
    "FlakyEngine",
    "FlakyTransport",
    "InjectedDispatchError",
    "SimulatedPreemptionError",
    "apply_fault_profile_to_market_data",
    "contaminate_market_data",
    "flaky_engine_from_profile",
    "nonfinite_report",
    "parse_fault_profile",
]
