"""Non-finite train-step guards (resilience pillar 1).

A single NaN/inf — a poisoned feed window, an exploding gradient, a
bf16 overflow — silently corrupts a PPO/IMPALA train state forever:
Adam moments go NaN and every later step inherits them.  The guards
here keep long runs alive:

  in-graph   ``tree_all_finite`` reductions decide per update whether
             the loss/grads are usable; ``select_tree`` keeps the
             last-good params/opt-state when they are not (the
             ``lax.cond``-style skip, traced once, no host round trip);
  per-env    ``quarantine_mask`` finds envs whose trajectory produced
             non-finite values so the trainer can auto-reset exactly
             those (a contaminated env would otherwise carry NaN equity
             into every future rollout);
  host-side  ``SkipMonitor`` counts consecutive fully-skipped steps and
             aborts with a diagnostic instead of burning a TPU
             allocation on a run that stopped learning.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp


def _finite_leaves(tree: Any):
    return [
        x
        for x in jax.tree.leaves(tree)
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.inexact)
    ]


def tree_all_finite(tree: Any):
    """Scalar bool: every element of every floating leaf is finite.
    Integer/bool leaves are ignored (they cannot hold NaN).  Traceable —
    this is the in-jit reduction the guarded updates branch on."""
    leaves = _finite_leaves(tree)
    if not leaves:
        return jnp.asarray(True)
    return jnp.stack([jnp.all(jnp.isfinite(x)) for x in leaves]).all()


def select_tree(pred, new_tree: Any, old_tree: Any) -> Any:
    """Per-leaf ``where(pred, new, old)`` with a scalar ``pred`` — the
    skip primitive: when ``pred`` is True the update is taken, when
    False the last-good tree is kept bit-for-bit.  Equivalent to
    ``lax.cond`` on pytrees but scan-carry friendly (both branches are
    already materialized by the caller)."""
    return jax.tree.map(lambda n, o: jnp.where(pred, n, o), new_tree, old_tree)


def quarantine_mask(tree: Any, *, env_axis: int = 1, mode: str = "nonfinite"):
    """Per-env poison mask over a trajectory pytree of ``(T, N, ...)``
    arrays (time-major, env axis 1): True where ANY value belonging to
    that env is poisoned.  The trainer resets exactly those envs to a
    fresh episode — without this, one NaN bar sticks in the env's
    accumulated equity and poisons every subsequent rollout.

    ``mode='nonfinite'`` flags NaN and ±inf (right for trajectory
    outputs — rewards/obs/log-probs are never legitimately infinite);
    ``mode='nan'`` flags NaN only — required for carried env state,
    whose peak/min/max trackers hold ±inf SENTINELS by design
    (core/types.py) that must not trigger a reset."""
    if mode == "nonfinite":
        is_bad = lambda x: ~jnp.isfinite(x)  # noqa: E731
    elif mode == "nan":
        is_bad = jnp.isnan
    else:
        raise ValueError(f"mode must be 'nonfinite' or 'nan', got {mode!r}")
    masks = []
    for x in _finite_leaves(tree):
        bad = is_bad(x)
        axes = tuple(i for i in range(bad.ndim) if i != env_axis)
        masks.append(bad.any(axis=axes))
    if not masks:
        raise ValueError("quarantine_mask needs at least one floating leaf")
    out = masks[0]
    for m in masks[1:]:
        out = out | m
    return out


class NonFiniteDivergenceError(RuntimeError):
    """Training diverged: every update in N consecutive steps was
    non-finite.  Carries the last metrics snapshot for the post-mortem."""

    def __init__(self, message: str, metrics: Optional[Dict[str, Any]] = None):
        super().__init__(message)
        self.metrics = dict(metrics or {})


class SkipMonitor:
    """Host-side divergence watchdog for the trainer loops.

    ``update(metrics)`` after every train step; a step whose skipped
    update count reaches its total update count (``nonfinite_skips`` >=
    ``guard_updates``) advances the consecutive counter, any usable
    step resets it, and ``max_consecutive`` fully-skipped steps in a
    row raise :class:`NonFiniteDivergenceError` with a diagnostic —
    params are provably stale at that point, so continuing only burns
    the allocation.
    """

    def __init__(self, max_consecutive: int = 10):
        if int(max_consecutive) < 1:
            raise ValueError(
                f"max_consecutive must be >= 1, got {max_consecutive}"
            )
        self.max_consecutive = int(max_consecutive)
        self.consecutive = 0
        self.total_skips = 0
        self.total_poisoned_env_resets = 0

    def update(self, metrics: Dict[str, Any], *, step: Optional[int] = None) -> None:
        skips = int(metrics.get("nonfinite_skips", 0))
        total = int(metrics.get("guard_updates", 0))
        self.total_skips += skips
        self.total_poisoned_env_resets += int(
            metrics.get("poisoned_env_resets", 0)
        )
        if total > 0 and skips >= total:
            self.consecutive += 1
        else:
            self.consecutive = 0
        if self.consecutive >= self.max_consecutive:
            at = f" at iteration {step}" if step is not None else ""
            raise NonFiniteDivergenceError(
                f"training diverged{at}: all {total} updates were "
                f"non-finite for {self.consecutive} consecutive steps "
                f"({self.total_skips} updates skipped in total, "
                f"{self.total_poisoned_env_resets} envs quarantine-reset); "
                "params/opt-state are the last finite values — inspect "
                "the data feed for NaN/inf contamination or lower the "
                "learning rate, then resume from the latest checkpoint",
                metrics={k: _to_float(v) for k, v in metrics.items()},
            )


def _to_float(v):
    try:
        return float(v)
    except (TypeError, ValueError):
        return v
