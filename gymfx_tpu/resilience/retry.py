"""Retry/backoff + circuit breaker (resilience pillar 2).

Generic, dependency-free primitives the live path composes:

  RetryPolicy    exponential backoff with deterministic seeded jitter,
                 a per-call timeout (honored by the urllib transport)
                 and an optional cross-call :class:`RetryBudget`;
  retry_call     drives any callable under a policy, with caller-chosen
                 retryability classification for results and
                 exceptions — the caller decides what is idempotent;
  CircuitBreaker repeated failures trip OPEN (fail fast instead of
                 hammering a dead venue); after ``recovery_time`` one
                 probe call is allowed through (HALF_OPEN) and its
                 outcome closes or re-opens the circuit.

Nothing here knows about OANDA; ``live/oanda.py`` wires these around
its injectable transport and the order router.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, NamedTuple, Optional


class RetryPolicy(NamedTuple):
    """Backoff schedule: attempt k (0-based retry index) sleeps
    ``min(max_delay, base_delay * 2**k)`` scaled by a seeded jitter in
    ``[1 - jitter, 1 + jitter]`` (decorrelates a fleet of workers
    retrying the same dead endpoint).  ``timeout`` is the per-call
    transport timeout in seconds."""

    max_attempts: int = 4
    base_delay: float = 0.25
    max_delay: float = 8.0
    jitter: float = 0.25
    timeout: float = 30.0

    def delay(self, retry_index: int, rng: Optional[random.Random] = None) -> float:
        d = min(self.max_delay, self.base_delay * (2.0 ** retry_index))
        if self.jitter and rng is not None:
            d *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return max(0.0, d)


class RetryBudget:
    """Cross-call retry budget: a run-level cap on TOTAL retries so a
    systemically failing dependency degrades to fail-fast instead of
    multiplying every call's latency by the per-call retry count.

    Thread-safe: the budget is shared across concurrent callers (the
    serving path fans requests out from many client threads), so
    ``take`` must grant exactly ``max_retries`` tokens in total no
    matter how many threads race it."""

    def __init__(self, max_retries: int = 64):
        if int(max_retries) < 0:
            raise ValueError(f"max_retries must be >= 0, got {max_retries}")
        self.max_retries = int(max_retries)
        self.used = 0
        self._lock = threading.Lock()

    @property
    def remaining(self) -> int:
        with self._lock:
            return max(0, self.max_retries - self.used)

    def take(self) -> bool:
        """Consume one retry token; False when the budget is exhausted
        (the caller must fail fast instead of retrying)."""
        with self._lock:
            if self.used >= self.max_retries:
                return False
            self.used += 1
            return True


class RetryError(RuntimeError):
    """Retries exhausted; ``last`` carries the final exception or
    rejected result."""

    def __init__(self, message: str, last: Any = None):
        super().__init__(message)
        self.last = last


def retry_call(
    fn: Callable[[], Any],
    *,
    policy: RetryPolicy,
    retry_on_exc: Callable[[BaseException], bool],
    retry_on_result: Optional[Callable[[Any], bool]] = None,
    budget: Optional[RetryBudget] = None,
    sleep: Callable[[float], None] = time.sleep,
    rng: Optional[random.Random] = None,
    on_retry: Optional[Callable[[int, Any], None]] = None,
) -> Any:
    """Call ``fn`` under ``policy``.

    ``retry_on_exc(exc)`` classifies exceptions (False re-raises
    immediately — non-retryable failures must not be masked);
    ``retry_on_result(res)`` optionally rejects returned values (e.g. a
    5xx status tuple).  A rejected final attempt raises
    :class:`RetryError`.  ``sleep``/``rng`` are injectable so tests run
    instantly and deterministically.
    """
    attempts = max(1, int(policy.max_attempts))
    last: Any = None
    for attempt in range(attempts):
        try:
            result = fn()
        except BaseException as exc:  # noqa: BLE001 - classified below
            if not retry_on_exc(exc):
                raise
            last = exc
        else:
            if retry_on_result is None or not retry_on_result(result):
                return result
            last = result
        if attempt == attempts - 1:
            break
        if budget is not None and not budget.take():
            break
        if on_retry is not None:
            on_retry(attempt, last)
        sleep(policy.delay(attempt, rng))
    if isinstance(last, BaseException):
        raise RetryError(
            f"retries exhausted after {attempts} attempts: {last!r}", last
        ) from last
    raise RetryError(
        f"retries exhausted after {attempts} attempts: {last!r}", last
    )


class CircuitOpenError(RuntimeError):
    """The circuit breaker is OPEN: the dependency failed repeatedly and
    calls are refused locally until the recovery window elapses."""


class CircuitBreaker:
    """Classic three-state breaker (closed -> open -> half-open).

    ``allow()`` gates every call: CLOSED passes, OPEN raises
    :class:`CircuitOpenError` until ``recovery_time`` has elapsed, then
    exactly one probe passes (HALF_OPEN).  ``record_success`` closes the
    circuit and clears the failure count; ``record_failure`` increments
    it and trips OPEN at ``failure_threshold`` (a half-open probe
    failure re-trips immediately).  ``on_trip`` fires on the CLOSED ->
    OPEN transition (not on half-open re-trips) — the live router uses
    it to enter its flatten-and-halt degraded mode exactly once.

    Thread-safe: the serving path shares one breaker between the
    batcher worker and any direct-dispatch callers, so transitions are
    serialized under a lock.  ``on_trip`` fires OUTSIDE the lock (the
    router's flatten hook makes venue calls; holding the breaker lock
    across those would invite deadlock)."""

    def __init__(
        self,
        failure_threshold: int = 5,
        recovery_time: float = 30.0,
        *,
        clock: Callable[[], float] = time.monotonic,
        on_trip: Optional[Callable[[], None]] = None,
    ):
        if int(failure_threshold) < 1:
            raise ValueError(
                f"failure_threshold must be >= 1, got {failure_threshold}"
            )
        self.failure_threshold = int(failure_threshold)
        self.recovery_time = float(recovery_time)
        self._clock = clock
        # public so a consumer built AFTER the breaker (the order
        # router) can attach its degraded-mode entry hook
        self.on_trip = on_trip
        self.failures = 0
        self.trip_count = 0
        self._opened_at: Optional[float] = None
        self._probing = False
        self._lock = threading.Lock()

    @property
    def state(self) -> str:
        with self._lock:
            return self._state_locked()

    def _state_locked(self) -> str:
        if self._opened_at is None:
            return "closed"
        if self._probing:
            return "half_open"
        if self._clock() - self._opened_at >= self.recovery_time:
            return "half_open"
        return "open"

    def allow(self) -> None:
        with self._lock:
            if self._opened_at is None:
                return
            if self._probing:
                # one probe is already in flight; refuse concurrent calls
                raise CircuitOpenError(
                    "circuit breaker half-open: probe in flight"
                )
            elapsed = self._clock() - self._opened_at
            if elapsed < self.recovery_time:
                raise CircuitOpenError(
                    f"circuit breaker open after {self.failures} consecutive "
                    f"failures; retrying in "
                    f"{self.recovery_time - elapsed:.1f}s"
                )
            self._probing = True  # half-open: let exactly one probe through

    def record_success(self) -> None:
        with self._lock:
            self.failures = 0
            self._opened_at = None
            self._probing = False

    def record_failure(self) -> None:
        fire_trip = False
        with self._lock:
            self.failures += 1
            was_open = self._opened_at is not None
            if self._probing or self.failures >= self.failure_threshold:
                self._opened_at = self._clock()  # (re-)arm the recovery window
                self._probing = False
                if not was_open:
                    self.trip_count += 1
                    fire_trip = self.on_trip is not None
        if fire_trip:
            self.on_trip()
