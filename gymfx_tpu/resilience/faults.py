"""Deterministic fault injection (resilience pillar 3).

Everything here is seeded and replayable: the same profile string
produces the same fault sequence on every run, so a chaos failure is a
plain red test, not a flake.

  FlakyTransport           wraps a live-path transport with a scripted
                           fault plan (timeouts, connection drops, 5xx,
                           accept-then-fail, truncated bodies);
  FlakyEngine              wraps a serving InferenceEngine with scripted
                           DISPATCH faults (slow dispatch, stalled
                           worker, engine exceptions) — the serving
                           chaos harness behind bench_infer.py's
                           burst-overload scenario;
  contaminate_market_data  injects NaN/inf into feed windows (the bars
                           AND the padded obs window, so both the
                           reward path and the policy input see them);
  SimulatedPreemptionError mid-run kill for checkpoint/resume drills;
  parse_fault_profile      the ``fault_profile`` config-knob grammar.

Profile grammar — semicolon-separated ``key=value`` clauses::

    nan_bars=30-31;transport=http:503,http:503,ok;seed=7
    serve=slow:40+slow:40+exc+ok;burst=32x4;seed=0

  nan_bars / inf_bars   bar indices to poison: ``N``, ``N-M`` (inclusive)
                        or ``N,M,K`` (comma list within the clause is
                        not supported — use multiple clauses or a range)
  fields                comma-free ``+``-joined MarketData fields to
                        poison (default ``close``)
  transport             ``+``- or ``,``-joined fault tokens consumed one
                        per HTTP call (see FAULT_TOKENS)
  serve                 ``+``- or ``,``-joined serving fault tokens
                        consumed one per engine dispatch (see
                        SERVE_FAULT_TOKENS), or ``pR`` for a seeded
                        probabilistic plan at rate R
  burst                 ``NxK`` — the burst-arrival shape for overload
                        scenarios: K rounds of N simultaneous requests
                        (consumed by bench_infer.py's chaos phase)
  fleet                 ``+``-joined fleet fault events of the form
                        ``<action>:<replica>@<decision>[:<ms>]`` —
                        ``kill:1@8`` kills replica 1 at global decision
                        index 8, ``stall:0@4:250`` wedges replica 0's
                        next dispatch for 250 ms at decision 4,
                        ``flap:2@6`` makes replica 2 throw transient
                        dispatch errors at decision 6 then recover
                        (consumed by tools/fleet_chaos.py)
  mesh                  ``+``-joined TRAINING-mesh fault events of the
                        form ``kill:<device>@<superstep>`` —
                        ``kill:3@2`` marks mesh device 3 lost at the
                        first superstep boundary reaching iteration 2;
                        the trainer loop raises DeviceLossError after
                        ledgering a ``mesh_degrade`` row and dumping
                        the flight recorder, and the elastic runtime
                        (parallel/elastic.py) re-plans a survivor mesh
                        and auto-resumes from the last checkpoint
                        (consumed by tools/elastic_chaos.py)
  preempt_at            iteration index after which the trainer raises
                        SimulatedPreemptionError (checkpoint drill)
  scengen               a scengen preset name (``scengen=flash_crash``):
                        overlays the preset's STRUCTURED market stress —
                        crash drops with recovery tails, drought spread
                        blowouts, gap level shifts — onto the training
                        feed (gymfx_tpu/scengen/stress.py), so chaos
                        runs fuzz with market moves, not only NaNs
  seed                  seed for probabilistic plans (``transport=p0.3``)
                        and the scengen stress layout
"""
from __future__ import annotations

import random
import socket
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

FAULT_TOKENS = (
    "ok",               # pass through untouched
    "timeout",          # socket.timeout before the venue sees anything
    "conn",             # ConnectionError before the venue sees anything
    "http:<code>",      # synthesize an HTTP error; venue sees nothing
    "accept-then-503",  # venue PROCESSES the call, response is lost as a
                        # 503 — the case that distinguishes safe retry
                        # (lookup-first) from double-fill (blind resubmit)
    "partial",          # venue processes, body truncated mid-JSON
)


SERVE_FAULT_TOKENS = (
    "ok",           # dispatch passes through untouched
    "slow:<ms>",    # dispatch completes after an injected delay —
                    # queued requests age past their deadlines
    "stall:<ms>",   # a long injected delay standing in for a wedged
                    # worker/runtime (same mechanics as slow, separate
                    # token so plans read as what they simulate)
    "exc",          # the dispatch raises InjectedDispatchError — feeds
                    # the serving circuit breaker
)


FLEET_FAULT_ACTIONS = (
    "kill",     # hard-fail the replica: batcher killed, standby promoted
    "stall",    # one wedged dispatch of <ms> (supervisor sees a slow/dead
                # probe; requests re-route)
    "flap",     # a short burst of dispatch exceptions, then recovery —
                # the transient-fault case retries must absorb
)


MESH_FAULT_ACTIONS = (
    "kill",     # mark a mesh device lost: the trainer loop aborts with
                # DeviceLossError at the superstep boundary and the
                # elastic runtime re-plans over the survivors
)


class InjectedDispatchError(RuntimeError):
    """Injected engine-dispatch failure (the serving chaos harness's
    stand-in for an XLA runtime error / device loss mid-dispatch)."""


class DeviceLossError(RuntimeError):
    """A mesh device (or host) was lost mid-training — real XLA device
    errors are re-classified into this type by
    :func:`gymfx_tpu.parallel.elastic.is_device_loss`; the simulated
    ``mesh=`` fault grammar raises it directly from the trainer loop.

    Carries everything the elastic auto-resume controller needs to
    re-plan and resume: the lost device indices, the superstep boundary
    the loss surfaced at, the last checkpoint step that made it to disk
    (None = nothing checkpointed yet, the retry cold-starts), and the
    step offset the dying run started from."""

    def __init__(self, lost: Sequence[int], at: Optional[int] = None,
                 checkpoint_step: Optional[int] = None,
                 step_offset: int = 0):
        lost_t = tuple(int(d) for d in lost)
        super().__init__(
            f"mesh device(s) {list(lost_t)} lost"
            + (f" at superstep {int(at)}" if at is not None else "")
            + (
                f"; last good checkpoint at step {int(checkpoint_step)}"
                if checkpoint_step is not None
                else "; no checkpoint written yet"
            )
        )
        self.lost = lost_t
        self.at = None if at is None else int(at)
        self.checkpoint_step = (
            None if checkpoint_step is None else int(checkpoint_step)
        )
        self.step_offset = int(step_offset or 0)


class FlakyEngine:
    """Deterministic chaos wrapper around a serving InferenceEngine.

    Intercepts ``decide_batch`` (the batcher's dispatch path) with a
    scripted fault plan consumed one token per dispatch — dispatches
    beyond the plan pass through — or a seeded probabilistic plan
    (``failure_rate`` + ``rate_tokens``).  Every other attribute
    (buckets, recurrent, obs_dtype, initial_carry, bucket_for, ...)
    delegates to the wrapped engine, so the wrapper drops into
    ``MicroBatcher(engine=...)`` unchanged.  ``sleep`` is injectable so
    tests can run stall plans instantly.
    """

    def __init__(
        self,
        inner: Any,
        *,
        plan: Sequence[str] = (),
        failure_rate: float = 0.0,
        rate_tokens: Sequence[str] = ("slow:50", "exc"),
        seed: int = 0,
        sleep: Callable[[float], None] = None,
    ):
        import time as _time

        self._inner = inner
        self._plan: List[str] = [str(t) for t in plan]
        self._rate = float(failure_rate)
        self._rate_tokens = tuple(rate_tokens)
        self._rng = random.Random(seed)
        self._sleep = _time.sleep if sleep is None else sleep
        self.dispatch_calls = 0
        self.faults_injected = 0
        self.history: List[str] = []

    # attributes that belong to the WRAPPER; everything else reads from
    # and writes through to the wrapped engine, so deployer/fleet wiring
    # (``engine.on_compile = cb``, ``engine.params = ...``) lands on the
    # real engine even when chaos is interposed
    _OWN_ATTRS = frozenset(
        {
            "_inner",
            "_plan",
            "_rate",
            "_rate_tokens",
            "_rng",
            "_sleep",
            "dispatch_calls",
            "faults_injected",
            "history",
        }
    )

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)

    def __setattr__(self, name: str, value: Any) -> None:
        if name in self._OWN_ATTRS or "_inner" not in self.__dict__:
            object.__setattr__(self, name, value)
        elif hasattr(self._inner, name):
            setattr(self._inner, name, value)
        else:
            object.__setattr__(self, name, value)

    def push_faults(self, *tokens: str) -> None:
        """Append fault tokens to the scripted plan mid-run — how the
        fleet-chaos harness turns a parsed ``fleet=`` stall/flap event
        into this replica's next dispatches."""
        self._plan.extend(str(t) for t in tokens)

    def _next_token(self) -> str:
        if self._plan:
            return self._plan.pop(0)
        if self._rate > 0.0 and self._rng.random() < self._rate:
            return self._rng.choice(self._rate_tokens)
        return "ok"

    def _consume_token(self) -> None:
        """One fault decision per dispatch, shared by every intercepted
        dispatch surface (sync, slot, async)."""
        self.dispatch_calls += 1
        token = self._next_token()
        self.history.append(token)
        if token == "ok":
            return
        self.faults_injected += 1
        if token.startswith(("slow:", "stall:")):
            self._sleep(float(token.split(":", 1)[1]) / 1e3)
            return
        if token == "exc":
            raise InjectedDispatchError(
                "injected engine dispatch failure"
            )
        raise ValueError(
            f"unknown serve fault token {token!r}; known: {SERVE_FAULT_TOKENS}"
        )

    def decide_batch(self, obs_batch: Any, carries: Any = None):
        self._consume_token()
        return self._inner.decide_batch(obs_batch, carries)

    # the slot-cache / pipelined dispatch surfaces (serve/slots.py,
    # docs/serving.md "Device-resident sessions") are class-defined on
    # InferenceEngine, so __getattr__ delegation alone would bypass
    # fault injection — intercept them explicitly.  Faults inject at
    # DISPATCH time (matching the sync path); a resolve() of an already
    # issued handle is never failed by the wrapper.
    def dispatch_async(self, obs_batch: Any, carries: Any = None, **kwargs):
        self._consume_token()
        return self._inner.dispatch_async(obs_batch, carries, **kwargs)

    def decide_batch_slots(
        self, obs_batch: Any, sessions: Any, seed_carries: Any = None
    ):
        return self.dispatch_async(
            obs_batch, sessions=sessions, seed_carries=seed_carries
        ).resolve()

    def decide(self, obs_vec: Any, carry: Any = None):
        """Single-request convenience routed through the FAULTED
        ``decide_batch`` (the inner engine's own ``decide`` would bypass
        the plan), so the live direct-dispatch path is chaos-testable
        too."""
        import jax

        carries = None
        if self._inner.recurrent:
            if carry is None:
                carry = self._inner.initial_carry()
            carries = jax.tree.map(lambda x: np.asarray(x)[None], carry)
        out = self.decide_batch(np.asarray(obs_vec)[None], carries)
        return type(out)(
            out.action[0],
            out.value[0],
            out.actor_out[0],
            jax.tree.map(lambda x: x[0], out.carry)
            if self._inner.recurrent
            else out.carry,
        )


def flaky_engine_from_profile(
    engine: Any,
    profile: Dict[str, Any],
    *,
    sleep: Callable[[float], None] = None,
) -> Any:
    """Wrap ``engine`` per the parsed profile's serving clauses; an
    inert profile returns the engine untouched, so the fast path stays
    byte-for-byte the pre-chaos code path."""
    plan = profile.get("serve_plan") or []
    rate = float(profile.get("serve_rate") or 0.0)
    if not plan and rate <= 0.0:
        return engine
    return FlakyEngine(
        engine,
        plan=plan,
        failure_rate=rate,
        seed=int(profile.get("seed", 0)),
        sleep=sleep,
    )


class SimulatedPreemptionError(RuntimeError):
    """Injected mid-run kill: the trainer stops as if the TPU allocation
    was preempted.  Carries the iteration index for the resume drill."""

    def __init__(self, iteration: int):
        super().__init__(
            f"simulated preemption after iteration {iteration}; resume "
            "from the latest auto-checkpoint"
        )
        self.iteration = int(iteration)


class FlakyTransport:
    """Deterministic flaky wrapper around a live-path transport.

    ``plan`` is a sequence of fault tokens consumed one per call (calls
    beyond the plan pass through); alternatively ``failure_rate`` draws
    tokens from ``rate_tokens`` with a seeded RNG.  Matches the
    ``Transport`` callable shape of ``live/oanda.py`` exactly, so it
    drops into ``OandaLiveBroker(transport=...)`` and composes under the
    retry layer.

    The injected HTTP errors return OANDA-shaped ``errorMessage`` bodies
    so the production error path (not a test-only one) handles them.
    """

    def __init__(
        self,
        inner: Callable[..., Any],
        *,
        plan: Sequence[str] = (),
        failure_rate: float = 0.0,
        rate_tokens: Sequence[str] = ("timeout", "http:503"),
        seed: int = 0,
        match: Optional[Callable[[str, str], bool]] = None,
    ):
        self._inner = inner
        self._plan: List[str] = [str(t) for t in plan]
        self._rate = float(failure_rate)
        self._rate_tokens = tuple(rate_tokens)
        self._rng = random.Random(seed)
        self._match = match
        self.calls = 0
        self.faults_injected = 0
        self.history: List[str] = []

    def _next_token(self) -> str:
        if self._plan:
            return self._plan.pop(0)
        if self._rate > 0.0 and self._rng.random() < self._rate:
            return self._rng.choice(self._rate_tokens)
        return "ok"

    def __call__(self, method: str, url: str, headers: Dict[str, str],
                 body: Optional[bytes]):
        self.calls += 1
        if self._match is not None and not self._match(method, url):
            self.history.append("ok")
            return self._inner(method, url, headers, body)
        token = self._next_token()
        self.history.append(token)
        if token == "ok":
            return self._inner(method, url, headers, body)
        self.faults_injected += 1
        if token == "timeout":
            raise socket.timeout("injected transport timeout")
        if token == "conn":
            raise ConnectionError("injected connection failure")
        if token.startswith("http:"):
            code = int(token.split(":", 1)[1])
            return code, (
                b'{"errorMessage":"injected fault: HTTP %d"}' % code
            )
        if token == "accept-then-503":
            # the venue processed the request; only the response is lost
            self._inner(method, url, headers, body)
            return 503, b'{"errorMessage":"injected fault: response lost"}'
        if token == "partial":
            status, raw = self._inner(method, url, headers, body)
            text = raw if isinstance(raw, (bytes, bytearray)) else str(raw).encode()
            return status, bytes(text)[: max(1, len(text) // 2)]
        raise ValueError(f"unknown fault token {token!r}; known: {FAULT_TOKENS}")


def contaminate_market_data(
    data: Any,
    *,
    bars: Iterable[int],
    fields: Sequence[str] = ("close",),
    value: float = float("nan"),
) -> Any:
    """Poison ``bars`` of the named MarketData fields with ``value``
    (NaN by default) and return the rebuilt MarketData.

    Price fields are mirrored into ``padded_close`` at the shifted
    offsets so BOTH consumption paths see the contamination: the reward
    path reads ``close[t]`` and the obs window dynamic-slices
    ``padded_close`` — poisoning only one would understate the blast
    radius a real bad feed row has.
    """
    import jax.numpy as jnp

    bar_idx = np.asarray(sorted(set(int(b) for b in bars)), dtype=np.int64)
    if bar_idx.size == 0:
        return data
    n = int(np.asarray(data.close).shape[0])
    if bar_idx.min() < 0 or bar_idx.max() >= n:
        raise ValueError(
            f"fault bars {bar_idx.min()}..{bar_idx.max()} out of range "
            f"for a {n}-bar dataset"
        )
    replace: Dict[str, Any] = {}
    for field in fields:
        arr = np.asarray(getattr(data, field)).copy()
        arr[bar_idx, ...] = value
        replace[field] = jnp.asarray(arr, dtype=getattr(data, field).dtype)
        if field == "close":
            padded = np.asarray(data.padded_close).copy()
            pad = padded.shape[0] - n
            padded[bar_idx + pad] = value
            replace["padded_close"] = jnp.asarray(
                padded, dtype=data.padded_close.dtype
            )
    return data._replace(**replace)


def nonfinite_report(data: Any) -> Dict[str, int]:
    """Host-side diagnostic: count of non-finite values per floating
    MarketData field (all zeros on a clean feed).  Cheap enough to run
    once at load time; the guard metrics point here when they fire."""
    out: Dict[str, int] = {}
    for field, arr in zip(type(data)._fields, data):
        host = np.asarray(arr)
        if not np.issubdtype(host.dtype, np.inexact):
            continue
        bad = int((~np.isfinite(host)).sum())
        if bad:
            out[field] = bad
    return out


def _parse_fleet_token(tok: str) -> Dict[str, Any]:
    """Parse one fleet fault event ``<action>:<replica>@<decision>[:<ms>]``
    (``ms`` only for ``stall``, default 250)."""
    action, sep, rest = tok.partition(":")
    if action not in FLEET_FAULT_ACTIONS or not sep:
        raise ValueError(
            f"fleet fault token {tok!r} must start with one of "
            f"{FLEET_FAULT_ACTIONS} followed by ':<replica>@<decision>'"
        )
    replica_s, at_sep, at_s = rest.partition("@")
    if not at_sep:
        raise ValueError(
            f"fleet fault token {tok!r} is missing '@<decision>'"
        )
    ms: Optional[float] = None
    if action == "stall":
        at_s, _, ms_s = at_s.partition(":")
        ms = float(ms_s) if ms_s else 250.0
        if ms <= 0:
            raise ValueError(f"fleet stall ms must be > 0, got {ms!r}")
    elif ":" in at_s:
        raise ValueError(
            f"fleet fault token {tok!r}: only 'stall' takes a ':<ms>' tail"
        )
    replica, at = int(replica_s), int(at_s)
    if replica < 0 or at < 0:
        raise ValueError(
            f"fleet fault token {tok!r}: replica and decision index "
            "must be >= 0"
        )
    return {"action": action, "replica": replica, "at": at, "ms": ms}


def _parse_mesh_token(tok: str) -> Dict[str, Any]:
    """Parse one mesh fault event ``kill:<device>@<superstep>``."""
    action, sep, rest = tok.partition(":")
    if action not in MESH_FAULT_ACTIONS or not sep:
        raise ValueError(
            f"mesh fault token {tok!r} must start with one of "
            f"{MESH_FAULT_ACTIONS} followed by ':<device>@<superstep>'"
        )
    device_s, at_sep, at_s = rest.partition("@")
    if not at_sep:
        raise ValueError(f"mesh fault token {tok!r} is missing '@<superstep>'")
    try:
        device, at = int(device_s), int(at_s)
    except ValueError:
        raise ValueError(
            f"mesh fault token {tok!r}: device and superstep must be ints"
        ) from None
    if device < 0 or at < 0:
        raise ValueError(
            f"mesh fault token {tok!r}: device and superstep index "
            "must be >= 0"
        )
    return {"action": action, "device": device, "at": at}


def strip_fired_mesh_events(spec: Optional[str],
                            fired_at: int) -> Optional[str]:
    """Rewrite a fault-profile string with every ``mesh=`` event whose
    ``at`` index is <= ``fired_at`` removed — how the elastic auto-
    resume controller keeps a retried run from re-killing the device
    it already lost.  Non-mesh clauses pass through verbatim; a mesh
    clause with no surviving events is dropped entirely."""
    if not spec:
        return spec
    clauses: List[str] = []
    for clause in str(spec).split(";"):
        stripped = clause.strip()
        if not stripped:
            continue
        key, sep, val = stripped.partition("=")
        if sep and key.strip() == "mesh":
            keep = [
                tok for tok in val.replace(",", "+").split("+")
                if tok and _parse_mesh_token(tok)["at"] > int(fired_at)
            ]
            if keep:
                clauses.append(f"mesh={'+'.join(keep)}")
            continue
        clauses.append(stripped)
    return ";".join(clauses)


def _parse_bars(spec: str) -> List[int]:
    spec = spec.strip()
    if "-" in spec:
        lo, hi = spec.split("-", 1)
        return list(range(int(lo), int(hi) + 1))
    return [int(spec)]


def parse_fault_profile(spec: Optional[str]) -> Dict[str, Any]:
    """Parse the ``fault_profile`` config string (grammar in the module
    docstring) into a plain dict::

        {"nan_bars": [...], "inf_bars": [...], "fields": [...],
         "transport_plan": [...], "transport_rate": float,
         "serve_plan": [...], "serve_rate": float,
         "burst": {"size": int, "rounds": int}|None,
         "fleet": [{"action": str, "replica": int, "at": int,
                    "ms": float|None}, ...]  (sorted by "at"),
         "mesh": [{"action": str, "device": int, "at": int}, ...]
                  (sorted by "at"),
         "preempt_at": int|None, "seed": int}

    Empty/None spec parses to an all-inert profile; unknown clause keys
    raise (a typo'd chaos knob must not silently run a clean baseline).
    """
    profile: Dict[str, Any] = {
        "nan_bars": [],
        "inf_bars": [],
        "fields": ["close"],
        "transport_plan": [],
        "transport_rate": 0.0,
        "serve_plan": [],
        "serve_rate": 0.0,
        "burst": None,
        "fleet": [],
        "mesh": [],
        "preempt_at": None,
        "scengen": None,
        "seed": 0,
    }
    if not spec:
        return profile
    for clause in str(spec).split(";"):
        clause = clause.strip()
        if not clause:
            continue
        if "=" not in clause:
            raise ValueError(
                f"fault_profile clause {clause!r} is not key=value"
            )
        key, val = (part.strip() for part in clause.split("=", 1))
        if key == "nan_bars":
            profile["nan_bars"].extend(_parse_bars(val))
        elif key == "inf_bars":
            profile["inf_bars"].extend(_parse_bars(val))
        elif key == "fields":
            profile["fields"] = [
                f for f in val.replace("+", ",").split(",") if f
            ]
        elif key == "transport":
            if val.startswith("p") and _is_float(val[1:]):
                profile["transport_rate"] = float(val[1:])
            else:
                profile["transport_plan"] = [
                    t for t in val.replace("+", ",").split(",") if t
                ]
        elif key == "serve":
            if val.startswith("p") and _is_float(val[1:]):
                profile["serve_rate"] = float(val[1:])
            else:
                profile["serve_plan"] = [
                    t for t in val.replace("+", ",").split(",") if t
                ]
        elif key == "burst":
            size, _, rounds = val.partition("x")
            profile["burst"] = {
                "size": int(size),
                "rounds": int(rounds) if rounds else 1,
            }
            if profile["burst"]["size"] < 1 or profile["burst"]["rounds"] < 1:
                raise ValueError(
                    f"burst clause must be NxK with N,K >= 1, got {val!r}"
                )
        elif key == "fleet":
            for tok in [t for t in val.replace(",", "+").split("+") if t]:
                profile["fleet"].append(_parse_fleet_token(tok))
            profile["fleet"].sort(key=lambda ev: ev["at"])
        elif key == "mesh":
            for tok in [t for t in val.replace(",", "+").split("+") if t]:
                profile["mesh"].append(_parse_mesh_token(tok))
            profile["mesh"].sort(key=lambda ev: ev["at"])
        elif key == "preempt_at":
            profile["preempt_at"] = int(val)
        elif key == "scengen":
            # honor-or-reject at parse time (params is numpy-only, so
            # this stays importable from jax-free serving contexts)
            from gymfx_tpu.scengen.params import scenario_params

            scenario_params(val)
            profile["scengen"] = val
        elif key == "seed":
            profile["seed"] = int(val)
        else:
            raise ValueError(
                f"unknown fault_profile key {key!r}; known: nan_bars, "
                "inf_bars, fields, transport, serve, burst, fleet, "
                "mesh, preempt_at, scengen, seed"
            )
    return profile


def apply_fault_profile_to_market_data(data: Any, profile: Dict[str, Any]) -> Any:
    """Apply the feed-contamination part of a parsed profile (transport
    and preemption faults are wired where those subsystems live).
    Scengen stress goes first so NaN/inf clauses can poison the
    stressed bars too."""
    if profile.get("scengen"):
        from gymfx_tpu.scengen.stress import apply_scengen_stress

        data = apply_scengen_stress(
            data, profile["scengen"], seed=int(profile.get("seed", 0))
        )
    if profile.get("nan_bars"):
        data = contaminate_market_data(
            data, bars=profile["nan_bars"],
            fields=tuple(profile.get("fields", ("close",))),
            value=float("nan"),
        )
    if profile.get("inf_bars"):
        data = contaminate_market_data(
            data, bars=profile["inf_bars"],
            fields=tuple(profile.get("fields", ("close",))),
            value=float("inf"),
        )
    return data


def _is_float(s: str) -> bool:
    try:
        float(s)
        return True
    except ValueError:
        return False
