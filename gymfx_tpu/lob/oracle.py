"""Pure-Python reference book — the oracle half of the LOB parity
contract.

This mirrors ``lob/book.py`` operation-for-operation in plain Python
ints (no JAX, no floats on the matching path): same fixed capacity
(``depth_levels`` price levels per side, ``queue_slots`` FIFO slots per
level, overflow drops the order), same price-time priority, same
partial-fill walk, same cancel-by-oid semantics.  The crosscheck
(simulation/crosscheck.py) and the 4096-stream parity test
(tests/test_lob.py) replay identical message streams through both and
require every fill record to match EXACTLY — integer ticks and lots,
no epsilon.

Capacity semantics that MUST stay in lockstep with the array engine:
  * a resting order at a new price claims a level only while fewer than
    ``depth_levels`` prices are active on that side; otherwise it is
    dropped (``rested_qty`` 0);
  * within a level, a full FIFO queue drops the incoming order;
  * the array engine assigns the lowest-index free level, which never
    affects matching order (matching sorts by price) — the oracle just
    tracks the set of active prices.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

from .book import (
    AGENT_OID,
    MSG_ADD,
    MSG_CANCEL,
    MSG_MARKET,
    MSG_NOOP,
    PRICE_CAP,
)


class OracleFill:
    """Mirror of book.FillRecord (plain ints)."""

    __slots__ = (
        "filled_qty", "filled_value", "fill_events", "agent_qty",
        "agent_value", "price_min", "price_max", "rested_qty",
        "cancelled_qty",
    )

    def __init__(self):
        self.filled_qty = 0
        self.filled_value = 0
        self.fill_events = 0
        self.agent_qty = 0
        self.agent_value = 0
        self.price_min = PRICE_CAP
        self.price_max = 0
        self.rested_qty = 0
        self.cancelled_qty = 0

    def astuple(self) -> Tuple[int, ...]:
        return (
            self.filled_qty, self.filled_value, self.fill_events,
            self.agent_qty, self.agent_value, self.price_min,
            self.price_max, self.rested_qty, self.cancelled_qty,
        )


class OracleBook:
    """Two-sided book: per side a dict price -> FIFO list of
    ``[qty, oid]`` entries (live orders only)."""

    def __init__(self, depth_levels: int, queue_slots: int):
        self.depth_levels = int(depth_levels)
        self.queue_slots = int(queue_slots)
        self.bids: Dict[int, List[List[int]]] = {}
        self.asks: Dict[int, List[List[int]]] = {}

    # -- views -----------------------------------------------------------
    def best_bid(self) -> int:
        return max(self.bids) if self.bids else 0

    def best_ask(self) -> int:
        return min(self.asks) if self.asks else PRICE_CAP

    def depth(self, is_bid: bool) -> int:
        side = self.bids if is_bid else self.asks
        return sum(q for lvl in side.values() for q, _ in lvl)

    def canonical(self):
        """Sorted (price, [(qty, oid), ...]) per side — for comparing a
        final book state against the array engine's."""
        return (
            sorted((p, [tuple(e) for e in lvl]) for p, lvl in self.bids.items()),
            sorted((p, [tuple(e) for e in lvl]) for p, lvl in self.asks.items()),
        )

    # -- primitives ------------------------------------------------------
    def _match(self, taker_is_buy: bool, qty: int, limit: int,
               fill: OracleFill) -> int:
        """Walk the opposing side best-price-first; returns unfilled."""
        side = self.asks if taker_is_buy else self.bids
        prices = sorted(side) if taker_is_buy else sorted(side, reverse=True)
        remaining = qty
        for p in prices:
            if remaining <= 0:
                break
            if taker_is_buy and p > limit:
                break
            if not taker_is_buy and p < limit:
                break
            level = side[p]
            for entry in level:
                if remaining <= 0:
                    break
                take = min(remaining, entry[0])
                if take <= 0:
                    continue
                entry[0] -= take
                remaining -= take
                fill.filled_qty += take
                fill.filled_value += take * p
                fill.fill_events += 1
                if entry[1] == AGENT_OID:
                    fill.agent_qty += take
                    fill.agent_value += take * p
                fill.price_min = min(fill.price_min, p)
                fill.price_max = max(fill.price_max, p)
            side[p] = [e for e in level if e[0] > 0]
            if not side[p]:
                del side[p]
        return remaining

    def _rest(self, is_buy: bool, price: int, qty: int, oid: int,
              fill: OracleFill) -> None:
        if qty <= 0:
            return
        side = self.bids if is_buy else self.asks
        if price not in side and len(side) >= self.depth_levels:
            return  # book full: drop (fixed capacity)
        level = side.setdefault(price, [])
        if len(level) >= self.queue_slots:
            if not level:
                del side[price]
            return  # queue full: drop
        level.append([qty, oid])
        fill.rested_qty = qty

    # -- message ops -----------------------------------------------------
    def market(self, is_buy: bool, qty: int) -> OracleFill:
        fill = OracleFill()
        limit = PRICE_CAP if is_buy else 0
        self._match(is_buy, qty, limit, fill)
        return fill

    def add(self, is_buy: bool, price: int, qty: int, oid: int) -> OracleFill:
        fill = OracleFill()
        remaining = self._match(is_buy, qty, price, fill)
        self._rest(is_buy, price, remaining, oid, fill)
        return fill

    def cancel(self, is_buy: bool, oid: int) -> OracleFill:
        fill = OracleFill()
        if oid == 0:
            return fill
        side = self.bids if is_buy else self.asks
        for p in list(side):
            level = side[p]
            removed = sum(q for q, o in level if o == oid)
            if removed:
                fill.cancelled_qty += removed
                side[p] = [e for e in level if e[1] != oid]
                if not side[p]:
                    del side[p]
        return fill

    def process(self, kind: int, side: int, price: int, qty: int,
                oid: int) -> OracleFill:
        kind = max(0, min(3, int(kind)))
        is_buy = int(side) > 0
        if kind == MSG_NOOP:
            return OracleFill()
        if kind == MSG_ADD:
            return self.add(is_buy, int(price), int(qty), int(oid))
        if kind == MSG_CANCEL:
            return self.cancel(is_buy, int(oid))
        assert kind == MSG_MARKET
        return self.market(is_buy, int(qty))


class OracleVenue:
    """Pure-Python float64 twin of ``venue.execute_bar`` — the third
    engine's oracle side in ``simulation/crosscheck.py``.

    Book matching runs through :class:`OracleBook` (exact integer
    parity with the array engine); the ledger mirrors
    ``broker.apply_fill``'s balance-relevant fields in float64.
    Discrete decisions that must match the f32 engine bit-for-bit
    (lots rounding, bracket tick snapping) are computed in
    ``np.float32`` arithmetic — the same IEEE ops the traced kernel
    runs — so oracle and engine always agree on WHAT traded and only
    the continuous ledger arithmetic carries dtype error.
    """

    def __init__(self, *, depth_levels: int, queue_slots: int,
                 seed_levels: int, tick: float, lot_units: float,
                 commission: float, initial_cash: float):
        self.depth_levels = int(depth_levels)
        self.queue_slots = int(queue_slots)
        self.seed_levels = int(seed_levels)
        self.tick = float(tick)
        self.lot_units = float(lot_units)
        self.commission = float(commission)
        self.initial_cash = float(initial_cash)
        # ledger (broker.apply_fill mirror: balance-relevant fields)
        self.pos = 0.0
        self.entry = 0.0
        self.cash_delta = 0.0
        self.commission_paid = 0.0
        self.fills_units = 0.0     # sum |delta| across fills (bound input)
        # brackets in ticks (0 = disarmed)
        self.sl = 0
        self.tp = 0
        self.denied = 0

    # -- f32-exact discrete helpers (mirror venue.to_lots/bracket_ticks) -
    def _to_lots(self, units: float) -> int:
        import numpy as np

        q = np.float32(abs(np.float32(units))) / np.float32(self.lot_units)
        return int(np.round(q))

    def _ticks(self, price: float) -> int:
        import numpy as np

        return int(np.round(np.float32(price) / np.float32(self.tick)))

    # -- ledger (broker.apply_fill, slippage/tick zero) ------------------
    def _apply_fill(self, price: float, target: float) -> None:
        delta = target - self.pos
        if delta == 0.0 and target != 0.0:
            return
        fill = float(price)
        commission = self.commission * fill * abs(delta)
        self.cash_delta -= delta * fill + commission
        self.commission_paid += commission
        self.fills_units += abs(delta)
        same_sign = self.pos * target > 0
        adding = same_sign and abs(target) > abs(self.pos)
        flipping = (not same_sign) and target != 0.0 and self.pos != 0.0
        opening = self.pos == 0.0 and target != 0.0
        if adding:
            self.entry = (
                self.entry * abs(self.pos) + fill * (abs(target) - abs(self.pos))
            ) / abs(target)
        if flipping or opening:
            self.entry = fill
        if target == 0.0:
            self.entry = 0.0
        self.pos = target

    def balance(self) -> float:
        return self.initial_cash + self.cash_delta + self.pos * self.entry

    # -- one advancing bar (venue.execute_bar mirror) --------------------
    def execute_bar(self, o_t: int, o_price: float, seed_msgs, flow_msgs,
                    pending) -> None:
        """``seed_msgs``/``flow_msgs``: concrete (kind, side, price, qty,
        oid) sequences regenerated from the SAME jax flow process;
        ``pending``: (active, target, sl_price, tp_price) from the scan
        trace (forced liquidations are out of crosscheck scope)."""
        book = OracleBook(self.depth_levels, self.queue_slots)
        for m in zip(*seed_msgs):
            book.process(*(int(x) for x in m))

        p_active, p_target, p_sl, p_tp = pending
        raw_target = float(p_target) if p_active else self.pos
        delta = raw_target - self.pos
        lots = self._to_lots(delta)
        denied = p_active and delta != 0.0 and lots < 1
        exec_lots = lots if (p_active and not denied) else 0
        is_buy = delta > 0
        fill = book.market(is_buy, exec_lots)
        worst = (fill.price_max if is_buy else fill.price_min) \
            if fill.filled_qty > 0 else o_t
        value = fill.filled_value + (exec_lots - fill.filled_qty) * worst
        open_price = value / max(exec_lots, 1) * self.tick
        sign = 1.0 if delta > 0 else (-1.0 if delta < 0 else 0.0)
        ledger_target = self.pos if denied \
            else self.pos + sign * exec_lots * self.lot_units
        old_pos = self.pos
        self.denied += int(denied)
        self._apply_fill(open_price if exec_lots > 0 else o_price,
                         ledger_target)

        # bracket arming (broker.opening_units rule)
        same = old_pos * ledger_target > 0
        opening = max(abs(ledger_target) - abs(old_pos), 0.0) if same or \
            ledger_target == 0.0 or old_pos == 0.0 else abs(ledger_target)
        entered = p_active and self.pos != 0.0 and opening > 0.0
        if self.pos == 0.0:
            self.sl = self.tp = 0
        elif entered:
            self.sl = self._ticks(p_sl) if p_sl > 0 else 0
            self.tp = self._ticks(p_tp) if p_tp > 0 else 0

        # intrabar: TP rests, SL triggers on prints
        pos_lots = self._to_lots(self.pos)
        long = self.pos > 0
        exit_is_buy = not long
        has_sl = self.sl > 0 and pos_lots > 0
        has_tp = self.tp > 0 and pos_lots > 0

        gap_sl = has_sl and (o_t <= self.sl if long else o_t >= self.sl)
        sl_lots = sl_value = 0
        tp_lots = tp_value = 0
        rem = pos_lots
        if gap_sl:
            x = book.market(exit_is_buy, rem)
            worst = (x.price_max if exit_is_buy else x.price_min) \
                if x.filled_qty > 0 else o_t
            sl_value = x.filled_value + (rem - x.filled_qty) * worst
            sl_lots, rem = rem, 0
        elif has_tp:
            f0 = book.add(exit_is_buy, max(self.tp, 1), rem, AGENT_OID)
            tp_lots, tp_value = f0.filled_qty, f0.filled_value
            rem -= f0.filled_qty

        sl_fired = gap_sl
        for m in zip(*flow_msgs):
            f = book.process(*(int(x) for x in m))
            rem -= f.agent_qty
            tp_lots += f.agent_qty
            tp_value += f.agent_value
            printed = f.price_min <= self.sl if long else f.price_max >= self.sl
            if has_sl and not sl_fired and rem > 0 and printed:
                book.cancel(exit_is_buy, AGENT_OID)
                x = book.market(exit_is_buy, rem)
                worst = (x.price_max if exit_is_buy else x.price_min) \
                    if x.filled_qty > 0 else self.sl
                sl_value += x.filled_value + (rem - x.filled_qty) * worst
                sl_lots += rem
                rem = 0
                sl_fired = True

        exit_lots = tp_lots + sl_lots
        if exit_lots > 0:
            exit_value = tp_value + sl_value
            full = exit_lots >= pos_lots > 0
            sgn = 1.0 if self.pos > 0 else -1.0
            target2 = 0.0 if full else self.pos - sgn * exit_lots * self.lot_units
            self._apply_fill(exit_value / exit_lots * self.tick, target2)
        if self.pos == 0.0 or sl_fired:
            self.sl = self.tp = 0


def replay_messages(depth_levels: int, queue_slots: int,
                    msgs) -> Tuple[OracleBook, List[Tuple[int, ...]]]:
    """Replay a concrete (kind, side, price, qty, oid) stream (each a
    length-M sequence) and return the final book plus per-message fill
    tuples in ``FillRecord`` field order."""
    book = OracleBook(depth_levels, queue_slots)
    fills = []
    for k, s, p, q, o in zip(*msgs):
        fills.append(book.process(int(k), int(s), int(p), int(q), int(o)).astuple())
    return book, fills
