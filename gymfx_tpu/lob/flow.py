"""Seeded order-flow process: bars -> per-bar LOB message streams.

The LOB venue replays the SAME bar data the bar engine trades
(data/feed.py ``MarketData``), so the flow process is a deterministic
bridge: each bar's O/H/L/C (converted to integer ticks) pins a
piecewise reference path O -> H -> L -> C (or O -> L -> H -> C when the
bar closes above its open), and a ``jax.random``-seeded message stream
decorates that path with limit adds, cancels, and market orders whose
intensities come from :class:`FlowParams`.  Determinism contract:

  * the stream for bar ``t`` depends only on
    ``fold_in(PRNGKey(lob_flow_seed), t_global)`` and the bar's OHLC —
    never on episode state — so the crosscheck oracle replay
    (simulation/crosscheck.py) regenerates bit-identical streams, and
    streamed shards reproduce full-dataset flow (feed.py row0 rebase
    keeps ``t_global`` stable);
  * threefry is backend-stable, so CPU tests pin TPU behavior;
  * all prices are clipped to ``[1, PRICE_CAP - 1]`` ticks (price 0 is
    the book's empty-level sentinel) and quantities to
    ``[1, QTY_CAP]`` lots so int32 value accumulation cannot overflow.

Messages per bar is a STATIC count (``lob_messages_per_bar``): the
stream shape is fixed, and low-activity scenarios thin the flow by
turning messages into ``MSG_NOOP`` rather than shortening arrays.
"""
from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from .book import MSG_ADD, MSG_CANCEL, MSG_MARKET, MSG_NOOP, PRICE_CAP, Messages, SEED_OID_BASE

# per-order lot cap: 2**10 lots * PRICE_CAP ticks * queue depth stays
# far inside int32 for the engine's value accumulators
QTY_CAP = 1 << 10


class FlowParams(NamedTuple):
    """Numeric knobs of the order-flow process (a pytree leaf bundle —
    jit-traceable, so scenarios can be swept under vmap)."""

    p_add: Any = 0.55      # P(message is a limit add)
    p_cancel: Any = 0.15   # P(message is a cancel); rest are markets
    p_noop: Any = 0.0      # P(message is a no-op) — thins activity
    base_qty: Any = 8      # mean order size in lots
    qty_jitter: Any = 6    # uniform size jitter [0, qty_jitter]
    band_ticks: Any = 6    # adds rest within this band off the path
    market_qty: Any = 4    # mean market-order size in lots
    seed_qty: Any = 16     # lots per seeded level at bar open
    crash_at: Any = -1     # message index where a sell burst starts (<0: off)
    crash_len: Any = 0     # burst length in messages
    crash_qty: Any = 32    # lots per burst market sell


def price_to_ticks(price, tick):
    """Float price -> int32 tick grid (round-half-away keeps the map
    monotone in f32; exactness is not required here because ticks ARE
    the venue's price system from this point on)."""
    return jnp.clip(
        jnp.round(price / tick).astype(jnp.int32), 1, PRICE_CAP - 1
    )


def reference_path(o, h, l, c, n_msgs: int):
    """Deterministic intrabar tick path visiting O, H, L, C.

    Bull bars (c >= o) sweep O -> L -> H -> C, bear bars O -> H -> L -> C
    — the same worst-case-first ordering assumption the bar broker's
    bracket resolution documents (core/broker.py:check_brackets).
    """
    t = jnp.linspace(0.0, 3.0, n_msgs)
    bull = c >= o
    w0 = jnp.where(bull, l, h).astype(jnp.float32)
    w1 = jnp.where(bull, h, l).astype(jnp.float32)
    of = o.astype(jnp.float32)
    cf = c.astype(jnp.float32)
    seg0 = of + (w0 - of) * jnp.clip(t, 0.0, 1.0)
    seg1 = w0 + (w1 - w0) * jnp.clip(t - 1.0, 0.0, 1.0)
    seg2 = w1 + (cf - w1) * jnp.clip(t - 2.0, 0.0, 1.0)
    path = jnp.where(t <= 1.0, seg0, jnp.where(t <= 2.0, seg1, seg2))
    return jnp.clip(jnp.round(path).astype(jnp.int32), 1, PRICE_CAP - 1)


def seed_messages(o_tick, n_levels: int, fp: FlowParams) -> Messages:
    """Deterministic book seed at bar open: ``n_levels`` bid levels at
    ``o - 1 - i`` and ask levels at ``o + 1 + i`` ticks, ``seed_qty``
    lots each — the baseline depth agent orders walk."""
    i = jnp.arange(n_levels, dtype=jnp.int32)
    off = 1 + i
    kind = jnp.full((2 * n_levels,), MSG_ADD, jnp.int32)
    side = jnp.concatenate([jnp.ones_like(i), -jnp.ones_like(i)])
    price = jnp.concatenate([o_tick - off, o_tick + off])
    price = jnp.clip(price, 1, PRICE_CAP - 1)
    qty = jnp.full((2 * n_levels,), jnp.int32(fp.seed_qty))
    qty = jnp.clip(qty, 1, QTY_CAP)
    oid = SEED_OID_BASE + jnp.arange(2 * n_levels, dtype=jnp.int32)
    return Messages(kind, side, price, qty, oid)


def bar_messages(key, o_tick, h_tick, l_tick, c_tick, n_msgs: int,
                 fp: FlowParams) -> Messages:
    """One bar's seeded message stream (static length ``n_msgs``).

    Flow oids are ``1 + message_index`` — unique within the bar and
    disjoint from ``SEED_OID_BASE`` / ``AGENT_OID`` — and cancels target
    a uniformly drawn earlier oid (a dead oid cancels nothing, matching
    real-feed races).
    """
    k_kind, k_side, k_jit, k_qty, k_band, k_cxl = jax.random.split(key, 6)
    idx = jnp.arange(n_msgs, dtype=jnp.int32)

    path = reference_path(o_tick, h_tick, l_tick, c_tick, n_msgs)
    jitter = jax.random.randint(k_jit, (n_msgs,), -2, 3, dtype=jnp.int32)
    mid = jnp.clip(path + jitter, l_tick, h_tick)
    mid = jnp.clip(mid, 1, PRICE_CAP - 1)

    u = jax.random.uniform(k_kind, (n_msgs,))
    kind = jnp.where(
        u < fp.p_noop, MSG_NOOP,
        jnp.where(
            u < fp.p_noop + fp.p_add, MSG_ADD,
            jnp.where(u < fp.p_noop + fp.p_add + fp.p_cancel,
                      MSG_CANCEL, MSG_MARKET),
        ),
    ).astype(jnp.int32)
    side = jnp.where(
        jax.random.uniform(k_side, (n_msgs,)) < 0.5, 1, -1
    ).astype(jnp.int32)

    band = 1 + jax.random.randint(
        k_band, (n_msgs,), 0, jnp.maximum(fp.band_ticks, 1), dtype=jnp.int32
    )
    add_price = jnp.clip(mid - side * band, 1, PRICE_CAP - 1)

    qty = jnp.int32(fp.base_qty) + jax.random.randint(
        k_qty, (n_msgs,), 0, jnp.maximum(fp.qty_jitter, 1), dtype=jnp.int32
    )
    mkt_qty = jnp.int32(fp.market_qty) + jax.random.randint(
        k_qty, (n_msgs,), 0, jnp.maximum(fp.qty_jitter, 1), dtype=jnp.int32
    )
    qty = jnp.where(kind == MSG_MARKET, mkt_qty, qty)

    oid = 1 + idx
    cxl_target = 1 + jnp.floor(
        jax.random.uniform(k_cxl, (n_msgs,)) * jnp.maximum(idx, 1)
    ).astype(jnp.int32)
    oid = jnp.where(kind == MSG_CANCEL, jnp.minimum(cxl_target, idx), oid)

    # flash-crash burst: a contiguous window of forced market sells
    in_crash = (fp.crash_at >= 0) & (idx >= fp.crash_at) \
        & (idx < fp.crash_at + fp.crash_len)
    kind = jnp.where(in_crash, MSG_MARKET, kind)
    side = jnp.where(in_crash, -1, side)
    qty = jnp.where(in_crash, jnp.int32(fp.crash_qty), qty)

    qty = jnp.clip(qty, 1, QTY_CAP)
    price = jnp.where(kind == MSG_ADD, add_price, mid)
    return Messages(kind, side, price, qty, oid)


def bar_key(flow_seed, t_global):
    """The per-bar stream key — the ONLY randomness the venue uses, so
    oracle replay and streamed shards regenerate identical flow."""
    return jax.random.fold_in(
        jax.random.PRNGKey(jnp.uint32(flow_seed)), jnp.uint32(t_global)
    )


def random_message_streams(key, n_streams: int, n_msgs: int,
                           fp: FlowParams, o_tick: int = 100):
    """Batch of seeded streams around a flat reference price — shared
    by the 4096-stream parity test and the fills/sec bench so both
    exercise the same message mix."""
    keys = jax.random.split(key, n_streams)
    ot = jnp.int32(o_tick)
    span = jnp.int32(max(4, n_msgs // 8))
    make = lambda k: bar_messages(k, ot, ot + span, ot - span, ot, n_msgs, fp)
    return jax.vmap(make)(keys)
