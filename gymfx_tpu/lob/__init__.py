"""Vectorized limit-order-book venue (pure JAX) + Python oracle twin.

  book.py       branch-free matching engine (jit/vmap/scan-composable)
  oracle.py     exact pure-Python reference book (parity contract)
  flow.py       seeded bar -> message-stream order-flow process
  scenarios.py  named flow presets (the lob_* training scenario family)
  venue.py      per-bar agent execution wired into core/env.py
"""
from .book import (  # noqa: F401
    AGENT_OID,
    PRICE_CAP,
    BookState,
    FillRecord,
    Messages,
    empty_book,
    process_message,
    process_stream,
)
from .flow import FlowParams, bar_key, bar_messages, seed_messages  # noqa: F401
from .scenarios import scenario_flow_params, scenario_names  # noqa: F401
