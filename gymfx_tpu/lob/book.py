"""Branch-free limit-order-book matching engine (JAX-LOB style).

A fixed-capacity book as pure array state: ``depth_levels`` price
levels per side, each holding a ``queue_slots``-deep FIFO of resting
orders, composable under ``jit``/``vmap``/``lax.scan`` exactly like the
bar broker kernel (core/broker.py) — no Python branching on data, so
thousands of books match in one vmapped program (PAPERS.md: JAX-LOB
arXiv:2308.13289, JaxMARL-HFT arXiv:2511.02136).

All quantities are integer lots and all prices are integer ticks
(int32): matching is EXACT, and the pure-Python oracle
(``lob/oracle.py``) reproduces every fill bit-for-bit — the parity
contract behind the LOB crosscheck (simulation/crosscheck.py).

Semantics (price-time priority):
  * a level is *active* while it holds quantity; its price lives in the
    per-level ``*_price`` array (0 = unused).  A resting order at a new
    price claims the LOWEST-index free level; when no level is free the
    order is dropped (``rested_qty`` 0) — fixed capacity is venue
    behavior, not an error;
  * within a level, orders queue FIFO in slot order; a full queue drops
    the incoming order; matched-out slots compact toward the front so
    slot 0 is always the queue head;
  * market orders walk eligible levels best-price-first and fill
    partially when liquidity runs out; limit adds match their
    marketable part first (price improvement at maker prices — the
    book-native form of the bar engine's ``cross`` gap fills) and rest
    the remainder;
  * cancels remove every live slot owned by ``oid`` on the given side
    (flow oids are unique per message, so this is one order).

Prices must stay below ``PRICE_CAP`` (2**20 ticks ≈ 10.5 for a 1e-5
tick) so the flattened price-time sort key stays exact in int32.
"""
from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp

# tick-price ceiling: the price-time sort key is price * queue_slots +
# slot, kept exact in int32 (2**20 * 64 slots << 2**31)
PRICE_CAP = 1 << 20
# reserved owner id for the trading agent's resting orders (flow
# messages use 1..M, seed messages SEED_OID_BASE+; 0 = empty slot)
AGENT_OID = 1 << 29
SEED_OID_BASE = 1 << 24

# message kinds
MSG_NOOP = 0
MSG_ADD = 1     # limit order: match marketable part, rest the remainder
MSG_CANCEL = 2  # cancel by (side, oid)
MSG_MARKET = 3  # market order: walk the book, partial-fill on dry-up


class BookState(NamedTuple):
    """Fixed-capacity two-sided book (all int32, all static shapes)."""

    bid_price: Any  # (D,)  tick price per level, 0 = unused
    bid_qty: Any    # (D, Q) FIFO slot quantities in lots, 0 = empty
    bid_oid: Any    # (D, Q) owner ids, 0 = empty
    ask_price: Any  # (D,)
    ask_qty: Any    # (D, Q)
    ask_oid: Any    # (D, Q)

    @property
    def depth_levels(self) -> int:
        return int(self.bid_qty.shape[0])

    @property
    def queue_slots(self) -> int:
        return int(self.bid_qty.shape[1])


class Messages(NamedTuple):
    """A stream of M book messages (arrays of shape (M,), int32)."""

    kind: Any   # MSG_*
    side: Any   # +1 buy / -1 sell
    price: Any  # ticks (ADD: limit price; MARKET: ignored)
    qty: Any    # lots
    oid: Any    # order id (ADD: the resting id; CANCEL: the target)


class FillRecord(NamedTuple):
    """Execution report for one processed message (int32 scalars)."""

    filled_qty: Any    # lots matched by this message (taker side)
    filled_value: Any  # sum(maker price * lots) in tick-lots
    fill_events: Any   # number of maker slots touched
    agent_qty: Any     # lots filled against AGENT_OID resting orders
    agent_value: Any   # sum(price * lots) of those agent maker fills
    price_min: Any     # lowest traded price (PRICE_CAP when no fill)
    price_max: Any     # highest traded price (0 when no fill)
    rested_qty: Any    # lots rested by an ADD (0 when dropped/matched)
    cancelled_qty: Any # lots removed by a CANCEL


def _zero_fill() -> FillRecord:
    z = jnp.int32(0)
    return FillRecord(z, z, z, z, z, jnp.int32(PRICE_CAP), z, z, z)


def empty_book(depth_levels: int, queue_slots: int) -> BookState:
    lvl = jnp.zeros((depth_levels,), jnp.int32)
    slots = jnp.zeros((depth_levels, queue_slots), jnp.int32)
    return BookState(lvl, slots, slots, lvl, slots, slots)


def best_bid(book: BookState):
    """Highest active bid price (0 when the side is empty)."""
    active = book.bid_qty.sum(axis=1) > 0
    return jnp.max(jnp.where(active, book.bid_price, 0))


def best_ask(book: BookState):
    """Lowest active ask price (PRICE_CAP when the side is empty)."""
    active = book.ask_qty.sum(axis=1) > 0
    return jnp.min(jnp.where(active, book.ask_price, PRICE_CAP))


def side_depth(book: BookState, is_bid: bool):
    """Total resting lots on one side."""
    return (book.bid_qty if is_bid else book.ask_qty).sum()


# ---------------------------------------------------------------------------
# half-book primitives (price, qty, oid) — shared by both sides
# ---------------------------------------------------------------------------
def _compact(qty, oid):
    """Shift live slots to the queue front, preserving FIFO order."""
    order = jnp.argsort(qty == 0, axis=1, stable=True)
    return (
        jnp.take_along_axis(qty, order, axis=1),
        jnp.take_along_axis(oid, order, axis=1),
    )


def _reset_empty_levels(price, qty):
    return jnp.where(qty.sum(axis=1) > 0, price, 0)


def _match_half(price, qty, oid, take_qty, limit, against_asks: bool):
    """Match ``take_qty`` lots against one half book in price-time
    priority; returns the updated half plus the taker's fill stats.

    ``against_asks``: the taker BUYS, eligible levels have
    price <= limit, walked ascending.  Otherwise the taker SELLS,
    eligible levels have price >= limit, walked descending.
    """
    D, Q = qty.shape
    active = price > 0
    if against_asks:
        eligible = active & (price <= limit)
        level_key = jnp.where(eligible, price, PRICE_CAP)
    else:
        eligible = active & (price >= limit)
        level_key = jnp.where(eligible, PRICE_CAP - price, PRICE_CAP)
    # price-time priority: unique flattened key = level price rank then
    # FIFO slot index (levels never share a price, so keys are unique)
    flat_key = (level_key[:, None] * Q + jnp.arange(Q, dtype=jnp.int32)).reshape(-1)
    order = jnp.argsort(flat_key)
    avail = jnp.where(eligible[:, None], qty, 0).reshape(-1)[order]
    cum = jnp.cumsum(avail)
    fill_sorted = jnp.clip(take_qty - (cum - avail), 0, avail)
    fill = jnp.zeros((D * Q,), jnp.int32).at[order].set(fill_sorted)
    fill = fill.reshape(D, Q)

    # sums pinned to int32: under jax_enable_x64 integer reductions
    # promote to int64, which would split lax.switch branch signatures
    filled = fill.sum(dtype=jnp.int32)
    value = (fill * price[:, None]).sum(dtype=jnp.int32)
    events = (fill > 0).sum(dtype=jnp.int32)
    agent = (oid == AGENT_OID) & (fill > 0)
    agent_qty = jnp.where(agent, fill, 0).sum(dtype=jnp.int32)
    agent_value = (jnp.where(agent, fill, 0) * price[:, None]).sum(dtype=jnp.int32)
    touched = fill.sum(axis=1) > 0
    pmin = jnp.min(jnp.where(touched, price, PRICE_CAP))
    pmax = jnp.max(jnp.where(touched, price, 0))

    new_qty = qty - fill
    new_oid = jnp.where(new_qty > 0, oid, 0)
    new_qty, new_oid = _compact(new_qty, new_oid)
    new_price = _reset_empty_levels(price, new_qty)
    stats = (filled, value, events, agent_qty, agent_value, pmin, pmax)
    return (new_price, new_qty, new_oid), stats


def _rest_half(price, qty, oid, p, q, o):
    """Rest ``q`` lots owned by ``o`` at price ``p`` on one half book.
    Returns the updated half and the lots actually rested (0 when the
    book/level is full — fixed capacity drops the order)."""
    has_level = (price == p) & (price > 0)
    level_free = qty.sum(axis=1) == 0
    li = jnp.where(
        has_level.any(), jnp.argmax(has_level), jnp.argmax(level_free)
    )
    can = (q > 0) & (has_level.any() | level_free.any())
    slot_free = qty[li] == 0
    si = jnp.argmax(slot_free)
    can = can & slot_free.any()
    rested = jnp.where(can, q, 0)
    qty = qty.at[li, si].set(jnp.where(can, q, qty[li, si]))
    oid = oid.at[li, si].set(jnp.where(can, o, oid[li, si]))
    price = price.at[li].set(jnp.where(can, p, price[li]))
    return (price, qty, oid), rested


def _cancel_half(price, qty, oid, target_oid):
    """Remove every live slot owned by ``target_oid``."""
    hit = (oid == target_oid) & (qty > 0) & (target_oid != 0)
    removed = jnp.where(hit, qty, 0).sum(dtype=jnp.int32)
    qty = jnp.where(hit, 0, qty)
    oid = jnp.where(hit, 0, oid)
    qty, oid = _compact(qty, oid)
    price = _reset_empty_levels(price, qty)
    return (price, qty, oid), removed


# ---------------------------------------------------------------------------
# book-level operations
# ---------------------------------------------------------------------------
def _bids(book: BookState):
    return book.bid_price, book.bid_qty, book.bid_oid


def _asks(book: BookState):
    return book.ask_price, book.ask_qty, book.ask_oid


def _with_bids(book: BookState, half) -> BookState:
    return book._replace(bid_price=half[0], bid_qty=half[1], bid_oid=half[2])


def _with_asks(book: BookState, half) -> BookState:
    return book._replace(ask_price=half[0], ask_qty=half[1], ask_oid=half[2])


def match_market(book: BookState, is_buy, qty) -> Tuple[BookState, FillRecord]:
    """Execute a market order of ``qty`` lots; partial when the
    opposing side runs dry.  ``is_buy`` may be traced (bool)."""

    def buy(b):
        half, s = _match_half(*_asks(b), qty, PRICE_CAP, True)
        return _with_asks(b, half), s

    def sell(b):
        half, s = _match_half(*_bids(b), qty, 0, False)
        return _with_bids(b, half), s

    new_book, s = jax.lax.cond(is_buy, buy, sell, book)
    z = jnp.int32(0)
    return new_book, FillRecord(s[0], s[1], s[2], s[3], s[4], s[5], s[6], z, z)


def add_limit(book: BookState, is_buy, price, qty, oid) -> Tuple[BookState, FillRecord]:
    """Limit order: match the marketable part at maker prices, rest the
    remainder at ``price`` (dropped when the book is full)."""

    def buy(b):
        half, s = _match_half(*_asks(b), qty, price, True)
        b = _with_asks(b, half)
        rest_half, rested = _rest_half(*_bids(b), price, qty - s[0], oid)
        return _with_bids(b, rest_half), s, rested

    def sell(b):
        half, s = _match_half(*_bids(b), qty, price, False)
        b = _with_bids(b, half)
        rest_half, rested = _rest_half(*_asks(b), price, qty - s[0], oid)
        return _with_asks(b, rest_half), s, rested

    new_book, s, rested = jax.lax.cond(is_buy, buy, sell, book)
    return new_book, FillRecord(
        s[0], s[1], s[2], s[3], s[4], s[5], s[6], rested, jnp.int32(0)
    )


def cancel(book: BookState, is_buy, oid) -> Tuple[BookState, FillRecord]:
    def buy(b):
        half, removed = _cancel_half(*_bids(b), oid)
        return _with_bids(b, half), removed

    def sell(b):
        half, removed = _cancel_half(*_asks(b), oid)
        return _with_asks(b, half), removed

    new_book, removed = jax.lax.cond(is_buy, buy, sell, book)
    return new_book, _zero_fill()._replace(cancelled_qty=removed)


def process_message(book: BookState, msg) -> Tuple[BookState, FillRecord]:
    """Dispatch one message (kind, side, price, qty, oid)."""
    kind, side, price, qty, oid = msg
    is_buy = side > 0

    def do_noop(b):
        return b, _zero_fill()

    def do_add(b):
        return add_limit(b, is_buy, price, qty, oid)

    def do_cancel(b):
        return cancel(b, is_buy, oid)

    def do_market(b):
        return match_market(b, is_buy, qty)

    return jax.lax.switch(
        jnp.clip(kind, 0, 3), (do_noop, do_add, do_cancel, do_market), book
    )


def process_stream(book: BookState, msgs: Messages) -> Tuple[BookState, FillRecord]:
    """Scan a message stream through the book; returns the final book
    and the stacked per-message fill records — the shape the parity
    test and the fills/sec bench both consume."""

    def step(b, m):
        b, fill = process_message(b, m)
        return b, fill

    return jax.lax.scan(step, book, tuple(msgs))
