"""LOB training scenario family: named FlowParams presets.

A scenario is a named microstructure regime — the LOB-venue analogue of
the bar engine's event overlays (simulation/events.py).  Selecting one
(`lob_scenario` config key / ``--lob_scenario`` CLI flag) changes ONLY
the order-flow process; the replayed bar data, the matching engine, and
the agent's action space are unchanged, so PPO/IMPALA runs across
scenarios are directly comparable.  All presets keep the flow's
determinism contract (flow.py): same seed + same bars => same streams.

Presets:
  * ``lob_calm``        — balanced flow, deep book, mild sizes (default)
  * ``lob_trend``       — add-heavy, tight bands: persistent one-sided
                          pressure along the bar path
  * ``lob_volatile``    — market-order-heavy, larger sizes, wide bands
  * ``lob_thin``        — sparse flow (high noop rate), shallow seeded
                          depth: agent orders walk multiple levels
  * ``lob_flash_crash`` — calm flow with a mid-bar burst of forced
                          market sells (crash window), stressing
                          stop-loss prints and partial exits
"""
from __future__ import annotations

from typing import Dict, Tuple

import jax.numpy as jnp

from .flow import FlowParams

_SCENARIOS: Dict[str, FlowParams] = {
    "lob_calm": FlowParams(),
    "lob_trend": FlowParams(
        p_add=0.70, p_cancel=0.10, band_ticks=3, base_qty=10,
    ),
    "lob_volatile": FlowParams(
        p_add=0.35, p_cancel=0.15, band_ticks=10,
        base_qty=10, qty_jitter=10, market_qty=8,
    ),
    "lob_thin": FlowParams(
        p_add=0.30, p_cancel=0.10, p_noop=0.35,
        base_qty=3, qty_jitter=3, market_qty=2, seed_qty=4,
    ),
    "lob_flash_crash": FlowParams(
        crash_at=24, crash_len=8, crash_qty=48,
    ),
}


def scenario_names() -> Tuple[str, ...]:
    return tuple(sorted(_SCENARIOS))


def scenario_flow_params(name: str) -> FlowParams:
    """Resolve a scenario name (honor-or-reject: unknown names raise at
    config-binding time, never mid-training)."""
    try:
        return _SCENARIOS[name]
    except KeyError:
        raise ValueError(
            f"unknown lob_scenario {name!r}; known: {scenario_names()}"
        ) from None


def flow_params_from_regime(base: FlowParams, scen_flags,
                            n_msgs: int) -> FlowParams:
    """Per-bar FlowParams from the generated tape's scenario bitmask
    (``feed=scengen`` + ``venue=lob``): drought bars take the
    ``lob_thin`` intensity/depth mix so the book thins WITH the tape's
    spread blowout, and crash bars arm the ``lob_flash_crash`` forced
    sell burst so the flow prints the drop the bars show.  Everything is
    ``jnp.where``-blended — FlowParams fields are traced pytree leaves,
    so this stays inside the one compiled bar program.

    Flag bits come from scengen/params.py; the import is local so the
    LOB package stays importable without the scengen subsystem loaded.
    """
    from gymfx_tpu.scengen.params import FLAG_CRASH, FLAG_DROUGHT

    flags = jnp.asarray(scen_flags, jnp.int32)
    thin = _SCENARIOS["lob_thin"]
    crash = _SCENARIOS["lob_flash_crash"]
    in_drought = (flags & FLAG_DROUGHT) != 0
    in_crash = (flags & FLAG_CRASH) != 0

    def mix(b, t):
        return jnp.where(in_drought, t, jnp.asarray(b))

    burst_at = jnp.int32(max(0, int(n_msgs) // 3))
    burst_len = jnp.int32(max(1, int(n_msgs) // 8))
    return FlowParams(
        p_add=mix(base.p_add, thin.p_add),
        p_cancel=mix(base.p_cancel, thin.p_cancel),
        p_noop=mix(base.p_noop, thin.p_noop),
        base_qty=mix(base.base_qty, thin.base_qty),
        qty_jitter=mix(base.qty_jitter, thin.qty_jitter),
        band_ticks=mix(base.band_ticks, thin.band_ticks),
        market_qty=mix(base.market_qty, thin.market_qty),
        seed_qty=mix(base.seed_qty, thin.seed_qty),
        crash_at=jnp.where(in_crash, burst_at, jnp.asarray(base.crash_at)),
        crash_len=jnp.where(in_crash, burst_len, jnp.asarray(base.crash_len)),
        crash_qty=jnp.where(in_crash, jnp.asarray(crash.crash_qty),
                            jnp.asarray(base.crash_qty)),
    )
