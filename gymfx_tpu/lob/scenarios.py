"""LOB training scenario family: named FlowParams presets.

A scenario is a named microstructure regime — the LOB-venue analogue of
the bar engine's event overlays (simulation/events.py).  Selecting one
(`lob_scenario` config key / ``--lob_scenario`` CLI flag) changes ONLY
the order-flow process; the replayed bar data, the matching engine, and
the agent's action space are unchanged, so PPO/IMPALA runs across
scenarios are directly comparable.  All presets keep the flow's
determinism contract (flow.py): same seed + same bars => same streams.

Presets:
  * ``lob_calm``        — balanced flow, deep book, mild sizes (default)
  * ``lob_trend``       — add-heavy, tight bands: persistent one-sided
                          pressure along the bar path
  * ``lob_volatile``    — market-order-heavy, larger sizes, wide bands
  * ``lob_thin``        — sparse flow (high noop rate), shallow seeded
                          depth: agent orders walk multiple levels
  * ``lob_flash_crash`` — calm flow with a mid-bar burst of forced
                          market sells (crash window), stressing
                          stop-loss prints and partial exits
"""
from __future__ import annotations

from typing import Dict, Tuple

from .flow import FlowParams

_SCENARIOS: Dict[str, FlowParams] = {
    "lob_calm": FlowParams(),
    "lob_trend": FlowParams(
        p_add=0.70, p_cancel=0.10, band_ticks=3, base_qty=10,
    ),
    "lob_volatile": FlowParams(
        p_add=0.35, p_cancel=0.15, band_ticks=10,
        base_qty=10, qty_jitter=10, market_qty=8,
    ),
    "lob_thin": FlowParams(
        p_add=0.30, p_cancel=0.10, p_noop=0.35,
        base_qty=3, qty_jitter=3, market_qty=2, seed_qty=4,
    ),
    "lob_flash_crash": FlowParams(
        crash_at=24, crash_len=8, crash_qty=48,
    ),
}


def scenario_names() -> Tuple[str, ...]:
    return tuple(sorted(_SCENARIOS))


def scenario_flow_params(name: str) -> FlowParams:
    """Resolve a scenario name (honor-or-reject: unknown names raise at
    config-binding time, never mid-training)."""
    try:
        return _SCENARIOS[name]
    except KeyError:
        raise ValueError(
            f"unknown lob_scenario {name!r}; known: {scenario_names()}"
        ) from None
