"""LOB execution venue: one bar's agent execution through the book.

``execute_bar`` replaces the bar engine's advance steps 1 and 2
(broker.fill_pending + broker.check_brackets, core/env.py) when
``cfg.venue == "lob"``.  Semantics per advancing bar:

  1. a fresh book is seeded at the bar open (``lob_seed_levels`` levels
     per side, flow.seed_messages) — per-bar books keep the state
     static-shape and scan-free across bars while the seeded depth
     models persistent liquidity;
  2. the pending order executes as a market walk: ``lots =
     round(|delta| / lot_units)`` lots consume the book best-price
     first; the unfilled remainder is priced at the worst touched level
     (the depth-derived slippage the bar engine cannot express), or at
     the bar open when the book gave nothing.  Sub-lot orders are
     DENIED (the venue's min-quantity rule, same diagnostics counter as
     the bar engine's size rules); a venue-forced liquidation
     (margin closeout) always trades at least one lot and moves the
     ledger to its exact target — a venue never strands a liquidation;
  3. the take-profit rests IN the book as an agent limit order
     (owner ``AGENT_OID``): it earns queue position behind the seeded
     depth at its level, fills only when flow takers reach it, and a
     bar that gaps open through it fills the marketable part
     immediately at maker prices (the bar engine's ``cross`` gap
     semantics, now emergent from matching);
  4. the stop-loss is a stop: tracked off-book and triggered by PRINTS
     — the first flow fill at or through the stop fires a market exit
     of the remaining lots (and cancels the resting TP); the unfilled
     remainder is priced at the stop level;
  5. all agent executions of the bar aggregate into at most two ledger
     fills (entry at open, exit at the lots-weighted vwap) through
     ``broker.apply_fill`` — exact, because realized PnL and commission
     are linear in fill price at fixed quantities.

The pure-Python twin of this function is ``oracle.OracleVenue``;
``simulation/crosscheck.crosscheck_lob_episode`` reconciles the two.

Honor-or-reject (``validate_lob_venue``, bound at Environment
construction): config knobs whose semantics the LOB venue replaces —
fractional slippage, venue quantization, execution cost profiles,
explicit limit-fill/collision policies — and kernels it cannot honor
yet (the calendar force-close session filter) fail loudly instead of
being silently degraded.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from gymfx_tpu.core import broker
from gymfx_tpu.core.types import EXEC_DIAG_INDEX, EnvConfig, EnvParams, EnvState

from .book import (
    AGENT_OID,
    BookState,
    FillRecord,
    add_limit,
    cancel,
    empty_book,
    match_market,
    process_message,
    process_stream,
)
from .flow import bar_key, bar_messages, price_to_ticks, seed_messages
from .scenarios import scenario_flow_params


def lot_size(cfg: EnvConfig, params: EnvParams):
    """Units per lot: the static config override, else position_size —
    so default strategies (target = ±position_size) trade one lot."""
    if cfg.lob_lot_units > 0:
        return jnp.asarray(cfg.lob_lot_units, params.position_size.dtype)
    return params.position_size


def to_lots(units, lot_units):
    """|units| -> integer lots (round-half-even, matching the oracle's
    Python round)."""
    return jnp.round(jnp.abs(units) / lot_units).astype(jnp.int32)


def bracket_ticks(price, tick):
    """Bracket price -> tick grid (0 stays 0 = disarmed)."""
    return jnp.round(price / tick).astype(jnp.int32)


def _vwap_price(value, lots, tick, dtype):
    """Integer (tick*lots) fill value -> per-unit float price."""
    lots_f = jnp.maximum(lots, 1).astype(dtype)
    return value.astype(dtype) / lots_f * jnp.asarray(tick, dtype)


def _walk_with_backstop(book: BookState, is_buy, lots, backstop_ticks):
    """Market-walk ``lots`` against the book; the unfilled remainder is
    priced at the worst touched level (else ``backstop_ticks``).
    Returns (book, total_value_ticklots, worst_touched)."""
    book, fill = match_market(book, is_buy, lots)
    worst = jnp.where(
        fill.filled_qty > 0,
        jnp.where(is_buy, fill.price_max, fill.price_min),
        backstop_ticks,
    )
    value = fill.filled_value + (lots - fill.filled_qty) * worst
    return book, value, worst


def execute_bar(
    state: EnvState, o, h, l, c, t_global, cfg: EnvConfig, params: EnvParams,
    scen_flags=None,
) -> EnvState:
    """One advancing bar through the LOB venue (replaces fill_pending +
    check_brackets; the caller gates with its ``advance`` select).

    ``scen_flags`` (feed=scengen only): the bar's scenario bitmask —
    the static FlowParams preset is blended per bar so the flow thins
    in droughts and bursts through crash windows
    (scenarios.flow_params_from_regime).
    """
    d = state.pos.dtype
    tick = cfg.lob_tick_size
    fp = scenario_flow_params(cfg.lob_scenario)
    if scen_flags is not None:
        from .scenarios import flow_params_from_regime

        fp = flow_params_from_regime(
            fp, scen_flags, cfg.lob_messages_per_bar
        )

    o_t = price_to_ticks(o, tick)
    c_t = price_to_ticks(c, tick)
    h_t = jnp.maximum(price_to_ticks(h, tick), jnp.maximum(o_t, c_t))
    l_t = jnp.minimum(price_to_ticks(l, tick), jnp.minimum(o_t, c_t))

    # fresh per-bar book, seeded with deterministic baseline depth;
    # lob_match_kernel routes the seed stream through the sort-free
    # pallas matcher (ops/lob_match.py) — exact int32 parity with the
    # argsort engine, so "on" falling back off-TPU is bitwise safe
    book = empty_book(cfg.lob_depth_levels, cfg.lob_queue_slots)
    seed = seed_messages(o_t, cfg.lob_seed_levels, fp)
    kernel_match = cfg.lob_match_kernel != "off" and (
        cfg.lob_match_kernel == "interpret"
        or jax.default_backend() == "tpu"
    )
    if kernel_match:
        from gymfx_tpu.ops import lob_match

        book, _ = lob_match.fused_process_stream(
            book, seed, interpret=cfg.lob_match_kernel == "interpret"
        )
    else:
        book, _ = process_stream(book, seed)

    lot_units = lot_size(cfg, params)

    # ---- 1. pending order: market walk at the bar open -------------------
    raw_target = jnp.where(state.pending_active, state.pending_target, state.pos)
    delta = raw_target - state.pos
    lots_raw = to_lots(delta, lot_units)
    forced = state.pending_active & state.pending_forced
    # a forced liquidation always trades (>= 1 lot for pricing) and the
    # ledger lands exactly on its target — same bypass as fill_pending
    lots = jnp.where(forced & (delta != 0), jnp.maximum(lots_raw, 1), lots_raw)
    denied = state.pending_active & ~forced & (delta != 0) & (lots < 1)
    exec_lots = jnp.where(state.pending_active & ~denied, lots, 0)
    is_buy = delta > 0
    book, open_value, _ = _walk_with_backstop(book, is_buy, exec_lots, o_t)
    open_price = _vwap_price(open_value, exec_lots, tick, d)

    signed_lots = jnp.sign(delta) * exec_lots.astype(d) * lot_units
    ledger_target = jnp.where(denied, state.pos, state.pos + signed_lots)
    ledger_target = jnp.where(forced, raw_target, ledger_target)

    state = state._replace(
        exec_diag=state.exec_diag.at[
            EXEC_DIAG_INDEX["order_denied_min_quantity"]
        ].add(denied.astype(jnp.int32))
    )
    st = broker.apply_fill(
        state, jnp.where(exec_lots > 0, open_price, o), ledger_target, params
    )

    # brackets arm when the fill OPENED units (entry/flip), quantized to
    # the venue tick grid (stored as ticks * tick so the oracle recovers
    # the integer exactly); a reduce keeps the live brackets
    entered = (
        state.pending_active
        & (st.pos != 0)
        & (broker.opening_units(state.pos, ledger_target) > 0)
    )
    t = jnp.asarray(tick, d)
    sl_armed = bracket_ticks(state.pending_sl, tick).astype(d) * t
    tp_armed = bracket_ticks(state.pending_tp, tick).astype(d) * t
    flat = st.pos == 0
    st = st._replace(
        pending_active=jnp.zeros_like(state.pending_active),
        pending_target=jnp.zeros_like(state.pending_target),
        pending_sl=jnp.zeros_like(state.pending_sl),
        pending_tp=jnp.zeros_like(state.pending_tp),
        pending_forced=jnp.zeros_like(state.pending_forced),
        bracket_sl=jnp.where(flat, 0.0, jnp.where(entered, sl_armed, st.bracket_sl)),
        bracket_tp=jnp.where(flat, 0.0, jnp.where(entered, tp_armed, st.bracket_tp)),
    )

    # ---- 2. intrabar: TP rests in the book, SL triggers on prints --------
    pos_lots = to_lots(st.pos, lot_units)
    long = st.pos > 0
    exit_is_buy = ~long  # exiting a short buys
    sl = bracket_ticks(st.bracket_sl, tick)
    tp = bracket_ticks(st.bracket_tp, tick)
    has_sl = (sl > 0) & (pos_lots > 0)
    has_tp = (tp > 0) & (pos_lots > 0)

    # a bar that gaps open through the stop exits at the open walk
    gap_sl = has_sl & jnp.where(long, o_t <= sl, o_t >= sl)
    gap_lots = jnp.where(gap_sl, pos_lots, 0)
    book, gap_value, _ = _walk_with_backstop(book, exit_is_buy, gap_lots, o_t)

    # rest the TP (skipped when the gap stop already flattened the bar);
    # its marketable part fills immediately at maker prices (gap cross)
    tp_rest = jnp.where(has_tp & ~gap_sl, pos_lots, 0)
    book, tp_fill0 = add_limit(
        book, exit_is_buy, jnp.maximum(tp, 1), tp_rest, AGENT_OID
    )

    rem0 = pos_lots - gap_lots - tp_fill0.filled_qty
    carry0 = (
        book,
        rem0,
        gap_sl,                                   # sl_fired
        tp_fill0.filled_qty, tp_fill0.filled_value,
        gap_lots, gap_value,
    )

    def flow_step(carry, msg):
        bk, rem, fired, tp_lots, tp_value, sl_lots, sl_value = carry
        bk, fill = process_message(bk, msg)
        # flow takers reaching our resting TP (maker fills)
        rem = rem - fill.agent_qty
        tp_lots = tp_lots + fill.agent_qty
        tp_value = tp_value + fill.agent_value
        # stop trigger: the first print at/through the stop level
        printed = jnp.where(
            long, fill.price_min <= sl, fill.price_max >= sl
        )
        trig = has_sl & ~fired & (rem > 0) & printed

        def fire(args):
            bk, rem = args
            bk, _ = cancel(bk, exit_is_buy, AGENT_OID)  # pull the TP
            return _walk_with_backstop(bk, exit_is_buy, rem, sl)

        bk, xvalue, _ = jax.lax.cond(
            trig, fire, lambda a: (a[0], jnp.int32(0), jnp.int32(0)),
            (bk, rem),
        )
        sl_lots = sl_lots + jnp.where(trig, rem, 0)
        sl_value = sl_value + jnp.where(trig, xvalue, 0)
        rem = jnp.where(trig, 0, rem)
        return (bk, rem, fired | trig, tp_lots, tp_value, sl_lots, sl_value), None

    flow = bar_messages(
        bar_key(cfg.lob_flow_seed, t_global),
        o_t, h_t, l_t, c_t, cfg.lob_messages_per_bar, fp,
    )
    carry, _ = jax.lax.scan(flow_step, carry0, tuple(flow))
    _, rem, sl_fired, tp_lots, tp_value, sl_lots, sl_value = carry

    # ---- 3. aggregate exit fill (lots-weighted vwap; exact: realized
    #         PnL and commission are linear in price at fixed lots) -------
    exit_lots = tp_lots + sl_lots
    exit_value = tp_value + sl_value
    full_exit = (exit_lots >= pos_lots) & (pos_lots > 0)
    exit_target = jnp.where(
        full_exit,
        jnp.zeros_like(st.pos),
        st.pos - jnp.sign(st.pos) * exit_lots.astype(d) * lot_units,
    )
    exit_price = _vwap_price(exit_value, exit_lots, tick, d)
    st = broker.apply_fill(
        st,
        jnp.where(exit_lots > 0, exit_price, o),
        jnp.where(exit_lots > 0, exit_target, st.pos),
        params,
    )
    # brackets survive a partial TP (re-rested with the remaining lots
    # next bar); a full exit or fired stop clears them
    now_flat = st.pos == 0
    return st._replace(
        bracket_sl=jnp.where(now_flat | sl_fired, 0.0, st.bracket_sl),
        bracket_tp=jnp.where(now_flat | sl_fired, 0.0, st.bracket_tp),
    )


def validate_lob_venue(cfg: EnvConfig, config: Dict[str, Any]) -> None:
    """Honor-or-reject at Environment binding time (the
    validate_profile_latency pattern, core/runtime.py): every config
    knob is either honored by the LOB venue or rejected loudly."""
    if cfg.venue != "lob":
        return
    problems = []
    if cfg.session_filter:
        problems.append(
            "session_filter=True: the calendar force-close strategy "
            "semantics are not implemented on the LOB venue yet"
        )
    if config.get("venue_quantization"):
        problems.append(
            "venue_quantization=True: the LOB venue quotes on its own "
            "lob_tick_size grid; the bar engine's tick/size-step "
            "quantization cannot be honored on top of it"
        )
    slippage = float(
        config.get("slippage_perc", config.get("slippage", 0.0)) or 0.0
    )
    if slippage != 0.0:
        problems.append(
            f"slippage={slippage}: the LOB venue derives slippage from "
            "book depth; fractional price slippage cannot be honored"
        )
    if config.get("execution_cost_profile"):
        problems.append(
            "execution_cost_profile: profiles drive spread/slippage "
            "displacement and fill policies the LOB venue replaces with "
            "book matching"
        )
    if str(config.get("limit_fill_policy", "cross")) != "cross":
        problems.append(
            f"limit_fill_policy={config['limit_fill_policy']!r}: the LOB "
            "take-profit is a resting limit order — touch/queue semantics "
            "come from matching, not a policy knob; only the default "
            "'cross' is honored"
        )
    if "intrabar_collision_policy" in config:
        problems.append(
            "intrabar_collision_policy: the LOB venue resolves SL/TP by "
            "actual print order along the flow path; collision policies "
            "are a bar-engine concept"
        )
    if problems:
        raise ValueError(
            "venue=lob cannot honor this configuration:\n  - "
            + "\n  - ".join(problems)
        )
