"""Device-mesh utilities: the distributed backbone.

The reference has no distributed backend (no NCCL/MPI — SURVEY.md
§2.9/§5.8); its only concurrency is one engine thread per env.  Here
scale-out is native JAX SPMD: pick a mesh, annotate shardings, let XLA
insert the collectives over ICI (psum for the learner all-reduce,
all-gathers for tensor-sharded layers).  Multi-host extends the same
mesh over DCN via ``jax.distributed.initialize`` (initialize_distributed).

Axes:
  data   env-batch data parallelism (rollout + gradient all-reduce)
  model  tensor parallelism for wide policy layers
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def make_mesh(
    shape: Optional[Dict[str, int]] = None,
    *,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a Mesh; default: all devices on the 'data' axis.

    shape e.g. {"data": 4, "model": 2}; the product must divide the
    device count (extra devices are left unused, deterministically).
    """
    devices = list(devices if devices is not None else jax.devices())
    if not shape:
        shape = {"data": len(devices)}
    axis_names = tuple(shape.keys())
    sizes = tuple(int(v) for v in shape.values())
    need = int(np.prod(sizes))
    if need > len(devices):
        raise ValueError(
            f"mesh shape {shape} needs {need} devices, have {len(devices)}"
        )
    grid = np.array(devices[:need]).reshape(sizes)
    return Mesh(grid, axis_names)


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Leading-dim sharding for env batches."""
    return NamedSharding(mesh, PartitionSpec(axis))


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host (DCN) initialization; single-process no-op when no
    coordinator is configured."""
    if coordinator_address is None:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
