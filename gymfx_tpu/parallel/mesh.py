"""Device-mesh utilities: the distributed backbone.

The reference has no distributed backend (no NCCL/MPI — SURVEY.md
§2.9/§5.8); its only concurrency is one engine thread per env.  Here
scale-out is native JAX SPMD: pick a mesh, annotate shardings, let XLA
insert the collectives over ICI (psum for the learner all-reduce,
all-gathers for tensor-sharded layers).  Multi-host extends the same
mesh over DCN via ``jax.distributed.initialize`` (initialize_distributed).

Axes:
  data   env-batch data parallelism (rollout + gradient all-reduce)
  model  tensor parallelism for wide policy layers
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

try:  # public alias since jax 0.5
    shard_map = jax.shard_map
except AttributeError:  # older jax: only the experimental module exists
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, **kwargs):
        # old-jax replication checking predates the varying-axis types
        # our kernels annotate with pcast_varying (a no-op there), so it
        # would reject loop carries that flip replicated -> varying;
        # disable the static check, the computation is unchanged
        return _experimental_shard_map(f, check_rep=False, **kwargs)


def pcast_varying(x, axis: str):
    """``jax.lax.pcast(x, axis, to="varying")`` where available: marks a
    replicated value as device-varying over ``axis`` so e.g. fori_loop
    carry types match after a ``ppermute``.  Old jax has no varying-axis
    type system — the annotation is unnecessary and the value is
    returned unchanged."""
    try:
        return jax.lax.pcast(x, axis, to="varying")
    except AttributeError:
        return x


def honor_jax_platforms_env() -> None:
    """Make ``JAX_PLATFORMS=cpu`` win even when a sitecustomize
    force-registers an accelerator plugin (plugin registration overrides
    the env var; the config update overrides the registration; harmless
    when already honored).  Without this a user-requested virtual
    multi-device CPU mesh (--xla_force_host_platform_device_count)
    never forms.  Shared by the CLI and the driver entry points."""
    import os

    if os.environ.get("JAX_PLATFORMS", "").lower().split(",")[0].strip() == "cpu":
        jax.config.update("jax_platforms", "cpu")


def make_mesh(
    shape: Optional[Dict[str, int]] = None,
    *,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a Mesh; default: all devices on the 'data' axis.

    shape e.g. {"data": 4, "model": 2}; the product must divide the
    device count (extra devices are left unused, deterministically).
    """
    devices = list(devices if devices is not None else jax.devices())
    if not shape:
        shape = {"data": len(devices)}
    axis_names = tuple(shape.keys())
    sizes = tuple(int(v) for v in shape.values())
    need = int(np.prod(sizes))
    if need > len(devices):
        raise ValueError(
            f"mesh shape {shape} needs {need} devices, have {len(devices)}"
        )
    grid = np.array(devices[:need]).reshape(sizes)
    return Mesh(grid, axis_names)


def mesh_from_config(config: Dict) -> Optional[Mesh]:
    """Resolve the ``mesh_shape`` config key into a live Mesh (or None).

    Honor-or-reject: accepts a dict (config file) or a JSON string (CLI
    passthrough), validates axis names/sizes, and raises when the shape
    cannot be realized on the available devices — never silently ignores
    the field.  ``n_envs`` divisibility is validated by the trainers
    (they know their batch axis).

    ``elastic_exclude_devices`` (written by the elastic auto-resume
    controller, parallel/elastic.py) lists GLOBAL device indices lost to
    degrade events — the mesh forms over the survivors, not the first N
    devices, so a resume attempt never lands work back on a dead chip.
    """
    raw = config.get("mesh_shape")
    if raw is None or raw == "":
        return None
    if isinstance(raw, str):
        import json

        try:
            raw = json.loads(raw)
        except json.JSONDecodeError as exc:
            raise ValueError(
                f"mesh_shape must be a JSON object like "
                f'{{"data": 4, "model": 2}}; got {raw!r}'
            ) from exc
    if not isinstance(raw, dict) or not raw:
        raise ValueError(f"mesh_shape must be a non-empty mapping, got {raw!r}")
    shape: Dict[str, int] = {}
    for axis, size in raw.items():
        if not isinstance(axis, str) or not axis:
            raise ValueError(f"mesh_shape axis names must be strings, got {axis!r}")
        try:
            size_i = int(size)
            ok = size_i >= 1 and size_i == float(size)
        except (TypeError, ValueError):
            ok = False
        if not ok:
            raise ValueError(f"mesh_shape[{axis!r}] must be a positive int, got {size!r}")
        shape[axis] = size_i
    exclude = config.get("elastic_exclude_devices") or ()
    if exclude:
        dead = set()
        for idx in exclude:
            try:
                idx_i = int(idx)
            except (TypeError, ValueError):
                raise ValueError(
                    f"elastic_exclude_devices entries must be device "
                    f"indices, got {idx!r}"
                )
            if idx_i < 0:
                raise ValueError(
                    f"elastic_exclude_devices entries must be >= 0, "
                    f"got {idx_i}"
                )
            dead.add(idx_i)
        survivors = [d for i, d in enumerate(jax.devices()) if i not in dead]
        return make_mesh(shape, devices=survivors)
    return make_mesh(shape)


def validate_batch_axis(mesh: Optional[Mesh], n: int, what: str,
                        axis: str = "data") -> None:
    """Reject meshes missing the batch axis and batch sizes the mesh
    cannot shard evenly (either would otherwise surface as a cryptic
    sharding error deep inside XLA)."""
    if mesh is None:
        return
    if axis not in mesh.axis_names:
        raise ValueError(
            f"mesh_shape must include a {axis!r} axis (got axes "
            f"{list(mesh.axis_names)}): the trainers shard the env "
            f"batch over it"
        )
    k = mesh.shape[axis]
    if n % k != 0:
        raise ValueError(
            f"{what}={n} is not divisible by mesh axis {axis!r} size {k}; "
            f"choose {what} as a multiple of {k}"
        )


def validate_population_axis(mesh: Optional[Mesh], population: int,
                             axis: str = "data") -> None:
    """PBT shards its POPULATION (not the env batch) over the mesh
    ``axis``; honor-or-reject before XLA, same style as
    :func:`validate_batch_axis` — a population the mesh cannot split
    evenly would otherwise surface as a cryptic GSPMD error."""
    if mesh is None:
        return
    if axis not in mesh.axis_names:
        raise ValueError(
            f"mesh_shape must include a {axis!r} axis (got axes "
            f"{list(mesh.axis_names)}): PBT shards the population over it"
        )
    k = mesh.shape[axis]
    if population % k != 0:
        raise ValueError(
            f"pbt_population={population} is not divisible by mesh axis "
            f"{axis!r} size {k}; PBT shards the population over {axis!r} — "
            f"choose pbt_population as a multiple of {k}"
        )


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Leading-dim sharding for env batches."""
    return NamedSharding(mesh, PartitionSpec(axis))


class CoordinatorTimeoutError(TimeoutError):
    """Multi-host initialization exhausted its retry budget without
    reaching the coordinator — carries the address and attempt count so
    the launcher can tell "coordinator never came up" apart from a
    generic hang."""

    def __init__(self, coordinator_address: str, attempts: int,
                 cause: Optional[BaseException] = None):
        super().__init__(
            f"could not reach coordinator {coordinator_address!r} after "
            f"{attempts} attempt(s): {cause}"
        )
        self.coordinator_address = coordinator_address
        self.attempts = attempts
        self.cause = cause


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    *,
    retries: int = 3,
    backoff_s: float = 2.0,
    timeout_s: Optional[float] = None,
    _initialize=None,
    _sleep=None,
) -> None:
    """Multi-host (DCN) initialization; single-process no-op when no
    coordinator is configured.

    At pod scale the coordinator host routinely comes up seconds after
    its workers, so a bare ``jax.distributed.initialize`` races boot
    order.  The attempt is bounded: ``retries`` tries with linear
    ``backoff_s`` between them, each passing ``initialization_timeout``
    through where the jax version supports it, and the budget exhausting
    raises :class:`CoordinatorTimeoutError` instead of a raw
    RuntimeError, so launchers can distinguish "coordinator never came
    up" from a real init bug.  ``_initialize``/``_sleep`` are test
    seams (default: the real jax call / time.sleep).
    """
    if coordinator_address is None:
        return
    import time as _time

    init = _initialize if _initialize is not None else jax.distributed.initialize
    sleep = _sleep if _sleep is not None else _time.sleep
    attempts = max(1, int(retries))
    last: Optional[BaseException] = None
    for attempt in range(1, attempts + 1):
        kwargs = dict(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        try:
            if timeout_s is not None:
                try:
                    init(initialization_timeout=int(timeout_s), **kwargs)
                except TypeError:
                    # older jax: no initialization_timeout kwarg
                    init(**kwargs)
            else:
                init(**kwargs)
            return
        except (RuntimeError, ConnectionError, TimeoutError) as exc:
            last = exc
            if attempt < attempts:
                sleep(backoff_s * attempt)
    raise CoordinatorTimeoutError(coordinator_address, attempts, last)
