"""Pod-scale sharded training runtime: ONE owner for mesh + placement.

Before this module every trainer carried its own copy of the placement
logic (PPO's private ``_shard_state``, IMPALA/portfolio duplicating the
same groups through ``train/common.shard_train_state``, PBT's ad-hoc
``_place``).  :class:`ShardedRuntime` centralizes the whole story:

  * the **mesh** (built here from ``mesh_shape`` config, or adopted);
  * the **NamedSharding plan** — one committed placement per state
    group, shared by all four trainers:

      ===============  =============================================
      group            placement
      ===============  =============================================
      params           wide 2-D matrices ``P(None, 'model')`` when
                       ``shape[-1] % model == 0`` and ``>= 128``
                       (tensor parallelism); everything else
                       replicated
      opt state / rng  replicated (``P()``)
      env batch        leading env axis ``P('data')`` (env states,
                       obs vectors, recurrent carries, trajectories)
      PBT population   leading member axis ``P('data')`` — members
                       are embarrassingly parallel between
                       exploit/explore syncs
      market data      replicated per streamed shard (every device's
                       env shard reads the full bar window)
      ===============  =============================================

  * **donated multi-chip supersteps**: the plan places the state once;
    the existing ``train/common.make_train_many`` driver (``jax.jit``
    + ``donate_argnums=0`` over a ``lax.scan`` of K fused steps) then
    runs as a single GSPMD program over the mesh — XLA inserts the
    gradient all-reduce over 'data' and the tensor-parallel collectives
    over 'model'; no per-device driver code exists anywhere;
  * **sharded host→device bar streaming**: :meth:`bar_streamer` builds
    a :class:`~gymfx_tpu.data.feed.BarStreamer` whose double-buffered
    ``shard_market_data`` shards are ``device_put`` with the mesh
    placement instead of landing on device 0 only;
  * **checkpoint round-trips**: restored host arrays re-enter the mesh
    placement through the same plan (:meth:`place_state`), so a resumed
    run is placed identically to the run that saved.

With ``mesh_shape`` unset the trainers hold no runtime at all
(``ShardedRuntime.from_config`` returns None) and their fast paths are
bit-for-bit the single-device ones.
"""
from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gymfx_tpu.parallel.mesh import (
    batch_sharding,
    mesh_from_config,
    replicated_sharding,
    validate_batch_axis,
    validate_population_axis,
)


class StatePlan(NamedTuple):
    """Field-group placement plan for one trainer's state NamedTuple:
    which fields are policy parameters (tensor-shard candidates), which
    replicate, and which shard their leading env axis over 'data'."""

    params: Tuple[str, ...] = ()
    replicated: Tuple[str, ...] = ()
    batched: Tuple[str, ...] = ()


class ShardedRuntime:
    """Owns a live mesh and the shared NamedSharding placement plan."""

    def __init__(self, mesh: Mesh):
        if mesh is None:
            raise ValueError(
                "ShardedRuntime requires a mesh; with mesh_shape unset the "
                "trainers run the single-device fast path without a runtime"
            )
        self.mesh = mesh

    @classmethod
    def from_config(cls, config: Dict[str, Any]) -> Optional["ShardedRuntime"]:
        """Resolve the ``mesh_shape`` config key (honor-or-reject,
        parallel/mesh.mesh_from_config); None when unset — the callers
        keep their exact no-mesh fast path."""
        mesh = mesh_from_config(config)
        return None if mesh is None else cls(mesh)

    # ------------------------------------------------------------------
    @property
    def n_devices(self) -> int:
        return int(self.mesh.devices.size)

    @property
    def mesh_shape(self) -> Dict[str, int]:
        return dict(self.mesh.shape)

    def validate_batch(self, n: int, what: str) -> None:
        validate_batch_axis(self.mesh, n, what)

    def validate_population(self, population: int) -> None:
        validate_population_axis(self.mesh, population)

    # -- shardings ------------------------------------------------------
    def replicated(self) -> NamedSharding:
        return replicated_sharding(self.mesh)

    def batched(self) -> NamedSharding:
        """Leading env (or population) axis over 'data'."""
        return batch_sharding(self.mesh)

    def _param_sharding(self, x: Any) -> NamedSharding:
        """Tensor-shard wide 2-D policy matrices over 'model'; replicate
        the rest (small/odd-shaped leaves all-gather more than they
        save)."""
        mesh = self.mesh
        if (
            "model" in mesh.axis_names
            and getattr(x, "ndim", 0) == 2
            and x.shape[-1] % mesh.shape["model"] == 0
            and x.shape[-1] >= 128
        ):
            return NamedSharding(mesh, P(None, "model"))
        return replicated_sharding(self.mesh)

    # -- placement ------------------------------------------------------
    def place_params(self, tree: Any) -> Any:
        return jax.tree.map(
            lambda x: jax.device_put(x, self._param_sharding(x)), tree
        )

    def place_replicated(self, tree: Any) -> Any:
        rep = self.replicated()
        return jax.tree.map(
            lambda x: jax.device_put(x, rep) if hasattr(x, "shape") else x,
            tree,
        )

    def _batched_or_rep(self, x: Any, batch: NamedSharding,
                        rep: NamedSharding) -> NamedSharding:
        # zero-sized leaves (e.g. an empty feat_window feature column)
        # come back REPLICATED from every compiled program regardless of
        # the input spec; placing them P('data') would make the AOT
        # executables reject their own output on the next call
        return rep if getattr(x, "size", 1) == 0 else batch

    def place_batched(self, tree: Any) -> Any:
        batch, rep = self.batched(), self.replicated()
        return jax.tree.map(
            lambda x: jax.device_put(x, self._batched_or_rep(x, batch, rep)),
            tree,
        )

    def place_groups(
        self,
        *,
        params: Optional[Dict[str, Any]] = None,
        replicated: Optional[Dict[str, Any]] = None,
        batched: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Place named field groups; returns ``{field: placed_tree}``."""
        out: Dict[str, Any] = {}
        for name, tree in (params or {}).items():
            out[name] = self.place_params(tree)
        for name, tree in (replicated or {}).items():
            out[name] = self.place_replicated(tree)
        for name, tree in (batched or {}).items():
            out[name] = self.place_batched(tree)
        return out

    def place_state(self, state: Any, plan: StatePlan) -> Any:
        """Place a trainer state NamedTuple per its :class:`StatePlan`.
        Used at init AND on checkpoint restore: host arrays loaded from
        a checkpoint re-enter the exact mesh placement the saving run
        used, so resume is placement-identical."""
        groups = self.place_groups(
            params={f: getattr(state, f) for f in plan.params},
            replicated={f: getattr(state, f) for f in plan.replicated},
            batched={f: getattr(state, f) for f in plan.batched},
        )
        return state._replace(**groups)

    def place_population(self, states: Any) -> Any:
        """Shard a vmapped population state (leading member axis) over
        'data': P members train on P/devices chips each.  Non-array
        leaves (e.g. injected-hyperparameter callables inside the
        optimizer state) pass through."""
        pop, rep = self.batched(), self.replicated()
        return jax.tree.map(
            lambda x: jax.device_put(x, self._batched_or_rep(x, pop, rep))
            if hasattr(x, "shape") else x,
            states,
        )

    def place_market_data(self, data: Any) -> Any:
        """Replicate a (host) MarketData shard onto every mesh device —
        each device's env shard reads the full bar window, and without
        an explicit placement ``jax.device_put`` lands host arrays on
        device 0 only (forcing an implicit transfer inside the sharded
        rollout program)."""
        rep = self.replicated()
        return jax.tree.map(lambda x: jax.device_put(x, rep), data)

    def bar_streamer(self, host_data: Any, *, window_size: int,
                     budget_mb: float, min_shard_bars: int = 64,
                     compress: str = "off", tick_size: float = 1e-5):
        """A double-buffered :class:`~gymfx_tpu.data.feed.BarStreamer`
        whose ``shard_market_data`` shards are placed across the mesh
        (host→device DMA of shard ``t+1`` still overlaps compute on
        shard ``t``; only the placement target changes).  With
        ``compress`` on, the int16 tapes ride the same placement and the
        fused decode materializes each replicated f32 shard on device
        (data/compress.py)."""
        from gymfx_tpu.data.feed import BarStreamer

        return BarStreamer(
            host_data, window_size=window_size, budget_mb=budget_mb,
            min_shard_bars=min_shard_bars, placement=self.replicated(),
            compress=compress, tick_size=tick_size,
        )

    # ------------------------------------------------------------------
    def describe(self) -> Dict[str, Any]:
        """Summary/docs slice: the mesh and the committed plan."""
        return {
            "mesh_shape": self.mesh_shape,
            "n_devices": self.n_devices,
            "plan": {
                "params": "wide 2-D matrices P(None,'model') "
                          "(last dim % model == 0 and >= 128); "
                          "rest replicated",
                "opt_state": "replicated",
                "env_batch": "P('data') on the leading env axis",
                "population": "P('data') on the leading member axis (PBT)",
                "market_data": "replicated per streamed shard",
            },
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"ShardedRuntime(mesh_shape={self.mesh_shape})"
