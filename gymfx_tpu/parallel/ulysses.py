"""Ulysses-style sequence parallelism: all-to-all head/sequence swap.

The second sequence-parallel backend next to ring attention
(parallel/ring_attention.py).  Where the ring streams K/V blocks around
the mesh with ``ppermute`` (P communication steps, memory O(S/P)),
Ulysses (DeepSpeed-Ulysses, Jacobs et al. 2023) uses two ``all_to_all``
collectives: the incoming sequence-sharded Q/K/V are redistributed so
each device holds the FULL sequence for H/P of the heads, attention
runs locally and exactly (no online-softmax recurrence), and a second
all-to-all restores sequence sharding.

Trade-offs on TPU: the all-to-alls ride ICI as one fused collective
each (latency ~2 hops instead of P ppermute steps), but each device must
hold full-sequence activations for its head slice — memory O(S·H/P·D)
vs the ring's O(S/P·H·D).  Short-window policies prefer Ulysses;
million-token streams prefer the ring.  Requires n_heads % n_shards == 0.

Same two entry points as the ring module:
  ulysses_attention        (S, H, D) global view, wraps its own shard_map
  ulysses_attention_inner  per-shard blocks inside an active shard_map
                           (what the transformer_ulysses policy calls)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from gymfx_tpu.parallel.mesh import shard_map
from gymfx_tpu.parallel.ring_attention import full_attention


def ulysses_attention_inner(
    q_blk, k_blk, v_blk, *, axis: str, n_shards: int, causal: bool = False
):
    """Exact attention on per-shard blocks inside an active shard_map.

    q/k/v blocks: (..., S/P, H, D) — the local sequence slice, any
    leading batch dims.  ``axis`` must be a mesh axis in scope with
    (static) size ``n_shards``; requires H % n_shards == 0.  Two
    all-to-alls: scatter heads / gather sequence, run full local
    attention over the device's H/P heads, then the inverse.
    """
    *_, sb, h, d = q_blk.shape
    if h % n_shards != 0:
        raise ValueError(
            f"n_heads={h} must divide by the sequence-parallel degree "
            f"{n_shards} for all-to-all sequence parallelism"
        )
    seq_ax = q_blk.ndim - 3
    head_ax = q_blk.ndim - 2

    def scatter_heads(x):
        # (..., S/P, H, D) -> (..., S, H/P, D)
        return jax.lax.all_to_all(
            x, axis, split_axis=head_ax, concat_axis=seq_ax, tiled=True
        )

    def gather_heads(x):
        # (..., S, H/P, D) -> (..., S/P, H, D)
        return jax.lax.all_to_all(
            x, axis, split_axis=seq_ax, concat_axis=head_ax, tiled=True
        )

    qg = scatter_heads(q_blk)
    kg = scatter_heads(k_blk)
    vg = scatter_heads(v_blk)
    # full sequence, local head slice: plain exact attention — the
    # causal mask is the ordinary global one, no ring-position algebra
    out = full_attention(qg, kg, vg, causal=causal)
    return gather_heads(out)


def ulysses_attention(
    q, k, v, *, mesh: Mesh, axis: str = "seq", causal: bool = False
):
    """Exact attention with the sequence sharded over ``mesh[axis]``.

    q/k/v: (S, H, D) arrays (global view); returns (S, H, D) with the
    same sharding.  S must divide by the axis size, H likewise.
    """
    s, h, d = q.shape
    p = mesh.shape[axis]
    if s % p != 0:
        raise ValueError(f"sequence length {s} must divide mesh axis {axis}={p}")

    def shard_fn(q_blk, k_blk, v_blk):
        return ulysses_attention_inner(
            q_blk, k_blk, v_blk, axis=axis, n_shards=p, causal=causal
        )

    spec = P(axis, None, None)
    fn = shard_map(
        shard_fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )
    return fn(q, k, v)
