from gymfx_tpu.parallel.mesh import (  # noqa: F401
    honor_jax_platforms_env,
    make_mesh,
    mesh_from_config,
    validate_batch_axis,
    validate_population_axis,
    batch_sharding,
    replicated_sharding,
    initialize_distributed,
    CoordinatorTimeoutError,
)
from gymfx_tpu.parallel.runtime import (  # noqa: F401
    ShardedRuntime,
    StatePlan,
)
from gymfx_tpu.parallel.elastic import (  # noqa: F401
    ElasticReplanError,
    MeshSupervisor,
    elastic_entry,
    is_device_loss,
    plan_survivor_shape,
    run_elastic,
    stream_preserving,
    survivor_devices,
)
