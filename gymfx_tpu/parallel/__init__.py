from gymfx_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    batch_sharding,
    replicated_sharding,
)
