from gymfx_tpu.parallel.mesh import (  # noqa: F401
    honor_jax_platforms_env,
    make_mesh,
    mesh_from_config,
    validate_batch_axis,
    batch_sharding,
    replicated_sharding,
)
