from gymfx_tpu.parallel.mesh import (  # noqa: F401
    honor_jax_platforms_env,
    make_mesh,
    mesh_from_config,
    validate_batch_axis,
    validate_population_axis,
    batch_sharding,
    replicated_sharding,
)
from gymfx_tpu.parallel.runtime import (  # noqa: F401
    ShardedRuntime,
    StatePlan,
)
