"""Ring attention: sequence-parallel exact attention over a mesh axis.

Long-context capability (mandated first-class; the reference's only
"sequence" dimension is a 32-bar numpy window — SURVEY.md §5.7).  For
sequences too long for one device, shard the sequence over a 'seq' mesh
axis and stream key/value blocks around the ring with ``ppermute``
while accumulating attention with the online-softmax recurrence
(Liu et al. 2023, blockwise ring attention).  Each device only ever
holds its own Q block and one K/V block: memory O(S/P), communication
riding ICI neighbor links, result exact (not approximate).

Two entry points:
  ring_attention        (S, H, D) global view, wraps its own shard_map —
                        the standalone capability (used by the dryrun).
  ring_attention_inner  per-shard blocks (..., S/P, H, D) with optional
                        leading batch dims, for use INSIDE an existing
                        shard_map — this is what the transformer_ring
                        policy calls so a whole batched policy forward
                        can be sequence-sharded (train/policies.py).

Causal masking uses global positions reconstructed from the ring
rotation, so it is exact across shards.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gymfx_tpu.parallel.mesh import pcast_varying, shard_map


def _block_attention(q, k, v, m, l, acc, scale, mask):
    """One online-softmax accumulation step (leading batch dims allowed).

    q: (..., Sq, H, D); k/v: (..., Sk, H, D); m/l: (..., H, Sq);
    acc: (..., Sq, H, D); mask: (Sq, Sk) additive (-inf masked) or None.
    """
    scores = jnp.einsum("...qhd,...khd->...hqk", q, k) * scale
    if mask is not None:
        scores = scores + mask[None, :, :]
    m_new = jnp.maximum(m, scores.max(axis=-1))
    p_ = jnp.exp(scores - m_new[..., None])
    corr = jnp.exp(m - m_new)                      # (..., H, Sq)
    l_new = l * corr + p_.sum(axis=-1)
    corr_q = jnp.swapaxes(corr, -1, -2)            # (..., Sq, H)
    acc_new = acc * corr_q[..., None] + jnp.einsum("...hqk,...khd->...qhd", p_, v)
    return m_new, l_new, acc_new


def ring_attention_inner(
    q_blk, k_blk, v_blk, *, axis: str, n_shards: int, causal: bool = False
):
    """Exact attention on per-shard blocks inside an active shard_map.

    q/k/v blocks: (..., S/P, H, D) — the local sequence slice, any
    leading batch dims.  ``axis`` must be a mesh axis currently in
    scope; ``n_shards`` its (static) size.  Streams K/V around the ring
    with ``ppermute``; returns the local (..., S/P, H, D) output block.
    """
    *batch, sb, h, d = q_blk.shape
    scale = 1.0 / (d ** 0.5)
    my = jax.lax.axis_index(axis)

    def body(i, carry):
        k_cur, v_cur, m, l, acc = carry
        # the K/V block currently held originated on shard (my - i) % P
        src = (my - i) % n_shards
        if causal:
            q_pos = my * sb + jnp.arange(sb)
            k_pos = src * sb + jnp.arange(sb)
            mask = jnp.where(q_pos[:, None] >= k_pos[None, :], 0.0, -jnp.inf)
        else:
            mask = None
        m, l, acc = _block_attention(q_blk, k_cur, v_cur, m, l, acc, scale, mask)
        perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]
        k_next = jax.lax.ppermute(k_cur, axis, perm)
        v_next = jax.lax.ppermute(v_cur, axis, perm)
        return (k_next, v_next, m, l, acc)

    # mark the accumulators as device-varying over the ring axis so the
    # fori_loop carry type matches after the first iteration
    m0 = pcast_varying(
        jnp.full((*batch, h, sb), -jnp.inf, q_blk.dtype), axis
    )
    l0 = pcast_varying(jnp.zeros((*batch, h, sb), q_blk.dtype), axis)
    acc0 = jnp.zeros_like(q_blk)
    _, _, m, l, acc = jax.lax.fori_loop(
        0, n_shards, body, (k_blk, v_blk, m0, l0, acc0)
    )
    denom = jnp.swapaxes(jnp.maximum(l, 1e-30), -1, -2)  # (..., S/P, H)
    return acc / denom[..., None]


def ring_attention(
    q, k, v, *, mesh: Mesh, axis: str = "seq", causal: bool = False
):
    """Exact attention with the sequence sharded over ``mesh[axis]``.

    q/k/v: (S, H, D) arrays (global view); returns (S, H, D) with the
    same sharding.  S must divide evenly by the axis size.
    """
    s, h, d = q.shape
    p = mesh.shape[axis]
    if s % p != 0:
        raise ValueError(f"sequence length {s} must divide mesh axis {axis}={p}")

    def shard_fn(q_blk, k_blk, v_blk):
        return ring_attention_inner(
            q_blk, k_blk, v_blk, axis=axis, n_shards=p, causal=causal
        )

    spec = P(axis, None, None)
    fn = shard_map(
        shard_fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
    )
    return fn(q, k, v)


def full_attention(q, k, v, *, causal: bool = False):
    """Single-device reference implementation (parity oracle);
    leading batch dims allowed."""
    d = q.shape[-1]
    s = q.shape[-3]
    scores = jnp.einsum("...qhd,...khd->...hqk", q, k) / (d ** 0.5)
    if causal:
        pos = jnp.arange(s)
        mask = jnp.where(pos[:, None] >= pos[None, :], 0.0, -jnp.inf)
        scores = scores + mask[None, :, :]
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("...hqk,...khd->...qhd", weights, v)
