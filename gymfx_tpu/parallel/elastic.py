"""Elastic degraded-mesh training: survive device loss without
stranding the run (docs/resilience.md, "Elastic training").

On a pod, preemption and chip loss are the steady state — yet a
checkpoint written by ``ShardedRuntime`` resumes placement-identical,
so losing one device used to strand the whole run.  This module gives
training the discipline serving already has (serve/fleet.py
``ReplicaSupervisor``): detect the loss, re-plan the mesh over the
survivors, and re-enter the last digest-verified checkpoint against the
NEW plan.

  is_device_loss          classify an exception: the simulated
                          :class:`DeviceLossError` (``mesh=`` fault
                          grammar) or a real XLA runtime device error;
  plan_survivor_shape     re-derive the mesh shape for the smaller
                          topology — honor-or-reject when num_envs /
                          the PBT population no longer divide the new
                          data axis, with an explicit
                          ``elastic_shrink_policy`` (repartition vs
                          reject);
  stream_preserving       whether a shrink keeps the env->shard mapping
                          a pure coarsening (every new shard is a
                          concatenation of whole old shards) — the case
                          where per-env streams stay bitwise identical;
  survivor_devices        the device list with the lost global indices
                          excluded (what the survivor mesh forms over);
  MeshSupervisor          tiny-dispatch health probes over the mesh
                          devices, healthy/degraded/dead classification
                          (mirrors serve's ReplicaSupervisor);
  run_elastic             the bounded-retry auto-resume controller the
                          trainers' ``train_from_config`` entries route
                          through when ``elastic_resume`` is set.

Cross-mesh resume path: the last good checkpoint is host-gathered
through the existing digest-verified restore
(train/checkpoint.py ``_restore_item`` verifies the sha256 sidecar and
falls back to the newest verifying step), then re-enters the device
mesh via ``ShardedRuntime.place_state`` against the survivor plan —
the one NamedSharding plan, re-derived for the smaller topology.  When
the repartition is stream-preserving, per-env trajectories continue
bitwise identical (env math is element-wise per env; only the shard
boundaries move).

Every knob unset keeps today's paths bitwise identical — ``run_elastic``
is only entered when ``elastic_resume`` is set, and an armed controller
with no faults is a plain passthrough (pinned by tests/test_elastic.py).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from gymfx_tpu.resilience.faults import (
    DeviceLossError,
    strip_fired_mesh_events,
)

# substrings (lowercased) that mark a real runtime error as device
# loss: the XLA runtime and the PJRT C API surface chip/host failures
# as RuntimeError/XlaRuntimeError with these status phrases
DEVICE_LOSS_MARKERS = (
    "device_unavailable",
    "device unavailable",
    "device lost",
    "device or resource busy",
    "failed to connect to",
    "socket closed",
    "halted execution",
    "slice health",
    "data transfer failure",
)


def is_device_loss(exc: BaseException) -> bool:
    """Whether an exception means "a device/host dropped out" — the
    simulated :class:`DeviceLossError` directly, or a runtime error
    whose message carries one of the known XLA device-failure phrases.
    Everything else (OOM, a genuine bug, divergence) must propagate —
    retrying those on a smaller mesh would only mask them."""
    if isinstance(exc, DeviceLossError):
        return True
    if not isinstance(exc, RuntimeError):
        return False
    msg = str(exc).lower()
    return any(marker in msg for marker in DEVICE_LOSS_MARKERS)


class ElasticReplanError(RuntimeError):
    """The survivor topology cannot honor the run's batch layout —
    either no devices remain for the model axis, or
    ``elastic_shrink_policy=reject`` forbids changing the env->shard
    mapping that the new data axis would force."""


def plan_survivor_shape(
    shape: Dict[str, int],
    *,
    n_lost: int = 1,
    must_divide: Sequence[int] = (),
    policy: str = "repartition",
    axis: str = "data",
) -> Dict[str, int]:
    """Re-derive the mesh shape after losing ``n_lost`` devices.

    Non-batch axes (``model`` tensor parallelism) keep their size — the
    wide-layer sharding plan depends on it — so the loss comes out of
    the ``axis`` (data) extent: ``new_data = surviving // model_prod``.

    Honor-or-reject: when any of ``must_divide`` (num_envs, the PBT
    population) no longer divides the shrunk data axis,
    ``policy="repartition"`` shrinks the data axis further to the
    largest size every constraint divides by (re-partitioning the same
    global batch over fewer shards), while ``policy="reject"`` raises
    :class:`ElasticReplanError` — never a silent wrong layout.
    """
    if not shape:
        raise ElasticReplanError("cannot re-plan an empty mesh shape")
    if axis not in shape:
        raise ElasticReplanError(
            f"mesh shape {shape} has no {axis!r} axis to shrink"
        )
    if policy not in ("repartition", "reject"):
        raise ValueError(
            f"elastic_shrink_policy must be 'repartition' or 'reject', "
            f"got {policy!r}"
        )
    sizes = {k: int(v) for k, v in shape.items()}
    other = int(np.prod([v for k, v in sizes.items() if k != axis] or [1]))
    total = int(np.prod(list(sizes.values())))
    surviving = total - int(n_lost)
    new_data = surviving // other
    if new_data < 1:
        raise ElasticReplanError(
            f"{surviving} surviving device(s) cannot carry the "
            f"non-{axis} axes of {shape} (need at least {other})"
        )
    constraints = [int(n) for n in must_divide if n]
    if any(n % new_data for n in constraints):
        if policy == "reject":
            bad = [n for n in constraints if n % new_data]
            raise ElasticReplanError(
                f"survivor {axis} axis {new_data} does not divide "
                f"{bad} and elastic_shrink_policy=reject forbids "
                f"re-partitioning the env->shard mapping"
            )
        new_data = max(
            d for d in range(1, new_data + 1)
            if all(n % d == 0 for n in constraints)
        )
    out = dict(sizes)
    out[axis] = new_data
    return out


def stream_preserving(
    old_shape: Dict[str, int], new_shape: Dict[str, int], axis: str = "data"
) -> bool:
    """Whether shrinking ``old_shape`` -> ``new_shape`` keeps the
    env->shard mapping a pure coarsening: same non-batch axes, and the
    old data extent a whole multiple of the new one, so every new shard
    is a concatenation of whole old shards (global env order unchanged,
    per-env streams bitwise identical)."""
    old = {k: int(v) for k, v in old_shape.items()}
    new = {k: int(v) for k, v in new_shape.items()}
    if set(old) != set(new):
        return False
    if any(old[k] != new[k] for k in old if k != axis):
        return False
    return new.get(axis, 0) > 0 and old.get(axis, 0) % new[axis] == 0


def survivor_devices(lost: Sequence[int],
                     devices: Optional[Sequence[Any]] = None) -> List[Any]:
    """The device list with the lost GLOBAL indices removed — what the
    survivor mesh forms over (``make_mesh(shape, devices=...)``)."""
    import jax

    pool = list(devices if devices is not None else jax.devices())
    dead = {int(i) for i in lost}
    return [d for i, d in enumerate(pool) if i not in dead]


# ---------------------------------------------------------------------------
class MeshSupervisor:
    """Tiny-dispatch health probes over the training mesh's devices,
    mirroring serve's :class:`~gymfx_tpu.serve.fleet.ReplicaSupervisor`:

      dead      probe raised ``dead_after`` consecutive times, or the
                device was marked lost by the fault grammar / elastic
                controller;
      degraded  at least one recent probe failure, not yet dead;
      healthy   the probe round-tripped.

    ``poll_once()`` is callable directly (no thread) — tests and the
    chaos harness drive it deterministically; ``start()`` runs it on a
    daemon thread every ``interval_s``.  The probe is one scalar
    ``device_put`` + add per device — small enough to run at cadence
    without perturbing training dispatches.

    ``snapshot()`` feeds the ``gymfx_mesh_devices{state=...}`` gauges
    (telemetry/registry.py ``register_mesh_health``) and the flight-
    recorder postmortem frame; ``degrades`` counts mark_lost events
    (the degrade counter).
    """

    def __init__(
        self,
        mesh: Any = None,
        *,
        devices: Optional[Sequence[Any]] = None,
        interval_s: float = 5.0,
        dead_after: int = 3,
        probe: Optional[Callable[[Any], float]] = None,
    ):
        if devices is None:
            if mesh is not None:
                devices = list(np.asarray(mesh.devices).ravel())
            else:
                import jax

                devices = list(jax.devices())
        self.devices = list(devices)
        self.interval_s = float(interval_s)
        self.dead_after = max(1, int(dead_after))
        self._probe = probe if probe is not None else self._default_probe
        self._failures = [0] * len(self.devices)
        self._lost: set = set()
        self._lock = threading.Lock()
        self.polls = 0
        self.degrades = 0
        self._stop = threading.Event()
        self._started = False
        self._thread = threading.Thread(
            target=self._run, name="gymfx-mesh-supervisor", daemon=True
        )

    @staticmethod
    def _default_probe(device: Any) -> float:
        import jax

        return float(
            np.asarray(jax.device_put(np.float32(1.0), device) + 1.0)
        )

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "MeshSupervisor":
        if not self._started:
            self._started = True
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._started:
            self._thread.join(timeout)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.poll_once()
            except Exception:
                # a probe crash must never kill the supervision loop
                pass

    # -- probing -------------------------------------------------------
    def mark_lost(self, indices: Sequence[int]) -> None:
        """Record devices lost out-of-band (the ``mesh=`` fault grammar
        or the elastic controller's classification of a real error) —
        they classify dead without waiting out ``dead_after`` probes."""
        with self._lock:
            fresh = {int(i) for i in indices} - self._lost
            if fresh:
                self._lost |= fresh
                self.degrades += 1

    def poll_once(self) -> Dict[int, str]:
        """Probe every device once; returns device index -> state."""
        self.polls += 1
        states: Dict[int, str] = {}
        for i, device in enumerate(self.devices):
            with self._lock:
                if i in self._lost:
                    states[i] = "dead"
                    continue
            try:
                self._probe(device)
            except Exception:
                self._failures[i] += 1
                states[i] = (
                    "dead" if self._failures[i] >= self.dead_after
                    else "degraded"
                )
            else:
                self._failures[i] = 0
                states[i] = "healthy"
        return states

    def classify(self) -> Dict[int, str]:
        """Current classification WITHOUT dispatching probes (reads the
        accumulated failure counts + out-of-band losses)."""
        states: Dict[int, str] = {}
        with self._lock:
            lost = set(self._lost)
        for i in range(len(self.devices)):
            if i in lost or self._failures[i] >= self.dead_after:
                states[i] = "dead"
            elif self._failures[i] > 0:
                states[i] = "degraded"
            else:
                states[i] = "healthy"
        return states

    def snapshot(self) -> Dict[str, int]:
        """State histogram for the ``gymfx_mesh_devices{state}`` gauges."""
        states = self.classify()
        return {
            "healthy": sum(1 for s in states.values() if s == "healthy"),
            "degraded": sum(1 for s in states.values() if s == "degraded"),
            "dead": sum(1 for s in states.values() if s == "dead"),
        }


# ---------------------------------------------------------------------------
def _shape_of(config: Dict[str, Any]) -> Optional[Dict[str, int]]:
    raw = config.get("mesh_shape")
    if raw in (None, ""):
        return None
    if isinstance(raw, str):
        import json

        raw = json.loads(raw)
    return {str(k): int(v) for k, v in dict(raw).items()}


def _attempt_ledger_path(path: Any, attempt: int) -> str:
    """``ledger.jsonl`` -> ``ledger.attempt2.jsonl``: each resume
    attempt appends to its OWN ledger file, keeping every file's ``seq``
    strictly monotonic (the schema contract) while the shared directory
    still tells the whole story in attempt order."""
    from pathlib import Path

    p = Path(str(path))
    return str(p.with_name(f"{p.stem}.attempt{int(attempt)}{p.suffix}"))


def run_elastic(
    train_once: Callable[[Dict[str, Any]], Dict[str, Any]],
    config: Dict[str, Any],
    *,
    must_divide: Sequence[int] = (),
    sleep: Callable[[float], None] = time.sleep,
) -> Dict[str, Any]:
    """The auto-resume controller: call ``train_once(cfg)`` and, on
    device loss, re-plan + resume on the survivor mesh — bounded by
    ``elastic_max_retries`` with ``elastic_backoff_s`` between attempts.

    Each retry rewrites its config copy (the caller's dict is never
    mutated):

      * ``mesh_shape``      the survivor shape from
                            :func:`plan_survivor_shape` (honor-or-reject
                            per ``elastic_shrink_policy``);
      * ``elastic_exclude_devices``  the lost global device indices, so
                            ``mesh_from_config`` forms the mesh over the
                            SURVIVORS, not the first N devices;
      * ``resume_training`` True — the trainer's own resume entry
                            host-gathers the last digest-verified
                            checkpoint and re-enters it via
                            ``place_state`` against the new plan;
      * ``train_total_steps``  reduced by the steps already safely
                            checkpointed, so the run finishes at the
                            originally requested global step;
      * ``fault_profile``   fired ``mesh=`` events stripped (the retry
                            must not re-kill the device it lost);
      * ``elastic_attempt`` the 1-based attempt index — the trainers
                            ledger a ``mesh_resume`` row when set;
      * ``telemetry_ledger``  re-pointed at a per-attempt file so each
                            ledger keeps a monotonic ``seq``.

    The returned summary carries an ``elastic`` audit block (attempts,
    per-degrade history, final mesh shape) whenever a resume happened.
    """
    cfg = dict(config)
    max_retries = int(cfg.get("elastic_max_retries", 2) or 0)
    backoff_s = float(cfg.get("elastic_backoff_s", 0.0) or 0.0)
    policy = str(cfg.get("elastic_shrink_policy") or "repartition")
    base_ledger = cfg.get("telemetry_ledger") or None
    history: List[Dict[str, Any]] = []
    lost_total: List[int] = []
    base_end: Optional[int] = None
    attempt = 0
    while True:
        try:
            summary = train_once(cfg)
        except BaseException as exc:
            if not is_device_loss(exc) or attempt >= max_retries:
                raise
            attempt += 1
            lost = list(getattr(exc, "lost", ()) or (0,))
            # offset the lost indices into GLOBAL device ids: a fault
            # naming device 0 of an already-shrunk mesh must not evict
            # global device 0 again
            already = set(lost_total)
            global_lost = []
            for idx in lost:
                alive = [
                    g for g in range(len(already) + len(lost) + idx + 1024)
                    if g not in already
                ]
                global_lost.append(alive[int(idx)])
                already.add(alive[int(idx)])
            lost_total.extend(global_lost)
            shape = _shape_of(cfg)
            if shape is None:
                raise ElasticReplanError(
                    "elastic_resume needs an explicit mesh_shape to "
                    "re-plan over survivors"
                ) from exc
            new_shape = plan_survivor_shape(
                shape, n_lost=len(lost), must_divide=must_divide,
                policy=policy,
            )
            ckpt_step = getattr(exc, "checkpoint_step", None)
            if base_end is None:
                base_end = (
                    int(getattr(exc, "step_offset", 0) or 0)
                    + int(cfg.get("train_total_steps", 0) or 0)
                )
            history.append({
                "attempt": attempt,
                "lost": [int(i) for i in global_lost],
                "at": getattr(exc, "at", None),
                "checkpoint_step": ckpt_step,
                "mesh_shape": dict(new_shape),
                "stream_preserving": stream_preserving(shape, new_shape),
            })
            cfg = dict(cfg)
            cfg["mesh_shape"] = dict(new_shape)
            cfg["elastic_exclude_devices"] = [int(i) for i in lost_total]
            cfg["resume_training"] = True
            cfg["elastic_attempt"] = attempt
            if ckpt_step is not None:
                cfg["train_total_steps"] = max(1, base_end - int(ckpt_step))
            at = getattr(exc, "at", None)
            if at is not None:
                cfg["fault_profile"] = strip_fired_mesh_events(
                    cfg.get("fault_profile"), int(at)
                )
            if base_ledger:
                cfg["telemetry_ledger"] = _attempt_ledger_path(
                    base_ledger, attempt
                )
            if backoff_s > 0:
                sleep(backoff_s * attempt)
            continue
        if history:
            summary = dict(summary)
            summary["elastic"] = {
                "attempts": attempt,
                "degrades": history,
                "mesh_shape": _shape_of(cfg),
                "lost_devices": [int(i) for i in lost_total],
            }
        return summary


def elastic_entry(
    train_once: Callable[[Dict[str, Any]], Dict[str, Any]],
    config: Dict[str, Any],
    *,
    must_divide: Sequence[int] = (),
) -> Dict[str, Any]:
    """The trainers' one-line gate: route through :func:`run_elastic`
    only when ``elastic_resume`` is set — unset, the call IS
    ``train_once(config)``, bitwise-identical to the pre-elastic path."""
    if not config.get("elastic_resume"):
        return train_once(config)
    return run_elastic(train_once, config, must_divide=must_divide)
