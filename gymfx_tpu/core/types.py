"""Core state/config/param structures for the functional environment.

The reference keeps episode state in a mutable ``BTBridge`` shared
between two threads (reference app/bt_bridge.py:30-83) plus hidden
state inside plugin objects (reward deques, ATR buffers).  Here ALL of
it is one explicit ``EnvState`` pytree threaded through a pure ``step``
— the precondition for ``jit``/``vmap``/``lax.scan`` and for sharding
state across a device mesh.

Three-way split:
  EnvConfig  static python values (hashable) — changing them recompiles.
  EnvParams  numeric leaves (a pytree) — changing them does NOT recompile;
             this is what optimizers / PBT sweeps mutate.
  EnvState   per-episode carry.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, NamedTuple, Tuple

import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------------------
# Diagnostics counter layouts (int32 vectors in EnvState).
# Names mirror the reference diagnostics dicts so info/summary emission is
# key-for-key compatible (reference app/bt_bridge.py:68-83, app/env.py:718-733).
# ---------------------------------------------------------------------------
EXEC_DIAG_KEYS = (
    "entry_actions_seen",
    "entry_orders_submitted",
    "blocked_session_filter",
    "blocked_atr_warmup",
    "blocked_non_positive_atr",
    "blocked_non_positive_size",
    "blocked_non_positive_price",
    "default_orders_submitted",
    "plugin_apply_errors",
    "event_context_no_trade_active_steps",
    "event_context_action_overrides",
    "event_context_blocked_entries",
    "event_context_forced_flat_actions",
    "event_context_forced_flat_orders",
    "preflight_denied",
    "margin_closeouts",
    "order_denied_min_quantity",
)
EXEC_DIAG_INDEX = {k: i for i, k in enumerate(EXEC_DIAG_KEYS)}

# EnvState.termination_reason codes (why `terminated` first became True;
# 0 while running).  An explicit flag — the bar cursor cannot distinguish
# a bankruptcy ON the final bar from ordinary exhaustion (r2 advisor
# finding, fixed r4).
TERMINATION_RUNNING = 0
TERMINATION_BANKRUPT = 1
TERMINATION_EXHAUSTED = 2
TERMINATION_REASONS = ("running", "bankrupt", "exhausted")

ACTION_DIAG_KEYS = (
    "steps",
    "hold_actions",
    "long_actions",
    "short_actions",
    "non_hold_actions",
    "continuous_deadband_actions",
)
ACTION_DIAG_INDEX = {k: i for i, k in enumerate(ACTION_DIAG_KEYS)}


@dataclasses.dataclass(frozen=True)
class EnvConfig:
    """Static environment configuration (trace-time constants)."""

    window_size: int = 32
    n_bars: int = 0
    n_features: int = 0
    binary_mask: Tuple[bool, ...] = ()
    feature_clip: float = 10.0

    action_space_mode: str = "discrete"      # discrete | continuous
    # widen the discrete space to include 3=force-flat as a PUBLIC
    # action (the portfolio env's per-pair action set; in the
    # single-pair env 3 stays internal to the event overlay)
    allow_flat_action: bool = False
    include_prices: bool = True
    include_agent_state: bool = True
    stage_b_force_close_obs: bool = False
    oanda_fx_calendar_obs: bool = False

    event_context_execution_overlay: bool = False
    event_context_block_new_entries: bool = True
    event_context_force_flat: bool = False

    strategy: str = "default"                # default | direct_fixed_sltp | direct_atr_sltp | registered kernel
    session_filter: bool = False
    sltp_risk_mode: str = "fixed_atr"        # fixed_atr | rel_volume_aware_atr | margin_aware_atr
    size_mode: str = "fx_units"              # fx_units | notional
    atr_period: int = 14

    reward: str = "pnl_reward"               # pnl_reward | sharpe_reward | dd_penalized_reward | registered kernel
    obs_kernels: Tuple[str, ...] = ()        # registered extra obs blocks
    # per-step fused feature scaling (ops/window_zscore.fused_step_obs):
    # "on" = pallas on TPU, plain XLA elsewhere; "interpret" = pallas
    # interpret mode on any backend (CPU parity tests); "off" = plain
    # XLA everywhere (the bitwise oracle)
    rollout_obs_kernel: str = "off"          # off | on | interpret
    # fused env-dynamics kernels (ops/env_dynamics.py): the bar venue's
    # fill/bracket/financing pass and the mark/reward pass each become
    # one env-blocked pallas VMEM pass bracketing the strategy kernel.
    # Same mode contract as rollout_obs_kernel; "off" is the plain-XLA
    # bitwise oracle (tests/test_env_dynamics_kernel.py pins parity).
    rollout_env_kernel: str = "off"          # off | on | interpret
    sharpe_window: int = 64
    stage_b_force_close_reward_penalty: bool = False

    # execution venue: "bar" = the broker scan (fill at next open,
    # brackets vs H/L); "lob" = the vectorized limit-order-book engine
    # (gymfx_tpu/lob/): agent orders walk a seeded book driven by a
    # deterministic per-bar message flow.  Static so the bar path stays
    # bitwise identical when unset — the LOB branch is never traced.
    venue: str = "bar"                       # bar | lob
    lob_depth_levels: int = 24               # price levels per side
    lob_queue_slots: int = 4                 # FIFO orders per level
    lob_messages_per_bar: int = 64           # flow messages per bar (static)
    lob_seed_levels: int = 8                 # seeded levels per side at open
    lob_flow_seed: int = 0                   # order-flow PRNG seed
    lob_scenario: str = "lob_calm"           # lob/scenarios.py preset
    lob_tick_size: float = 1e-5              # quote-currency size of one tick
    lob_lot_units: float = 0.0               # units per lot (0 = position_size)
    # pallas LOB matching (ops/lob_match.py): the sort-free ranked
    # matcher replaces the per-message argsort walk for stream
    # processing (book seeding + the bench depth sweep), exact int32
    # parity with lob/book.py (tests/test_lob_match_kernel.py)
    lob_match_kernel: str = "off"            # off | on | interpret
    # feed=scengen + venue=lob: derive per-bar FlowParams from the
    # generated tape's scen_flags (lob/scenarios.flow_params_from_regime)
    # so droughts thin the book and crash bars burst the flow.  Static:
    # when off (every replay feed) the scen_flags leaf is never traced.
    lob_flow_from_scengen: bool = False

    intrabar_collision_policy: str = "worst_case"  # worst_case | adaptive | ohlc
    # "cross" (price-improving gap fills) is the scan engine's historical
    # no-profile behavior; profiles always set the field explicitly.
    limit_fill_policy: str = "cross"               # conservative | touch | cross
    enforce_margin_preflight: bool = False
    # maintenance-margin liquidation: equity below the maintenance
    # requirement at a bar close force-flattens at the next bar open
    # (reference: Nautilus margin account via margin_maint,
    # simulation_engines/contracts.py:117-120, nautilus_adapter.py:397-427)
    enforce_margin_closeout: bool = False
    margin_model: str = "leveraged"                # standard | leveraged
    financing_enabled: bool = False                # FX rollover interest accrual

    # per-fill-type slippage switches — the reference broker's
    # set_slippage_perc(slip_open, slip_limit, slip_match)
    # (broker_plugins/default_broker.py:52, backtrader semantics).
    # Scan defaults keep the engine's historical behavior (market/stop
    # fills slip, limit fills exempt, no bar-range cap); the reference's
    # backtrader run enables all three — set them in the config to match.
    slip_open: bool = True    # slippage on fills executing at the bar open
    slip_limit: bool = False  # slippage on limit (TP) fills, capped at the limit price
    slip_match: bool = False  # cap slipped fill prices into the bar's [low, high]

    dtype: Any = jnp.float32

    def __post_init__(self):
        from gymfx_tpu.plugins import kernels as _k

        if self.action_space_mode not in ("discrete", "continuous"):
            raise ValueError("action_space_mode must be discrete|continuous")
        if self.strategy not in _k.BUILTIN_STRATEGIES and not _k.has_strategy_kernel(
            self.strategy
        ):
            raise ValueError(f"unknown strategy kernel {self.strategy!r}")
        if self.reward not in _k.BUILTIN_REWARDS and not _k.has_reward_kernel(
            self.reward
        ):
            raise ValueError(f"unknown reward kernel {self.reward!r}")
        for name in self.obs_kernels:
            if not _k.has_obs_kernel(name):
                raise ValueError(f"unknown obs kernel {name!r}")
        if self.rollout_obs_kernel not in ("off", "on", "interpret"):
            raise ValueError(
                f"rollout_obs_kernel must be off|on|interpret, got "
                f"{self.rollout_obs_kernel!r}"
            )
        if self.rollout_env_kernel not in ("off", "on", "interpret"):
            raise ValueError(
                f"rollout_env_kernel must be off|on|interpret, got "
                f"{self.rollout_env_kernel!r}"
            )
        if self.rollout_env_kernel != "off":
            # honor-or-reject: the fused dynamics kernels cover exactly
            # the bar venue's fill/bracket/mark/reward scalar ledger.
            # Anything they cannot reproduce bitwise fails loudly here
            # instead of silently degrading (validate_lob_venue pattern).
            if self.venue != "bar":
                raise ValueError(
                    "rollout_env_kernel requires venue='bar' (the LOB "
                    "venue's matching has its own kernel knob, "
                    "lob_match_kernel)"
                )
            if self.reward not in ("pnl_reward", "dd_penalized_reward"):
                raise ValueError(
                    "rollout_env_kernel supports reward kernels with "
                    "packed scalar carries (pnl_reward, "
                    "dd_penalized_reward); sharpe_reward's per-env ring "
                    f"buffer and registered kernels are XLA-only, got "
                    f"{self.reward!r}"
                )
            if self.dtype != jnp.float32:
                raise ValueError(
                    "rollout_env_kernel requires compute_dtype float32 "
                    f"(got {self.dtype!r}); the f64 oracle mode stays on "
                    "the plain-XLA path"
                )
        if self.lob_match_kernel not in ("off", "on", "interpret"):
            raise ValueError(
                f"lob_match_kernel must be off|on|interpret, got "
                f"{self.lob_match_kernel!r}"
            )
        if self.margin_model not in ("standard", "leveraged"):
            raise ValueError(f"unknown margin_model {self.margin_model!r}")
        if self.intrabar_collision_policy not in ("worst_case", "adaptive", "ohlc"):
            raise ValueError(
                f"unknown intrabar_collision_policy {self.intrabar_collision_policy!r}"
            )
        if self.limit_fill_policy not in ("conservative", "touch", "cross"):
            raise ValueError(
                f"unknown limit_fill_policy {self.limit_fill_policy!r}"
            )
        if self.venue not in ("bar", "lob"):
            raise ValueError(f"venue must be bar|lob, got {self.venue!r}")
        if self.venue == "lob":
            if self.lob_depth_levels < 2:
                raise ValueError("lob_depth_levels must be >= 2")
            if self.lob_queue_slots < 1:
                raise ValueError("lob_queue_slots must be >= 1")
            if self.lob_messages_per_bar < 1:
                raise ValueError("lob_messages_per_bar must be >= 1")
            if not 0 <= self.lob_seed_levels <= self.lob_depth_levels:
                raise ValueError(
                    "lob_seed_levels must be in [0, lob_depth_levels]"
                )
            if self.lob_tick_size <= 0:
                raise ValueError("lob_tick_size must be > 0")
            if self.lob_lot_units < 0:
                raise ValueError("lob_lot_units must be >= 0")
            from gymfx_tpu.lob.scenarios import scenario_flow_params

            scenario_flow_params(self.lob_scenario)  # honor-or-reject


class EnvParams(NamedTuple):
    """Numeric environment parameters (pytree leaves; no recompilation)."""

    initial_cash: Any
    position_size: Any
    commission: Any            # fraction of notional per executed order
    slippage: Any              # fraction of price per fill
    leverage: Any
    min_equity: Any
    continuous_action_threshold: Any

    # reward family
    reward_scale: Any
    penalty_lambda: Any
    annualization_factor: Any

    # fixed-sltp strategy
    sl_pips: Any
    tp_pips: Any
    pip_size: Any

    # atr-sltp strategy
    k_sl: Any
    k_tp: Any
    use_rel_volume: Any        # 0/1 flag (reference: rel_volume=None disables)
    rel_volume: Any
    min_order_volume: Any
    max_order_volume: Any
    min_sltp_frac: Any         # <0 disables
    max_sltp_frac: Any         # <0 disables
    baseline_rel_volume: Any
    max_risk_rel_volume: Any
    rel_volume_sl_shrink_alpha: Any
    rel_volume_tp_shrink_alpha: Any
    min_k_sl: Any
    min_reward_risk_ratio: Any
    max_planned_loss_fraction: Any  # <0 disables

    # session/weekend filter (minute-of-week bounds)
    entry_start_mow: Any
    force_close_mow: Any

    # event-context overlay
    event_no_trade_threshold: Any

    # stage-B force-close reward penalty
    force_close_penalty_coef: Any
    force_close_penalty_window_hours: Any

    # margin (instrument initial / maintenance fractions)
    margin_init: Any
    margin_maint: Any

    # opt-in venue quantization (0 = off): book-price tick, order-size
    # step, minimum order quantity — the scan twins of the replay
    # venue's make_price/make_qty/min_quantity (simulation/replay.py;
    # reference nautilus_adapter.py:111-113,190).  Params-only sentinel
    # design: enabling it never recompiles the step.
    price_tick: Any = 0.0
    size_step: Any = 0.0
    min_qty: Any = 0.0

    # registered third-party kernel parameters ({config_key: scalar});
    # an empty tuple when no custom kernel is selected
    user: Any = ()


class EnvState(NamedTuple):
    """Per-episode carry threaded through the scan."""

    t: Any                 # i32 current bar row (0-based); bar_index = t + 1
    started: Any           # bool — warmup handshake done (reference bt_bridge.py:144-151)
    terminated: Any        # bool
    termination_reason: Any  # i32 TERMINATION_* code (0 while running)

    # broker ledger (all in quote currency, relative to initial cash)
    pos: Any               # signed units
    entry_price: Any       # avg entry price of open position
    cash_delta: Any        # cash - initial_cash
    equity_delta: Any      # marked at close of bar t
    prev_equity_delta: Any
    commission_paid: Any
    last_trade_cost: Any
    trade_count: Any       # i32 closed trades

    # pending order (created at bar t close, fills at bar t+1 open)
    pending_active: Any    # bool
    pending_target: Any    # desired signed units
    pending_sl: Any        # bracket prices to arm after fill (0 = none)
    pending_tp: Any
    # venue-forced liquidation flag: the pending order was created by the
    # maintenance-margin closeout, not the agent — it bypasses the venue's
    # min-quantity/size-step rules exactly like the replay engine's
    # liquidation ("a venue never strands a liquidation on a size rule",
    # simulation/replay.py check_margin_closeout)
    pending_forced: Any    # bool

    # active bracket on the open position (0 = none)
    bracket_sl: Any
    bracket_tp: Any

    # trade statistics (for SQN / won / lost / avg pnl)
    trade_pnl_sum: Any
    trade_pnl_sumsq: Any
    trades_won: Any        # i32
    trades_lost: Any       # i32
    open_trade_commission: Any  # commissions attributed to the open trade

    # drawdown tracking
    peak_equity_delta: Any
    max_drawdown_money: Any
    max_drawdown_pct: Any

    # reward carries
    reward_buffer: Any     # (sharpe_window,) step returns ring buffer
    reward_buffer_len: Any # i32
    reward_buffer_idx: Any # i32
    reward_peak: Any       # dd_penalized peak equity

    # ATR true-range ring buffer (direct_atr_sltp)
    tr_buffer: Any         # (atr_period,)
    tr_len: Any            # i32
    tr_idx: Any            # i32
    prev_close: Any        # previous bar close (<=0 sentinel: none yet)

    # streaming observation windows.  Kept as carries and updated
    # incrementally (shift + append) on each bar advance: a vmapped
    # dynamic_slice gather per step costs ~15x the entire env step on
    # TPU, while the streaming update is pure vector ops.
    price_window: Any      # (window_size,) close window ending at the current bar
    feat_window: Any       # (window_size, n_features) raw feature window

    # diagnostics
    exec_diag: Any         # (len(EXEC_DIAG_KEYS),) i32
    action_diag: Any       # (len(ACTION_DIAG_KEYS),) i32
    raw_abs_sum: Any
    raw_min: Any
    raw_max: Any
    last_raw_action: Any
    last_coerced_action: Any  # i32


# ---------------------------------------------------------------------------
# Builders from a merged config dict
# ---------------------------------------------------------------------------
def _parse_profile(config: Dict[str, Any]):
    raw = config.get("execution_cost_profile")
    if not raw:
        return None
    from gymfx_tpu.contracts import ExecutionCostProfile, load_execution_cost_profile

    if isinstance(raw, str):
        return load_execution_cost_profile(raw)
    if isinstance(raw, dict):
        return ExecutionCostProfile.from_dict(raw)
    return raw


def make_env_config(config: Dict[str, Any], *, n_bars: int, n_features: int = 0,
                    binary_mask: Tuple[bool, ...] = (), profile=None) -> EnvConfig:
    feature_columns = list(config.get("feature_columns") or [])
    include_prices = bool(config.get("include_price_window", not feature_columns))
    oanda_cal = bool(
        config.get("oanda_fx_calendar_obs", False)
        or str(config.get("broker_profile") or "").lower() == "oanda_us_fx"
    )
    dtype = {"float32": jnp.float32, "float64": jnp.float64, "bfloat16": jnp.bfloat16}[
        str(config.get("compute_dtype", "float32"))
    ]
    profile = _parse_profile(config) if profile is None else profile
    collision = str(
        config.get(
            "intrabar_collision_policy",
            profile.intrabar_collision_policy if profile else "worst_case",
        )
    )
    enforce_margin = bool(
        config.get(
            "enforce_margin_preflight",
            profile.enforce_margin_preflight if profile else False,
        )
    )
    # maintenance enforcement follows the preflight flag by default (one
    # venue either runs a margin account or does not — the reference's
    # Nautilus engine enforces both implicitly); the explicit config key
    # overrides either way
    enforce_closeout = bool(config.get("enforce_margin_closeout", enforce_margin))
    margin_model = str(
        config.get("margin_model", profile.margin_model if profile else "leveraged")
    )
    limit_fill = str(
        config.get(
            "limit_fill_policy",
            profile.limit_fill_policy if profile else "cross",
        )
    )
    financing = bool(
        config.get(
            "financing_enabled",
            profile.financing_enabled if profile else False,
        )
    )
    if collision == "adaptive":
        import warnings

        warnings.warn(
            "intrabar_collision_policy 'adaptive' resolves to 'worst_case' in "
            "the scan engine (no per-bar path data to adapt on); see "
            "DIVERGENCES.md",
            stacklevel=2,
        )
    return EnvConfig(
        window_size=int(config.get("window_size", 32)),
        n_bars=int(n_bars),
        n_features=int(n_features),
        binary_mask=tuple(binary_mask),
        feature_clip=float(config.get("feature_clip", 10.0)),
        action_space_mode=str(config.get("action_space_mode", "discrete")).lower(),
        include_prices=include_prices,
        include_agent_state=bool(config.get("include_agent_state", True)),
        stage_b_force_close_obs=bool(config.get("stage_b_force_close_obs", False)),
        oanda_fx_calendar_obs=oanda_cal,
        event_context_execution_overlay=bool(
            config.get("event_context_execution_overlay", False)
        ),
        event_context_block_new_entries=bool(
            config.get("event_context_block_new_entries", True)
        ),
        event_context_force_flat=bool(config.get("event_context_force_flat", False)),
        strategy=_strategy_kernel_name(config),
        session_filter=bool(config.get("session_filter", False)),
        sltp_risk_mode=str(config.get("sltp_risk_mode", "fixed_atr")).lower(),
        size_mode=str(config.get("size_mode", "fx_units")).lower(),
        atr_period=int(config.get("atr_period", 14)),
        reward=str(config.get("reward_plugin", "pnl_reward")),
        obs_kernels=_obs_kernel_names(config.get("obs_plugins")),
        rollout_obs_kernel=str(config.get("rollout_obs_kernel", "off")).lower(),
        rollout_env_kernel=str(config.get("rollout_env_kernel", "off")).lower(),
        sharpe_window=int(config.get("window", config.get("sharpe_window", 64))),
        stage_b_force_close_reward_penalty=bool(
            config.get("stage_b_force_close_reward_penalty", False)
        ),
        venue=str(config.get("venue", "bar")).lower(),
        lob_depth_levels=int(config.get("lob_depth_levels", 24)),
        lob_queue_slots=int(config.get("lob_queue_slots", 4)),
        lob_messages_per_bar=int(config.get("lob_messages_per_bar", 64)),
        lob_seed_levels=int(config.get("lob_seed_levels", 8)),
        lob_flow_seed=int(config.get("lob_flow_seed", 0)),
        lob_scenario=str(config.get("lob_scenario", "lob_calm")),
        lob_tick_size=float(config.get("lob_tick_size", 1e-5)),
        lob_lot_units=float(config.get("lob_lot_units", 0.0)),
        lob_match_kernel=str(config.get("lob_match_kernel", "off")).lower(),
        lob_flow_from_scengen=(
            str(config.get("feed") or "replay").lower() == "scengen"
            and str(config.get("venue", "bar")).lower() == "lob"
        ),
        intrabar_collision_policy=collision,
        limit_fill_policy=limit_fill,
        slip_open=bool(config.get("slip_open", True)),
        slip_limit=bool(config.get("slip_limit", False)),
        slip_match=bool(config.get("slip_match", False)),
        enforce_margin_preflight=enforce_margin,
        enforce_margin_closeout=enforce_closeout,
        margin_model=margin_model,
        financing_enabled=financing,
        dtype=dtype,
    )


def _obs_kernel_names(raw: Any) -> Tuple[str, ...]:
    """obs_plugins accepts a list OR the CLI's comma-separated string —
    tuple() on a bare string would split it into characters."""
    if not raw:
        return ()
    if isinstance(raw, str):
        return tuple(s.strip() for s in raw.split(",") if s.strip())
    return tuple(str(s) for s in raw)


def _strategy_kernel_name(config: Dict[str, Any]) -> str:
    name = str(config.get("strategy_plugin", "default_strategy"))
    if name in ("direct_fixed_sltp", "direct_atr_sltp"):
        return name
    if name in ("default", "default_strategy"):
        # the reference's default_strategy is an action DRIVER, not an
        # executor; the kernel equivalent is the default order flow
        return "default"
    from gymfx_tpu.plugins import kernels as _k

    if _k.has_strategy_kernel(name):
        return name
    raise ValueError(
        f"unknown strategy kernel {name!r}: not a built-in and not a "
        "registered strategy kernel (plugins/kernels.py)"
    )


def make_env_params(config: Dict[str, Any], cfg: EnvConfig, profile=None) -> EnvParams:
    d = cfg.dtype
    initial_cash = float(config.get("initial_cash", 10000.0))
    min_equity = config.get("min_equity")
    if min_equity is None:
        min_equity = initial_cash * 0.01  # reference app/env.py:122
    rel_volume = config.get("rel_volume")
    use_rel = rel_volume is not None

    def f(x) -> Any:
        return jnp.asarray(float(x), dtype=d)

    def opt(x, disabled=-1.0) -> Any:
        return f(disabled if x is None else x)

    slippage = config.get("slippage_perc", config.get("slippage", 0.0)) or 0.0
    commission = config.get("commission", 0.0)
    # An execution cost profile (path or dict) overrides commission and
    # fill displacement: fills move adversely from mid by
    # half-spread + slippage (contracts.py quote_adverse_rate_per_side).
    # The reference applies profiles only on its Nautilus engine
    # (simulation_engines/nautilus_gym.py:236-238); the scan engine
    # honors them directly.
    profile = _parse_profile(config) if profile is None else profile
    if profile is not None:
        commission = profile.commission_rate_per_side
        slippage = profile.quote_adverse_rate_per_side
    entry_start_mow = (
        int(config.get("entry_dow_start", 0)) * 24 * 60
        + int(config.get("entry_hour_start", 12)) * 60
    )
    force_close_mow = (
        int(config.get("force_close_dow", 4)) * 24 * 60
        + int(config.get("force_close_hour", 20)) * 60
    )
    return EnvParams(
        initial_cash=f(initial_cash),
        position_size=f(config.get("position_size", 1.0)),
        commission=f(commission),
        slippage=f(slippage),
        leverage=f(config.get("leverage", 1.0)),
        min_equity=f(min_equity),
        continuous_action_threshold=f(
            0.33
            if config.get("continuous_action_threshold", 0.33) is None
            else config.get("continuous_action_threshold", 0.33)
        ),
        reward_scale=f(config.get("reward_scale", 1.0)),
        penalty_lambda=f(config.get("penalty_lambda", 1.0)),
        annualization_factor=f(config.get("annualization_factor", 252.0)),
        sl_pips=f(config.get("sl_pips", 20.0)),
        tp_pips=f(config.get("tp_pips", 40.0)),
        pip_size=f(config.get("pip_size", 0.0001)),
        k_sl=f(config.get("k_sl", 2.0)),
        k_tp=f(config.get("k_tp", 3.0)),
        use_rel_volume=f(1.0 if use_rel else 0.0),
        rel_volume=f(rel_volume if use_rel else 0.0),
        min_order_volume=f(config.get("min_order_volume", 0.0)),
        max_order_volume=f(config.get("max_order_volume", 1e12)),
        min_sltp_frac=opt(config.get("min_sltp_frac", 0.001)),
        max_sltp_frac=opt(config.get("max_sltp_frac", 0.20)),
        baseline_rel_volume=f(config.get("baseline_rel_volume", 0.05)),
        max_risk_rel_volume=f(config.get("max_risk_rel_volume", 0.50)),
        rel_volume_sl_shrink_alpha=f(config.get("rel_volume_sl_shrink_alpha", 0.35)),
        rel_volume_tp_shrink_alpha=f(config.get("rel_volume_tp_shrink_alpha", 0.20)),
        min_k_sl=f(config.get("min_k_sl", 1.0)),
        min_reward_risk_ratio=f(config.get("min_reward_risk_ratio", 1.0)),
        max_planned_loss_fraction=opt(config.get("max_planned_loss_fraction")),
        entry_start_mow=jnp.asarray(entry_start_mow, dtype=jnp.int32),
        force_close_mow=jnp.asarray(force_close_mow, dtype=jnp.int32),
        event_no_trade_threshold=f(config.get("event_context_no_trade_threshold", 0.5)),
        force_close_penalty_coef=f(
            config.get("force_close_exposure_penalty_coef", 0.0)
        ),
        margin_init=f(config.get("margin_init", 0.05)),
        margin_maint=f(config.get("margin_maint", 0.025)),
        **_venue_quantization_params(config, f),
        force_close_penalty_window_hours=f(
            config.get(
                "force_close_exposure_penalty_window_hours",
                config.get("force_close_window_hours", 4),
            )
        ),
        user=_user_params(config, cfg, f),
    )


def _venue_quantization_params(config: Dict[str, Any], f) -> Dict[str, Any]:
    """Opt-in (``venue_quantization: true``): derive tick/step/min-qty
    from the instrument spec resolved exactly as the replay engine does
    (contracts.instrument_spec_from_config), so both engines quantize to
    the same grid.  Off -> zero sentinels, the step is untouched."""
    if not config.get("venue_quantization"):
        return {"price_tick": f(0.0), "size_step": f(0.0), "min_qty": f(0.0)}
    from gymfx_tpu.contracts import instrument_spec_from_config

    spec = instrument_spec_from_config(config)
    return {
        "price_tick": f(10.0 ** (-spec.price_precision)),
        "size_step": f(10.0 ** (-spec.size_precision)),
        "min_qty": f(spec.min_quantity),
    }


def _user_params(config: Dict[str, Any], cfg: EnvConfig, f) -> Any:
    """Numeric parameters declared by the selected registered kernels,
    read from the merged config (plugins/kernels.py contract)."""
    from gymfx_tpu.plugins import kernels as _k

    schema = _k.user_param_schema(cfg.reward, cfg.strategy, cfg.obs_kernels)
    if not schema:
        return ()
    return {
        key: f(config.get(key, default) if config.get(key) is not None else default)
        for key, default in sorted(schema.items())
    }


def initial_state(cfg: EnvConfig) -> EnvState:
    d = cfg.dtype
    z = jnp.zeros((), dtype=d)
    zi = jnp.zeros((), dtype=jnp.int32)

    return EnvState(
        t=zi,
        started=jnp.zeros((), dtype=bool),
        terminated=jnp.zeros((), dtype=bool),
        termination_reason=zi,
        pos=z,
        entry_price=z,
        cash_delta=z,
        equity_delta=z,
        prev_equity_delta=z,
        commission_paid=z,
        last_trade_cost=z,
        trade_count=zi,
        pending_active=jnp.zeros((), dtype=bool),
        pending_target=z,
        pending_sl=z,
        pending_tp=z,
        pending_forced=jnp.zeros((), dtype=bool),
        bracket_sl=z,
        bracket_tp=z,
        trade_pnl_sum=z,
        trade_pnl_sumsq=z,
        trades_won=zi,
        trades_lost=zi,
        open_trade_commission=z,
        peak_equity_delta=z,
        max_drawdown_money=z,
        max_drawdown_pct=z,
        reward_buffer=jnp.zeros((cfg.sharpe_window,), dtype=d),
        reward_buffer_len=zi,
        reward_buffer_idx=zi,
        reward_peak=jnp.asarray(-np.inf, dtype=d),  # delta-space peak
        tr_buffer=jnp.zeros((cfg.atr_period,), dtype=d),
        tr_len=zi,
        tr_idx=zi,
        prev_close=jnp.asarray(-1.0, dtype=d),
        price_window=jnp.zeros((cfg.window_size,), dtype=d),
        feat_window=jnp.zeros((cfg.window_size, cfg.n_features), dtype=jnp.float32),
        exec_diag=jnp.zeros((len(EXEC_DIAG_KEYS),), dtype=jnp.int32),
        action_diag=jnp.zeros((len(ACTION_DIAG_KEYS),), dtype=jnp.int32),
        raw_abs_sum=z,
        raw_min=jnp.asarray(np.inf, dtype=d),
        raw_max=jnp.asarray(-np.inf, dtype=d),
        last_raw_action=z,
        last_coerced_action=zi,
    )
