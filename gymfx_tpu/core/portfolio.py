"""Multi-pair portfolio environment (BASELINE.json config 5).

New capability: the reference env trades a single instrument; its only
multi-asset surface is the Nautilus replay fixture.  Here the portfolio
env is a first-class scan kernel over I instruments simultaneously:
positions, pending orders and pnl conversion are (I,)-vectors, one step
advances all pairs in lockstep, and the whole thing jits/vmaps/shards
exactly like the single-pair core.

Accounting: one account currency; each pair carries a per-bar
conversion factor from its quote currency to the account currency
(precomputed host-side: 1 for XXX/ACC pairs, 1/price for ACC/XXX
pairs — the same direct-pair rule as the reconciliation oracle,
simulation/oracle.py).  Cash effects of fills and mark-to-market pnl
convert at the bar where they occur.

Timing matches the single-pair kernel: actions at bar t create pending
orders that fill at bar t+1's open; equity marks at every close; the
first step is the same-bar warmup.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd


class PortfolioData(NamedTuple):
    open: Any      # (n, I)
    high: Any      # (n, I)
    low: Any       # (n, I)
    close: Any     # (n, I)
    conv: Any      # (n, I) quote->account conversion factor
    padded_close: Any  # (n + w, I)

    @property
    def n_bars(self) -> int:
        return int(self.close.shape[0])

    @property
    def n_pairs(self) -> int:
        return int(self.close.shape[1])


@dataclasses.dataclass(frozen=True)
class PortfolioConfig:
    n_pairs: int
    n_bars: int
    window_size: int = 32
    margin_rate: float = 0.0   # 0 disables the margin preflight
    dtype: Any = jnp.float32


class PortfolioParams(NamedTuple):
    initial_cash: Any
    position_size: Any     # (I,) units per order
    commission: Any
    slippage: Any
    leverage: Any
    min_equity: Any
    reward_scale: Any


class PortfolioState(NamedTuple):
    t: Any
    started: Any
    terminated: Any
    pos: Any               # (I,) signed units
    entry: Any             # (I,) avg entry price
    cash_delta: Any        # scalar, account currency
    equity_delta: Any
    prev_equity_delta: Any
    commission_paid: Any
    trade_count: Any       # i32 scalar
    pending_active: Any    # (I,) bool
    pending_target: Any    # (I,)
    blocked_margin: Any    # i32 counter


def load_portfolio_frames(
    files: Dict[str, str],
    *,
    date_column: str = "DATE_TIME",
    price_column: str = "CLOSE",
    max_rows: Optional[int] = None,
) -> Tuple[List[str], Dict[str, pd.DataFrame]]:
    """Load and time-align several pair CSVs on their shared timestamps
    (inner join).  Returns (pair names, per-pair aligned frames)."""
    frames: Dict[str, pd.DataFrame] = {}
    for pair, path in files.items():
        df = pd.read_csv(path, nrows=max_rows)
        df[date_column] = pd.to_datetime(df[date_column], errors="coerce")
        df = df.dropna(subset=[date_column]).set_index(date_column)
        for col in ("OPEN", "HIGH", "LOW", "CLOSE"):
            if col not in df.columns:
                df[col] = df[price_column]
        frames[pair] = df
    common = None
    for df in frames.values():
        common = df.index if common is None else common.intersection(df.index)
    if common is None or len(common) < 3:
        raise ValueError("portfolio pairs share too few timestamps")
    aligned = {pair: df.loc[common] for pair, df in frames.items()}
    return list(files.keys()), aligned


def build_portfolio_data(
    pairs: Sequence[str],
    aligned: Dict[str, pd.DataFrame],
    *,
    window_size: int,
    account_currency: str = "USD",
    dtype: Any = jnp.float32,
) -> PortfolioData:
    n = len(next(iter(aligned.values())))
    cols = {k: np.stack([aligned[p][k].to_numpy(np.float64) for p in pairs], 1)
            for k in ("OPEN", "HIGH", "LOW", "CLOSE")}
    closes = cols["CLOSE"]
    # quote-currency -> account-currency factors; crosses bridge through
    # another pair in the book that quotes/bases the account currency
    parsed = [p.replace("/", "_").split("_", 1) for p in pairs]
    conv = np.ones((n, len(pairs)))
    for i, (base, quote) in enumerate(parsed):
        if quote == account_currency:
            conv[:, i] = 1.0
        elif base == account_currency:
            conv[:, i] = 1.0 / closes[:, i]
        else:
            bridge = None
            for j, (b2, q2) in enumerate(parsed):
                if b2 == quote and q2 == account_currency:
                    bridge = closes[:, j]          # quote/ACC price
                    break
                if b2 == account_currency and q2 == quote:
                    bridge = 1.0 / closes[:, j]    # ACC/quote price inverted
                    break
            if bridge is None:
                raise ValueError(
                    f"pair {pairs[i]}: no direct conversion from {quote} to "
                    f"{account_currency} and no bridging pair in the book"
                )
            conv[:, i] = bridge
    padded = np.concatenate(
        [np.tile(cols["CLOSE"][:1], (window_size, 1)), cols["CLOSE"]], axis=0
    )
    return PortfolioData(
        open=jnp.asarray(cols["OPEN"], dtype),
        high=jnp.asarray(cols["HIGH"], dtype),
        low=jnp.asarray(cols["LOW"], dtype),
        close=jnp.asarray(cols["CLOSE"], dtype),
        conv=jnp.asarray(conv, dtype),
        padded_close=jnp.asarray(padded, dtype),
    )


# ---------------------------------------------------------------------------
def reset(cfg: PortfolioConfig, params: PortfolioParams, data: PortfolioData):
    d = cfg.dtype
    I = cfg.n_pairs
    z = jnp.zeros((), d)
    state = PortfolioState(
        t=jnp.zeros((), jnp.int32),
        started=jnp.zeros((), bool),
        terminated=jnp.zeros((), bool),
        pos=jnp.zeros((I,), d),
        entry=jnp.zeros((I,), d),
        cash_delta=z,
        equity_delta=z,
        prev_equity_delta=z,
        commission_paid=z,
        trade_count=jnp.zeros((), jnp.int32),
        pending_active=jnp.zeros((I,), bool),
        pending_target=jnp.zeros((I,), d),
        blocked_margin=jnp.zeros((), jnp.int32),
    )
    return state, build_obs(state, data, cfg, params)


def build_obs(state, data: PortfolioData, cfg: PortfolioConfig, params):
    w = cfg.window_size
    step = jnp.minimum(state.t + 1, cfg.n_bars)
    prices = jax.lax.dynamic_slice(
        data.padded_close, (step, jnp.zeros((), step.dtype)), (w, cfg.n_pairs)
    )
    returns = prices - jnp.concatenate([prices[:1], prices[:-1]])
    initial = jnp.where(params.initial_cash == 0, 1.0, params.initial_cash)
    return {
        "prices": prices.astype(jnp.float32),
        "returns": returns.astype(jnp.float32),
        "position": jnp.sign(state.pos).astype(jnp.float32),
        "equity_norm": jnp.asarray(
            [state.equity_delta / initial], jnp.float32
        ),
        "steps_remaining_norm": jnp.asarray(
            [jnp.maximum(0, cfg.n_bars - (state.t + 1)) / max(1, cfg.n_bars)],
            jnp.float32,
        ),
    }


def step(cfg: PortfolioConfig, params: PortfolioParams, data: PortfolioData,
         state: PortfolioState, actions):
    """actions: (I,) ints in {0=hold, 1=long, 2=short, 3=flat}."""
    n = cfg.n_bars
    was_terminated = state.terminated
    live = ~was_terminated
    a = jnp.asarray(actions, jnp.int32).reshape(cfg.n_pairs)
    a = jnp.where((a >= 0) & (a <= 3), a, 0)

    advance = live & state.started & (state.t < n - 1)
    exhausted = live & state.started & (state.t >= n - 1)
    act = live & ~exhausted

    t_new = jnp.where(advance, state.t + 1, state.t)
    o = data.open[t_new]      # (I,)
    c = data.close[t_new]
    conv = data.conv[t_new]

    pos, entry, cash = state.pos, state.entry, state.cash_delta
    commission_paid = state.commission_paid
    trade_count = state.trade_count

    # ---- fill pending orders at the new bar's open -------------------
    do_fill = advance & state.pending_active
    target = jnp.where(do_fill, state.pending_target, pos)
    delta = target - pos
    direction = jnp.sign(delta)
    fill = o * (1.0 + params.slippage * direction)
    commission = params.commission * fill * jnp.abs(delta) * conv
    # realized pnl on closed units, converted to the account currency
    same_sign = pos * target > 0
    closed = jnp.where(same_sign, jnp.maximum(jnp.abs(pos) - jnp.abs(target), 0.0),
                       jnp.abs(pos))
    closed = jnp.where(delta == 0, 0.0, closed)
    realized = closed * (fill - entry) * jnp.sign(pos) * conv
    cash = cash + jnp.sum(realized - commission)
    commission_paid = commission_paid + jnp.sum(commission)

    flipping = (~same_sign) & (target != 0) & (pos != 0)
    opening = (pos == 0) & (target != 0)
    adding = same_sign & (jnp.abs(target) > jnp.abs(pos))
    new_entry = jnp.where(
        adding,
        (entry * jnp.abs(pos) + fill * (jnp.abs(target) - jnp.abs(pos)))
        / jnp.maximum(jnp.abs(target), 1e-30),
        entry,
    )
    new_entry = jnp.where(flipping | opening, fill, new_entry)
    new_entry = jnp.where(target == 0, 0.0, new_entry)
    trade_closed = (pos != 0) & ((target == 0) | flipping)
    # .astype: jnp.sum promotes int32 to int64 under jax_enable_x64,
    # which breaks the scan-carry dtype contract
    trade_count = trade_count + jnp.sum(trade_closed.astype(jnp.int32)).astype(jnp.int32)
    pos = target
    entry = new_entry

    # ---- apply new actions at the close ------------------------------
    size = params.position_size
    want = jnp.where(
        a == 1, size, jnp.where(a == 2, -size, jnp.where(a == 3, 0.0, jnp.nan))
    )
    submit = act & (a != 0) & (
        (a == 3) & (pos != 0)
        | (a == 1) & (pos <= 0)
        | (a == 2) & (pos >= 0)
    )
    new_target = jnp.where(submit, jnp.nan_to_num(want), pos)

    # optional margin preflight on the TOTAL post-fill book
    if cfg.margin_rate > 0:
        notional = jnp.sum(jnp.abs(new_target) * c * conv)
        equity_now = params.initial_cash + cash + jnp.sum(pos * (c - entry) * conv)
        required = notional * cfg.margin_rate / jnp.maximum(params.leverage, 1e-12)
        margin_ok = required <= equity_now
        blocked = submit & ~margin_ok & (jnp.abs(new_target) > jnp.abs(pos))
        new_target = jnp.where(blocked, pos, new_target)
        submit = submit & ~blocked
        state_blocked = state.blocked_margin + jnp.sum(blocked.astype(jnp.int32)).astype(jnp.int32)
    else:
        state_blocked = state.blocked_margin

    pending_active = jnp.where(act, submit & (new_target != pos), False)
    pending_target = jnp.where(pending_active, new_target, 0.0)

    # ---- mark to market ----------------------------------------------
    unrealized = jnp.sum(pos * (c - entry) * conv)
    equity_delta = jnp.where(
        advance | (live & ~state.started), cash + unrealized, state.equity_delta
    )
    prev_equity_delta = jnp.where(
        advance | (live & ~state.started), state.equity_delta,
        state.prev_equity_delta,
    )

    initial = jnp.where(params.initial_cash == 0, 1.0, params.initial_cash)
    reward = jnp.where(
        live, (equity_delta - prev_equity_delta) / initial * params.reward_scale, 0.0
    )
    equity = params.initial_cash + equity_delta
    terminated = was_terminated | exhausted | (live & (equity <= params.min_equity))

    new_state = PortfolioState(
        t=t_new,
        started=state.started | live,
        terminated=terminated,
        pos=jnp.where(advance, pos, state.pos),
        entry=jnp.where(advance, entry, state.entry),
        cash_delta=jnp.where(advance, cash, state.cash_delta),
        equity_delta=equity_delta,
        prev_equity_delta=prev_equity_delta,
        commission_paid=jnp.where(advance, commission_paid, state.commission_paid),
        trade_count=jnp.where(advance, trade_count, state.trade_count),
        pending_active=pending_active,
        pending_target=pending_target,
        blocked_margin=state_blocked,
    )
    obs = build_obs(new_state, data, cfg, params)
    info = {
        "equity": equity,
        "equity_delta": equity_delta,
        "positions": jnp.sign(new_state.pos).astype(jnp.int32),
        "position_units": new_state.pos,
        "bar_index": t_new + 1,
        "trades": new_state.trade_count,
        "commission_paid": new_state.commission_paid,
        "blocked_margin": new_state.blocked_margin,
    }
    return new_state, obs, reward, terminated, info


# ---------------------------------------------------------------------------
class PortfolioEnvironment:
    """Host-side binding: pair CSVs -> jitted portfolio reset/step."""

    def __init__(self, config: Dict[str, Any]):
        files = config.get("portfolio_files")
        if not files:
            raise ValueError("portfolio env requires config['portfolio_files']")
        account = str(config.get("account_currency", "USD"))
        pairs, aligned = load_portfolio_frames(
            dict(files),
            date_column=str(config.get("date_column", "DATE_TIME")),
            price_column=str(config.get("price_column", "CLOSE")),
            max_rows=config.get("max_rows"),
        )
        self.pairs = pairs
        w = int(config.get("window_size", 32))
        self.data = build_portfolio_data(
            pairs, aligned, window_size=w, account_currency=account
        )
        self.cfg = PortfolioConfig(
            n_pairs=len(pairs),
            n_bars=self.data.n_bars,
            window_size=w,
            margin_rate=float(config.get("margin_rate", 0.0)),
        )
        d = self.cfg.dtype
        initial_cash = float(config.get("initial_cash", 10000.0))
        min_eq = config.get("min_equity")
        sizes = config.get("portfolio_position_sizes")
        if sizes is None:
            sizes = [float(config.get("position_size", 1.0))] * len(pairs)
        self.params = PortfolioParams(
            initial_cash=jnp.asarray(initial_cash, d),
            position_size=jnp.asarray(sizes, d),
            commission=jnp.asarray(float(config.get("commission", 0.0)), d),
            slippage=jnp.asarray(
                float(config.get("slippage_perc", config.get("slippage", 0.0)) or 0.0), d
            ),
            leverage=jnp.asarray(float(config.get("leverage", 1.0)), d),
            min_equity=jnp.asarray(
                float(initial_cash * 0.01 if min_eq is None else min_eq), d
            ),
            reward_scale=jnp.asarray(float(config.get("reward_scale", 1.0)), d),
        )

    def reset(self):
        return _jit_p_reset(self.cfg, self.params, self.data)

    def step(self, state, actions):
        return _jit_p_step(self.cfg, self.params, self.data, state, actions)


_jit_p_reset = jax.jit(reset, static_argnums=0)
_jit_p_step = jax.jit(step, static_argnums=0)
