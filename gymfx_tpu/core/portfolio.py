"""Multi-pair portfolio environment (BASELINE.json config 5).

New capability: the reference env trades a single instrument; its only
multi-asset surface is the Nautilus replay fixture
(reference simulation_engines/bakeoff.py:26-101, margin preflight with
cross-currency conversion nautilus_adapter.py:191-237).  Here the
portfolio env is the single-pair kernel itself, ``jax.vmap``-ed over an
instrument axis — NOT a simplified sibling:

  * each pair advances through the REAL ``core.env.step`` (pending
    fills at next open, bracket SL/TP against the bar's H/L under the
    profile's collision + limit-fill policies, ATR strategy with
    session/weekend filter, event-context overlay, rollover financing,
    full diagnostics) with its own quote-currency ledger and its own
    ``EnvParams`` — per-pair execution-cost profiles are just different
    rows of the stacked params pytree;
  * one shared account couples the pairs: per-bar quote->account
    conversion factors (direct pairs convert by rule, crosses bridge
    through another pair in the book — same rule as the reconciliation
    oracle, simulation/oracle.py), account-level margin preflight over
    the opening margin of ALL newly-submitted orders (greedy in pair
    order, deterministic), account-level reward kernels
    (pnl/sharpe/dd with the explicit carries of core/rewards.py), the
    stage-B force-close penalty, and account-level bankruptcy
    termination.

Accounting note: each pair's ledger lives in its quote currency and is
converted at the CURRENT bar's rate when the account is marked, so
realized pnl "parked" in a foreign quote currency floats with FX until
the episode ends — how a real multi-currency margin account behaves
before sweeps.  The replay engine (like Nautilus) converts realized pnl
at fill time; the difference is conversion drift on already-realized
pnl.  ``sweep_realized_pnl: true`` switches the account to the
replay/fill-time semantics: each bar's realized increment is banked in
the account currency at that bar's rate, bounding the residual to one
bar's FX move on the increment (tests/test_portfolio.py drift tests);
the default keeps the float-with-FX behavior the oracle reconciles,
whose drift is exactly sum(realized_q * (conv_now - conv_then)) — see
DIVERGENCES.md.

Static-policy constraint: per-pair profiles may differ in every numeric
field (commission, spread, slippage, margin), but fields that select
compiled code paths (collision policy, limit-fill policy, margin model,
financing) must agree across pairs — one XLA program serves all pairs.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from gymfx_tpu.core import broker
from gymfx_tpu.core import env as env_core
from gymfx_tpu.core import rewards
from gymfx_tpu.core.types import (
    EXEC_DIAG_INDEX,
    EnvConfig,
    EnvParams,
    EnvState,
    initial_state,
    make_env_config,
    make_env_params,
)
from gymfx_tpu.data.feed import MarketData, MarketDataset


class PortfolioData(NamedTuple):
    pair: MarketData   # every leaf stacked with a leading (I,) axis
    conv: Any          # (n, I) quote->account conversion factor

    @property
    def n_bars(self) -> int:
        return int(self.pair.close.shape[1])

    @property
    def n_pairs(self) -> int:
        return int(self.pair.close.shape[0])

    # (n, I) convenience views matching the portfolio layout
    @property
    def open(self):
        return self.pair.open.T

    @property
    def high(self):
        return self.pair.high.T

    @property
    def low(self):
        return self.pair.low.T

    @property
    def close(self):
        return self.pair.close.T


@dataclasses.dataclass(frozen=True)
class PortfolioConfig:
    n_pairs: int
    n_bars: int
    window_size: int
    pair_cfg: EnvConfig    # inner per-pair kernel config
    acct_cfg: EnvConfig    # account-level reward/penalty config
    enforce_margin_preflight: bool = False
    enforce_margin_closeout: bool = False
    margin_model: str = "leveraged"
    # opt-in (``sweep_realized_pnl``): convert each bar's REALIZED pnl
    # increment to the account currency at that bar's rate and bank it,
    # instead of letting realized pnl float in the quote currency until
    # episode end — the replay/Nautilus fill-time conversion semantics
    # (bounded residual: one bar's FX move on the increment, vs the
    # whole episode's move on the balance).  Default off = the
    # real-margin-account behavior the oracle reconciles.
    sweep_realized_pnl: bool = False
    dtype: Any = jnp.float32


class PortfolioParams(NamedTuple):
    pair: EnvParams        # every leaf (I,); margin_init is per-pair here
    acct: EnvParams        # scalars (account currency)


class PortfolioState(NamedTuple):
    pairs: EnvState        # every leaf with a leading (I,) axis
    acct: EnvState         # scalar account-level carry
    # realized-pnl sweep carries (used when cfg.sweep_realized_pnl; zero
    # otherwise): account-currency bank of swept realized pnl, and each
    # pair's last-seen realized balance delta (quote currency)
    swept_realized: Any = 0.0      # scalar, account currency
    prev_realized_q: Any = 0.0     # (I,) quote currency


# ---------------------------------------------------------------------------
# host-side data loading
# ---------------------------------------------------------------------------
def load_portfolio_frames(
    files: Dict[str, str],
    *,
    date_column: str = "DATE_TIME",
    price_column: str = "CLOSE",
    max_rows: Optional[int] = None,
) -> Tuple[List[str], Dict[str, pd.DataFrame]]:
    """Load and time-align several pair CSVs on their shared timestamps
    (inner join).  Returns (pair names, per-pair aligned frames)."""
    frames: Dict[str, pd.DataFrame] = {}
    for pair, path in files.items():
        df = pd.read_csv(path, nrows=max_rows)
        df[date_column] = pd.to_datetime(df[date_column], errors="coerce")
        df = df.dropna(subset=[date_column]).set_index(date_column)
        for col in ("OPEN", "HIGH", "LOW", "CLOSE"):
            if col not in df.columns:
                df[col] = df[price_column]
        frames[pair] = df
    common = None
    for df in frames.values():
        common = df.index if common is None else common.intersection(df.index)
    if common is None or len(common) < 3:
        raise ValueError("portfolio pairs share too few timestamps")
    aligned = {pair: df.loc[common] for pair, df in frames.items()}
    return list(files.keys()), aligned


def build_conversion_factors(
    pairs: Sequence[str],
    closes: np.ndarray,          # (n, I) float64
    account_currency: str = "USD",
) -> np.ndarray:
    """(n, I) quote-currency -> account-currency factors; crosses bridge
    through another pair in the book that quotes/bases the account
    currency (same direct-pair rule as the reconciliation oracle)."""
    n = closes.shape[0]
    parsed = [p.replace("/", "_").split("_", 1) for p in pairs]
    conv = np.ones((n, len(pairs)))
    for i, (base, quote) in enumerate(parsed):
        if quote == account_currency:
            conv[:, i] = 1.0
        elif base == account_currency:
            conv[:, i] = 1.0 / closes[:, i]
        else:
            bridge = None
            for j, (b2, q2) in enumerate(parsed):
                if b2 == quote and q2 == account_currency:
                    bridge = closes[:, j]          # quote/ACC price
                    break
                if b2 == account_currency and q2 == quote:
                    bridge = 1.0 / closes[:, j]    # ACC/quote price inverted
                    break
            if bridge is None:
                raise ValueError(
                    f"pair {pairs[i]}: no direct conversion from {quote} to "
                    f"{account_currency} and no bridging pair in the book"
                )
            conv[:, i] = bridge
    return conv


# ---------------------------------------------------------------------------
# pure kernel: reset / step
# ---------------------------------------------------------------------------
def reset(cfg: PortfolioConfig, params: PortfolioParams, data: PortfolioData):
    pair_reset = lambda p, d: env_core.reset(cfg.pair_cfg, p, d)  # noqa: E731
    pairs, obs_i = jax.vmap(pair_reset)(params.pair, data.pair)
    acct = initial_state(cfg.acct_cfg)
    eq = jnp.sum(data.conv[0] * pairs.equity_delta).astype(acct.equity_delta.dtype)
    acct = acct._replace(
        equity_delta=eq,
        prev_equity_delta=eq,
        peak_equity_delta=jnp.maximum(acct.peak_equity_delta, eq),
    )
    state = PortfolioState(
        pairs=pairs, acct=acct,
        swept_realized=jnp.zeros((), cfg.dtype),
        prev_realized_q=jnp.zeros((cfg.n_pairs,), cfg.dtype),
    )
    return state, _portfolio_obs(obs_i, state, data, cfg, params)


def step(cfg: PortfolioConfig, params: PortfolioParams, data: PortfolioData,
         state: PortfolioState, actions):
    """actions: (I,) ints in {0=hold, 1=long, 2=short, 3=flat}."""
    was_terminated = state.acct.terminated
    live = ~was_terminated

    # terminated account -> per-pair steps become no-ops (their own
    # terminated flags were set when the account terminated)
    pair_step = lambda p, d, s, a: env_core.step(  # noqa: E731
        cfg.pair_cfg, p, d, s, a
    )
    pairs, obs_i, _pr, _pd, info_i = jax.vmap(pair_step)(
        params.pair, data.pair, state.pairs,
        jnp.asarray(actions, jnp.int32).reshape(cfg.n_pairs),
    )

    t_new = pairs.t[0]
    conv = data.conv[t_new]                        # (I,)
    close = data.pair.close[jnp.arange(cfg.n_pairs), t_new]  # (I,)

    # ---- account-level margin preflight over newly-submitted orders ----
    # (the inner kernel's own preflight is disabled; the account gate
    # sees the whole book).  Greedy in pair order: each order is granted
    # only if the margin GRANTED so far plus its own still fits the free
    # realized balance — denied orders reserve nothing, matching a
    # sequential broker (and the replay engine) processing one order at
    # a time.  Deterministic regardless of XLA scheduling.
    if cfg.enforce_margin_preflight:
        opening = broker.opening_units(pairs.pos, pairs.pending_target)  # (I,)
        required_q = opening * close * params.pair.margin_init
        if cfg.margin_model == "leveraged":
            required_q = required_q / jnp.maximum(params.pair.leverage, 1e-12)
        required = required_q * conv               # account currency
        if cfg.sweep_realized_pnl:
            # fill-time-conversion mode: free balance = banked realized
            # pnl (historic rates) + this bar's unbanked increment at the
            # current rate — the same measure the equity mark below uses,
            # so margin granted never diverges from the account's equity
            realized_q = pairs.cash_delta + pairs.pos * pairs.entry_price
            free = (
                params.acct.initial_cash
                + state.swept_realized
                + jnp.sum(conv * (realized_q - state.prev_realized_q))
            )
        else:
            free = params.acct.initial_cash + jnp.sum(
                conv * (pairs.cash_delta + pairs.pos * pairs.entry_price)
            )
        want = pairs.pending_active & (opening > 0)

        def grant_body(granted_sum, req_want):
            req, wants = req_want
            ok = wants & (granted_sum + req <= free)
            return granted_sum + jnp.where(ok, req, 0.0), ok

        _, granted = jax.lax.scan(
            grant_body, jnp.zeros_like(free), (required, want)
        )
        denied = want & ~granted
        pairs = pairs._replace(
            pending_active=pairs.pending_active & ~denied,
            pending_target=jnp.where(denied, 0.0, pairs.pending_target),
            pending_sl=jnp.where(denied, 0.0, pairs.pending_sl),
            pending_tp=jnp.where(denied, 0.0, pairs.pending_tp),
            exec_diag=pairs.exec_diag.at[:, EXEC_DIAG_INDEX["preflight_denied"]].add(
                denied.astype(jnp.int32)
            ),
        )

    # ---- account equity mark + reward ---------------------------------
    acct = state.acct
    n = cfg.n_bars
    advance = live & acct.started & (acct.t < n - 1)
    exhausted = live & acct.started & (acct.t >= n - 1)
    marking = advance | (live & ~acct.started)

    if cfg.sweep_realized_pnl:
        # fill-time conversion semantics (replay/Nautilus): each bar's
        # realized increment is banked at THAT bar's rate; only the
        # unrealized leg floats with FX.  realized_q = cash + pos*entry
        # (the position's entry notional cancels the open cash outlay),
        # unrealized_q = pos * (close - entry).
        realized_q = (pairs.cash_delta + pairs.pos * pairs.entry_price).astype(
            state.prev_realized_q.dtype
        )
        unrealized_q = pairs.equity_delta - realized_q
        swept = state.swept_realized + jnp.sum(
            conv * (realized_q - state.prev_realized_q)
        ).astype(state.swept_realized.dtype)
        swept = jnp.where(marking, swept, state.swept_realized)
        prev_realized_q = jnp.where(
            marking, realized_q, state.prev_realized_q
        )
        eq = (swept + jnp.sum(conv * unrealized_q)).astype(
            acct.equity_delta.dtype
        )
    else:
        swept = state.swept_realized
        prev_realized_q = state.prev_realized_q
        eq = jnp.sum(conv * pairs.equity_delta).astype(acct.equity_delta.dtype)
    acct = acct._replace(
        t=t_new,
        started=acct.started | live,
        prev_equity_delta=jnp.where(marking, acct.equity_delta, acct.prev_equity_delta),
        equity_delta=jnp.where(marking, eq, acct.equity_delta),
        pos=jnp.sum(jnp.abs(pairs.pos)).astype(acct.pos.dtype),
    )
    peak = jnp.where(marking, jnp.maximum(acct.peak_equity_delta, acct.equity_delta),
                     acct.peak_equity_delta)
    money_down = peak - acct.equity_delta
    peak_equity = params.acct.initial_cash + peak
    acct = acct._replace(
        peak_equity_delta=peak,
        max_drawdown_money=jnp.maximum(acct.max_drawdown_money, money_down),
        max_drawdown_pct=jnp.maximum(
            acct.max_drawdown_pct,
            jnp.where(peak_equity > 0, money_down / peak_equity * 100.0, 0.0),
        ),
    )

    # ---- account maintenance-margin closeout ---------------------------
    # equity marked below the book's total maintenance requirement
    # force-flattens EVERY pair at the next bar's open (deterministic
    # whole-book liquidation; OANDA-style partial closeouts would be
    # order-dependent).  Forced flats REPLACE any pending orders.
    if cfg.enforce_margin_closeout:
        maint = jnp.sum(
            broker.maintenance_margin(pairs.pos, close, params.pair,
                                      cfg.margin_model) * conv
        )
        equity_now = params.acct.initial_cash + acct.equity_delta
        # gated on `advance` like the single-pair kernel (core/env.py
        # step 4b): the exhausted step would double-count the breach
        breach = advance & jnp.any(pairs.pos != 0) & (equity_now < maint)
        held = breach & (pairs.pos != 0)
        pairs = pairs._replace(
            pending_active=jnp.where(breach, pairs.pos != 0, pairs.pending_active),
            pending_target=jnp.where(breach, 0.0, pairs.pending_target),
            pending_sl=jnp.where(breach, 0.0, pairs.pending_sl),
            pending_tp=jnp.where(breach, 0.0, pairs.pending_tp),
            pending_forced=pairs.pending_forced | held,
            exec_diag=pairs.exec_diag.at[:, EXEC_DIAG_INDEX["margin_closeouts"]].add(
                held.astype(jnp.int32)
            ),
        )

    acct, base_reward = rewards.compute_reward(acct, cfg.acct_cfg, params.acct, live)
    fc_row = jnp.minimum(t_new + 1, n - 1)
    penalty = rewards.force_close_penalty(
        acct, data.pair.force_close[0, fc_row], cfg.acct_cfg, params.acct
    )
    penalty = jnp.where(live, penalty, 0.0)
    reward = base_reward - penalty

    # ---- account termination ------------------------------------------
    equity = params.acct.initial_cash + acct.equity_delta
    broke = equity <= params.acct.min_equity
    terminated = was_terminated | exhausted | (live & broke)
    from gymfx_tpu.core.types import TERMINATION_BANKRUPT, TERMINATION_EXHAUSTED

    reason_now = jnp.where(
        live & broke,
        jnp.int32(TERMINATION_BANKRUPT),
        jnp.where(exhausted, jnp.int32(TERMINATION_EXHAUSTED), jnp.int32(0)),
    )
    acct = acct._replace(
        terminated=terminated,
        termination_reason=jnp.where(
            was_terminated, acct.termination_reason, reason_now
        ).astype(jnp.int32),
    )
    pairs = pairs._replace(terminated=pairs.terminated | terminated)

    new_state = PortfolioState(
        pairs=pairs, acct=acct,
        swept_realized=swept, prev_realized_q=prev_realized_q,
    )
    obs = _portfolio_obs(obs_i, new_state, data, cfg, params)
    info = _portfolio_info(info_i, new_state, conv, cfg, params)
    info["reward"] = reward
    info["force_close_reward_penalty"] = penalty
    return new_state, obs, reward, terminated, info


def _portfolio_obs(obs_i: Dict[str, Any], state: PortfolioState,
                   data: PortfolioData, cfg: PortfolioConfig,
                   params: PortfolioParams) -> Dict[str, Any]:
    """Vmapped per-pair obs blocks -> portfolio layout: window blocks are
    (window, I) (bars as the leading axis, pairs as channels), per-pair
    scalars are (I,), account scalars are (1,)."""
    obs: Dict[str, Any] = {}
    if "features" in obs_i:
        f = obs_i["features"]                  # (I, w, F)
        obs["features"] = jnp.transpose(f, (1, 0, 2)).reshape(
            f.shape[1], -1
        )
    if "prices" in obs_i:
        obs["prices"] = obs_i["prices"].T      # (w, I)
        obs["returns"] = obs_i["returns"].T
    if "position" in obs_i:
        obs["position"] = obs_i["position"][:, 0]  # (I,)
        obs["unrealized_pnl_norm"] = obs_i["unrealized_pnl_norm"][:, 0]
    initial = jnp.where(params.acct.initial_cash == 0, 1.0, params.acct.initial_cash)
    obs["equity_norm"] = jnp.asarray(
        [state.acct.equity_delta / initial], jnp.float32
    )
    obs["steps_remaining_norm"] = jnp.asarray(
        [jnp.maximum(0, cfg.n_bars - (state.acct.t + 1)) / max(1, cfg.n_bars)],
        jnp.float32,
    )
    # shared-timestamp blocks (stage-B / calendar) are identical across
    # pairs, so pair 0's copy is surfaced; that collapse is applied ONLY
    # to the known timestamp-derived keys — anything else (a registered
    # obs kernel's block may be per-pair state) keeps its full (I, ...)
    # array.  Account-DEPENDENT calendar entries are excluded and
    # re-emitted from the account ledger below — pair 0's quote-currency
    # view would be wrong for the book.
    from gymfx_tpu.data.calendar import FORCE_CLOSE_FEATURE_KEYS
    from gymfx_tpu.core.obs import CALENDAR_OBS_KEYS

    account_dependent = ("margin_available_norm", "margin_closeout_percent")
    shared_keys = set(FORCE_CLOSE_FEATURE_KEYS) | set(CALENDAR_OBS_KEYS)
    handled = {
        "position", "unrealized_pnl_norm", "equity_norm",
        "steps_remaining_norm", *account_dependent,
    }
    for key, val in obs_i.items():
        if key in obs or key in handled:
            continue
        obs[key] = val[0] if key in shared_keys else val
    if "margin_available_norm" in obs_i:
        # account-level margin ratio from the real book: total
        # maintenance requirement over account equity (1.0 = liquidation
        # boundary), mirroring the single-pair ledger value
        # (core/broker.py margin_closeout_percent)
        t = state.acct.t
        close = data.pair.close[jnp.arange(cfg.n_pairs), t]
        conv = data.conv[t]
        maint = jnp.sum(
            broker.maintenance_margin(state.pairs.pos, close, params.pair,
                                      cfg.margin_model) * conv
        )
        equity = params.acct.initial_cash + state.acct.equity_delta
        pct = jnp.where(equity > 0, maint / jnp.maximum(equity, 1e-30), 100.0)
        pct = jnp.where(jnp.any(state.pairs.pos != 0), pct, 0.0)
        obs["margin_closeout_percent"] = jnp.clip(pct, 0.0, 100.0)[None].astype(
            jnp.float32
        )
        obs["margin_available_norm"] = jnp.asarray(
            [(params.acct.initial_cash + state.acct.equity_delta) / initial],
            jnp.float32,
        )
    return obs


def _portfolio_info(info_i: Dict[str, Any], state: PortfolioState, conv,
                    cfg: PortfolioConfig, params: PortfolioParams) -> Dict[str, Any]:
    pairs = state.pairs
    equity = params.acct.initial_cash + state.acct.equity_delta
    info = {
        "equity": equity,
        "equity_delta": state.acct.equity_delta,
        "positions": jnp.sign(pairs.pos).astype(jnp.int32),
        "position_units": pairs.pos,
        "bar_index": state.acct.t + 1,
        "trades": jnp.sum(pairs.trade_count).astype(jnp.int32),
        "commission_paid": jnp.sum(conv * pairs.commission_paid),
        "blocked_margin": jnp.sum(
            pairs.exec_diag[:, EXEC_DIAG_INDEX["preflight_denied"]]
        ).astype(jnp.int32),
        "margin_closeouts": jnp.sum(
            pairs.exec_diag[:, EXEC_DIAG_INDEX["margin_closeouts"]]
        ).astype(jnp.int32),
        "bracket_sl": pairs.bracket_sl,
        "bracket_tp": pairs.bracket_tp,
        "pending_active": pairs.pending_active,
        "atr": info_i["atr"],
        "max_drawdown_money": state.acct.max_drawdown_money,
        "max_drawdown_pct": state.acct.max_drawdown_pct,
        "trades_won": jnp.sum(pairs.trades_won).astype(jnp.int32),
        "trades_lost": jnp.sum(pairs.trades_lost).astype(jnp.int32),
    }
    return info


# ---------------------------------------------------------------------------
# host-side binding
# ---------------------------------------------------------------------------
_STATIC_PROFILE_FIELDS = (
    "intrabar_collision_policy",
    "limit_fill_policy",
    "margin_model",
    "financing_enabled",
    "enforce_margin_preflight",
)


class PortfolioEnvironment:
    """Host-side binding: pair CSVs -> jitted portfolio reset/step."""

    def __init__(self, config: Dict[str, Any],
                 split: Optional[Tuple[str, float]] = None):
        """``split=("train"|"eval", frac)`` applies the chronological
        out-of-sample split AFTER the cross-pair timestamp join: the
        last ``frac`` of the ALIGNED bars is the eval part, so the two
        parts never share a bar on any pair (train/common.py
        build_portfolio_train_eval_envs)."""
        self.config = dict(config)
        account = str(config.get("account_currency", "USD"))
        feed = str(config.get("feed") or "replay").lower()
        from gymfx_tpu.data.compress import validate_compress_mode

        # honor-or-reject: the int16 wire format (data/compress.py)
        # covers single-pair MarketData tapes; portfolio books are
        # PortfolioData pytrees (stacked pair leaves + a conversion
        # matrix) with no compressed form yet
        if validate_compress_mode(config.get("data_compress", "off")) != "off":
            raise ValueError(
                "data_compress applies to single-pair MarketData tapes; "
                "portfolio books (stacked pair leaves + a conversion "
                "matrix) have no compressed form — unset data_compress "
                "for the portfolio env"
            )
        self.curriculum = None
        curriculum_specs = None
        base_config = None
        if feed == "curriculum":
            from gymfx_tpu.data import tapes as tapes_mod

            if split is not None:
                raise ValueError(
                    "feed=curriculum cannot be combined with eval_split "
                    "on the portfolio env (which tape would be cut?); "
                    "evaluate on a held-out book instead"
                )
            curriculum_specs = tapes_mod.parse_tape_specs(config)
            base_config = dict(config)
            # rebind this env to tape 0 — the overlay strips the
            # curriculum keys, so the nested tape builds cannot recurse
            config = tapes_mod.overlay_config(config, curriculum_specs[0])
            self.config = dict(config)
            feed = str(config.get("feed") or "replay").lower()
        if feed == "scengen":
            # correlated multi-asset generation on one shared grid —
            # already aligned, no timestamp join needed
            from gymfx_tpu.scengen.feed import synthesize_portfolio_frames

            pairs, aligned, _flags = synthesize_portfolio_frames(config)
        else:
            files = config.get("portfolio_files")
            if not files:
                raise ValueError(
                    "portfolio env requires config['portfolio_files'] "
                    "(or feed=scengen for a generated book)"
                )
            pairs, aligned = load_portfolio_frames(
                dict(files),
                date_column=str(config.get("date_column", "DATE_TIME")),
                price_column=str(config.get("price_column", "CLOSE")),
                max_rows=config.get("max_rows"),
            )
        self.pairs = pairs
        w = int(config.get("window_size", 32))
        if split is not None:
            part, frac = split
            frac = float(frac)
            if part not in ("train", "eval"):
                raise ValueError(f"split part must be train|eval, got {part!r}")
            if not 0.0 < frac < 1.0:
                raise ValueError(f"eval_split must be in (0, 1), got {frac!r}")
            n_all = len(next(iter(aligned.values())))
            cut = n_all - int(n_all * frac)
            min_bars = w + 2
            if cut < min_bars or n_all - cut < min_bars:
                raise ValueError(
                    f"eval_split={frac} leaves too few aligned bars (train "
                    f"{cut}, eval {n_all - cut}; both need >= {min_bars})"
                )
            sl = slice(0, cut) if part == "train" else slice(cut, None)
            aligned = {p: df.iloc[sl] for p, df in aligned.items()}
        self.timestamps = next(iter(aligned.values())).index
        n = len(next(iter(aligned.values())))
        if n < w + 2:
            raise ValueError("aligned portfolio data too short for the window")

        profiles = self._load_profiles(config, pairs)
        self._check_static_profile_agreement(profiles)
        cfg0 = make_env_config(
            config, n_bars=n, n_features=len(config.get("feature_columns") or []),
            binary_mask=tuple(
                c in set(config.get("feature_binary_columns") or [])
                for c in (config.get("feature_columns") or [])
            ),
            profile=profiles[0],
        )
        # margin backcompat: the old portfolio key 'margin_rate' doubles
        # as margin_init + enforcement flag
        margin_rate = float(config.get("margin_rate", 0.0) or 0.0)
        enforce = bool(cfg0.enforce_margin_preflight or margin_rate > 0)
        enforce_closeout = bool(config.get("enforce_margin_closeout", enforce))
        # the inner kernel runs per-pair with the ACCOUNT-level gates off
        pair_cfg = dataclasses.replace(
            cfg0,
            enforce_margin_preflight=False,
            # margin is an ACCOUNT property: the account-level gates run
            # in portfolio.step; a per-pair closeout on the pair's own
            # quote-currency ledger would double-count the shared cash
            enforce_margin_closeout=False,
            reward="pnl_reward",
            stage_b_force_close_reward_penalty=False,
            allow_flat_action=True,
        )
        acct_cfg = dataclasses.replace(
            cfg0, n_features=0, include_prices=False, include_agent_state=False
        )
        self.cfg = PortfolioConfig(
            n_pairs=len(pairs),
            n_bars=n,
            window_size=w,
            pair_cfg=pair_cfg,
            acct_cfg=acct_cfg,
            enforce_margin_preflight=enforce,
            enforce_margin_closeout=enforce_closeout,
            margin_model=cfg0.margin_model,
            sweep_realized_pnl=bool(config.get("sweep_realized_pnl", False)),
            dtype=cfg0.dtype,
        )

        from gymfx_tpu.core.runtime import (
            load_financing_rates,
            validate_profile_latency,
        )

        financing_rate_data = load_financing_rates(
            config, pair_cfg.financing_enabled
        )

        # per-pair market data through the SAME pipeline as the
        # single-pair env, leaves stacked on a leading pair axis
        datasets = [MarketDataset(aligned[p], config) for p in pairs]
        mds = [
            ds.build_market_data(
                window_size=w,
                feature_columns=tuple(config.get("feature_columns") or ()),
                feature_scaling=str(config.get("feature_scaling", "rolling_zscore")),
                feature_scaling_window=int(config.get("feature_scaling_window", 256)),
                dtype=cfg0.dtype,
                financing_rate_data=financing_rate_data,
                instrument=p,
            )
            for p, ds in zip(pairs, datasets)
        ]
        stacked = MarketData(*(jnp.stack(leaves) for leaves in zip(*mds)))
        closes = np.stack(
            [aligned[p]["CLOSE"].to_numpy(np.float64) for p in pairs], 1
        )
        conv = build_conversion_factors(pairs, closes, account)
        self.data = PortfolioData(
            pair=stacked, conv=jnp.asarray(conv, cfg0.dtype)
        )

        # per-pair params (per-pair profiles + sizes), stacked to (I,)
        sizes = config.get("portfolio_position_sizes")
        if sizes is None:
            sizes = [float(config.get("position_size", 1.0))] * len(pairs)
        overrides = config.get("portfolio_param_overrides") or {}
        per_pair = []
        for i, p in enumerate(pairs):
            cfg_i = dict(config, position_size=float(sizes[i]), min_equity=None)
            if margin_rate > 0 and "margin_init" not in cfg_i:
                cfg_i["margin_init"] = margin_rate  # legacy portfolio key
            cfg_i.update(overrides.get(p) or {})
            params_i = make_env_params(cfg_i, pair_cfg, profile=profiles[i])
            # pair ledgers never terminate on their own equity: the
            # account gates bankruptcy
            params_i = params_i._replace(
                min_equity=jnp.asarray(-1e30, cfg0.dtype)
            )
            per_pair.append(params_i)
        # tree-map (not per-field zip): EnvParams.user may be a nested
        # pytree of registered-kernel parameters
        pair_params = jax.tree.map(lambda *xs: jnp.stack(xs), *per_pair)
        acct_params = make_env_params(dict(config), acct_cfg, profile=profiles[0])
        self.params = PortfolioParams(pair=pair_params, acct=acct_params)

        # honor-or-reject: latency vs the shared bar interval
        bar_ms = datasets[0].bar_interval_ms()
        for prof in profiles:
            validate_profile_latency(prof, bar_ms)
        self.timeframe_hours = datasets[0].timeframe_hours

        if curriculum_specs is not None:
            from gymfx_tpu.data import tapes as tapes_mod

            self.curriculum = tapes_mod.PortfolioCurriculumSampler(
                base_config, curriculum_specs, base_env=self
            )

    @property
    def n_bars(self) -> int:
        return self.cfg.n_bars

    @staticmethod
    def _load_profiles(config: Dict[str, Any], pairs: List[str]):
        from gymfx_tpu.core.types import _parse_profile

        shared = _parse_profile(config)
        per_pair_raw = config.get("portfolio_profiles") or {}
        profiles = []
        for p in pairs:
            raw = per_pair_raw.get(p)
            if raw is None:
                profiles.append(shared)
            else:
                profiles.append(_parse_profile({"execution_cost_profile": raw}))
        return profiles

    @staticmethod
    def _check_static_profile_agreement(profiles):
        bound = [p for p in profiles if p is not None]
        if not bound:
            return
        if len(bound) != len(profiles):
            # a partial binding would silently apply pair 0's static
            # policy (or none) to the profile-less pairs — reject
            raise ValueError(
                "portfolio_profiles must cover every pair (or bind one "
                "shared execution_cost_profile): profiles must never be "
                "silently degraded"
            )
        head = bound[0]
        for other in bound[1:]:
            for field in _STATIC_PROFILE_FIELDS:
                if getattr(other, field) != getattr(head, field):
                    raise ValueError(
                        "per-pair profiles must agree on static policy field "
                        f"{field!r} (one XLA program serves all pairs): "
                        f"{getattr(head, field)!r} != {getattr(other, field)!r}"
                    )

    def reset(self):
        return _jit_p_reset(self.cfg, self.params, self.data)

    def step(self, state, actions):
        return _jit_p_step(self.cfg, self.params, self.data, state, actions)


_jit_p_reset = jax.jit(reset, static_argnums=0)
_jit_p_step = jax.jit(step, static_argnums=0)
