"""Observation / info assembly (pure, static-structure dicts).

Obs blocks and semantics mirror the reference Dict observation space
(reference app/env.py:31-90 and the preprocessor family):
  features   (window, n_features) leakage-safe scaled feature window
             (reference preprocessor_plugins/feature_window_preprocessor.py)
  prices     (window,) close window, front-padded with the first value
  returns    (window,) first differences, 0 for the first element
             (reference preprocessor_plugins/default_preprocessor.py:47-53)
  position / equity_norm / unrealized_pnl_norm / steps_remaining_norm
             (1,) agent-state scalars
plus the optional stage-B force-close block (reference app/env.py:480-486)
and the OANDA calendar block (reference app/env.py:487-507).

Indexing parity note: the window at step ``t`` covers rows
[bar_index - window, bar_index) where bar_index = t+1 (the current row
inclusive), while calendar/force-close/event features are read at row
min(bar_index, n-1) — one bar ahead, the bar the pending action will
trade on — exactly as the reference indexes them
(reference app/env.py:465,481,489,369).
"""
from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp

from gymfx_tpu.data.calendar import CALENDAR_FEATURE_KEYS, FORCE_CLOSE_FEATURE_KEYS
from gymfx_tpu.data.feed import MarketData
from gymfx_tpu.core.types import (
    ACTION_DIAG_KEYS,
    EXEC_DIAG_KEYS,
    EnvConfig,
    EnvParams,
    EnvState,
)

# Calendar obs keys exclude is_no_trade_window (info-only in the obs dict,
# reference app/env.py:490-501) and add the two margin placeholders.
CALENDAR_OBS_KEYS = tuple(k for k in CALENDAR_FEATURE_KEYS if k != "is_no_trade_window")


def scale_feature_window(win, mean, std, neutral, cfg: "EnvConfig"):
    """THE O(1) leakage-safe scaling of one (window, F) feature block:
    z-score against the precomputed strictly-past moments, binary
    passthrough columns, clip, nan_to_num — in exactly this op order.

    Both obs producers go through this one definition — the training env
    (:func:`build_obs`) and the serving featurizer
    (serve/features.py, via the numpy twin below) — which is what makes
    serving observations bit-identical to training observations."""
    import jax.numpy as xp

    scaled = xp.where(neutral, 0.0, (win - mean) / std)
    if any(cfg.binary_mask):
        mask = xp.asarray(cfg.binary_mask, dtype=bool)
        scaled = xp.where(mask[None, :], win, scaled)
    clip = cfg.feature_clip
    if clip and clip > 0:
        scaled = xp.clip(scaled, -clip, clip)
    scaled = xp.nan_to_num(
        scaled, nan=0.0, posinf=clip or 0.0, neginf=-(clip or 0.0)
    )
    return scaled.astype(xp.float32)


def scale_feature_window_host(win, mean, std, neutral, cfg: "EnvConfig"):
    """Numpy twin of :func:`scale_feature_window` for the serving hot
    path (one request = one window; a device round trip per request
    would dominate the latency budget).  Every op is the elementwise
    IEEE-754 single-precision counterpart of the jnp version in the
    same order, so the result is bit-identical
    (tests/test_serve_features.py pins the two against each other)."""
    import numpy as xp

    win = xp.asarray(win, xp.float32)
    mean = xp.asarray(mean, xp.float32)
    std = xp.asarray(std, xp.float32)
    scaled = xp.where(neutral, xp.float32(0.0), (win - mean) / std)
    if any(cfg.binary_mask):
        mask = xp.asarray(cfg.binary_mask, dtype=bool)
        scaled = xp.where(mask[None, :], win, scaled)
    clip = cfg.feature_clip
    if clip and clip > 0:
        scaled = xp.clip(scaled, xp.float32(-clip), xp.float32(clip))
    scaled = xp.nan_to_num(
        scaled, nan=0.0, posinf=clip or 0.0, neginf=-(clip or 0.0)
    )
    return scaled.astype(xp.float32)


def _scaled_features(win, mean, std, neutral, cfg: "EnvConfig"):
    """Rollout feature-scaling dispatch (`rollout_obs_kernel` knob,
    docs/performance.md): "on" routes through the fused pallas per-step
    kernel on TPU and falls back to the plain-XLA oracle elsewhere;
    "interpret" forces pallas interpret mode on any backend (the CPU
    parity tests); "off" is the plain-XLA path everywhere.  All three
    are bitwise-identical by construction (the kernel body reproduces
    :func:`scale_feature_window` op for op; tests/test_ops.py +
    tests/test_rollout_obs_kernel.py pin it)."""
    mode = getattr(cfg, "rollout_obs_kernel", "off")
    if mode != "off":
        import jax

        on_tpu = jax.default_backend() == "tpu"
        if mode == "interpret" or on_tpu:
            from gymfx_tpu.ops.window_zscore import fused_step_obs

            return fused_step_obs(
                win, mean, std, neutral,
                binary_mask=cfg.binary_mask, clip=cfg.feature_clip,
                interpret=(mode == "interpret") or not on_tpu,
            )
        # "on" off-TPU: the plain-XLA fallback below
    return scale_feature_window(win, mean, std, neutral, cfg)


def build_obs(
    state: EnvState, data: MarketData, cfg: EnvConfig, params: EnvParams
) -> Dict[str, Any]:
    w = cfg.window_size
    n = cfg.n_bars
    step = jnp.minimum(state.t + 1, n)  # == bar_index, clamped
    r0 = data.row0  # shard-local rebase for streamed data (0 resident)
    obs: Dict[str, Any] = {}

    if cfg.n_features > 0:
        win = state.feat_window  # streaming carry == padded[step : step+w]
        mean = data.feat_mean[step - r0]
        std = data.feat_std[step - r0]
        neutral = data.feat_neutral[step - r0]
        obs["features"] = _scaled_features(win, mean, std, neutral, cfg)

    price = data.close[state.t - r0]
    prices = None
    if cfg.include_prices:
        prices = state.price_window  # streaming carry
        returns = prices - jnp.concatenate([prices[:1], prices[:-1]])
        obs["prices"] = prices.astype(jnp.float32)
        obs["returns"] = returns.astype(jnp.float32)

    if cfg.include_agent_state:
        initial = jnp.where(params.initial_cash == 0, 1.0, params.initial_cash)
        pos_sign = jnp.sign(state.pos)
        ref_price = prices[-1] if prices is not None else price
        unrealized = pos_sign * (price - ref_price) * params.position_size
        obs["position"] = jnp.asarray([pos_sign], dtype=jnp.float32)
        obs["equity_norm"] = jnp.asarray(
            [state.equity_delta / initial], dtype=jnp.float32
        )
        obs["unrealized_pnl_norm"] = jnp.asarray(
            [unrealized / initial], dtype=jnp.float32
        )
        # explicit f32 reciprocal multiply instead of `/ n`: XLA rewrites
        # a constant-divisor division into this multiply at runtime but
        # constant-folds it to the correctly-rounded quotient when the
        # cursor is static (reset_at with literal t0) — the explicit form
        # produces the SAME bits on both paths, and on the serving host
        # twin (serve/features.py)
        import numpy as _np

        remaining = jnp.maximum(0, n - (state.t + 1)) * (
            _np.float32(1.0) / _np.float32(max(1, n))
        )
        obs["steps_remaining_norm"] = jnp.asarray([remaining], dtype=jnp.float32)

    row = jnp.minimum(step, n - 1) - r0
    if cfg.stage_b_force_close_obs:
        fc = data.force_close[row]
        for i, key in enumerate(FORCE_CLOSE_FEATURE_KEYS):
            obs[key] = fc[i][None]

    if cfg.oanda_fx_calendar_obs:
        cal = data.calendar[row]
        cal_map = dict(zip(CALENDAR_FEATURE_KEYS, cal))
        for key in CALENDAR_OBS_KEYS:
            obs[key] = cal_map[key][None]
        initial = jnp.where(params.initial_cash == 0, 1.0, params.initial_cash)
        # real-ledger margin ratio (the reference publishes 0.0 when its
        # bridge lacks a margin account, app/env.py:615-623; here the
        # ledger always has one): maintenance margin / equity, 1.0 = at
        # the liquidation boundary (core/broker.py margin_closeout_percent)
        from gymfx_tpu.core import broker as _broker

        obs["margin_closeout_percent"] = jnp.asarray(
            [_broker.margin_closeout_percent(state, price, params, cfg.margin_model)],
            dtype=jnp.float32,
        )
        obs["margin_available_norm"] = jnp.asarray(
            [(params.initial_cash + state.equity_delta) / initial],
            dtype=jnp.float32,
        )

    for name in cfg.obs_kernels:
        # registered third-party obs blocks (plugins/kernels.py)
        from gymfx_tpu.plugins import kernels as _k

        obs.update(_k.get_obs_kernel(name)(state, data, cfg, params))
    return obs


def build_info(
    state: EnvState,
    data: MarketData,
    cfg: EnvConfig,
    params: EnvParams,
    event_info: Dict[str, Any] | None = None,
) -> Dict[str, Any]:
    n = cfg.n_bars
    r0 = data.row0  # shard-local rebase for streamed data (0 resident)
    info: Dict[str, Any] = {
        "equity": params.initial_cash + state.equity_delta,
        "position": jnp.sign(state.pos).astype(jnp.int32),
        "price": data.close[state.t - r0],
        "bar_index": state.t + 1,
        "total_bars": jnp.asarray(n, dtype=jnp.int32),
        "trades": state.trade_count,
        "commission_paid": state.commission_paid,
        "raw_action_value": state.last_raw_action,
        "coerced_action": state.last_coerced_action,
    }
    for i, key in enumerate(ACTION_DIAG_KEYS):
        info[f"action_diagnostics/{key}"] = state.action_diag[i]
    info["action_diagnostics/raw_abs_sum"] = state.raw_abs_sum
    info["action_diagnostics/raw_min"] = state.raw_min
    info["action_diagnostics/raw_max"] = state.raw_max
    for i, key in enumerate(EXEC_DIAG_KEYS):
        info[f"execution_diagnostics/{key}"] = state.exec_diag[i]
    if event_info:
        info.update(event_info)

    row = jnp.minimum(jnp.minimum(state.t + 1, n), n - 1) - r0
    if cfg.stage_b_force_close_obs:
        fc = data.force_close[row]
        for i, key in enumerate(FORCE_CLOSE_FEATURE_KEYS):
            info[key] = fc[i]
    if cfg.oanda_fx_calendar_obs:
        cal = data.calendar[row]
        for i, key in enumerate(CALENDAR_FEATURE_KEYS):
            info[key] = cal[i]
        initial = jnp.where(params.initial_cash == 0, 1.0, params.initial_cash)
        from gymfx_tpu.core import broker as _broker

        info["margin_closeout_percent"] = _broker.margin_closeout_percent(
            state, data.close[state.t - r0], params, cfg.margin_model
        ).astype(jnp.float32)
        info["margin_available_norm"] = (
            params.initial_cash + state.equity_delta
        ) / initial
    return info
