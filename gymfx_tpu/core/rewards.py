"""Reward kernels with explicit carried state.

The reference reward plugins are stateful Python objects (deque /
peak-equity attributes) that detect episode resets by step-index
regression (reference reward_plugins/sharpe_reward.py:42-45,
dd_penalized_reward.py:38-39).  Here the state is explicit in
``EnvState`` (ring buffer / scalar carries) and reset happens in
``reset()`` — no detection tricks needed under ``lax.scan``.

Kernels (selected statically by EnvConfig.reward):
  pnl_reward           (new-prev)/initial_cash * reward_scale
                       (reference reward_plugins/pnl_reward.py:26-36)
  sharpe_reward        annualized rolling Sharpe of normalized step
                       returns; warmup (<2 samples) -> 0
                       (reference reward_plugins/sharpe_reward.py:37-58)
  dd_penalized_reward  pnl_norm - lambda * drawdown_norm with running
                       peak (reference reward_plugins/dd_penalized_reward.py:31-47)
"""
from __future__ import annotations

import jax.numpy as jnp

from gymfx_tpu.core.types import EnvConfig, EnvParams, EnvState


def compute_reward(
    state: EnvState, cfg: EnvConfig, params: EnvParams, active
):
    """Return (new_state, base_reward).  ``active`` masks carry updates
    (terminated steps must not mutate reward state)."""
    # Work in equity-delta space: (initial + delta) - (initial + delta')
    # in f32 quantizes at ~1e-3 on a 10k account and destroys the ~1e-7
    # per-step normalized returns; the deltas carry full precision.
    initial = jnp.where(params.initial_cash == 0, 1.0, params.initial_cash)
    r_norm = (state.equity_delta - state.prev_equity_delta) / initial

    if cfg.reward == "pnl_reward":
        return state, jnp.where(active, r_norm * params.reward_scale, 0.0)

    from gymfx_tpu.plugins import kernels as _k

    if cfg.reward not in _k.BUILTIN_REWARDS:
        # registered third-party kernel (plugins/kernels.py): traced
        # into the compiled step at this static branch
        return _k.get_reward_kernel(cfg.reward)(state, cfg, params, active)

    if cfg.reward == "sharpe_reward":
        buf = jnp.where(
            active,
            state.reward_buffer.at[state.reward_buffer_idx].set(
                r_norm.astype(state.reward_buffer.dtype)
            ),
            state.reward_buffer,
        )
        idx = jnp.where(
            active, (state.reward_buffer_idx + 1) % cfg.sharpe_window,
            state.reward_buffer_idx,
        )
        n = jnp.where(
            active,
            jnp.minimum(state.reward_buffer_len + 1, cfg.sharpe_window),
            state.reward_buffer_len,
        )
        nf = jnp.maximum(n, 1).astype(buf.dtype)
        mean = jnp.sum(buf) / nf
        # sample variance (ddof=1), over the n live slots (empty slots are 0)
        var = (jnp.sum(buf**2) - nf * mean**2) / jnp.maximum(nf - 1, 1)
        std = jnp.sqrt(jnp.maximum(var, 0.0))
        sharpe = jnp.where(
            (n >= 2) & (std > 0),
            mean / jnp.where(std > 0, std, 1.0)
            * jnp.sqrt(params.annualization_factor),
            0.0,
        )
        new_state = state._replace(
            reward_buffer=buf, reward_buffer_idx=idx, reward_buffer_len=n
        )
        return new_state, jnp.where(active, sharpe, 0.0)

    # dd_penalized_reward — peak tracked in delta space (initialized to
    # -inf, standing in for the reference's raw peak of 0.0, which only
    # differs when equity goes negative; the peak>0 gate covers that).
    peak = jnp.where(
        active,
        jnp.maximum(
            state.reward_peak,
            jnp.maximum(state.equity_delta, state.prev_equity_delta),
        ),
        state.reward_peak,
    )
    peak_positive = (params.initial_cash + peak) > 0
    dd_norm = jnp.where(peak_positive, (peak - state.equity_delta) / initial, 0.0)
    reward = r_norm - params.penalty_lambda * dd_norm
    return state._replace(reward_peak=peak), jnp.where(active, reward, 0.0)


def force_close_penalty(
    state: EnvState, fc_features, cfg: EnvConfig, params: EnvParams
):
    """Stage-B late-Friday exposure penalty (reference app/env.py:639-665)."""
    if not (cfg.stage_b_force_close_obs and cfg.stage_b_force_close_reward_penalty):
        return jnp.zeros_like(state.equity_delta)
    hours_to_fc = fc_features[1]
    in_zone = fc_features[2] > 0
    in_window = (hours_to_fc >= 0.0) & (
        hours_to_fc <= jnp.maximum(params.force_close_penalty_window_hours, 0.0)
    )
    applies = (
        (params.force_close_penalty_coef > 0)
        & (state.pos != 0)
        & (in_zone | in_window)
    )
    # |position| in the reference is the -1/0/+1 bridge sign -> 1 when open
    return jnp.where(applies, params.force_close_penalty_coef, 0.0)
