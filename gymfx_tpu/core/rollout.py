"""Episode rollout: the driver loop as a single ``lax.scan``.

The reference runs a Python while-loop calling
``strategy.decide_action`` then ``env.step`` once per bar over two
thread context switches (reference app/main.py:58-66).  Here the whole
episode is one scanned XLA program; drivers are pure functions and the
rollout is jit/vmap-able (thousands of envs per device) — this is the
throughput path behind the 1M steps/sec target.

Built-in drivers mirror the reference driver modes
(reference strategy_plugins/default_strategy.py:44-54):
  buy_hold  long on the first step, hold after
  flat      always hold
  random    uniform over {0,1,2} per step
  replay    actions from an array, 0 past its end
plus ``policy`` (any callable obs->action) for trained agents.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from gymfx_tpu.core import env as env_core
from gymfx_tpu.core.types import EXEC_DIAG_INDEX, EnvConfig, EnvParams, EnvState
from gymfx_tpu.data.feed import MarketData


class Driver(NamedTuple):
    """A pure action source: carry -> (action, carry)."""

    init: Callable[[], Any]
    act: Callable[[Any, Dict[str, Any], Any, Any], Tuple[Any, Any]]
    # act(carry, obs, step_index, rng_key) -> (action, carry)


# Drivers are static jit arguments (compared by identity), so the
# built-ins are module-level singletons — a fresh Driver per call would
# re-trace and re-compile the whole episode scan on every rollout.
_BUY_HOLD = Driver(
    init=lambda: (),
    act=lambda carry, obs, i, key: (jnp.where(i == 0, 1, 0), carry),
)
_FLAT = Driver(
    init=lambda: (),
    act=lambda carry, obs, i, key: (jnp.zeros((), jnp.int32), carry),
)
_RANDOM = Driver(
    init=lambda: (),
    act=lambda carry, obs, i, key: (
        jax.random.randint(key, (), 0, 3, dtype=jnp.int32),
        carry,
    ),
)


def buy_hold_driver() -> Driver:
    return _BUY_HOLD


def flat_driver() -> Driver:
    return _FLAT


def random_driver() -> Driver:
    return _RANDOM


def replay_driver(actions) -> Driver:
    """Replay a host-provided action sequence; 0 past its end
    (reference default_strategy.py:50-53)."""
    arr = jnp.asarray(actions, dtype=jnp.int32)
    m = arr.shape[0]

    def act(carry, obs, i, key):
        a = jnp.where(i < m, arr[jnp.minimum(i, m - 1)], 0)
        return a, carry

    return Driver(init=lambda: (), act=act)


def policy_driver(apply_fn: Callable[..., Any], policy_params) -> Driver:
    """Wrap a policy network; apply_fn(policy_params, obs, rng) -> action."""

    def act(carry, obs, i, key):
        return apply_fn(policy_params, obs, key), carry

    return Driver(init=lambda: (), act=act)


DRIVERS = {
    "buy_hold": buy_hold_driver,
    "flat": flat_driver,
    "random": random_driver,
}


def _make_scan_body(cfg, params, data, driver, collect, offset,
                    collect_dtype=None):
    """The one scan body shared by rollout and rollout_chunked.

    ``collect_dtype`` (None = keep f32) narrows only the float
    *diagnostic* streams — reward and the pending/bracket price
    traces — to cut collected-buffer HBM traffic on long episodes.
    equity_delta/equity stay full precision (metrics derive equity
    from the delta in f64), and done/action/position/counters are
    integral and untouched.
    """
    _cd = (lambda x: x) if collect_dtype is None else (
        lambda x: x.astype(collect_dtype))

    def body(carry, i):
        state, obs, rng, dcarry = carry
        rng, key = jax.random.split(rng)
        action, dcarry = driver.act(dcarry, obs, offset + i, key)
        state, obs, reward, done, info = env_core.step(cfg, params, data, state, action)
        if collect:
            out = {
                # equity_delta carries the full precision: adding
                # initial_cash in f32 quantizes at ~1e-3 on a 10k account,
                # so metrics must derive equity from the delta in f64.
                "equity_delta": state.equity_delta,
                "equity": params.initial_cash + state.equity_delta,
                "reward": _cd(reward),
                "done": done,
                "action": jnp.asarray(action, dtype=jnp.int32),
                "position": jnp.sign(state.pos).astype(jnp.int32),
                "trade_count": state.trade_count,
                "bar_index": state.t + 1,
                # the pending order this step recorded (fills at the
                # NEXT bar's open) — the decision stream the replay
                # cross-check re-executes, incl. bracket prices
                # (simulation/crosscheck.py)
                "pending_active": state.pending_active,
                "pending_target": _cd(state.pending_target),
                "pending_sl": _cd(state.pending_sl),
                "pending_tp": _cd(state.pending_tp),
                "pos_units": state.pos,
                # the ACTUAL armed bracket levels and the venue-denial
                # counter after this step: the crosscheck builds each
                # bar's execution path from these instead of inferring
                # them from order history (stale levels / denied fills
                # would otherwise poison later bars' paths)
                "bracket_sl": _cd(state.bracket_sl),
                "bracket_tp": _cd(state.bracket_tp),
                "order_denied": state.exec_diag[
                    EXEC_DIAG_INDEX["order_denied_min_quantity"]
                ],
            }
            if cfg.event_context_execution_overlay:
                out["event_context"] = {
                    k: v for k, v in info.items()
                    if k.startswith("event_context_")
                }
        else:
            out = {}
        return (state, obs, rng, dcarry), out

    return body


@partial(jax.jit, static_argnames=("cfg", "steps", "driver", "collect",
                                   "collect_dtype"))
def rollout(
    cfg: EnvConfig,
    params: EnvParams,
    data: MarketData,
    driver: Driver,
    steps: int,
    rng: Any,
    collect: bool = True,
    driver_carry: Any = None,
    collect_dtype: Any = None,
):
    """Run one episode for ``steps`` env steps (frozen after termination).

    Returns (final_state, outputs) where outputs is a dict of per-step
    arrays (equity, reward, done, action, position) when ``collect``,
    else an empty dict — training collects its own trajectories.

    ``driver`` is a STATIC argument (jit cache key by identity); runtime
    data a driver needs (e.g. policy weights) must flow through
    ``driver_carry``, which is traced — that way re-evaluating with new
    weights reuses the compiled episode instead of retracing it.
    """
    state, obs = env_core.reset(cfg, params, data)
    init_carry = driver.init() if driver_carry is None else driver_carry
    body = _make_scan_body(cfg, params, data, driver, collect, 0,
                           collect_dtype)
    (state, obs, rng, _), outputs = jax.lax.scan(
        body, (state, obs, rng, init_carry), jnp.arange(steps)
    )
    return state, outputs


def episode_step_count(outputs) -> Any:
    """Steps executed before (and including) termination."""
    done = outputs["done"]
    return jnp.where(
        jnp.any(done), jnp.argmax(done) + 1, done.shape[-1]
    )


@partial(
    jax.jit,
    static_argnames=("cfg", "chunk", "driver", "collect", "collect_dtype"),
)
def _rollout_chunk(
    cfg, params, data, driver, chunk, state, obs, rng, dcarry, offset,
    collect=True, collect_dtype=None,
):
    """One fixed-size compiled segment of an episode (see rollout_chunked)."""
    body = _make_scan_body(cfg, params, data, driver, collect, offset,
                           collect_dtype)
    (state, obs, rng, dcarry), outputs = jax.lax.scan(
        body, (state, obs, rng, dcarry), jnp.arange(chunk)
    )
    return state, obs, rng, dcarry, outputs


def rollout_chunked(
    cfg: EnvConfig,
    params: EnvParams,
    data: MarketData,
    driver: Driver,
    steps: int,
    rng: Any,
    collect: bool = True,
    driver_carry: Any = None,
    chunk_size: int = 64,
    collect_dtype: Any = None,
):
    """Episode rollout as a host loop over fixed-size compiled segments.

    Behaviorally identical to ``rollout`` (same scan body), but the
    compiled program length is ``chunk_size`` regardless of ``steps`` —
    long-episode scans can take minutes to compile on some backends
    (observed on remote-compiled TPU), and chunking also reuses one
    executable across every episode length.  At most two compiles per
    (cfg, driver): the chunk and the final remainder.
    """
    state, obs = env_core.reset(cfg, params, data)
    if steps <= 0:
        return state, {}
    dcarry = driver.init() if driver_carry is None else driver_carry
    pieces = []
    done_steps = 0
    while done_steps < steps:
        this = min(chunk_size, steps - done_steps)
        state, obs, rng, dcarry, out = _rollout_chunk(
            cfg, params, data, driver, this, state, obs, rng, dcarry,
            jnp.asarray(done_steps, jnp.int32), collect, collect_dtype,
        )
        if collect:
            pieces.append(out)
        done_steps += this
    if collect:
        outputs = jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=0), *pieces)
    else:
        outputs = {}
    return state, outputs


def rollout_streamed(
    cfg: EnvConfig,
    params: EnvParams,
    streamer,
    driver: Driver,
    steps: int,
    rng: Any,
    collect: bool = True,
    driver_carry: Any = None,
    chunk_size: int = 64,
    collect_dtype: Any = None,
):
    """Episode rollout over a :class:`~gymfx_tpu.data.feed.BarStreamer`.

    Behaviorally identical to ``rollout_chunked`` on the fully-resident
    dataset — same scan body, same cursor sequence; each shard's
    ``row0`` rebases the global bar cursor into shard-local array
    indices — but only two shards ever occupy device memory, and the
    streamer enqueues shard ``t+1``'s host→device transfer before the
    chunks of shard ``t`` are dispatched, so the DMA overlaps compute.

    Every shard has identical static shapes, so all shards share the
    same compiled chunk executable(s).

    Caveat: an episode that terminates mid-stream freezes its cursor at
    the terminal bar; once serving moves to a shard that no longer
    covers the frozen cursor, the (inert, post-``done``) obs/info reads
    clamp to the shard edge and may differ from the resident path.
    Steps at or before termination are bit-identical.
    """
    state = obs = None
    dcarry = driver.init() if driver_carry is None else driver_carry
    pieces = []
    done_steps = 0
    for lo, hi, shard in streamer.iter_shards():
        if state is None:
            # cursor starts at bar 0 — shard 0 always covers it
            state, obs = env_core.reset(cfg, params, shard)
            if steps <= 0:
                return state, {}
        # step i advances the cursor to bar i (i=0 is the warmup step at
        # bar 0): shard serving cursors [lo, hi) runs steps [lo, hi)
        end = steps if hi is None else min(int(hi), steps)
        while done_steps < end:
            this = min(chunk_size, end - done_steps)
            state, obs, rng, dcarry, out = _rollout_chunk(
                cfg, params, shard, driver, this, state, obs, rng, dcarry,
                jnp.asarray(done_steps, jnp.int32), collect, collect_dtype,
            )
            if collect:
                pieces.append(out)
            done_steps += this
        if done_steps >= steps:
            break
    if collect and pieces:
        outputs = jax.tree.map(lambda *xs: jnp.concatenate(xs, axis=0), *pieces)
    else:
        outputs = {}
    return state, outputs
