from gymfx_tpu.core.types import (  # noqa: F401
    EnvConfig,
    EnvParams,
    EnvState,
    make_env_config,
    make_env_params,
)
from gymfx_tpu.core.env import reset, step  # noqa: F401
