"""Strategy kernels: agent action -> pending orders (branch-free).

Three kernels mirror the reference strategy family:
  default           long/short/flip/close flow, no brackets
                    (reference app/bt_bridge.py:175-237)
  direct_fixed_sltp brackets at fixed +/- pips
                    (reference strategy_plugins/direct_fixed_sltp.py:51-77)
  direct_atr_sltp   ATR-scaled brackets with true-range ring buffer,
                    warmup/entry gating, risk modes, distance clamps,
                    relative-volume sizing and session/weekend filter
                    (reference strategy_plugins/direct_atr_sltp.py:133-343)

All orders are *pending*: they execute at the next bar's open (see
core/broker.py).  The hidden action ``3`` force-flattens
(reference app/bt_bridge.py:178-188).
"""
from __future__ import annotations

import jax.numpy as jnp

from gymfx_tpu.core.types import EXEC_DIAG_INDEX, EnvConfig, EnvParams, EnvState


def _inc(diag, key, amount):
    return diag.at[EXEC_DIAG_INDEX[key]].add(
        jnp.asarray(amount, dtype=jnp.int32)
    )


def apply_action(
    state: EnvState,
    action,                  # i32 in {0,1,2,3} (post-overlay)
    o, h, l, c,              # current bar OHLC
    minute_of_week,          # i32, -1 when timestamp invalid
    cfg: EnvConfig,
    params: EnvParams,
    active,                  # bool — whether the strategy acts this step
) -> EnvState:
    a = jnp.asarray(action, dtype=jnp.int32)
    diag = state.exec_diag
    pos = state.pos

    # --- force-flat action (pre-plugin, reference bt_bridge.py:178) ---
    # In the single-pair env action 3 only ever comes from the event
    # overlay, so it counts as an overlay intervention; when the env
    # exposes 3 as a PUBLIC action (allow_flat_action, portfolio env)
    # voluntary flats must not inflate the overlay audit counter.
    force_flat = active & (a == 3) & (pos != 0)
    diag = _inc(diag, "default_orders_submitted", force_flat)
    if not cfg.allow_flat_action:
        diag = _inc(diag, "event_context_forced_flat_orders", force_flat)

    if cfg.strategy == "direct_atr_sltp":
        state, diag, pending = _atr_sltp(
            state, a, o, h, l, c, minute_of_week, cfg, params, diag,
            active & (a != 3),
        )
    elif cfg.strategy == "direct_fixed_sltp":
        pending = _fixed_sltp(state, a, c, params, active & (a != 3))
    elif cfg.strategy == "default":
        diag, pending = _default_flow(state, a, params, diag, active & (a != 3))
    else:
        # registered third-party kernel (plugins/kernels.py): returns
        # (state, (submit, target, sl, tp)); its pending order fills at
        # the next bar's open through the shared broker kernel.  The
        # force-flat counter increments above must reach the kernel's
        # state so they survive its _replace calls.
        from gymfx_tpu.plugins import kernels as _k

        state, pending = _k.get_strategy_kernel(cfg.strategy)(
            state._replace(exec_diag=diag), a, o, h, l, c, minute_of_week,
            cfg, params, active & (a != 3),
        )
        diag = state.exec_diag

    p_active, p_target, p_sl, p_tp = pending
    p_active = jnp.where(force_flat, True, p_active)
    p_target = jnp.where(force_flat, 0.0, p_target)
    p_sl = jnp.where(force_flat, 0.0, p_sl)
    p_tp = jnp.where(force_flat, 0.0, p_tp)

    return state._replace(
        exec_diag=diag,
        pending_active=p_active,
        pending_target=p_target.astype(state.pos.dtype),
        pending_sl=p_sl.astype(state.pos.dtype),
        pending_tp=p_tp.astype(state.pos.dtype),
    )


# ---------------------------------------------------------------------------
def _default_flow(state, a, params, diag, act):
    pos = state.pos
    size = params.position_size
    is_entry = act & ((a == 1) | (a == 2))
    diag = _inc(diag, "entry_actions_seen", is_entry)

    want_long = act & (a == 1)
    want_short = act & (a == 2)
    # long: flip from short (2 orders) or open from flat (1); no pyramiding
    open_long = want_long & (pos <= 0)
    open_short = want_short & (pos >= 0)
    orders_long = jnp.where(want_long & (pos < 0), 2, jnp.where(open_long, 1, 0))
    orders_short = jnp.where(want_short & (pos > 0), 2, jnp.where(open_short, 1, 0))
    diag = _inc(diag, "default_orders_submitted", orders_long + orders_short)

    submit = open_long | open_short
    target = jnp.where(open_long, size, jnp.where(open_short, -size, 0.0))
    zero = jnp.zeros_like(state.pending_sl)
    return diag, (submit, target, zero, zero)


def _fixed_sltp(state, a, c, params, act):
    pos = state.pos
    size = params.position_size
    pip = params.pip_size
    sl_d = params.sl_pips * pip
    tp_d = params.tp_pips * pip

    open_long = act & (a == 1) & (pos <= 0)
    open_short = act & (a == 2) & (pos >= 0)
    submit = open_long | open_short
    target = jnp.where(open_long, size, jnp.where(open_short, -size, 0.0))
    sl = jnp.where(open_long, c - sl_d, jnp.where(open_short, c + sl_d, 0.0))
    tp = jnp.where(open_long, c + tp_d, jnp.where(open_short, c - tp_d, 0.0))
    return submit, target, sl, tp


def _atr_sltp(state, a, o, h, l, c, mow, cfg, params, diag, act):
    d = state.pos.dtype
    pos = state.pos

    # ---- true-range ring buffer (updated every acted bar, even on hold;
    # reference direct_atr_sltp.py:143-155) -------------------------------
    has_prev = state.prev_close > 0
    tr = jnp.where(
        has_prev,
        jnp.maximum(
            h - l, jnp.maximum(jnp.abs(h - state.prev_close), jnp.abs(l - state.prev_close))
        ),
        h - l,
    )
    buf = jnp.where(
        act,
        state.tr_buffer.at[state.tr_idx].set(tr.astype(d)),
        state.tr_buffer,
    )
    tr_idx = jnp.where(act, (state.tr_idx + 1) % cfg.atr_period, state.tr_idx)
    tr_len = jnp.where(
        act, jnp.minimum(state.tr_len + 1, cfg.atr_period), state.tr_len
    )
    prev_close = jnp.where(act, c.astype(d), state.prev_close)
    state = state._replace(
        tr_buffer=buf, tr_idx=tr_idx, tr_len=tr_len, prev_close=prev_close
    )

    # ---- session/weekend filter (minute-of-week window, reference :320-342)
    if cfg.session_filter:
        mow_valid = mow >= 0
        in_entry = jnp.where(
            mow_valid,
            (mow >= params.entry_start_mow) & (mow < params.force_close_mow),
            True,
        )
        in_close_zone = jnp.where(mow_valid, ~in_entry, False)
    else:
        in_entry = jnp.ones_like(act)
        in_close_zone = jnp.zeros_like(act)

    # Force-close bar with an open position: flatten and stop processing
    # (reference :158-166); a flat position in the close zone still counts
    # the entry attempt and then blocks on the session filter.
    session_close = act & in_close_zone & (pos != 0)

    is_entry_action = act & ((a == 1) | (a == 2)) & ~session_close
    diag = _inc(diag, "entry_actions_seen", is_entry_action)

    if cfg.session_filter:
        blocked_session = is_entry_action & ~in_entry
    else:
        blocked_session = jnp.zeros_like(is_entry_action)
    diag = _inc(diag, "blocked_session_filter", blocked_session)

    # ---- ATR + gating ----------------------------------------------------
    ready = tr_len >= cfg.atr_period
    atr = jnp.where(
        tr_len > 0, jnp.sum(buf) / jnp.maximum(tr_len, 1).astype(d), 0.0
    )
    size = _compute_size(state, c, params, cfg)

    attempt = is_entry_action & ~blocked_session & in_entry
    blocked_warmup = attempt & ~ready
    diag = _inc(diag, "blocked_atr_warmup", blocked_warmup)
    blocked_atr = attempt & ready & (atr <= 0.0)
    diag = _inc(diag, "blocked_non_positive_atr", blocked_atr)
    blocked_size = attempt & ready & (atr > 0.0) & (size <= 0.0)
    diag = _inc(diag, "blocked_non_positive_size", blocked_size)
    blocked_price = attempt & ready & (atr > 0.0) & (size > 0.0) & (c <= 0.0)
    diag = _inc(diag, "blocked_non_positive_price", blocked_price)
    can_trade = attempt & ready & (atr > 0.0) & (size > 0.0) & (c > 0.0)

    # ---- SL/TP geometry (risk modes + clamps, reference :203-247) -------
    k_sl_eff, k_tp_eff = _effective_sltp_multiples(cfg, params)
    sl_dist = k_sl_eff * atr
    tp_dist = k_tp_eff * atr
    if cfg.sltp_risk_mode == "margin_aware_atr":
        rel = jnp.maximum(params.rel_volume * params.use_rel_volume, 0.0)
        max_loss = params.max_planned_loss_fraction
        cap_on = (max_loss > 0.0) & (rel > 0.0)
        cap = c * jnp.maximum(max_loss, 0.0) / jnp.maximum(
            rel * jnp.maximum(params.leverage, 1e-12), 1e-30
        )
        sl_dist = jnp.where(cap_on, jnp.minimum(sl_dist, cap), sl_dist)
    floor = params.min_sltp_frac * c
    use_floor = params.min_sltp_frac >= 0
    sl_dist = jnp.where(use_floor, jnp.maximum(sl_dist, floor), sl_dist)
    tp_dist = jnp.where(use_floor, jnp.maximum(tp_dist, floor), tp_dist)
    ceil = params.max_sltp_frac * c
    use_ceil = params.max_sltp_frac >= 0
    sl_dist = jnp.where(use_ceil, jnp.minimum(sl_dist, ceil), sl_dist)
    tp_dist = jnp.where(use_ceil, jnp.minimum(tp_dist, ceil), tp_dist)
    tp_dist = jnp.where(tp_dist >= c, c * 0.5, tp_dist)

    open_long = can_trade & (a == 1) & (pos <= 0)
    open_short = can_trade & (a == 2) & (pos >= 0)
    diag = _inc(diag, "entry_orders_submitted", open_long | open_short)

    submit = open_long | open_short | session_close
    target = jnp.where(
        session_close,
        0.0,
        jnp.where(open_long, size, jnp.where(open_short, -size, 0.0)),
    )
    sl = jnp.where(open_long, c - sl_dist, jnp.where(open_short, c + sl_dist, 0.0))
    tp = jnp.where(open_long, c + tp_dist, jnp.where(open_short, c - tp_dist, 0.0))
    return state, diag, (submit, target, sl, tp)


def _compute_size(state, c, params, cfg):
    """Order size (reference direct_atr_sltp.py:291-311)."""
    cash = params.initial_cash + state.cash_delta
    raw_fx = cash * params.rel_volume * params.leverage
    raw_notional = jnp.where(c > 0, raw_fx / jnp.maximum(c, 1e-30), 0.0)
    raw = raw_notional if cfg.size_mode == "notional" else raw_fx
    sized = jnp.clip(raw, params.min_order_volume, params.max_order_volume)
    return jnp.where(params.use_rel_volume > 0, sized, params.position_size)


def _effective_sltp_multiples(cfg: EnvConfig, params: EnvParams):
    """Risk-mode SL/TP multiples (reference direct_atr_sltp.py:263-289)."""
    k_sl = jnp.maximum(params.k_sl, 0.0)
    k_tp = jnp.maximum(params.k_tp, 0.0)
    if cfg.sltp_risk_mode not in ("rel_volume_aware_atr", "margin_aware_atr"):
        return k_sl, k_tp
    rel = jnp.maximum(params.rel_volume * params.use_rel_volume, 0.0)
    baseline = jnp.maximum(params.baseline_rel_volume, 0.0)
    max_rel = jnp.maximum(baseline + 1e-12, params.max_risk_rel_volume)
    sl_alpha = jnp.clip(params.rel_volume_sl_shrink_alpha, 0.0, 0.95)
    tp_alpha = jnp.clip(params.rel_volume_tp_shrink_alpha, 0.0, 0.95)
    min_k_sl = jnp.maximum(params.min_k_sl, 0.0)
    min_rr = jnp.maximum(params.min_reward_risk_ratio, 0.0)

    progress = jnp.clip((rel - baseline) / (max_rel - baseline), 0.0, 1.0)
    shrink = rel > baseline
    k_sl_eff = jnp.where(
        shrink, jnp.maximum(min_k_sl, k_sl * (1.0 - sl_alpha * progress)), k_sl
    )
    k_tp_eff = jnp.where(shrink, k_tp * (1.0 - tp_alpha * progress), k_tp)
    k_tp_eff = jnp.maximum(k_tp_eff, k_sl_eff * min_rr)
    return k_sl_eff, k_tp_eff
