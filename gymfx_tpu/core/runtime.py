"""Host-side runtime: merged config dict -> bound functional environment.

``Environment`` resolves the dataset once, builds the static EnvConfig,
numeric EnvParams and device MarketData, and exposes jitted
reset/step/rollout.  This is the seam between the gym-fx-compatible
config surface and the pure-JAX core.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import numpy as np

from gymfx_tpu.core import env as env_core
from gymfx_tpu.core import rollout as rollout_mod
from gymfx_tpu.core.types import (
    EnvConfig,
    EnvParams,
    EnvState,
    make_env_config,
    make_env_params,
)
from gymfx_tpu.data.feed import MarketData, MarketDataset, load_market_dataset


def validate_profile_latency(profile, bar_ms: Optional[float]) -> None:
    """Honor-or-reject: the scan engine's timing model (orders submitted
    at a bar close fill at the next bar open) subsumes sub-bar latency
    only; anything it cannot honor must fail loudly at binding time.
    Shared by the single-pair and portfolio bindings."""
    if profile is None or profile.latency_ms <= 0:
        return
    if bar_ms is None:
        raise ValueError(
            "cannot validate latency_ms: the dataset has neither a "
            "timeframe label nor enough timestamps to infer the bar "
            "interval; set the 'timeframe' config key"
        )
    if float(profile.latency_ms) > bar_ms:
        raise ValueError(
            f"latency_ms={profile.latency_ms} exceeds one bar "
            f"({bar_ms:.0f} ms): the scan engine's execution model "
            "(orders submitted at a bar close fill at the next bar "
            "open) subsumes sub-bar latency only; use the replay "
            "engine for multi-bar latency"
        )


def load_financing_rates(config: Dict[str, Any], financing_enabled: bool):
    """Rate table for the scan engine's rollover accrual; required (same
    error as the reference, simulation_engines/nautilus_gym.py:277-281)
    whenever the bound profile/config enables financing."""
    if not financing_enabled:
        return None
    rate_path = config.get("financing_rate_data_file")
    if not rate_path:
        raise ValueError(
            "financing_rate_data_file is required by the selected cost profile"
        )
    import pandas as pd

    return pd.read_csv(rate_path)


def _parse_column_list(value: Any, key: str) -> list:
    """Column-name lists arrive as real lists from file/library configs
    and as JSON strings from the CLI unknown-arg passthrough (the same
    convention as optimize_atr_periods, train/optimize.py)."""
    if isinstance(value, str):
        import json

        try:
            value = json.loads(value)
        except json.JSONDecodeError as e:
            raise ValueError(
                f"{key} must be a JSON list of column names (e.g. "
                f"'[\"CLOSE\", \"RET1\"]'), got {value!r}"
            ) from e
    if value is None:
        return []
    if not isinstance(value, (list, tuple)):
        raise ValueError(f"{key} must be a list of column names, got {value!r}")
    return [str(c) for c in value]


class Environment:
    def __init__(self, config: Dict[str, Any], dataset: Optional[MarketDataset] = None):
        self.config = dict(config)
        # feed dispatch: "replay" (the default — bitwise-identical code
        # path when the knob is unset) loads the CSV dataset; "scengen"
        # synthesizes a seed-deterministic scenario tape through the
        # SAME MarketDataset pipeline (gymfx_tpu/scengen/, docs/scenarios.md);
        # "curriculum" samples over a registry of tapes (data/tapes.py) —
        # tape 0 of the registry is this Environment's dataset, the
        # sampler itself is built after the device data exists (below)
        feed = str(config.get("feed") or "replay").lower()
        self.curriculum = None
        curriculum_specs = None
        if feed == "curriculum":
            from gymfx_tpu.data import tapes as tapes_mod

            curriculum_specs = tapes_mod.parse_tape_specs(self.config)
        if dataset is not None:
            self.dataset = dataset
        elif feed == "replay":
            self.dataset = load_market_dataset(self.config)
        elif feed == "scengen":
            from gymfx_tpu.scengen.feed import ScenGenDataset

            self.dataset = ScenGenDataset(self.config)
        elif feed == "curriculum":
            from gymfx_tpu.data import tapes as tapes_mod

            self.dataset = tapes_mod.dataset_for_spec(
                self.config, curriculum_specs[0]
            )
        else:
            raise ValueError(
                f"feed must be replay|scengen|curriculum, got {feed!r}"
            )
        if len(self.dataset) < int(config.get("window_size", 32)) + 2:
            raise ValueError(
                "input data is empty or too short for the configured window"
            )

        feature_columns = _parse_column_list(
            config.get("feature_columns"), "feature_columns"
        )
        binary_cols = set(_parse_column_list(
            config.get("feature_binary_columns"), "feature_binary_columns"
        ))
        binary_mask = tuple(c in binary_cols for c in feature_columns)
        # normalized forms back into the held config so every consumer
        # (obs export, summaries) sees lists, not CLI JSON strings
        self.config["feature_columns"] = feature_columns
        self.config["feature_binary_columns"] = sorted(binary_cols)

        from gymfx_tpu.core.types import _parse_profile

        profile = _parse_profile(self.config)
        self.cfg: EnvConfig = make_env_config(
            self.config,
            n_bars=len(self.dataset),
            n_features=len(feature_columns),
            binary_mask=binary_mask,
            profile=profile,
        )
        self.params: EnvParams = make_env_params(
            self.config, self.cfg, profile=profile
        )

        # Honor-or-reject: every profile field must either drive the scan
        # engine or fail loudly here — a profile must never be silently
        # degraded (reference wires these through Nautilus' LatencyModel /
        # FXRolloverInterestModule, simulation_engines/nautilus_gym.py:276-310).
        validate_profile_latency(profile, self.dataset.bar_interval_ms())
        if self.cfg.venue == "lob":
            from gymfx_tpu.lob.venue import validate_lob_venue

            validate_lob_venue(self.cfg, self.config)
        financing_rate_data = load_financing_rates(
            self.config, self.cfg.financing_enabled
        )

        budget = config.get("stream_hbm_budget_mb")
        self.stream_budget_mb: Optional[float] = (
            float(budget) if budget else None
        )
        from gymfx_tpu.data.compress import validate_compress_mode

        # int16 tick-delta wire format for streamed shards and the
        # curriculum tape library (data/compress.py); "off" (default)
        # leaves every existing path bitwise untouched
        self.data_compress = validate_compress_mode(
            config.get("data_compress", "off")
        )
        self.tick_size = float(config.get("lob_tick_size", 1e-5) or 1e-5)
        md_kwargs = dict(
            window_size=self.cfg.window_size,
            feature_columns=feature_columns,
            feature_scaling=str(config.get("feature_scaling", "rolling_zscore")),
            feature_scaling_window=int(config.get("feature_scaling_window", 256)),
            dtype=self.cfg.dtype,
            event_context_no_trade_column=str(
                config.get("event_context_no_trade_column", "event_no_trade_window_active")
            ),
            event_context_spread_stress_column=str(
                config.get("event_context_spread_stress_column", "event_spread_stress_multiplier")
            ),
            event_context_slippage_stress_column=str(
                config.get("event_context_slippage_stress_column", "event_slippage_stress_multiplier")
            ),
            force_close_dow=int(config.get("force_close_dow", 4)),
            force_close_hour=int(config.get("force_close_hour", 20)),
            force_close_window_hours=int(config.get("force_close_window_hours", 4)),
            monday_entry_window_hours=int(config.get("monday_entry_window_hours", 4)),
            financing_rate_data=financing_rate_data,
            instrument=str(config.get("instrument", "EUR_USD")),
        )

        self.streamer = None
        self.host_data: Optional[MarketData] = None
        if self.stream_budget_mb is not None:
            from gymfx_tpu.data.feed import BarStreamer, market_data_nbytes

            host = self.dataset.build_market_data(device=False, **md_kwargs)
            if market_data_nbytes(host) > self.stream_budget_mb * 2**20:
                # streamed: shards are uploaded on demand (rollout path);
                # no resident device copy exists
                self.streamer = BarStreamer(
                    host,
                    window_size=self.cfg.window_size,
                    budget_mb=self.stream_budget_mb,
                    compress=self.data_compress,
                    tick_size=self.tick_size,
                )
                # compressed mode never holds the f32 tape host-side;
                # generated feeds can also drop their f64 frame so a
                # large scengen tape exists in ONE representation only
                self.host_data = self.streamer.host_data
                if self.data_compress != "off":
                    del host
                    self.dataset.release_frame()
                self.data = None
            else:
                # fits the budget after all — resident, bit-identical to
                # the default path (same host-side casts, one device_put)
                self.data = jax.tree.map(jax.device_put, host)
        else:
            self.data: MarketData = self.dataset.build_market_data(**md_kwargs)

        if curriculum_specs is not None:
            if self.streamer is not None:
                raise ValueError(
                    "feed=curriculum cannot be combined with shard "
                    "streaming (stream_hbm_budget_mb="
                    f"{self.stream_budget_mb}): the sampler swaps whole "
                    "tapes at superstep boundaries; raise the budget or "
                    "compress the tape library with data_compress=on"
                )
            from gymfx_tpu.data import tapes as tapes_mod

            self.curriculum = tapes_mod.CurriculumSampler(
                self.config,
                curriculum_specs,
                base_dataset=self.dataset,
                base_data=self.data,
                md_kwargs=md_kwargs,
                compress=self.data_compress,
                tick_size=self.tick_size,
            )

    # ------------------------------------------------------------------
    @property
    def n_bars(self) -> int:
        return self.cfg.n_bars

    @property
    def streaming(self) -> bool:
        return self.streamer is not None

    def require_resident_data(self, what: str) -> MarketData:
        """The resident device MarketData, or a loud error for paths
        that need random access to the whole history (trainers, batch
        scans, gym stepping) while the dataset is being streamed."""
        if self.data is None:
            raise ValueError(
                f"{what} requires the full bar history resident in "
                "device memory, but this Environment streams it in "
                f"shards (stream_hbm_budget_mb={self.stream_budget_mb}); "
                "unset stream_hbm_budget_mb or raise the budget"
            )
        return self.data

    def reset(self, params: Optional[EnvParams] = None):
        return env_core.jit_reset(
            self.cfg, params or self.params, self.require_resident_data("reset()")
        )

    def step(self, state: EnvState, action, params: Optional[EnvParams] = None):
        return env_core.jit_step(
            self.cfg, params or self.params,
            self.require_resident_data("step()"), state, action
        )

    def rollout(self, driver, steps: int, seed: int = 0, params=None,
                collect=True, chunk_size: int = 64):
        """Host-level episode rollout (chunked: compile cost independent
        of episode length).  For rollouts INSIDE jit/vmap use
        core.rollout.rollout directly.  On a streaming Environment the
        shards are uploaded double-buffered while the episode runs
        (rollout_streamed)."""
        if self.streamer is not None:
            return rollout_mod.rollout_streamed(
                self.cfg,
                params or self.params,
                self.streamer,
                driver,
                int(steps),
                jax.random.PRNGKey(seed),
                collect=collect,
                chunk_size=chunk_size,
            )
        return rollout_mod.rollout_chunked(
            self.cfg,
            params or self.params,
            self.data,
            driver,
            int(steps),
            jax.random.PRNGKey(seed),
            collect=collect,
            chunk_size=chunk_size,
        )

    def make_driver(self, rng: Optional[np.random.Generator] = None):
        """Driver from config['driver_mode'] (reference driver loop,
        app/main.py:58-66 + default_strategy.py:44-54)."""
        mode = str(self.config.get("driver_mode", "buy_hold"))
        if mode == "replay":
            path = self.config.get("replay_actions_file")
            if not path:
                raise ValueError("driver_mode=replay requires replay_actions_file")
            import csv

            with open(path, "r", encoding="utf-8") as fh:
                actions = [int(row.get("action", 0)) for row in csv.DictReader(fh)]
            return rollout_mod.replay_driver(np.asarray(actions or [0]))
        try:
            return rollout_mod.DRIVERS[mode]()
        except KeyError:
            raise ValueError(f"unknown driver_mode {mode!r}") from None
