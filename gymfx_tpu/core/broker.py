"""Branch-free broker ledger kernel.

Replaces backtrader's BackBroker + order matching (the engine side of
reference app/bt_bridge.py:136-248, broker config
broker_plugins/default_broker.py:35-53) with pure functions over the
``EnvState`` ledger fields, composable under ``jit``/``vmap``/``scan``.

Execution model (matching backtrader's default, no cheat-on-open):
  * market orders created at bar t (the strategy acts on bar t's close)
    execute at bar t+1's OPEN;
  * percent slippage is applied adversely by fill direction
    (buy: open*(1+slip); sell: open*(1-slip));
  * commission = commission_rate * fill_price * |units| per executed
    order; a long<->short flip is close+open = two orders, equivalent
    to commission on |delta| at one fill price;
  * equity = cash + position * close, marked at every bar close.

Bracket (SL/TP) semantics: armed when the parent entry fills; evaluated
against each bar's H/L while the position is open; collision policies
``worst_case`` (SL wins when both touched — reference
simulation_engines/contracts.py:100, bakeoff fixture semantics
bakeoff.py:116-163), ``ohlc`` (O->H->L->C path order) and ``adaptive``
(treated as worst_case).  Deliberate divergence from the reference
backtrader path: closing a bracketed position cancels its children
(backtrader leaves orphaned child orders alive — a latent footgun the
scan kernel does not reproduce).
"""
from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from gymfx_tpu.core.types import (
    EXEC_DIAG_INDEX,
    EnvConfig,
    EnvParams,
    EnvState,
)


def quantize(x, tick):
    """Round ``x`` to the nearest multiple of ``tick``; identity when
    tick == 0 (the venue-quantization-off sentinel).  Round-half-even,
    matching the replay venue's ``make_price``/``make_qty`` (Python
    ``round``) so both engines land on the same grid.

    The arithmetic runs in float64 when x64 is enabled (bit-parity with
    the replay venue's double rounding).  In pure-f32 mode the ratio
    ``x/tick`` (~1e5 for FX ticks) keeps only ~7 fractional bits, so a
    value within ~0.01 tick of a midpoint can round to the adjacent
    tick vs the f64 path — the crosscheck bound carries a documented
    midpoint-flip slack for exactly this (simulation/crosscheck.py)."""
    import jax

    x = jnp.asarray(x)
    if jax.config.jax_enable_x64:
        xi, ti = x.astype(jnp.float64), jnp.asarray(tick, jnp.float64)
        safe = jnp.where(ti > 0, ti, 1.0)
        return jnp.where(ti > 0, jnp.round(xi / safe) * safe, xi).astype(x.dtype)
    safe = jnp.where(tick > 0, tick, 1.0)
    return jnp.where(tick > 0, jnp.round(x / safe) * safe, x)


def snap_in_bar(price, low, high, tick):
    """Clip ``price`` into the bar's [low, high], then snap to the
    nearest IN-BAR venue tick, so ``apply_fill``'s round-half-even
    re-quantization is an identity and slip_match's in-range guarantee
    survives venue quantization (ADVICE r4).  Each one-tick correction
    only fires when it LANDS in-bar: a bar narrower than one tick
    (off-grid H/L, a data/venue inconsistency) keeps the nearest tick —
    the best on-grid price that exists — instead of oscillating.
    Identity when tick == 0 (quantization off)."""
    p = jnp.clip(price, low, high)
    q = quantize(p, tick)
    t = jnp.asarray(tick, q.dtype)
    down, up = q - t, q + t
    q = jnp.where((q > high) & (down >= low), down, q)
    q = jnp.where((q < low) & (up <= high), up, q)
    return q


def opening_units(pos, target):
    """Units newly opened by moving ``pos`` -> ``target``: the size
    increase when flat/adding, the whole new position on a flip.
    (Single source for preflight and fill decomposition semantics.)"""
    same_sign = pos * target > 0
    opening = jnp.maximum(jnp.abs(target) - jnp.abs(pos), 0.0)
    return jnp.where(
        (~same_sign) & (target != 0) & (pos != 0), jnp.abs(target), opening
    )


def maintenance_margin(pos, price, params: EnvParams, margin_model: str):
    """Maintenance requirement of the open position, in quote currency:
    |pos| * price * margin_maint, divided by leverage under the
    leveraged model — the same model split as the init-margin preflight
    (reference margin models, simulation_engines/nautilus_adapter.py:397-427)."""
    m = jnp.abs(pos) * price * params.margin_maint
    if margin_model == "leveraged":
        m = m / jnp.maximum(params.leverage, 1e-12)
    return m


def margin_closeout_percent(state: EnvState, price, params: EnvParams,
                            margin_model: str, cap: float = 100.0):
    """How close the account is to liquidation: maintenance margin over
    equity — 0 flat, 1.0 at the closeout boundary, capped when equity is
    non-positive.  This is the REAL-ledger value behind the
    ``margin_closeout_percent`` obs field (the reference publishes it
    from its margin account when one exists, app/env.py:615-623)."""
    maint = maintenance_margin(state.pos, price, params, margin_model)
    eq = params.initial_cash + state.equity_delta
    pct = jnp.where(eq > 0, maint / jnp.maximum(eq, 1e-30), cap)
    pct = jnp.where(state.pos == 0, 0.0, pct)
    return jnp.clip(pct, 0.0, cap)


def realized_balance(state: EnvState, params: EnvParams):
    """Realized-PnL account balance (initial + realized - commissions):
    cash plus the open position's entry notional — the same measure the
    replay engine's margin preflight compares against
    (simulation/replay.py balance semantics)."""
    return params.initial_cash + state.cash_delta + state.pos * state.entry_price


def apply_fill(
    state: EnvState, fill_price, target_units, params: EnvParams
) -> EnvState:
    """Move the position to ``target_units`` at ``fill_price`` (pre-slippage).

    No-op when ``target_units == pos``.  Handles open/add/reduce/close/
    flip with avg-entry-price tracking, commission accrual and
    closed-trade statistics.
    """
    d = state.pos.dtype
    pos = state.pos
    target = jnp.asarray(target_units, dtype=d)
    delta = target - pos
    direction = jnp.sign(delta)
    # venue quantization (opt-in): the book holds prices at the
    # instrument's tick, so the post-slippage fill price is quantized —
    # the replay venue's make_price on bid/ask (simulation/replay.py
    # market_price)
    fill = quantize(fill_price * (1.0 + params.slippage * direction),
                    params.price_tick)

    abs_pos = jnp.abs(pos)
    abs_target = jnp.abs(target)
    same_sign = pos * target > 0
    # units closed out of the existing position by this fill
    closed = jnp.where(
        same_sign,
        jnp.maximum(abs_pos - abs_target, 0.0),
        abs_pos,
    )
    closed = jnp.where(delta == 0, 0.0, closed)
    opened = jnp.abs(delta) - closed

    realized = closed * (fill - state.entry_price) * jnp.sign(pos)
    commission = params.commission * fill * jnp.abs(delta)
    comm_close = params.commission * fill * closed
    comm_open = commission - comm_close

    cash_delta = state.cash_delta - delta * fill - commission

    # average entry price of the resulting position
    new_abs = jnp.abs(target)
    adding = same_sign & (abs_target > abs_pos)
    flipping = (~same_sign) & (target != 0) & (pos != 0)
    opening = (pos == 0) & (target != 0)
    entry = jnp.where(
        adding,
        (state.entry_price * abs_pos + fill * (new_abs - abs_pos)) / jnp.maximum(new_abs, 1e-30),
        state.entry_price,
    )
    entry = jnp.where(flipping | opening, fill, entry)
    entry = jnp.where(target == 0, 0.0, entry)

    # closed-trade bookkeeping: a trade closes when the old position is
    # fully exited (to flat or by flip) — reference counts on
    # trade.isclosed (app/bt_bridge.py:132-134)
    trade_closed = (pos != 0) & ((target == 0) | flipping)
    trade_net = realized - (state.open_trade_commission + comm_close)
    trade_count = state.trade_count + trade_closed.astype(jnp.int32)
    trade_pnl_sum = state.trade_pnl_sum + jnp.where(trade_closed, trade_net, 0.0)
    trade_pnl_sumsq = state.trade_pnl_sumsq + jnp.where(trade_closed, trade_net**2, 0.0)
    trades_won = state.trades_won + (trade_closed & (trade_net > 0)).astype(jnp.int32)
    trades_lost = state.trades_lost + (trade_closed & (trade_net < 0)).astype(jnp.int32)
    open_trade_commission = jnp.where(
        trade_closed, comm_open, state.open_trade_commission + comm_open
    )
    open_trade_commission = jnp.where(target == 0, 0.0, open_trade_commission)

    return state._replace(
        pos=target,
        entry_price=entry,
        cash_delta=cash_delta,
        commission_paid=state.commission_paid + commission,
        last_trade_cost=state.last_trade_cost + commission,
        trade_count=trade_count,
        trade_pnl_sum=trade_pnl_sum,
        trade_pnl_sumsq=trade_pnl_sumsq,
        trades_won=trades_won,
        trades_lost=trades_lost,
        open_trade_commission=open_trade_commission,
    )


def fill_pending(
    state: EnvState, open_price, params: EnvParams,
    cfg: EnvConfig = None, high=None, low=None,
) -> EnvState:
    """Execute the pending market order at the new bar's open.

    Venue quantization (opt-in, zero-sentinel params): the order DELTA
    is rounded to the instrument's size step and orders below
    min_quantity are denied — the replay venue's make_qty/min_quantity
    rule (simulation/replay.py process_action; reference RiskEngine,
    nautilus_adapter.py:190).  Denials apply to closing orders too,
    exactly like the replay engine.

    Per-fill-type slippage switches (reference backtrader
    set_slippage_perc, broker_plugins/default_broker.py:52): with
    ``cfg.slip_open`` off, fills at the open take no slippage; with
    ``cfg.slip_match`` on (and ``high``/``low`` given), the slipped
    price is capped into the bar's range.  The default flags take the
    untouched code path — bit-identical to the pre-toggle kernel.
    """
    raw_target = jnp.where(state.pending_active, state.pending_target, state.pos)
    delta = raw_target - state.pos
    qty = quantize(jnp.abs(delta), params.size_step)
    # A venue-forced liquidation (maintenance-margin closeout) bypasses the
    # size rules entirely: it fills the exact open position, un-quantized
    # and below min_quantity if need be — the replay venue's bypass
    # (simulation/replay.py check_margin_closeout: "a venue never strands
    # a liquidation on a size rule").  Without this a position left below
    # min_qty by partial reduces would be permanently unliquidatable.
    forced = state.pending_active & state.pending_forced
    qty = jnp.where(forced, jnp.abs(delta), qty)
    denied = (
        state.pending_active
        & ~forced
        & (delta != 0)
        & ((qty < params.min_qty) | ((params.size_step > 0) & (qty <= 0)))
    )
    target = jnp.where(denied, state.pos, state.pos + jnp.sign(delta) * qty)
    state = state._replace(
        exec_diag=state.exec_diag.at[
            EXEC_DIAG_INDEX["order_denied_min_quantity"]
        ].add(denied.astype(jnp.int32))
    )
    fill_price = open_price
    slip_open = cfg.slip_open if cfg is not None else True
    slip_match = (cfg.slip_match if cfg is not None else False) and high is not None
    if (not slip_open) or slip_match:
        # pre-adjust so apply_fill's own slippage lands on the desired
        # final price (the same neutralization trick as the TP path)
        direction = jnp.sign(target - state.pos)
        final = open_price * (
            1.0 + params.slippage * (1.0 if slip_open else 0.0) * direction
        )
        if slip_match:
            final = snap_in_bar(final, low, high, params.price_tick)
        denom = 1.0 + params.slippage * direction
        fill_price = final / jnp.where(denom == 0, 1.0, denom)
    new_state = apply_fill(state, fill_price, target, params)
    # Re-arm brackets only when the fill actually OPENED units (fresh
    # entry or flip) — a fill that merely reduces an existing bracketed
    # position must not overwrite its live brackets with the reduce
    # order's (zero) SL/TP.
    entered = (
        state.pending_active
        & (new_state.pos != 0)
        & (opening_units(state.pos, target) > 0)
    )
    # bracket levels rest on the venue book -> quantized at arming (the
    # replay's make_price on sl/tp; identity when quantization is off)
    bracket_sl = jnp.where(
        entered, quantize(state.pending_sl, params.price_tick), state.bracket_sl
    )
    bracket_tp = jnp.where(
        entered, quantize(state.pending_tp, params.price_tick), state.bracket_tp
    )
    flat = new_state.pos == 0
    return new_state._replace(
        pending_active=jnp.zeros_like(state.pending_active),
        pending_target=jnp.zeros_like(state.pending_target),
        pending_sl=jnp.zeros_like(state.pending_sl),
        pending_tp=jnp.zeros_like(state.pending_tp),
        pending_forced=jnp.zeros_like(state.pending_forced),
        bracket_sl=jnp.where(flat, 0.0, bracket_sl),
        bracket_tp=jnp.where(flat, 0.0, bracket_tp),
    )


def check_brackets(
    state: EnvState, open_price, high, low, cfg: EnvConfig, params: EnvParams
) -> EnvState:
    """Resolve SL/TP exits intrabar against the bar's H/L."""
    pos = state.pos
    has_pos = pos != 0
    long = pos > 0
    sl = state.bracket_sl
    tp = state.bracket_tp
    has_sl = sl > 0
    has_tp = tp > 0

    # trigger + raw fill price per side (stop orders gap-fill at open).
    # The take-profit (a limit order) honors the profile's
    # limit_fill_policy (contracts.py _LIMIT_FILL_POLICIES; reference
    # simulation_engines/contracts.py:101):
    #   conservative  price must trade THROUGH the limit (strict
    #                 inequality — an exact touch does not fill, modeling
    #                 queue position); fills at the limit price exactly;
    #   touch         an exact touch fills, at the limit price exactly;
    #   cross         an exact touch fills, and a bar that gaps open
    #                 beyond the limit fills at the open (price
    #                 improvement) — the scan engine's no-profile default.
    sl_trig = has_pos & has_sl & jnp.where(long, low <= sl, high >= sl)
    strict = cfg.limit_fill_policy == "conservative"
    if strict:
        tp_trig = has_pos & has_tp & jnp.where(long, high > tp, low < tp)
    else:
        tp_trig = has_pos & has_tp & jnp.where(long, high >= tp, low <= tp)
    sl_fill = jnp.where(
        long,
        jnp.where(open_price <= sl, open_price, sl),
        jnp.where(open_price >= sl, open_price, sl),
    )
    if cfg.limit_fill_policy == "cross":
        tp_fill = jnp.where(
            long,
            jnp.where(open_price >= tp, open_price, tp),
            jnp.where(open_price <= tp, open_price, tp),
        )
    else:  # conservative / touch: a limit never fills better than its price
        tp_fill = tp

    if cfg.intrabar_collision_policy == "ohlc":
        # Walk the O->H->L->C path.  A bar that opens through either
        # bracket fills it at the open (gap_sl and gap_tp are mutually
        # exclusive: SL and TP sit on opposite sides of the entry).
        # With no gap, longs reach TP on the O->H leg before SL on H->L;
        # shorts reach SL (above) on the O->H leg before TP on H->L.
        gap_sl = has_pos & has_sl & jnp.where(long, open_price <= sl, open_price >= sl)
        if strict:
            gap_tp = has_pos & has_tp & jnp.where(
                long, open_price > tp, open_price < tp
            )
        else:
            gap_tp = has_pos & has_tp & jnp.where(
                long, open_price >= tp, open_price <= tp
            )
        exit_sl = gap_sl | (
            sl_trig & ~gap_tp & jnp.where(long, ~tp_trig, jnp.ones_like(gap_sl))
        )
        exit_tp = (gap_tp | tp_trig) & ~exit_sl
    else:  # worst_case / adaptive
        exit_sl = sl_trig
        exit_tp = tp_trig & ~sl_trig

    exiting = exit_sl | exit_tp
    # SL exits suffer adverse slippage (stop -> market); TP exits fill at
    # the limit price exactly (a limit cannot fill worse than its price)
    # unless cfg.slip_limit re-enables slippage on them (capped at the
    # limit).  cfg.slip_open / cfg.slip_match adjust gap and intrabar
    # fills per the reference broker's set_slippage_perc switches; the
    # default flags take the original code path bit-for-bit.
    exit_dir = -jnp.sign(pos)  # sell to exit long, buy to exit short
    denom = 1.0 + params.slippage * exit_dir
    safe_denom = jnp.where(denom == 0, 1.0, denom)
    if cfg.slip_open and not cfg.slip_match:
        sl_adj = sl_fill  # apply_fill slips it (historical path)
    else:
        sl_gap = has_pos & has_sl & jnp.where(
            long, open_price <= sl, open_price >= sl
        )
        # gap SLs execute at the open (slip_open gates them); intrabar
        # stop fills always slip
        sl_scale = jnp.where(sl_gap, 1.0 if cfg.slip_open else 0.0, 1.0)
        sl_final = sl_fill * (1.0 + params.slippage * sl_scale * exit_dir)
        if cfg.slip_match:
            sl_final = snap_in_bar(sl_final, low, high, params.price_tick)
        sl_adj = sl_final / safe_denom
    if cfg.slip_limit:
        tp_final = tp_fill * (1.0 + params.slippage * exit_dir)
        if cfg.slip_match:
            tp_final = snap_in_bar(tp_final, low, high, params.price_tick)
        # a limit never fills worse than its price (cap applied last)
        tp_final = jnp.where(
            long, jnp.maximum(tp_final, tp), jnp.minimum(tp_final, tp)
        )
        tp_adj = tp_final / safe_denom
    else:
        tp_adj = tp_fill / safe_denom  # neutralize: fill at the limit exactly
    adj_price = jnp.where(exit_sl, sl_adj, tp_adj)

    target = jnp.where(exiting, 0.0, pos)
    new_state = apply_fill(state, jnp.where(exiting, adj_price, open_price), target, params)
    return new_state._replace(
        bracket_sl=jnp.where(exiting, 0.0, state.bracket_sl),
        bracket_tp=jnp.where(exiting, 0.0, state.bracket_tp),
    )


def mark_to_market(state: EnvState, close_price, params: EnvParams) -> EnvState:
    """Mark equity at the bar close; update drawdown tracking."""
    equity_delta = state.cash_delta + state.pos * close_price
    peak = jnp.maximum(state.peak_equity_delta, equity_delta)
    money_down = peak - equity_delta
    peak_equity = params.initial_cash + peak
    pct_down = jnp.where(peak_equity > 0, money_down / peak_equity * 100.0, 0.0)
    return state._replace(
        prev_equity_delta=state.equity_delta,
        equity_delta=equity_delta,
        peak_equity_delta=peak,
        max_drawdown_money=jnp.maximum(state.max_drawdown_money, money_down),
        max_drawdown_pct=jnp.maximum(state.max_drawdown_pct, pct_down),
    )


def equity(state: EnvState, params: EnvParams):
    return params.initial_cash + state.equity_delta


def prev_equity(state: EnvState, params: EnvParams):
    return params.initial_cash + state.prev_equity_delta
