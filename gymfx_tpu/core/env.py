"""The functional environment: ``reset`` / ``step`` as pure JAX.

One ``step`` fuses what the reference spreads across two threads and a
per-bar Event handshake (reference app/env.py:279-328 on the main
thread, app/bt_bridge.py:136-248 on the cerebro thread):

  coerce action -> event-context overlay -> diagnostics ->
  [advance bar: fill pending at open, resolve brackets intrabar,
   apply strategy at close, mark equity] -> reward -> obs/info

Step/bar timing parity with the reference handshake:
  * ``reset`` yields the observation at bar_index=1 (first bar
    processed, warmup publish — reference bt_bridge.py:144-151);
  * the FIRST ``step`` applies its action on that same bar without
    advancing (the order fills at bar 2's open);
  * every subsequent step advances exactly one bar: the previous
    action's order fills at the new bar's open, brackets resolve
    against the new bar's H/L, the new action is applied at its close,
    equity is marked at that close;
  * a step taken when the final bar was already processed terminates
    the episode without advancing (reference cerebro stop() path).

Documented divergences from the reference (quirks not reproduced):
  * ``last_trade_cost`` reports the commissions actually paid during
    the step; the reference zeroes its accumulator after notification
    delivery and therefore always publishes 0.0 (bt_bridge.py:175,239-248);
  * on the terminal exhausted step the sharpe reward buffer is not
    cleared-and-repopulated (the reference's step-regression reset
    fires there, sharpe_reward.py:42-45); pnl/dd rewards match exactly.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from gymfx_tpu.core import broker, rewards, strategy
from gymfx_tpu.core.obs import build_info, build_obs
from gymfx_tpu.core.types import (
    ACTION_DIAG_INDEX,
    EXEC_DIAG_INDEX,
    EnvConfig,
    EnvParams,
    EnvState,
    initial_state,
)
from gymfx_tpu.data.feed import MarketData


def jit_reset(cfg, params, data):
    """Module-level jitted reset — cached across Environment instances
    (a per-instance jax.jit wrapper would recompile for every env)."""
    return _JIT_RESET(cfg, params, data)


def jit_step(cfg, params, data, state, action):
    """Module-level jitted step (see jit_reset)."""
    return _JIT_STEP(cfg, params, data, state, action)


def reset(
    cfg: EnvConfig, params: EnvParams, data: MarketData
) -> Tuple[EnvState, Dict[str, Any]]:
    """Start an episode; returns (state, obs) at bar_index=1."""
    return reset_at(cfg, params, data, 0)


def reset_at(
    cfg: EnvConfig, params: EnvParams, data: MarketData, t0
) -> Tuple[EnvState, Dict[str, Any]]:
    """Reset with the episode starting at bar row ``t0`` (traced).

    New capability for training diversity (the reference always starts
    at bar 1): rollout collectors draw random start offsets so an env
    batch covers the dataset instead of replaying its head.  Windows
    are seeded with one dynamic slice — called per reset, never per
    step, so the streaming-window fast path is unaffected.
    """
    t0 = jnp.asarray(t0, jnp.int32)
    # every data read is rebased by row0: a streamed shard carries its
    # global start row there (0 when fully resident), so cursors stay
    # global while array indices are shard-local
    r0 = data.row0
    state = initial_state(cfg)
    state = state._replace(t=t0)
    state = broker.mark_to_market(state, data.close[t0 - r0], params)
    state = state._replace(
        prev_equity_delta=state.equity_delta,
        price_window=jax.lax.dynamic_slice(
            data.padded_close, (t0 + 1 - r0,), (cfg.window_size,)
        ).astype(state.price_window.dtype),
        feat_window=jax.lax.dynamic_slice(
            data.padded_features,
            (t0 + 1 - r0, jnp.zeros((), jnp.int32)),
            (cfg.window_size, cfg.n_features),
        ),
    )
    return state, build_obs(state, data, cfg, params)


def step(
    cfg: EnvConfig,
    params: EnvParams,
    data: MarketData,
    state: EnvState,
    action,
) -> Tuple[EnvState, Dict[str, Any], Any, Any, Dict[str, Any]]:
    """Pure step. Returns (state, obs, reward, done, info)."""
    n = cfg.n_bars
    was_terminated = state.terminated

    # ---- action coercion (reference app/env.py:343-360) ------------------
    raw = jnp.asarray(action).reshape(-1)[0].astype(state.pos.dtype)
    if cfg.action_space_mode == "continuous":
        thr = params.continuous_action_threshold
        a = jnp.where(raw >= thr, 1, jnp.where(raw <= -thr, 2, 0)).astype(jnp.int32)
    else:
        ai = jnp.asarray(action).reshape(-1)[0].astype(jnp.int32)
        hi = 3 if cfg.allow_flat_action else 2
        a = jnp.where((ai >= 0) & (ai <= hi), ai, 0)

    # ---- event-context overlay (reference app/env.py:394-440) ------------
    a, state, event_info = _event_overlay(state, a, data, cfg, params)

    # ---- action diagnostics (post-overlay, reference app/env.py:287) -----
    # Post-termination steps are complete no-ops (the reference's driver
    # never steps a finished env, so its quirk of still counting
    # diagnostics there is unobservable; making them inert keeps the
    # scanned and step-by-step paths byte-identical).
    state = _record_action(state, raw, a, cfg, ~was_terminated)

    # ---- engine advance ---------------------------------------------------
    live = ~was_terminated
    advance = live & state.started & (state.t < n - 1)
    exhausted = live & state.started & (state.t >= n - 1)
    act_strategy = live & ~exhausted          # warmup or advancing step

    t_new = jnp.where(advance, state.t + 1, state.t)
    r0 = data.row0  # shard-local rebase (0 when fully resident)
    o = data.open[t_new - r0]
    h = data.high[t_new - r0]
    l = data.low[t_new - r0]
    c = data.close[t_new - r0]
    mow = data.minute_of_week[t_new - r0]

    st = state._replace(t=t_new, last_trade_cost=jnp.zeros_like(state.last_trade_cost))

    # fused env-dynamics kernel dispatch (`rollout_env_kernel` knob,
    # docs/performance.md "MFU push"): "on" routes the bar venue's
    # fill/bracket/financing and mark/reward chains through the pallas
    # env-blocked kernels on TPU (plain XLA elsewhere); "interpret"
    # forces pallas interpret mode anywhere (CPU parity tests); "off"
    # is plain XLA everywhere.  All three bitwise-identical by
    # construction (ops/env_dynamics.py; tests/test_env_dynamics_kernel.py).
    kernel_env = cfg.venue == "bar" and cfg.rollout_env_kernel != "off" and (
        cfg.rollout_env_kernel == "interpret"
        or jax.default_backend() == "tpu"
    )

    if cfg.venue == "lob":
        # 1+2 (LOB venue): the pending order walks the seeded book at
        # the open and brackets resolve against actual prints along the
        # bar's message flow (gymfx_tpu/lob/venue.py).  Static branch:
        # with venue unset the bar path below is traced bit-identically
        # and no LOB code reaches the hot path.
        from gymfx_tpu.lob import venue as lob_venue

        # feed=scengen: the generated tape's per-bar scenario bitmask
        # reshapes the order flow (droughts thin the book, crash bars
        # burst the flow) — static gate, so replay feeds never trace
        # the scen_flags leaf
        scen = (
            data.scen_flags[t_new - r0] if cfg.lob_flow_from_scengen
            else None
        )
        st_l = lob_venue.execute_bar(
            st, o, h, l, c, t_new, cfg, params, scen_flags=scen
        )
        st = _select(advance, st_l, st)
    elif kernel_env:
        # 1+2+2b fused (kernel A, ops/env_dynamics.py): the same
        # fill_pending -> check_brackets -> financing chain as below,
        # packed into one env-blocked pallas VMEM pass
        from gymfx_tpu.ops import env_dynamics

        st = env_dynamics.fused_fill_brackets(
            st, o, h, l, c,
            data.rollover_accrual[t_new - r0]
            if cfg.financing_enabled else None,
            advance, cfg, params,
            interpret=cfg.rollout_env_kernel == "interpret",
        )
    else:
        # 1. pending order fills at the new bar's open (only when advancing)
        st_f = broker.fill_pending(st, o, params, cfg, h, l)
        st = _select(advance, st_f, st)
        # 2. brackets resolve against the new bar's H/L
        st_b = broker.check_brackets(st, o, h, l, cfg, params)
        st = _select(advance, st_b, st)
    # 2b. FX rollover financing: the position held at a rollover bar
    #     (first bar at/after 22:00 UTC of its day) accrues interest from
    #     the pair's daily rate differential, precomputed into
    #     data.rollover_accrual (data/financing.py).  One fused
    #     multiply-add per step — the scan twin of the replay engine's
    #     apply_rollover (simulation/replay.py) and of the reference's
    #     FXRolloverInterestModule (reference
    #     simulation_engines/nautilus_gym.py:276-290).  (Folded into
    #     kernel A on the fused path above.)
    if cfg.financing_enabled and not kernel_env:
        accrual = st.pos * c * data.rollover_accrual[t_new - r0]
        st = st._replace(
            cash_delta=st.cash_delta + jnp.where(advance, accrual, 0.0)
        )
    # 3. strategy applies the (post-overlay) action at the bar close
    st = strategy.apply_action(st, a, o, h, l, c, mow, cfg, params, act_strategy)
    # 3b. margin preflight (profile-gated): deny entries whose opening
    # margin exceeds free cash (reference Nautilus env denial path,
    # simulation_engines/nautilus_gym.py:162-171; counter kept
    # engine-neutral as 'preflight_denied')
    if cfg.enforce_margin_preflight:
        opening = broker.opening_units(st.pos, st.pending_target)
        required = opening * c * params.margin_init
        if cfg.margin_model == "leveraged":
            required = required / jnp.maximum(params.leverage, 1e-12)
        # compare against the realized-balance account (NOT the
        # full-notional cash ledger, which would mis-gate flips of
        # leveraged positions) — same measure as the replay engine
        free = broker.realized_balance(st, params)
        denied = st.pending_active & (opening > 0) & (required > free)
        st = st._replace(
            pending_active=st.pending_active & ~denied,
            pending_target=jnp.where(denied, 0.0, st.pending_target),
            pending_sl=jnp.where(denied, 0.0, st.pending_sl),
            pending_tp=jnp.where(denied, 0.0, st.pending_tp),
            exec_diag=st.exec_diag.at[EXEC_DIAG_INDEX["preflight_denied"]].add(
                denied.astype(jnp.int32)
            ),
        )
    # 4. mark equity at the close (advancing bars only; the warmup step
    #    re-marks bar 0, which is a no-op on an untouched ledger)
    if kernel_env:
        # 4 + reward fused (kernel B): mark, drawdown and the reward
        # carries in one VMEM pass.  The base reward is computed HERE —
        # nothing between this mark and the reward block below reads or
        # writes the equity deltas or reward carries, so the program is
        # identical with the reward hoisted to the mark.
        from gymfx_tpu.ops import env_dynamics

        st, _kernel_base_reward = env_dynamics.fused_mark_reward(
            st, c, advance | (live & ~state.started), live, cfg, params,
            interpret=cfg.rollout_env_kernel == "interpret",
        )
    else:
        st_m = broker.mark_to_market(st, c, params)
        st = _select(advance | (live & ~state.started), st_m, st)
    # 4b. maintenance-margin closeout: equity marked below the position's
    #     maintenance requirement forces a liquidation that REPLACES any
    #     pending order and fills at the next bar's open through the
    #     ordinary order path (slippage and commission apply) — the scan
    #     twin of Nautilus' margin-account liquidation (reference
    #     simulation_engines/nautilus_adapter.py:397-427, margin_maint
    #     contracts.py:117-120).  The agent may re-enter afterwards
    #     (subject to the init-margin preflight), as on a real venue.
    if cfg.enforce_margin_closeout:
        maint = broker.maintenance_margin(st.pos, c, params, cfg.margin_model)
        equity_now = params.initial_cash + st.equity_delta
        # gated on `advance`: the exhausted terminal step re-visits the
        # same mark and would double-count the breach (and its forced
        # order could never fill — there is no next bar)
        breach = advance & (st.pos != 0) & (equity_now < maint)
        st = st._replace(
            pending_active=st.pending_active | breach,
            pending_target=jnp.where(breach, 0.0, st.pending_target),
            pending_sl=jnp.where(breach, 0.0, st.pending_sl),
            pending_tp=jnp.where(breach, 0.0, st.pending_tp),
            pending_forced=st.pending_forced | breach,
            exec_diag=st.exec_diag.at[EXEC_DIAG_INDEX["margin_closeouts"]].add(
                breach.astype(jnp.int32)
            ),
        )

    # streaming obs windows: on advance, shift left and append the new
    # bar's close / raw feature row (raw row i lives at padded[i + w])
    if cfg.include_prices:
        new_price = jnp.concatenate(
            [st.price_window[1:], c[None].astype(st.price_window.dtype)]
        )
        st = st._replace(
            price_window=jnp.where(advance, new_price, st.price_window)
        )
    if cfg.n_features > 0:
        new_feat_row = data.padded_features[t_new + cfg.window_size - r0]
        new_feat = jnp.concatenate([st.feat_window[1:], new_feat_row[None, :]])
        st = st._replace(
            feat_window=jnp.where(advance, new_feat, st.feat_window)
        )

    st = st._replace(started=state.started | live)

    # ---- reward -----------------------------------------------------------
    if kernel_env:
        base_reward = _kernel_base_reward  # computed inside kernel B
    else:
        st, base_reward = rewards.compute_reward(st, cfg, params, live)
    fc_row = jnp.minimum(st.t + 1, n - 1)
    penalty = rewards.force_close_penalty(
        st, data.force_close[fc_row - r0], cfg, params
    )
    penalty = jnp.where(live, penalty, 0.0)
    reward = base_reward - penalty

    # ---- termination ------------------------------------------------------
    equity = params.initial_cash + st.equity_delta
    broke = equity <= params.min_equity
    terminated = was_terminated | exhausted | (live & broke)
    # explicit reason, latched at FIRST termination: bankruptcy wins over
    # exhaustion (a final-bar bankruptcy is a bankruptcy — the bar cursor
    # alone cannot tell them apart, types.py TERMINATION_*)
    from gymfx_tpu.core.types import TERMINATION_BANKRUPT, TERMINATION_EXHAUSTED

    reason_now = jnp.where(
        live & broke,
        jnp.int32(TERMINATION_BANKRUPT),
        jnp.where(exhausted, jnp.int32(TERMINATION_EXHAUSTED), jnp.int32(0)),
    )
    st = st._replace(
        terminated=terminated,
        termination_reason=jnp.where(
            was_terminated, st.termination_reason, reason_now
        ).astype(jnp.int32),
    )

    obs = build_obs(st, data, cfg, params)
    info = build_info(st, data, cfg, params, event_info)
    info["reward"] = reward
    info["base_reward"] = base_reward
    info["force_close_reward_penalty"] = penalty
    info["pnl"] = st.equity_delta - st.prev_equity_delta
    info["trade_cost"] = st.last_trade_cost
    # full-precision equity relative to initial cash (info["equity"] is
    # initial+delta in f32, quantized at ~1e-3 on a 10k account)
    info["equity_delta"] = st.equity_delta
    # order/bracket state for the host-side audit trail (reference
    # GYMFX_BRACKET_AUDIT JSONL, strategy_plugins/direct_atr_sltp.py:40-50)
    info["pending_active"] = st.pending_active
    info["pending_target"] = st.pending_target
    info["pending_sl"] = st.pending_sl
    info["pending_tp"] = st.pending_tp
    info["bracket_sl"] = st.bracket_sl
    info["bracket_tp"] = st.bracket_tp
    info["position_units"] = st.pos
    info["termination_reason"] = st.termination_reason
    info["atr"] = jnp.where(
        st.tr_len > 0,
        jnp.sum(st.tr_buffer) / jnp.maximum(st.tr_len, 1).astype(st.tr_buffer.dtype),
        0.0,
    )
    return st, obs, reward, terminated, info


# ---------------------------------------------------------------------------
def _select(pred, a: EnvState, b: EnvState) -> EnvState:
    return EnvState(*(jnp.where(pred, x, y) for x, y in zip(a, b)))


def _event_overlay(state, a, data: MarketData, cfg: EnvConfig, params: EnvParams):
    """Event-context action transform (reference app/env.py:362-440).

    Reads engineered no-trade columns at the upcoming row and blocks new
    entries / force-flattens open positions during event windows."""
    n = cfg.n_bars
    row = jnp.minimum(jnp.minimum(state.t + 1, n), n - 1) - data.row0
    no_trade_value = data.ev_no_trade[row]
    spread_mult = data.ev_spread_mult[row]
    slip_mult = data.ev_slip_mult[row]
    active = no_trade_value >= params.event_no_trade_threshold
    pos_sign = jnp.sign(state.pos).astype(jnp.int32)
    before = a

    live = ~state.terminated
    if cfg.event_context_execution_overlay:
        diag = state.exec_diag
        diag = diag.at[EXEC_DIAG_INDEX["event_context_no_trade_active_steps"]].add(
            (active & live).astype(jnp.int32)
        )
        forced_flat = (
            active & jnp.asarray(cfg.event_context_force_flat) & (pos_sign != 0)
        )
        blocked = (
            active
            & ~forced_flat
            & jnp.asarray(cfg.event_context_block_new_entries)
            & (pos_sign == 0)
            & ((before == 1) | (before == 2))
        )
        after = jnp.where(forced_flat, 3, jnp.where(blocked, 0, before))
        overridden = after != before
        diag = diag.at[EXEC_DIAG_INDEX["event_context_action_overrides"]].add(
            (overridden & live).astype(jnp.int32)
        )
        diag = diag.at[EXEC_DIAG_INDEX["event_context_blocked_entries"]].add(
            (blocked & live).astype(jnp.int32)
        )
        diag = diag.at[EXEC_DIAG_INDEX["event_context_forced_flat_actions"]].add(
            (forced_flat & live).astype(jnp.int32)
        )
        state = state._replace(exec_diag=diag)
    else:
        forced_flat = jnp.zeros_like(active)
        blocked = jnp.zeros_like(active)
        after = before

    event_info = {
        "event_context_no_trade_value": no_trade_value,
        "event_context_no_trade_active": active.astype(jnp.float32),
        "event_context_spread_stress_multiplier": spread_mult,
        "event_context_slippage_stress_multiplier": slip_mult,
        "event_context_execution_overlay": jnp.asarray(
            cfg.event_context_execution_overlay
        ),
        "event_context_action_before_overlay": before,
        "event_context_action_after_overlay": after,
        "event_context_action_overridden": after != before,
        "event_context_blocked_entry": blocked,
        "event_context_forced_flat": forced_flat,
        "event_context_position_before_overlay": pos_sign,
    }
    return after, state, event_info


def _record_action(state: EnvState, raw, a, cfg: EnvConfig, live) -> EnvState:
    """Per-episode action counters (reference app/env.py:744-761);
    inert when ``live`` is False (post-termination)."""
    one = live.astype(jnp.int32)
    diag = state.action_diag
    diag = diag.at[ACTION_DIAG_INDEX["steps"]].add(one)
    is_long = (a == 1) & live
    is_short = (a == 2) & live
    is_hold = ~is_long & ~is_short & live
    diag = diag.at[ACTION_DIAG_INDEX["long_actions"]].add(is_long.astype(jnp.int32))
    diag = diag.at[ACTION_DIAG_INDEX["short_actions"]].add(is_short.astype(jnp.int32))
    diag = diag.at[ACTION_DIAG_INDEX["non_hold_actions"]].add(
        (is_long | is_short).astype(jnp.int32)
    )
    diag = diag.at[ACTION_DIAG_INDEX["hold_actions"]].add(is_hold.astype(jnp.int32))
    if cfg.action_space_mode == "continuous":
        diag = diag.at[ACTION_DIAG_INDEX["continuous_deadband_actions"]].add(
            is_hold.astype(jnp.int32)
        )
    return state._replace(
        action_diag=diag,
        raw_abs_sum=state.raw_abs_sum + jnp.where(live, jnp.abs(raw), 0.0),
        raw_min=jnp.where(live, jnp.minimum(state.raw_min, raw), state.raw_min),
        raw_max=jnp.where(live, jnp.maximum(state.raw_max, raw), state.raw_max),
        last_raw_action=jnp.where(live, raw, state.last_raw_action),
        last_coerced_action=jnp.where(
            live, a.astype(jnp.int32), state.last_coerced_action
        ),
    )


import jax as _jax  # noqa: E402

_JIT_RESET = _jax.jit(reset, static_argnums=0)
_JIT_STEP = _jax.jit(step, static_argnums=0)
