"""JSON config load/save.

``compose_config`` persists only keys that differ from the repo defaults
(reference app/config_handler.py:11-17 semantics).  The reference's
vestigial remote HTTP load/save (app/config_handler.py:30-73) is
intentionally not reproduced; remote config belongs to the orchestration
layer, not the env package.
"""
import json
from pathlib import Path
from typing import Any, Dict

from gymfx_tpu.config.defaults import DEFAULT_VALUES


def load_config(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as fh:
        config = json.load(fh)
    if not isinstance(config, dict):
        raise ValueError("config file must contain a JSON object")
    return config


def compose_config(config: Dict[str, Any]) -> Dict[str, Any]:
    """Keep only non-default, JSON-serializable keys."""
    composed: Dict[str, Any] = {}
    for key, value in config.items():
        if key in DEFAULT_VALUES and DEFAULT_VALUES[key] == value:
            continue
        try:
            json.dumps(value)
        except (TypeError, ValueError):
            continue
        composed[key] = value
    return composed


def save_config(config: Dict[str, Any], path: str) -> None:
    out = Path(path)
    out.parent.mkdir(parents=True, exist_ok=True)
    with out.open("w", encoding="utf-8") as fh:
        json.dump(compose_config(config), fh, indent=2)
