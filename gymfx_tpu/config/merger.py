"""Layered config merge with fixed precedence.

Precedence (low -> high), matching the reference merge semantics
(reference app/config_merger.py:37-51):
    plugin defaults < repo defaults < config file < explicit CLI args
    (non-None) < unknown ``--key value`` args with type coercion.
"""
from typing import Any, Dict, Iterable, Mapping, Optional


def process_unknown_args(unknown_args: Iterable[str]) -> Dict[str, Any]:
    """Turn leftover ``--key value`` / ``--flag`` CLI tokens into a dict."""
    args = list(unknown_args)
    parsed: Dict[str, Any] = {}
    i = 0
    while i < len(args):
        key = args[i]
        if not key.startswith("--"):
            i += 1
            continue
        if i + 1 < len(args) and not args[i + 1].startswith("--"):
            parsed[key.lstrip("-")] = args[i + 1]
            i += 2
        else:
            parsed[key.lstrip("-")] = True
            i += 1
    return parsed


def convert_type(value: Any) -> Any:
    """Coerce CLI string values: bool / None / int / float / str."""
    if isinstance(value, bool):
        return value
    if not isinstance(value, str):
        return value
    lowered = value.strip().lower()
    if lowered in {"true", "false"}:
        return lowered == "true"
    if lowered in {"none", "null"}:
        return None
    try:
        return int(value)
    except ValueError:
        try:
            return float(value)
        except ValueError:
            return value


def merge_config(
    defaults: Optional[Mapping[str, Any]],
    plugin_params1: Optional[Mapping[str, Any]] = None,
    plugin_params2: Optional[Mapping[str, Any]] = None,
    file_config: Optional[Mapping[str, Any]] = None,
    cli_args: Optional[Mapping[str, Any]] = None,
    unknown_args: Optional[Mapping[str, Any]] = None,
) -> Dict[str, Any]:
    merged: Dict[str, Any] = {}
    merged.update(plugin_params1 or {})
    merged.update(plugin_params2 or {})
    merged.update(defaults or {})
    merged.update(file_config or {})
    for key, value in (cli_args or {}).items():
        if value is not None:
            merged[key] = value
    for key, value in (unknown_args or {}).items():
        merged[key] = convert_type(value)
    return merged
