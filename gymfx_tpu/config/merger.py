"""Layered config merge with fixed precedence.

Precedence (low -> high), matching the reference merge semantics
(reference app/config_merger.py:37-51):
    plugin defaults < repo defaults < config file < explicit CLI args
    (non-None) < unknown ``--key value`` args with type coercion.
"""
from typing import Any, Dict, Iterable, Mapping, Optional


def process_unknown_args(unknown_args: Iterable[str]) -> Dict[str, Any]:
    """Turn leftover ``--key value`` / ``--flag`` CLI tokens into a dict.

    A ``--key`` immediately followed by a non-flag token takes that
    token as its value; a ``--key`` followed by another flag (or by
    nothing) is a boolean switch.  Stray positional tokens with no
    preceding flag are ignored.  Semantics pinned by tests/test_config.py
    (reference behavior: app/config_merger.py unknown-arg passthrough).
    """
    parsed: Dict[str, Any] = {}
    pending: Optional[str] = None  # flag still waiting for its value
    for token in unknown_args:
        if token.startswith("--"):
            if pending is not None:
                parsed[pending] = True
            pending = token.lstrip("-")
        elif pending is not None:
            parsed[pending] = token
            pending = None
    if pending is not None:
        parsed[pending] = True
    return parsed


_LITERAL_VALUES: Dict[str, Any] = {
    "true": True,
    "false": False,
    "none": None,
    "null": None,
}


def convert_type(value: Any) -> Any:
    """Coerce CLI string values: literal bool/None, else the narrowest
    of int -> float -> str.  Non-strings pass through untouched."""
    if not isinstance(value, str):
        return value
    lowered = value.strip().lower()
    if lowered in _LITERAL_VALUES:
        return _LITERAL_VALUES[lowered]
    for parse in (int, float):
        try:
            return parse(value)
        except ValueError:
            continue
    return value


def merge_config(
    defaults: Optional[Mapping[str, Any]],
    plugin_params1: Optional[Mapping[str, Any]] = None,
    plugin_params2: Optional[Mapping[str, Any]] = None,
    file_config: Optional[Mapping[str, Any]] = None,
    cli_args: Optional[Mapping[str, Any]] = None,
    unknown_args: Optional[Mapping[str, Any]] = None,
) -> Dict[str, Any]:
    merged: Dict[str, Any] = {}
    merged.update(plugin_params1 or {})
    merged.update(plugin_params2 or {})
    merged.update(defaults or {})
    merged.update(file_config or {})
    for key, value in (cli_args or {}).items():
        if value is not None:
            merged[key] = value
    for key, value in (unknown_args or {}).items():
        merged[key] = convert_type(value)
    return merged
