"""Repo-level config defaults.

Same key surface as the reference defaults (reference app/config.py:1-47)
so a gym-fx user can bring an existing JSON config unchanged, plus
TPU-framework keys (batching, mesh, training) that the reference does not
have because it is single-process Python.
"""

DEFAULT_VALUES = {
    # execution
    "mode": "inference",  # training|optimization|inference
    "driver_mode": "buy_hold",  # random|buy_hold|flat|replay|policy
    "steps": 500,

    # plugin selection (registry names; mirrors reference entry-point names)
    "data_feed_plugin": "default_data_feed",
    "broker_plugin": "default_broker",
    "strategy_plugin": "default_strategy",
    "preprocessor_plugin": "default_preprocessor",
    "reward_plugin": "pnl_reward",
    "metrics_plugin": "default_metrics",

    # data + symbol
    "input_data_file": "examples/data/eurusd_sample.csv",  # repo-root relative
    "date_column": "DATE_TIME",
    "price_column": "CLOSE",
    "instrument": "EUR_USD",
    "timeframe": "M1",
    "headers": True,
    "max_rows": None,

    # env and execution settings
    "window_size": 32,
    "initial_cash": 10000.0,
    "position_size": 1.0,
    "simulation_engine": "scan",  # the XLA scan engine (reference: backtrader|nautilus)
    "execution_cost_profile": None,
    "commission": 0.0,
    "slippage": 0.0,
    "leverage": 1.0,
    "min_equity": None,  # default: 1% of initial_cash (reference app/env.py:122)
    # opt-in scan-engine venue quantization: fills/brackets on the
    # instrument's tick grid, order sizes on its size step, min_quantity
    # denial — the replay venue's book semantics (DIVERGENCES #9d closed)
    "venue_quantization": False,
    # execution venue: "bar" = broker scan (next-open fills, H/L
    # brackets); "lob" = the vectorized limit-order-book engine
    # (gymfx_tpu/lob/, docs/lob.md) — agent orders walk a seeded book
    # driven by a deterministic per-bar message flow
    "venue": "bar",
    "lob_depth_levels": 24,      # book price levels per side
    "lob_queue_slots": 4,        # FIFO orders per level
    "lob_messages_per_bar": 64,  # flow messages per bar (static shape)
    "lob_seed_levels": 8,        # seeded depth levels per side at open
    "lob_flow_seed": 0,          # order-flow PRNG seed
    "lob_scenario": "lob_calm",  # lob_calm|lob_trend|lob_volatile|lob_thin|lob_flash_crash
    "lob_tick_size": 1e-5,       # quote-currency size of one book tick
    "lob_lot_units": 0.0,        # units per lot (0 = position_size)
    # data feed: "replay" = the CSV tape (input_data_file); "scengen" =
    # the seed-deterministic generative scenario engine
    # (gymfx_tpu/scengen/, docs/scenarios.md) — same MarketData pipeline,
    # no file needed
    "feed": "replay",
    "scengen_preset": "regime_mix",  # scengen/params.py preset registry
    "scengen_bars": 2048,            # generated tape length in bars
    "scengen_seed": 0,               # generation PRNG seed (decoupled
                                     # from the training seed)
    "scengen_pairs": None,           # portfolio pair list (None = the
                                     # default 4 USD-quote pairs)
    # snap generated OHLC onto the lob_tick_size grid at synthesis (f64,
    # before the f32 cast) so scengen tapes satisfy data_compress's
    # on-grid requirement; False = bitwise-identical generation
    "scengen_snap_to_tick": False,
    "action_space_mode": "discrete",  # discrete|continuous
    "continuous_action_threshold": 0.33,
    "seed": 0,

    # optional replay actions
    "replay_actions_file": None,

    # config I/O
    "remote_log": None,
    "remote_load_config": None,
    "remote_save_config": None,
    "username": None,
    "password": None,
    "load_config": None,
    "save_config": "./config_out.json",
    "save_log": "./debug_out.json",
    "results_file": "./results.json",
    "quiet_mode": False,

    # ---- TPU-framework keys (new capability; no reference counterpart) ----
    "num_envs": 1,            # vmapped env batch size
    "compute_dtype": "float32",   # float32 on TPU; float64 for oracle checks
    "mesh_shape": None,       # e.g. {"data": 4, "model": 2}; None = single device
    "train_total_steps": 1_000_000,
    "checkpoint_dir": None,
    # out-of-sample evaluation: hold out the LAST fraction of bars
    # (chronological split) or evaluate on a separate file
    "eval_split": None,
    "eval_data_file": None,
    # policy: unset by default — PPO defaults to "mlp", IMPALA to "lstm";
    # pass --policy mlp|lstm|transformer|transformer_ring|
    # transformer_ulysses to override.
    "policy": None,

    # ---- resilience (docs/resilience.md) ----
    # in-jit non-finite guard on every train step: skip poisoned
    # minibatches (keep last-good params/opt state) instead of
    # propagating NaN into the weights
    "nonfinite_guard": True,
    # abort training after this many CONSECUTIVE fully-skipped steps
    "guard_max_consecutive_skips": 10,
    # preemption-safe periodic auto-checkpointing: save every N env
    # steps into checkpoint_dir (0 = final save only)
    "checkpoint_every": 0,
    # deterministic fault-injection profile for chaos tests, e.g.
    # "nan_bars=30-31;transport=http:503,http:503,ok;preempt_at=2;seed=7"
    "fault_profile": None,
    # ---- elastic degraded-mesh training (docs/resilience.md,
    # "Elastic training") — every knob below unset keeps today's code
    # paths bitwise identical (pinned by tests/test_elastic.py) ----
    # master switch: route the training entry through the elastic
    # auto-resume controller (parallel/elastic.py run_elastic) — on
    # device loss the mesh is re-planned over the survivors and the run
    # resumes from the last digest-verified checkpoint
    "elastic_resume": False,
    # bounded retry budget: how many device-loss resumes before the
    # error propagates (each retry shrinks the mesh further)
    "elastic_max_retries": 2,
    # host-side backoff between a device loss and its resume attempt
    "elastic_backoff_s": 0.0,
    # honor-or-reject when num_envs / pbt_population no longer divide
    # the survivor mesh's data axis: "repartition" shrinks the data
    # axis to the largest size that still divides the batch;
    # "reject" raises ElasticReplanError instead of changing the
    # env->shard mapping
    "elastic_shrink_policy": "repartition",  # repartition | reject
    # checkpoint retention: keep only the newest N step dirs (digest +
    # empty-leaves sidecars pruned with them); 0 = keep everything.
    # The step an active resume points at is never pruned.
    "checkpoint_keep": 0,

    # ---- dispatch / memory (docs/performance.md) ----
    # superstep driver: fuse K train steps into one donated lax.scan
    # dispatch; metrics (incl. guard counters) accumulate on device and
    # are fetched once per superstep (1 = per-step dispatch)
    "supersteps_per_dispatch": 1,
    # stream the bar history host->device in double-buffered shards when
    # the resident MarketData would exceed this many MiB (None = always
    # resident); rollout-only — trainers need the full history resident
    "stream_hbm_budget_mb": None,
    # int16 tick-delta wire format for streamed shards and the
    # curriculum tape library (data/compress.py, docs/performance.md
    # "Billion-bar data path"): off = f32 everywhere (bitwise-identical
    # default), on = fused Pallas decode on TPU, interpret = the same
    # kernel interpreted (CPU-testable bitwise oracle)
    "data_compress": "off",
    # feed=curriculum tape registry: 'file:PATH[@W],scengen:PRESET[@W]'
    # string or a JSON list of {file|scengen, weight, ...} dicts
    # (data/tapes.py); tape 0 is the environment's own dataset
    "tapes": None,
    # PCG64 seed for the weighted tape draws (None = the training seed)
    "curriculum_seed": None,
    # PPO minibatch source: env-permuted trajectory minibatches
    # (contiguous update-phase DMA; measured 12.4M vs 8.3M steps/s at
    # 8192 envs with identical held-out learning — the round-5 fix,
    # examples/results/minibatch_scheme_parity.json) vs the classic
    # flattened sample permutation.  env_permute needs num_envs
    # divisible by ppo_minibatches; configs where that cannot hold
    # (num_envs < ppo_minibatches, e.g. the single-env inference
    # default) degrade to sample_permute with a warning at the
    # from-config entry points (train/common.resolve_minibatch_scheme)
    "ppo_minibatch_scheme": "env_permute",  # env_permute | sample_permute
    # per-step fused feature scaling in the rollout (pallas kernel,
    # ops/window_zscore.fused_step_obs): off = plain XLA (the bitwise
    # oracle), on = pallas on TPU / XLA fallback elsewhere, interpret =
    # pallas interpret mode anywhere (CPU parity tests)
    "rollout_obs_kernel": "off",
    # fused env-dynamics kernel family (ops/env_dynamics.py): the bar
    # venue's fill/bracket/financing chain and the mark/reward chain as
    # two env-blocked pallas VMEM passes bracketing the strategy kernel.
    # off = plain XLA (the bitwise oracle), on = pallas on TPU / XLA
    # fallback elsewhere, interpret = pallas interpret mode anywhere
    "rollout_env_kernel": "off",
    # pallas LOB stream matching (ops/lob_match.py): sort-free ranked
    # matcher with exact int32 parity vs lob/book.py; same mode contract
    "lob_match_kernel": "off",
    # storage dtype for the COLLECTED trajectory obs (the widest rollout
    # buffers): bfloat16 halves trajectory write+read HBM traffic;
    # actions/log-probs/values always stay f32 so PPO ratio numerics
    # are untouched (quality-parity gate: docs/performance.md)
    "rollout_collect_dtype": "float32",  # float32 | bfloat16
    # opt-in bf16 optimizer state: Adam's first moment (the largest
    # optimizer buffer) stored in bfloat16; params and the second moment
    # stay float32 (the master-weight rule).  Gated by a learning-parity
    # smoke (tests/test_opt_state_dtype.py), off by default
    "optimizer_state_dtype": "float32",  # float32 | bfloat16
    # overlap superstep driver (train/common.make_train_many_overlapped):
    # iteration i's rollout is issued against pre-update params while
    # iteration i-1's update GEMMs execute, so the XLA scheduler can
    # overlap the two phases.  Opt-in: rollouts see one-update-stale
    # params and guard-quarantine env resets are dropped inside a
    # dispatch (docs/performance.md, "MFU push")
    "superstep_overlap": False,
    # rematerialize the policy forward in the PPO loss (jax.remat): the
    # update phase recomputes activations inside the backward GEMM chain
    # instead of staging them through HBM — numerically identical,
    # memory-traffic win on TPU
    "ppo_update_remat": False,
    # live-path retry/backoff + circuit breaker (oanda_broker plugin)
    "live_retry_max_attempts": 4,
    "live_retry_base_delay": 0.25,
    "live_retry_max_delay": 8.0,
    "live_retry_timeout": 30.0,
    "live_retry_budget": 64,
    "live_breaker_threshold": 5,
    "live_breaker_recovery_time": 30.0,

    # ---- serving (gymfx_tpu/serve/, docs/serving.md) ----
    # AOT-compiled padded-batch ladder: every bucket compiles at boot so
    # the decision path never traces (bench_infer.py)
    "serve_buckets": [1, 8, 64, 512, 4096],
    # micro-batcher coalescing window: max extra latency a request pays
    # to share a dispatch with concurrent sessions
    "serve_max_batch_wait_ms": 2.0,
    # auto = matmul on TPU (MXU batching), exact elsewhere (responses
    # bit-identical to the unbatched policy at every bucket size)
    "serve_batch_mode": "auto",
    # compile + run every bucket at engine construction (False defers
    # to first use — only for tooling that never serves)
    "serve_warmup": True,
    # ---- serving overload resilience (docs/serving.md, "Overload
    # behavior") — admission control is OFF by default (unbounded
    # queue, no deadlines), so the bare serving path behaves exactly
    # as before; production configs bound both.
    # admission queue capacity (requests queued ahead of the batching
    # window); null = unbounded
    "serve_max_queue": None,
    # full-queue shed policy: reject (newest submit fails fast with
    # ShedError) | evict_oldest (oldest queued request is dropped so the
    # freshest data wins)
    "serve_shed_policy": "reject",
    # per-request deadline; a request that cannot dispatch before it
    # fails fast with DeadlineExceeded instead of occupying a batch
    # slot.  null = no deadline
    "serve_deadline_ms": None,
    # live degraded-mode fallback when the serving path sheds / misses
    # a deadline / trips the breaker: hold (keep the current pending
    # target, no venue traffic) | flat (route to flat) | reject (raise
    # the typed error to the caller)
    "serve_fallback": "hold",
    # serving circuit breaker around engine dispatch: consecutive
    # dispatch failures to trip OPEN (0 disables), and the open ->
    # half-open recovery window
    "serve_breaker_threshold": 5,
    "serve_breaker_recovery_s": 5.0,
    # live stale-feed watchdog: when the gap since the previous bar
    # exceeds this many seconds, PolicyDecisionService decides via the
    # fallback policy instead of acting on a stale window.  null = off
    "feed_stale_after_s": None,
    # ---- device-resident sessions (docs/serving.md, "Device-resident
    # sessions") — recurrent session carry cached in pre-allocated
    # device slot arrays; each dispatch passes only slot indices + obs
    # through a fused gather->policy->scatter program (zero per-decision
    # carry transfers).  0 keeps the host-carry serving path bitwise
    # identical to the pre-slot code.
    "serve_session_slots": 0,
    # one-dispatch-late host mirror of dirty slots: the failover /
    # blue-green carry-handoff contract.  Only read with slots enabled
    "serve_slot_mirror": True,
    # pipelined batch assembly: the micro-batcher fills double-buffered
    # host staging while the previous batch's executable runs, and
    # resolves batch N only after batch N+1 is dispatched.  Only
    # engages with serve_session_slots > 0
    "serve_staging": True,
    # ---- continuous deployment (docs/serving.md, "Hot-swap and
    # blue/green"; docs/resilience.md) — only read when a
    # BlueGreenDeployer / deploy controller is constructed; a plain
    # engine + batcher session never touches these.
    # pinned-obs rows per shadow-parity probe run against the standby
    # engine before a promote flips routing; 0 disables the probe
    "serve_swap_parity_probe": 4,
    # run the scenario gate in --quick mode inside the deploy
    # controller's train->gate->swap loop (full matrix when False)
    "deploy_gate_quick": True,

    # ---- decision fleet (docs/serving.md, "Decision fleet") — only
    # read when serve_fleet_replicas >= 1; with it at 0 the serving path
    # is the single engine + micro-batcher pair, bitwise identical to
    # the pre-fleet code.
    # active replicas behind the fleet front-end; 0 = fleet off
    "serve_fleet_replicas": 0,
    # warm standby engines booted alongside (promoted on failover)
    "serve_fleet_standbys": 1,
    # fleet-wide admission gate: total queued requests across replicas
    # before submits shed with reason "fleet_queue_full"; null = no gate
    # (per-replica serve_max_queue still applies)
    "serve_fleet_max_queue": None,
    # supervisor probe cadence / per-probe timeout / pinned-obs rows
    "serve_fleet_probe_interval_s": 0.25,
    "serve_fleet_probe_timeout_s": 2.0,
    "serve_fleet_probe_rows": 2,
    # probe latency above this marks a replica degraded (new sessions
    # avoid it); consecutive probe FAILURES at/above dead_after mark it
    # dead and trigger failover
    "serve_fleet_degraded_latency_ms": 250.0,
    "serve_fleet_dead_after": 1,
    # replica-death re-routes per request before its future fails with
    # the underlying error
    "serve_fleet_retry_limit": 2,
    # SessionStateStore capacity: LRU-evicted beyond this many sessions
    "serve_fleet_max_sessions": 1000000,

    # ---- telemetry (gymfx_tpu/telemetry/, docs/observability.md) ----
    # ALL off by default: with every telemetry_* knob unset,
    # telemetry_from_config returns None and the train/serve hot paths
    # are bitwise identical to the pre-telemetry code.
    # master switch: metrics registry + device metric drain + serve
    # instruments
    "telemetry_enabled": False,
    # rotating JSONL sink path for structured rows (metric snapshots,
    # spans, run summaries); null = no sink
    "telemetry_jsonl": None,
    # host-side span records around supersteps/serve dispatch (plus
    # jax.profiler TraceAnnotation regions under an active trace)
    "telemetry_spans": False,
    # /metrics (Prometheus) + /healthz (JSON) endpoint port for the
    # serving stack; 0 = ephemeral, null = no endpoint
    "telemetry_http_port": None,
    # rolling window for the serving SLO gauges (shed_rate,
    # deadline_miss_rate, p99 over the last N seconds)
    "telemetry_slo_window_s": 60.0,

    # ---- run forensics (ledger / compile watch / flight recorder) ----
    # append-only schema-pinned JSONL run ledger path (lifecycle events:
    # compiles, superstep dispatches, checkpoints, preemption,
    # divergence, gate verdicts, bench rows); null = no ledger
    "telemetry_ledger": None,
    # directory for flight-recorder postmortem bundles (last-K superstep
    # metric stacks + rng key + resilience snapshot + compile events,
    # dumped on divergence/watchdog/preemption); null = no recorder
    "telemetry_flight_recorder_dir": None,
    # ring-buffer depth: how many drained superstep frames a postmortem
    # bundle retains
    "telemetry_flight_recorder_k": 8,
    # install jax.monitoring compile listeners + executable
    # fingerprinting (gymfx_compile_* metrics, silent-recompile and
    # serve-bucket-miss detection)
    "telemetry_compile_watch": False,

    # ---- performance observatory (telemetry/profiler.py) ----
    # capture-bundle directory for managed jax.profiler traces around
    # superstep windows (manifest + scope map + profile_capture ledger
    # event; read back by tools/profile_report.py); null = no profiling
    "telemetry_profile_dir": None,
    # comma-separated superstep indices to capture ("1" or "1,8");
    # null with profile_dir set = capture superstep 1 (the first
    # dispatch whose window holds no jit compile)
    "telemetry_profile_supersteps": None,
    # additionally capture every Nth superstep; 0 = off
    "telemetry_profile_every": 0,
}
