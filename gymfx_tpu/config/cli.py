"""CLI argument surface — same flags as the reference CLI
(reference app/cli.py:4-37) plus TPU-framework flags.  Unknown
``--key value`` pairs pass through into the config with type coercion.
"""
import argparse


def parse_args(argv=None):
    parser = argparse.ArgumentParser(
        description="gymfx-tpu runtime (TPU-native env + trainer)."
    )
    parser.add_argument("--mode", choices=["training", "optimization", "inference"])
    parser.add_argument(
        "--driver_mode", choices=["random", "buy_hold", "flat", "replay", "policy"]
    )
    parser.add_argument("--steps", type=int)

    parser.add_argument("--input_data_file", type=str)
    parser.add_argument("--date_column", type=str)
    parser.add_argument("--price_column", type=str)
    parser.add_argument("--headers", action="store_true", default=None)
    parser.add_argument("--max_rows", type=int)

    parser.add_argument("--window_size", type=int)
    parser.add_argument("--initial_cash", type=float)
    parser.add_argument("--position_size", type=float)
    parser.add_argument("--commission", type=float)
    parser.add_argument("--slippage", type=float)
    parser.add_argument("--seed", type=int)

    parser.add_argument("--data_feed_plugin", type=str)
    parser.add_argument("--broker_plugin", type=str)
    parser.add_argument("--strategy_plugin", type=str)
    parser.add_argument("--preprocessor_plugin", type=str)
    parser.add_argument("--reward_plugin", type=str)
    parser.add_argument("--metrics_plugin", type=str)

    # execution venue (docs/lob.md)
    parser.add_argument("--venue", choices=["bar", "lob"])
    parser.add_argument("--lob_depth_levels", type=int)
    parser.add_argument("--lob_queue_slots", type=int)
    parser.add_argument("--lob_messages_per_bar", type=int)
    parser.add_argument("--lob_seed_levels", type=int)
    parser.add_argument("--lob_flow_seed", type=int)
    parser.add_argument(
        "--lob_scenario",
        choices=["lob_calm", "lob_trend", "lob_volatile", "lob_thin",
                 "lob_flash_crash"],
    )
    parser.add_argument("--lob_tick_size", type=float)
    parser.add_argument("--lob_lot_units", type=float)

    # data feed: replayed CSV tape vs the generative scenario engine
    # (docs/scenarios.md)
    parser.add_argument("--feed", choices=["replay", "scengen", "curriculum"])
    parser.add_argument(
        "--scengen_preset",
        choices=["regime_mix", "trend_calm", "range_chop", "flash_crash",
                 "gap_open", "liquidity_drought", "multi_asset_calm",
                 "multi_asset_stress"],
    )
    parser.add_argument("--scengen_bars", type=int)
    parser.add_argument("--scengen_seed", type=int)
    parser.add_argument(
        "--scengen_snap_to_tick", action="store_true", default=None
    )

    # billion-bar data path (docs/performance.md): compressed tapes and
    # the dataset-of-tapes curriculum registry
    parser.add_argument(
        "--data_compress", choices=["off", "on", "interpret"]
    )
    parser.add_argument("--tapes", type=str)
    parser.add_argument("--curriculum_seed", type=int)

    parser.add_argument("--replay_actions_file", type=str)
    parser.add_argument("--results_file", type=str)
    parser.add_argument("--load_config", type=str)
    parser.add_argument("--save_config", type=str)
    parser.add_argument("--quiet_mode", action="store_true", default=None)

    # TPU-framework flags
    parser.add_argument("--num_envs", type=int)
    parser.add_argument(
        "--policy",
        choices=["mlp", "lstm", "transformer", "transformer_ring",
                 "transformer_ulysses"],
    )
    parser.add_argument("--checkpoint_dir", type=str)
    parser.add_argument("--train_total_steps", type=int)

    # resilience flags (docs/resilience.md)
    parser.add_argument("--checkpoint_every", type=int)
    parser.add_argument("--fault_profile", type=str)
    parser.add_argument("--guard_max_consecutive_skips", type=int)

    # elastic degraded-mesh training (docs/resilience.md, "Elastic
    # training"): auto-resume on survivor meshes after device loss
    parser.add_argument(
        "--elastic_resume", action="store_true", default=None
    )
    parser.add_argument("--elastic_max_retries", type=int)
    parser.add_argument("--elastic_backoff_s", type=float)
    parser.add_argument(
        "--elastic_shrink_policy", choices=["repartition", "reject"]
    )
    parser.add_argument("--checkpoint_keep", type=int)

    # pod-scale mesh (docs/performance.md, "Scaling out"); JSON axis
    # sizes, e.g. '{"data": 8}' or '{"data": 16, "model": 2}'
    parser.add_argument("--mesh_shape", type=str)

    # dispatch / memory flags (docs/performance.md)
    parser.add_argument("--supersteps_per_dispatch", type=int)
    parser.add_argument("--stream_hbm_budget_mb", type=float)
    parser.add_argument(
        "--ppo_minibatch_scheme", choices=["env_permute", "sample_permute"]
    )
    parser.add_argument(
        "--rollout_obs_kernel", choices=["off", "on", "interpret"]
    )
    parser.add_argument(
        "--rollout_env_kernel", choices=["off", "on", "interpret"]
    )
    parser.add_argument(
        "--lob_match_kernel", choices=["off", "on", "interpret"]
    )
    parser.add_argument(
        "--rollout_collect_dtype", choices=["float32", "bfloat16"]
    )
    parser.add_argument(
        "--optimizer_state_dtype", choices=["float32", "bfloat16"]
    )
    parser.add_argument(
        "--superstep_overlap", action="store_true", default=None
    )
    parser.add_argument(
        "--ppo_update_remat", action="store_true", default=None
    )

    # serving flags (docs/serving.md); buckets as JSON, e.g. "[1,8,64]"
    parser.add_argument("--serve_buckets", type=str)
    parser.add_argument("--serve_max_batch_wait_ms", type=float)
    parser.add_argument(
        "--serve_batch_mode", choices=["auto", "exact", "matmul"]
    )

    # serving overload resilience (docs/serving.md, "Overload behavior")
    parser.add_argument("--serve_max_queue", type=int)
    parser.add_argument(
        "--serve_shed_policy", choices=["reject", "evict_oldest"]
    )
    parser.add_argument("--serve_deadline_ms", type=float)
    parser.add_argument(
        "--serve_fallback", choices=["hold", "flat", "reject"]
    )
    parser.add_argument("--serve_breaker_threshold", type=int)
    parser.add_argument("--serve_breaker_recovery_s", type=float)
    parser.add_argument("--feed_stale_after_s", type=float)

    # device-resident sessions (docs/serving.md, "Device-resident
    # sessions"); 0 slots = the host-carry serving path
    parser.add_argument("--serve_session_slots", type=int)
    parser.add_argument(
        "--serve_slot_mirror", action="store_true", default=None
    )
    parser.add_argument(
        "--serve_staging", action="store_true", default=None
    )

    # telemetry (docs/observability.md); all off unless set
    parser.add_argument(
        "--telemetry_enabled", action="store_true", default=None
    )
    parser.add_argument("--telemetry_jsonl", type=str)
    parser.add_argument(
        "--telemetry_spans", action="store_true", default=None
    )
    parser.add_argument("--telemetry_http_port", type=int)
    parser.add_argument("--telemetry_slo_window_s", type=float)

    # run forensics (docs/observability.md: ledger / compile watch /
    # flight recorder); all off unless set
    parser.add_argument("--telemetry_ledger", type=str)
    parser.add_argument("--telemetry_flight_recorder_dir", type=str)
    parser.add_argument("--telemetry_flight_recorder_k", type=int)
    parser.add_argument(
        "--telemetry_compile_watch", action="store_true", default=None
    )

    # performance observatory (docs/observability.md: managed
    # jax.profiler capture + measured-MFU reports); off unless set
    parser.add_argument("--telemetry_profile_dir", type=str)
    parser.add_argument("--telemetry_profile_supersteps", type=str)
    parser.add_argument("--telemetry_profile_every", type=int)

    return parser.parse_known_args(argv)
