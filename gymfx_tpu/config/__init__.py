from gymfx_tpu.config.defaults import DEFAULT_VALUES
from gymfx_tpu.config.merger import convert_type, merge_config, process_unknown_args
from gymfx_tpu.config.handler import (
    compose_config,
    load_config,
    save_config,
)

__all__ = [
    "DEFAULT_VALUES",
    "convert_type",
    "merge_config",
    "process_unknown_args",
    "compose_config",
    "load_config",
    "save_config",
]
