"""Benchmark helpers: XLA-counted FLOPs and model FLOPs utilization.

MFU here is defined against the XLA cost model of the FULL compiled
train step (policy matmuls + optimizer + env arithmetic — the env's
elementwise math is a rounding error next to the policy GEMMs), divided
by the chip's public peak dense-bf16 throughput.  That makes it an
end-to-end hardware-utilization number for the fused
rollout+update program, reproducible from the compiled executable
alone (no hand-counted FLOP formulas to drift out of date).
"""
from __future__ import annotations

import time
from typing import Any, Optional


def ensure_cpu_if_requested() -> None:
    """Tool-entry alias for ``parallel.mesh.honor_jax_platforms_env``
    (ONE definition of the sitecustomize-override workaround)."""
    from gymfx_tpu.parallel.mesh import honor_jax_platforms_env

    honor_jax_platforms_env()


def probe_device(
    metric: str,
    *,
    unit: str = "",
    timeout_s: int = 240,
    extra: Optional[dict] = None,
) -> None:
    """Fail fast with a diagnostic JSON line when the accelerator is
    unreachable.  A wedged device tunnel blocks the first device op
    inside the C++ runtime, where Python signal handlers never run —
    so the watchdog is a daemon timer that prints (in the calling
    benchmark's own metric schema, hence the parameters) and
    hard-exits.  Only the probe is timed: a slow-but-healthy benchmark
    run is never killed."""
    import json
    import os
    import threading

    def on_timeout():
        record = {
            "metric": metric,
            "value": 0.0,
            "unit": f"{unit} (BENCH ABORTED: device probe timed out — "
                    "accelerator unreachable)",
        }
        record.update(extra or {})
        print(json.dumps(record), flush=True)
        os._exit(0)

    timer = threading.Timer(timeout_s, on_timeout)
    timer.daemon = True
    timer.start()
    import jax.numpy as jnp

    (jnp.ones((8, 8)) @ jnp.ones((8, 8))).block_until_ready()
    timer.cancel()


# 20 timed iterations by default: each dispatch pays ~10ms host->device
# round-trip over the remote-device tunnel, so short runs understate
# steady-state throughput by ~6% (measured r4: 7.05M at 5 iters vs
# 8.44M at 20 on identical code).
DEFAULT_BENCH_ITERS = 20


def measure_train_step(trainer: Any, state: Any, iters: int):
    """One shared timing harness for every benchmark: AOT-compile once
    (cost analysis + execution off the same executable), warmup, timed
    loop.  Returns ``(seconds, flops_per_iter, final_state, step)`` —
    ``step`` is the compiled callable so callers (e.g. the profiler
    capture) never trigger a second compilation of the same program."""
    import jax

    compiled, flops = compile_with_flops(trainer._train_step, state)
    step = compiled if compiled is not None else trainer.train_step
    state, _ = step(state)  # warmup
    jax.block_until_ready(state)  # whole pytree: works for every trainer
    t0 = time.perf_counter()
    for _ in range(iters):
        state, _metrics = step(state)
    jax.block_until_ready(state)
    return time.perf_counter() - t0, flops, state, step

def measure_train_many(trainer: Any, state: Any, dispatches: int, k: int):
    """Superstep twin of :func:`measure_train_step`: times ``dispatches``
    invocations of the compiled K-step ``train_many`` program (one
    donated lax.scan dispatch per K train steps).  Returns ``(seconds,
    flops_per_dispatch, final_state, step)`` — divide seconds by
    ``dispatches * k`` for per-train-step time."""
    import jax

    compiled, flops = compile_with_flops(trainer._train_many, state, k)
    if compiled is not None:
        step = compiled  # static k is baked into the executable
    else:
        step = lambda s: trainer.train_many(s, k)  # noqa: E731
    state, _ = step(state)  # warmup
    jax.block_until_ready(state)
    t0 = time.perf_counter()
    for _ in range(dispatches):
        state, _metrics = step(state)
    jax.block_until_ready(state)
    return time.perf_counter() - t0, flops, state, step


def measure_phase_split(trainer: Any, state: Any, iters: int):
    """Phase-attributed twin of :func:`measure_train_step`: times the
    rollout and update halves of the train step as two donated-carry
    sub-programs compiled off the same phase methods the fused step is
    composed from (``_rollout_phase`` / ``_update_phase``), so the split
    is measured on real executables rather than inferred.

    The sum slightly overstates the fused step (two dispatches, a
    host sync between phases, and no cross-phase fusion), so callers
    should report the *fraction* against the fused per-step time.
    Returns ``(rollout_seconds, update_seconds, final_state,
    update_flops)`` — ``update_flops`` is the XLA cost-model FLOPs of
    the compiled update phase (the GEMM chain), None where the backend
    hides cost analysis — or ``None`` when the trainer has no phase
    methods.
    """
    import jax

    if not (hasattr(trainer, "_rollout_phase")
            and hasattr(trainer, "_update_phase")):
        return None

    r_jit = jax.jit(trainer._rollout_phase, donate_argnums=0)
    u_jit = jax.jit(trainer._update_phase, donate_argnums=(0, 1))
    r_step, _ = compile_with_flops(r_jit, state)
    if r_step is None:
        r_step = r_jit
    inter, rollout_out = r_step(state)
    u_step, u_flops = compile_with_flops(u_jit, inter, rollout_out)
    if u_step is None:
        u_step = u_jit
    state, _ = u_step(inter, rollout_out)  # warmup both phases
    jax.block_until_ready(state)

    rollout_s = update_s = 0.0
    for _ in range(iters):
        t0 = time.perf_counter()
        inter, rollout_out = r_step(state)
        jax.block_until_ready((inter, rollout_out))
        t1 = time.perf_counter()
        state, _metrics = u_step(inter, rollout_out)
        jax.block_until_ready(state)
        update_s += time.perf_counter() - t1
        rollout_s += t1 - t0
    return rollout_s, update_s, state, u_flops


def stamp_comparability(record: dict, device: Any = None) -> dict:
    """Stamp the comparability triple the bench sentinel gates on:
    ``platform`` / ``device_kind`` (where the row was measured) and
    ``comparable`` (False on CPU proxies unless the caller already
    decided).  Shared by ``emit_bench_record`` and the record builders
    that print their own contract line (tools/multichip_bench.py)."""
    try:
        if device is None:
            import jax

            device = jax.local_devices()[0]
        platform = str(getattr(device, "platform", "unknown"))
        device_kind = str(getattr(device, "device_kind", platform))
    except Exception:
        platform = device_kind = "unknown"
    record.setdefault("platform", platform)
    record.setdefault("device_kind", device_kind)
    # CPU rows are functional proxies, never trajectory anchors; any
    # explicit caller verdict wins over the platform heuristic
    record.setdefault("comparable", record["platform"] not in ("cpu", "unknown"))
    return record


def emit_bench_record(
    record: dict,
    *,
    analytic_flops: Optional[float] = None,
    step_time_s: Optional[float] = None,
    device: Any = None,
) -> dict:
    """ONE row-construction path for every benchmark emitter (bench.py
    ppo/lob/scengen mains, tools/tpu_bench.py sweep rows): append the
    telemetry/mfu.py analytic-MFU slice — analytic_flops_per_step /
    hw_flops_peak / mfu_analytic / device_memory_bytes, every key
    always present, null where the backend or workload cannot say
    (CPU peak FLOPs; integer workloads with no FLOP model) — plus the
    comparability stamp the bench sentinel gates on: ``platform`` /
    ``device_kind`` (where the row was measured) and ``comparable``
    (False on CPU proxies unless the caller already decided), then
    print the record as the single JSON contract line and return it.
    When a run ledger is active the row is also ledgered."""
    import json

    from gymfx_tpu.telemetry.mfu import mfu_report

    record.update(mfu_report(analytic_flops, step_time_s, device))
    stamp_comparability(record, device=device)
    try:
        from gymfx_tpu.telemetry.ledger import get_active_ledger

        ledger = get_active_ledger()
        if ledger is not None:
            ledger.record(
                "bench_row", metric=record.get("metric"),
                value=record.get("value"),
                comparable=record.get("comparable"),
                platform=record.get("platform"),
            )
    except Exception:
        pass
    print(json.dumps(record), flush=True)
    return record


# Public per-chip peak dense bf16 FLOPs/sec (vendor-published specs).
PEAK_BF16_FLOPS = {
    "v6e": 918e12,
    "v6 lite": 918e12,
    "trillium": 918e12,
    "v5p": 459e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5litepod": 197e12,
    "v4": 275e12,
}


def device_peak_flops(device: Any) -> Optional[float]:
    """Peak dense-bf16 FLOPs/sec of ``device``, or None when unknown
    (CPU, or a TPU generation missing from the table)."""
    kind = str(getattr(device, "device_kind", "")).lower()
    if not kind:
        return None
    for key in sorted(PEAK_BF16_FLOPS, key=len, reverse=True):
        if key in kind:
            return PEAK_BF16_FLOPS[key]
    return None


def compile_with_flops(jitted_fn: Any, *args: Any):
    """AOT-compile ``jitted_fn`` for ``args`` ONCE and read the XLA cost
    analysis off the same executable: ``(compiled_or_None,
    flops_or_None)``.  Benchmarks execute the returned executable
    directly, so the program is never compiled a second time through the
    jit dispatch cache."""
    try:
        compiled = jitted_fn.lower(*args).compile()
    except Exception:
        return None, None
    flops = None
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0] if analysis else None
        if analysis:
            raw = analysis.get("flops")
            if raw and raw > 0:
                flops = float(raw)
    except Exception:
        pass
    return compiled, flops


def compiled_step_flops(jitted_fn: Any, *args: Any) -> Optional[float]:
    """FLOPs of one invocation per the XLA cost analysis; None when the
    backend does not expose it (compiles as a side effect — benchmarks
    should use :func:`compile_with_flops` and keep the executable)."""
    return compile_with_flops(jitted_fn, *args)[1]


def mfu(flops_per_iter: Optional[float], iters: int, seconds: float,
        device: Any) -> Optional[float]:
    """Achieved / peak FLOPs fraction, or None when either side is
    unknown."""
    peak = device_peak_flops(device)
    if not (flops_per_iter and peak and seconds > 0):
        return None
    return (flops_per_iter * iters / seconds) / peak
