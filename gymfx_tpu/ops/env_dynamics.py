"""Pallas TPU kernels: fused per-step env dynamics (broker + reward).

The bar venue's hot loop spends its non-GEMM time in two chains of
small elementwise ops over per-env ledger scalars, each materializing
(envs,)-wide intermediates in HBM dozens of times per step:

  A. ``broker.fill_pending`` -> ``broker.check_brackets`` (+ the FX
     financing accrual) — the order/bracket chain at the bar open;
  B. ``broker.mark_to_market`` -> ``rewards.compute_reward`` — the
     equity mark + reward at the bar close.

The strategy kernel sits between the two in ``core/env.step``, so the
family is TWO env-blocked pallas VMEM passes bracketing it (not one) —
no reordering of the XLA program, which is what keeps the parity
argument trivial.  Each kernel packs the touched ``EnvState`` scalars
into (env_block, n_fields) faces, runs THE SAME ``core/broker`` /
``core/rewards`` functions elementwise on the block (op-for-op the XLA
path, including the ``advance``/``mark`` select gating), and repacks.
The plain-XLA path stays the bitwise oracle
(tests/test_env_dynamics_kernel.py), exactly like
``ops/window_zscore.fused_step_obs``.

The trainers' per-env ``vmap`` folds into the grid via
``jax.custom_batching.custom_vmap`` (the fused-obs pattern); off-TPU
the "on" mode falls back to XLA and "interpret" runs the pallas
interpreter for CPU parity tests.  Dispatch lives in ``core/env.step``
behind the ``rollout_env_kernel`` knob; EnvConfig validation rejects
configurations the packed-scalar form cannot reproduce (LOB venue,
sharpe's ring buffer, f64 oracle mode).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from gymfx_tpu.core import broker, rewards
from gymfx_tpu.core.types import (
    EXEC_DIAG_INDEX,
    EXEC_DIAG_KEYS,
    EnvConfig,
    EnvParams,
    EnvState,
)

_DENIED_IDX = EXEC_DIAG_INDEX["order_denied_min_quantity"]

# EnvState scalars read/written by fill_pending + check_brackets (via
# apply_fill).  Order is the packing contract between the dispatch
# wrappers and the kernel bodies.
FILL_FLOAT_FIELDS = (
    "pos", "entry_price", "cash_delta", "commission_paid",
    "last_trade_cost", "trade_pnl_sum", "trade_pnl_sumsq",
    "open_trade_commission", "pending_target", "pending_sl",
    "pending_tp", "bracket_sl", "bracket_tp",
)
FILL_BOOL_FIELDS = ("pending_active", "pending_forced")
FILL_INT_FIELDS = ("trade_count", "trades_won", "trades_lost")
# params consumed by the fill/bracket chain, packed as a broadcast row
FILL_PARAM_FIELDS = (
    "slippage", "commission", "price_tick", "size_step", "min_qty",
)

# EnvState scalars read/written by mark_to_market + compute_reward
MARK_FLOAT_FIELDS = (
    "pos", "cash_delta", "equity_delta", "prev_equity_delta",
    "peak_equity_delta", "max_drawdown_money", "max_drawdown_pct",
    "reward_peak",
)
MARK_OUT_FIELDS = (
    "equity_delta", "prev_equity_delta", "peak_equity_delta",
    "max_drawdown_money", "max_drawdown_pct", "reward_peak",
)
MARK_PARAM_FIELDS = ("initial_cash", "reward_scale", "penalty_lambda")


def _select(pred, a: EnvState, b: EnvState) -> EnvState:
    # core/env._select, re-derived here to avoid a circular import
    return EnvState(*(jnp.where(pred, x, y) for x, y in zip(a, b)))


def _block_state(float_cols, bool_cols, int_cols, eb: int) -> EnvState:
    """An EnvState whose listed fields are (eb,) columns and whose
    untouched fields are typed dummies — the broker/reward functions
    never read the dummies, and ``_select`` zips over all of them
    harmlessly (where(pred, 0, 0))."""
    zf = jnp.zeros((eb,), jnp.float32)
    zi = jnp.zeros((eb,), jnp.int32)
    zb = jnp.zeros((eb,), bool)
    fields = {}
    for name in EnvState._fields:
        if name in ("started", "terminated", "pending_active",
                    "pending_forced"):
            fields[name] = zb
        elif name in ("t", "termination_reason", "trade_count",
                      "trades_won", "trades_lost", "reward_buffer_len",
                      "reward_buffer_idx", "tr_len", "tr_idx",
                      "last_coerced_action"):
            fields[name] = zi
        elif name == "exec_diag":
            # (n_counters, eb): row-indexed .at[idx].add works
            # elementwise across the env block
            fields[name] = jnp.zeros((len(EXEC_DIAG_KEYS), eb), jnp.int32)
        elif name == "action_diag":
            fields[name] = jnp.zeros((1, eb), jnp.int32)
        else:
            fields[name] = zf
    fields.update(float_cols)
    for name, col in bool_cols.items():
        fields[name] = col != 0
    fields.update(int_cols)
    return EnvState(**fields)


def _dummy_params(cols) -> EnvParams:
    z = jnp.zeros((), jnp.float32)
    fields = {name: z for name in EnvParams._fields}
    fields["user"] = ()
    fields.update(cols)
    return EnvParams(**fields)


# ---------------------------------------------------------------------------
# Kernel A: fill_pending + check_brackets (+ financing accrual)
# ---------------------------------------------------------------------------
def _fill_bracket_kernel(fl_ref, it_ref, bars_ref, pp_ref, out_f_ref,
                         out_i_ref, *, cfg: EnvConfig):
    fl = fl_ref[...]                        # (eb, NF) f32
    it = it_ref[...]                        # (eb, NB + NI + 1) i32
    bars = bars_ref[...]                    # (eb, 5) f32: o h l c accrual
    pp = pp_ref[...]                        # (1, NP) f32
    eb = fl.shape[0]

    float_cols = {n: fl[:, i] for i, n in enumerate(FILL_FLOAT_FIELDS)}
    nb = len(FILL_BOOL_FIELDS)
    bool_cols = {n: it[:, i] for i, n in enumerate(FILL_BOOL_FIELDS)}
    int_cols = {
        n: it[:, nb + i] for i, n in enumerate(FILL_INT_FIELDS)
    }
    advance = it[:, nb + len(FILL_INT_FIELDS)] != 0
    st = _block_state(float_cols, bool_cols, int_cols, eb)
    params = _dummy_params(
        {n: pp[0, i] for i, n in enumerate(FILL_PARAM_FIELDS)}
    )
    o, h, l, c = bars[:, 0], bars[:, 1], bars[:, 2], bars[:, 3]

    # op-for-op the core/env.step bar-venue advance (steps 1, 2, 2b)
    st_f = broker.fill_pending(st, o, params, cfg, h, l)
    st = _select(advance, st_f, st)
    st_b = broker.check_brackets(st, o, h, l, cfg, params)
    st = _select(advance, st_b, st)
    if cfg.financing_enabled:
        accrual = st.pos * c * bars[:, 4]
        st = st._replace(
            cash_delta=st.cash_delta + jnp.where(advance, accrual, 0.0)
        )

    out_f_ref[...] = jnp.stack(
        [getattr(st, n) for n in FILL_FLOAT_FIELDS], axis=-1
    )
    out_i_ref[...] = jnp.stack(
        [getattr(st, n).astype(jnp.int32) for n in FILL_BOOL_FIELDS]
        + [getattr(st, n) for n in FILL_INT_FIELDS]
        + [st.exec_diag[_DENIED_IDX]],
        axis=-1,
    )


# ---------------------------------------------------------------------------
# Kernel B: mark_to_market + compute_reward
# ---------------------------------------------------------------------------
def _mark_reward_kernel(fl_ref, it_ref, pp_ref, out_ref, *,
                        cfg: EnvConfig):
    fl = fl_ref[...]                        # (eb, NF + 1) f32 (+close)
    it = it_ref[...]                        # (eb, 2) i32: mark_pred live
    pp = pp_ref[...]                        # (1, 3) f32
    eb = fl.shape[0]

    float_cols = {n: fl[:, i] for i, n in enumerate(MARK_FLOAT_FIELDS)}
    close = fl[:, len(MARK_FLOAT_FIELDS)]
    mark_pred = it[:, 0] != 0
    live = it[:, 1] != 0
    st = _block_state(float_cols, {}, {}, eb)
    params = _dummy_params(
        {n: pp[0, i] for i, n in enumerate(MARK_PARAM_FIELDS)}
    )

    # op-for-op core/env.step step 4 + the reward block
    st_m = broker.mark_to_market(st, close, params)
    st = _select(mark_pred, st_m, st)
    st, base_reward = rewards.compute_reward(st, cfg, params, live)

    out_ref[...] = jnp.stack(
        [getattr(st, n) for n in MARK_OUT_FIELDS] + [base_reward],
        axis=-1,
    )


# ---------------------------------------------------------------------------
# batched pallas dispatch + custom_vmap plumbing
# ---------------------------------------------------------------------------
def _env_block(batch: int, interpret: bool) -> int:
    """Envs per program.  The per-env footprint is a few dozen scalars,
    so VMEM never binds; 256 keeps the grid small on flagship batches
    while interpret mode takes the whole batch in one program (the
    interpreter's per-program overhead dominates there)."""
    if interpret:
        return batch
    for eb in (256, 128, 64, 32, 16, 8, 4, 2, 1):
        if batch % eb == 0:
            return eb
    return 1


def _row_specs(widths, eb):
    return [
        pl.BlockSpec((eb, w), lambda i: (i, 0)) for w in widths[:-1]
    ] + [pl.BlockSpec((1, widths[-1]), lambda i: (0, 0))]


@functools.lru_cache(maxsize=None)
def _make_fill_bracket(cfg: EnvConfig, interpret: bool):
    from jax.custom_batching import custom_vmap

    nf, ni = len(FILL_FLOAT_FIELDS), len(FILL_BOOL_FIELDS) + len(FILL_INT_FIELDS) + 1
    np_ = len(FILL_PARAM_FIELDS)
    kernel = functools.partial(_fill_bracket_kernel, cfg=cfg)

    def batched(fl, it, bars, pp):
        b = fl.shape[0]
        eb = _env_block(b, interpret)
        return pl.pallas_call(
            kernel,
            grid=(b // eb,),
            in_specs=_row_specs((nf, ni, 5, np_), eb),
            out_specs=[
                pl.BlockSpec((eb, nf), lambda i: (i, 0)),
                pl.BlockSpec((eb, ni), lambda i: (i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b, nf), jnp.float32),
                jax.ShapeDtypeStruct((b, ni), jnp.int32),
            ],
            interpret=interpret,
        )(fl, it, bars, pp)

    @custom_vmap
    def one(fl, it, bars, pp):               # (NF,), (NI,), (5,), (NP,)
        out_f, out_i = batched(
            fl[None], it[None], bars[None], pp.reshape(1, -1)
        )
        return out_f[0], out_i[0]

    @one.def_vmap
    def _rule(axis_size, in_batched, fl, it, bars, pp):
        fl, it, bars, pp = (
            x if bat else jnp.broadcast_to(x[None], (axis_size, *x.shape))
            for x, bat in zip((fl, it, bars, pp), in_batched)
        )
        # params are identical across envs: one broadcast row
        out = batched(fl, it, bars, pp[:1])
        return out, (True, True)

    return one


@functools.lru_cache(maxsize=None)
def _make_mark_reward(cfg: EnvConfig, interpret: bool):
    from jax.custom_batching import custom_vmap

    nf = len(MARK_FLOAT_FIELDS) + 1
    no = len(MARK_OUT_FIELDS) + 1
    kernel = functools.partial(_mark_reward_kernel, cfg=cfg)

    def batched(fl, it, pp):
        b = fl.shape[0]
        eb = _env_block(b, interpret)
        return pl.pallas_call(
            kernel,
            grid=(b // eb,),
            in_specs=_row_specs((nf, 2, len(MARK_PARAM_FIELDS)), eb),
            out_specs=pl.BlockSpec((eb, no), lambda i: (i, 0)),
            out_shape=jax.ShapeDtypeStruct((b, no), jnp.float32),
            interpret=interpret,
        )(fl, it, pp)

    @custom_vmap
    def one(fl, it, pp):
        return batched(fl[None], it[None], pp.reshape(1, -1))[0]

    @one.def_vmap
    def _rule(axis_size, in_batched, fl, it, pp):
        fl, it, pp = (
            x if bat else jnp.broadcast_to(x[None], (axis_size, *x.shape))
            for x, bat in zip((fl, it, pp), in_batched)
        )
        return batched(fl, it, pp[:1]), True

    return one


# ---------------------------------------------------------------------------
# public entry points (called from core/env.step)
# ---------------------------------------------------------------------------
def fused_fill_brackets(
    st: EnvState, o, h, l, c, accrual_rate, advance, cfg: EnvConfig,
    params: EnvParams, *, interpret: bool | None = None,
) -> EnvState:
    """Kernel A: the advance-gated fill/bracket/financing chain of
    ``core/env.step`` (steps 1, 2, 2b) as one VMEM pass.  Bitwise
    identical to the XLA path by construction (same functions, same
    select gating, packed per-env scalars)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    one = _make_fill_bracket(cfg, bool(interpret))
    d = st.pos.dtype
    fl = jnp.stack(
        [getattr(st, n).astype(jnp.float32) for n in FILL_FLOAT_FIELDS],
        axis=-1,
    )
    it = jnp.stack(
        [getattr(st, n).astype(jnp.int32) for n in FILL_BOOL_FIELDS]
        + [getattr(st, n) for n in FILL_INT_FIELDS]
        + [advance.astype(jnp.int32)],
        axis=-1,
    )
    accrual = (
        accrual_rate if accrual_rate is not None
        else jnp.zeros_like(jnp.asarray(o))
    )
    bars = jnp.stack(
        [jnp.asarray(x, jnp.float32) for x in (o, h, l, c, accrual)],
        axis=-1,
    )
    pp = jnp.stack(
        [getattr(params, n).astype(jnp.float32)
         for n in FILL_PARAM_FIELDS],
        axis=-1,
    )
    out_f, out_i = one(fl, it, bars, pp)
    updates = {
        n: out_f[..., i].astype(d)
        for i, n in enumerate(FILL_FLOAT_FIELDS)
    }
    nb = len(FILL_BOOL_FIELDS)
    for i, n in enumerate(FILL_BOOL_FIELDS):
        updates[n] = out_i[..., i] != 0
    for i, n in enumerate(FILL_INT_FIELDS):
        updates[n] = out_i[..., nb + i]
    denied = out_i[..., nb + len(FILL_INT_FIELDS)]
    updates["exec_diag"] = st.exec_diag.at[..., _DENIED_IDX].add(denied)
    return st._replace(**updates)


def fused_mark_reward(
    st: EnvState, c, mark_pred, live, cfg: EnvConfig, params: EnvParams,
    *, interpret: bool | None = None,
):
    """Kernel B: the mark/drawdown/reward chain of ``core/env.step``
    (step 4 + the reward block) as one VMEM pass.  Returns
    (new_state, base_reward); the reward carries are updated at the
    mark's program position — nothing between mark and reward in the
    XLA step reads or writes them, so the final state is identical."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    one = _make_mark_reward(cfg, bool(interpret))
    d = st.pos.dtype
    fl = jnp.stack(
        [getattr(st, n).astype(jnp.float32) for n in MARK_FLOAT_FIELDS]
        + [jnp.asarray(c, jnp.float32)],
        axis=-1,
    )
    it = jnp.stack(
        [mark_pred.astype(jnp.int32), live.astype(jnp.int32)], axis=-1
    )
    pp = jnp.stack(
        [getattr(params, n).astype(jnp.float32)
         for n in MARK_PARAM_FIELDS],
        axis=-1,
    )
    out = one(fl, it, pp)
    updates = {
        n: out[..., i].astype(d) for i, n in enumerate(MARK_OUT_FIELDS)
    }
    base_reward = out[..., len(MARK_OUT_FIELDS)].astype(d)
    return st._replace(**updates), base_reward
