"""Pallas TPU kernel: fused window gather + leakage-safe z-score + clip.

The feature-window observation is the reference's per-step hot spot
(reference preprocessor_plugins/feature_window_preprocessor.py:174-191:
slice + z-score over up to 256 history rows per step per env).  The
scan env already reduces that to an O(1) dynamic-slice + normalize; this
kernel covers the BATCHED form — materializing scaled windows for many
steps/envs at once (offline featurization, eval sweeps, replay-buffer
exports) — as one fused pass: for each requested step, DMA the window
rows from HBM into VMEM, normalize with that step's precomputed
scaler moments, clip, and write the scaled window.  One kernel instead
of gather + sub + div + clip materializing (B, w, F) intermediates in
HBM three times.

The PER-STEP variant (:func:`fused_step_obs`) covers the rollout hot
path: the env scan already carries this step's (window, F) rows in
VMEM-resident registers (``state.feat_window``), so there is no gather
to fuse — what the kernel removes is the sub / div / mask / clip /
nan_to_num chain each materializing an (envs, window, F) intermediate
in HBM every step.  A ``jax.custom_batching.custom_vmap`` rule folds
the trainers' per-env ``vmap`` into an env-blocked grid (the
``ops/fused_attention.py`` pattern), and the kernel body reproduces
``core/obs.scale_feature_window`` op for op, so the plain-XLA path
stays the bitwise parity oracle (tests/test_ops.py) and the off-TPU
fallback.

Falls back to pallas interpret mode off-TPU, so tests run on CPU.
"""
from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(steps_ref, feat_hbm, mean_ref, std_ref, neutral_ref, out_ref,
            scratch, sem, *, window: int, clip: float):
    b = pl.program_id(0)
    start = steps_ref[b]
    copy = pltpu.make_async_copy(
        feat_hbm.at[pl.ds(start, window), :], scratch, sem
    )
    copy.start()
    copy.wait()
    win = scratch[:]
    # moments live whole in VMEM; pick this step's row dynamically
    mean = mean_ref[pl.ds(start, 1), :]  # (1, F)
    std = std_ref[pl.ds(start, 1), :]
    neutral = neutral_ref[pl.ds(start, 1), :][0, 0]
    scaled = jnp.where(neutral != 0, 0.0, (win - mean) / std)
    if clip > 0:
        scaled = jnp.clip(scaled, -clip, clip)
    out_ref[0] = scaled


@functools.partial(jax.jit, static_argnames=("window", "clip", "interpret"))
def batched_scaled_windows(
    padded_features,  # (n + window, F) float32
    feat_mean,        # (n + 1, F)
    feat_std,         # (n + 1, F)
    feat_neutral,     # (n + 1,) bool
    steps,            # (B,) int32 — window ends (exclusive) at row `step`
    *,
    window: int,
    clip: float = 10.0,
    interpret: bool | None = None,
):
    """Scaled feature windows for a batch of steps: (B, window, F)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b = steps.shape[0]
    f = orig_f = padded_features.shape[-1]
    steps = steps.astype(jnp.int32)

    if window % 8 != 0:
        raise ValueError("window must be a multiple of 8 (TPU sublane tiling)")

    # Lane-align the feature axis: Mosaic DMA slices must be 128-aligned
    # on the last dimension.  Pad features/means with zeros and stds with
    # ones (benign division), slice the result back to F at the end.
    f_pad = max(128, -(-f // 128) * 128) if not interpret else f
    if f_pad != f:
        pad = ((0, 0), (0, f_pad - f))
        padded_features = jnp.pad(padded_features, pad)
        feat_mean = jnp.pad(feat_mean, pad)
        feat_std = jnp.pad(feat_std, pad, constant_values=1.0)
        f = f_pad

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),   # features stay in HBM
            pl.BlockSpec(memory_space=pltpu.VMEM),  # moments whole in VMEM
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, window, f), lambda i, steps_ref: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((window, f), jnp.float32),
            pltpu.SemaphoreType.DMA,
        ],
    )
    kernel = functools.partial(_kernel, window=window, clip=float(clip))
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, window, f), jnp.float32),
        interpret=interpret,
    )(steps, padded_features, feat_mean, feat_std,
      feat_neutral.astype(jnp.int32).reshape(-1, 1))
    return out[:, :, :orig_f]


def reference_scaled_windows(
    padded_features, feat_mean, feat_std, feat_neutral, steps, *, window, clip=10.0
):
    """Plain-XLA reference implementation (for parity tests and as the
    fallback path on backends without pallas support)."""

    def one(step):
        win = jax.lax.dynamic_slice(
            padded_features, (step, jnp.zeros((), step.dtype)),
            (window, padded_features.shape[-1]),
        )
        scaled = jnp.where(
            feat_neutral[step], 0.0, (win - feat_mean[step]) / feat_std[step]
        )
        if clip > 0:
            scaled = jnp.clip(scaled, -clip, clip)
        return scaled

    return jax.vmap(one)(steps.astype(jnp.int32))


# ---------------------------------------------------------------------------
# Per-step rollout variant (core/obs.py `rollout_obs_kernel` knob)
# ---------------------------------------------------------------------------
def _step_obs_kernel(win_ref, mean_ref, std_ref, neutral_ref, mask_ref,
                     out_ref, *, has_mask: bool, clip: float):
    """One env block's scaled policy input, op-for-op the body of
    ``core/obs.scale_feature_window`` (neutral-zero -> binary
    passthrough -> clip -> nan_to_num -> f32) so the XLA path stays a
    bitwise oracle."""
    win = win_ref[...]                      # (eb, W, F)
    mean = mean_ref[...]                    # (eb, 1, F)
    std = std_ref[...]
    neutral = neutral_ref[...]              # (eb, 1, 1) int32, nonzero=neutral
    scaled = jnp.where(neutral != 0, 0.0, (win - mean) / std)
    if has_mask:
        # pallas kernels cannot capture array constants, so the static
        # binary mask rides in as a broadcast (1, 1, F) int32 input
        scaled = jnp.where(mask_ref[...] != 0, win, scaled)
    if clip > 0:
        scaled = jnp.clip(scaled, -clip, clip)
    scaled = jnp.nan_to_num(
        scaled, nan=0.0, posinf=clip or 0.0, neginf=-(clip or 0.0)
    )
    out_ref[...] = scaled.astype(jnp.float32)


def _step_obs_env_block(batch: int, window: int, features: int) -> int:
    """Envs per program: two (W, F) f32 faces (window in, scaled out)
    plus moments per env, within a few MB of VMEM."""
    per_env = (2 * window * features + 2 * features + 1) * 4
    budget = max(1, (4 * 1024 * 1024) // per_env)
    for eb in (16, 8, 4, 2, 1):
        if eb <= budget and batch % eb == 0:
            return eb
    return 1


def _step_obs_batched(win, mean, std, neutral, *, binary_mask, clip: float,
                      interpret: bool):
    """Fused scaling on (B, W, F) windows + (B, F) moments + (B,) flags."""
    b, w, f = win.shape
    eb = _step_obs_env_block(b, w, f)
    has_mask = any(binary_mask)
    mask = np.asarray(
        binary_mask if has_mask else (False,) * f, dtype=np.int32
    ).reshape(1, 1, f)
    kernel = functools.partial(
        _step_obs_kernel, has_mask=has_mask, clip=float(clip)
    )
    # every block spans its array's trailing dims ((W, F), (1, F), (1, 1))
    # so Mosaic needs no (8, 128) tiling and F needs no lane padding —
    # the fused_attention (S, D)-face precedent
    out = pl.pallas_call(
        kernel,
        grid=(b // eb,),
        in_specs=[
            pl.BlockSpec((eb, w, f), lambda i: (i, 0, 0)),
            pl.BlockSpec((eb, 1, f), lambda i: (i, 0, 0)),
            pl.BlockSpec((eb, 1, f), lambda i: (i, 0, 0)),
            pl.BlockSpec((eb, 1, 1), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1, f), lambda i: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((eb, w, f), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, w, f), jnp.float32),
        interpret=interpret,
    )(
        win,
        mean.reshape(b, 1, f),
        std.reshape(b, 1, f),
        neutral.astype(jnp.int32).reshape(b, 1, 1),
        jnp.asarray(mask),
    )
    return out


@functools.lru_cache(maxsize=None)
def _make_step_obs(binary_mask, clip: float, interpret: bool):
    from jax.custom_batching import custom_vmap

    def batched(win, mean, std, neutral):
        return _step_obs_batched(
            win, mean, std, neutral,
            binary_mask=binary_mask, clip=clip, interpret=interpret,
        )

    @custom_vmap
    def one(win, mean, std, neutral):       # (W, F), (F,), (F,), ()
        return batched(
            win[None], mean[None], std[None], neutral[None]
        )[0]

    @one.def_vmap
    def _one_vmap_rule(axis_size, in_batched, win, mean, std, neutral):
        if not all(in_batched):
            win, mean, std, neutral = (
                x if bat else jnp.broadcast_to(x[None], (axis_size, *x.shape))
                for x, bat in zip((win, mean, std, neutral), in_batched)
            )
        return batched(win, mean, std, neutral), True

    return one


def fused_step_obs(win, mean, std, neutral, *, binary_mask=(), clip=10.0,
                   interpret: bool | None = None):
    """Per-env fused rollout observation: one (window, F) feature
    window + this step's scaler moments -> the scaled, masked, clipped
    policy input, in one VMEM pass.  The trainers' per-env ``vmap``
    folds into an env-blocked grid via custom_vmap (obs building is
    never differentiated — the update replays stored obs — so no
    custom_vjp is needed).  Bitwise-identical to
    ``core/obs.scale_feature_window`` (the parity oracle)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    one = _make_step_obs(
        tuple(bool(x) for x in binary_mask), float(clip), bool(interpret)
    )
    return one(win, mean, std, jnp.asarray(neutral))
