"""Pallas TPU kernel: fused window gather + leakage-safe z-score + clip.

The feature-window observation is the reference's per-step hot spot
(reference preprocessor_plugins/feature_window_preprocessor.py:174-191:
slice + z-score over up to 256 history rows per step per env).  The
scan env already reduces that to an O(1) dynamic-slice + normalize; this
kernel covers the BATCHED form — materializing scaled windows for many
steps/envs at once (offline featurization, eval sweeps, replay-buffer
exports) — as one fused pass: for each requested step, DMA the window
rows from HBM into VMEM, normalize with that step's precomputed
scaler moments, clip, and write the scaled window.  One kernel instead
of gather + sub + div + clip materializing (B, w, F) intermediates in
HBM three times.

Falls back to pallas interpret mode off-TPU, so tests run on CPU.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(steps_ref, feat_hbm, mean_ref, std_ref, neutral_ref, out_ref,
            scratch, sem, *, window: int, clip: float):
    b = pl.program_id(0)
    start = steps_ref[b]
    copy = pltpu.make_async_copy(
        feat_hbm.at[pl.ds(start, window), :], scratch, sem
    )
    copy.start()
    copy.wait()
    win = scratch[:]
    # moments live whole in VMEM; pick this step's row dynamically
    mean = mean_ref[pl.ds(start, 1), :]  # (1, F)
    std = std_ref[pl.ds(start, 1), :]
    neutral = neutral_ref[pl.ds(start, 1), :][0, 0]
    scaled = jnp.where(neutral != 0, 0.0, (win - mean) / std)
    if clip > 0:
        scaled = jnp.clip(scaled, -clip, clip)
    out_ref[0] = scaled


@functools.partial(jax.jit, static_argnames=("window", "clip", "interpret"))
def batched_scaled_windows(
    padded_features,  # (n + window, F) float32
    feat_mean,        # (n + 1, F)
    feat_std,         # (n + 1, F)
    feat_neutral,     # (n + 1,) bool
    steps,            # (B,) int32 — window ends (exclusive) at row `step`
    *,
    window: int,
    clip: float = 10.0,
    interpret: bool | None = None,
):
    """Scaled feature windows for a batch of steps: (B, window, F)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b = steps.shape[0]
    f = orig_f = padded_features.shape[-1]
    steps = steps.astype(jnp.int32)

    if window % 8 != 0:
        raise ValueError("window must be a multiple of 8 (TPU sublane tiling)")

    # Lane-align the feature axis: Mosaic DMA slices must be 128-aligned
    # on the last dimension.  Pad features/means with zeros and stds with
    # ones (benign division), slice the result back to F at the end.
    f_pad = max(128, -(-f // 128) * 128) if not interpret else f
    if f_pad != f:
        pad = ((0, 0), (0, f_pad - f))
        padded_features = jnp.pad(padded_features, pad)
        feat_mean = jnp.pad(feat_mean, pad)
        feat_std = jnp.pad(feat_std, pad, constant_values=1.0)
        f = f_pad

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b,),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),   # features stay in HBM
            pl.BlockSpec(memory_space=pltpu.VMEM),  # moments whole in VMEM
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, window, f), lambda i, steps_ref: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((window, f), jnp.float32),
            pltpu.SemaphoreType.DMA,
        ],
    )
    kernel = functools.partial(_kernel, window=window, clip=float(clip))
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, window, f), jnp.float32),
        interpret=interpret,
    )(steps, padded_features, feat_mean, feat_std,
      feat_neutral.astype(jnp.int32).reshape(-1, 1))
    return out[:, :, :orig_f]


def reference_scaled_windows(
    padded_features, feat_mean, feat_std, feat_neutral, steps, *, window, clip=10.0
):
    """Plain-XLA reference implementation (for parity tests and as the
    fallback path on backends without pallas support)."""

    def one(step):
        win = jax.lax.dynamic_slice(
            padded_features, (step, jnp.zeros((), step.dtype)),
            (window, padded_features.shape[-1]),
        )
        scaled = jnp.where(
            feat_neutral[step], 0.0, (win - feat_mean[step]) / feat_std[step]
        )
        if clip > 0:
            scaled = jnp.clip(scaled, -clip, clip)
        return scaled

    return jax.vmap(one)(steps.astype(jnp.int32))
