"""Pallas TPU kernel: sort-free LOB stream matching.

``lob/book.process_stream`` is a ``lax.scan`` of ``lax.switch`` message
dispatch whose hot op is an ``argsort`` over the flattened price-time
keys — a sort the TPU vector unit has no native lowering for, so XLA
serializes it through expensive generic sorts per message.  This module
re-derives every half-book primitive in sort-free dense int32 algebra
so the whole stream runs as ONE pallas program per book (grid over
books, ``fori_loop`` over messages, book state resident in VMEM):

  * matching: each slot's fill is ``clip(take - prior, 0, avail)``
    where ``prior`` is the liquidity strictly ahead of it in price-time
    priority — the sum over strictly-better level keys plus the FIFO
    prefix within its own level.  Identical to the sorted cumsum walk
    because live levels never share a price, so flattened keys are
    unique wherever liquidity exists;
  * queue compaction: each live slot moves to its rank = count of live
    slots before it (exclusive prefix sum) — the stable
    ``argsort(qty == 0)`` without the sort;
  * resting/cancelling: first-free-index selects become masked-min +
    one-hot dense updates.

Message dispatch is dense too: every branch (add buy/sell, cancel,
market) is computed and the result selected by kind/side — exact,
because all branches are pure int32 and a zero-quantity match /
zero-oid cancel / zero-lot rest is a bitwise no-op on an invariant
book (front-compacted queues, zero oid in empty slots, zero price on
empty levels).  ``tests/test_lob_match_kernel.py`` pins exact int32
parity against ``book.process_stream`` message-for-message.

Dispatch: ``lob/venue.execute_bar`` (per-bar seed stream) and
``bench.py --lob`` behind the ``lob_match_kernel`` off|on|interpret
knob — "off" keeps the argsort engine (the oracle), "on" uses pallas
on TPU and falls back to the oracle elsewhere (bitwise safe: both are
exact), "interpret" forces the pallas interpreter for CPU parity
tests.  The intrabar agent flow scan keeps the oracle engine: its
per-message ``lax.cond`` stop-trigger logic is agent bookkeeping, not
matching.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from gymfx_tpu.lob.book import (
    AGENT_OID,
    MSG_ADD,
    MSG_CANCEL,
    MSG_MARKET,
    PRICE_CAP,
    BookState,
    FillRecord,
    Messages,
)

_FILL_COLS = len(FillRecord._fields)


def _iota(shape, dim):
    # 1D iota is not allowed on TPU pallas; broadcasted_iota always is
    return jax.lax.broadcasted_iota(jnp.int32, shape, dim)


def _prefix_sum_q(x):
    """Exclusive prefix sum along axis 1 — a static-Q loop of masked
    adds instead of ``cumsum`` (no scan lowering needed in-kernel)."""
    cols = _iota(x.shape, 1)
    out = jnp.zeros_like(x)
    for b in range(x.shape[1]):
        out = out + jnp.where(cols > b, x[:, b:b + 1], 0)
    return out


def _first_true(mask, size):
    """Index of the first True (``size`` when none) — ``argmax`` on
    bool without the argmax: masked min over the iota."""
    return jnp.min(jnp.where(mask, _iota(mask.shape, 0), size))


def _compact_dense(qty, oid):
    """``book._compact`` without the argsort: each live slot moves to
    its rank (count of live slots before it); dead slots zero-fill.
    Exact: ranks of live slots are distinct and increasing, which IS
    the stable sort order."""
    live = qty > 0
    rank = _prefix_sum_q(live.astype(jnp.int32))
    cols = _iota(qty.shape, 1)
    new_qty = jnp.zeros_like(qty)
    new_oid = jnp.zeros_like(oid)
    for j in range(qty.shape[1]):
        m = live[:, j:j + 1] & (cols == rank[:, j:j + 1])
        new_qty = jnp.where(m, qty[:, j:j + 1], new_qty)
        new_oid = jnp.where(m, oid[:, j:j + 1], new_oid)
    return new_qty, new_oid


def _reset_empty_levels(price, qty):
    return jnp.where(jnp.sum(qty, axis=1, dtype=jnp.int32) > 0, price, 0)


def _match_half(price, qty, oid, take_qty, limit, against_asks: bool):
    """``book._match_half`` with the sorted cumsum walk replaced by the
    prior-liquidity form: fill_j = clip(take - prior_j, 0, avail_j),
    prior_j = liquidity at strictly better price-time keys.  Bitwise
    identical because keys are unique wherever avail > 0."""
    active = price > 0
    if against_asks:
        eligible = active & (price <= limit)
        level_key = jnp.where(eligible, price, PRICE_CAP)
    else:
        eligible = active & (price >= limit)
        level_key = jnp.where(eligible, PRICE_CAP - price, PRICE_CAP)
    avail = jnp.where(eligible[:, None], qty, 0)

    level_avail = jnp.sum(avail, axis=1, dtype=jnp.int32)          # (D,)
    ahead_levels = jnp.sum(
        jnp.where(level_key[None, :] < level_key[:, None],
                  level_avail[None, :], 0),
        axis=1, dtype=jnp.int32,
    )
    prior = ahead_levels[:, None] + _prefix_sum_q(avail)
    fill = jnp.clip(take_qty - prior, 0, avail)

    # sums pinned to int32 (the book.py x64 rule)
    filled = jnp.sum(fill, dtype=jnp.int32)
    value = jnp.sum(fill * price[:, None], dtype=jnp.int32)
    events = jnp.sum(fill > 0, dtype=jnp.int32)
    agent = (oid == AGENT_OID) & (fill > 0)
    agent_fill = jnp.where(agent, fill, 0)
    agent_qty = jnp.sum(agent_fill, dtype=jnp.int32)
    agent_value = jnp.sum(agent_fill * price[:, None], dtype=jnp.int32)
    touched = jnp.sum(fill, axis=1, dtype=jnp.int32) > 0
    pmin = jnp.min(jnp.where(touched, price, PRICE_CAP))
    pmax = jnp.max(jnp.where(touched, price, 0))

    new_qty = qty - fill
    new_oid = jnp.where(new_qty > 0, oid, 0)
    new_qty, new_oid = _compact_dense(new_qty, new_oid)
    new_price = _reset_empty_levels(price, new_qty)
    stats = (filled, value, events, agent_qty, agent_value, pmin, pmax)
    return (new_price, new_qty, new_oid), stats


def _rest_half(price, qty, oid, p, q, o):
    """``book._rest_half`` with the (li, si) scatter as a one-hot dense
    update.  li = D (empty one-hot, no write) when neither an existing
    level nor a free one exists — the original's ``can`` gate."""
    D, Q = qty.shape
    has_level = (price == p) & (price > 0)
    level_free = jnp.sum(qty, axis=1, dtype=jnp.int32) == 0
    li = jnp.where(
        jnp.any(has_level),
        _first_true(has_level, D),
        _first_true(level_free, D),
    )
    can = (q > 0) & (jnp.any(has_level) | jnp.any(level_free))
    lvl = _iota((D,), 0) == li
    free = qty == 0
    si_per_level = jnp.min(jnp.where(free, _iota((D, Q), 1), Q), axis=1)
    si = jnp.sum(jnp.where(lvl, si_per_level, 0), dtype=jnp.int32)
    can = can & jnp.any(lvl & jnp.any(free, axis=1))
    slot = can & lvl[:, None] & (_iota((D, Q), 1) == si)
    rested = jnp.where(can, q, 0)
    qty = jnp.where(slot, q, qty)
    oid = jnp.where(slot, o, oid)
    price = jnp.where(can & lvl, p, price)
    return (price, qty, oid), rested


def _cancel_half(price, qty, oid, target_oid):
    hit = (oid == target_oid) & (qty > 0) & (target_oid != 0)
    removed = jnp.sum(jnp.where(hit, qty, 0), dtype=jnp.int32)
    qty = jnp.where(hit, 0, qty)
    oid = jnp.where(hit, 0, oid)
    qty, oid = _compact_dense(qty, oid)
    price = _reset_empty_levels(price, qty)
    return (price, qty, oid), removed


def _process_message_dense(halves, msg):
    """``book.process_message`` with the lax.switch/cond dispatch as
    dense compute-all-branches-and-select — every branch is pure int32
    and the inapplicable ones are bitwise no-ops (zero take / zero rest
    / zero cancel target) on an invariant book."""
    bp, bq, bo, ap, aq, ao = halves
    kind, side, price, qty, oid = msg
    k = jnp.clip(kind, 0, 3)
    is_buy = side > 0
    is_add = k == MSG_ADD
    is_cancel = k == MSG_CANCEL
    matchable = is_add | (k == MSG_MARKET)

    # taker match against the opposite side
    ask_take = jnp.where(matchable & is_buy, qty, 0)
    ask_limit = jnp.where(is_add, price, PRICE_CAP)
    (ap, aq, ao), s_a = _match_half(ap, aq, ao, ask_take, ask_limit, True)
    bid_take = jnp.where(matchable & ~is_buy, qty, 0)
    bid_limit = jnp.where(is_add, price, 0)
    (bp, bq, bo), s_b = _match_half(bp, bq, bo, bid_take, bid_limit, False)

    # rest an ADD's unmatched remainder on its own side
    bid_rest = jnp.where(is_add & is_buy, qty - s_a[0], 0)
    (bp, bq, bo), rest_b = _rest_half(bp, bq, bo, price, bid_rest, oid)
    ask_rest = jnp.where(is_add & ~is_buy, qty - s_b[0], 0)
    (ap, aq, ao), rest_a = _rest_half(ap, aq, ao, price, ask_rest, oid)

    # cancel by (side, oid); target 0 hits nothing
    (bp, bq, bo), rm_b = _cancel_half(
        bp, bq, bo, jnp.where(is_cancel & is_buy, oid, 0)
    )
    (ap, aq, ao), rm_a = _cancel_half(
        ap, aq, ao, jnp.where(is_cancel & ~is_buy, oid, 0)
    )

    rec = FillRecord(
        filled_qty=s_a[0] + s_b[0],
        filled_value=s_a[1] + s_b[1],
        fill_events=s_a[2] + s_b[2],
        agent_qty=s_a[3] + s_b[3],
        agent_value=s_a[4] + s_b[4],
        price_min=jnp.minimum(s_a[5], s_b[5]),
        price_max=jnp.maximum(s_a[6], s_b[6]),
        rested_qty=rest_b + rest_a,
        cancelled_qty=rm_b + rm_a,
    )
    return (bp, bq, bo, ap, aq, ao), rec


def process_stream_dense(book: BookState, msgs: Messages):
    """XLA twin of the kernel body (same dense math, no pallas) — the
    parity tests use it to separate ranked-math bugs from pallas
    lowering bugs.  Not a dispatch target."""

    def step(halves, m):
        return _process_message_dense(halves, m)

    halves, fills = jax.lax.scan(step, tuple(book), tuple(msgs))
    return BookState(*halves), fills


# ---------------------------------------------------------------------------
# pallas dispatch: one book per program, fori_loop over the stream
# ---------------------------------------------------------------------------
def _stream_kernel(bp_ref, bq_ref, bo_ref, ap_ref, aq_ref, ao_ref,
                   k_ref, s_ref, p_ref, q_ref, o_ref,
                   obp_ref, obq_ref, obo_ref, oap_ref, oaq_ref, oao_ref,
                   of_ref):
    halves = (bp_ref[0], bq_ref[0], bo_ref[0],
              ap_ref[0], aq_ref[0], ao_ref[0])
    stream = (k_ref[0], s_ref[0], p_ref[0], q_ref[0], o_ref[0])
    n_msgs = stream[0].shape[0]
    fills0 = jnp.zeros((n_msgs, _FILL_COLS), jnp.int32)

    def body(m, carry):
        halves, fills = carry
        msg = tuple(
            jax.lax.dynamic_index_in_dim(x, m, keepdims=False)
            for x in stream
        )
        halves, rec = _process_message_dense(halves, msg)
        row = jnp.stack(list(rec))[None, :]
        fills = jax.lax.dynamic_update_slice(fills, row, (m, 0))
        return halves, fills

    halves, fills = jax.lax.fori_loop(0, n_msgs, body, (halves, fills0))
    obp_ref[0] = halves[0]
    obq_ref[0] = halves[1]
    obo_ref[0] = halves[2]
    oap_ref[0] = halves[3]
    oaq_ref[0] = halves[4]
    oao_ref[0] = halves[5]
    of_ref[0] = fills


@functools.lru_cache(maxsize=None)
def _make_stream(depth: int, slots: int, n_msgs: int, interpret: bool):
    from jax.custom_batching import custom_vmap

    lvl = pl.BlockSpec((1, depth), lambda i: (i, 0))
    slab = pl.BlockSpec((1, depth, slots), lambda i: (i, 0, 0))
    msg = pl.BlockSpec((1, n_msgs), lambda i: (i, 0))
    fill = pl.BlockSpec((1, n_msgs, _FILL_COLS), lambda i: (i, 0, 0))

    def batched(bp, bq, bo, ap, aq, ao, k, s, p, q, o):
        b = bp.shape[0]
        return pl.pallas_call(
            _stream_kernel,
            grid=(b,),
            in_specs=[lvl, slab, slab, lvl, slab, slab,
                      msg, msg, msg, msg, msg],
            out_specs=[lvl, slab, slab, lvl, slab, slab, fill],
            out_shape=[
                jax.ShapeDtypeStruct((b, depth), jnp.int32),
                jax.ShapeDtypeStruct((b, depth, slots), jnp.int32),
                jax.ShapeDtypeStruct((b, depth, slots), jnp.int32),
                jax.ShapeDtypeStruct((b, depth), jnp.int32),
                jax.ShapeDtypeStruct((b, depth, slots), jnp.int32),
                jax.ShapeDtypeStruct((b, depth, slots), jnp.int32),
                jax.ShapeDtypeStruct((b, n_msgs, _FILL_COLS), jnp.int32),
            ],
            interpret=interpret,
        )(bp, bq, bo, ap, aq, ao, k, s, p, q, o)

    @custom_vmap
    def one(bp, bq, bo, ap, aq, ao, k, s, p, q, o):
        out = batched(*(x[None] for x in (bp, bq, bo, ap, aq, ao,
                                          k, s, p, q, o)))
        return tuple(y[0] for y in out)

    @one.def_vmap
    def _rule(axis_size, in_batched, *args):
        args = tuple(
            x if bat else jnp.broadcast_to(x[None], (axis_size, *x.shape))
            for x, bat in zip(args, in_batched)
        )
        return tuple(batched(*args)), (True,) * 7

    return one


def fused_process_stream(
    book: BookState, msgs: Messages, *, interpret: bool | None = None,
):
    """``book.process_stream`` as one pallas program per book: the book
    lives in VMEM across the whole stream and every message is matched
    with the sort-free dense primitives.  Exact int32 parity with the
    argsort engine (tests/test_lob_match_kernel.py).  Composes with the
    trainers' per-env ``vmap`` via custom_vmap (batch -> grid)."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    depth = int(book.bid_qty.shape[-2])
    slots = int(book.bid_qty.shape[-1])
    n_msgs = int(msgs.kind.shape[-1])
    one = _make_stream(depth, slots, n_msgs, bool(interpret))
    arrays = tuple(
        jnp.asarray(x, jnp.int32) for x in (*book, *msgs)
    )
    out = one(*arrays)
    new_book = BookState(*out[:6])
    fills = out[6]
    rec = FillRecord(*(fills[..., i] for i in range(_FILL_COLS)))
    return new_book, rec
