"""Pallas TPU kernel: fused int16 tick-delta tape decode.

The compressed data path (data/compress.py) stores every quantized
MarketData column as int16 deltas against a per-shard int32 base with an
f32 divisor sidecar.  This kernel materializes the f32 view for a whole
stacked block of columns in one pass — sign-extend, rebase, convert,
divide — instead of XLA materializing an int32 intermediate per column
in HBM.  The pure-XLA ``data/compress.decode_q16_ref`` is the bitwise
parity oracle (tests/test_data_compress.py) and the decode arithmetic is
pinned: ``(base_i32 + delta_i32) -> f32 / inv_f32``, elementwise, so the
kernel and oracle agree bit-for-bit on any backend.

Rows are blocked over a grid (whole-tape curriculum slabs can run to
hundreds of thousands of rows — far beyond one VMEM face); the column
axis pads to the int16 sublane tile and the divisor pads with ones, both
sliced back after the call.  Falls back to pallas interpret mode off-TPU
so the CI parity leg runs on CPU (the ``data_compress=interpret`` knob
forces it anywhere).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_ROW_BLOCK = 2048


def _decode_kernel(delta_ref, base_ref, inv_ref, out_ref):
    d = delta_ref[...].astype(jnp.int32)       # (C, RB) int16 -> i32
    b = base_ref[...].astype(jnp.int32)        # (C, 1)
    out_ref[...] = (b + d).astype(jnp.float32) / inv_ref[...]


@functools.partial(jax.jit, static_argnames=("interpret",))
def decode_q16_block(delta, base, inv, *, interpret: bool | None = None):
    """Fused decode of a stacked q16 block.

    ``delta`` (C, rows) int16, ``base`` (C,) int32, ``inv`` (C,) f32 ->
    (C, rows) f32 = ``(base + delta) / inv``, bitwise-identical to
    ``decode_q16_ref``.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    c, rows = delta.shape
    base2 = base.reshape(c, 1).astype(jnp.int32)
    inv2 = inv.reshape(c, 1).astype(jnp.float32)
    if interpret:
        c_pad, rb = c, rows
    else:
        # int16 sublane tile is 16; lane-align and block the row axis so
        # arbitrarily long slabs never exceed one VMEM face
        c_pad = -(-c // 16) * 16
        rb = min(_ROW_BLOCK, -(-rows // 128) * 128)
    rows_pad = -(-rows // rb) * rb
    if c_pad != c or rows_pad != rows:
        delta = jnp.pad(delta, ((0, c_pad - c), (0, rows_pad - rows)))
        base2 = jnp.pad(base2, ((0, c_pad - c), (0, 0)))
        # padded divisors are 1.0: benign division in the dead lanes
        inv2 = jnp.pad(inv2, ((0, c_pad - c), (0, 0)), constant_values=1.0)
    out = pl.pallas_call(
        _decode_kernel,
        grid=(rows_pad // rb,),
        in_specs=[
            pl.BlockSpec((c_pad, rb), lambda i: (0, i)),
            pl.BlockSpec((c_pad, 1), lambda i: (0, 0)),
            pl.BlockSpec((c_pad, 1), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((c_pad, rb), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((c_pad, rows_pad), jnp.float32),
        interpret=interpret,
    )(delta, base2, inv2)
    return out[:c, :rows]
