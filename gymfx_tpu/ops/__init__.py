from gymfx_tpu.ops.window_zscore import batched_scaled_windows  # noqa: F401
