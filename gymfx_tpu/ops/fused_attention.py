"""Pallas TPU kernel: fused single-pass attention for policy windows.

VERDICT r4 weak #5: the transformer_ring policy's single-device path
computed plain ``softmax(QK^T)V`` through XLA, which materializes the
``(envs, heads, W, W)`` score tensor in HBM — at window 256 x 8192 envs
that is ~4 GB of score traffic per forward, and the long-context bench
row ran at 0.2x the per-chip target.  This kernel computes a BLOCK of
envs' whole-window attention per program in a single VMEM-resident
pass (flash-attention's insight specialized to policy windows:
W <= 1024 means the full W x W score block FITS in VMEM, so no
online-softmax streaming is needed — one exp, one normalize, zero HBM
score traffic).

Granularity matters twice here:
  * env blocks (``_env_block``) amortize per-program overhead — one
    program per (env, head) measured SLOWER than XLA (dispatch
    overhead beats the HBM saving at 16k tiny programs);
  * a ``jax.custom_batching.custom_vmap`` rule folds the trainers'
    per-env ``vmap`` into the blocked kernel — pallas' default
    batching rule would add a size-1 grid dimension per env and
    recreate exactly the tiny-program problem.

Numerics run in float32 inside the kernel regardless of the policy
dtype, like XLA's f32 matmul accumulation on bf16 inputs.
Differentiable: the backward is a fused Pallas kernel too
(``_bwd_kernel``) — it saves no score tensor, recomputes the softmax
probabilities from q/k inside VMEM (the standard flash-attention
recompute trade: extra forward FLOPs on the rarer update pass, zero
HBM score traffic), then forms dV, dS, dQ, dK in the same
env-blocked single pass.  The plain-XLA twin
(``parallel.ring_attention.full_attention``) is the parity oracle
for BOTH directions (tests/test_ops.py), not part of the compiled
gradient.

Falls back to pallas interpret mode off-TPU, so tests run on CPU; the
plain-XLA twin remains the parity oracle and the >1024-window fallback.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# beyond this window the W x W f32 score blocks (plus q/k/v) stop
# fitting comfortably in ~16 MB VMEM; longer sequences are the ring /
# Ulysses backends' territory anyway (parallel/ring_attention.py)
MAX_FUSED_WINDOW = 1024

# below this window the kernel LOSES to XLA: at W=32 the measured A/B
# on the v5e chip was 30.8k vs 145.9k env-steps/s — the per-program
# work is tiny, and the (B,S,H,D)<->(B,H,S,D) transposes around the
# call cost more than the (small) score tensors ever did.  The fused
# path only pays off where score HBM traffic is the wall (W^2 scaling):
# measured 1.43x op-level at W=256.  Callers (policies.py
# dense_window_attention) route short windows to plain XLA.
MIN_FUSED_WINDOW = 192


def _env_block(batch: int, window: int, score_blocks_live: int = 1) -> int:
    """Envs per program: amortize program overhead while keeping the
    live f32 score blocks (score_blocks_live * eb * W * W * 4 bytes)
    within a few MB of VMEM.  The backward pass holds three
    score-shaped values at once (scores/p, dp, ds)."""
    budget = max(
        1, (4 * 1024 * 1024) // (score_blocks_live * window * window * 4)
    )
    for eb in (16, 8, 4, 2, 1):
        if eb <= budget and batch % eb == 0:
            return eb
    return 1


def _kernel(q_ref, k_ref, v_ref, o_ref, *, scale: float, causal: bool):
    q = q_ref[:, 0].astype(jnp.float32)   # (eb, S, D)
    k = k_ref[:, 0].astype(jnp.float32)
    v = v_ref[:, 0].astype(jnp.float32)
    scores = jax.lax.dot_general(
        q, k, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    ) * scale                              # (eb, S, S)
    if causal:
        s = scores.shape[-1]
        row = jax.lax.broadcasted_iota(jnp.int32, (s, s), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (s, s), 1)
        scores = jnp.where((row >= col)[None], scores, -jnp.inf)
    m = jnp.max(scores, axis=-1, keepdims=True)
    p = jnp.exp(scores - m)
    num = jax.lax.dot_general(
        p, v, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )                                      # (eb, S, D)
    out = num / jnp.sum(p, axis=-1, keepdims=True)
    o_ref[:, 0] = out.astype(o_ref.dtype)


def _bwd_kernel(q_ref, k_ref, v_ref, g_ref, dq_ref, dk_ref, dv_ref, *,
                scale: float, causal: bool):
    """VMEM-resident attention backward: recompute the score block from
    q/k (cheaper than ever writing it to HBM), then the standard
    softmax-attention gradients — dV = P^T dO, dP = dO V^T,
    dS = P (dP - rowsum(dP P)), dQ = scale dS K, dK = scale dS^T Q."""
    q = q_ref[:, 0].astype(jnp.float32)   # (eb, S, D)
    k = k_ref[:, 0].astype(jnp.float32)
    v = v_ref[:, 0].astype(jnp.float32)
    g = g_ref[:, 0].astype(jnp.float32)
    scores = jax.lax.dot_general(
        q, k, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    ) * scale
    if causal:
        s = scores.shape[-1]
        row = jax.lax.broadcasted_iota(jnp.int32, (s, s), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (s, s), 1)
        scores = jnp.where((row >= col)[None], scores, -jnp.inf)
    m = jnp.max(scores, axis=-1, keepdims=True)
    e = jnp.exp(scores - m)
    p = e / jnp.sum(e, axis=-1, keepdims=True)      # (eb, Sq, Sk)
    dv = jax.lax.dot_general(
        p, g, (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )                                               # (eb, Sk, D)
    dp = jax.lax.dot_general(
        g, v, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )                                               # (eb, Sq, Sk)
    delta = jnp.sum(dp * p, axis=-1, keepdims=True)
    ds = p * (dp - delta) * scale
    dq = jax.lax.dot_general(
        ds, k, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )
    dk = jax.lax.dot_general(
        ds, q, (((1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )
    dq_ref[:, 0] = dq.astype(dq_ref.dtype)
    dk_ref[:, 0] = dk.astype(dk_ref.dtype)
    dv_ref[:, 0] = dv.astype(dv_ref.dtype)


def _backward_batched(q, k, v, g, causal: bool, interpret: bool):
    """Fused backward on (B, S, H, D) primals + cotangent."""
    b, s, h, d = q.shape
    eb = _env_block(b, s, score_blocks_live=3)
    scale = 1.0 / (d ** 0.5)
    kernel = functools.partial(_bwd_kernel, scale=scale, causal=causal)
    spec = pl.BlockSpec((eb, 1, s, d), lambda i, j: (i, j, 0, 0))
    call = pl.pallas_call(
        kernel,
        grid=(b // eb, h),
        in_specs=[spec] * 4,
        out_specs=[spec] * 3,
        out_shape=[jax.ShapeDtypeStruct((b, h, s, d), q.dtype)] * 3,
        interpret=interpret,
    )
    sw = lambda x: jnp.swapaxes(x, 1, 2)  # noqa: E731
    dq, dk, dv = call(sw(q), sw(k), sw(v), sw(g))
    return sw(dq), sw(dk), sw(dv)


def _forward_batched(q, k, v, causal: bool, interpret: bool):
    """Fused pass on (B, S, H, D) inputs."""
    b, s, h, d = q.shape
    eb = _env_block(b, s)
    scale = 1.0 / (d ** 0.5)
    kernel = functools.partial(_kernel, scale=scale, causal=causal)
    # (B, H, S, D) layout: heads and env blocks ride the grid; Mosaic
    # requires the last two block dims to tile (8, 128) or span the
    # array, so the (S, D) face stays whole
    call = pl.pallas_call(
        kernel,
        grid=(b // eb, h),
        in_specs=[pl.BlockSpec((eb, 1, s, d), lambda i, j: (i, j, 0, 0))] * 3,
        out_specs=pl.BlockSpec((eb, 1, s, d), lambda i, j: (i, j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        interpret=interpret,
    )
    out = call(
        jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2), jnp.swapaxes(v, 1, 2)
    )
    return jnp.swapaxes(out, 1, 2)


@functools.lru_cache(maxsize=None)
def _make(causal: bool, interpret: bool):
    from jax.custom_batching import custom_vmap

    @jax.custom_vjp
    def attend_batched(q, k, v):           # (B, S, H, D)
        return _forward_batched(q, k, v, causal, interpret)

    def fwd(q, k, v):
        return attend_batched(q, k, v), (q, k, v)

    def bwd(res, g):
        q, k, v = res
        return _backward_batched(q, k, v, g, causal, interpret)

    attend_batched.defvjp(fwd, bwd)

    @custom_vmap
    def attend_raw(q, k, v):               # (S, H, D)
        return _forward_batched(
            q[None], k[None], v[None], causal, interpret
        )[0]

    @attend_raw.def_vmap
    def _attend_vmap_rule(axis_size, in_batched, q, k, v):
        if not all(in_batched):
            # replicate any unbatched operand along the vmapped axis,
            # each with its OWN trailing shape
            q, k, v = (
                x if bat else jnp.broadcast_to(x[None], (axis_size, *x.shape))
                for x, bat in zip((q, k, v), in_batched)
            )
        return attend_batched(q, k, v), True

    # the backward gets the same vmap-collapse treatment: without it,
    # grad-of-vmap (the training update) would push the pallas backward
    # through the default size-1-grid batching rule — the tiny-program
    # regime the env blocks exist to avoid
    @custom_vmap
    def bwd_raw(q, k, v, g):               # (S, H, D)
        dq, dk, dv = _backward_batched(
            q[None], k[None], v[None], g[None], causal, interpret
        )
        return dq[0], dk[0], dv[0]

    @bwd_raw.def_vmap
    def _bwd_vmap_rule(axis_size, in_batched, q, k, v, g):
        if not all(in_batched):
            q, k, v, g = (
                x if bat else jnp.broadcast_to(x[None], (axis_size, *x.shape))
                for x, bat in zip((q, k, v, g), in_batched)
            )
        return (
            _backward_batched(q, k, v, g, causal, interpret),
            (True, True, True),
        )

    # custom_vmap alone does not support reverse AD; the outer
    # custom_vjp makes every transform order work — vmap(attend) hits
    # the collapse rule, grad(attend) and grad(vmap(attend)) hit the
    # fused backward kernel
    @jax.custom_vjp
    def attend(q, k, v):
        return attend_raw(q, k, v)

    def afwd(q, k, v):
        return attend(q, k, v), (q, k, v)

    def abwd(res, g):
        q, k, v = res
        return bwd_raw(q, k, v, g)

    attend.defvjp(afwd, abwd)
    return attend, attend_batched


def fused_window_attention(q, k, v, *, causal: bool = False,
                           interpret: bool | None = None):
    """Exact attention for (..., W, H, D) q/k/v with the score blocks
    kept in VMEM.  Any leading batch dims (flattened into the kernel's
    env-block grid).  Differentiable (fused Pallas backward that
    recomputes the probabilities in VMEM — see module docstring).
    Returns (..., W, H, D) in the input dtype."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    *batch, s, h, d = q.shape
    if s > MAX_FUSED_WINDOW:
        raise ValueError(
            f"fused_window_attention holds whole {s}x{s} score blocks "
            f"in VMEM; windows beyond {MAX_FUSED_WINDOW} belong to the "
            "ring/Ulysses sequence-parallel backends"
        )
    attend, attend_batched = _make(bool(causal), bool(interpret))
    if not batch:
        return attend(q, k, v)
    flat = lambda x: x.reshape(-1, s, h, d)  # noqa: E731
    out = attend_batched(flat(q), flat(k), flat(v))
    return out.reshape(*batch, s, h, d)
