"""Rotating JSONL sink: structured telemetry rows (metric snapshots,
span records, run summaries) appended one JSON object per line.

Rotation keeps unattended runs from filling a disk: when the active
file would exceed ``max_bytes`` the sink renames it to ``<path>.1``
(shifting older backups up to ``backups``) and starts fresh — the same
scheme as stdlib ``RotatingFileHandler``, without dragging the logging
module's global configuration into library code.

Thread-safe; writes are line-atomic under the sink lock.  ``append``
never raises into the caller's hot path — a full disk degrades
telemetry, it must not kill training or serving.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional


class JsonlSink:
    def __init__(self, path: str, *, max_bytes: int = 64 * 1024 * 1024,
                 backups: int = 3):
        if int(max_bytes) <= 0:
            raise ValueError(f"max_bytes must be > 0, got {max_bytes}")
        if int(backups) < 0:
            raise ValueError(f"backups must be >= 0, got {backups}")
        self.path = str(path)
        self.max_bytes = int(max_bytes)
        self.backups = int(backups)
        self._lock = threading.Lock()
        self.rows_written = 0
        self.rotations = 0
        self.write_errors = 0
        parent = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(parent, exist_ok=True)

    # ------------------------------------------------------------------
    def _rotate_locked(self) -> None:
        if self.backups == 0:
            # no backups: truncate in place
            open(self.path, "w").close()
        else:
            oldest = f"{self.path}.{self.backups}"
            if os.path.exists(oldest):
                os.remove(oldest)
            for i in range(self.backups - 1, 0, -1):
                src = f"{self.path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{self.path}.{i + 1}")
            if os.path.exists(self.path):
                os.replace(self.path, f"{self.path}.1")
        self.rotations += 1

    def append(self, record: Dict[str, Any], *,
               ts: Optional[float] = None) -> bool:
        """Write one row (a ``ts`` epoch-seconds field is added when
        absent).  Returns False when the write failed (disk full,
        permissions) — the error is counted, never raised."""
        row = dict(record)
        row.setdefault("ts", time.time() if ts is None else ts)
        try:
            line = json.dumps(row, default=_json_default) + "\n"
        except (TypeError, ValueError):
            with self._lock:
                self.write_errors += 1
            return False
        with self._lock:
            try:
                try:
                    size = os.path.getsize(self.path)
                except OSError:
                    size = 0
                if size and size + len(line) > self.max_bytes:
                    self._rotate_locked()
                with open(self.path, "a", encoding="utf-8") as fh:
                    fh.write(line)
                self.rows_written += 1
                return True
            except OSError:
                self.write_errors += 1
                return False

    def close(self) -> None:  # symmetry with other telemetry components
        pass


def _json_default(value: Any):
    """Last-resort coercion for numpy scalars / device arrays that leak
    into a telemetry row."""
    try:
        return float(value)
    except (TypeError, ValueError):
        return repr(value)


def append_jsonl(path: str, record: Dict[str, Any]) -> bool:
    """One-shot append through a throwaway sink (no rotation pressure:
    the run_tests.sh PROGRESS row and similar single-row writers)."""
    return JsonlSink(path, max_bytes=1 << 40, backups=0).append(record)
