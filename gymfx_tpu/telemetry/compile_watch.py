"""Compile watch: jax compile events as metrics, plus executable
fingerprinting so silent recompiles are DETECTED instead of suspected.

Two complementary signal paths land in one registry + ledger:

  * ``jax.monitoring`` listeners — every ``/jax/core/compile/*``
    duration event (jaxpr trace, MLIR lowering, backend compile)
    becomes a ``gymfx_compile_events_total`` counter tick and a
    ``gymfx_compile_seconds`` histogram observation, and every backend
    compile is ledgered as a ``compile_end`` event.  This path catches
    compiles NOBODY asked for — the silent jit-cache misses the serving
    contract ("zero late compiles") forbids.
  * explicit program records — :meth:`CompileWatch.record_compile`
    takes a (name, key) identity plus the lowered-HLO sha256
    (:func:`fingerprint`), so a *recompile of a known key* (same
    (name, shapes, donation) identity compiled again, fingerprint
    drifted or not) is counted separately and ledgered as
    ``recompile``.  The serving engine's boot ladder and late-compile
    path report through :meth:`watch_engine`.

``jax.monitoring`` offers registration but no per-listener removal, so
the process installs ONE forwarding listener pair lazily and routes
through a module-level active-watch slot; :meth:`uninstall` clears the
slot (cheap, test-safe) rather than the global listener list.
"""
from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Dict, Optional, Tuple

# compile times span trace-cache hits (~1ms) to pod-scale XLA runs
# (minutes) — wider edges than the request-latency default
COMPILE_BUCKETS = (
    0.001, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 15.0, 60.0, 300.0,
)

_install_lock = threading.Lock()
_listeners_installed = False
_active: Optional["CompileWatch"] = None


def fingerprint(lowered: Any) -> str:
    """sha256 of the lowered program text — the executable identity the
    recompile detector compares.  Accepts a ``jax.stages.Lowered`` (or
    anything with ``as_text()``) or a plain string."""
    text = lowered if isinstance(lowered, str) else lowered.as_text()
    return hashlib.sha256(text.encode("utf-8")).hexdigest()


def _forward_event(event: str, **kwargs: Any) -> None:
    watch = _active
    if watch is not None:
        watch._on_event(event)


def _forward_duration(event: str, duration: float, **kwargs: Any) -> None:
    watch = _active
    if watch is not None:
        watch._on_duration(event, duration)


class CompileWatch:
    """Registry + ledger view of every compile the process performs."""

    def __init__(self, registry: Any, *, ledger: Any = None,
                 recorder: Any = None, name: str = "default"):
        self.registry = registry
        self.ledger = ledger
        self.recorder = recorder
        self.name = str(name)
        self.events = registry.counter(
            "gymfx_compile_events_total",
            "jax.monitoring compile-pipeline events by stage",
            labels=("event",),
        )
        self.seconds = registry.histogram(
            "gymfx_compile_seconds",
            "Compile-stage durations (jax.monitoring)",
            labels=("event",),
            buckets=COMPILE_BUCKETS,
        )
        self.programs = registry.counter(
            "gymfx_compile_programs_total",
            "Explicitly recorded program compiles by (watch, late)",
            labels=("watch", "late"),
        )
        self.recompiles = registry.counter(
            "gymfx_compile_recompiles_total",
            "Program keys compiled MORE THAN ONCE (silent-recompile "
            "detector)",
            labels=("watch",),
        )
        self.bucket_misses = registry.counter(
            "gymfx_serve_bucket_miss_total",
            "Serve requests that landed outside the compiled bucket "
            "ladder (late compile on the decision path)",
            labels=("watch",),
        )
        # (name, key) -> lowered-HLO digest (or None when unavailable)
        self._fingerprints: Dict[Tuple[str, str], Optional[str]] = {}
        self._lock = threading.Lock()

    # -- jax.monitoring forwarders -------------------------------------
    def install(self) -> "CompileWatch":
        """Become the process's active watch (one forwarding listener
        pair is registered with jax.monitoring on first install)."""
        global _listeners_installed, _active
        with _install_lock:
            if not _listeners_installed:
                try:
                    from jax import monitoring

                    monitoring.register_event_listener(_forward_event)
                    monitoring.register_event_duration_secs_listener(
                        _forward_duration
                    )
                    _listeners_installed = True
                except Exception:
                    # no jax / an incompatible monitoring surface:
                    # explicit record_compile/watch_engine still work
                    pass
            _active = self
        return self

    def uninstall(self) -> None:
        global _active
        with _install_lock:
            if _active is self:
                _active = None

    def _on_event(self, event: str) -> None:
        if "compile" not in event:
            return
        try:
            self.events.inc(event=event)
        except Exception:
            pass

    def _on_duration(self, event: str, duration: float) -> None:
        if "compile" not in event:
            return
        try:
            self.events.inc(event=event)
            self.seconds.observe(float(duration), event=event)
            if event.endswith("backend_compile_duration"):
                # a real XLA compile happened in this process — ledger
                # it even when nobody claimed it via record_compile
                if self.ledger is not None:
                    self.ledger.record(
                        "compile_end", name=f"jax:{event}",
                        duration_s=float(duration),
                    )
                if self.recorder is not None:
                    self.recorder.record_compile({
                        "kind": "compile_end", "name": f"jax:{event}",
                        "duration_s": float(duration),
                    })
        except Exception:
            pass

    # -- explicit program-identity records -----------------------------
    def record_compile(
        self,
        name: str,
        *,
        key: str = "",
        hlo_sha256: Optional[str] = None,
        duration_s: Optional[float] = None,
        late: bool = False,
    ) -> None:
        """Record one program compile under the identity ``(name, key)``
        (key = the shapes/donation signature the caller buckets by).  A
        second compile of a known identity is a recompile — the silent
        kind this watch exists to catch."""
        ident = (str(name), str(key))
        with self._lock:
            seen = ident in self._fingerprints
            self._fingerprints[ident] = hlo_sha256
        try:
            self.programs.inc(watch=self.name, late=str(bool(late)).lower())
        except Exception:
            pass
        event = {
            "name": str(name), "key": str(key), "hlo_sha256": hlo_sha256,
            "duration_s": duration_s, "late": bool(late),
        }
        if seen:
            try:
                self.recompiles.inc(watch=self.name)
            except Exception:
                pass
            if self.ledger is not None:
                self.ledger.record("recompile", **event)
        else:
            if self.ledger is not None:
                self.ledger.record("compile_begin", name=str(name),
                                   key=str(key), late=bool(late))
                self.ledger.record(
                    "compile_end", name=str(name), key=str(key),
                    duration_s=duration_s, hlo_sha256=hlo_sha256,
                    late=bool(late),
                )
        if self.recorder is not None:
            self.recorder.record_compile({"kind": "compile", **event})

    @property
    def fingerprint_count(self) -> int:
        with self._lock:
            return len(self._fingerprints)

    def fingerprints(self) -> Dict[str, Optional[str]]:
        """Snapshot of every executable identity seen so far:
        ``{"name|key": lowered-HLO sha256-or-None}`` — what the
        profiler stamps into capture manifests so a trace is
        attributable to exact program versions."""
        with self._lock:
            return {
                f"{name}|{key}": sha
                for (name, key), sha in self._fingerprints.items()
            }

    # -- serving-engine binding ----------------------------------------
    def watch_engine(self, engine: Any, *, name: str = "serve") -> None:
        """Attach to an :class:`~gymfx_tpu.serve.engine.InferenceEngine`:
        future bucket compiles (boot ladder via ``warmup()`` and late
        compiles on the decision path) report through the engine's
        ``on_compile`` hook; buckets ALREADY compiled at attach time are
        recorded retroactively (no duration — boot happened before the
        watch existed).  Late compiles additionally count as serve
        bucket misses and ledger a ``serve_bucket_miss`` event."""
        for bucket in sorted(getattr(engine, "_compiled", {})):
            self.record_compile(
                f"{name}_forward", key=f"bucket={bucket}", late=False,
            )

        def on_compile(bucket: int, duration_s: Optional[float],
                       late: bool) -> None:
            self.record_compile(
                f"{name}_forward", key=f"bucket={bucket}",
                duration_s=duration_s, late=late,
            )
            if late:
                try:
                    self.bucket_misses.inc(watch=self.name)
                except Exception:
                    pass
                if self.ledger is not None:
                    self.ledger.record("serve_bucket_miss", bucket=int(bucket))

        engine.on_compile = on_compile


def timed(fn):
    """``(result, seconds)`` of ``fn()`` — the engine compile sites use
    it so the hook gets a real duration."""
    t0 = time.perf_counter()
    result = fn()
    return result, time.perf_counter() - t0
