"""Prometheus text exposition (format version 0.0.4) for a
:class:`~gymfx_tpu.telemetry.registry.MetricsRegistry`.

Deterministic output: families sorted by name, label sets sorted by
label values — the golden-file test (tests/test_telemetry.py) depends
on byte-stable rendering for identical registry contents.
"""
from __future__ import annotations

from typing import Dict, Tuple

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label_value(text: str) -> str:
    return (
        text.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')
    )


def _format_value(value: float) -> str:
    value = float(value)
    if value != value:  # NaN
        return "NaN"
    if value in (float("inf"), float("-inf")):
        return "+Inf" if value > 0 else "-Inf"
    if value == int(value) and abs(value) < 1e15:
        return str(int(value))
    return repr(value)


def _labels_str(names: Tuple[str, ...], values: Tuple[str, ...],
                extra: Dict[str, str] = None) -> str:
    pairs = [
        f'{n}="{_escape_label_value(v)}"' for n, v in zip(names, values)
    ]
    for k, v in (extra or {}).items():
        pairs.append(f'{k}="{_escape_label_value(v)}"')
    return "{" + ",".join(pairs) + "}" if pairs else ""


def render(registry) -> str:
    """The full ``/metrics`` payload for ``registry``."""
    lines = []
    for fam in registry.families():
        lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        if fam.kind == "histogram":
            for key, state in fam.samples():
                cum = 0
                for edge, count in zip(fam.buckets, state.bucket_counts):
                    cum += count
                    le = _labels_str(
                        fam.label_names, key, {"le": _format_value(edge)}
                    )
                    lines.append(f"{fam.name}_bucket{le} {cum}")
                inf = _labels_str(fam.label_names, key, {"le": "+Inf"})
                lines.append(f"{fam.name}_bucket{inf} {state.count}")
                ls = _labels_str(fam.label_names, key)
                lines.append(f"{fam.name}_sum{ls} {_format_value(state.sum)}")
                lines.append(f"{fam.name}_count{ls} {state.count}")
        else:
            for key, value in fam.samples():
                ls = _labels_str(fam.label_names, key)
                lines.append(f"{fam.name}{ls} {_format_value(value)}")
    return "\n".join(lines) + ("\n" if lines else "")
