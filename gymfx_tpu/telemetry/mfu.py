"""Analytic MFU and device-memory accounting.

bench_util.py derives MFU from the XLA cost model of the compiled
executable — exact, but only available where the backend exposes
``cost_analysis`` and only for programs we compiled ourselves.  This
module is the independent cross-check ISSUE'd for the telemetry PR: a
closed-form per-train-step FLOP model from the policy's parameter
shapes, so dashboards can sanity-check the cost-model number (and
report SOMETHING on backends that hide cost analysis).

Model (dense-matmul accounting, the standard MFU convention):

  * every 2-D parameter ``(m, n)`` is a GEMM costing ``2·m·n`` FLOPs
    per sample (per token for token policies) — biases/norms are
    rounding errors against the GEMMs and are ignored;
  * self-attention adds ``4·W²·d_model`` per layer per sample
    (``QKᵀ`` and ``A·V``, ``2·W²·d`` each) for window length ``W``;
  * one train step = rollout forwards over ``num_envs · horizon``
    samples + update passes at the standard ``3×`` forward cost
    (forward + backward) over the same samples, ``update_epochs``
    times.
"""
from __future__ import annotations

from typing import Any, Dict, Optional


def param_flops_per_sample(params: Any, *, tokens: int = 1) -> float:
    """``2·m·n`` summed over every 2-D leaf of ``params``, times the
    ``tokens`` each sample pushes through the trunk (1 for flat-obs
    policies, the window length for token policies)."""
    import jax

    total = 0.0
    for leaf in jax.tree_util.tree_leaves(params):
        shape = getattr(leaf, "shape", ())
        if len(shape) == 2:
            total += 2.0 * float(shape[0]) * float(shape[1])
    return total * float(tokens)


def attention_flops_per_sample(window: int, d_model: int,
                               n_layers: int) -> float:
    """The activation-activation matmuls parameter counting misses:
    ``QKᵀ`` + ``A·V`` = ``4·W²·d`` per layer."""
    return 4.0 * float(n_layers) * float(window) ** 2 * float(d_model)


def analytic_train_step_flops(
    params: Any,
    *,
    num_envs: int,
    horizon: int,
    update_epochs: int = 1,
    tokens: int = 1,
    window: int = 0,
    d_model: int = 0,
    n_layers: int = 0,
) -> float:
    """Closed-form FLOPs of ONE fused rollout+update train step."""
    fwd = param_flops_per_sample(params, tokens=tokens)
    if n_layers and window and d_model:
        fwd += attention_flops_per_sample(window, d_model, n_layers)
    samples = float(num_envs) * float(horizon)
    rollout = samples * fwd
    update = 3.0 * samples * fwd * float(max(1, update_epochs))
    return rollout + update


# ---------------------------------------------------------------------------
def hw_flops_peak(device: Any = None) -> Optional[float]:
    """Public peak dense-bf16 FLOPs/sec of ``device`` (default: the
    first local device); None when unknown (CPU)."""
    from gymfx_tpu.bench_util import device_peak_flops

    if device is None:
        import jax

        device = jax.local_devices()[0]
    return device_peak_flops(device)


def device_memory_bytes(device: Any = None) -> Optional[int]:
    """``bytes_in_use`` from the device allocator, or None where the
    backend does not expose memory stats (CPU)."""
    try:
        if device is None:
            import jax

            device = jax.local_devices()[0]
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    raw = stats.get("bytes_in_use", stats.get("pool_bytes"))
    return None if raw is None else int(raw)


# the allocator stats worth a per-superstep gauge; peak_bytes_in_use is
# the watermark the OOM postmortems actually want
MEMORY_WATERMARK_KEYS = (
    "bytes_in_use",
    "peak_bytes_in_use",
    "bytes_limit",
    "largest_alloc_size",
)


def device_memory_watermarks(device: Any = None) -> Optional[dict]:
    """The allocator watermark slice of ``device.memory_stats()`` as
    ``{key: int}``, or None where the backend exposes no stats (CPU).
    A pure host-side allocator query — safe on the drain cadence, it
    never syncs the device."""
    try:
        if device is None:
            import jax

            device = jax.local_devices()[0]
        stats = device.memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    out = {
        key: int(stats[key]) for key in MEMORY_WATERMARK_KEYS
        if stats.get(key) is not None
    }
    return out or None


def mfu_report(
    flops_per_step: Optional[float],
    step_time_s: Optional[float],
    device: Any = None,
) -> Dict[str, Any]:
    """The bench.py JSON slice: analytic FLOPs, hardware peak, their
    ratio, and device memory — every key always present, null where the
    backend cannot say (the bench contract schema pins the key set, not
    TPU availability)."""
    peak = hw_flops_peak(device)
    util = None
    if flops_per_step and peak and step_time_s and step_time_s > 0:
        util = (flops_per_step / step_time_s) / peak
    return {
        "analytic_flops_per_step": (
            float(flops_per_step) if flops_per_step else None
        ),
        "hw_flops_peak": peak,
        "mfu_analytic": round(util, 5) if util is not None else None,
        "device_memory_bytes": device_memory_bytes(device),
    }
