"""Lightweight span/trace API for the host-side hot paths.

``Tracer.span("train/superstep", k=4)`` times a region and

  * enters ``jax.profiler.TraceAnnotation`` (when jax is importable and
    profiling is on, the region shows up on the device timeline a
    ``--trace`` capture produces — the on-device half of the story;
    inside jitted code the trainers additionally use ``jax.named_scope``
    so the XLA ops themselves carry phase names);
  * records a structured host span — name, start, duration, attrs,
    trace/parent ids from a thread-local stack — into a bounded ring,
    an optional :class:`~gymfx_tpu.telemetry.registry.MetricsRegistry`
    histogram (``gymfx_span_seconds{span=...}``) and an optional JSONL
    sink.

A disabled tracer (``Tracer(enabled=False)`` or the module-level
:func:`span` with no tracer configured) returns a shared no-op context
manager: the off path costs one attribute check and allocates nothing.
"""
from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional

# span durations range from sub-ms dispatches to multi-second
# supersteps; widen the default latency edges accordingly
SPAN_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = (
        "tracer", "name", "attrs", "span_id", "parent_id", "trace_id",
        "t0", "_annotation",
    )

    def __init__(self, tracer: "Tracer", name: str, attrs: Dict[str, Any]):
        self.tracer = tracer
        self.name = name
        self.attrs = attrs
        self.span_id = next(tracer._ids)
        self.parent_id: Optional[int] = None
        self.trace_id: Optional[int] = None
        self.t0 = 0.0
        self._annotation = None

    def __enter__(self):
        stack = self.tracer._stack()
        if stack:
            self.parent_id = stack[-1].span_id
            self.trace_id = stack[-1].trace_id
        else:
            self.trace_id = self.span_id
        stack.append(self)
        if self.tracer._annotation_cls is not None:
            try:
                self._annotation = self.tracer._annotation_cls(self.name)
                self._annotation.__enter__()
            except Exception:
                self._annotation = None
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter() - self.t0
        if self._annotation is not None:
            try:
                self._annotation.__exit__(*exc)
            except Exception:
                pass
        stack = self.tracer._stack()
        if stack and stack[-1] is self:
            stack.pop()
        self.tracer._record(self, dur, error=exc[0] is not None)
        return False


class Tracer:
    """Span recorder; one per Telemetry bundle (or standalone in tests)."""

    def __init__(
        self,
        *,
        enabled: bool = True,
        registry: Any = None,
        sink: Any = None,
        keep: int = 4096,
        use_jax_annotation: bool = True,
    ):
        self.enabled = bool(enabled)
        self.registry = registry
        self.sink = sink
        self.records: Deque[Dict[str, Any]] = deque(maxlen=int(keep))
        self._ids = itertools.count(1)
        self._local = threading.local()
        self._hist = None
        if registry is not None:
            self._hist = registry.histogram(
                "gymfx_span_seconds",
                "Host-side span durations by span name",
                labels=("span",),
                buckets=SPAN_BUCKETS,
            )
        self._annotation_cls = None
        if use_jax_annotation:
            try:  # jax stays an optional import: spans work without it
                from jax.profiler import TraceAnnotation

                self._annotation_cls = TraceAnnotation
            except Exception:
                self._annotation_cls = None

    def _stack(self) -> List[_Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def span(self, name: str, **attrs: Any):
        """Context manager timing one region; no-op when disabled."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, str(name), attrs)

    def _record(self, span: _Span, dur_s: float, *, error: bool) -> None:
        row = {
            "kind": "span",
            "span": span.name,
            "dur_ms": dur_s * 1e3,
            "span_id": span.span_id,
            "trace_id": span.trace_id,
            "parent_id": span.parent_id,
        }
        if span.attrs:
            row["attrs"] = span.attrs
        if error:
            row["error"] = True
        self.records.append(row)
        if self._hist is not None:
            self._hist.observe(dur_s, span=span.name)
        if self.sink is not None:
            self.sink.append(row)


_DISABLED = Tracer(enabled=False, use_jax_annotation=False)


def null_tracer() -> Tracer:
    """The shared disabled tracer (for default arguments)."""
    return _DISABLED
