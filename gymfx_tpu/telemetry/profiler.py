"""Managed jax.profiler trace capture: superstep-windowed, manifested,
never-raises.

Raw ``jax.profiler.trace`` dumps (the old ``bench.py --trace`` /
``tools/profile_rollout.py`` path) leave an anonymous directory nobody
can attribute later.  :class:`ProfilerSession` owns the capture
instead: it starts/stops the trace around a superstep dispatch window
on a configured cadence and writes a **capture bundle** —

  ``capture_NNN_itM/``
    ``plugins/profile/<ts>/*.trace.json.gz``  (what jax.profiler wrote)
    ``manifest.json``   provenance: config sha256, superstep range,
                        platform/device_kind/comparable triple,
                        compile-watch executable fingerprints, and the
                        workload payload (XLA/analytic FLOPs, the
                        ``bench_util.measure_phase_split`` baseline)
    ``scope_map.json``  op name -> rollout/update scope, recovered from
                        the compiled executable's optimized-HLO
                        ``op_name`` metadata (trace_parse.py) — CPU
                        trace events carry no scope info, so this
                        sidecar is what keeps attribution tier-1
                        testable

and ledgers a ``profile_capture`` event.  ``tools/profile_report.py``
turns a bundle into the schema-pinned ``profile_report.json``
(attribution.py).

Config knobs (defaults.py, all off; built by ``telemetry_from_config``):

  ``telemetry_profile_dir``        capture bundle directory (the master
                                   switch — unset = sessions are never
                                   constructed, fast paths untouched)
  ``telemetry_profile_supersteps`` comma-separated superstep indices to
                                   capture ("1" or "1,8"); default "1"
                                   (the first post-warmup dispatch —
                                   superstep 0's window contains the
                                   jit compile)
  ``telemetry_profile_every``      cadence: capture every Nth superstep
                                   (0 = off)

Cost model: a due capture adds ONE device sync (the trainer blocks the
dispatch so the trace covers it) plus, at bundle-write time, one AOT
recompile of the dispatched program (for the scope map + cost model)
and the two phase-split sub-programs on a copy of the live state —
seconds on CPU CI shapes, tens of seconds at TPU flagship shapes, paid
only on capture supersteps.  Everything is wrapped in the telemetry
never-raises discipline: failures land in ``capture_errors``, never in
the training loop.
"""
from __future__ import annotations

import json
import threading
import time
from pathlib import Path
from typing import Any, Callable, Dict, Iterable, Optional, Sequence, Union

from gymfx_tpu.telemetry.trace_parse import PHASE_SCOPES

MANIFEST_NAME = "manifest.json"
SCOPE_MAP_NAME = "scope_map.json"
CAPTURE_MANIFEST_VERSION = 1


def _parse_supersteps(raw: Union[None, int, str, Iterable[int]]
                      ) -> Optional[tuple]:
    """Normalize the ``telemetry_profile_supersteps`` knob: int, list,
    or comma-separated string -> sorted tuple of superstep indices."""
    if raw is None or raw == "" or raw is False:
        return None
    if isinstance(raw, bool):
        return None
    if isinstance(raw, int):
        return (int(raw),)
    if isinstance(raw, (list, tuple, set)):
        return tuple(sorted(int(v) for v in raw))
    return tuple(sorted(
        int(tok) for tok in str(raw).split(",") if tok.strip()
    ))


class _Capture:
    """Context manager returned by :meth:`ProfilerSession.capture`."""

    def __init__(self, session: "ProfilerSession", it_start: int, k: int,
                 label: str):
        self.session = session
        self.it_start = int(it_start)
        self.k = int(k)
        self.label = label
        self.bundle: Optional[str] = None

    def __enter__(self) -> "_Capture":
        self.session.start_capture(
            self.it_start, self.k, label=self.label, force=True
        )
        return self

    def __exit__(self, *exc: Any) -> None:
        self.bundle = self.session.finish_capture()


class ProfilerSession:
    """Cadence-gated jax.profiler capture windows with manifested
    bundles; every public method is never-raises."""

    def __init__(
        self,
        out_dir: str,
        *,
        supersteps: Union[None, int, str, Iterable[int]] = None,
        every: int = 0,
        config_sha256: Optional[str] = None,
        registry: Any = None,
        ledger: Any = None,
        compile_watch: Any = None,
        scopes: Sequence[str] = PHASE_SCOPES,
    ):
        self.out_dir = Path(out_dir)
        self.supersteps = _parse_supersteps(supersteps)
        self.every = int(every or 0)
        if self.supersteps is None and self.every <= 0:
            # dir configured but no cadence: one capture at superstep 1,
            # the first dispatch whose window holds no jit compile
            self.supersteps = (1,)
        self.config_sha256 = config_sha256
        self.ledger = ledger
        self.compile_watch = compile_watch
        self.scopes = tuple(scopes)
        self._workload_source: Optional[Callable[[int, int], Any]] = None
        self._lock = threading.Lock()
        self._capture_seq = 0
        self._active: Optional[Dict[str, Any]] = None
        self._last_capture_ts: Optional[float] = None
        self.captures = 0
        self.capture_errors = 0
        self._counter = None
        if registry is not None:
            try:
                self._counter = registry.counter(
                    "gymfx_profile_captures_total",
                    "Completed profiler trace captures",
                )
                registry.gauge(
                    "gymfx_profile_last_capture_age_seconds",
                    "Seconds since the last completed profiler capture "
                    "(-1 before the first)",
                ).set_function(self._last_capture_age)
            except Exception:
                self._counter = None

    # ------------------------------------------------------------------
    def _last_capture_age(self) -> float:
        ts = self._last_capture_ts
        return -1.0 if ts is None else max(0.0, time.time() - ts)

    def set_workload_source(self, fn: Callable[[int, int], Any]) -> None:
        """Bind a ``fn(it_start, k) -> dict`` resolved at bundle-write
        time (after the trace stopped, outside the capture window).
        The dict is merged into the manifest; the special key
        ``hlo_text`` (the dispatched program's optimized HLO) is parsed
        into the ``scope_map.json`` sidecar instead of stored."""
        self._workload_source = fn

    def due(self, it_start: int, k: int = 1) -> bool:
        """True when the dispatch window ``[it_start, it_start + k)``
        contains a configured capture superstep (explicit list, or a
        multiple of ``every``)."""
        try:
            it_start, k = int(it_start), max(1, int(k))
        except Exception:
            return False
        if self.supersteps is not None and any(
                it_start <= t < it_start + k for t in self.supersteps):
            return True
        if self.every > 0:
            first = ((it_start + self.every - 1) // self.every) * self.every
            if it_start <= first < it_start + k:
                return True
        return False

    @property
    def capturing(self) -> bool:
        return self._active is not None

    # ------------------------------------------------------------------
    def start_capture(self, it_start: int, k: int = 1, *,
                      label: str = "superstep", force: bool = False) -> bool:
        """Start tracing the window when due (or ``force``); returns
        whether a capture is now open.  The caller must block the
        dispatch result before :meth:`finish_capture` so the trace
        covers the device work."""
        try:
            if self._active is not None:
                return False
            if not force and not self.due(it_start, k):
                return False
            with self._lock:
                self._capture_seq += 1
                seq = self._capture_seq
            bundle = self.out_dir / f"capture_{seq:03d}_it{int(it_start)}"
            bundle.mkdir(parents=True, exist_ok=True)
            import jax

            jax.profiler.start_trace(str(bundle))
            self._active = {
                "bundle": bundle,
                "it_start": int(it_start),
                "k": max(1, int(k)),
                "label": str(label),
                "seq": seq,
                "t0": time.time(),
            }
            return True
        except Exception:
            self.capture_errors += 1
            self._active = None
            return False

    def finish_capture(self) -> Optional[str]:
        """Stop the open trace and write the bundle (manifest, scope
        map, ledger event, counter tick); returns the bundle path, or
        None when no capture was open / the write failed."""
        active = self._active
        if active is None:
            return None
        self._active = None
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            self.capture_errors += 1
            return None
        try:
            return self._write_bundle(active)
        except Exception:
            self.capture_errors += 1
            return None

    def capture(self, *, it_start: int = 0, k: int = 1,
                label: str = "manual") -> _Capture:
        """One-shot context manager for the bench tools (ignores the
        cadence knobs).  The body must block its device work before
        exiting so the trace covers it."""
        return _Capture(self, it_start, k, label)

    def close(self) -> None:
        """Finalize a capture left open by an aborted loop
        (idempotent)."""
        self.finish_capture()

    # ------------------------------------------------------------------
    def _write_bundle(self, active: Dict[str, Any]) -> Optional[str]:
        from gymfx_tpu.telemetry.flight_recorder import _jsonable

        bundle: Path = active["bundle"]
        it_start, k = active["it_start"], active["k"]
        manifest: Dict[str, Any] = {
            "schema_version": CAPTURE_MANIFEST_VERSION,
            "ts": time.time(),
            "label": active["label"],
            "seq": active["seq"],
            "config_sha256": self.config_sha256,
            "it_start": it_start,
            "k": k,
            "it_end": it_start + k,
            "capture_wall_s": time.time() - active["t0"],
        }
        try:
            import jax

            from gymfx_tpu.bench_util import (
                device_peak_flops,
                stamp_comparability,
            )

            device = jax.local_devices()[0]
            stamp_comparability(manifest, device=device)
            manifest["hw_flops_peak"] = device_peak_flops(device)
        except Exception:
            manifest.setdefault("platform", "unknown")
            manifest.setdefault("device_kind", "unknown")
            manifest.setdefault("comparable", False)
            manifest.setdefault("hw_flops_peak", None)
        info: Dict[str, Any] = {}
        if self._workload_source is not None:
            try:
                info = dict(self._workload_source(it_start, k) or {})
            except Exception:
                manifest["workload_error"] = True
        hlo_text = info.pop("hlo_text", None)
        if hlo_text:
            try:
                from gymfx_tpu.telemetry.trace_parse import scope_map_from_hlo

                scope_map = scope_map_from_hlo(hlo_text, scopes=self.scopes)
                if scope_map:
                    (bundle / SCOPE_MAP_NAME).write_text(
                        json.dumps(scope_map), encoding="utf-8"
                    )
                    manifest["scope_map_file"] = SCOPE_MAP_NAME
                    manifest["scope_map_ops"] = len(scope_map)
            except Exception:
                pass
            try:
                import hashlib

                sha = hashlib.sha256(
                    hlo_text.encode("utf-8", errors="replace")
                ).hexdigest()
                manifest["hlo_sha256"] = sha
                if self.compile_watch is not None:
                    # register the captured program's identity so it
                    # shows up in the fingerprint table below (training
                    # compiles arrive via jax.monitoring without one)
                    self.compile_watch.record_compile(
                        f"profile:{active['label']}",
                        key=f"it{it_start}", hlo_sha256=sha,
                    )
            except Exception:
                pass
        if self.compile_watch is not None:
            try:
                manifest["fingerprints"] = self.compile_watch.fingerprints()
            except Exception:
                manifest["fingerprints"] = {}
        else:
            manifest["fingerprints"] = {}
        for key, value in info.items():
            manifest.setdefault(str(key), _jsonable(value))
        with open(bundle / MANIFEST_NAME, "w", encoding="utf-8") as fh:
            json.dump(_jsonable(manifest), fh, indent=2, sort_keys=True)
            fh.write("\n")
        self._last_capture_ts = time.time()
        with self._lock:
            self.captures += 1
        if self._counter is not None:
            try:
                self._counter.inc()
            except Exception:
                pass
        if self.ledger is not None:
            self.ledger.record(
                "profile_capture", path=str(bundle),
                it_start=int(it_start), k=int(k),
            )
        return str(bundle)


def find_captures(root: str) -> list:
    """Manifested capture bundles under ``root`` (itself a bundle, a
    session dir, or any ancestor), oldest first."""
    try:
        base = Path(root)
        if (base / MANIFEST_NAME).exists():
            return [str(base)]
        return sorted(
            str(p.parent) for p in base.rglob(MANIFEST_NAME)
        )
    except Exception:
        return []
