"""Serving-path instruments: the registry-backed view of a
MicroBatcher and the decision service.

``ServeInstruments`` owns every serve-side metric family so the batcher
stays free of metric-name string literals; the batcher calls the
``on_*`` hooks from its existing counter sites (all no-cost when no
instruments object is injected — the off path keeps the plain-int
counters it always had).  Queue pressure is NOT mirrored per mutation:
:meth:`bind_batcher` registers callback gauges that read the live
batcher at scrape time.
"""
from __future__ import annotations

from typing import Any, Optional

# batch sizes are powers-of-two-ish bucket ladders; request stage
# latencies reuse the default request-shaped edges
BATCH_SIZE_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)


class ServeInstruments:
    def __init__(self, registry: Any, *, slo: Any = None,
                 name: str = "serve", replica: Optional[str] = None):
        self.registry = registry
        self.slo = slo
        self.name = str(name)
        # the replica label is OPT-IN: with replica=None every family
        # keeps its original ("batcher", ...) label names and exposition
        # is byte-for-byte the single-engine serving path (a registry
        # rejects re-declaring a family with different label names, so
        # fleet and non-fleet instruments must not share a registry)
        self.replica = None if replica is None else str(replica)
        extra = () if self.replica is None else ("replica",)
        self._base = {"batcher": self.name}
        if self.replica is not None:
            self._base["replica"] = self.replica
        self.requests = registry.counter(
            "gymfx_serve_requests_total",
            "Requests resolved by terminal outcome",
            labels=("batcher", "outcome") + extra,
        )
        self.shed = registry.counter(
            "gymfx_serve_shed_total",
            "Requests shed by admission control, by shed reason",
            labels=("batcher", "reason") + extra,
        )
        self.deadline = registry.counter(
            "gymfx_serve_deadline_miss_total",
            "Requests failed past their deadline, by detection phase",
            labels=("batcher", "phase") + extra,
        )
        self.breaker_open = registry.counter(
            "gymfx_serve_breaker_open_total",
            "Requests failed fast by an open dispatch circuit breaker",
            labels=("batcher",) + extra,
        )
        self.failures = registry.counter(
            "gymfx_serve_dispatch_failures_total",
            "Engine dispatches that raised (whole batch failed)",
            labels=("batcher",) + extra,
        )
        self.dispatches = registry.counter(
            "gymfx_serve_dispatches_total",
            "Engine dispatches completed",
            labels=("batcher",) + extra,
        )
        self.batch_size = registry.histogram(
            "gymfx_serve_batch_size",
            "Real requests coalesced per engine dispatch",
            labels=("batcher",) + extra,
            buckets=BATCH_SIZE_BUCKETS,
        )
        self.h_queue = registry.histogram(
            "gymfx_serve_enqueue_to_pickup_seconds",
            "submit() to worker pickup (queue wait)",
            labels=("batcher",) + extra,
        )
        self.h_window = registry.histogram(
            "gymfx_serve_pickup_to_dispatch_seconds",
            "worker pickup to engine dispatch (batching window)",
            labels=("batcher",) + extra,
        )
        self.h_dispatch = registry.histogram(
            "gymfx_serve_dispatch_seconds",
            "engine dispatch to response resolution",
            labels=("batcher",) + extra,
        )
        self.h_latency = registry.histogram(
            "gymfx_serve_latency_seconds",
            "submit() to response resolution (end-to-end)",
            labels=("batcher",) + extra,
        )

    # -- batcher hook points (called from MicroBatcher when injected) --
    def on_shed(self, reason: str, n: int = 1) -> None:
        self.shed.inc(n, reason=reason, **self._base)
        self.requests.inc(n, outcome="shed", **self._base)
        if self.slo is not None:
            for _ in range(n):
                self.slo.observe("shed")

    def on_deadline_miss(self, phase: str, n: int = 1) -> None:
        self.deadline.inc(n, phase=phase, **self._base)
        self.requests.inc(n, outcome="deadline_miss", **self._base)
        if self.slo is not None:
            for _ in range(n):
                self.slo.observe("deadline_miss")

    def on_breaker_open(self, n: int = 1) -> None:
        self.breaker_open.inc(n, **self._base)
        self.requests.inc(n, outcome="breaker_open", **self._base)
        if self.slo is not None:
            for _ in range(n):
                self.slo.observe("breaker_open")

    def on_dispatch_failure(self, n: int = 1) -> None:
        self.failures.inc(1, **self._base)
        self.requests.inc(n, outcome="failed", **self._base)
        if self.slo is not None:
            for _ in range(n):
                self.slo.observe("failed")

    def on_batch_complete(self, records) -> None:
        """``records`` — the dispatch's RequestRecord rows (one per
        served request, shared pickup/dispatch/done stamps)."""
        rows = list(records)
        if not rows:
            return
        self.dispatches.inc(1, **self._base)
        self.batch_size.observe(float(len(rows)), **self._base)
        for r in rows:
            self.requests.inc(1, outcome="served", **self._base)
            self.h_queue.observe(
                max(0.0, r.t_pickup - r.t_enqueue), **self._base
            )
            self.h_window.observe(
                max(0.0, r.t_dispatch - r.t_pickup), **self._base
            )
            self.h_dispatch.observe(
                max(0.0, r.t_done - r.t_dispatch), **self._base
            )
            self.h_latency.observe(r.latency_s, **self._base)
            if self.slo is not None:
                self.slo.observe("served", latency_s=r.latency_s)

    # ------------------------------------------------------------------
    def bind_batcher(self, batcher: Any) -> None:
        """Register scrape-time callback gauges over the live batcher
        (queue depth, in-flight count, breaker state) and the rolling
        SLO gauges when an SLO window is attached."""
        extra = () if self.replica is None else ("replica",)
        depth = self.registry.gauge(
            "gymfx_serve_queue_depth",
            "Requests currently queued (read at scrape time)",
            labels=("batcher",) + extra,
        )
        # len() on a deque is atomic under the GIL: safe without the
        # batcher lock, and a scrape must never contend with dispatch
        depth.set_function(
            lambda b=batcher: float(len(b._pending)), **self._base
        )
        inflight = self.registry.gauge(
            "gymfx_serve_inflight",
            "Batches currently inside an engine dispatch",
            labels=("batcher",) + extra,
        )
        inflight.set_function(
            lambda b=batcher: float(b._inflight), **self._base
        )
        if batcher.max_queue is not None:
            cap = self.registry.gauge(
                "gymfx_serve_queue_capacity",
                "Configured admission-control queue bound",
                labels=("batcher",) + extra,
            )
            cap.set(float(batcher.max_queue), **self._base)
        engine = getattr(batcher, "engine", None)
        if engine is not None and hasattr(engine, "late_compiles"):
            late = self.registry.gauge(
                "gymfx_serve_late_compiles_total",
                "Engine compiles AFTER boot (a warm serving path scrapes "
                "0 forever; monotonic, read at scrape time)",
                labels=("batcher",) + extra,
            )
            # read through the batcher at scrape time: the blue/green
            # deployer retargets batcher.engine between micro-batches,
            # and the gauge must follow the ACTIVE engine across flips
            late.set_function(
                lambda b=batcher: float(
                    getattr(b.engine, "late_compiles", 0)
                ),
                **self._base,
            )
        if engine is not None and getattr(engine, "slot_cache", None) is not None:
            # device-resident sessions (serve/slots.py): scrape-time
            # callbacks through batcher.engine so the gauges follow the
            # active engine across blue/green flips, like late_compiles
            slot_specs = (
                ("gymfx_serve_slot_resident",
                 "Sessions resident in the device slot cache",
                 lambda e: float(len(e.slot_cache))),
                ("gymfx_serve_slot_evictions_total",
                 "LRU slot evictions (evicted sessions restart from the "
                 "initial carry; monotonic, read at scrape time)",
                 lambda e: float(e.slot_cache.evictions)),
                ("gymfx_serve_slot_decisions_total",
                 "Decisions served through the fused slot ladder "
                 "(monotonic, read at scrape time)",
                 lambda e: float(getattr(e, "slot_decisions", 0))),
                ("gymfx_serve_slot_mirror_bytes_total",
                 "Carry bytes fetched for the one-dispatch-late host "
                 "mirror (monotonic, read at scrape time)",
                 lambda e: float(getattr(e, "mirror_fetch_bytes", 0))),
            )
            for gname, help_text, reader in slot_specs:
                gauge = self.registry.gauge(
                    gname, help_text, labels=("batcher",) + extra
                )
                gauge.set_function(
                    lambda b=batcher, r=reader: (
                        r(b.engine)
                        if getattr(b.engine, "slot_cache", None) is not None
                        else 0.0
                    ),
                    **self._base,
                )
        if batcher.breaker is not None:
            from gymfx_tpu.telemetry.registry import register_resilience

            # per-replica breakers need distinct name label values or
            # the callback gauges of N breakers would collide
            breaker_name = (
                self.name if self.replica is None
                else f"{self.name}:{self.replica}"
            )
            register_resilience(
                self.registry, breaker=batcher.breaker, name=breaker_name
            )
        if self.slo is not None:
            self.slo.register_gauges(self.registry)


def instruments_from_telemetry(telemetry: Optional[Any],
                               name: str = "serve") -> Optional[ServeInstruments]:
    """The one construction path serving callers share: ``None`` in,
    ``None`` out (telemetry off keeps the batcher untouched)."""
    if telemetry is None:
        return None
    return telemetry.serve_instruments(name=name)
