"""Append-only, schema-pinned run ledger: the lifecycle black box.

Every run-shaping event — compiles, superstep dispatches, checkpoint
writes/restores/rollbacks, preemptions, divergences, gate verdicts,
bench rows — lands as one JSONL row with a monotonic ``seq``, a wall
clock ``ts`` (stamped by the sink) and the run's config sha256, so a
post-mortem can replay WHAT happened in WHAT order under WHICH config
without trusting anyone's memory of the session.

Built on the never-raises :class:`~gymfx_tpu.telemetry.sink.JsonlSink`:
a full disk degrades the ledger (``write_errors`` counts it), it never
kills training or serving.  The row shape is pinned by the committed
``ledger_schema.json`` next to this module — :func:`validate_ledger_rows`
is the one validator tests, the run_tests.sh smoke and tooling share,
so the emitter and the schema cannot drift apart silently.
"""
from __future__ import annotations

import hashlib
import json
import threading
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional

from gymfx_tpu.telemetry.sink import JsonlSink

SCHEMA_PATH = Path(__file__).resolve().parent / "ledger_schema.json"

LEDGER_SCHEMA_VERSION = 1

# the pinned lifecycle vocabulary; record() drops (and counts) anything
# else rather than letting ad-hoc kinds rot the schema
EVENT_KINDS = (
    "run_start",
    "run_end",
    "compile_begin",
    "compile_end",
    "recompile",
    "superstep_dispatch",
    "checkpoint_write",
    "checkpoint_restore",
    "checkpoint_rollback",
    "preemption",
    "divergence",
    "gate_verdict",
    "bench_row",
    "serve_bucket_miss",
    "postmortem_dump",
    "profile_capture",
    "policy_promote",
    "policy_demote",
    "policy_rollback",
    "replica_up",
    "replica_down",
    "replica_failover",
    "curriculum_pick",
    "mesh_degrade",
    "mesh_resume",
)


def config_digest(config: Optional[Dict[str, Any]]) -> Optional[str]:
    """sha256 of the canonical-JSON config dict (sorted keys, non-JSON
    leaves repr-coerced) — the provenance stamp every ledger row and
    postmortem manifest carries.  None in, None out."""
    if config is None:
        return None
    blob = json.dumps(config, sort_keys=True, default=repr)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


class RunLedger:
    """Append lifecycle events with a monotonic ``seq``; never raises.

    ``record`` returns True when the row was accepted AND written —
    unknown kinds and sink write failures both return False (the former
    counted in ``dropped_events``, the latter in ``sink.write_errors``).
    """

    def __init__(
        self,
        path: str,
        *,
        config: Optional[Dict[str, Any]] = None,
        config_sha256: Optional[str] = None,
        max_bytes: int = 64 * 1024 * 1024,
        backups: int = 3,
    ):
        self.sink = JsonlSink(path, max_bytes=max_bytes, backups=backups)
        self.path = self.sink.path
        self.config_sha256 = (
            config_sha256 if config_sha256 is not None else config_digest(config)
        )
        self._lock = threading.Lock()
        self._seq = 0
        self.dropped_events = 0
        self._closed = False
        self.record("run_start")

    # ------------------------------------------------------------------
    def record(self, kind: str, **fields: Any) -> bool:
        """Append one event row.  ``fields`` ride alongside the pinned
        base keys (seq, ts, kind, config_sha256, schema_version); a
        field named like a base key is ignored rather than trusted."""
        if kind not in EVENT_KINDS:
            with self._lock:
                self.dropped_events += 1
            return False
        with self._lock:
            if self._closed:
                self.dropped_events += 1
                return False
            self._seq += 1
            seq = self._seq
        row = {k: v for k, v in fields.items()
               if k not in ("seq", "kind", "config_sha256", "schema_version")}
        row.update(
            seq=seq,
            kind=kind,
            config_sha256=self.config_sha256,
            schema_version=LEDGER_SCHEMA_VERSION,
        )
        return self.sink.append(row)

    def close(self) -> None:
        """Append the terminal ``run_end`` row (idempotent)."""
        with self._lock:
            if self._closed:
                return
        self.record("run_end", events=self._seq)
        with self._lock:
            self._closed = True

    def __enter__(self) -> "RunLedger":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


# ---------------------------------------------------------------------------
# the process-global active ledger: emitters that cannot thread a
# Telemetry bundle through their call path (bench row printers, the
# scenario gate CLI) publish through it when a run installed one
_ACTIVE: Optional[RunLedger] = None
_ACTIVE_LOCK = threading.Lock()


def set_active_ledger(ledger: Optional[RunLedger]) -> None:
    global _ACTIVE
    with _ACTIVE_LOCK:
        _ACTIVE = ledger


def get_active_ledger() -> Optional[RunLedger]:
    with _ACTIVE_LOCK:
        return _ACTIVE


# ---------------------------------------------------------------------------
# validation: the committed schema, enforced in tier-1 and the CI smoke
def load_ledger_schema() -> Dict[str, Any]:
    with open(SCHEMA_PATH, encoding="utf-8") as fh:
        schema = json.load(fh)
    schema.pop("_comment", None)
    return schema


def read_ledger(path: str) -> List[Dict[str, Any]]:
    """Parse every row of a ledger file (skipping blank lines)."""
    rows = []
    for line in Path(path).read_text(encoding="utf-8").splitlines():
        if line.strip():
            rows.append(json.loads(line))
    return rows


def validate_ledger_rows(
    rows: Iterable[Dict[str, Any]],
    schema: Optional[Dict[str, Any]] = None,
) -> List[str]:
    """Return a list of violations (empty = the ledger conforms):
    base keys present, known kinds, per-kind required keys, and a
    strictly monotonic ``seq``."""
    if schema is None:
        schema = load_ledger_schema()
    base = schema.get("base_required", ())
    kinds = schema.get("kinds", {})
    problems: List[str] = []
    prev_seq = 0
    for i, row in enumerate(rows):
        where = f"row {i}"
        if not isinstance(row, dict):
            problems.append(f"{where}: not a JSON object")
            continue
        for key in base:
            if key not in row:
                problems.append(f"{where}: missing base key {key!r}")
        kind = row.get("kind")
        spec = kinds.get(kind)
        if spec is None:
            problems.append(
                f"{where}: unknown kind {kind!r}; schema knows {sorted(kinds)}"
            )
        else:
            for key in spec.get("required", ()):
                if key not in row:
                    problems.append(
                        f"{where} ({kind}): missing required key {key!r}"
                    )
        seq = row.get("seq")
        if isinstance(seq, int):
            if seq <= prev_seq:
                problems.append(
                    f"{where}: seq {seq} not monotonic (previous {prev_seq})"
                )
            prev_seq = seq
        else:
            problems.append(f"{where}: seq must be an int, got {seq!r}")
    return problems


def validate_ledger(path: str,
                    schema: Optional[Dict[str, Any]] = None) -> List[str]:
    return validate_ledger_rows(read_ledger(path), schema)
