"""Rolling-window SLO accounting for the serving path.

The bench/overload counters are run totals; operators page on RECENT
behavior.  :class:`SLOWindow` keeps per-request outcomes for the last
``window_s`` seconds and derives the serving SLO trio on demand —
``shed_rate``, ``deadline_miss_rate`` and served-latency ``p99`` —
which :func:`SLOWindow.register_gauges` exposes as callback gauges so a
``/metrics`` scrape always reads the live window.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, Optional, Tuple

# outcome vocabulary shared with bench_infer.py's burst phase
OUTCOMES = ("served", "shed", "deadline_miss", "breaker_open", "failed")


class SLOWindow:
    def __init__(self, window_s: float = 60.0, *,
                 clock: Callable[[], float] = time.monotonic,
                 max_events: int = 100_000):
        if float(window_s) <= 0:
            raise ValueError(f"window_s must be > 0, got {window_s}")
        self.window_s = float(window_s)
        self._clock = clock
        # (t, outcome, latency_s_or_None)
        self._events: Deque[Tuple[float, str, Optional[float]]] = deque(
            maxlen=int(max_events)
        )
        self._lock = threading.Lock()

    def observe(self, outcome: str, latency_s: Optional[float] = None) -> None:
        if outcome not in OUTCOMES:
            raise ValueError(
                f"outcome must be one of {OUTCOMES}, got {outcome!r}"
            )
        with self._lock:
            self._events.append((self._clock(), outcome, latency_s))

    def _prune_locked(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._events and self._events[0][0] < cutoff:
            self._events.popleft()

    def rates(self) -> Dict[str, Any]:
        """Point-in-time SLO view over the trailing window."""
        now = self._clock()
        with self._lock:
            self._prune_locked(now)
            events = list(self._events)
        total = len(events)
        counts = {o: 0 for o in OUTCOMES}
        lat = []
        for _t, outcome, latency in events:
            counts[outcome] += 1
            if outcome == "served" and latency is not None:
                lat.append(latency)
        out: Dict[str, Any] = {
            "window_s": self.window_s,
            "requests": total,
            "shed_rate": counts["shed"] / total if total else 0.0,
            "deadline_miss_rate": (
                counts["deadline_miss"] / total if total else 0.0
            ),
            "p99_s": _percentile(lat, 99.0),
            "p50_s": _percentile(lat, 50.0),
        }
        out.update({f"{o}_count": c for o, c in counts.items()})
        return out

    def register_gauges(self, registry: Any,
                        prefix: str = "gymfx_serve_slo") -> None:
        specs = (
            ("shed_rate", "Requests shed over the trailing window",
             lambda r: r["shed_rate"]),
            ("deadline_miss_rate",
             "Requests past deadline over the trailing window",
             lambda r: r["deadline_miss_rate"]),
            ("p99_seconds",
             "p99 served-request latency over the trailing window",
             lambda r: r["p99_s"]),
            ("requests", "Requests observed in the trailing window",
             lambda r: float(r["requests"])),
            ("window_seconds", "Trailing window length",
             lambda r: r["window_s"]),
        )
        for suffix, help_text, pick in specs:
            g = registry.gauge(f"{prefix}_{suffix}", help_text)
            g.set_function(lambda p=pick: float(p(self.rates()) or 0.0))


def _percentile(values, q: float) -> float:
    """Nearest-rank percentile without numpy (telemetry stays
    import-light); 0.0 on an empty sample."""
    if not values:
        return 0.0
    ordered = sorted(values)
    rank = max(0, min(len(ordered) - 1, int(round(q / 100.0 * len(ordered) + 0.5)) - 1))
    return float(ordered[rank])
