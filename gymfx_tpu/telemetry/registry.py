"""Host-side metrics registry: counters, gauges, fixed-bucket
histograms with label support.

The one shared metric surface for train, serve, live and resilience
telemetry (docs/observability.md).  Everything here is plain Python —
no jax import, no device traffic: device-side accumulation happens in
the trainers' donated scans (telemetry/device_stream.py) and only the
already-fetched host values land here.

Thread-safety: instrument updates take a per-family lock (the serving
path increments from the batcher worker, client threads and the HTTP
scrape thread concurrently); registration takes the registry lock.
Registration is idempotent — asking for an existing name with the same
kind/labels returns the existing instrument, a mismatch raises loudly
(two subsystems silently sharing one name with different shapes is a
dashboard corruption bug).

Gauges support callbacks (:meth:`Gauge.set_function`) so externally
owned state — queue depths, breaker states, retry-budget spend — is
read at scrape time instead of mirrored on every mutation.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# histogram default: request-latency shaped, in seconds (Prometheus
# convention); callers with different dynamics pass their own edges
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
)

_VALID_KINDS = ("counter", "gauge", "histogram")


def _label_key(label_names: Tuple[str, ...], labels: Dict[str, str]) -> Tuple[str, ...]:
    if tuple(sorted(labels)) != tuple(sorted(label_names)):
        raise ValueError(
            f"labels {sorted(labels)} do not match declared label names "
            f"{sorted(label_names)}"
        )
    return tuple(str(labels[name]) for name in label_names)


class _Family:
    """Base of the three instrument kinds: name, help text, declared
    label names and the per-label-set value store."""

    kind = "abstract"

    def __init__(self, name: str, help: str, label_names: Sequence[str]):
        self.name = str(name)
        self.help = str(help)
        self.label_names = tuple(str(n) for n in label_names)
        self._lock = threading.Lock()
        self._values: Dict[Tuple[str, ...], Any] = {}

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        return _label_key(self.label_names, labels)

    def samples(self) -> List[Tuple[Tuple[str, ...], Any]]:
        """Point-in-time [(label_values, value)] sorted by label values
        (deterministic exposition order)."""
        with self._lock:
            items = list(self._values.items())
        return sorted(items, key=lambda kv: kv[0])


class Counter(_Family):
    """Monotonically increasing total.  ``inc`` only accepts
    non-negative amounts — a decreasing counter is always a bug."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease ({amount})")
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + float(amount)

    def value(self, **labels: str) -> float:
        with self._lock:
            return float(self._values.get(self._key(labels), 0.0))


class Gauge(_Family):
    """Point-in-time value; ``set_function`` registers a zero-arg
    callback evaluated at scrape time (for externally owned state)."""

    kind = "gauge"

    def set(self, value: float, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            cur = self._values.get(key, 0.0)
            if callable(cur):
                raise ValueError(
                    f"gauge {self.name}{dict(labels)} is callback-backed"
                )
            self._values[key] = float(cur) + float(amount)

    def set_function(self, fn: Callable[[], float], **labels: str) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = fn

    def value(self, **labels: str) -> float:
        with self._lock:
            raw = self._values.get(self._key(labels), 0.0)
        return float(raw() if callable(raw) else raw)

    def samples(self) -> List[Tuple[Tuple[str, ...], Any]]:
        out = []
        for key, raw in super().samples():
            if callable(raw):
                try:
                    raw = float(raw())
                except Exception:
                    continue  # a dead callback must not kill the scrape
            out.append((key, raw))
        return out


class _HistogramState:
    __slots__ = ("bucket_counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.bucket_counts = [0] * n_buckets  # cumulative at exposition
        self.sum = 0.0
        self.count = 0


class Histogram(_Family):
    """Fixed-bucket histogram: ``observe(v)`` lands in the FIRST bucket
    whose upper edge is ``>= v`` (Prometheus ``le`` semantics); values
    above the last edge count only toward the implicit +Inf bucket."""

    kind = "histogram"

    def __init__(self, name: str, help: str, label_names: Sequence[str],
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, label_names)
        edges = tuple(float(b) for b in buckets)
        if not edges or list(edges) != sorted(set(edges)):
            raise ValueError(
                f"histogram {name} buckets must be non-empty and strictly "
                f"increasing, got {buckets!r}"
            )
        self.buckets = edges

    def observe(self, value: float, **labels: str) -> None:
        value = float(value)
        key = self._key(labels)
        with self._lock:
            state = self._values.get(key)
            if state is None:
                state = self._values[key] = _HistogramState(len(self.buckets))
            for i, edge in enumerate(self.buckets):
                if value <= edge:
                    state.bucket_counts[i] += 1
                    break
            state.sum += value
            state.count += 1

    def snapshot(self, **labels: str) -> Dict[str, Any]:
        """{"buckets": {le: cumulative_count}, "sum": s, "count": n}."""
        with self._lock:
            state = self._values.get(self._key(labels))
            if state is None:
                return {
                    "buckets": {e: 0 for e in self.buckets}, "sum": 0.0,
                    "count": 0,
                }
            cum, out = 0, {}
            for edge, c in zip(self.buckets, state.bucket_counts):
                cum += c
                out[edge] = cum
            return {"buckets": out, "sum": state.sum, "count": state.count}


class MetricsRegistry:
    """Get-or-create factory and collection point for metric families."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    # ------------------------------------------------------------------
    def _get_or_create(self, cls, name: str, help: str,
                       labels: Sequence[str], **kw) -> Any:
        with self._lock:
            existing = self._families.get(name)
            if existing is not None:
                if not isinstance(existing, cls) or existing.label_names != tuple(labels):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind} with labels {existing.label_names}, "
                        f"requested {cls.kind} with labels {tuple(labels)}"
                    )
                return existing
            family = cls(name, help, labels, **kw)
            self._families[name] = family
            return family

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labels, buckets=buckets
        )

    # ------------------------------------------------------------------
    def families(self) -> List[_Family]:
        with self._lock:
            return sorted(self._families.values(), key=lambda f: f.name)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready {name: {kind, help, samples}} — the JSONL sink row
        shape and the /healthz metric mirror."""
        out: Dict[str, Any] = {}
        for fam in self.families():
            samples = []
            if fam.kind == "histogram":
                for key, state in fam.samples():
                    cum, buckets = 0, {}
                    for edge, c in zip(fam.buckets, state.bucket_counts):
                        cum += c
                        buckets[str(edge)] = cum
                    samples.append({
                        "labels": dict(zip(fam.label_names, key)),
                        "buckets": buckets,
                        "sum": state.sum,
                        "count": state.count,
                    })
            else:
                for key, value in fam.samples():
                    samples.append({
                        "labels": dict(zip(fam.label_names, key)),
                        "value": value,
                    })
            out[fam.name] = {
                "kind": fam.kind, "help": fam.help, "samples": samples,
            }
        return out


# ---------------------------------------------------------------------------
# process-global default registry: tools and tests that do not thread a
# Telemetry bundle through (bench scrapes, the run_tests smoke) share it
_GLOBAL: Optional[MetricsRegistry] = None
_GLOBAL_LOCK = threading.Lock()


def global_registry() -> MetricsRegistry:
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = MetricsRegistry()
        return _GLOBAL


# ---------------------------------------------------------------------------
_BREAKER_STATE_CODE = {"closed": 0.0, "half_open": 1.0, "open": 2.0}


def register_resilience(
    registry: MetricsRegistry,
    *,
    monitor: Any = None,
    budget: Any = None,
    breaker: Any = None,
    name: str = "default",
) -> None:
    """Bind the existing resilience counter sets — SkipMonitor guard
    trips, RetryBudget spend, CircuitBreaker state — into ``registry``
    as callback gauges, so ``/healthz``, ``/metrics`` and
    ``MicroBatcher.health()`` read the SAME live objects instead of
    three privately mirrored counter sets.

    ``name`` labels the binding (several breakers/budgets can coexist:
    the live transport's and the serving dispatch's)."""
    if monitor is not None:
        g = registry.gauge(
            "gymfx_resilience_skip_monitor_consecutive",
            "Consecutive fully-skipped train steps (SkipMonitor)",
            labels=("name",),
        )
        g.set_function(lambda m=monitor: float(m.consecutive), name=name)
        g2 = registry.gauge(
            "gymfx_resilience_skip_monitor_skips_total",
            "Total non-finite updates skipped (SkipMonitor)",
            labels=("name",),
        )
        g2.set_function(lambda m=monitor: float(m.total_skips), name=name)
        g3 = registry.gauge(
            "gymfx_resilience_quarantine_resets_total",
            "Total poisoned-env quarantine resets (SkipMonitor)",
            labels=("name",),
        )
        g3.set_function(
            lambda m=monitor: float(m.total_poisoned_env_resets), name=name
        )
    if budget is not None:
        g = registry.gauge(
            "gymfx_resilience_retry_budget_used",
            "Retry tokens spent out of the run-level budget",
            labels=("name",),
        )
        g.set_function(lambda b=budget: float(b.used), name=name)
        g2 = registry.gauge(
            "gymfx_resilience_retry_budget_remaining",
            "Retry tokens remaining in the run-level budget",
            labels=("name",),
        )
        g2.set_function(lambda b=budget: float(b.remaining), name=name)
    if breaker is not None:
        g = registry.gauge(
            "gymfx_resilience_breaker_state",
            "Circuit breaker state (0=closed, 1=half_open, 2=open)",
            labels=("name",),
        )
        g.set_function(
            lambda b=breaker: _BREAKER_STATE_CODE.get(b.state, -1.0),
            name=name,
        )
        g2 = registry.gauge(
            "gymfx_resilience_breaker_trips_total",
            "Closed->open circuit breaker transitions",
            labels=("name",),
        )
        g2.set_function(lambda b=breaker: float(b.trip_count), name=name)
        g3 = registry.gauge(
            "gymfx_resilience_breaker_failures",
            "Consecutive recorded failures inside the breaker",
            labels=("name",),
        )
        g3.set_function(lambda b=breaker: float(b.failures), name=name)


def register_mesh_health(
    registry: MetricsRegistry,
    supervisor: Any,
    *,
    name: str = "train",
) -> None:
    """Bind a :class:`~gymfx_tpu.parallel.elastic.MeshSupervisor` into
    ``registry`` as callback gauges (same idiom as
    :func:`register_resilience` — the gauges read the LIVE supervisor,
    nothing is mirrored):

      gymfx_mesh_devices{state=healthy|degraded|dead}
          device counts from the supervisor's probe classification;
      gymfx_mesh_degrades_total{name=...}
          degrade events (devices marked lost) since run start.
    """
    g = registry.gauge(
        "gymfx_mesh_devices",
        "Mesh devices by health state (MeshSupervisor classification)",
        labels=("state",),
    )
    for state in ("healthy", "degraded", "dead"):
        g.set_function(
            lambda s=supervisor, st=state: float(s.snapshot()[st]),
            state=state,
        )
    g2 = registry.gauge(
        "gymfx_mesh_degrades_total",
        "Mesh degrade events (devices marked lost) since run start",
        labels=("name",),
    )
    g2.set_function(lambda s=supervisor: float(s.degrades), name=name)


def resilience_snapshot(registry: MetricsRegistry) -> Dict[str, Any]:
    """The ``gymfx_resilience_*`` slice of the registry as plain floats,
    merged into ``/healthz`` and ``MicroBatcher.health()`` consumers so
    every surface reports the one registry-backed view."""
    out: Dict[str, Any] = {}
    for fam in registry.families():
        if not fam.name.startswith("gymfx_resilience_"):
            continue
        for key, value in fam.samples():
            short = fam.name[len("gymfx_resilience_"):]
            suffix = "" if key in ((), ("default",)) else "_" + "_".join(key)
            out[short + suffix] = value
    return out
