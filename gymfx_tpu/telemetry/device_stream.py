"""On-device metric streams, drained host-side one dispatch late.

The superstep driver (train/common.make_train_many) already stacks
every per-iteration metric — guard counters included — on a leading
``(k,)`` axis ON DEVICE inside the donated ``lax.scan``; nothing here
adds device work.  :class:`DeviceMetricStream` is the host half: it
holds each dispatch's stacked metrics tree as device arrays and only
materializes them AFTER the next dispatch has been issued (the same
pipelining trick as ResilientLoop's delayed guard fetch), so telemetry
never inserts a hot host sync.  One drain per dispatch feeds

  * the legacy ``log_every`` console line (the old DelayedLogger
    behavior, preserved bit-for-bit — :class:`DelayedLogger` below is
    the back-compat constructor);
  * a :class:`~gymfx_tpu.telemetry.registry.MetricsRegistry`: guard
    counters summed over the superstep into ``gymfx_train_*_total``
    counters, every other scalar (loss, entropy, grad stats) as a
    newest-value ``gymfx_train_metric`` gauge, plus iteration/env-step
    progress counters;
  * an optional JSONL sink row per drained dispatch;
  * an optional :class:`~gymfx_tpu.telemetry.flight_recorder.FlightRecorder`
    frame (the full per-iteration stacks, riding the same single host
    fetch) plus per-superstep device-memory watermark gauges
    (``gymfx_device_memory_bytes{stat=...}`` from the allocator's
    ``memory_stats()`` — a host-side query, never a device sync).

With no registry/sink/recorder and ``log_every=0`` the stream holds
nothing and the training loop is exactly the pre-telemetry one.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

# per-iteration guard counters: summed over the superstep axis when
# drained (everything else is reported as a newest-value gauge)
COUNTER_KEYS = ("nonfinite_skips", "guard_updates", "poisoned_env_resets")


class DeviceMetricStream:
    def __init__(
        self,
        tag: str,
        *,
        iters: int,
        log_every: int = 0,
        registry: Any = None,
        sink: Any = None,
        steps_per_iter: Optional[int] = None,
        printer: Callable[[str], None] = print,
        recorder: Any = None,
    ):
        self.tag = str(tag)
        self.every = int(log_every or 0)
        self.iters = int(iters)
        self.registry = registry
        self.sink = sink
        self.recorder = recorder
        self.steps_per_iter = (
            None if steps_per_iter is None else int(steps_per_iter)
        )
        self._printer = printer
        # (it_end, k, stacked device tree, want_print)
        self._held: Optional[Tuple[int, int, Dict[str, Any], bool]] = None
        self._counters = self._gauge = self._iters_ctr = self._steps_ctr = None
        self._mem_gauge = None
        if registry is not None:
            self._mem_gauge = registry.gauge(
                "gymfx_device_memory_bytes",
                "Device allocator watermark sampled per drained "
                "superstep (memory_stats)",
                labels=("algo", "stat"),
            )
            self._counters = {
                key: registry.counter(
                    f"gymfx_train_{key}_total",
                    f"Cumulative train-step {key} (summed per superstep)",
                    labels=("algo",),
                )
                for key in COUNTER_KEYS
            }
            self._gauge = registry.gauge(
                "gymfx_train_metric",
                "Newest per-iteration training scalar by metric name",
                labels=("algo", "metric"),
            )
            self._iters_ctr = registry.counter(
                "gymfx_train_iterations_total",
                "Training iterations drained through telemetry",
                labels=("algo",),
            )
            self._steps_ctr = registry.counter(
                "gymfx_train_env_steps_total",
                "Environment steps drained through telemetry",
                labels=("algo",),
            )

    # ------------------------------------------------------------------
    def after_dispatch(self, it_start: int, k: int,
                       metrics: Dict[str, Any]) -> None:
        """Call right after dispatching iterations
        ``[it_start, it_start + k)``; ``metrics`` is the dispatch's
        (device) metrics tree — per-iteration values stacked on a
        leading ``(k,)`` axis, or plain scalars when ``k == 1``."""
        self._flush()
        want_print = bool(
            self.every
            and (it_start + k) // self.every > it_start // self.every
        )
        if (want_print or self.registry is not None
                or self.sink is not None or self.recorder is not None):
            self._held = (it_start + k, k, metrics, want_print)

    def finish(self) -> None:
        """Flush the last held dispatch after (or when aborting) the
        loop — ResilientLoop calls this on every exit path so the final
        superstep's metrics are never silently dropped."""
        self._flush()

    # ------------------------------------------------------------------
    def _flush(self) -> None:
        if self._held is None:
            return
        import jax
        import numpy as np

        it_end, k, tree, want_print = self._held
        self._held = None
        # ONE jax.device_get of the whole stacked tree: on a mesh the
        # held leaves are sharded/committed jax.Arrays, and a per-leaf
        # np.asarray would issue one cross-device gather each — this
        # stays a single host fetch per drained dispatch (the dispatch
        # before it has already been issued, so no hot sync either way);
        # plain Python scalars pass through unchanged
        tree = jax.device_get(tree)
        host = {
            key: np.ravel(np.asarray(value)) for key, value in tree.items()
        }
        newest = {
            key: float(arr[-1]) for key, arr in host.items() if arr.size
        }
        if want_print:
            self._printer(
                f"[{self.tag}] iter {it_end}/{self.iters} {newest}"
            )
        if self.recorder is not None:
            self.recorder.record_frame(
                it_end, k,
                {key: arr.tolist() for key, arr in host.items()},
            )
        if self.registry is not None:
            for key, ctr in self._counters.items():
                arr = host.get(key)
                if arr is not None and arr.size:
                    ctr.inc(float(arr.sum()), algo=self.tag)
            for key, value in newest.items():
                if key not in COUNTER_KEYS:
                    self._gauge.set(value, algo=self.tag, metric=key)
            self._iters_ctr.inc(float(k), algo=self.tag)
            if self.steps_per_iter is not None:
                self._steps_ctr.inc(
                    float(k * self.steps_per_iter), algo=self.tag
                )
            from gymfx_tpu.telemetry.mfu import device_memory_watermarks

            watermarks = device_memory_watermarks()
            if watermarks:
                for stat, value in watermarks.items():
                    self._mem_gauge.set(
                        float(value), algo=self.tag, stat=stat
                    )
        if self.sink is not None:
            self.sink.append({
                "kind": "train_metrics",
                "algo": self.tag,
                "iter": it_end,
                "iters": self.iters,
                **newest,
            })


class DelayedLogger(DeviceMetricStream):
    """One-dispatch-delayed ``log_every`` console logging — the original
    train/common.py surface, now a thin construction of the stream with
    telemetry off.  The snapshot for iteration ``i`` is held as device
    arrays and stringified only after the NEXT dispatch is in flight."""

    def __init__(self, tag: str, log_every: int, iters: int):
        super().__init__(tag, iters=iters, log_every=log_every)
