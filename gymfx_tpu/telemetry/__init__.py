"""Unified telemetry: one registry, one sink, one tracer per run.

The :class:`Telemetry` bundle is the object the trainers, the serving
stack and the live wiring thread around; :func:`telemetry_from_config`
is the single construction path off the merged config dict and returns
``None`` when every ``telemetry_*`` knob is unset — callers take the
exact pre-telemetry code path in that case, which is what keeps the
off path bitwise identical (tests/test_telemetry.py pins this).

Config keys (config/defaults.py, all default off):

  ``telemetry_enabled``       master switch (registry + instruments)
  ``telemetry_jsonl``         rotating JSONL sink path
  ``telemetry_spans``         host span records (+ jax.profiler
                              TraceAnnotation regions when profiling)
  ``telemetry_http_port``     /metrics + /healthz endpoint; 0 binds an
                              ephemeral port (serving only)
  ``telemetry_slo_window_s``  rolling SLO window length (serving)

Run-forensics knobs (same off-by-default contract):

  ``telemetry_ledger``              append-only JSONL run-ledger path
  ``telemetry_flight_recorder_dir`` postmortem bundle directory
  ``telemetry_flight_recorder_k``   frames the ring buffer retains
  ``telemetry_compile_watch``       jax.monitoring compile listeners +
                                    executable fingerprinting

Performance-observatory knobs (same off-by-default contract):

  ``telemetry_profile_dir``        managed jax.profiler capture-bundle
                                   directory (telemetry/profiler.py)
  ``telemetry_profile_supersteps`` superstep indices to capture
                                   (comma-separated; default "1")
  ``telemetry_profile_every``      additionally capture every Nth
                                   superstep (0 = off)
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from gymfx_tpu.telemetry.device_stream import (  # noqa: F401
    DelayedLogger,
    DeviceMetricStream,
)
from gymfx_tpu.telemetry.registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    global_registry,
    register_mesh_health,
    register_resilience,
    resilience_snapshot,
)
from gymfx_tpu.telemetry.compile_watch import CompileWatch  # noqa: F401
from gymfx_tpu.telemetry.flight_recorder import (  # noqa: F401
    FlightRecorder,
    validate_postmortem,
)
from gymfx_tpu.telemetry.ledger import (  # noqa: F401
    RunLedger,
    config_digest,
    get_active_ledger,
    set_active_ledger,
    validate_ledger,
)
from gymfx_tpu.telemetry.attribution import (  # noqa: F401
    build_profile_report,
    compare_profile_reports,
    validate_profile_report,
)
from gymfx_tpu.telemetry.profiler import (  # noqa: F401
    ProfilerSession,
    find_captures,
)
from gymfx_tpu.telemetry.sink import JsonlSink, append_jsonl  # noqa: F401
from gymfx_tpu.telemetry.slo import SLOWindow  # noqa: F401
from gymfx_tpu.telemetry.spans import Tracer, null_tracer  # noqa: F401

__all__ = [
    "CompileWatch",
    "Counter",
    "DelayedLogger",
    "DeviceMetricStream",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "JsonlSink",
    "MetricsRegistry",
    "ProfilerSession",
    "RunLedger",
    "SLOWindow",
    "Telemetry",
    "Tracer",
    "append_jsonl",
    "build_profile_report",
    "compare_profile_reports",
    "config_digest",
    "find_captures",
    "get_active_ledger",
    "global_registry",
    "null_tracer",
    "register_mesh_health",
    "register_resilience",
    "resilience_snapshot",
    "set_active_ledger",
    "telemetry_from_config",
    "validate_ledger",
    "validate_postmortem",
    "validate_profile_report",
]


class Telemetry:
    """Registry + sink + tracer + serving knobs for one run."""

    def __init__(
        self,
        *,
        registry: Optional[MetricsRegistry] = None,
        sink: Optional[JsonlSink] = None,
        tracer: Optional[Tracer] = None,
        slo_window_s: float = 60.0,
        http_port: Optional[int] = None,
        ledger: Optional[RunLedger] = None,
        recorder: Optional[FlightRecorder] = None,
        compile_watch: Optional[CompileWatch] = None,
        profiler: Optional[ProfilerSession] = None,
    ):
        self.registry = registry if registry is not None else MetricsRegistry()
        self.sink = sink
        self.tracer = tracer if tracer is not None else null_tracer()
        self.slo_window_s = float(slo_window_s)
        self.http_port = None if http_port is None else int(http_port)
        self.ledger = ledger
        self.recorder = recorder
        self.compile_watch = compile_watch
        self.profiler = profiler
        self._server = None

    # -- construction helpers the layers share -------------------------
    def span(self, name: str, **attrs: Any):
        return self.tracer.span(name, **attrs)

    def device_stream(self, tag: str, *, iters: int, log_every: int = 0,
                      steps_per_iter: Optional[int] = None) -> DeviceMetricStream:
        return DeviceMetricStream(
            tag, iters=iters, log_every=log_every, registry=self.registry,
            sink=self.sink, steps_per_iter=steps_per_iter,
            recorder=self.recorder,
        )

    def serve_instruments(self, name: str = "serve"):
        from gymfx_tpu.telemetry.instruments import ServeInstruments

        return ServeInstruments(
            self.registry, slo=SLOWindow(self.slo_window_s), name=name
        )

    def start_http(self, health_fn=None):
        """Start the /metrics + /healthz endpoint when
        ``telemetry_http_port`` was configured (idempotent); returns the
        server or None."""
        if self.http_port is None:
            return None
        if self._server is None:
            from gymfx_tpu.telemetry.http import TelemetryServer

            self._server = TelemetryServer(
                self.registry, health_fn=health_fn, port=self.http_port
            )
        return self._server

    @property
    def server(self):
        return self._server

    def close(self) -> None:
        if self._server is not None:
            self._server.close()
            self._server = None
        if self.profiler is not None:
            self.profiler.close()  # finalize a capture an abort left open
        if self.compile_watch is not None:
            self.compile_watch.uninstall()
        if self.ledger is not None:
            if get_active_ledger() is self.ledger:
                set_active_ledger(None)
            self.ledger.close()
        if self.sink is not None:
            self.sink.close()


def telemetry_from_config(config: Dict[str, Any]) -> Optional[Telemetry]:
    """``None`` unless some ``telemetry_*`` knob is set — the contract
    callers rely on to keep the off path untouched."""
    enabled = bool(config.get("telemetry_enabled"))
    jsonl = config.get("telemetry_jsonl") or None
    spans = bool(config.get("telemetry_spans"))
    port = config.get("telemetry_http_port")
    port = None if port in (None, "") or int(port) < 0 else int(port)
    ledger_path = config.get("telemetry_ledger") or None
    recorder_dir = config.get("telemetry_flight_recorder_dir") or None
    watch = bool(config.get("telemetry_compile_watch"))
    profile_dir = config.get("telemetry_profile_dir") or None
    if not (enabled or jsonl or spans or port is not None
            or ledger_path or recorder_dir or watch or profile_dir):
        return None
    registry = MetricsRegistry()
    sink = JsonlSink(str(jsonl)) if jsonl else None
    tracer = Tracer(enabled=spans, registry=registry if spans else None,
                    sink=sink if spans else None)
    sha = config_digest(config)
    ledger = None
    if ledger_path:
        ledger = RunLedger(str(ledger_path), config_sha256=sha)
        set_active_ledger(ledger)
    recorder = None
    if recorder_dir:
        recorder = FlightRecorder(
            str(recorder_dir),
            k=int(config.get("telemetry_flight_recorder_k", 8) or 8),
            config_sha256=sha,
            ledger=ledger,
        )
        recorder.set_resilience_source(
            lambda: resilience_snapshot(registry)
        )
    compile_watch = None
    if watch:
        compile_watch = CompileWatch(
            registry, ledger=ledger, recorder=recorder
        ).install()
    profiler = None
    if profile_dir:
        profiler = ProfilerSession(
            str(profile_dir),
            supersteps=config.get("telemetry_profile_supersteps"),
            every=int(config.get("telemetry_profile_every", 0) or 0),
            config_sha256=sha,
            registry=registry,
            ledger=ledger,
            compile_watch=compile_watch,
        )
    return Telemetry(
        registry=registry,
        sink=sink,
        tracer=tracer,
        slo_window_s=float(config.get("telemetry_slo_window_s", 60.0) or 60.0),
        http_port=port,
        ledger=ledger,
        recorder=recorder,
        compile_watch=compile_watch,
        profiler=profiler,
    )
