"""Opt-in stdlib exposition endpoint: ``/metrics`` (Prometheus text)
and ``/healthz`` (JSON).

A daemon-threaded ``http.server.ThreadingHTTPServer`` — no new
dependencies, no framework — bound to localhost by default.  Serving
fast paths never touch it: scrapes read the registry under its own
per-family locks.  ``port=0`` binds an ephemeral port (tests, the
run_tests.sh smoke); the bound port is exposed as :attr:`port`.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional

from gymfx_tpu.telemetry import prometheus


class TelemetryServer:
    """``TelemetryServer(registry, health_fn=..., port=0)`` then
    :meth:`close` (or use as a context manager)."""

    def __init__(
        self,
        registry: Any,
        *,
        health_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        port: int = 0,
        host: str = "127.0.0.1",
    ):
        self.registry = registry
        self.health_fn = health_fn
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # keep scrapes off stderr
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 (stdlib handler API)
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        body = prometheus.render(outer.registry).encode()
                        self._send(200, body, prometheus.CONTENT_TYPE)
                    elif path == "/healthz":
                        payload = (
                            outer.health_fn()
                            if outer.health_fn is not None
                            else {"status": "ok"}
                        )
                        body = json.dumps(
                            payload, default=_coerce
                        ).encode()
                        self._send(200, body, "application/json")
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except Exception as exc:  # a scrape bug must not wedge the server
                    try:
                        self._send(
                            500, f"error: {exc}\n".encode(), "text/plain"
                        )
                    except Exception:
                        pass

        self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="gymfx-telemetry-http",
            daemon=True,
        )
        self._thread.start()

    @property
    def port(self) -> int:
        return int(self._httpd.server_address[1])

    @property
    def url(self) -> str:
        host = self._httpd.server_address[0]
        return f"http://{host}:{self.port}"

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:
            pass

    def __enter__(self) -> "TelemetryServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _coerce(value: Any):
    try:
        return float(value)
    except (TypeError, ValueError):
        return repr(value)


def scrape(url: str, timeout: float = 5.0) -> str:
    """GET one exposition page (the smoke tools and tests' one-liner;
    localhost only — no retry machinery)."""
    from urllib.request import urlopen

    with urlopen(url, timeout=timeout) as resp:
        return resp.read().decode("utf-8", "replace")
