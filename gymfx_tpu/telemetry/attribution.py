"""Measured-MFU attribution: trace -> schema-pinned profile report.

The closing arc of the performance observatory: take one capture bundle
(:mod:`gymfx_tpu.telemetry.profiler`), parse its device timeline
(:mod:`gymfx_tpu.telemetry.trace_parse`), and reconcile what the
hardware *measured* against what the repo previously only *inferred* —
the ``bench_util.measure_phase_split`` wall split and the analytic FLOP
model (:mod:`gymfx_tpu.telemetry.mfu`).  The output is one
``profile_report.json``:

  * ``trace``          device/host lanes, busy vs window time, the
                       dispatch gap (host overhead), fusion coverage,
                       and the top-N kernel table
  * ``phases``         device time grouped under the rollout/update
                       ``jax.named_scope`` annotations
  * ``reconciliation`` trace-attributed phase fractions vs the
                       phase-split baseline the capture manifest
                       carries, with a tolerance verdict
  * ``mfu_measured``   FLOPs over *measured device time* — the
                       measured twin of the ``mfu_analytic`` block
                       (``mfu`` itself stays null where the chip's
                       peak is unknown, the repo-wide CPU convention)

pinned by the committed ``profile_report_schema.json`` next to this
module; :func:`validate_profile_report` is the one validator tests,
``tools/profile_report.py`` and the run_tests.sh smoke share.
:func:`compare_profile_reports` diffs two reports at a per-kernel
regression threshold — the hook ``tools/bench_sentinel.py`` uses to
gate kernel-level regressions, not just end-to-end steps/sec.
"""
from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence

from gymfx_tpu.telemetry.profiler import MANIFEST_NAME, SCOPE_MAP_NAME
from gymfx_tpu.telemetry.trace_parse import (
    PHASE_SCOPES,
    group_by_scope,
    parse_trace,
)

SCHEMA_PATH = Path(__file__).resolve().parent / "profile_report_schema.json"

PROFILE_REPORT_SCHEMA_VERSION = 1

# phase-attribution agreement the CI smoke demands: the trace-measured
# rollout fraction within this of the measure_phase_split fraction
DEFAULT_TOLERANCE = 0.25

_MANIFEST_ECHO_KEYS = (
    "config_sha256", "it_start", "k", "it_end", "label",
    "platform", "device_kind", "comparable", "hw_flops_peak",
    "algo", "n_envs", "horizon", "steps_per_iter", "fingerprints",
)


def _round(value: Optional[float], digits: int = 4) -> Optional[float]:
    return None if value is None else round(float(value), digits)


def _load_json(path: Path) -> Dict[str, Any]:
    try:
        doc = json.loads(path.read_text(encoding="utf-8"))
        return doc if isinstance(doc, dict) else {}
    except Exception:
        return {}


def build_profile_report(
    capture_dir: str,
    *,
    top_n: int = 15,
    tolerance: float = DEFAULT_TOLERANCE,
    scopes: Sequence[str] = PHASE_SCOPES,
) -> Dict[str, Any]:
    """One capture bundle -> the report dict (never raises; a broken
    bundle yields ``trace.ok=False`` and null attribution)."""
    bundle = Path(capture_dir)
    manifest = _load_json(bundle / MANIFEST_NAME)
    scope_map = _load_json(bundle / str(
        manifest.get("scope_map_file") or SCOPE_MAP_NAME
    ))
    summary = parse_trace(str(bundle), scopes=scopes)
    groups = group_by_scope(summary, scope_map, scopes=scopes)

    k = manifest.get("k")
    k = int(k) if isinstance(k, (int, float)) and k else 1
    busy_ms = summary["device_busy_us"] / 1e3
    window_ms = summary["window_us"] / 1e3
    gap_ms = max(0.0, window_ms - busy_ms)
    total_op_ms = summary["device_total_us"] / 1e3

    ops = summary.get("ops") or {}
    fusion_ms = sum(
        op["total_us"] for name, op in ops.items() if "fusion" in name
    ) / 1e3
    top = sorted(
        ops.items(), key=lambda kv: kv[1]["total_us"], reverse=True
    )[: max(0, int(top_n))]
    top_kernels = []
    for name, op in top:
        scope = op.get("scope")
        if scope not in scopes:
            mapped = scope_map.get(name)
            scope = mapped if mapped in scopes else None
        ms = op["total_us"] / 1e3
        top_kernels.append({
            "name": name,
            "count": int(op["count"]),
            "total_ms": _round(ms),
            "total_ms_per_step": _round(ms / k),
            "frac": _round(ms / total_op_ms if total_op_ms else 0.0),
            "scope": scope,
        })

    # -- phases: device op time under the named_scope annotations ------
    phase_ms = {scope: groups.get(scope, 0.0) / 1e3 for scope in scopes}
    unattributed_ms = groups.get("unattributed", 0.0) / 1e3
    attributed_ms = sum(phase_ms.values())
    rollout_ms = phase_ms.get("rollout", 0.0)
    update_ms = phase_ms.get("update", 0.0)
    rollout_frac = update_frac = None
    if attributed_ms > 0:
        rollout_frac = rollout_ms / attributed_ms
        update_frac = update_ms / attributed_ms
    phases = {
        "rollout_ms": _round(rollout_ms),
        "update_ms": _round(update_ms),
        "unattributed_ms": _round(unattributed_ms),
        "rollout_frac": _round(rollout_frac),
        "update_frac": _round(update_frac),
        # how much of the device op time the scope map explained at all
        "attributed_frac": _round(
            attributed_ms / total_op_ms if total_op_ms else 0.0
        ),
    }

    # -- reconciliation vs the measure_phase_split baseline ------------
    split = manifest.get("phase_split") or {}
    split_rollout = split.get("rollout_ms")
    split_update = split.get("update_ms")
    split_rollout_frac = None
    if (isinstance(split_rollout, (int, float))
            and isinstance(split_update, (int, float))
            and (split_rollout + split_update) > 0):
        split_rollout_frac = split_rollout / (split_rollout + split_update)
    err = within = None
    if split_rollout_frac is not None and rollout_frac is not None:
        err = abs(rollout_frac - split_rollout_frac)
        # relative to the split fraction, floored at an absolute share
        # so a tiny phase cannot explode the ratio
        within = bool(
            err <= float(tolerance) * max(split_rollout_frac, 0.05)
            or err <= float(tolerance) * 0.5
        )
    reconciliation = {
        "split_rollout_ms": _round(split_rollout),
        "split_update_ms": _round(split_update),
        "split_rollout_frac": _round(split_rollout_frac),
        "trace_rollout_frac": _round(rollout_frac),
        "rollout_frac_abs_err": _round(err),
        "tolerance": float(tolerance),
        "within_tolerance": within,
        "split_source": split.get("source"),
    }

    # -- measured MFU: FLOPs over measured device time -----------------
    device_ms_per_step = (busy_ms / k) if busy_ms > 0 else None
    xla_flops = manifest.get("xla_flops_per_step")
    analytic_flops = manifest.get("analytic_flops_per_step")
    flops, flops_source = None, None
    if isinstance(xla_flops, (int, float)) and xla_flops > 0:
        flops, flops_source = float(xla_flops), "xla"
    elif isinstance(analytic_flops, (int, float)) and analytic_flops > 0:
        flops, flops_source = float(analytic_flops), "analytic"
    achieved = None
    if flops is not None and device_ms_per_step:
        achieved = flops / (device_ms_per_step / 1e3)
    peak = manifest.get("hw_flops_peak")
    peak = float(peak) if isinstance(peak, (int, float)) and peak > 0 else None
    mfu_measured = {
        "device_ms_per_step": _round(device_ms_per_step),
        "flops_per_step": flops,
        "flops_source": flops_source,
        "achieved_flops_per_sec": _round(achieved, 1),
        "hw_flops_peak": peak,
        # null where the chip's public peak is unknown (CPU) — same
        # convention as mfu_analytic on every bench row
        "mfu": _round(
            achieved / peak if achieved is not None and peak else None, 5
        ),
    }
    analytic_mfu = None
    if (isinstance(analytic_flops, (int, float)) and analytic_flops > 0
            and peak and device_ms_per_step):
        analytic_mfu = analytic_flops / (device_ms_per_step / 1e3) / peak
    mfu_analytic = {
        "analytic_flops_per_step": (
            float(analytic_flops)
            if isinstance(analytic_flops, (int, float)) else None
        ),
        "hw_flops_peak": peak,
        "mfu_analytic": _round(analytic_mfu, 5),
    }

    return {
        "schema_version": PROFILE_REPORT_SCHEMA_VERSION,
        "capture_dir": str(bundle),
        "manifest": {
            key: manifest.get(key) for key in _MANIFEST_ECHO_KEYS
        },
        "trace": {
            "ok": bool(summary.get("ok")),
            "error": summary.get("error"),
            "events": int(summary.get("events", 0)),
            "device_lanes": summary.get("device_lanes", []),
            "host_lanes": summary.get("host_lanes", []),
            "device_busy_ms": _round(busy_ms),
            "device_op_ms": _round(total_op_ms),
            "window_ms": _round(window_ms),
            "dispatch_gap_ms": _round(gap_ms),
            "dispatch_gap_frac": _round(
                gap_ms / window_ms if window_ms else None
            ),
            "fusion_coverage": _round(
                fusion_ms / total_op_ms if total_op_ms else None
            ),
            "top_kernels": top_kernels,
        },
        "phases": phases,
        "reconciliation": reconciliation,
        "mfu_measured": mfu_measured,
        "mfu_analytic": mfu_analytic,
    }


# ---------------------------------------------------------------------------
# validation: the committed schema, shared by tier-1 and the CI smoke
def load_profile_report_schema() -> Dict[str, Any]:
    with open(SCHEMA_PATH, encoding="utf-8") as fh:
        schema = json.load(fh)
    schema.pop("_comment", None)
    return schema


def validate_profile_report(
    report: Dict[str, Any],
    schema: Optional[Dict[str, Any]] = None,
) -> List[str]:
    """Return a list of violations (empty = the report conforms):
    top-level sections, per-section required keys, and per-kernel row
    keys — presence-pinned like the bench contract (values may be null
    where the backend cannot say)."""
    if schema is None:
        schema = load_profile_report_schema()
    problems: List[str] = []
    if not isinstance(report, dict):
        return ["report is not a JSON object"]
    for key in schema.get("required", ()):
        if key not in report:
            problems.append(f"missing top-level key {key!r}")
    version = report.get("schema_version")
    if version != schema.get("schema_version"):
        problems.append(
            f"schema_version {version!r} != {schema.get('schema_version')!r}"
        )
    for section, req_key in (
        ("manifest", "manifest_required"),
        ("trace", "trace_required"),
        ("phases", "phases_required"),
        ("reconciliation", "reconciliation_required"),
        ("mfu_measured", "mfu_measured_required"),
        ("mfu_analytic", "mfu_analytic_required"),
    ):
        block = report.get(section)
        if not isinstance(block, dict):
            problems.append(f"section {section!r} is not an object")
            continue
        for key in schema.get(req_key, ()):
            if key not in block:
                problems.append(f"{section}: missing required key {key!r}")
    kernels = (report.get("trace") or {}).get("top_kernels")
    if isinstance(kernels, list):
        for i, row in enumerate(kernels):
            if not isinstance(row, dict):
                problems.append(f"top_kernels[{i}]: not an object")
                continue
            for key in schema.get("kernel_required", ()):
                if key not in row:
                    problems.append(
                        f"top_kernels[{i}]: missing required key {key!r}"
                    )
    else:
        problems.append("trace.top_kernels is not a list")
    return problems


# ---------------------------------------------------------------------------
def compare_profile_reports(
    base: Dict[str, Any],
    new: Dict[str, Any],
    *,
    threshold: float = DEFAULT_TOLERANCE,
    min_ms: float = 0.05,
) -> Dict[str, Any]:
    """Per-kernel regression diff of two reports: a kernel regresses
    when its per-step time grows more than ``threshold`` over the base
    (kernels under ``min_ms`` per step are noise and skipped), and the
    end-to-end device time is gated the same way.  ``ok`` is the gate
    verdict; ``comparable`` records whether the two captures came from
    the same platform/device_kind (the caller decides whether a
    non-comparable pair should gate)."""
    def _kernels(report: Dict[str, Any]) -> Dict[str, float]:
        out = {}
        for row in (report.get("trace") or {}).get("top_kernels") or []:
            ms = row.get("total_ms_per_step")
            if isinstance(row.get("name"), str) and isinstance(
                    ms, (int, float)):
                out[row["name"]] = float(ms)
        return out

    base_m = base.get("manifest") or {}
    new_m = new.get("manifest") or {}
    comparable = (
        base_m.get("platform") == new_m.get("platform")
        and base_m.get("device_kind") == new_m.get("device_kind")
    )
    base_k, new_k = _kernels(base), _kernels(new)
    regressions: List[Dict[str, Any]] = []
    improvements: List[Dict[str, Any]] = []
    for name in sorted(set(base_k) & set(new_k)):
        b, n = base_k[name], new_k[name]
        if b < float(min_ms):
            continue
        ratio = n / b if b > 0 else None
        entry = {
            "kind": "kernel", "name": name,
            "base_ms_per_step": round(b, 4), "new_ms_per_step": round(n, 4),
            "ratio": round(ratio, 4) if ratio is not None else None,
        }
        if ratio is not None and ratio > 1.0 + float(threshold):
            regressions.append(entry)
        elif ratio is not None and ratio < 1.0 - float(threshold):
            improvements.append(entry)
    b_step = (base.get("mfu_measured") or {}).get("device_ms_per_step")
    n_step = (new.get("mfu_measured") or {}).get("device_ms_per_step")
    if (isinstance(b_step, (int, float)) and isinstance(n_step, (int, float))
            and b_step > 0):
        ratio = n_step / b_step
        entry = {
            "kind": "device_time",
            "name": "device_ms_per_step",
            "base_ms_per_step": round(float(b_step), 4),
            "new_ms_per_step": round(float(n_step), 4),
            "ratio": round(ratio, 4),
        }
        if ratio > 1.0 + float(threshold):
            regressions.append(entry)
        elif ratio < 1.0 - float(threshold):
            improvements.append(entry)
    return {
        "threshold": float(threshold),
        "min_ms": float(min_ms),
        "comparable": bool(comparable),
        "only_in_base": sorted(set(base_k) - set(new_k)),
        "only_in_new": sorted(set(new_k) - set(base_k)),
        "regressions": regressions,
        "improvements": improvements,
        "ok": not regressions,
    }
