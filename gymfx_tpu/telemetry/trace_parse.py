"""Stdlib-only parser for the perfetto ``trace.json.gz`` jax.profiler
emits — the reading half of the performance observatory.

``bench.py --trace`` and :class:`~gymfx_tpu.telemetry.profiler.ProfilerSession`
write Chrome-trace JSON under ``<dir>/plugins/profile/<ts>/``; nothing
in the repo read it back until this module.  :func:`parse_trace` turns
one capture into an aggregate summary: device vs host lanes, per-op
duration totals, the device-busy interval union and the dispatch-gap
window — everything :mod:`gymfx_tpu.telemetry.attribution` needs to
attribute measured device time.

Lane splitting: an "X" (complete) event is DEVICE work when its args
carry the XLA op identity (``hlo_op``/``hlo_module`` — how the CPU
backend's executor threads report) or when its process is a
``/device:``-named lane (how TPU device streams report); everything
else is host-side (python dispatch, ``TraceAnnotation`` spans).

Scope grouping: TPU device events often carry the full
``jit(...)/rollout/...`` op path in their args; CPU thunk events carry
only the bare HLO instruction name.  :func:`scope_map_from_hlo`
recovers the mapping from the compiled executable's optimized-HLO
``op_name`` metadata (where the ``jax.named_scope("rollout")`` /
``("update")`` annotations the trainers plant survive compilation), and
the profiler stores it as a ``scope_map.json`` sidecar in the capture
bundle so grouping works on any backend.

Never-raises contract: a malformed capture yields ``ok=False`` and an
empty summary — a broken trace costs the report, never the caller.
"""
from __future__ import annotations

import gzip
import json
import re
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

# the phase annotations PR 6 plants in every trainer's fused step
PHASE_SCOPES = ("rollout", "update")

# optimized-HLO instruction with op_name metadata, e.g.
#   %copy.340 = f32[...] copy(...), metadata={op_name="jit(main)/rollout/..."}
_HLO_OP_NAME_RE = re.compile(
    r'%?([A-Za-z0-9_.\-]+)\s*=\s*[^\n]*metadata=\{[^}]*op_name="([^"]*)"'
)


def find_trace_files(root: str) -> List[str]:
    """Every ``*.trace.json(.gz)`` under ``root`` (a capture bundle or
    a raw ``jax.profiler`` output dir), sorted for determinism."""
    try:
        base = Path(root)
        if base.is_file():
            return [str(base)]
        out = sorted(
            str(p) for pattern in ("*.trace.json.gz", "*.trace.json")
            for p in base.rglob(pattern)
        )
        return out
    except Exception:
        return []


def _load_events(path: str) -> List[Dict[str, Any]]:
    raw = Path(path).read_bytes()
    if raw[:2] == b"\x1f\x8b":
        raw = gzip.decompress(raw)
    doc = json.loads(raw.decode("utf-8", errors="replace"))
    events = doc.get("traceEvents", []) if isinstance(doc, dict) else []
    return [e for e in events if isinstance(e, dict)]


def _scope_from_path(path: str,
                     scopes: Sequence[str]) -> Optional[str]:
    """First ``scopes`` member on an ``op_name`` path ("jit(main)/
    rollout/while/..." -> "rollout"), or None."""
    for part in str(path).split("/"):
        if part in scopes:
            return part
    return None


# computation header at column 0: `%region_2.101 (arg: ...) -> ... {`
# or `ENTRY %main.2164 (...) -> ... {`
_HLO_COMP_RE = re.compile(r"^(?:ENTRY\s+)?%?([A-Za-z0-9_.\-]+)\s*[({]")
# `%while.158 = (...) while(%tuple.5), condition=..., body=%region_2.101`
_HLO_WHILE_RE = re.compile(
    r"%?([A-Za-z0-9_.\-]+)\s*=[^\n]*?\bwhile\("
    r"[^\n]*?body=%?([A-Za-z0-9_.\-]+)"
)


def scope_map_from_hlo(hlo_text: str,
                       scopes: Optional[Sequence[str]] = PHASE_SCOPES,
                       ) -> Dict[str, str]:
    """``{instruction_name: scope}`` from optimized-HLO ``op_name``
    metadata.  With ``scopes`` (default rollout/update) only
    instructions under one of those named scopes are kept; with
    ``scopes=None`` the full op path is returned instead.  Trace event
    names on the CPU backend are the top-level optimized-HLO
    instruction names, so this map is exactly the join key
    :func:`group_by_scope` needs.

    XLA's scan loops surface as ``while`` instructions that carry no
    ``op_name`` of their own (the scan is a compiler artifact) yet hold
    real self time in the trace (loop bookkeeping + inlined body work),
    so an unscoped ``while`` inherits the strict-majority scope of the
    instructions in its body computation — the rollout scan body is
    wall-to-wall rollout-tagged ops."""
    out: Dict[str, str] = {}
    if scopes is None:
        try:
            for name, op_name in _HLO_OP_NAME_RE.findall(hlo_text or ""):
                out[name] = op_name
        except Exception:
            return {}
        return out
    try:
        # one line walk: per-instruction scope, per-computation scope
        # histogram, and while -> body-computation edges
        comp_counts: Dict[str, Dict[str, int]] = {}
        while_edges: List[Tuple[str, str, str]] = []  # (name, comp, body)
        comp = "?"
        for line in (hlo_text or "").splitlines():
            if line[:1] not in (" ", "\t", ""):
                match = _HLO_COMP_RE.match(line)
                if match:
                    comp = match.group(1)
                continue
            match = _HLO_OP_NAME_RE.search(line)
            if match:
                scope = _scope_from_path(match.group(2), scopes)
                if scope is not None:
                    out[match.group(1)] = scope
                    counts = comp_counts.setdefault(comp, {})
                    counts[scope] = counts.get(scope, 0) + 1
            if "while(" in line:
                match = _HLO_WHILE_RE.search(line)
                if match:
                    while_edges.append((match.group(1), comp, match.group(2)))
        # resolve unscoped whiles inner-to-outer so a nested scan feeds
        # its parent's histogram (two passes reach any practical depth)
        for _ in range(2):
            for name, comp, body in while_edges:
                if name in out:
                    continue
                counts = comp_counts.get(body, {})
                total = sum(counts.values())
                if not total:
                    continue
                scope, votes = max(counts.items(), key=lambda kv: kv[1])
                if votes * 2 > total:
                    out[name] = scope
                    parent = comp_counts.setdefault(comp, {})
                    parent[scope] = parent.get(scope, 0) + 1
    except Exception:
        return {}
    return out


def _merged_span_us(intervals: List[Tuple[float, float]]) -> float:
    """Total covered microseconds of the interval union (device lanes
    can overlap across executor threads; a plain sum double-counts)."""
    total = 0.0
    end = None
    for start, stop in sorted(intervals):
        if end is None or start > end:
            total += stop - start
            end = stop
        elif stop > end:
            total += stop - end
            end = stop
    return total


def _empty_summary(error: Optional[str] = None) -> Dict[str, Any]:
    return {
        "ok": error is None,
        "error": error,
        "trace_files": [],
        "events": 0,
        "device_lanes": [],
        "host_lanes": [],
        "device_total_us": 0.0,
        "device_busy_us": 0.0,
        "window_us": 0.0,
        "host_total_us": 0.0,
        "ops": {},
        "host_ops": {},
    }


def parse_trace(root: str,
                scopes: Sequence[str] = PHASE_SCOPES) -> Dict[str, Any]:
    """Aggregate one capture (bundle dir, profiler output dir, or a
    single trace file) into a summary dict; never raises.

    ``ops`` maps device op name -> ``{count, total_us, module, path,
    scope}`` (``path``/``scope`` filled when the event args carried the
    op path — TPU traces); ``host_ops`` is the same aggregation over
    host-lane events (python dispatch frames, ``TraceAnnotation``
    spans like ``train/superstep``).

    Device op totals are SELF time (duration minus contained child
    events on the same thread): the CPU executor emits a ``while``
    loop thunk as one long event *containing* its body thunks, so raw
    durations double-count every nested op and skew attribution —
    self times partition the busy time instead."""
    try:
        files = find_trace_files(root)
        if not files:
            return _empty_summary(f"no trace files under {root!r}")
        processes: Dict[Any, str] = {}
        threads: Dict[Tuple[Any, Any], str] = {}
        ops: Dict[str, Dict[str, Any]] = {}
        host_ops: Dict[str, Dict[str, Any]] = {}
        device_lanes: Dict[str, float] = {}
        host_lanes: Dict[str, float] = {}
        device_intervals: List[Tuple[float, float]] = []
        # (file, pid, tid) -> [[ts, dur, name, lane, args], ...] so the
        # self-time pass can detect nesting per thread
        lane_events: Dict[Tuple[Any, Any, Any], List[list]] = {}
        n_events = 0
        parsed_any = False
        for path in files:
            try:
                events = _load_events(path)
            except Exception:
                continue
            parsed_any = True
            # metadata pass first: lane names may be declared after use
            for ev in events:
                if ev.get("ph") != "M":
                    continue
                args = ev.get("args") or {}
                if ev.get("name") == "process_name":
                    processes[ev.get("pid")] = str(args.get("name", ""))
                elif ev.get("name") == "thread_name":
                    threads[(ev.get("pid"), ev.get("tid"))] = str(
                        args.get("name", "")
                    )
            for ev in events:
                if ev.get("ph") != "X":
                    continue
                n_events += 1
                args = ev.get("args") or {}
                pid, tid = ev.get("pid"), ev.get("tid")
                pname = processes.get(pid, str(pid))
                lane = f"{pname}/{threads.get((pid, tid), str(tid))}"
                name = str(ev.get("name", "?"))
                try:
                    ts = float(ev.get("ts", 0.0))
                    dur = float(ev.get("dur", 0.0))
                except Exception:
                    ts, dur = 0.0, 0.0
                is_device = (
                    "hlo_op" in args or "hlo_module" in args
                    or pname.startswith("/device:")
                )
                if is_device:
                    lane_events.setdefault((path, pid, tid), []).append(
                        [ts, dur, name, lane, args]
                    )
                    device_intervals.append((ts, ts + dur))
                else:
                    hop = host_ops.setdefault(
                        name, {"count": 0, "total_us": 0.0}
                    )
                    hop["count"] += 1
                    hop["total_us"] += dur
                    host_lanes[lane] = host_lanes.get(lane, 0.0) + dur
        if not parsed_any:
            return _empty_summary(f"unparseable trace files under {root!r}")
        # self-time pass: per thread, subtract each event's directly
        # contained children so a container thunk (the rollout `while`)
        # keeps only its loop overhead and the body ops keep their own
        for events_list in lane_events.values():
            events_list.sort(key=lambda e: (e[0], -e[1]))
            stack: List[list] = []  # [end, child_dur_accumulator]
            for ev in events_list:
                ts, dur = ev[0], ev[1]
                while stack and stack[-1][0] <= ts:
                    stack.pop()
                if stack:
                    stack[-1][1] += dur
                frame = [ts + dur, 0.0]
                stack.append(frame)
                ev.append(frame)  # read child_dur after the walk
            for ts, dur, name, lane, args, frame in events_list:
                self_us = max(0.0, dur - frame[1])
                op = ops.setdefault(
                    name,
                    {"count": 0, "total_us": 0.0, "module": None,
                     "path": None, "scope": None},
                )
                op["count"] += 1
                op["total_us"] += self_us
                if op["module"] is None and args.get("hlo_module"):
                    op["module"] = str(args["hlo_module"])
                if op["path"] is None:
                    # TPU traces carry the op path in args; take the
                    # first arg value that looks like one
                    for key in ("long_name", "tf_op", "name"):
                        value = args.get(key)
                        if isinstance(value, str) and "/" in value:
                            op["path"] = value
                            op["scope"] = _scope_from_path(value, scopes)
                            break
                device_lanes[lane] = device_lanes.get(lane, 0.0) + self_us
        window = 0.0
        if device_intervals:
            window = (max(stop for _, stop in device_intervals)
                      - min(start for start, _ in device_intervals))
        return {
            "ok": True,
            "error": None,
            "trace_files": files,
            "events": n_events,
            "device_lanes": sorted(device_lanes),
            "host_lanes": sorted(host_lanes),
            "device_total_us": sum(op["total_us"] for op in ops.values()),
            "device_busy_us": _merged_span_us(device_intervals),
            "window_us": window,
            "host_total_us": sum(op["total_us"] for op in host_ops.values()),
            "ops": ops,
            "host_ops": host_ops,
        }
    except Exception as exc:  # the never-raises floor
        return _empty_summary(f"trace parse failed: {exc!r}")


def group_by_scope(summary: Dict[str, Any],
                   scope_map: Optional[Dict[str, str]] = None,
                   scopes: Sequence[str] = PHASE_SCOPES) -> Dict[str, float]:
    """Device time (us) per named scope: ``{scope: us, ...,
    "unattributed": us}``.  Attribution order per op: the scope the
    parser found in the event args (TPU), then the ``scope_map``
    sidecar lookup by op name (CPU), else unattributed."""
    groups: Dict[str, float] = {scope: 0.0 for scope in scopes}
    groups["unattributed"] = 0.0
    scope_map = scope_map or {}
    try:
        for name, op in (summary.get("ops") or {}).items():
            scope = op.get("scope")
            if scope not in scopes:
                mapped = scope_map.get(name)
                if mapped is not None and mapped not in scopes:
                    mapped = _scope_from_path(mapped, scopes)
                scope = mapped
            if scope in scopes:
                groups[scope] += float(op.get("total_us", 0.0))
            else:
                groups["unattributed"] += float(op.get("total_us", 0.0))
    except Exception:
        pass
    return groups
