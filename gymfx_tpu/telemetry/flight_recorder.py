"""Flight recorder: the last K drained superstep frames, held host-side
and dumped as one postmortem bundle when a run dies.

The recorder rides the existing one-dispatch-late drain: each frame is
the host metric stack :class:`~gymfx_tpu.telemetry.device_stream.DeviceMetricStream`
already fetched (ONE ``jax.device_get`` per superstep — the recorder
adds zero host syncs).  On divergence, watchdog trip, or preemption,
:meth:`dump` writes a bundle directory:

  * ``frames.jsonl`` — the retained frames, oldest first
  * ``manifest.json`` — reason, wall time, config sha256, the rng key
    at dump time, a resilience-counter snapshot, and every compile
    event the run observed

pinned by the committed ``postmortem_schema.json`` next to this module
(:func:`validate_postmortem` is the shared validator).  Everything on
the record path follows the sink discipline: never raises, failures
are counted (``dropped_frames``, ``dump_errors``), a broken disk costs
you forensics, not the run.
"""
from __future__ import annotations

import json
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional

import numpy as np

SCHEMA_PATH = Path(__file__).resolve().parent / "postmortem_schema.json"

POSTMORTEM_SCHEMA_VERSION = 1

# compile events are small dicts; keep enough for any real session but
# bound the host memory a pathological recompile storm could take
MAX_COMPILE_EVENTS = 4096


def _jsonable(obj: Any) -> Any:
    """Coerce numpy/jax leaves to plain JSON types (lossy repr as the
    last resort — a postmortem that drops a weird leaf beats no
    postmortem)."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    try:
        arr = np.asarray(obj)
        # an object-dtype array round-trips the unserializable leaf
        # right back out of tolist(); repr it instead
        if arr.dtype != object:
            return arr.tolist()
    except Exception:
        pass
    return repr(obj)


class FlightRecorder:
    """Ring buffer of superstep frames + run provenance, dumpable as a
    schema-pinned postmortem bundle."""

    def __init__(
        self,
        out_dir: str,
        *,
        k: int = 8,
        config: Optional[Dict[str, Any]] = None,
        config_sha256: Optional[str] = None,
        ledger: Any = None,
    ):
        from gymfx_tpu.telemetry.ledger import config_digest

        self.out_dir = Path(out_dir)
        self.k = max(1, int(k))
        self.config_sha256 = (
            config_sha256 if config_sha256 is not None else config_digest(config)
        )
        self.ledger = ledger
        self._frames: deque = deque(maxlen=self.k)
        self._compile_events: List[Dict[str, Any]] = []
        self._rng_source: Optional[Callable[[], Any]] = None
        self._resilience_source: Optional[Callable[[], Dict[str, Any]]] = None
        self._lock = threading.Lock()
        self._frame_seq = 0
        self._dump_seq = 0
        self.dropped_frames = 0
        self.dump_errors = 0
        self.dumps = 0

    # -- sources resolved lazily at dump time --------------------------
    def set_rng_source(self, fn: Callable[[], Any]) -> None:
        """A zero-arg closure returning the CURRENT rng key — called at
        dump time so the bundle carries the key the run died with, not
        the key it started with."""
        self._rng_source = fn

    def set_resilience_source(self, fn: Callable[[], Dict[str, Any]]) -> None:
        """A zero-arg closure returning the resilience-counter snapshot
        (e.g. ``lambda: resilience_snapshot(registry)``)."""
        self._resilience_source = fn

    # -- record paths (hot; never raise) -------------------------------
    def record_frame(self, it_end: int, k: int, metrics: Any) -> None:
        """Retain one drained superstep frame.  ``metrics`` is the
        already-fetched host tree — the recorder only coerces and
        stores, it never touches the device."""
        try:
            frame = {
                "frame_seq": None,  # stamped under the lock below
                "it_end": int(it_end),
                "k": int(k),
                "metrics": _jsonable(metrics),
            }
            with self._lock:
                self._frame_seq += 1
                frame["frame_seq"] = self._frame_seq
                self._frames.append(frame)
        except Exception:
            with self._lock:
                self.dropped_frames += 1

    def record_compile(self, event: Dict[str, Any]) -> None:
        try:
            row = _jsonable(event)
            with self._lock:
                if len(self._compile_events) < MAX_COMPILE_EVENTS:
                    self._compile_events.append(row)
        except Exception:
            pass

    @property
    def frame_count(self) -> int:
        with self._lock:
            return len(self._frames)

    # -- the dump -------------------------------------------------------
    def dump(self, reason: str, extra: Optional[Dict[str, Any]] = None
             ) -> Optional[str]:
        """Write the bundle; returns its directory path, or None when
        the write failed (counted in ``dump_errors``).  Safe to call
        more than once — each dump gets its own directory."""
        try:
            with self._lock:
                self._dump_seq += 1
                dump_seq = self._dump_seq
                frames = list(self._frames)
                compile_events = list(self._compile_events)
            bundle = self.out_dir / f"postmortem_{dump_seq:03d}_{reason}"
            bundle.mkdir(parents=True, exist_ok=True)

            frames_file = "frames.jsonl"
            with open(bundle / frames_file, "w", encoding="utf-8") as fh:
                for frame in frames:
                    fh.write(json.dumps(frame) + "\n")

            rng_key = None
            if self._rng_source is not None:
                try:
                    rng_key = _jsonable(np.asarray(self._rng_source()))
                except Exception:
                    rng_key = None
            resilience: Dict[str, Any] = {}
            if self._resilience_source is not None:
                try:
                    resilience = _jsonable(self._resilience_source()) or {}
                except Exception:
                    resilience = {}

            manifest = {
                "schema_version": POSTMORTEM_SCHEMA_VERSION,
                "reason": str(reason),
                "ts": time.time(),
                "config_sha256": self.config_sha256,
                "frames": len(frames),
                "frames_file": frames_file,
                "rng_key": rng_key,
                "resilience": resilience,
                "compile_events": compile_events,
            }
            if extra:
                for key, value in extra.items():
                    manifest.setdefault(str(key), _jsonable(value))
            with open(bundle / "manifest.json", "w", encoding="utf-8") as fh:
                json.dump(manifest, fh, indent=2, sort_keys=True)
                fh.write("\n")

            with self._lock:
                self.dumps += 1
            if self.ledger is not None:
                self.ledger.record("postmortem_dump", reason=str(reason),
                                   path=str(bundle))
            return str(bundle)
        except Exception:
            with self._lock:
                self.dump_errors += 1
            return None


# ---------------------------------------------------------------------------
# validation: committed schema, shared by tier-1 tests and tooling
def load_postmortem_schema() -> Dict[str, Any]:
    with open(SCHEMA_PATH, encoding="utf-8") as fh:
        schema = json.load(fh)
    schema.pop("_comment", None)
    return schema


def validate_postmortem(bundle_dir: str,
                        schema: Optional[Dict[str, Any]] = None) -> List[str]:
    """Return a list of violations (empty = the bundle conforms):
    manifest keys, known reason, frame count matching frames.jsonl,
    per-frame required keys, and monotonic frame_seq."""
    if schema is None:
        schema = load_postmortem_schema()
    problems: List[str] = []
    bundle = Path(bundle_dir)
    manifest_path = bundle / "manifest.json"
    if not manifest_path.exists():
        return [f"{bundle}: missing manifest.json"]
    try:
        manifest = json.loads(manifest_path.read_text(encoding="utf-8"))
    except Exception as exc:
        return [f"{manifest_path}: unparseable manifest ({exc})"]
    for key in schema.get("manifest_required", ()):
        if key not in manifest:
            problems.append(f"manifest: missing required key {key!r}")
    reasons = schema.get("reasons", ())
    if reasons and manifest.get("reason") not in reasons:
        problems.append(
            f"manifest: unknown reason {manifest.get('reason')!r}; "
            f"schema knows {list(reasons)}"
        )
    frames_file = bundle / str(manifest.get("frames_file", "frames.jsonl"))
    if not frames_file.exists():
        problems.append(f"{frames_file}: missing frames file")
        return problems
    frames = []
    for i, line in enumerate(
            frames_file.read_text(encoding="utf-8").splitlines()):
        if not line.strip():
            continue
        try:
            frames.append(json.loads(line))
        except Exception as exc:
            problems.append(f"frames.jsonl row {i}: unparseable ({exc})")
    declared = manifest.get("frames")
    if isinstance(declared, int) and declared != len(frames):
        problems.append(
            f"manifest declares {declared} frames, frames.jsonl has "
            f"{len(frames)}"
        )
    prev_seq = 0
    for i, frame in enumerate(frames):
        for key in schema.get("frame_required", ()):
            if key not in frame:
                problems.append(f"frame {i}: missing required key {key!r}")
        seq = frame.get("frame_seq")
        if isinstance(seq, int):
            if seq <= prev_seq:
                problems.append(
                    f"frame {i}: frame_seq {seq} not monotonic "
                    f"(previous {prev_seq})"
                )
            prev_seq = seq
        else:
            problems.append(f"frame {i}: frame_seq must be an int, got {seq!r}")
    return problems
