"""Population-based training (BASELINE.json config 5: "pod-scale
population-based training").

TPU-shaped PBT: the whole population trains as ONE program — member
train states are stacked on a leading population axis and the PPO train
step is ``vmap``-ed across it, so P members cost one batched step (and
shard over mesh devices at pod scale).  Per-member learning rates live
inside the optimizer state via ``optax.inject_hyperparams``, which is
what makes them traced (vmappable) instead of compile-time constants.

Exploit/explore (Jaderberg et al. 2017), every ``interval`` steps:
members in the bottom quantile copy the params + optimizer state of a
random top-quantile member and perturb EACH explored hyperparameter —
learning rate, PPO clip epsilon and entropy coefficient — independently
by x0.8 or x1.25 (clipped to per-key bounds).  clip_eps/ent_coef ride
in ``opt_state.hyperparams`` next to the learning rate (stored there by
``inject_hyperparams``, read back by the loss via ``_loss_hyper``), so
all three are traced per-member values under the population ``vmap``.
Fitness = running mean reward of the member's own rollouts.
"""
from __future__ import annotations

import time
from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from gymfx_tpu.core.runtime import Environment
from gymfx_tpu.train.ppo import PPOConfig, PPOTrainer, ppo_config_from


class PBTConfig(NamedTuple):
    population: int = 8
    interval: int = 5            # train steps between exploit/explore
    quantile: float = 0.25
    lr_min: float = 1e-5
    lr_max: float = 1e-2
    clip_eps_min: float = 0.05
    clip_eps_max: float = 0.5
    ent_coef_min: float = 1e-4
    ent_coef_max: float = 0.1
    perturb: float = 1.25
    fitness_decay: float = 0.7   # EMA over per-step mean reward

    def explore_bounds(self) -> Dict[str, Any]:
        """Per-hyperparameter (min, max) clip bounds for explore."""
        return {
            "learning_rate": (self.lr_min, self.lr_max),
            "clip_eps": (self.clip_eps_min, self.clip_eps_max),
            "ent_coef": (self.ent_coef_min, self.ent_coef_max),
        }


class _InjectedHyperMixin:
    """Makes lr + clip_eps + ent_coef per-member traced values: all
    three live in ``opt_state.hyperparams`` (inject_hyperparams stores
    every argument of the wrapped factory, used or not), and the loss
    reads clip/ent back through ``_loss_hyper`` during the train-step
    trace — so a population ``vmap`` gives each member its own values
    with no recompilation."""

    def _make_optimizer(self):
        def make(learning_rate, clip_eps, ent_coef):
            del clip_eps, ent_coef  # carried for the loss, not the optimizer
            return optax.chain(
                optax.clip_by_global_norm(self.pcfg.max_grad_norm),
                optax.adam(learning_rate),
            )

        return optax.inject_hyperparams(make)(
            learning_rate=self.pcfg.lr,
            clip_eps=self.pcfg.clip_eps,
            ent_coef=self.pcfg.ent_coef,
        )

    def _train_step_impl(self, state, data=None):
        h = state.opt_state.hyperparams
        self._hyper = (h["clip_eps"], h["ent_coef"])
        try:
            return super()._train_step_impl(state, data)
        finally:
            self._hyper = None

    def _loss_hyper(self):
        if getattr(self, "_hyper", None) is not None:
            return self._hyper
        return super()._loss_hyper()


class _PBTTrainerCore(_InjectedHyperMixin, PPOTrainer):
    """PPOTrainer with lr/clip_eps/ent_coef injected into opt_state."""


class PBTTrainer:
    """PBT over any core trainer exposing ``init_state_from_key``,
    ``_train_step_impl`` and an inject_hyperparams optimizer (the
    single-pair PPO core by default; see make_portfolio_pbt)."""

    def __init__(
        self,
        env: Environment,
        pcfg: PPOConfig = None,
        pbt: PBTConfig = PBTConfig(),
        core=None,
        mesh=None,
    ):
        self.trainer = core if core is not None else _PBTTrainerCore(env, pcfg)
        self.pbt = pbt
        # Pod-scale placement: the POPULATION axis shards over the mesh
        # 'data' axis (members are embarrassingly parallel between
        # exploit/explore syncs), so P members train on P/devices chips
        # each — distinct from the single-trainer mesh, which shards the
        # env batch of ONE member.  Placement and the divisibility check
        # are owned by the shared ShardedRuntime plan.
        self.mesh = mesh
        self.runtime = None
        if mesh is not None:
            from gymfx_tpu.parallel import ShardedRuntime

            self.runtime = ShardedRuntime(mesh)
            self.runtime.validate_population(pbt.population)
        self._vstep = jax.jit(jax.vmap(self.trainer._train_step_impl), donate_argnums=0)
        # curriculum feed: one tape per population step, shared (in_axes
        # None) across members so every member trains the same market
        # while hyperparameters differ — the tape is never donated
        self.curriculum = getattr(self.trainer, "curriculum", None)
        self._vstep_data = jax.jit(
            jax.vmap(self.trainer._train_step_impl, in_axes=(0, None)),
            donate_argnums=0,
        )
        self._vinit = jax.jit(jax.vmap(self.trainer.init_state_from_key))

    # ------------------------------------------------------------------
    def init_population(self, seed: int = 0):
        keys = jax.random.split(jax.random.PRNGKey(seed), self.pbt.population)
        states = self._vinit(keys)
        rng = np.random.default_rng(seed)
        lrs = np.exp(
            rng.uniform(
                np.log(self.pbt.lr_min), np.log(self.pbt.lr_max),
                self.pbt.population,
            )
        )
        states = self._set_lrs(states, jnp.asarray(lrs, jnp.float32))
        states = self._place(states)
        fitness = np.zeros(self.pbt.population)
        return states, fitness

    def _place(self, states):
        """Shard the population axis over the mesh (no-op without one)."""
        if self.runtime is None:
            return states
        return self.runtime.place_population(states)

    def _set_hyper(self, states, key: str, values):
        opt_state = states.opt_state
        hyper = dict(opt_state.hyperparams)
        hyper[key] = jnp.asarray(values).astype(hyper[key].dtype)
        return states._replace(opt_state=opt_state._replace(hyperparams=hyper))

    def _set_lrs(self, states, lrs):
        return self._set_hyper(states, "learning_rate", lrs)

    def get_hyper(self, states, key: str) -> np.ndarray:
        return np.asarray(states.opt_state.hyperparams[key])

    def get_lrs(self, states) -> np.ndarray:
        return self.get_hyper(states, "learning_rate")

    # ------------------------------------------------------------------
    def _exploit_explore(self, states, fitness, rng):
        P = self.pbt.population
        k = max(1, int(P * self.pbt.quantile))
        order = np.argsort(fitness)          # ascending
        bottom, top = order[:k], order[-k:]
        src_for = {int(b): int(top[rng.integers(0, len(top))]) for b in bottom}

        idx = np.arange(P)
        for b, s in src_for.items():
            idx[b] = s
        idx_dev = jnp.asarray(idx)
        # bottom members copy params + optimizer state (incl. lr) of donors
        copied = jax.tree.map(lambda x: x[idx_dev], (states.params, states.opt_state))
        states = states._replace(params=copied[0], opt_state=copied[1])

        # explore: perturb EVERY explored hyperparameter of each replaced
        # member independently (x perturb or /perturb, clipped per-key)
        for key, (lo, hi) in self.pbt.explore_bounds().items():
            vals = self.get_hyper(states, key).copy()
            for b in src_for:
                factor = (
                    self.pbt.perturb if rng.random() < 0.5
                    else 1.0 / self.pbt.perturb
                )
                vals[b] = float(np.clip(vals[b] * factor, lo, hi))
            states = self._set_hyper(states, key, vals)
        # the donor gather returns replicated arrays; re-shard the
        # population axis or the rest of training runs unsharded
        states = self._place(states)
        fitness[list(src_for)] = fitness[[src_for[b] for b in src_for]]
        return states, fitness, sorted(src_for)

    # ------------------------------------------------------------------
    def train(self, total_env_steps: int, seed: int = 0) -> Dict[str, Any]:
        pcfg = self.trainer.pcfg
        per_iter = pcfg.n_envs * pcfg.horizon * self.pbt.population
        iters = max(1, int(total_env_steps) // per_iter)
        states, fitness = self.init_population(seed)
        rng = np.random.default_rng(seed + 1)
        decay = self.pbt.fitness_decay
        replacements = []
        t0 = time.perf_counter()
        metrics = {}
        for it in range(iters):
            if self.curriculum is not None:
                _ti, _label, tape = self.curriculum.pick(it)
                states, metrics = self._vstep_data(states, tape)
            else:
                states, metrics = self._vstep(states)
            step_fit = np.asarray(metrics["mean_reward"], np.float64)
            fitness = decay * fitness + (1 - decay) * step_fit
            if (it + 1) % self.pbt.interval == 0 and it + 1 < iters:
                states, fitness, replaced = self._exploit_explore(
                    states, fitness, rng
                )
                replacements.append({"iter": it + 1, "replaced": replaced})
        jax.block_until_ready(states.params)
        dt = time.perf_counter() - t0

        best = int(np.argmax(fitness))
        best_params = jax.tree.map(lambda x: x[best], states.params)
        return {
            "population": self.pbt.population,
            "iterations": iters,
            "total_env_steps": per_iter * iters,
            "env_steps_per_sec": per_iter * iters / dt,
            "fitness": fitness.tolist(),
            "learning_rates": self.get_lrs(states).tolist(),
            "clip_eps": self.get_hyper(states, "clip_eps").tolist(),
            "ent_coef": self.get_hyper(states, "ent_coef").tolist(),
            "best_member": best,
            "best_params": best_params,
            "replacements": replacements,
            "final_metrics": {
                k: np.asarray(v).tolist() for k, v in metrics.items()
            },
        }


class _PBTPortfolioCore:
    """Portfolio PPO core with the learning rate injected into opt_state
    (BASELINE config 5: multi-pair + transformer under PBT)."""

    def __new__(cls, env, pcfg):
        from gymfx_tpu.train.portfolio_ppo import PortfolioPPOTrainer

        class Core(_InjectedHyperMixin, PortfolioPPOTrainer):
            pass

        return Core(env, pcfg)


def make_portfolio_pbt(config: Dict[str, Any], pbt: PBTConfig,
                       mesh=None, env=None) -> "PBTTrainer":
    from gymfx_tpu.core.portfolio import PortfolioEnvironment
    from gymfx_tpu.train.portfolio_ppo import PortfolioPPOConfig

    if env is None:
        env = PortfolioEnvironment(config)
    from gymfx_tpu.train.common import resolve_minibatch_scheme
    resolve_minibatch_scheme(config, int(config.get("num_envs", 64) or 64),
                             int(config.get("ppo_minibatches", 4)))
    pcfg = PortfolioPPOConfig(
        n_envs=int(config.get("num_envs", 64) or 64),
        horizon=int(config.get("ppo_horizon", 64)),
        epochs=int(config.get("ppo_epochs", 2)),
        minibatches=int(config.get("ppo_minibatches", 4)),
        lr=float(config.get("learning_rate", 3e-4)),
        policy=str(config.get("policy") or "mlp"),
        minibatch_scheme=str(
            config.get("ppo_minibatch_scheme", "env_permute")
        ),
    )
    return PBTTrainer(env, None, pbt, core=_PBTPortfolioCore(env, pcfg),
                      mesh=mesh)


def _pbt_config_from(config: Dict[str, Any]) -> PBTConfig:
    return PBTConfig(
        population=int(config.get("pbt_population", 8)),
        interval=int(config.get("pbt_interval", 5)),
        quantile=float(config.get("pbt_quantile", 0.25)),
        lr_min=float(config.get("pbt_lr_min", 1e-5)),
        lr_max=float(config.get("pbt_lr_max", 1e-2)),
        clip_eps_min=float(config.get("pbt_clip_eps_min", 0.05)),
        clip_eps_max=float(config.get("pbt_clip_eps_max", 0.5)),
        ent_coef_min=float(config.get("pbt_ent_coef_min", 1e-4)),
        ent_coef_max=float(config.get("pbt_ent_coef_max", 0.1)),
        perturb=float(config.get("pbt_perturb", 1.25)),
        fitness_decay=float(config.get("pbt_fitness_decay", 0.7)),
    )


def train_pbt_from_config(config: Dict[str, Any]) -> Dict[str, Any]:
    """CLI entry; with ``elastic_resume`` set the run routes through the
    elastic auto-resume controller (parallel/elastic.py).  PBT runs no
    mid-run checkpoints (the population evolves in one sweep), so a
    device loss here warm-restarts the sweep on the survivor mesh —
    ``validate_population_axis`` re-runs honor-or-reject at entry, and
    plan_survivor_shape already rejected shapes the population cannot
    divide."""
    from gymfx_tpu.parallel.elastic import elastic_entry

    return elastic_entry(
        _train_pbt_from_config, config,
        must_divide=(int(config.get("pbt_population", 8) or 8),),
    )


def _train_pbt_from_config(config: Dict[str, Any]) -> Dict[str, Any]:
    from gymfx_tpu.parallel import mesh_from_config, validate_population_axis

    mesh = mesh_from_config(config)
    # honor-or-reject at the config entry point: a population the mesh
    # cannot split evenly fails HERE, before env construction / XLA
    validate_population_axis(mesh, int(config.get("pbt_population", 8)))
    if config.get("portfolio_files"):
        from gymfx_tpu.train.common import (
            build_portfolio_train_eval_envs,
            labeled_eval_summary,
        )
        from gymfx_tpu.train.portfolio_ppo import (
            PortfolioPPOTrainer,
            evaluate as portfolio_evaluate,
        )

        env, eval_env = build_portfolio_train_eval_envs(config)
        pbt = _pbt_config_from(config)
        trainer = make_portfolio_pbt(config, pbt, mesh=mesh, env=env)
        result = trainer.train(
            int(config.get("train_total_steps", 1_000_000)),
            seed=int(config.get("seed", 0) or 0),
        )
        best_params = result.pop("best_params", None)
        # held-out evaluation of the best member (VERDICT r4 item #3)
        pcfg = trainer.trainer.pcfg
        out = labeled_eval_summary(
            lambda e: portfolio_evaluate(
                trainer.trainer if e is None else PortfolioPPOTrainer(e, pcfg),
                best_params,
            ),
            env, eval_env,
        )
        out.update({"mode": "training", "trainer": "pbt_portfolio",
                    "pbt": result})
        if mesh is not None:
            out["mesh_shape"] = dict(mesh.shape)
        return out

    from gymfx_tpu.train.common import build_train_eval_envs

    env, eval_env = build_train_eval_envs(config)
    from gymfx_tpu.train.common import resolve_minibatch_scheme

    resolve_minibatch_scheme(
        config, int(config.get("num_envs", 256) or 256),
        int(config.get("ppo_minibatches", 4)),
    )
    pcfg = ppo_config_from(config)
    pbt = _pbt_config_from(config)
    trainer = PBTTrainer(env, pcfg, pbt, mesh=mesh)
    result = trainer.train(
        int(config.get("train_total_steps", 1_000_000)),
        seed=int(config.get("seed", 0) or 0),
    )
    best_params = result.pop("best_params")

    from gymfx_tpu.train import ppo as ppo_mod

    from gymfx_tpu.train.common import labeled_eval_summary

    summary = labeled_eval_summary(
        lambda e: ppo_mod.evaluate(
            trainer.trainer if e is None else PPOTrainer(e, pcfg), best_params
        ),
        env, eval_env,
    )
    summary["pbt"] = result
    if mesh is not None:
        summary["mesh_shape"] = dict(mesh.shape)

    ckpt_dir = config.get("checkpoint_dir")
    if ckpt_dir:
        from gymfx_tpu.train.checkpoint import save_checkpoint

        save_checkpoint(
            ckpt_dir, best_params, step=result["total_env_steps"],
            metadata={"policy": pcfg.policy,
                      "policy_kwargs": dict(pcfg.policy_kwargs),
                      "state_format": "params"},
            keep=int(config.get("checkpoint_keep", 0) or 0),
        )
        summary["checkpoint_dir"] = str(ckpt_dir)
    return summary
