"""Vectorized hyperparameter optimization (mode=optimization).

The reference exposes a GA-tunable schema on its ATR bracket strategy
((name, lo, hi, type) tuples, reference
strategy_plugins/direct_atr_sltp.py:345-350) for an EXTERNAL optimizer
to consume, one slow episode per candidate.  Here the optimizer is
in-framework and TPU-shaped: because strategy hyperparameters live in
``EnvParams`` (traced, not static), a whole POPULATION of candidates
evaluates as one ``vmap`` over the episode scan — population-based
search at the cost of one batched rollout per generation.

Algorithm: elitist evolution — evaluate population fitness (risk-
adjusted performance: total_return - lambda * drawdown_fraction, the
reference's `rap`), keep the top half, refill with Gaussian mutations
of elites clipped to the schema bounds.

``atr_period`` from the reference schema sizes a ring buffer (static
shape) and therefore cannot vary inside one compiled program; it is
covered by an OUTER sweep instead: ``optimize_from_config`` re-jits the
batched GA once per period over a small grid (``optimize_atr_periods``,
defaulting to points spanning the reference's 7..30 range) and selects
the best (k_sl, k_tp, atr_period) triple by fitness — the full schema
of reference strategy_plugins/direct_atr_sltp.py:345-350.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gymfx_tpu.core import env as env_core
from gymfx_tpu.core.runtime import Environment

DEFAULT_SCHEMA: Tuple[Tuple[str, float, float], ...] = (
    ("k_sl", 1.0, 4.0),
    ("k_tp", 1.5, 6.0),
)


def hparam_schema(config: Dict[str, Any]) -> List[Tuple[str, float, float]]:
    raw = config.get("optimize_params")
    if isinstance(raw, str):  # CLI unknown-arg path delivers a JSON string
        import json

        raw = json.loads(raw)
    if raw:
        return [(str(k), float(lo), float(hi)) for k, (lo, hi) in raw.items()]
    return list(DEFAULT_SCHEMA)


class Optimizer:
    def __init__(
        self,
        env: Environment,
        schema: Sequence[Tuple[str, float, float]],
        *,
        population: int = 32,
        risk_lambda: float = 1.0,
        mutation_scale: float = 0.15,
        episode_steps: Optional[int] = None,
    ):
        self.env = env
        self.schema = list(schema)
        self.population = int(population)
        if self.population < 2:
            raise ValueError("optimize_population must be >= 2")
        self.risk_lambda = float(risk_lambda)
        self.mutation_scale = float(mutation_scale)
        self.episode_steps = int(episode_steps or env.cfg.n_bars - 1)
        for name, _, _ in self.schema:
            if not hasattr(env.params, name):
                raise ValueError(f"unknown hyperparameter {name!r} (not in EnvParams)")
        self._fitness = jax.jit(self._fitness_impl)

    # ------------------------------------------------------------------
    def _with_candidate(self, vals):
        updates = {
            name: vals[i].astype(self.env.cfg.dtype)
            for i, (name, _, _) in enumerate(self.schema)
        }
        return self.env.params._replace(**updates)

    def _episode_fitness(self, vals, rng):
        cfg, data = self.env.cfg, self.env.data
        params = self._with_candidate(vals)
        state, _obs = env_core.reset(cfg, params, data)

        def body(carry, _):
            state, rng = carry
            rng, k = jax.random.split(rng)
            action = jax.random.randint(k, (), 0, 3, dtype=jnp.int32)
            state, _obs, _r, _done, _info = env_core.step(cfg, params, data, state, action)
            return (state, rng), ()

        (state, _), _ = jax.lax.scan(
            body, (state, rng), None, length=self.episode_steps
        )
        initial = params.initial_cash
        total_return = state.equity_delta / initial
        dd_fraction = state.max_drawdown_pct / 100.0
        rap = total_return - self.risk_lambda * dd_fraction
        return rap, total_return, dd_fraction

    def _fitness_impl(self, population_vals, rng):
        # identical entry stream across candidates: fitness differences
        # come from the hyperparameters, not from action-sampling luck
        return jax.vmap(self._episode_fitness, in_axes=(0, None))(
            population_vals, rng
        )

    # ------------------------------------------------------------------
    def run(self, generations: int = 8, seed: int = 0) -> Dict[str, Any]:
        rng = np.random.default_rng(seed)
        lo = np.array([s[1] for s in self.schema])
        hi = np.array([s[2] for s in self.schema])
        pop = rng.uniform(lo, hi, size=(self.population, len(self.schema)))
        episode_key = jax.random.PRNGKey(seed)

        history = []
        t0 = time.perf_counter()
        best_vals, best_fit = None, -np.inf
        for gen in range(generations):
            rap, total_return, dd = self._fitness(
                jnp.asarray(pop, dtype=jnp.float32), episode_key
            )
            rap = np.asarray(rap, np.float64)
            order = np.argsort(-rap)
            if rap[order[0]] > best_fit:
                best_fit = float(rap[order[0]])
                best_vals = pop[order[0]].copy()
            history.append(
                {
                    "generation": gen,
                    "best_rap": float(rap[order[0]]),
                    "mean_rap": float(rap.mean()),
                    "best_candidate": {
                        name: float(pop[order[0]][i])
                        for i, (name, _, _) in enumerate(self.schema)
                    },
                }
            )
            # elitist refill that preserves the population size exactly
            # (odd sizes would otherwise shrink and force a recompile)
            elites = pop[order[: max(1, self.population // 2)]]
            n_fill = self.population - len(elites)
            parents = elites[rng.integers(0, len(elites), size=n_fill)]
            mutations = parents + rng.normal(
                0.0, self.mutation_scale * (hi - lo), size=parents.shape
            )
            pop = np.clip(np.concatenate([elites, mutations], axis=0), lo, hi)

        return {
            "mode": "optimization",
            "schema": [
                {"name": n, "low": float(l), "high": float(h)}
                for n, l, h in self.schema
            ],
            "population": self.population,
            "generations": generations,
            "risk_penalty_lambda": self.risk_lambda,
            "best_params": {
                name: float(best_vals[i])
                for i, (name, _, _) in enumerate(self.schema)
            },
            "best_rap": best_fit,
            "history": history,
            "wall_seconds": time.perf_counter() - t0,
        }


def atr_period_bounds(config: Dict[str, Any]) -> Tuple[int, int]:
    """The sweepable ``atr_period`` range: a user ``optimize_params``
    override wins; otherwise the builtin strategy schema's 7..30
    (reference strategy_plugins/direct_atr_sltp.py:346)."""
    override = next(
        ((l, h) for n, l, h in hparam_schema(config) if n == "atr_period"),
        None,
    )
    if override is None:
        from gymfx_tpu.plugins.builtin.strategies import (
            hparam_schema as _builtin_schema,
        )

        override = next(
            (l, h) for n, l, h, _t in _builtin_schema() if n == "atr_period"
        )
    lo, hi = int(override[0]), int(override[1])
    if lo < 1 or hi < lo:
        raise ValueError(
            f"atr_period bounds [{lo}, {hi}] must be positive ints with "
            "low <= high (ring-buffer length)"
        )
    return lo, hi


def atr_period_grid(config: Dict[str, Any]) -> List[int]:
    """The outer-sweep grid for ``atr_period``.  Explicit
    ``optimize_atr_periods`` wins (validated against the schema bounds);
    otherwise the ATR strategy gets a default 4-point grid spanning
    :func:`atr_period_bounds` UNLESS the user pinned ``atr_period`` in
    the config; non-ATR strategies never sweep."""
    raw = config.get("optimize_atr_periods")
    if isinstance(raw, str):  # CLI unknown-arg path delivers a JSON string
        import json

        try:
            raw = json.loads(raw)
        except json.JSONDecodeError as e:
            raise ValueError(
                "optimize_atr_periods must be a JSON list (e.g. "
                f"'[7, 14, 21]') or a single integer, got {raw!r}"
            ) from e
    if isinstance(raw, (int, float)):  # scalar: a one-point grid
        raw = [raw]
    if raw:
        lo, hi = atr_period_bounds(config)
        grid = sorted({int(p) for p in raw})
        bad = [p for p in grid if not lo <= p <= hi]
        if bad:
            raise ValueError(
                f"optimize_atr_periods entries {bad} outside the strategy "
                f"schema's [{lo}, {hi}] range (plugins/builtin/"
                "strategies.py:hparam_schema, or the optimize_params "
                "override) — the summary reports grid points as schema "
                "low/high, so out-of-range periods would misdescribe the "
                "search space"
            )
        return grid
    if (
        str(config.get("strategy_plugin", "")) == "direct_atr_sltp"
        and config.get("atr_period") is None
    ):
        lo, hi = atr_period_bounds(config)
        if (lo, hi) == (7, 30):
            return [7, 14, 21, 30]  # the documented reference-range grid
        span = hi - lo
        return sorted({lo + span * i // 3 for i in range(4)})
    return []


def optimize_from_config(config: Dict[str, Any]) -> Dict[str, Any]:
    from gymfx_tpu.train.common import reject_eval_keys

    # honor-or-reject: GA fitness is DEFINED on the training bars (the
    # reference's external optimizer likewise scores candidates on the
    # episode it runs); accepting the out-of-sample keys silently would
    # sell contaminated numbers as held-out, so they are rejected loudly
    # and the summary labels its scope explicitly
    reject_eval_keys(config, "optimization")

    def run_at(period: Optional[int]) -> Dict[str, Any]:
        cfg = dict(config)
        if period is not None:
            cfg["atr_period"] = int(period)
        env = Environment(cfg)
        # atr_period is swept OUTSIDE the GA (static ring-buffer shape);
        # an optimize_params override listing it feeds atr_period_grid's
        # bounds, never the inner continuous schema
        inner_schema = [s for s in hparam_schema(cfg) if s[0] != "atr_period"]
        population = int(cfg.get("optimize_population", 32))
        generations = int(cfg.get("optimize_generations", 8))
        if not inner_schema:
            # nothing continuous to tune: every candidate is identical,
            # so one minimal evaluation per grid point scores the period
            # without burning population x generations of rollouts
            population, generations = 2, 1
        optimizer = Optimizer(
            env,
            inner_schema,
            population=population,
            risk_lambda=float(
                cfg.get("risk_lambda", cfg.get("risk_penalty_lambda", 1.0))
            ),
            mutation_scale=float(cfg.get("optimize_mutation_scale", 0.15)),
            episode_steps=cfg.get("steps"),
        )
        return optimizer.run(
            generations=generations,
            seed=int(cfg.get("seed", 0) or 0),
        )

    def label(result: Dict[str, Any]) -> Dict[str, Any]:
        result["eval_scope"] = "in_sample_by_design"
        result["eval_note"] = (
            "GA fitness is defined on the training bars; eval_split/"
            "eval_data_file are rejected (re-evaluate the best candidate "
            "with driver_mode=policy or the training trainers for a "
            "held-out number)"
        )
        return result

    grid = atr_period_grid(config)
    if not grid and any(n == "atr_period" for n, _, _ in hparam_schema(config)):
        # atr_period never reaches the inner GA (static shape), so an
        # optimize_params declaring it with nothing sweeping it would
        # silently optimize nothing — fail the way the old inner-schema
        # rejection did
        raise ValueError(
            "optimize_params declares atr_period but nothing sweeps it: "
            "unpin atr_period from the config or pass "
            "optimize_atr_periods (non-ATR strategies cannot sweep it)"
        )
    if not grid:
        return label(run_at(None))

    # outer sweep: one re-jitted batched GA per ring-buffer size, best
    # triple selected by fitness (same identical-entry-stream seed per
    # period, so periods compete on the hyperparameter, not on luck)
    sweep, best_period, best = [], None, None
    for period in grid:
        res = run_at(period)
        sweep.append(
            {
                "atr_period": period,
                "best_rap": res["best_rap"],
                "best_params": dict(res["best_params"]),
            }
        )
        if best is None or res["best_rap"] > best["best_rap"]:
            best_period, best = period, res

    best["best_params"] = {**best["best_params"], "atr_period": best_period}
    best["schema"].append(
        {
            "name": "atr_period",
            "low": float(grid[0]),
            "high": float(grid[-1]),
            "grid": grid,
        }
    )
    best["atr_period_sweep"] = sweep
    return label(best)
