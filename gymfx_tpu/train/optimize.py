"""Vectorized hyperparameter optimization (mode=optimization).

The reference exposes a GA-tunable schema on its ATR bracket strategy
((name, lo, hi, type) tuples, reference
strategy_plugins/direct_atr_sltp.py:345-350) for an EXTERNAL optimizer
to consume, one slow episode per candidate.  Here the optimizer is
in-framework and TPU-shaped: because strategy hyperparameters live in
``EnvParams`` (traced, not static), a whole POPULATION of candidates
evaluates as one ``vmap`` over the episode scan — population-based
search at the cost of one batched rollout per generation.

Algorithm: elitist evolution — evaluate population fitness (risk-
adjusted performance: total_return - lambda * drawdown_fraction, the
reference's `rap`), keep the top half, refill with Gaussian mutations
of elites clipped to the schema bounds.

``atr_period`` from the reference schema sizes a ring buffer (static
shape) and therefore cannot vary inside one compiled program; it is
covered by an OUTER sweep instead: ``optimize_from_config`` re-jits the
batched GA once per period over a small grid (``optimize_atr_periods``,
defaulting to points spanning the reference's 7..30 range) and selects
the best (k_sl, k_tp, atr_period) triple by fitness — the full schema
of reference strategy_plugins/direct_atr_sltp.py:345-350.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gymfx_tpu.core import env as env_core
from gymfx_tpu.core.runtime import Environment

DEFAULT_SCHEMA: Tuple[Tuple[str, float, float], ...] = (
    ("k_sl", 1.0, 4.0),
    ("k_tp", 1.5, 6.0),
)


def candidate_episode_metrics(
    env: Environment,
    schema: Sequence[Tuple[str, float, float]],
    risk_lambda: float,
    steps: int,
):
    """Jittable ``(vals, rng) -> (rap, total_return, dd_fraction,
    trades)``: one seeded random-entry episode with the candidate's
    hyperparameter values substituted into ``EnvParams``.  Shared by the
    GA's vmapped population fitness and the winner's automatic held-out
    re-evaluation (one definition, so both numbers measure the same
    thing on different bars)."""
    cfg, data = env.cfg, env.data

    def run(vals, rng):
        updates = {
            name: vals[i].astype(cfg.dtype)
            for i, (name, _, _) in enumerate(schema)
        }
        params = env.params._replace(**updates)
        state, _obs = env_core.reset(cfg, params, data)

        def body(carry, _):
            state, rng = carry
            rng, k = jax.random.split(rng)
            action = jax.random.randint(k, (), 0, 3, dtype=jnp.int32)
            state, _obs, _r, _done, _info = env_core.step(
                cfg, params, data, state, action
            )
            return (state, rng), ()

        (state, _), _ = jax.lax.scan(body, (state, rng), None, length=int(steps))
        initial = params.initial_cash
        total_return = state.equity_delta / initial
        dd_fraction = state.max_drawdown_pct / 100.0
        rap = total_return - risk_lambda * dd_fraction
        return rap, total_return, dd_fraction, state.trade_count

    return run


def hparam_schema(config: Dict[str, Any]) -> List[Tuple[str, float, float]]:
    raw = config.get("optimize_params")
    if isinstance(raw, str):  # CLI unknown-arg path delivers a JSON string
        import json

        raw = json.loads(raw)
    if raw:
        return [(str(k), float(lo), float(hi)) for k, (lo, hi) in raw.items()]
    return list(DEFAULT_SCHEMA)


class Optimizer:
    def __init__(
        self,
        env: Environment,
        schema: Sequence[Tuple[str, float, float]],
        *,
        population: int = 32,
        risk_lambda: float = 1.0,
        mutation_scale: float = 0.15,
        episode_steps: Optional[int] = None,
    ):
        self.env = env
        self.schema = list(schema)
        self.population = int(population)
        if self.population < 2:
            raise ValueError("optimize_population must be >= 2")
        self.risk_lambda = float(risk_lambda)
        self.mutation_scale = float(mutation_scale)
        self.episode_steps = int(episode_steps or env.cfg.n_bars - 1)
        for name, _, _ in self.schema:
            if not hasattr(env.params, name):
                raise ValueError(f"unknown hyperparameter {name!r} (not in EnvParams)")
        self._fitness = jax.jit(self._fitness_impl)

    # ------------------------------------------------------------------
    def _fitness_impl(self, population_vals, rng):
        # identical entry stream across candidates: fitness differences
        # come from the hyperparameters, not from action-sampling luck
        episode = candidate_episode_metrics(
            self.env, self.schema, self.risk_lambda, self.episode_steps
        )
        return jax.vmap(episode, in_axes=(0, None))(population_vals, rng)

    # ------------------------------------------------------------------
    def run(self, generations: int = 8, seed: int = 0) -> Dict[str, Any]:
        rng = np.random.default_rng(seed)
        lo = np.array([s[1] for s in self.schema])
        hi = np.array([s[2] for s in self.schema])
        pop = rng.uniform(lo, hi, size=(self.population, len(self.schema)))
        episode_key = jax.random.PRNGKey(seed)

        history = []
        t0 = time.perf_counter()
        best_vals, best_fit = None, -np.inf
        for gen in range(generations):
            rap, total_return, dd, _trades = self._fitness(
                jnp.asarray(pop, dtype=jnp.float32), episode_key
            )
            rap = np.asarray(rap, np.float64)
            order = np.argsort(-rap)
            if rap[order[0]] > best_fit:
                best_fit = float(rap[order[0]])
                best_vals = pop[order[0]].copy()
            history.append(
                {
                    "generation": gen,
                    "best_rap": float(rap[order[0]]),
                    "mean_rap": float(rap.mean()),
                    # population spread: zero means NOTHING discriminated
                    # the candidates this generation — an artifact whose
                    # history is all-zero std carries no selection signal
                    # (VERDICT r4 weak #2)
                    "rap_std": float(rap.std()),
                    "best_candidate": {
                        name: float(pop[order[0]][i])
                        for i, (name, _, _) in enumerate(self.schema)
                    },
                }
            )
            # elitist refill that preserves the population size exactly
            # (odd sizes would otherwise shrink and force a recompile)
            elites = pop[order[: max(1, self.population // 2)]]
            n_fill = self.population - len(elites)
            parents = elites[rng.integers(0, len(elites), size=n_fill)]
            mutations = parents + rng.normal(
                0.0, self.mutation_scale * (hi - lo), size=parents.shape
            )
            pop = np.clip(np.concatenate([elites, mutations], axis=0), lo, hi)

        # a winner pinned to a schema bound (e.g. the k_tp=1.5 floor)
        # says the optimum may lie OUTSIDE the searched box — the bound
        # is the binding constraint, not a free optimum, and the
        # evidence tooling must surface that instead of presenting the
        # clipped value as converged (tools/optimize_evidence.py)
        boundary: Dict[str, str] = {}
        for i, (name, l, h) in enumerate(self.schema):
            v = float(best_vals[i])
            tol = 1e-3 * max(h - l, 1e-12)
            if v <= l + tol:
                boundary[name] = "low"
            elif v >= h - tol:
                boundary[name] = "high"

        return {
            "mode": "optimization",
            "schema": [
                {"name": n, "low": float(l), "high": float(h)}
                for n, l, h in self.schema
            ],
            "population": self.population,
            "generations": generations,
            "risk_penalty_lambda": self.risk_lambda,
            "best_params": {
                name: float(best_vals[i])
                for i, (name, _, _) in enumerate(self.schema)
            },
            "best_rap": best_fit,
            "boundary_clipped": boundary,
            "history": history,
            "selection_signal": bool(any(h["rap_std"] > 0.0 for h in history)),
            "wall_seconds": time.perf_counter() - t0,
        }


def atr_period_bounds(config: Dict[str, Any]) -> Tuple[int, int]:
    """The sweepable ``atr_period`` range: a user ``optimize_params``
    override wins; otherwise the builtin strategy schema's 7..30
    (reference strategy_plugins/direct_atr_sltp.py:346)."""
    override = next(
        ((l, h) for n, l, h in hparam_schema(config) if n == "atr_period"),
        None,
    )
    if override is None:
        from gymfx_tpu.plugins.builtin.strategies import (
            hparam_schema as _builtin_schema,
        )

        override = next(
            (l, h) for n, l, h, _t in _builtin_schema() if n == "atr_period"
        )
    lo, hi = int(override[0]), int(override[1])
    if lo < 1 or hi < lo:
        raise ValueError(
            f"atr_period bounds [{lo}, {hi}] must be positive ints with "
            "low <= high (ring-buffer length)"
        )
    return lo, hi


def atr_period_grid(config: Dict[str, Any]) -> List[int]:
    """The outer-sweep grid for ``atr_period``.  Explicit
    ``optimize_atr_periods`` wins (validated against the schema bounds);
    otherwise the ATR strategy gets a default 4-point grid spanning
    :func:`atr_period_bounds` UNLESS the user pinned ``atr_period`` in
    the config; non-ATR strategies never sweep."""
    raw = config.get("optimize_atr_periods")
    if isinstance(raw, str):  # CLI unknown-arg path delivers a JSON string
        import json

        try:
            raw = json.loads(raw)
        except json.JSONDecodeError as e:
            raise ValueError(
                "optimize_atr_periods must be a JSON list (e.g. "
                f"'[7, 14, 21]') or a single integer, got {raw!r}"
            ) from e
    if isinstance(raw, (int, float)):  # scalar: a one-point grid
        raw = [raw]
    if raw:
        lo, hi = atr_period_bounds(config)
        grid = sorted({int(p) for p in raw})
        bad = [p for p in grid if not lo <= p <= hi]
        if bad:
            raise ValueError(
                f"optimize_atr_periods entries {bad} outside the strategy "
                f"schema's [{lo}, {hi}] range (plugins/builtin/"
                "strategies.py:hparam_schema, or the optimize_params "
                "override) — the summary reports grid points as schema "
                "low/high, so out-of-range periods would misdescribe the "
                "search space"
            )
        return grid
    if (
        str(config.get("strategy_plugin", "")) == "direct_atr_sltp"
        and config.get("atr_period") is None
    ):
        lo, hi = atr_period_bounds(config)
        if (lo, hi) == (7, 30):
            return [7, 14, 21, 30]  # the documented reference-range grid
        span = hi - lo
        return sorted({lo + span * i // 3 for i in range(4)})
    return []


def optimize_from_config(config: Dict[str, Any]) -> Dict[str, Any]:
    from gymfx_tpu.train.common import build_train_eval_envs

    # GA fitness is DEFINED on the training bars (the reference's
    # external optimizer likewise scores candidates on the episode it
    # runs).  The out-of-sample keys therefore never touch FITNESS —
    # they hold bars out of the candidate episodes entirely, and the
    # WINNING candidate is automatically re-evaluated on them after the
    # search (VERDICT r4 item #3), so one invocation returns both an
    # honest in-sample fitness and an honest held-out number.
    holds_out = bool(config.get("eval_split") or config.get("eval_data_file"))

    # one dataset load + chronological split for the whole sweep: the
    # training slice is period-independent (atr_period only sizes the
    # TR ring buffer), so grid points share it instead of re-loading
    # and re-splitting the CSV per period
    _base_train_env, _ = build_train_eval_envs(dict(config))
    train_dataset = _base_train_env.dataset

    def run_at(period: Optional[int]) -> Dict[str, Any]:
        cfg = dict(config)
        if period is not None:
            cfg["atr_period"] = int(period)
        env = Environment(cfg, dataset=train_dataset)
        # atr_period is swept OUTSIDE the GA (static ring-buffer shape);
        # an optimize_params override listing it feeds atr_period_grid's
        # bounds, never the inner continuous schema
        inner_schema = [s for s in hparam_schema(cfg) if s[0] != "atr_period"]
        population = int(cfg.get("optimize_population", 32))
        generations = int(cfg.get("optimize_generations", 8))
        if not inner_schema:
            # nothing continuous to tune: every candidate is identical,
            # so one minimal evaluation per grid point scores the period
            # without burning population x generations of rollouts
            population, generations = 2, 1
        optimizer = Optimizer(
            env,
            inner_schema,
            population=population,
            risk_lambda=float(
                cfg.get("risk_lambda", cfg.get("risk_penalty_lambda", 1.0))
            ),
            mutation_scale=float(cfg.get("optimize_mutation_scale", 0.15)),
            episode_steps=cfg.get("steps"),
        )
        return optimizer.run(
            generations=generations,
            seed=int(cfg.get("seed", 0) or 0),
        )

    def label(result: Dict[str, Any]) -> Dict[str, Any]:
        if not holds_out:
            result["eval_scope"] = "in_sample_by_design"
            result["eval_note"] = (
                "GA fitness is defined on the training bars; pass "
                "eval_split or eval_data_file to automatically "
                "re-evaluate the winning candidate held-out"
            )
            return result
        # automatic held-out evaluation of the winner: the same episode
        # definition as fitness (candidate_episode_metrics), on bars the
        # search never saw, over the FULL holdout
        cfg = dict(config)
        bp = result["best_params"]
        if "atr_period" in bp:
            cfg["atr_period"] = int(bp["atr_period"])
        train_env, eval_env = build_train_eval_envs(cfg)
        schema = [s for s in hparam_schema(cfg) if s[0] != "atr_period"]
        vals = jnp.asarray([bp[n] for n, _, _ in schema], jnp.float32)
        steps = eval_env.cfg.n_bars - 1
        risk_lambda = float(
            cfg.get("risk_lambda", cfg.get("risk_penalty_lambda", 1.0))
        )
        episode = jax.jit(
            candidate_episode_metrics(eval_env, schema, risk_lambda, steps)
        )
        rap, total_return, dd, trades = episode(
            vals, jax.random.PRNGKey(int(cfg.get("seed", 0) or 0))
        )
        result["held_out"] = {
            "rap": float(rap),
            "total_return": float(total_return),
            "drawdown_fraction": float(dd),
            "trades": int(trades),
            "eval_bars": int(eval_env.cfg.n_bars),
            "train_bars": int(train_env.cfg.n_bars),
            "driver": "seeded random-entry stream (the fitness episode "
                      "definition, on held-out bars)",
        }
        result["eval_scope"] = "fitness_in_sample_winner_held_out"
        result["eval_note"] = (
            "GA fitness is defined on the training bars (in-sample by "
            "design); the winning candidate was automatically "
            "re-evaluated on the held-out bars — see held_out"
        )
        return result

    grid = atr_period_grid(config)
    if not grid and any(n == "atr_period" for n, _, _ in hparam_schema(config)):
        # atr_period never reaches the inner GA (static shape), so an
        # optimize_params declaring it with nothing sweeping it would
        # silently optimize nothing — fail the way the old inner-schema
        # rejection did
        raise ValueError(
            "optimize_params declares atr_period but nothing sweeps it: "
            "unpin atr_period from the config or pass "
            "optimize_atr_periods (non-ATR strategies cannot sweep it)"
        )
    if not grid:
        return label(run_at(None))

    # outer sweep: one re-jitted batched GA per ring-buffer size, best
    # triple selected by fitness (same identical-entry-stream seed per
    # period, so periods compete on the hyperparameter, not on luck)
    sweep, best_period, best = [], None, None
    for period in grid:
        res = run_at(period)
        sweep.append(
            {
                "atr_period": period,
                "best_rap": res["best_rap"],
                "best_params": dict(res["best_params"]),
            }
        )
        if best is None or res["best_rap"] > best["best_rap"]:
            best_period, best = period, res

    best["best_params"] = {**best["best_params"], "atr_period": best_period}
    # the outer sweep has bounds too: a winner at a grid endpoint is as
    # boundary-clipped as an inner-GA winner at a schema bound
    if len(grid) > 1:
        bc = dict(best.get("boundary_clipped") or {})
        if best_period == grid[0]:
            bc["atr_period"] = "low"
        elif best_period == grid[-1]:
            bc["atr_period"] = "high"
        best["boundary_clipped"] = bc
    best["schema"].append(
        {
            "name": "atr_period",
            "low": float(grid[0]),
            "high": float(grid[-1]),
            "grid": grid,
        }
    )
    best["atr_period_sweep"] = sweep
    return label(best)
