"""Shared training helpers."""
from __future__ import annotations

import jax
import jax.numpy as jnp


def masked_reset(done, fresh_tree, cur_tree):
    """Where ``done`` (batch bool), replace each leaf of ``cur_tree``
    with the (broadcast) corresponding leaf of ``fresh_tree``.  Used for
    env-state / obs / recurrent-carry auto-reset inside rollout scans —
    one definition so actor rollout and learner replay cannot diverge.
    """

    def expand(d, leaf):
        return d.reshape(d.shape + (1,) * (leaf.ndim - 1))

    return jax.tree.map(
        lambda fresh, cur: jnp.where(
            expand(done, cur), jnp.broadcast_to(fresh, cur.shape), cur
        ),
        fresh_tree,
        cur_tree,
    )
