"""Shared training helpers."""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp


def masked_reset(done, fresh_tree, cur_tree):
    """Where ``done`` (batch bool), replace each leaf of ``cur_tree``
    with the (broadcast) corresponding leaf of ``fresh_tree``.  Used for
    env-state / obs / recurrent-carry auto-reset inside rollout scans —
    one definition so actor rollout and learner replay cannot diverge.
    """

    def expand(d, leaf):
        return d.reshape(d.shape + (1,) * (leaf.ndim - 1))

    return jax.tree.map(
        lambda fresh, cur: jnp.where(
            expand(done, cur), jnp.broadcast_to(fresh, cur.shape), cur
        ),
        fresh_tree,
        cur_tree,
    )


def shard_train_state(
    mesh,
    *,
    params: Dict[str, Any],
    replicated: Dict[str, Any],
    batched: Dict[str, Any],
) -> Dict[str, Any]:
    """Place train-state field groups on a mesh: policy params get wide
    2-D matrices tensor-sharded over 'model' (rest replicated),
    ``replicated`` trees replicate, ``batched`` trees shard their
    leading env axis over 'data'.  Returns {field: placed_tree}."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    rep = NamedSharding(mesh, P())
    batch = NamedSharding(mesh, P("data"))

    def shard_param(path, x):
        if (
            "model" in mesh.axis_names
            and hasattr(x, "ndim")
            and x.ndim == 2
            and x.shape[-1] % mesh.shape["model"] == 0
            and x.shape[-1] >= 128
        ):
            return jax.device_put(x, NamedSharding(mesh, P(None, "model")))
        return jax.device_put(x, rep)

    out: Dict[str, Any] = {}
    for name, tree in params.items():
        out[name] = jax.tree_util.tree_map_with_path(shard_param, tree)
    for name, tree in replicated.items():
        out[name] = jax.tree.map(
            lambda x: jax.device_put(x, rep) if hasattr(x, "shape") else x, tree
        )
    for name, tree in batched.items():
        out[name] = jax.tree.map(lambda x: jax.device_put(x, batch), tree)
    return out
