"""Shared training helpers."""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

# DelayedLogger grew into the telemetry device stream (same delayed-
# drain discipline, optionally feeding a MetricsRegistry/JSONL sink);
# the original class name and construction stay importable from here.
from gymfx_tpu.telemetry.device_stream import (  # noqa: F401
    DelayedLogger,
    DeviceMetricStream,
)


def make_train_many(step_impl):
    """Superstep driver: jitted ``train_many(state, k)`` running ``k``
    fused train steps in ONE donated dispatch.

    ``step_impl(state) -> (state, metrics)`` is the same per-step impl
    the trainers jit as ``train_step``; here it becomes the body of a
    ``lax.scan``, so the Python interpreter pays one dispatch (and the
    caller one metrics fetch) per K steps instead of per step.  Metrics
    come back stacked on a leading ``(k,)`` axis — accumulated on
    device, including the resilience guard counters, and fetched once
    per superstep.

    ``k`` is static: each distinct K compiles once (the trainers use one
    K for the whole run plus at most one remainder).
    """

    def impl(state, k: int):
        def body(s, _):
            return step_impl(s)

        return jax.lax.scan(body, state, None, length=k)

    return jax.jit(impl, static_argnums=1, donate_argnums=0)


def make_train_many_with_data(step_impl):
    """Curriculum variant of :func:`make_train_many`: jitted
    ``train_many(state, data, k)`` where the MarketData tape is a traced
    argument instead of a closure constant, so ONE compiled superstep
    serves every tape of the registry (all tapes share static shapes).
    Only the state is donated — the tape is owned by the sampler and
    reused across supersteps."""

    def impl(state, data, k: int):
        def body(s, _):
            return step_impl(s, data)

        return jax.lax.scan(body, state, None, length=k)

    return jax.jit(impl, static_argnums=2, donate_argnums=0)


def make_train_many_overlapped(
    rollout_phase, update_phase, learner_fields=("params", "opt_state"),
):
    """Software-pipelined superstep driver: jitted ``train_many(state,
    k)`` where iteration ``i+1``'s rollout is ISSUED in the same scan
    body as iteration ``i``'s update, so the XLA scheduler can overlap
    the rollout's small-op env chain with the update's GEMM chain
    instead of running the two phases back to back.

    Shape: prologue rollout, then ``k - 1`` pipelined bodies
    {rollout(i+1) on pre-update params || update(i)}, then the epilogue
    update — the same number of rollouts and updates as the sequential
    driver.  ``learner_fields`` names the state fields the update owns
    (params/opt state/actor-sync counters); the body grafts them from
    the update's result onto the already-issued rollout's carry.

    Semantics (why this is OPT-IN, ``superstep_overlap`` in
    config/defaults.py):

      * rollouts act on params ONE update stale — the standard
        actor-learner pipelining trade (IMPALA makes it explicit with
        V-trace; for PPO the stored log-probs stay self-consistent, the
        data is just one policy version old);
      * the guard's quarantine env resets (and any other update-side
        edits to env/obs/carry state) are dropped inside a dispatch,
        because the next rollout already consumed the pre-update state;
      * the rollout/update RNG streams are pre-split per body so the
        two concurrent phases never share a key.

    ``k=1`` has no pipelined body — prologue + epilogue compose exactly
    the sequential train step, which the parity test pins bitwise
    (tests/test_overlap_superstep.py).  Metrics return stacked on a
    leading ``(k,)`` axis like :func:`make_train_many`.
    """

    def merge(rolled, updated):
        return rolled._replace(
            **{f: getattr(updated, f) for f in learner_fields}
        )

    def impl(state, k: int):
        inter, ro = rollout_phase(state)

        def body(carry, _):
            inter, ro = carry
            r_next, r_upd = jax.random.split(inter.rng)
            inter2, ro2 = rollout_phase(inter._replace(rng=r_next))
            updated, metrics = update_phase(inter._replace(rng=r_upd), ro)
            return (merge(inter2, updated), ro2), metrics

        if k > 1:
            (inter, ro), stacked = jax.lax.scan(
                body, (inter, ro), None, length=k - 1
            )
        final, last = update_phase(inter, ro)
        if k > 1:
            metrics = jax.tree.map(
                lambda a, b: jnp.concatenate([a, b[None]]), stacked, last
            )
        else:
            metrics = jax.tree.map(lambda x: x[None], last)
        return final, metrics

    return jax.jit(impl, static_argnums=1, donate_argnums=0)


def build_train_eval_envs(config: Dict[str, Any]) -> Tuple[Any, Optional[Any]]:
    """(train_env, eval_env-or-None) honoring the out-of-sample keys.

    ``eval_data_file``   evaluate on a separate dataset file;
    ``eval_split``       hold out the LAST fraction of bars (chronological
                         split — the only sound one for market series:
                         a random split would leak future bars into
                         training).
    Without either, eval_env is None and evaluation is in-sample (the
    round-2 behavior, now labeled as such in the summary).
    """
    from gymfx_tpu.core.runtime import Environment

    eval_file = config.get("eval_data_file")
    split = config.get("eval_split")
    feed = str(config.get("feed") or "replay").lower()
    if eval_file and split:
        raise ValueError("set either eval_data_file or eval_split, not both")
    if feed == "curriculum" and split:
        raise ValueError(
            "feed=curriculum cannot hold out via eval_split (which tape "
            "would be cut?); name a held-out tape with eval_data_file"
        )
    if eval_file:
        eval_config = dict(config)
        eval_config["input_data_file"] = str(eval_file)
        if feed in ("scengen", "curriculum"):
            # train-on-synthetic / eval-on-real: the named eval file is
            # by definition a replayed tape
            eval_config["feed"] = "replay"
            eval_config.pop("tapes", None)
        return Environment(config), Environment(eval_config)
    if split:
        frac = float(split)
        if not 0.0 < frac < 1.0:
            raise ValueError(f"eval_split must be in (0, 1), got {split!r}")
        min_bars = int(config.get("window_size", 32)) + 2

        def check(cut: int, n_all: int) -> None:
            if cut < min_bars or n_all - cut < min_bars:
                raise ValueError(
                    f"eval_split={frac} leaves too few bars (train {cut}, "
                    f"eval {n_all - cut}; both need >= {min_bars})"
                )

        if feed == "scengen":
            # generate ONCE, then split chronologically — the same
            # no-leakage cut as the replay path, and both halves come
            # from one seeded tape (regenerating per half would desync
            # the overlay processes at the cut)
            from gymfx_tpu.scengen.feed import ScenGenDataset

            full = ScenGenDataset(config)
            n_all = len(full)
            cut = n_all - int(n_all * frac)
            check(cut, n_all)
            return (
                Environment(config, dataset=full.sliced(slice(0, cut))),
                Environment(config, dataset=full.sliced(slice(cut, None))),
            )
        from gymfx_tpu.data.feed import MarketDataset, load_dataframe

        df = load_dataframe(config)
        cut = len(df) - int(len(df) * frac)
        check(cut, len(df))
        train_env = Environment(
            config, dataset=MarketDataset(df.iloc[:cut], config)
        )
        eval_env = Environment(
            config, dataset=MarketDataset(df.iloc[cut:], config)
        )
        return train_env, eval_env
    return Environment(config), None


def build_portfolio_train_eval_envs(config: Dict[str, Any]) -> Tuple[Any, Optional[Any]]:
    """(train_env, eval_env-or-None) for the multi-pair portfolio env.

    ``eval_portfolio_files``  evaluate on a separate per-pair file map;
    ``eval_split``            hold out the LAST fraction of the ALIGNED
                              bars (chronological, applied after the
                              cross-pair timestamp join so no pair
                              leaks future bars into training).
    ``eval_data_file`` is rejected loudly: a single file cannot describe
    a multi-pair book.
    """
    from gymfx_tpu.core.portfolio import PortfolioEnvironment

    if config.get("eval_data_file"):
        raise ValueError(
            "portfolio trainers hold out via eval_split or "
            "eval_portfolio_files (a per-pair file map); eval_data_file "
            "is single-pair only"
        )
    eval_files = config.get("eval_portfolio_files")
    split = config.get("eval_split")
    if eval_files and split:
        raise ValueError("set either eval_portfolio_files or eval_split, not both")
    if eval_files:
        eval_config = dict(config)
        eval_config["portfolio_files"] = dict(eval_files)
        eval_config.pop("eval_portfolio_files", None)
        train_env = PortfolioEnvironment(config)
        eval_env = PortfolioEnvironment(eval_config)
        # the policy's per-pair heads/obs channels are POSITIONAL: a
        # different pair set or ordering would silently evaluate the
        # wrong instruments on the wrong heads
        if list(eval_env.pairs) != list(train_env.pairs):
            raise ValueError(
                "eval_portfolio_files must list the same pairs in the "
                f"same order as portfolio_files (train {train_env.pairs}, "
                f"eval {eval_env.pairs})"
            )
        return train_env, eval_env
    if split:
        frac = float(split)
        return (
            PortfolioEnvironment(config, split=("train", frac)),
            PortfolioEnvironment(config, split=("eval", frac)),
        )
    return PortfolioEnvironment(config), None


def labeled_eval_summary(make_summary, train_env, eval_env) -> Dict[str, Any]:
    """One definition of the out-of-sample summary shape for every
    trainer: ``make_summary(env_or_None)`` runs a greedy evaluation on
    the given env (None = the training env)."""
    if eval_env is None:
        summary = make_summary(None)
        summary["eval_scope"] = "in_sample"
        return summary
    summary = make_summary(eval_env)
    summary["eval_scope"] = "held_out"
    summary["eval_bars"] = eval_env.n_bars
    summary["train_bars"] = train_env.n_bars
    summary["in_sample"] = make_summary(None)
    return summary


def eval_checkpointed_policy(
    config: Dict[str, Any],
    *,
    build_envs,
    make_trainer,
    evaluate_fn,
    resolve_policy=None,
    validate=None,
) -> Dict[str, Any]:
    """The one ``driver_mode=policy`` skeleton shared by the single-pair
    and portfolio paths: checkpoint-dir guard, metadata honor
    (``resolve_policy(meta, config)`` mutates the config copy),
    train/eval env build, template-validated params restore, greedy
    evaluation, and the labeled summary keys.  ``validate(meta, env)``
    rejects checkpoint/config mismatches loudly (e.g. portfolio pair
    sets)."""
    import jax

    ckpt_dir = config.get("checkpoint_dir")
    if not ckpt_dir:
        raise ValueError("driver_mode=policy requires checkpoint_dir")
    from gymfx_tpu.train.checkpoint import load_params, read_metadata

    meta = read_metadata(str(ckpt_dir))
    config = dict(config)
    # the minibatch scheme shapes only the UPDATE pass, which never runs
    # in inference — pin the scheme that is valid for ANY env count so
    # the env_permute training default (config/defaults.py) cannot
    # reject a single-env eval trainer at construction
    config["ppo_minibatch_scheme"] = "sample_permute"
    if resolve_policy is not None:
        resolve_policy(meta, config)
    train_env, eval_env = build_envs(config)
    env = eval_env if eval_env is not None else train_env
    if validate is not None:
        validate(meta, env)
    trainer = make_trainer(env, config)
    # template-validated restore: an architecture mismatch fails loudly
    # at load time, not as an opaque shape error inside the episode scan
    template = jax.eval_shape(
        lambda k: trainer.init_state_from_key(k).params, jax.random.PRNGKey(0)
    )
    params, step = load_params(str(ckpt_dir), template=template)
    summary = evaluate_fn(trainer, params, config.get("steps"))
    summary["checkpoint_step"] = step
    summary["eval_scope"] = "held_out" if eval_env is not None else "in_sample"
    summary["mode"] = "inference"
    return summary


def validate_minibatch_scheme(scheme: str, n_envs: int, minibatches: int,
                              *, horizon: Optional[int] = None) -> None:
    """Construction-time validation shared by the PPO trainers."""
    if scheme not in ("sample_permute", "env_permute"):
        raise ValueError(
            "ppo_minibatch_scheme must be 'sample_permute' or "
            f"'env_permute', got {scheme!r}"
        )
    if scheme == "env_permute" and n_envs % minibatches:
        raise ValueError(
            f"env_permute needs num_envs ({n_envs}) divisible by "
            f"ppo_minibatches ({minibatches})"
        )
    if scheme == "sample_permute" and horizon is not None:
        # minibatch_plan slices the permutation into minibatches chunks
        # of floor(T*N / minibatches) — a non-zero remainder of samples
        # is silently never trained on each epoch.  Mirror the
        # env_permute divisibility check as a warning (the drop is a
        # per-epoch random subset, so it biases coverage, not
        # correctness).
        total = int(horizon) * int(n_envs)
        dropped = total % int(minibatches)
        if dropped:
            import warnings

            warnings.warn(
                f"sample_permute drops {dropped} of {total} samples per "
                f"epoch (horizon*num_envs={total} not divisible by "
                f"ppo_minibatches={minibatches}); pick sizes where "
                "horizon*num_envs % minibatches == 0 to train on every "
                "sample",
                stacklevel=2,
            )


def resolve_minibatch_scheme(config, n_envs: int, minibatches: int) -> None:
    """From-config entry-point resolution of the env_permute default
    (config/defaults.py): when the requested scheme is env_permute but
    num_envs < ppo_minibatches — a shape where whole-trajectory
    minibatches CANNOT exist (e.g. the single-env inference default) —
    degrade to sample_permute with a warning instead of refusing to
    train.  Fixable mismatches (num_envs >= minibatches but not
    divisible) still raise at trainer construction
    (:func:`validate_minibatch_scheme`): those have a right answer the
    user should pick.  Mutates ``config`` in place."""
    scheme = str(config.get("ppo_minibatch_scheme", "env_permute"))
    if scheme == "env_permute" and int(n_envs) < int(minibatches):
        import warnings

        warnings.warn(
            f"ppo_minibatch_scheme=env_permute needs num_envs "
            f"({n_envs}) >= ppo_minibatches ({minibatches}); falling "
            "back to sample_permute for this run — raise num_envs to a "
            "multiple of ppo_minibatches to use trajectory minibatches",
            stacklevel=2,
        )
        config["ppo_minibatch_scheme"] = "sample_permute"


def minibatch_plan(fields, *, scheme: str, n_envs: int, horizon: int,
                   minibatches: int):
    """One definition of the PPO update's minibatching schemes, shared
    by the single-pair and portfolio trainers: returns
    ``(n_perm, mb, take)`` where a per-epoch permutation of
    ``n_perm`` indices is sliced into ``minibatches`` chunks of ``mb``
    indices each, and ``take(idx)`` materializes one flat minibatch
    from the (T, N, ...) ``fields``.

      sample_permute  classic iid shuffle of all T*N samples;
      env_permute     permute ENVS, minibatches gather whole (T, ...)
                      trajectories — contiguous DMA, the wide-batch
                      HBM fix (VERDICT r4 #4) and the standard
                      recurrent sequence-minibatch treatment.
    """
    if scheme == "env_permute":
        source = jax.tree.map(lambda x: jnp.swapaxes(x, 0, 1), fields)
        mb = n_envs // minibatches

        def take(idx):
            return jax.tree.map(
                lambda x: x[idx].reshape(mb * horizon, *x.shape[2:]),
                source,
            )

        return n_envs, mb, take

    n_total = horizon * n_envs
    source = jax.tree.map(
        lambda x: x.reshape(n_total, *x.shape[2:]), fields
    )

    def take(idx):
        return jax.tree.map(lambda x: x[idx], source)

    return n_total, n_total // minibatches, take


def masked_reset(done, fresh_tree, cur_tree):
    """Where ``done`` (batch bool), replace each leaf of ``cur_tree``
    with the (broadcast) corresponding leaf of ``fresh_tree``.  Used for
    env-state / obs / recurrent-carry auto-reset inside rollout scans —
    one definition so actor rollout and learner replay cannot diverge.
    """

    def expand(d, leaf):
        return d.reshape(d.shape + (1,) * (leaf.ndim - 1))

    return jax.tree.map(
        lambda fresh, cur: jnp.where(
            expand(done, cur), jnp.broadcast_to(fresh, cur.shape), cur
        ),
        fresh_tree,
        cur_tree,
    )


def shard_train_state(
    mesh,
    *,
    params: Dict[str, Any],
    replicated: Dict[str, Any],
    batched: Dict[str, Any],
) -> Dict[str, Any]:
    """Legacy surface: the placement plan moved to
    :class:`~gymfx_tpu.parallel.runtime.ShardedRuntime` (one owner for
    all four trainers); this wrapper keeps old callers working."""
    from gymfx_tpu.parallel.runtime import ShardedRuntime

    return ShardedRuntime(mesh).place_groups(
        params=params, replicated=replicated, batched=batched
    )


def profiler_workload(
    trainer: Any,
    state: Any,
    k: int,
    *,
    algo: str,
    params: Any,
    n_envs: int,
    horizon: int,
    update_epochs: int = 1,
    split_iters: int = 2,
) -> Dict[str, Any]:
    """Capture-time workload payload for a profiler bundle manifest
    (:meth:`~gymfx_tpu.telemetry.profiler.ProfilerSession.set_workload_source`):
    the dispatched program's optimized HLO (-> the rollout/update scope
    map), its XLA cost-model FLOPs, the analytic FLOP model, and the
    ``measure_phase_split`` baseline the report reconciles against.

    Runs OUTSIDE the capture window (after stop_trace) and pays one AOT
    recompile of the dispatched program plus the two phase sub-programs
    — only on capture supersteps.  ``measure_phase_split`` donates its
    input, so it runs on a copy of the live ``state``; never raises
    (the profiler counts a workload_error instead).
    """
    from gymfx_tpu.bench_util import compile_with_flops, measure_phase_split

    info: Dict[str, Any] = {
        "algo": str(algo),
        "n_envs": int(n_envs),
        "horizon": int(horizon),
        "steps_per_iter": int(n_envs) * int(horizon),
    }
    k = max(1, int(k))
    if k == 1:
        compiled, flops = compile_with_flops(trainer._train_step, state)
    else:
        compiled, flops = compile_with_flops(trainer._train_many, state, k)
    if compiled is not None:
        try:
            info["hlo_text"] = compiled.as_text()
        except Exception:
            pass
    info["xla_flops_per_dispatch"] = flops
    info["xla_flops_per_step"] = (flops / k) if flops else None
    try:
        from gymfx_tpu.telemetry.mfu import analytic_train_step_flops

        info["analytic_flops_per_step"] = analytic_train_step_flops(
            params, num_envs=int(n_envs), horizon=int(horizon),
            update_epochs=int(update_epochs),
        )
    except Exception:
        info["analytic_flops_per_step"] = None
    try:
        split = measure_phase_split(
            trainer, jax.tree.map(jnp.copy, state), int(split_iters)
        )
    except Exception:
        split = None
    if split is not None:
        rollout_s, update_s, _split_state, _u_flops = split
        info["phase_split"] = {
            "rollout_ms": rollout_s / int(split_iters) * 1e3,
            "update_ms": update_s / int(split_iters) * 1e3,
            "iters": int(split_iters),
            "source": "measure_phase_split",
        }
    else:
        info["phase_split"] = None
    return info
