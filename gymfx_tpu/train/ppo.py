"""PPO actor-learner, fused with the env scan, sharded over a mesh.

New capability per the north star (BASELINE.json): the reference has no
trainer.  Design:

  * rollout collection IS the env scan: policy apply + env.step run in
    one ``lax.scan`` per train step — no host round trips, no replay
    buffers in host memory;
  * the env batch is data-parallel across the mesh 'data' axis (each
    device steps its shard of envs); wide policy layers may also be
    tensor-sharded across 'model' — placement is owned by the shared
    :class:`~gymfx_tpu.parallel.runtime.ShardedRuntime` plan;
  * gradients are averaged over all envs — under jit with replicated
    params and sharded batch, XLA emits the all-reduce over ICI;
  * auto-reset: terminated envs restart from a fresh reset state inside
    the scan, so training streams continuously over episodes.
"""
from __future__ import annotations

import time
from functools import partial
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from gymfx_tpu.core import env as env_core
from gymfx_tpu.core.runtime import Environment
from gymfx_tpu.parallel.runtime import ShardedRuntime, StatePlan
from gymfx_tpu.train.common import masked_reset
from gymfx_tpu.train.policies import (
    flatten_obs,
    gaussian_entropy,
    is_token_policy,
    make_obs_spec,
    make_trainer_policy,
    normal_logp,
    sample_normal,
    tokens_from_obs,
)


class PPOConfig(NamedTuple):
    n_envs: int = 256
    horizon: int = 128
    epochs: int = 4
    minibatches: int = 4
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    lr: float = 3e-4
    ent_coef: float = 0.01
    vf_coef: float = 0.5
    max_grad_norm: float = 0.5
    policy: str = "mlp"
    policy_dtype: Any = jnp.float32
    policy_kwargs: Tuple[Tuple[str, Any], ...] = ()
    # sample_permute: iid shuffle of all T*N samples per epoch (the
    #   classic PPO treatment; a 2M-row random HBM gather at 32k envs).
    # env_permute: permute ENVS, each minibatch holding whole (T, ...)
    #   trajectories — contiguous large-granularity DMA, the standard
    #   recurrent-PPO sequence minibatching; the product default since
    #   round 6 (held-out parity evidence:
    #   examples/results/minibatch_scheme_parity.json).
    minibatch_scheme: str = "sample_permute"
    # storage dtype for the collected trajectory obs — the (T, N,
    # obs_dim) buffer is the rollout's widest write and the update's
    # widest read.  Resolved in ppo_config_from to the NARROWER of this
    # and policy_dtype (storing wider than the policy's entry cast is
    # pure HBM waste); bf16 with a f32 policy is the lossy opt-in
    # (quality-parity gate: docs/performance.md).  Actions, log-probs,
    # values, advantages stay f32 — PPO ratio numerics untouched.
    collect_dtype: Any = jnp.float32
    # non-finite guard (resilience/guards.py): skip any minibatch update
    # whose loss or grads are non-finite (params/opt-state keep the
    # last-good values bit-for-bit) and quarantine-reset envs whose
    # rollout produced NaN/inf — one poisoned feed bar no longer
    # corrupts the train state irrecoverably
    nonfinite_guard: bool = True
    # Adam first-moment storage dtype (the largest optimizer buffer).
    # bfloat16 halves its HBM footprint/traffic; params and the second
    # moment stay float32 — the master-weight rule, mirrored on
    # resolve_collect_dtype and gated by a learning-parity smoke
    # (tests/test_opt_state_dtype.py).  float32 = bitwise-identical
    # default (optax stores mu in the param dtype either way).
    opt_state_dtype: Any = jnp.float32
    # software-pipelined superstep driver
    # (train/common.make_train_many_overlapped): rollout i+1 issues
    # alongside update i inside train_many dispatches.  Opt-in — see
    # the semantics note on that function.
    superstep_overlap: bool = False
    # rematerialize the policy forward inside the PPO loss (jax.remat):
    # the backward GEMM chain recomputes activations in VMEM instead of
    # staging them through HBM — same math, fewer HBM round trips
    update_remat: bool = False


def resolve_collect_dtype(config: Dict[str, Any], policy_dtype) -> Any:
    """Trajectory-obs storage dtype: the narrower of
    ``rollout_collect_dtype`` and the policy compute dtype.  Every
    policy casts its input to its compute dtype at entry, so storing
    wider than that cast is pure HBM waste (bf16 policies keep the
    historical bf16 storage under the f32 default), while
    ``rollout_collect_dtype: bfloat16`` with a f32 policy is the lossy
    opt-in documented in docs/performance.md."""
    cd = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
        str(config.get("rollout_collect_dtype", "float32"))
    ]
    if policy_dtype == jnp.bfloat16 or cd == jnp.bfloat16:
        return jnp.bfloat16
    return cd


def resolve_optimizer_state_dtype(config: Dict[str, Any]) -> Any:
    """Adam first-moment storage dtype from the config knob.  The
    master-weight rule is fixed, not configurable: only ``mu`` narrows
    (it is a smoothed gradient — bf16's ~3 decimal digits track it),
    while params and ``nu`` stay float32 (``nu`` feeds the 1/sqrt
    rescale where bf16 quantization would modulate the effective lr).
    Mirrors :func:`resolve_collect_dtype`'s one-definition discipline —
    every trainer resolves through here."""
    dt = str(config.get("optimizer_state_dtype", "float32")).lower()
    if dt not in ("float32", "bfloat16"):
        raise ValueError(
            f"optimizer_state_dtype must be 'float32' or 'bfloat16', "
            f"got {dt!r}"
        )
    return {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[dt]


def ppo_config_from(config: Dict[str, Any]) -> PPOConfig:
    dt = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
        str(config.get("policy_dtype", "float32"))
    ]
    return PPOConfig(
        n_envs=int(config.get("num_envs", 256) or 256),
        horizon=int(config.get("ppo_horizon", 128)),
        epochs=int(config.get("ppo_epochs", 4)),
        minibatches=int(config.get("ppo_minibatches", 4)),
        gamma=float(config.get("gamma", 0.99)),
        gae_lambda=float(config.get("gae_lambda", 0.95)),
        clip_eps=float(config.get("ppo_clip_eps", 0.2)),
        lr=float(config.get("learning_rate", 3e-4)),
        ent_coef=float(config.get("entropy_coef", 0.01)),
        vf_coef=float(config.get("value_coef", 0.5)),
        max_grad_norm=float(config.get("max_grad_norm", 0.5)),
        policy=str(config.get("policy") or "mlp"),
        policy_dtype=dt,
        policy_kwargs=tuple(
            (k, tuple(v) if isinstance(v, list) else v)
            for k, v in (config.get("policy_kwargs") or {}).items()
        ),
        minibatch_scheme=str(
            config.get("ppo_minibatch_scheme", "env_permute")
        ),
        collect_dtype=resolve_collect_dtype(config, dt),
        nonfinite_guard=bool(config.get("nonfinite_guard", True)),
        opt_state_dtype=resolve_optimizer_state_dtype(config),
        superstep_overlap=bool(config.get("superstep_overlap", False)),
        update_remat=bool(config.get("ppo_update_remat", False)),
    )


# one shared definition of the Gaussian distribution helpers
# (train/policies.py); the local alias keeps this module's call sites
_normal_logp = normal_logp


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    env_states: Any      # vmapped EnvState batch
    obs_vec: Any         # (n_envs, obs_dim) policy inputs
    policy_carry: Any    # recurrent carry (or ())
    rng: Any


class PPOTrainer:
    """Builds the jitted train_step for (Environment, PPOConfig)."""

    # shared placement plan (parallel/runtime.ShardedRuntime): params
    # tensor-shard wide matrices over 'model', opt/rng replicate, the
    # env batch shards its leading axis over 'data'
    STATE_PLAN = StatePlan(
        params=("params",),
        replicated=("opt_state", "rng"),
        batched=("env_states", "obs_vec", "policy_carry"),
    )

    def __init__(self, env: Environment, pcfg: PPOConfig, mesh: Optional[Any] = None):
        self.env = env
        self.pcfg = pcfg
        self.mesh = mesh
        self.runtime = None if mesh is None else ShardedRuntime(mesh)
        from gymfx_tpu.train.common import validate_minibatch_scheme

        validate_minibatch_scheme(
            pcfg.minibatch_scheme, pcfg.n_envs, pcfg.minibatches,
            horizon=pcfg.horizon,
        )
        self._continuous = env.cfg.action_space_mode == "continuous"
        self.policy = make_trainer_policy(
            pcfg.policy, continuous=self._continuous,
            dtype=pcfg.policy_dtype, kwargs=dict(pcfg.policy_kwargs),
            window=env.cfg.window_size,
        )
        self.optimizer = self._make_optimizer()

        cfg, params = env.cfg, env.params
        if hasattr(env, "require_resident_data"):
            data = env.require_resident_data("PPO training (random-access rollouts)")
        else:
            data = env.data
        self._reset_state, reset_obs = env_core.reset(cfg, params, data)
        self._is_transformer = is_token_policy(pcfg.policy)
        self._window = cfg.window_size
        # static obs layout, derived once per env config: the encode hot
        # path (traced per rollout step, and per request when serving)
        # must not re-sort keys / re-derive shapes every call
        self.obs_spec = make_obs_spec(reset_obs)
        self._reset_vec = self._encode(reset_obs)
        self.obs_dim = self._reset_vec.shape

        self._random_start = bool(env.config.get("random_episode_start", False))
        self._train_step = jax.jit(self._train_step_impl, donate_argnums=0)
        from gymfx_tpu.train.common import (
            make_train_many,
            make_train_many_overlapped,
            make_train_many_with_data,
        )

        # feed=curriculum: the sampler swaps whole tapes at superstep
        # boundaries, so the tape becomes a TRACED train_many argument
        # (make_train_many_with_data) — one executable serves every tape
        self.curriculum = getattr(env, "curriculum", None)
        if self.curriculum is not None and pcfg.superstep_overlap:
            raise ValueError(
                "feed=curriculum cannot be combined with "
                "superstep_overlap: the pipelined driver issues rollout "
                "i+1 before update i, so a tape swap inside the dispatch "
                "would feed half a superstep from the wrong tape"
            )
        if self.curriculum is not None:
            self._train_step_data = jax.jit(
                self._train_step_impl, donate_argnums=0
            )
            self._train_many_data = make_train_many_with_data(
                self._train_step_impl
            )
        if pcfg.superstep_overlap:
            self._train_many = make_train_many_overlapped(
                self._rollout_phase, self._update_phase
            )
        else:
            self._train_many = make_train_many(self._train_step_impl)

    # ------------------------------------------------------------------
    def _make_optimizer(self):
        return optax.chain(
            optax.clip_by_global_norm(self.pcfg.max_grad_norm),
            optax.adam(self.pcfg.lr, mu_dtype=self.pcfg.opt_state_dtype),
        )

    def _encode(self, obs: Dict[str, Any]):
        spec = getattr(self, "obs_spec", None)
        if self._is_transformer:
            return tokens_from_obs(obs, self._window, spec)
        return flatten_obs(obs, spec)

    def init_state(self, seed: int = 0) -> TrainState:
        state = self.init_state_from_key(jax.random.PRNGKey(seed))
        if self.runtime is not None:
            state = self.runtime.place_state(state, self.STATE_PLAN)
        return state

    def init_state_from_key(self, rng) -> TrainState:
        """Key-based init (traceable — PBT vmaps this over a population)."""
        rng, k_init = jax.random.split(rng)
        carry0 = self.policy.initial_carry(())
        if self._is_transformer:
            p = self.policy.init(k_init, self._reset_vec)
        elif self.pcfg.policy == "lstm":
            p = self.policy.init(k_init, self._reset_vec, carry0)
        else:
            p = self.policy.init(k_init, self._reset_vec)
        opt_state = self.optimizer.init(p)

        n = self.pcfg.n_envs
        env_states = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (n, *x.shape)), self._reset_state
        )
        obs_vec = jnp.broadcast_to(self._reset_vec, (n, *self._reset_vec.shape))
        pcarry = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (n, *x.shape)), carry0
        )
        return TrainState(p, opt_state, env_states, obs_vec, pcarry, rng)

    # ------------------------------------------------------------------
    def _policy_forward(self, params, obs_vec, pcarry):
        if self.pcfg.policy == "lstm":
            return self.policy.apply(params, obs_vec, pcarry)
        logits, value = self.policy.apply(params, obs_vec)
        return logits, value, pcarry

    def _rollout(self, params, env_states, obs_vec, pcarry, rng, data=None):
        cfg, eparams = self.env.cfg, self.env.params
        # data=None (every non-curriculum path) bakes the env's resident
        # tape into the trace exactly as before — bitwise identical; an
        # explicit tape (curriculum) is a traced argument, so the reset
        # state/obs must be derived from IT in-graph
        explicit_data = data is not None
        if not explicit_data:
            data = self.env.data
        vstep = jax.vmap(env_core.step, in_axes=(None, None, None, 0, 0))
        vencode = jax.vmap(self._encode)
        fwd = jax.vmap(self._policy_forward, in_axes=(None, 0, 0))
        carry0 = self.policy.initial_carry(())
        if self._random_start:
            # a per-env bank of fresh episodes at random offsets, drawn
            # once per rollout (per-step random resets would reintroduce
            # the vmapped window gather the streaming carries eliminated)
            rng, k0 = jax.random.split(rng)
            t0s = jax.random.randint(
                k0, (self.pcfg.n_envs,), 0, max(1, cfg.n_bars - 2)
            )
            reset_state, fresh_obs = jax.vmap(
                env_core.reset_at, in_axes=(None, None, None, 0)
            )(cfg, eparams, data, t0s)
            reset_vec = vencode(fresh_obs)
        elif explicit_data:
            reset_state, fresh_obs = env_core.reset(cfg, eparams, data)
            reset_vec = self._encode(fresh_obs)
        else:
            reset_state = self._reset_state
            reset_vec = self._reset_vec

        continuous = self._continuous

        def body(carry, _):
            env_states, obs_vec, pcarry, rng = carry
            rng, k = jax.random.split(rng)
            dist, value, pcarry2 = fwd(params, obs_vec, pcarry)
            if continuous:
                mu, log_std = dist
                action = sample_normal(k, dist)
                logp = _normal_logp(action, mu, log_std)
            else:
                logits = dist
                keys = jax.random.split(k, logits.shape[0])
                action = jax.vmap(jax.random.categorical)(keys, logits)
                logp = jnp.take_along_axis(
                    jax.nn.log_softmax(logits), action[:, None], axis=1
                )[:, 0]
            env_states2, obs2, reward, done, _ = vstep(
                cfg, eparams, data, env_states, action
            )
            obs_vec2 = vencode(obs2)
            # auto-reset terminated envs (fresh episode, fresh carry)
            env_states2 = masked_reset(done, reset_state, env_states2)
            obs_vec2 = masked_reset(done, reset_vec, obs_vec2)
            pcarry2 = masked_reset(done, carry0, pcarry2)
            out = dict(
                # store obs in the resolved collect dtype (never wider
                # than the policy's entry cast — resolve_collect_dtype):
                # the (T*N, obs_dim) buffer is the rollout's widest
                # write and the update's widest read, and it halves
                # under bf16
                obs=obs_vec.astype(self.pcfg.collect_dtype),
                action=action, logp=logp, value=value,
                reward=reward.astype(jnp.float32), done=done,
                # the carry that ENTERED this step — replayed during the
                # minibatch passes so recurrent policies see exactly the
                # state they acted with (stored-state recurrent replay)
                pcarry=pcarry,
            )
            return (env_states2, obs_vec2, pcarry2, rng), out

        (env_states, obs_vec, pcarry, rng), traj = jax.lax.scan(
            body, (env_states, obs_vec, pcarry, rng), None,
            length=self.pcfg.horizon,
        )
        # bootstrap value for the final obs
        logits, last_value, _ = fwd(params, obs_vec, pcarry)
        return env_states, obs_vec, pcarry, rng, traj, last_value

    def _gae(self, traj, last_value):
        g, lam = self.pcfg.gamma, self.pcfg.gae_lambda

        def body(carry, x):
            adv_next, v_next = carry
            reward, value, done = x
            nonterm = 1.0 - done.astype(jnp.float32)
            delta = reward + g * v_next * nonterm - value
            adv = delta + g * lam * nonterm * adv_next
            return (adv, value), adv

        (_, _), advs = jax.lax.scan(
            body,
            (jnp.zeros_like(last_value), last_value),
            (traj["reward"], traj["value"], traj["done"]),
            reverse=True,
        )
        returns = advs + traj["value"]
        return advs, returns

    def _loss(self, params, batch):
        fwd = jax.vmap(self._policy_forward, in_axes=(None, 0, 0))
        if self.pcfg.update_remat:
            # recompute the forward activations inside the backward pass
            # (same ops, same order — no numeric change) instead of
            # staging every minibatch activation through HBM; on TPU the
            # whole loss GEMM chain then runs VMEM-resident
            fwd = jax.remat(fwd)
        dist, value, _ = fwd(params, batch["obs"], batch["pcarry"])
        if self._continuous:
            mu, log_std = dist
            logp = _normal_logp(batch["action"], mu, log_std)
            entropy = gaussian_entropy(log_std)
        else:
            logits = dist
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["action"][:, None], axis=1
            )[:, 0]
            entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        ratio = jnp.exp(logp - batch["logp"])
        adv = batch["adv"]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        clip_eps, ent_coef = self._loss_hyper()
        unclipped = ratio * adv
        clipped = jnp.clip(ratio, 1 - clip_eps, 1 + clip_eps) * adv
        policy_loss = -jnp.mean(jnp.minimum(unclipped, clipped))
        value_loss = 0.5 * jnp.mean((value - batch["ret"]) ** 2)
        total = (
            policy_loss
            + self.pcfg.vf_coef * value_loss
            - ent_coef * entropy
        )
        return total, dict(
            policy_loss=policy_loss, value_loss=value_loss, entropy=entropy
        )

    def _loss_hyper(self):
        """(clip_eps, ent_coef) used by the loss — static config values
        here; the PBT cores override them with per-member TRACED values
        read from opt_state.hyperparams so a vmapped population explores
        them independently (train/pbt.py)."""
        return self.pcfg.clip_eps, self.pcfg.ent_coef

    def _rollout_phase(self, state: TrainState, data=None):
        """Phase 1 of the train step: collect one horizon of experience.
        Returns the post-rollout carry state (params/opt untouched) and
        the rollout products the update consumes.  ``_train_step_impl``
        is EXACTLY the composition of this and :meth:`_update_phase` —
        the split exists so bench.py can time each phase off its own
        donated executable (rollout_ms / update_ms), and the superstep
        bit-identity tests (tests/test_superstep.py) pin the factoring."""
        env_states, obs_vec, pcarry_end, rng, traj, last_value = self._rollout(
            state.params, state.env_states, state.obs_vec, state.policy_carry,
            state.rng, data,
        )
        inter = TrainState(
            state.params, state.opt_state, env_states, obs_vec, pcarry_end, rng
        )
        return inter, (traj, last_value)

    def _update_phase(self, state: TrainState, rollout_out, data=None):
        """Phase 2 of the train step: GAE + minibatched epochs + guard
        bookkeeping on an already-collected trajectory."""
        pcfg = self.pcfg
        if data is not None:
            # curriculum: quarantine resets must come from the ACTIVE
            # tape, not the baked tape-0 reset (XLA CSEs this with the
            # rollout's identical reset when both phases share a trace)
            reset_state, reset_obs = env_core.reset(
                self.env.cfg, self.env.params, data
            )
            reset_vec = self._encode(reset_obs)
        else:
            reset_state, reset_vec = self._reset_state, self._reset_vec
        traj, last_value = rollout_out
        env_states, obs_vec, pcarry_end, rng = (
            state.env_states, state.obs_vec, state.policy_carry, state.rng
        )
        advs, returns = self._gae(traj, last_value)

        # Stored-state recurrent replay: each step replays with the carry
        # it was collected under (R2D2-style stored state), so at the
        # first epoch the replayed log-probs equal the stored ones
        # exactly (ratio == 1) — no zero-carry approximation.  Carries
        # go stale across epochs as params move, the standard stored-
        # state trade-off; IMPALA re-unrolls from scratch instead
        # (train/impala.py).
        fields = {
            "obs": traj["obs"],
            "action": traj["action"],
            "logp": traj["logp"],
            "adv": advs,
            "ret": returns,
            "pcarry": traj["pcarry"],
        }
        from gymfx_tpu.train.common import minibatch_plan

        n_perm, mb, take = minibatch_plan(
            fields, scheme=pcfg.minibatch_scheme, n_envs=pcfg.n_envs,
            horizon=pcfg.horizon, minibatches=pcfg.minibatches,
        )
        params, opt_state = state.params, state.opt_state
        guard = pcfg.nonfinite_guard
        from gymfx_tpu.resilience.guards import (
            quarantine_mask,
            select_tree,
            tree_all_finite,
        )

        def epoch_body(carry, k):
            params, opt_state = carry
            perm = jax.random.permutation(k, n_perm)

            def mb_body(carry, i):
                params, opt_state = carry
                idx = jax.lax.dynamic_slice_in_dim(perm, i * mb, mb)
                batch = take(idx)
                (loss, aux), grads = jax.value_and_grad(self._loss, has_aux=True)(
                    params, batch
                )
                updates, new_opt_state = self.optimizer.update(
                    grads, opt_state, params
                )
                new_params = optax.apply_updates(params, updates)
                if guard:
                    # non-finite loss/grads: keep last-good params and
                    # opt-state bit-for-bit (one NaN minibatch would
                    # otherwise poison the Adam moments forever)
                    ok = jnp.isfinite(loss) & tree_all_finite(grads)
                    params = select_tree(ok, new_params, params)
                    opt_state = select_tree(ok, new_opt_state, opt_state)
                else:
                    ok = jnp.asarray(True)
                    params, opt_state = new_params, new_opt_state
                return (params, opt_state), (loss, aux, ok)

            (params, opt_state), (losses, auxes, oks) = jax.lax.scan(
                mb_body, (params, opt_state), jnp.arange(pcfg.minibatches)
            )
            return (params, opt_state), (losses, auxes, oks)

        rng, *ks = jax.random.split(rng, pcfg.epochs + 1)
        (params, opt_state), (losses, auxes, oks) = jax.lax.scan(
            epoch_body, (params, opt_state), jnp.stack(ks)
        )

        if guard:
            okf = oks.astype(jnp.float32)
            n_ok = okf.sum()

            def mmean(x):
                # mean over SURVIVING minibatches only; NaN iff every
                # update this step was skipped (an honest signal — a
                # finite number here would hide total divergence)
                safe = jnp.where(jnp.isfinite(x), x, 0.0)
                return jnp.where(
                    n_ok > 0, (safe * okf).sum() / jnp.maximum(n_ok, 1.0),
                    jnp.nan,
                )

            metrics = dict(
                loss=mmean(losses),
                policy_loss=mmean(auxes["policy_loss"]),
                value_loss=mmean(auxes["value_loss"]),
                entropy=mmean(auxes["entropy"]),
                mean_reward=traj["reward"].mean(),
                mean_episode_done=traj["done"].mean(),
                nonfinite_skips=(1.0 - okf).sum(),
                guard_updates=jnp.asarray(
                    float(pcfg.epochs * pcfg.minibatches), jnp.float32
                ),
            )
            # quarantine: envs whose rollout or carried state went
            # non-finite restart from a fresh episode — NaN equity would
            # otherwise stick and re-poison every later rollout
            poison = quarantine_mask(
                {
                    "reward": traj["reward"],
                    "obs": traj["obs"],
                    "value": traj["value"],
                    "logp": traj["logp"],
                },
                env_axis=1,
            ) | quarantine_mask(
                # NaN-only for carried state: env peak/min/max trackers
                # hold ±inf sentinels by design (core/types.py)
                {"obs_vec": obs_vec, "env_states": env_states},
                env_axis=0, mode="nan",
            )
            carry0 = self.policy.initial_carry(())
            env_states = masked_reset(poison, reset_state, env_states)
            obs_vec = masked_reset(poison, reset_vec, obs_vec)
            pcarry_end = masked_reset(poison, carry0, pcarry_end)
            metrics["poisoned_env_resets"] = poison.astype(jnp.float32).sum()
        else:
            metrics = dict(
                loss=losses.mean(),
                policy_loss=auxes["policy_loss"].mean(),
                value_loss=auxes["value_loss"].mean(),
                entropy=auxes["entropy"].mean(),
                mean_reward=traj["reward"].mean(),
                mean_episode_done=traj["done"].mean(),
            )
        new_state = TrainState(
            params, opt_state, env_states, obs_vec, pcarry_end, rng
        )
        return new_state, metrics

    def _train_step_impl(self, state: TrainState, data=None):
        # named_scope labels the XLA ops by phase (trace-time metadata
        # only — the compiled program and numerics are unchanged), so a
        # profiler capture attributes device time to rollout vs update
        with jax.named_scope("rollout"):
            inter, rollout_out = self._rollout_phase(state, data)
        with jax.named_scope("update"):
            return self._update_phase(inter, rollout_out, data)

    # ------------------------------------------------------------------
    def train_step(self, state: TrainState):
        return self._train_step(state)

    def train_many(self, state: TrainState, k: int):
        """``k`` fused train steps in ONE donated dispatch (lax.scan over
        the per-step impl).  Returns ``(state, metrics)`` with every
        metric stacked on a leading ``(k,)`` axis — accumulated on
        device, fetched by the caller once per superstep."""
        return self._train_many(state, int(k))

    def train(self, total_env_steps: int, seed: int = 0, log_every: int = 0,
              initial_params=None, initial_state: Optional[TrainState] = None,
              *, checkpoint_dir: Optional[str] = None,
              checkpoint_every: int = 0, step_offset: int = 0,
              checkpoint_metadata: Optional[Dict[str, Any]] = None,
              max_consecutive_skips: int = 10,
              preempt_at: Optional[int] = None,
              supersteps_per_dispatch: int = 1,
              telemetry=None,
              mesh_faults=(),
              checkpoint_keep: int = 0):
        """Run PPO for ~total_env_steps; log metrics every ``log_every``
        iterations when > 0.  ``initial_state`` continues a checkpointed
        run exactly (full TrainState: params + opt_state + env batch +
        RNG); ``initial_params`` is a params-only warm start.

        ``supersteps_per_dispatch=K > 1`` drives the loop through
        :meth:`train_many`: one donated dispatch (and one host metrics
        fetch) per K iterations.  The iteration trajectory is
        bit-identical to K=1; resilience checkpoints/preemption land on
        superstep boundaries.

        Resilience hooks (resilience/loop.py): ``checkpoint_every > 0``
        auto-saves the full state every that many iterations (cumulative
        ``step_offset`` + env-steps step ids, preemption-safe resume);
        under the non-finite guard, ``max_consecutive_skips`` fully-
        skipped steps in a row abort with NonFiniteDivergenceError;
        ``preempt_at`` injects a SimulatedPreemptionError after that
        iteration (checkpoint/resume drills).

        ``telemetry`` (a :class:`gymfx_tpu.telemetry.Telemetry` bundle,
        None = off) drains the superstep's on-device metric stack into
        its registry/sink once per dispatch and wraps each dispatch in a
        span — no extra host syncs either way; with ``telemetry=None``
        this loop is the exact pre-telemetry one."""
        if initial_state is not None:
            state = initial_state
            if self.runtime is not None:
                state = self.runtime.place_state(state, self.STATE_PLAN)
        else:
            state = self.init_state(seed)
        if initial_params is not None:
            state = state._replace(params=initial_params)
            if self.runtime is not None:
                # restored host arrays must re-enter the mesh placement
                # (model-axis tensor sharding), like the full-state path
                state = self.runtime.place_state(state, self.STATE_PLAN)
        steps_per_iter = self.pcfg.n_envs * self.pcfg.horizon
        iters = max(1, int(total_env_steps) // steps_per_iter)
        from gymfx_tpu.resilience.loop import ResilientLoop

        K = max(1, int(supersteps_per_dispatch or 1))
        from gymfx_tpu.train.common import DelayedLogger

        if telemetry is not None:
            logger = telemetry.device_stream(
                "ppo", iters=iters, log_every=log_every,
                steps_per_iter=steps_per_iter,
            )
        else:
            logger = DelayedLogger("ppo", log_every, iters)
        # mesh health supervision (parallel/elastic.py): only when the
        # run has a mesh AND something observes it — scripted mesh
        # faults or telemetry — so the no-mesh/no-knobs path is untouched
        supervisor = None
        if self.runtime is not None and (mesh_faults or telemetry is not None):
            from gymfx_tpu.parallel.elastic import MeshSupervisor

            supervisor = MeshSupervisor(self.runtime.mesh)
        hooks = ResilientLoop(
            steps_per_iter=steps_per_iter,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
            step_offset=step_offset,
            checkpoint_metadata=checkpoint_metadata,
            max_consecutive_skips=(
                max_consecutive_skips if self.pcfg.nonfinite_guard else 0
            ),
            preempt_at=preempt_at,
            loggers=(logger,),
            ledger=telemetry.ledger if telemetry is not None else None,
            recorder=telemetry.recorder if telemetry is not None else None,
            profiler=telemetry.profiler if telemetry is not None else None,
            mesh_faults=tuple(mesh_faults or ()),
            supervisor=supervisor,
            checkpoint_keep=int(checkpoint_keep or 0),
        )
        if telemetry is not None and supervisor is not None:
            from gymfx_tpu.telemetry import register_mesh_health

            register_mesh_health(telemetry.registry, supervisor, name="ppo")
        if telemetry is not None and telemetry.profiler is not None:
            from gymfx_tpu.train.common import profiler_workload

            # late-binding over the rebound local: the manifest payload
            # (HLO scope map, FLOPs, phase split on a state copy) is
            # resolved at bundle-write time against the live state
            telemetry.profiler.set_workload_source(
                lambda it_start, kk: profiler_workload(
                    self, state, kk, algo="ppo", params=state.params,
                    n_envs=self.pcfg.n_envs, horizon=self.pcfg.horizon,
                    update_epochs=self.pcfg.epochs,
                )
            )
        if telemetry is not None and telemetry.recorder is not None:
            # the closure reads the rebound local, so a postmortem dump
            # captures the rng key the run DIED with, not the seed key
            telemetry.recorder.set_rng_source(lambda: state.rng)
        if telemetry is not None and hooks.monitor is not None:
            from gymfx_tpu.telemetry import register_resilience

            register_resilience(
                telemetry.registry, monitor=hooks.monitor, name="ppo"
            )
        from gymfx_tpu.telemetry import null_tracer

        tracer = telemetry.tracer if telemetry is not None else null_tracer()
        t0 = time.perf_counter()
        metrics = {}
        it = 0
        while it < iters:
            k = min(K, iters - it)
            capturing = hooks.begin_superstep(it, k)
            # curriculum: one weighted seed-deterministic tape draw per
            # superstep boundary (ledgered as a curriculum_pick row)
            tape = None
            if self.curriculum is not None:
                _ti, _label, tape = self.curriculum.pick(it)
            with tracer.span("train/superstep", algo="ppo", it=it, k=k):
                if k == 1:
                    if tape is None:
                        state, metrics = self.train_step(state)
                    else:
                        state, metrics = self._train_step_data(state, tape)
                    guard_metrics = metrics
                else:
                    if tape is None:
                        state, stacked = self.train_many(state, k)
                    else:
                        state, stacked = self._train_many_data(state, tape, k)
                    # newest iteration's metrics, still on device (no sync)
                    metrics = jax.tree.map(lambda x: x[-1], stacked)
                    guard_metrics = stacked
            if capturing:
                # the trace window must cover the device work, so the
                # async dispatch is synced — only on capture supersteps
                jax.block_until_ready(state)
            # logger BEFORE hooks: when the hooks abort (preemption,
            # divergence) they flush the attached logger, so the final
            # superstep's held metrics must already be in its hands
            logger.after_dispatch(it, k, guard_metrics)
            hooks.after_superstep(
                it, k, guard_metrics, lambda: (state._asdict(), state.params)
            )
            it += k
        logger.finish()
        hooks.finish(lambda: (state._asdict(), state.params))
        jax.block_until_ready(state.params)
        dt = time.perf_counter() - t0
        metrics = {k: float(v) for k, v in metrics.items()}
        metrics["env_steps_per_sec"] = steps_per_iter * iters / dt
        metrics["iterations"] = iters
        metrics["total_env_steps"] = steps_per_iter * iters
        if hooks.last_checkpoint_step is not None:
            metrics["last_checkpoint_step"] = hooks.last_checkpoint_step
        return state, metrics


# ---------------------------------------------------------------------------
def greedy_policy_driver(trainer: PPOTrainer):
    """Deterministic (argmax) eval driver.  Cached per trainer: the
    Driver is a static jit argument, so the policy params travel in the
    (traced) driver carry — repeated evals with new weights reuse the
    compiled episode scan."""
    if getattr(trainer, "_greedy_driver", None) is not None:
        return trainer._greedy_driver
    from gymfx_tpu.core.rollout import Driver

    def act(carry, obs, i, key):
        params, pcarry = carry
        vec = trainer._encode(obs)
        dist, _value, pcarry = trainer._policy_forward(params, vec, pcarry)
        if trainer._continuous:
            mu, _log_std = dist
            return mu, (params, pcarry)  # deterministic: the mean action
        return jnp.argmax(dist, axis=-1).astype(jnp.int32), (params, pcarry)

    trainer._greedy_driver = Driver(init=lambda: (), act=act)
    return trainer._greedy_driver


def evaluate(trainer: PPOTrainer, params, steps: Optional[int] = None, seed: int = 0):
    """Greedy-policy episode -> reference-style metrics summary."""
    from gymfx_tpu.core.rollout import rollout_chunked
    from gymfx_tpu.metrics import compute_analyzers, summarize_trading

    env = trainer.env
    steps = int(steps or env.cfg.n_bars - 1)
    driver = greedy_policy_driver(trainer)
    state, out = rollout_chunked(
        env.cfg, env.params, env.data, driver, steps, jax.random.PRNGKey(seed),
        driver_carry=(params, trainer.policy.initial_carry(())),
    )
    equity = np.asarray(out["equity_delta"], np.float64) + float(
        env.params.initial_cash
    )
    done = np.asarray(out["done"])
    ts = env.dataset.timestamps.iloc[1 : steps + 1]
    analyzers = compute_analyzers(equity=equity, done=done, state=state, timestamps=ts)
    final_eq = float(equity[int(np.argmax(done))] if done.any() else equity[-1])
    summary = summarize_trading(
        initial_cash=float(env.params.initial_cash),
        final_equity=final_eq,
        analyzers=analyzers,
        config=env.config,
    )
    tf_hours = env.dataset.timeframe_hours or (1.0 / 60.0)
    summary["sharpe_ratio_steps"] = _step_sharpe(equity, tf_hours)
    return summary


def _step_sharpe(equity: np.ndarray, timeframe_hours: float) -> Optional[float]:
    """Per-step Sharpe annualized by the bar timeframe (252 trading
    days x 24h / bar hours steps per year)."""
    rets = np.diff(equity) / equity[:-1]
    if rets.size < 2 or rets.std(ddof=1) == 0:
        return None
    steps_per_year = 252.0 * 24.0 / max(timeframe_hours, 1e-9)
    return float(rets.mean() / rets.std(ddof=1) * np.sqrt(steps_per_year))


def eval_policy_from_config(config: Dict[str, Any]) -> Dict[str, Any]:
    """CLI driver_mode=policy: load the checkpointed policy and run a
    greedy evaluation episode (shared skeleton:
    train/common.py eval_checkpointed_policy — honors the checkpoint's
    recorded architecture and the out-of-sample keys)."""
    from gymfx_tpu.train.common import (
        build_train_eval_envs,
        eval_checkpointed_policy,
    )

    def resolve(meta, cfg):
        if not cfg.get("policy") and meta.get("policy"):
            cfg["policy"] = meta["policy"]
            cfg.setdefault("policy_kwargs", meta.get("policy_kwargs") or {})

    return eval_checkpointed_policy(
        config,
        build_envs=build_train_eval_envs,
        make_trainer=lambda env, cfg: PPOTrainer(env, ppo_config_from(cfg)),
        evaluate_fn=lambda tr, params, steps: evaluate(tr, params, steps=steps),
        resolve_policy=resolve,
    )


def train_from_config(config: Dict[str, Any]) -> Dict[str, Any]:
    """CLI mode=training entry: train PPO, optionally checkpoint,
    return a summary merging training metrics and greedy-eval metrics.

    With ``elastic_resume`` set, the run routes through the elastic
    auto-resume controller (parallel/elastic.py): device loss re-plans
    the mesh over survivors and resumes from the last digest-verified
    checkpoint; unset, this call IS :func:`_train_from_config`."""
    from gymfx_tpu.parallel.elastic import elastic_entry

    return elastic_entry(
        _train_from_config, config,
        must_divide=(int(config.get("num_envs", 256) or 256),),
    )


def _train_from_config(config: Dict[str, Any]) -> Dict[str, Any]:
    from gymfx_tpu.parallel import mesh_from_config, validate_batch_axis
    from gymfx_tpu.train.common import build_train_eval_envs

    env, eval_env = build_train_eval_envs(config)
    # chaos runs: the fault_profile knob contaminates the TRAINING feed
    # before the trainer closes over it (eval data stays clean so the
    # guard's effect is measurable)
    from gymfx_tpu.resilience.faults import (
        apply_fault_profile_to_market_data,
        parse_fault_profile,
    )

    profile = parse_fault_profile(config.get("fault_profile"))
    if profile["nan_bars"] or profile["inf_bars"] or profile.get("scengen"):
        env.data = apply_fault_profile_to_market_data(env.data, profile)
    from gymfx_tpu.train.common import resolve_minibatch_scheme

    resolve_minibatch_scheme(
        config, int(config.get("num_envs", 256) or 256),
        int(config.get("ppo_minibatches", 4)),
    )
    pcfg = ppo_config_from(config)
    mesh = mesh_from_config(config)
    validate_batch_axis(mesh, pcfg.n_envs, "num_envs")
    trainer = PPOTrainer(env, pcfg, mesh=mesh)
    total = int(config.get("train_total_steps", 1_000_000))
    from gymfx_tpu.train.checkpoint import resume_from_config

    # full-state checkpoints continue the exact trajectory (opt moments,
    # env batch, RNG); params-only ones warm-start
    resume_state, resume_params, resume_step = resume_from_config(
        config, trainer, TrainState
    )
    ckpt_meta = {"policy": pcfg.policy,
                 "policy_kwargs": dict(pcfg.policy_kwargs)}
    from gymfx_tpu.telemetry import telemetry_from_config

    telemetry = telemetry_from_config(config)
    if telemetry is not None and telemetry.ledger is not None and (
            resume_state is not None or resume_params is not None):
        telemetry.ledger.record("checkpoint_restore", step=int(resume_step))
        if config.get("elastic_attempt"):
            # elastic re-entry: the restore above came back through the
            # digest-verified path and re-enters the SURVIVOR mesh plan
            telemetry.ledger.record(
                "mesh_resume", step=int(resume_step),
                attempt=int(config["elastic_attempt"]), verified=True,
                mesh_shape=dict(mesh.shape) if mesh is not None else None,
            )
    try:
        state, train_metrics = trainer.train(
            total, seed=int(config.get("seed", 0) or 0),
            initial_params=resume_params, initial_state=resume_state,
            checkpoint_dir=config.get("checkpoint_dir"),
            checkpoint_every=int(config.get("checkpoint_every", 0) or 0),
            step_offset=resume_step,
            checkpoint_metadata=ckpt_meta,
            max_consecutive_skips=int(
                config.get("guard_max_consecutive_skips", 10) or 0
            ),
            preempt_at=profile.get("preempt_at"),
            supersteps_per_dispatch=int(
                config.get("supersteps_per_dispatch", 1) or 1
            ),
            telemetry=telemetry,
            mesh_faults=profile.get("mesh") or (),
            checkpoint_keep=int(config.get("checkpoint_keep", 0) or 0),
        )
    except BaseException:
        # abort paths (preemption drill, divergence) still seal the run
        # ledger with its run_end row — the postmortem bundle was
        # already dumped by ResilientLoop before the raise
        if telemetry is not None:
            telemetry.close()
        raise
    if telemetry is not None and telemetry.sink is not None:
        telemetry.sink.append({
            "kind": "metrics_snapshot", "algo": "ppo",
            "registry": telemetry.registry.snapshot(),
        })
    if telemetry is not None:
        telemetry.close()

    # out-of-sample: greedy episode on bars the agent never trained on
    # (BASELINE metric 2 made scientifically meaningful); the in-sample
    # numbers ride along for the generalization gap
    from gymfx_tpu.train.common import labeled_eval_summary

    summary = labeled_eval_summary(
        lambda e: evaluate(
            trainer if e is None else PPOTrainer(e, pcfg), state.params
        ),
        env, eval_env,
    )
    summary["train_metrics"] = train_metrics
    if mesh is not None:
        summary["mesh_shape"] = dict(mesh.shape)

    ckpt_dir = config.get("checkpoint_dir")
    if ckpt_dir:
        from gymfx_tpu.train.checkpoint import save_checkpoint

        # cumulative step count: orbax silently skips saving a step that
        # already exists, so a resumed run must advance past the loaded
        # step; a periodic auto-checkpoint that already landed on the
        # final step makes this save redundant
        final_step = resume_step + train_metrics["total_env_steps"]
        if train_metrics.get("last_checkpoint_step") != final_step:
            save_checkpoint(
                ckpt_dir, state._asdict(),
                step=final_step,
                metadata={"policy": pcfg.policy,
                          "policy_kwargs": dict(pcfg.policy_kwargs)},
                params=state.params,
                keep=int(config.get("checkpoint_keep", 0) or 0),
                protect=(int(resume_step),),
            )
        summary["checkpoint_dir"] = str(ckpt_dir)
    return summary
