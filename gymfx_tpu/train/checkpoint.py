"""Checkpoint/resume via orbax — new capability (the reference has no
training checkpointing; closest mechanisms are action replay and config
save/restore, SURVEY.md §5.4).

Format ("composite"): two orbax items per step —
  state   the trainer's FULL train state (params + optimizer state +
          env batch + RNG), so a resumed run continues the exact
          trajectory an uninterrupted run would have produced;
  params  the policy params alone, so evaluation restores them without
          paying the I/O of the whole train state.
``metadata.json`` records the policy architecture and the state format.
Legacy single-item checkpoints (round-2 "params" format, PBT
best-member saves) load through the same functions.

Zero-size leaves (e.g. a (N, W, 0) feature window when no feature
columns are configured) cannot be stored by orbax; they are masked with
a placeholder at save and rebuilt at load — from the template when one
is given, else from the ``empty_leaves_<step>.json`` sidecar.

Integrity: every save writes a ``digest_<step>.json`` sidecar holding a
sha256 over the step directory's file names and bytes (and all JSON
sidecars are written atomically: tmp file + ``os.replace``).  A restore
verifies the digest first; a torn or bit-rotted step is logged loudly
and skipped in favor of the newest step that still verifies.  Steps
without a digest sidecar (saves predating this format) are accepted
unchanged.
"""
from __future__ import annotations

import hashlib
import json
import logging
import math
import os
import tempfile
import warnings
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

logger = logging.getLogger(__name__)


def _atomic_write_text(target: Path, text: str) -> None:
    """Write-then-rename so a crash mid-write can never leave a torn
    sidecar next to a valid checkpoint (os.replace is atomic on POSIX
    within one filesystem, and the tmp file lives in the target dir)."""
    fd, tmp = tempfile.mkstemp(
        dir=str(target.parent), prefix=f".{target.name}.", suffix=".tmp"
    )
    try:
        with os.fdopen(fd, "w") as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, target)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _digest_step_dir(path: Path, step: int) -> Optional[Dict[str, Any]]:
    """sha256 over the step directory's sorted relative file names and
    contents — torn/partial files change the digest directly, with no
    dependency on orbax's restore or casting semantics."""
    step_dir = path / str(int(step))
    if not step_dir.is_dir():
        return None
    h = hashlib.sha256()
    n_files = 0
    for f in sorted(p for p in step_dir.rglob("*") if p.is_file()):
        h.update(str(f.relative_to(step_dir)).encode())
        h.update(b"\0")
        with f.open("rb") as fh:
            for chunk in iter(lambda: fh.read(1 << 20), b""):
                h.update(chunk)
        h.update(b"\0")
        n_files += 1
    return {"algo": "sha256", "digest": h.hexdigest(), "files": n_files}


def _digest_sidecar(path: Path, step: int) -> Path:
    return path / f"digest_{int(step)}.json"


def verify_checkpoint_step(directory: str, step: int) -> bool:
    """Recompute the step directory's digest against its sidecar.

    True when they match or when no sidecar exists (legacy saves carry
    no digest and are accepted); False — with a loud log — on any
    mismatch, including a recorded digest whose step dir is gone."""
    path = Path(directory).resolve()
    sidecar = _digest_sidecar(path, step)
    if not sidecar.exists():
        return True
    try:
        recorded = json.loads(sidecar.read_text())
    except (OSError, ValueError) as exc:
        logger.error(
            "checkpoint step %d under %s has an unreadable digest sidecar "
            "(%s); treating the step as corrupt", step, path, exc,
        )
        return False
    actual = _digest_step_dir(path, step)
    if actual is None or actual["digest"] != recorded.get("digest"):
        logger.error(
            "checkpoint step %d under %s FAILED integrity verification "
            "(stored sha256 %s, recomputed %s) — the step is torn or "
            "bit-rotted and will be skipped",
            step, path, recorded.get("digest"),
            actual["digest"] if actual else "<step dir missing>",
        )
        return False
    return True


class CheckpointIntegrityError(RuntimeError):
    """A checkpoint step failed sha256 digest verification — torn write,
    bit rot, or tampering.  Raised by :func:`verify_checkpoint` (the
    deployer's pre-promote gate) so a corrupt candidate is rejected
    BEFORE any weights are loaded or routing is touched."""


def _list_steps(path: Path) -> List[int]:
    if not path.is_dir():
        return []
    return sorted(
        int(p.name) for p in path.iterdir()
        if p.is_dir() and p.name.isdigit()
    )


def verify_checkpoint(
    directory: str, step: Optional[int] = None
) -> Tuple[int, Optional[str]]:
    """Digest-verify one checkpoint step WITHOUT restoring any tensors.

    ``step=None`` picks the newest step under ``directory``.  Returns
    ``(step, digest)`` on success — ``digest`` is the recorded sha256
    hex, or None for a legacy save with no sidecar (accepted, per the
    restore-path contract).  Raises :class:`CheckpointIntegrityError`
    when the recomputed digest disagrees with the sidecar, and
    ``FileNotFoundError`` when the step (or any step) is absent.
    """
    path = Path(directory).resolve()
    steps = _list_steps(path)
    if step is None:
        if not steps:
            raise FileNotFoundError(f"no checkpoint steps under {path}")
        step = steps[-1]
    step = int(step)
    if step not in steps:
        raise FileNotFoundError(
            f"checkpoint step {step} not found under {path} "
            f"(available: {steps or 'none'})"
        )
    sidecar = _digest_sidecar(path, step)
    if not sidecar.exists():
        return step, None
    if not verify_checkpoint_step(str(path), step):
        raise CheckpointIntegrityError(
            f"checkpoint step {step} under {path} failed sha256 digest "
            f"verification — refusing to use it"
        )
    recorded = json.loads(sidecar.read_text())
    return step, str(recorded.get("digest"))


def audit_checkpoint_tree(directory: str) -> List[Dict[str, Any]]:
    """Digest-audit every step under a checkpoint directory — no orbax
    restore, no tensor I/O beyond hashing bytes.  One row per step (and
    per ORPHANED digest sidecar whose step dir is gone):

        {"step", "verified", "legacy", "digest", "files", "bytes"}

    ``legacy`` marks steps saved before the digest format (no sidecar;
    verified=True by the restore-path contract).  ``bytes`` is the
    step's on-disk footprint including its sidecars — what retention
    (:func:`prune_checkpoints`) would reclaim.  The operator CLI is
    ``tools/checkpoint_audit.py``."""
    path = Path(directory).resolve()
    steps = _list_steps(path)
    sidecar_steps = set()
    if path.is_dir():
        for f in path.glob("digest_*.json"):
            suffix = f.stem.split("_", 1)[-1]
            if suffix.isdigit():
                sidecar_steps.add(int(suffix))
    rows: List[Dict[str, Any]] = []
    for step in sorted(set(steps) | sidecar_steps):
        sidecar = _digest_sidecar(path, step)
        if not sidecar.exists():
            rows.append({
                "step": step, "verified": True, "legacy": True,
                "digest": None, "files": None,
                "bytes": _step_bytes(path, step),
            })
            continue
        try:
            recorded = json.loads(sidecar.read_text())
        except (OSError, ValueError):
            recorded = {}
        rows.append({
            "step": step,
            "verified": verify_checkpoint_step(str(path), step),
            "legacy": False,
            "digest": recorded.get("digest"),
            "files": recorded.get("files"),
            "bytes": _step_bytes(path, step),
        })
    return rows


def _step_bytes(path: Path, step: int) -> int:
    """Disk footprint of one step: the step directory's files plus the
    digest/empty-leaves sidecars that belong to it."""
    total = 0
    step_dir = path / str(int(step))
    if step_dir.is_dir():
        total += sum(
            f.stat().st_size for f in step_dir.rglob("*") if f.is_file()
        )
    for sidecar in (
        _digest_sidecar(path, step),
        path / f"empty_leaves_{int(step)}.json",
    ):
        if sidecar.exists():
            total += sidecar.stat().st_size
    return total


def prune_checkpoints(
    directory: str,
    keep: int,
    protect: Tuple[int, ...] = (),
) -> List[Dict[str, Any]]:
    """Newest-N retention: delete every checkpoint step older than the
    newest ``keep``, SIDECARS INCLUDED (``digest_<step>.json`` and
    ``empty_leaves_<step>.json`` go with their step — an orphaned digest
    would read as corruption in the audit).

    ``keep <= 0`` keeps everything (the default posture).  Steps in
    ``protect`` are never pruned regardless of age — the resume entry
    step stays restorable while the resumed run is still writing newer
    checkpoints on top of it.  Returns one ``{"step", "bytes"}`` row per
    pruned step (bytes as measured before deletion).
    """
    import shutil

    if int(keep) <= 0:
        return []
    path = Path(directory).resolve()
    steps = _list_steps(path)
    keep_set = set(steps[-int(keep):]) | {int(s) for s in protect}
    pruned: List[Dict[str, Any]] = []
    for step in steps:
        if step in keep_set:
            continue
        size = _step_bytes(path, step)
        shutil.rmtree(path / str(step), ignore_errors=True)
        for sidecar in (
            _digest_sidecar(path, step),
            path / f"empty_leaves_{step}.json",
        ):
            try:
                sidecar.unlink()
            except OSError:
                pass
        pruned.append({"step": step, "bytes": size})
        logger.info(
            "pruned checkpoint step %d under %s (%d bytes, keep=%d)",
            step, path, size, keep,
        )
    return pruned


def _is_empty(x: Any) -> bool:
    return hasattr(x, "shape") and math.prod(x.shape) == 0


def _mask_empty(tree: Any) -> Any:
    return jax.tree.map(
        lambda x: np.zeros((1,), np.float32) if _is_empty(x) else x, tree
    )


def _unmask_empty(template: Any, restored: Any) -> Any:
    return jax.tree.map(
        lambda t, r: np.zeros(t.shape, t.dtype) if _is_empty(t) else r,
        template,
        restored,
    )


def _empty_record(tree: Any, prefix: Tuple = ()) -> List[Dict[str, Any]]:
    """Paths (as orbax's raw-restored dict/list structure addresses
    them: NamedTuples become dicts keyed by field) + shape/dtype of
    every zero-size leaf."""
    if isinstance(tree, dict):
        items = tree.items()
    elif hasattr(tree, "_asdict"):  # NamedTuple
        items = tree._asdict().items()
    elif isinstance(tree, (list, tuple)):
        items = enumerate(tree)
    else:
        if _is_empty(tree):
            return [{
                "path": list(prefix),
                "shape": list(tree.shape),
                "dtype": str(np.dtype(tree.dtype)),
            }]
        return []
    out: List[Dict[str, Any]] = []
    for k, v in items:
        out.extend(_empty_record(v, prefix + (k,)))
    return out


def _apply_empty_record(tree: Any, records: List[Dict[str, Any]]) -> Any:
    for rec in records:
        node = tree
        for k in rec["path"][:-1]:
            node = node[k]
        node[rec["path"][-1]] = np.zeros(
            tuple(rec["shape"]), np.dtype(rec["dtype"])
        )
    return tree


def save_checkpoint(
    directory: str,
    tree: Any,
    step: int = 0,
    metadata: Optional[Dict[str, Any]] = None,
    params: Optional[Any] = None,
    keep: int = 0,
    protect: Tuple[int, ...] = (),
) -> str:
    """Save a checkpoint at ``step``.

    With ``params`` given, ``tree`` is a full train-state dict and the
    two are stored as separate items (composite format); without, a
    bare pytree (params-only saves).  Orbax silently skips a step that
    already exists — in that case the metadata is left untouched too,
    so it can never describe a tree that was not actually stored.

    ``keep > 0`` applies newest-N retention AFTER the new step lands
    (:func:`prune_checkpoints`; ``protect`` steps are exempt), so the
    directory never transiently holds fewer than ``keep`` good steps.
    """
    path = Path(directory).resolve()
    path.mkdir(parents=True, exist_ok=True)
    with ocp.CheckpointManager(path) as mngr:
        if int(step) in set(mngr.all_steps()):
            warnings.warn(
                f"checkpoint step {step} already exists under {path}; "
                "orbax skips the save — advance the step to persist",
                stacklevel=2,
            )
            return str(path)
        if params is not None:
            metadata = {**(metadata or {}), "state_format": "composite"}
            empties = {"state": _empty_record(tree),
                       "params": _empty_record(params)}
            mngr.save(
                int(step),
                args=ocp.args.Composite(
                    state=ocp.args.StandardSave(_mask_empty(tree)),
                    params=ocp.args.StandardSave(_mask_empty(params)),
                ),
            )
        else:
            empties = {"default": _empty_record(tree)}
            mngr.save(int(step), args=ocp.args.StandardSave(_mask_empty(tree)))
        mngr.wait_until_finished()
    if any(empties.values()):
        _atomic_write_text(
            path / f"empty_leaves_{int(step)}.json", json.dumps(empties)
        )
    digest = _digest_step_dir(path, int(step))
    if digest is not None:
        _atomic_write_text(
            _digest_sidecar(path, int(step)), json.dumps(digest)
        )
    if metadata is not None:
        _atomic_write_text(
            path / "metadata.json", json.dumps(metadata, indent=2)
        )
    if int(keep) > 0:
        prune_checkpoints(str(path), keep, protect=protect)
    return str(path)


def read_metadata(directory: str) -> Dict[str, Any]:
    meta = Path(directory).resolve() / "metadata.json"
    if meta.exists():
        return json.loads(meta.read_text())
    return {}


def load_checkpoint(directory: str, template: Optional[Any] = None) -> Tuple[Any, int]:
    """Load the latest checkpoint's main tree (the full train state for
    composite checkpoints, the bare tree otherwise); returns (tree, step).

    With ``template`` (a pytree of arrays or ShapeDtypeStructs) the
    restore is validated against it; without, the raw stored tree comes
    back (NamedTuples as plain dicts — fine for params consumers).
    """
    composite = read_metadata(directory).get("state_format") == "composite"
    return _restore_item(directory, "state" if composite else None, template)


def load_params(directory: str, template: Optional[Any] = None) -> Tuple[Any, int]:
    """Policy params from a checkpoint of any format, restoring ONLY the
    params item when the format allows (composite), so evaluation never
    pays the full-train-state I/O."""
    fmt = read_metadata(directory).get("state_format")
    if fmt == "composite":
        return _restore_item(directory, "params", template)
    if fmt == "params":
        return _restore_item(directory, None, template)
    # legacy/unknown format: restore raw FIRST (a params template would
    # mismatch a full-state tree before the subtree pick could run),
    # then pick the params subtree if the tree is a full train state
    # (PPO stores "params"; IMPALA "learner_params")
    tree, step = _restore_item(directory, None, None)
    if isinstance(tree, dict) and "opt_state" in tree:
        for key in ("params", "learner_params"):
            if key in tree:
                tree = tree[key]
                break
        else:
            raise KeyError(
                f"train_state checkpoint in {directory} has no params "
                f"entry (keys: {sorted(tree)})"
            )
    if template is not None:
        tree = _validate_like(template, tree, directory)
    return tree, step


# EnvState fields added AFTER a release that shipped full-state
# checkpoints, with their backfill default: restores of older composite
# checkpoints synthesize these instead of failing (each entry documents
# the round that added the field).
_MIGRATED_FIELDS = {
    "pending_forced",      # r4: venue-forced liquidation flag (False at rest)
    "termination_reason",  # r4: explicit TERMINATION_* code (0 = running)
}


def _is_structure_mismatch(exc: BaseException) -> bool:
    """Whether a template-validated restore failure looks like a tree-
    STRUCTURE mismatch (rebuildable from a raw restore) rather than an
    I/O / storage fault (never rebuildable — retrying with no template
    would only mask the real error).

    Orbax and flax wrap structure mismatches in their own exception
    types (which vary across versions), so beyond the stdlib trio the
    check is by module + message rather than by class identity."""
    if isinstance(exc, OSError):
        # includes FileNotFoundError — cold-start detection upstream
        # (resume_from_config) depends on it propagating untouched
        return False
    if isinstance(exc, (ValueError, KeyError, TypeError)):
        return True
    module = type(exc).__module__ or ""
    if module.split(".")[0] in ("orbax", "flax", "jax"):
        msg = str(exc).lower()
        return any(
            marker in msg
            for marker in (
                "structure", "mismatch", "does not match", "not match",
                "pytree", "missing field", "unexpected key", "custom node",
            )
        )
    return False


def _rebuild_like(template: Any, raw: Any, path: str = "") -> Any:
    """Rebuild ``raw`` (orbax's dict/list structure) into the template's
    NamedTuple/dict/tuple structure, synthesizing zero-leaves for fields
    in ``_MIGRATED_FIELDS`` that the stored tree predates.  Leaves are
    shape-checked and cast to the template dtype (like _check_leaf)."""
    if hasattr(template, "_asdict"):
        fields = template._asdict()
        vals = {}
        for k, tv in fields.items():
            if isinstance(raw, dict) and k in raw:
                vals[k] = _rebuild_like(tv, raw[k], f"{path}.{k}")
            elif k in _MIGRATED_FIELDS and hasattr(tv, "shape"):
                vals[k] = np.zeros(tv.shape, np.dtype(tv.dtype))
            else:
                raise KeyError(
                    f"checkpoint tree is missing field {path}.{k} and it "
                    "is not a known migrated field"
                )
        return type(template)(**vals)
    if isinstance(template, dict):
        return {
            k: _rebuild_like(tv, raw[k], f"{path}.{k}")
            for k, tv in template.items()
        }
    if isinstance(template, (list, tuple)):
        if len(raw) != len(template):
            raise ValueError(
                f"checkpoint tree at {path or '<root>'} has "
                f"{len(raw)} entries, expected {len(template)}"
            )
        return type(template)(
            _rebuild_like(t, r, f"{path}[{i}]")
            for i, (t, r) in enumerate(zip(template, raw))
        )
    if _is_empty(template):
        return np.zeros(template.shape, np.dtype(template.dtype))
    if hasattr(template, "shape") and tuple(template.shape) != tuple(np.shape(raw)):
        raise ValueError(
            f"stored leaf {path} shape {tuple(np.shape(raw))} != expected "
            f"{tuple(template.shape)}"
        )
    return np.asarray(raw, getattr(template, "dtype", None))


def load_train_state(directory: str, trainer: Any, state_cls: Any):
    """Resume helper shared by the trainers: returns
    ``(initial_state, initial_params, step)`` — a full train state when
    the checkpoint carries one, else params for a warm start.

    ``trainer`` must expose ``init_state_from_key`` (the unsharded
    template source); ``state_cls`` is its train-state NamedTuple.
    """
    if read_metadata(directory).get("state_format") in ("composite", "train_state"):
        template_nt = jax.eval_shape(
            trainer.init_state_from_key, jax.random.PRNGKey(0)
        )
        try:
            restored, step = load_checkpoint(
                directory, template=template_nt._asdict()
            )
            return state_cls(**restored), None, step
        except Exception as exc:
            if not _is_structure_mismatch(exc):
                raise
            # structure mismatch only: the stored tree may predate
            # newly-added EnvState fields (e.g. pending_forced, r4) —
            # raw-restore and rebuild with the documented backfills; a
            # genuine mismatch still fails loudly inside _rebuild_like.
            # I/O or orbax sharding errors propagate untouched so they
            # don't surface as confusing rebuild errors.
            raw, step = load_checkpoint(directory, template=None)
            return _rebuild_like(template_nt, raw), None, step
    # params-only checkpoint (round-2 format / PBT best member)
    pfield = "params" if "params" in state_cls._fields else "learner_params"
    ptpl = jax.eval_shape(
        lambda k: getattr(trainer.init_state_from_key(k), pfield),
        jax.random.PRNGKey(0),
    )
    params, step = load_params(directory, template=ptpl)
    return None, params, step


def resume_from_config(config: Dict[str, Any], trainer: Any, state_cls: Any):
    """The trainers' shared --resume_training entry: returns
    ``(initial_state, initial_params, resume_step)``, all falsy when the
    config does not ask for a resume or the directory is empty."""
    ckpt_dir = config.get("checkpoint_dir")
    if not (ckpt_dir and config.get("resume_training")):
        return None, None, 0
    try:
        return load_train_state(str(ckpt_dir), trainer, state_cls)
    except FileNotFoundError:
        return None, None, 0  # cold start, empty dir


def _validate_like(template: Any, tree: Any, directory: str) -> Any:
    """Shape/structure check of a raw-restored tree against the caller's
    template (a clear load-time error instead of an opaque one later);
    rebuilds masked empty leaves along the way."""
    try:
        return jax.tree.map(
            lambda t, r: _check_leaf(t, r, directory), template, tree
        )
    except ValueError as exc:
        raise ValueError(
            f"checkpoint in {directory} does not match the configured "
            f"policy architecture: {exc}"
        ) from None


def _check_leaf(t: Any, r: Any, directory: str) -> Any:
    if _is_empty(t):
        return np.zeros(t.shape, t.dtype)
    if tuple(t.shape) != tuple(np.shape(r)):
        raise ValueError(
            f"stored leaf shape {tuple(np.shape(r))} != expected {tuple(t.shape)}"
        )
    # cast to the template dtype (what StandardRestore(template) does on
    # the validated paths) so a float32 legacy save feeds a bfloat16
    # policy as bfloat16, not as a silent promotion
    return np.asarray(r, getattr(t, "dtype", None))


def _restore_item(
    directory: str, item: Optional[str], template: Optional[Any]
) -> Tuple[Any, int]:
    path = Path(directory).resolve()
    with ocp.CheckpointManager(path) as mngr:
        steps = sorted(int(s) for s in mngr.all_steps())
        if not steps:
            raise FileNotFoundError(f"no checkpoint found under {path}")
        # newest step whose content digest still verifies; a torn latest
        # step falls back to the previous valid one instead of feeding a
        # half-written tree into the restore
        step = next(
            (s for s in reversed(steps) if verify_checkpoint_step(path, s)),
            None,
        )
        if step is None:
            raise RuntimeError(
                f"every checkpoint step under {path} failed integrity "
                f"verification (steps checked: {steps}); refusing to "
                "restore corrupt state"
            )
        if step != steps[-1]:
            logger.error(
                "restoring checkpoint step %d under %s — newer step(s) "
                "%s failed integrity verification",
                step, path, [s for s in steps if s > step],
            )
        if item is not None:
            args = (
                ocp.args.StandardRestore(_mask_empty(template))
                if template is not None
                else ocp.args.StandardRestore()
            )
            restored = mngr.restore(
                step, args=ocp.args.Composite(**{item: args})
            )[item]
        elif template is not None:
            restored = mngr.restore(
                step, args=ocp.args.StandardRestore(_mask_empty(template))
            )
        else:
            # argless raw restore: newer orbax refuses to infer handlers
            # for stored items, so name them explicitly from the step
            # directory's item subdirs ("default" = single-item save)
            items = sorted(
                p.name
                for p in (path / str(step)).iterdir()
                if p.is_dir() and not p.name.startswith("_")
            )
            if items == ["default"] or not items:
                restored = mngr.restore(
                    step, args=ocp.args.StandardRestore()
                )
            else:
                restored = mngr.restore(
                    step,
                    args=ocp.args.Composite(
                        **{n: ocp.args.StandardRestore() for n in items}
                    ),
                )
    if template is not None:
        restored = _unmask_empty(template, restored)
    else:
        sidecar = path / f"empty_leaves_{int(step)}.json"
        if sidecar.exists():
            records = json.loads(sidecar.read_text()).get(item or "default", [])
            restored = _apply_empty_record(restored, records)
    return restored, int(step)
