"""Checkpoint/resume via orbax — new capability (the reference has no
training checkpointing; closest mechanisms are action replay and config
save/restore, SURVEY.md §5.4)."""
from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, Optional, Tuple

import orbax.checkpoint as ocp


def save_checkpoint(
    directory: str,
    params: Any,
    step: int = 0,
    metadata: Optional[Dict[str, Any]] = None,
) -> str:
    """Save params (+ a metadata.json describing e.g. which policy
    architecture produced them, so evaluation can rebuild the right
    template without the user re-passing --policy)."""
    path = Path(directory).resolve()
    path.mkdir(parents=True, exist_ok=True)
    with ocp.CheckpointManager(path) as mngr:
        mngr.save(int(step), args=ocp.args.StandardSave(params))
        mngr.wait_until_finished()
    if metadata is not None:
        (path / "metadata.json").write_text(json.dumps(metadata, indent=2))
    return str(path)


def read_metadata(directory: str) -> Dict[str, Any]:
    meta = Path(directory).resolve() / "metadata.json"
    if meta.exists():
        return json.loads(meta.read_text())
    return {}


def load_checkpoint(directory: str, template: Optional[Any] = None) -> Tuple[Any, int]:
    """Load the latest checkpoint; returns (params, step)."""
    path = Path(directory).resolve()
    with ocp.CheckpointManager(path) as mngr:
        step = mngr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found under {path}")
        if template is not None:
            params = mngr.restore(step, args=ocp.args.StandardRestore(template))
        else:
            params = mngr.restore(step)
    return params, int(step)
