"""Checkpoint/resume via orbax — new capability (the reference has no
training checkpointing; closest mechanisms are action replay and config
save/restore, SURVEY.md §5.4)."""
from __future__ import annotations

from pathlib import Path
from typing import Any, Optional, Tuple

import orbax.checkpoint as ocp


def save_checkpoint(directory: str, params: Any, step: int = 0) -> str:
    path = Path(directory).resolve()
    path.mkdir(parents=True, exist_ok=True)
    with ocp.CheckpointManager(path) as mngr:
        mngr.save(int(step), args=ocp.args.StandardSave(params))
        mngr.wait_until_finished()
    return str(path)


def load_checkpoint(directory: str, template: Optional[Any] = None) -> Tuple[Any, int]:
    """Load the latest checkpoint; returns (params, step)."""
    path = Path(directory).resolve()
    with ocp.CheckpointManager(path) as mngr:
        step = mngr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found under {path}")
        if template is not None:
            params = mngr.restore(step, args=ocp.args.StandardRestore(template))
        else:
            params = mngr.restore(step)
    return params, int(step)
