"""IMPALA actor-learner with V-trace off-policy correction.

New capability (BASELINE.json config 4: recurrent LSTM policy, IMPALA
async actor-learner over ICI).  Single-program SPMD formulation: the
"actors" are the vmapped env batch stepping with a STALE copy of the
policy (synced every ``sync_every`` learner updates — that staleness is
exactly what V-trace corrects), the learner consumes whole trajectory
segments.  On a pod the same program shards actors over the mesh 'data'
axis and the gradient all-reduce rides ICI; across hosts the mesh
extends over DCN — no parameter server, no gRPC queues.

Unlike the PPO-LSTM shortcut (ppo.py), the learner REPLAYS the segment
through the policy with the stored initial carry, so recurrent credit
assignment is exact over the segment.

V-trace (Espeholt et al. 2018):
  delta_t = rho_t (r_t + gamma_t V(x_{t+1}) - V(x_t))
  vs_t    = V(x_t) + delta_t + gamma_t c_t (vs_{t+1} - V(x_{t+1}))
  pg_adv  = rho_t (r_t + gamma_t vs_{t+1} - V(x_t))
with rho_t = min(rho_bar, pi/mu), c_t = min(c_bar, pi/mu).
"""
from __future__ import annotations

import time
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from gymfx_tpu.core import env as env_core
from gymfx_tpu.core.runtime import Environment
from gymfx_tpu.parallel.runtime import ShardedRuntime, StatePlan
from gymfx_tpu.train.common import masked_reset
from gymfx_tpu.train.policies import (
    flatten_obs,
    gaussian_entropy,
    is_token_policy,
    make_obs_spec,
    make_trainer_policy,
    normal_logp,
    sample_normal,
    tokens_from_obs,
)


class ImpalaConfig(NamedTuple):
    n_envs: int = 256
    unroll: int = 64
    gamma: float = 0.99
    rho_bar: float = 1.0
    c_bar: float = 1.0
    lr: float = 3e-4
    ent_coef: float = 0.01
    vf_coef: float = 0.5
    max_grad_norm: float = 0.5
    sync_every: int = 4          # actor params refresh period (staleness)
    policy: str = "lstm"
    policy_dtype: Any = jnp.float32
    policy_kwargs: Tuple[Tuple[str, Any], ...] = ()
    # trajectory-obs storage dtype (resolved like PPO's:
    # train/ppo.resolve_collect_dtype — never wider than policy_dtype)
    collect_dtype: Any = jnp.float32
    # non-finite guard (resilience/guards.py): skip the whole learner
    # update when loss/grads go non-finite and quarantine-reset envs
    # whose segment produced NaN/inf (see train/ppo.py)
    nonfinite_guard: bool = True
    # Adam first-moment storage dtype — resolved through the shared
    # master-weight rule (train/ppo.resolve_optimizer_state_dtype)
    opt_state_dtype: Any = jnp.float32
    # software-pipelined superstep driver (see train/ppo.PPOConfig);
    # for IMPALA the one-update-stale rollout params are the NATIVE
    # regime — V-trace corrects actor/learner staleness by design
    superstep_overlap: bool = False


def _resolve_collect_dtype(config, policy_dtype):
    # ONE definition of the collect-dtype resolution (train/ppo.py);
    # imported lazily to keep this module import-light
    from gymfx_tpu.train.ppo import resolve_collect_dtype

    return resolve_collect_dtype(config, policy_dtype)


def _resolve_opt_state_dtype(config):
    from gymfx_tpu.train.ppo import resolve_optimizer_state_dtype

    return resolve_optimizer_state_dtype(config)


def impala_config_from(config: Dict[str, Any]) -> ImpalaConfig:
    dt = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
        str(config.get("policy_dtype", "float32"))
    ]
    return ImpalaConfig(
        n_envs=int(config.get("num_envs", 256) or 256),
        unroll=int(config.get("impala_unroll", 64)),
        gamma=float(config.get("gamma", 0.99)),
        rho_bar=float(config.get("vtrace_rho_bar", 1.0)),
        c_bar=float(config.get("vtrace_c_bar", 1.0)),
        lr=float(config.get("learning_rate", 3e-4)),
        ent_coef=float(config.get("entropy_coef", 0.01)),
        vf_coef=float(config.get("value_coef", 0.5)),
        max_grad_norm=float(config.get("max_grad_norm", 0.5)),
        sync_every=int(config.get("impala_sync_every", 4)),
        policy=str(config.get("policy") or "lstm"),
        policy_dtype=dt,
        policy_kwargs=tuple(
            (k, tuple(v) if isinstance(v, list) else v)
            for k, v in (config.get("policy_kwargs") or {}).items()
        ),
        collect_dtype=_resolve_collect_dtype(config, dt),
        nonfinite_guard=bool(config.get("nonfinite_guard", True)),
        opt_state_dtype=_resolve_opt_state_dtype(config),
        superstep_overlap=bool(config.get("superstep_overlap", False)),
    )


class ImpalaState(NamedTuple):
    learner_params: Any
    actor_params: Any
    opt_state: Any
    env_states: Any
    obs_vec: Any
    policy_carry: Any
    rng: Any
    updates_since_sync: Any  # i32


class ImpalaTrainer:
    # shared placement plan (parallel/runtime.ShardedRuntime): learner
    # AND actor params are tensor-shard candidates, the sync counter
    # replicates with opt/rng, the env batch shards over 'data'
    STATE_PLAN = StatePlan(
        params=("learner_params", "actor_params"),
        replicated=("opt_state", "rng", "updates_since_sync"),
        batched=("env_states", "obs_vec", "policy_carry"),
    )

    def __init__(self, env: Environment, icfg: ImpalaConfig, mesh: Optional[Any] = None):
        self.env = env
        self.icfg = icfg
        self.mesh = mesh
        self.runtime = None if mesh is None else ShardedRuntime(mesh)
        # V-trace is distribution-agnostic: continuous mode swaps in the
        # Gaussian twin via the shared construction path (only the
        # log-prob and entropy terms change, train/policies.py)
        self._continuous = env.cfg.action_space_mode == "continuous"
        self.policy = make_trainer_policy(
            icfg.policy, continuous=self._continuous,
            dtype=icfg.policy_dtype, kwargs=dict(icfg.policy_kwargs),
            window=env.cfg.window_size,
        )
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(icfg.max_grad_norm),
            optax.adam(icfg.lr, mu_dtype=icfg.opt_state_dtype),
        )
        cfg, params = env.cfg, env.params
        if hasattr(env, "require_resident_data"):
            data = env.require_resident_data(
                "IMPALA training (random-access rollouts)"
            )
        else:
            data = env.data
        self._reset_state, reset_obs = env_core.reset(cfg, params, data)
        self._is_transformer = is_token_policy(icfg.policy)
        self._window = cfg.window_size
        # static obs layout, derived once (see PPOTrainer: the encode
        # hot path must not re-sort keys per call)
        self.obs_spec = make_obs_spec(reset_obs)
        self._reset_vec = self._encode(reset_obs)
        self._train_step = jax.jit(self._train_step_impl, donate_argnums=0)
        from gymfx_tpu.train.common import (
            make_train_many,
            make_train_many_overlapped,
            make_train_many_with_data,
        )

        # feed=curriculum: tape swaps at superstep boundaries with the
        # tape as a traced argument (see PPOTrainer)
        self.curriculum = getattr(env, "curriculum", None)
        if self.curriculum is not None and icfg.superstep_overlap:
            raise ValueError(
                "feed=curriculum cannot be combined with "
                "superstep_overlap: the pipelined driver issues rollout "
                "i+1 before update i, so a tape swap inside the dispatch "
                "would feed half a superstep from the wrong tape"
            )
        if self.curriculum is not None:
            self._train_step_data = jax.jit(
                self._train_step_impl, donate_argnums=0
            )
            self._train_many_data = make_train_many_with_data(
                self._train_step_impl
            )
        if icfg.superstep_overlap:
            # the update phase owns both param sets (learner gradients,
            # periodic actor sync) and the staleness counter
            self._train_many = make_train_many_overlapped(
                self._rollout_phase, self._update_phase,
                learner_fields=(
                    "learner_params", "actor_params", "opt_state",
                    "updates_since_sync",
                ),
            )
        else:
            self._train_many = make_train_many(self._train_step_impl)

    def _encode(self, obs):
        spec = getattr(self, "obs_spec", None)
        if self._is_transformer:
            return tokens_from_obs(obs, self._window, spec)
        return flatten_obs(obs, spec)

    def _forward(self, params, x, carry):
        if self.icfg.policy == "lstm":
            return self.policy.apply(params, x, carry)
        logits, value = self.policy.apply(params, x)
        return logits, value, carry

    # ------------------------------------------------------------------
    def init_state(self, seed: int = 0) -> ImpalaState:
        state = self.init_state_from_key(jax.random.PRNGKey(seed))
        if self.runtime is not None:
            state = self.runtime.place_state(state, self.STATE_PLAN)
        return state

    def init_state_from_key(self, rng) -> ImpalaState:
        """Key-based, unsharded init (traceable; also the resume-template
        shape source)."""
        rng, k = jax.random.split(rng)
        carry0 = self.policy.initial_carry(())
        if self.icfg.policy == "lstm":
            p = self.policy.init(k, self._reset_vec, carry0)
        else:
            p = self.policy.init(k, self._reset_vec)
        n = self.icfg.n_envs
        bcast = lambda x: jnp.broadcast_to(x, (n, *x.shape))  # noqa: E731
        state = ImpalaState(
            learner_params=p,
            # distinct buffers: learner and actor trees are both donated
            # by the jitted step, and XLA rejects donating one buffer twice
            actor_params=jax.tree.map(jnp.copy, p),
            opt_state=self.optimizer.init(p),
            env_states=jax.tree.map(bcast, self._reset_state),
            obs_vec=bcast(self._reset_vec),
            policy_carry=jax.tree.map(bcast, carry0),
            rng=rng,
            updates_since_sync=jnp.zeros((), jnp.int32),
        )
        return state

    # ------------------------------------------------------------------
    def _rollout(self, actor_params, env_states, obs_vec, pcarry, rng,
                 data=None):
        cfg, eparams = self.env.cfg, self.env.params
        # data=None keeps the baked resident tape (bitwise-identical
        # default); an explicit tape (curriculum) is traced and supplies
        # its own in-graph reset (see PPOTrainer._rollout)
        explicit_data = data is not None
        if not explicit_data:
            data = self.env.data
        vstep = jax.vmap(env_core.step, in_axes=(None, None, None, 0, 0))
        vencode = jax.vmap(self._encode)
        fwd = jax.vmap(self._forward, in_axes=(None, 0, 0))
        carry0 = self.policy.initial_carry(())
        if explicit_data:
            reset_state, fresh_obs = env_core.reset(cfg, eparams, data)
            reset_vec = self._encode(fresh_obs)
        else:
            reset_state, reset_vec = self._reset_state, self._reset_vec

        continuous = self._continuous

        def body(carry, _):
            env_states, obs_vec, pcarry, rng = carry
            rng, k = jax.random.split(rng)
            dist, _value, pcarry2 = fwd(actor_params, obs_vec, pcarry)
            if continuous:
                mu, log_std = dist
                action = sample_normal(k, dist)
                logp = normal_logp(action, mu, log_std)
            else:
                logits = dist
                keys = jax.random.split(k, logits.shape[0])
                action = jax.vmap(jax.random.categorical)(keys, logits)
                logp = jnp.take_along_axis(
                    jax.nn.log_softmax(logits), action[:, None], axis=1
                )[:, 0]
            env_states2, obs2, reward, done, _ = vstep(
                cfg, eparams, data, env_states, action
            )
            obs_vec2 = vencode(obs2)
            env_states2 = masked_reset(done, reset_state, env_states2)
            obs_vec2 = masked_reset(done, reset_vec, obs_vec2)
            pcarry2 = masked_reset(done, carry0, pcarry2)
            out = dict(
                # obs stored in the resolved collect dtype (never wider
                # than the policy's entry cast — see
                # train/ppo.resolve_collect_dtype); halves the
                # learner-pass HBM buffer under bf16
                obs=obs_vec.astype(self.icfg.collect_dtype),
                action=action, mu_logp=logp,
                reward=reward.astype(jnp.float32), done=done,
            )
            return (env_states2, obs_vec2, pcarry2, rng), out

        (env_states, obs_vec, pcarry, rng), traj = jax.lax.scan(
            body, (env_states, obs_vec, pcarry, rng), None, length=self.icfg.unroll
        )
        return env_states, obs_vec, pcarry, rng, traj

    def _learner_replay(self, params, traj, init_carry, final_obs_vec):
        """Recompute logits/values over the segment with the LEARNER
        params, threading the true recurrent carry (reset on done)."""
        fwd = jax.vmap(self._forward, in_axes=(None, 0, 0))
        carry0 = self.policy.initial_carry(())

        def body(pcarry, x):
            obs, done = x
            logits, value, pcarry2 = fwd(params, obs, pcarry)
            pcarry2 = masked_reset(done, carry0, pcarry2)
            return pcarry2, (logits, value)

        pcarry, (logits, values) = jax.lax.scan(
            body, init_carry, (traj["obs"], traj["done"])
        )
        _, bootstrap, _ = fwd(params, final_obs_vec, pcarry)
        return logits, values, bootstrap

    def _vtrace(self, values, bootstrap, rewards, dones, rhos):
        g = self.icfg.gamma
        discounts = g * (1.0 - dones.astype(jnp.float32))
        cs = jnp.minimum(self.icfg.c_bar, rhos)
        clipped_rhos = jnp.minimum(self.icfg.rho_bar, rhos)
        values_next = jnp.concatenate([values[1:], bootstrap[None]], axis=0)
        deltas = clipped_rhos * (rewards + discounts * values_next - values)

        def body(acc, x):
            delta, discount, c = x
            acc = delta + discount * c * acc
            return acc, acc

        _, vs_minus_v = jax.lax.scan(
            body,
            jnp.zeros_like(bootstrap),
            (deltas, discounts, cs),
            reverse=True,
        )
        vs = values + vs_minus_v
        vs_next = jnp.concatenate([vs[1:], bootstrap[None]], axis=0)
        pg_adv = clipped_rhos * (rewards + discounts * vs_next - values)
        return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)

    def _loss(self, params, traj, init_carry, final_obs_vec):
        dist, values, bootstrap = self._learner_replay(
            params, traj, init_carry, final_obs_vec
        )
        if self._continuous:
            mu, log_std = dist
            pi_logp = normal_logp(traj["action"], mu, log_std)
            entropy = gaussian_entropy(log_std)
        else:
            logits = dist
            logp_all = jax.nn.log_softmax(logits)
            pi_logp = jnp.take_along_axis(
                logp_all, traj["action"][..., None], axis=-1
            )[..., 0]
            entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
        rhos = jnp.exp(pi_logp - traj["mu_logp"])
        vs, pg_adv = self._vtrace(
            values, bootstrap, traj["reward"], traj["done"], rhos
        )
        policy_loss = -jnp.mean(pi_logp * pg_adv)
        value_loss = 0.5 * jnp.mean((vs - values) ** 2)
        total = (
            policy_loss
            + self.icfg.vf_coef * value_loss
            - self.icfg.ent_coef * entropy
        )
        return total, dict(
            policy_loss=policy_loss,
            value_loss=value_loss,
            entropy=entropy,
            mean_rho=rhos.mean(),
        )

    def _rollout_phase(self, state: ImpalaState, data=None):
        """Phase 1: collect one unroll with the (stale) actor params.
        ``rollout_out`` carries the PRE-rollout policy carry alongside
        the segment: the learner replay unrolls the segment from the
        carry the actors STARTED from, not the one they ended with.
        ``_train_step_impl`` is exactly the composition of this and
        :meth:`_update_phase` (bench.py phase attribution; the
        superstep bit-identity tests pin the factoring)."""
        env_states, obs_vec, pcarry, rng, traj = self._rollout(
            state.actor_params, state.env_states, state.obs_vec,
            state.policy_carry, state.rng, data,
        )
        inter = state._replace(
            env_states=env_states, obs_vec=obs_vec, policy_carry=pcarry,
            rng=rng,
        )
        return inter, (traj, state.policy_carry)

    def _update_phase(self, state: ImpalaState, rollout_out, data=None):
        """Phase 2: one V-trace learner update on the collected segment
        (+ guard bookkeeping and the staleness-sync counter)."""
        traj, init_carry = rollout_out
        if data is not None:
            # curriculum quarantine resets come from the ACTIVE tape
            # (XLA CSEs with the rollout's identical reset)
            reset_state, reset_obs = env_core.reset(
                self.env.cfg, self.env.params, data
            )
            reset_vec = self._encode(reset_obs)
        else:
            reset_state, reset_vec = self._reset_state, self._reset_vec
        env_states, obs_vec, pcarry, rng = (
            state.env_states, state.obs_vec, state.policy_carry, state.rng
        )
        (loss, aux), grads = jax.value_and_grad(self._loss, has_aux=True)(
            state.learner_params, traj, init_carry, obs_vec
        )
        updates, new_opt_state = self.optimizer.update(
            grads, state.opt_state, state.learner_params
        )
        new_params = optax.apply_updates(state.learner_params, updates)

        metrics = dict(
            loss=loss,
            mean_reward=traj["reward"].mean(),
            mean_episode_done=traj["done"].mean(),
            **aux,
        )
        if self.icfg.nonfinite_guard:
            from gymfx_tpu.resilience.guards import (
                quarantine_mask,
                select_tree,
                tree_all_finite,
            )

            # IMPALA takes ONE update per step, so the guard is
            # whole-step: a non-finite loss/grad keeps last-good
            # learner params and opt-state bit-for-bit
            ok = jnp.isfinite(loss) & tree_all_finite(grads)
            learner_params = select_tree(
                ok, new_params, state.learner_params
            )
            opt_state = select_tree(ok, new_opt_state, state.opt_state)
            metrics["nonfinite_skips"] = 1.0 - ok.astype(jnp.float32)
            metrics["guard_updates"] = jnp.asarray(1.0, jnp.float32)
            # quarantine envs whose segment or carried state went
            # non-finite (sticky NaN equity, see train/ppo.py)
            poison = quarantine_mask(
                {
                    "reward": traj["reward"],
                    "obs": traj["obs"],
                    "mu_logp": traj["mu_logp"],
                },
                env_axis=1,
            ) | quarantine_mask(
                # NaN-only for carried state: env peak/min/max trackers
                # hold ±inf sentinels by design (core/types.py)
                {"obs_vec": obs_vec, "env_states": env_states},
                env_axis=0, mode="nan",
            )
            carry0 = self.policy.initial_carry(())
            env_states = masked_reset(poison, reset_state, env_states)
            obs_vec = masked_reset(poison, reset_vec, obs_vec)
            pcarry = masked_reset(poison, carry0, pcarry)
            metrics["poisoned_env_resets"] = poison.astype(jnp.float32).sum()
        else:
            learner_params, opt_state = new_params, new_opt_state

        count = state.updates_since_sync + 1
        do_sync = count >= self.icfg.sync_every
        actor_params = jax.tree.map(
            lambda new, old: jnp.where(do_sync, new, old),
            learner_params,
            state.actor_params,
        )
        count = jnp.where(do_sync, 0, count)

        return (
            ImpalaState(
                learner_params, actor_params, opt_state, env_states,
                obs_vec, pcarry, rng, count,
            ),
            metrics,
        )

    def _train_step_impl(self, state: ImpalaState, data=None):
        # phase-named XLA ops for profiler attribution (trace-time
        # metadata only; numerics unchanged) — same scheme as PPO
        with jax.named_scope("rollout"):
            inter, rollout_out = self._rollout_phase(state, data)
        with jax.named_scope("update"):
            return self._update_phase(inter, rollout_out, data)

    # ------------------------------------------------------------------
    def train_step(self, state: ImpalaState):
        return self._train_step(state)

    def train_many(self, state: ImpalaState, k: int):
        """``k`` fused train steps in ONE donated dispatch; metrics come
        back stacked on a leading ``(k,)`` axis (see PPOTrainer.train_many)."""
        return self._train_many(state, int(k))

    def train(self, total_env_steps: int, seed: int = 0, log_every: int = 0,
              initial_state: Optional[ImpalaState] = None,
              initial_params=None,
              *, checkpoint_dir: Optional[str] = None,
              checkpoint_every: int = 0, step_offset: int = 0,
              checkpoint_metadata: Optional[Dict[str, Any]] = None,
              max_consecutive_skips: int = 10,
              preempt_at: Optional[int] = None,
              supersteps_per_dispatch: int = 1,
              telemetry=None,
              mesh_faults=(),
              checkpoint_keep: int = 0):
        if initial_state is not None:
            state = initial_state
            if self.runtime is not None:
                state = self.runtime.place_state(state, self.STATE_PLAN)
        else:
            state = self.init_state(seed)
        if initial_params is not None:
            # params-only warm start: both copies (learner + stale actor)
            state = state._replace(
                learner_params=initial_params,
                actor_params=jax.tree.map(jnp.copy, initial_params),
            )
            if self.runtime is not None:
                # restored host arrays must re-enter the mesh placement
                state = self.runtime.place_state(state, self.STATE_PLAN)
        per_iter = self.icfg.n_envs * self.icfg.unroll
        iters = max(1, int(total_env_steps) // per_iter)
        from gymfx_tpu.resilience.loop import ResilientLoop

        K = max(1, int(supersteps_per_dispatch or 1))
        from gymfx_tpu.train.common import DelayedLogger

        if telemetry is not None:
            logger = telemetry.device_stream(
                "impala", iters=iters, log_every=log_every,
                steps_per_iter=per_iter,
            )
        else:
            logger = DelayedLogger("impala", log_every, iters)
        # mesh health supervision (see PPOTrainer.train): only when a
        # mesh exists AND something observes it
        supervisor = None
        if self.runtime is not None and (mesh_faults or telemetry is not None):
            from gymfx_tpu.parallel.elastic import MeshSupervisor

            supervisor = MeshSupervisor(self.runtime.mesh)
        hooks = ResilientLoop(
            steps_per_iter=per_iter,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
            step_offset=step_offset,
            checkpoint_metadata=checkpoint_metadata,
            max_consecutive_skips=(
                max_consecutive_skips if self.icfg.nonfinite_guard else 0
            ),
            preempt_at=preempt_at,
            loggers=(logger,),
            ledger=telemetry.ledger if telemetry is not None else None,
            recorder=telemetry.recorder if telemetry is not None else None,
            profiler=telemetry.profiler if telemetry is not None else None,
            mesh_faults=tuple(mesh_faults or ()),
            supervisor=supervisor,
            checkpoint_keep=int(checkpoint_keep or 0),
        )
        if telemetry is not None and supervisor is not None:
            from gymfx_tpu.telemetry import register_mesh_health

            register_mesh_health(telemetry.registry, supervisor, name="impala")
        if telemetry is not None and telemetry.profiler is not None:
            from gymfx_tpu.train.common import profiler_workload

            # late-binding over the rebound local (see PPO): resolved
            # at bundle-write time against the live state
            telemetry.profiler.set_workload_source(
                lambda it_start, kk: profiler_workload(
                    self, state, kk, algo="impala",
                    params=state.learner_params,
                    n_envs=self.icfg.n_envs, horizon=self.icfg.unroll,
                )
            )
        if telemetry is not None and telemetry.recorder is not None:
            # the closure reads the rebound local, so a postmortem dump
            # captures the rng key the run DIED with, not the seed key
            telemetry.recorder.set_rng_source(lambda: state.rng)
        if telemetry is not None and hooks.monitor is not None:
            from gymfx_tpu.telemetry import register_resilience

            register_resilience(
                telemetry.registry, monitor=hooks.monitor, name="impala"
            )
        from gymfx_tpu.telemetry import null_tracer

        tracer = telemetry.tracer if telemetry is not None else null_tracer()
        t0 = time.perf_counter()
        metrics: Dict[str, Any] = {}
        it = 0
        while it < iters:
            k = min(K, iters - it)
            capturing = hooks.begin_superstep(it, k)
            # curriculum: one seed-deterministic weighted tape draw per
            # superstep boundary (ledgered as a curriculum_pick row)
            tape = None
            if self.curriculum is not None:
                _ti, _label, tape = self.curriculum.pick(it)
            with tracer.span("train/superstep", algo="impala", it=it, k=k):
                if k == 1:
                    if tape is None:
                        state, metrics = self.train_step(state)
                    else:
                        state, metrics = self._train_step_data(state, tape)
                    guard_metrics = metrics
                else:
                    if tape is None:
                        state, stacked = self.train_many(state, k)
                    else:
                        state, stacked = self._train_many_data(state, tape, k)
                    metrics = jax.tree.map(lambda x: x[-1], stacked)
                    guard_metrics = stacked
            if capturing:
                # sync so the trace window covers the device work —
                # only on capture supersteps (see PPO)
                jax.block_until_ready(state)
            # logger first: an aborting hook flushes the attached logger,
            # which must already hold this superstep's metrics (see PPO)
            logger.after_dispatch(it, k, guard_metrics)
            hooks.after_superstep(
                it, k, guard_metrics,
                lambda: (state._asdict(), state.learner_params),
            )
            it += k
        logger.finish()
        hooks.finish(lambda: (state._asdict(), state.learner_params))
        jax.block_until_ready(state.learner_params)
        dt = time.perf_counter() - t0
        out = {k: float(v) for k, v in metrics.items()}
        out["env_steps_per_sec"] = per_iter * iters / dt
        out["iterations"] = iters
        out["total_env_steps"] = per_iter * iters
        if hooks.last_checkpoint_step is not None:
            out["last_checkpoint_step"] = hooks.last_checkpoint_step
        return state, out


def train_impala_from_config(config: Dict[str, Any]) -> Dict[str, Any]:
    """CLI entry; with ``elastic_resume`` set the run routes through the
    elastic auto-resume controller (parallel/elastic.py, see
    train/ppo.py train_from_config)."""
    from gymfx_tpu.parallel.elastic import elastic_entry

    return elastic_entry(
        _train_impala_from_config, config,
        must_divide=(int(config.get("num_envs", 256) or 256),),
    )


def _train_impala_from_config(config: Dict[str, Any]) -> Dict[str, Any]:
    from gymfx_tpu.train.common import build_train_eval_envs

    env, eval_env = build_train_eval_envs(config)
    # chaos runs: contaminate the TRAINING feed per the fault_profile
    # knob before the trainer closes over it (train/ppo.py)
    from gymfx_tpu.resilience.faults import (
        apply_fault_profile_to_market_data,
        parse_fault_profile,
    )

    profile = parse_fault_profile(config.get("fault_profile"))
    if profile["nan_bars"] or profile["inf_bars"] or profile.get("scengen"):
        env.data = apply_fault_profile_to_market_data(env.data, profile)
    icfg = impala_config_from(config)
    from gymfx_tpu.parallel import mesh_from_config, validate_batch_axis

    mesh = mesh_from_config(config)
    validate_batch_axis(mesh, icfg.n_envs, "num_envs")
    trainer = ImpalaTrainer(env, icfg, mesh=mesh)
    total = int(config.get("train_total_steps", 1_000_000))
    from gymfx_tpu.train.checkpoint import resume_from_config

    resume_state, resume_params, resume_step = resume_from_config(
        config, trainer, ImpalaState
    )
    from gymfx_tpu.telemetry import telemetry_from_config

    telemetry = telemetry_from_config(config)
    if telemetry is not None and telemetry.ledger is not None and (
            resume_state is not None or resume_params is not None):
        telemetry.ledger.record("checkpoint_restore", step=int(resume_step))
        if config.get("elastic_attempt"):
            # elastic re-entry: digest-verified restore re-entering the
            # SURVIVOR mesh plan (see train/ppo.py)
            telemetry.ledger.record(
                "mesh_resume", step=int(resume_step),
                attempt=int(config["elastic_attempt"]), verified=True,
                mesh_shape=dict(mesh.shape) if mesh is not None else None,
            )
    try:
        state, train_metrics = trainer.train(
            total, seed=int(config.get("seed", 0) or 0),
            initial_state=resume_state, initial_params=resume_params,
            checkpoint_dir=config.get("checkpoint_dir"),
            checkpoint_every=int(config.get("checkpoint_every", 0) or 0),
            step_offset=resume_step,
            checkpoint_metadata={"policy": icfg.policy,
                                 "policy_kwargs": dict(icfg.policy_kwargs)},
            max_consecutive_skips=int(
                config.get("guard_max_consecutive_skips", 10) or 0
            ),
            supersteps_per_dispatch=int(
                config.get("supersteps_per_dispatch", 1) or 1
            ),
            preempt_at=profile.get("preempt_at"),
            telemetry=telemetry,
            mesh_faults=profile.get("mesh") or (),
            checkpoint_keep=int(config.get("checkpoint_keep", 0) or 0),
        )
    except BaseException:
        # abort paths (preemption drill, divergence) still seal the run
        # ledger with its run_end row — the postmortem bundle was
        # already dumped by ResilientLoop before the raise
        if telemetry is not None:
            telemetry.close()
        raise
    if telemetry is not None and telemetry.sink is not None:
        telemetry.sink.append({
            "kind": "metrics_snapshot", "algo": "impala",
            "registry": telemetry.registry.snapshot(),
        })
    if telemetry is not None:
        telemetry.close()

    # greedy eval through the shared evaluate() machinery
    from gymfx_tpu.train import ppo as ppo_mod

    from gymfx_tpu.train.common import labeled_eval_summary

    summary = labeled_eval_summary(
        lambda e: ppo_mod.evaluate(
            _EvalShim(trainer, env=e), state.learner_params
        ),
        env, eval_env,
    )
    summary["train_metrics"] = train_metrics
    if mesh is not None:
        summary["mesh_shape"] = dict(mesh.shape)

    ckpt_dir = config.get("checkpoint_dir")
    if ckpt_dir:
        from gymfx_tpu.train.checkpoint import save_checkpoint

        # skip when the periodic auto-checkpoint already landed here
        final_step = resume_step + train_metrics["total_env_steps"]
        if train_metrics.get("last_checkpoint_step") != final_step:
            save_checkpoint(
                ckpt_dir, state._asdict(),
                step=final_step,
                metadata={"policy": icfg.policy,
                          "policy_kwargs": dict(icfg.policy_kwargs)},
                params=state.learner_params,
                keep=int(config.get("checkpoint_keep", 0) or 0),
                protect=(int(resume_step),),
            )
        summary["checkpoint_dir"] = str(ckpt_dir)
    return summary


class _EvalShim:
    """Duck-typed adapter exposing the trainer surface evaluate() needs;
    ``env`` overrides the episode dataset (held-out evaluation)."""

    def __init__(self, trainer: ImpalaTrainer, env=None):
        self.env = env if env is not None else trainer.env
        self.policy = trainer.policy
        self._encode = trainer._encode
        self._policy_forward = trainer._forward
        self._greedy_driver = None
        self._continuous = trainer._continuous
