"""PPO over the multi-pair portfolio environment (BASELINE config 5:
multi-pair portfolio, Transformer policy, pod scale).

Differences from the single-pair trainer (train/ppo.py):
  * actions are per-pair vectors (I,) in {0,1,2,3}\\{3} — the policy
    emits independent categorical heads, one per instrument, and the
    joint log-prob is the sum of per-pair log-probs;
  * observations come from the portfolio obs dict ((window, I) price
    blocks); the Transformer treats bars as tokens with per-pair
    channels, the MLP flattens.
"""
from __future__ import annotations

import time
from typing import Any, Dict, NamedTuple, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from gymfx_tpu.core import portfolio as P
from gymfx_tpu.parallel.runtime import ShardedRuntime, StatePlan
from gymfx_tpu.train.common import masked_reset
from gymfx_tpu.train.policies import RingTransformerEncoder, is_token_policy


def _per_pair_heads(pooled, n_pairs: int):
    """Shared actor-critic head: per-pair categorical logits (I, 3) +
    scalar value — one definition for all portfolio policies."""
    logits = nn.Dense(n_pairs * 3, dtype=jnp.float32)(pooled)
    value = nn.Dense(1, dtype=jnp.float32)(pooled)
    return logits.reshape(*logits.shape[:-1], n_pairs, 3), jnp.squeeze(value, -1)


class PortfolioMLPPolicy(nn.Module):
    n_pairs: int
    hidden: Tuple[int, ...] = (256, 256, 256)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        for width in self.hidden:
            x = nn.tanh(nn.Dense(width, dtype=self.dtype)(x))
        return _per_pair_heads(x, self.n_pairs)


class PortfolioTransformerPolicy(nn.Module):
    """Attention over bars; tokens carry all pairs' features."""

    n_pairs: int
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tokens):
        x = nn.Dense(self.d_model, dtype=self.dtype)(tokens.astype(self.dtype))
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02),
            (tokens.shape[-2], self.d_model), jnp.float32,
        )
        x = x + pos.astype(self.dtype)
        for _ in range(self.n_layers):
            y = nn.LayerNorm(dtype=self.dtype)(x)
            y = nn.MultiHeadDotProductAttention(
                num_heads=self.n_heads, dtype=self.dtype
            )(y, y)
            x = x + y
            y = nn.LayerNorm(dtype=self.dtype)(x)
            y = nn.Dense(self.d_model * 4, dtype=self.dtype)(y)
            y = nn.gelu(y)
            y = nn.Dense(self.d_model, dtype=self.dtype)(y)
            x = x + y
        pooled = jnp.mean(nn.LayerNorm(dtype=self.dtype)(x), axis=-2)
        return _per_pair_heads(pooled, self.n_pairs)


class PortfolioRingTransformerPolicy(nn.Module):
    """Portfolio actor-critic over the shared RingTransformerEncoder:
    attention over bars (tokens carry all pairs' features) that can run
    sequence-parallel ring attention over a 'seq' mesh axis — BASELINE
    config 5's portfolio + Transformer + pod-scale combination.  Use
    train.policies.seq_sharded_forward for the sharded mode; parameters
    are identical in both modes."""

    n_pairs: int
    window: int = 32
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    dtype: Any = jnp.float32
    seq_axis: Any = None
    seq_shards: int = 1
    sp_backend: str = "ring"

    @nn.compact
    def __call__(self, tokens):
        pooled = RingTransformerEncoder(
            window=self.window, d_model=self.d_model, n_heads=self.n_heads,
            n_layers=self.n_layers, dtype=self.dtype,
            seq_axis=self.seq_axis, seq_shards=self.seq_shards,
            sp_backend=self.sp_backend,
        )(tokens)
        return _per_pair_heads(pooled, self.n_pairs)


class PortfolioPPOConfig(NamedTuple):
    n_envs: int = 64
    horizon: int = 64
    epochs: int = 2
    minibatches: int = 4
    gamma: float = 0.99
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    lr: float = 3e-4
    ent_coef: float = 0.01
    vf_coef: float = 0.5
    max_grad_norm: float = 0.5
    policy: str = "mlp"  # mlp | transformer | transformer_ring | transformer_ulysses
    # sample_permute | env_permute — the same schemes as the single-pair
    # trainer (train/ppo.py PPOConfig.minibatch_scheme)
    minibatch_scheme: str = "sample_permute"
    # policy compute dtype (heads stay f32 like the single-pair policies)
    policy_dtype: Any = jnp.float32
    # trajectory-obs storage dtype — THE widest buffers in the repo
    # ((T, N, window*pairs*features) portfolio obs); resolved like the
    # single-pair trainers (train/ppo.resolve_collect_dtype)
    collect_dtype: Any = jnp.float32
    # Adam first-moment dtype (train/ppo.resolve_optimizer_state_dtype):
    # only mu narrows — nu feeds the 1/sqrt(nu) rescale and stays f32
    # alongside the master weights
    opt_state_dtype: Any = jnp.float32


class PortfolioTrainState(NamedTuple):
    params: Any
    opt_state: Any
    env_states: Any
    obs_vec: Any
    rng: Any


def _encode_mlp(obs: Dict[str, Any]):
    return jnp.concatenate(
        [jnp.ravel(obs[k]).astype(jnp.float32) for k in sorted(obs)], axis=0
    )


def _encode_tokens(obs: Dict[str, Any], window: int):
    cols = []
    for k in sorted(obs):
        v = obs[k]
        # portfolio window blocks are 2-D (window, I); 1-D blocks are
        # per-pair/scalar state broadcast along the window (shape tests
        # alone would misfire when n_pairs == window)
        if v.ndim >= 2 and v.shape[0] == window:
            cols.append(v.reshape(window, -1).astype(jnp.float32))
        else:
            flat = jnp.ravel(v).astype(jnp.float32)
            cols.append(jnp.broadcast_to(flat[None, :], (window, flat.shape[0])))
    return jnp.concatenate(cols, axis=-1)


class PortfolioPPOTrainer:
    # shared placement plan (parallel/runtime.ShardedRuntime); the
    # portfolio state has no recurrent carry — otherwise identical to PPO
    STATE_PLAN = StatePlan(
        params=("params",),
        replicated=("opt_state", "rng"),
        batched=("env_states", "obs_vec"),
    )

    def __init__(self, env: P.PortfolioEnvironment, pcfg: PortfolioPPOConfig,
                 mesh: Optional[Any] = None):
        self.env = env
        self.pcfg = pcfg
        self.mesh = mesh
        self.runtime = None if mesh is None else ShardedRuntime(mesh)
        from gymfx_tpu.train.common import validate_minibatch_scheme

        validate_minibatch_scheme(
            pcfg.minibatch_scheme, pcfg.n_envs, pcfg.minibatches,
            horizon=pcfg.horizon,
        )
        n_pairs = env.cfg.n_pairs
        if pcfg.policy == "transformer":
            self.policy = PortfolioTransformerPolicy(
                n_pairs=n_pairs, dtype=pcfg.policy_dtype
            )
        elif pcfg.policy in ("transformer_ring", "transformer_ulysses"):
            self.policy = PortfolioRingTransformerPolicy(
                n_pairs=n_pairs, window=env.cfg.window_size,
                dtype=pcfg.policy_dtype,
                sp_backend="ulysses" if pcfg.policy == "transformer_ulysses"
                else "ring",
            )
        elif pcfg.policy == "mlp":
            self.policy = PortfolioMLPPolicy(
                n_pairs=n_pairs, dtype=pcfg.policy_dtype
            )
        else:
            raise ValueError(
                f"portfolio trainer supports policy "
                f"mlp|transformer|transformer_ring|transformer_ulysses, "
                f"got {pcfg.policy!r}"
            )
        self.optimizer = self._make_optimizer()
        self._reset_state, reset_obs = P.reset(env.cfg, env.params, env.data)
        self._window = env.cfg.window_size
        self._is_transformer = is_token_policy(pcfg.policy)
        self._reset_vec = self._encode(reset_obs)
        self._train_step = jax.jit(self._train_step_impl, donate_argnums=0)
        # curriculum feed (data/tapes.py): the sampler picks a tape per
        # iteration and the step runs against it as a traced argument —
        # donate the state only, never the shared tape
        self.curriculum = getattr(env, "curriculum", None)
        self._train_step_data = jax.jit(self._train_step_impl, donate_argnums=0)

    def _encode(self, obs):
        if self._is_transformer:
            return _encode_tokens(obs, self._window)
        return _encode_mlp(obs)

    # ------------------------------------------------------------------
    def _make_optimizer(self):
        return optax.chain(
            optax.clip_by_global_norm(self.pcfg.max_grad_norm),
            optax.adam(self.pcfg.lr, mu_dtype=self.pcfg.opt_state_dtype),
        )

    def init_state(self, seed: int = 0) -> PortfolioTrainState:
        state = self.init_state_from_key(jax.random.PRNGKey(seed))
        if self.runtime is not None:
            state = self.runtime.place_state(state, self.STATE_PLAN)
        return state

    def init_state_from_key(self, rng) -> PortfolioTrainState:
        rng, k = jax.random.split(rng)
        params = self.policy.init(k, self._reset_vec)
        n = self.pcfg.n_envs
        bcast = lambda x: jnp.broadcast_to(x, (n, *x.shape))  # noqa: E731
        return PortfolioTrainState(
            params=params,
            opt_state=self.optimizer.init(params),
            env_states=jax.tree.map(bcast, self._reset_state),
            obs_vec=bcast(self._reset_vec),
            rng=rng,
        )

    def _forward(self, params, x):
        return self.policy.apply(params, x)

    def _rollout(self, params, env_states, obs_vec, rng, data=None):
        cfg, eparams = self.env.cfg, self.env.params
        explicit_data = data is not None
        if not explicit_data:
            data = self.env.data
        vstep = jax.vmap(P.step, in_axes=(None, None, None, 0, 0))
        vencode = jax.vmap(self._encode)
        fwd = jax.vmap(self._forward, in_axes=(None, 0))
        if explicit_data:
            # curriculum tape: episode restarts must come from the ACTIVE
            # tape, so the reset rides the trace instead of the baked
            # (tape-0) constants
            reset_state, fresh_obs = P.reset(cfg, eparams, data)
            reset_vec = self._encode(fresh_obs)
        else:
            reset_state, reset_vec = self._reset_state, self._reset_vec

        def body(carry, _):
            env_states, obs_vec, rng = carry
            rng, k = jax.random.split(rng)
            logits, value = fwd(params, obs_vec)          # (B, I, 3), (B,)
            actions = jax.random.categorical(k, logits)   # (B, I)
            logp = jnp.take_along_axis(
                jax.nn.log_softmax(logits), actions[..., None], axis=-1
            )[..., 0].sum(axis=-1)                        # joint logp
            env_states2, obs2, reward, done, _info = vstep(
                cfg, eparams, data, env_states, actions
            )
            obs_vec2 = vencode(obs2)
            env_states2 = masked_reset(done, reset_state, env_states2)
            obs_vec2 = masked_reset(done, reset_vec, obs_vec2)
            out = dict(
                # the (T, N, window*pairs*features) obs block is the
                # repo's widest trajectory buffer — stored in the
                # resolved collect dtype (train/ppo.resolve_collect_dtype;
                # bf16 halves its write+read HBM traffic); actions/
                # log-probs/values stay f32 so ratio numerics hold
                obs=obs_vec.astype(self.pcfg.collect_dtype),
                action=actions, logp=logp, value=value,
                reward=reward.astype(jnp.float32), done=done)
            return (env_states2, obs_vec2, rng), out

        (env_states, obs_vec, rng), traj = jax.lax.scan(
            body, (env_states, obs_vec, rng), None, length=self.pcfg.horizon
        )
        _, bootstrap = jax.vmap(self._forward, in_axes=(None, 0))(params, obs_vec)
        return env_states, obs_vec, rng, traj, bootstrap

    def _gae(self, traj, last_value):
        g, lam = self.pcfg.gamma, self.pcfg.gae_lambda

        def body(carry, x):
            adv_next, v_next = carry
            reward, value, done = x
            nonterm = 1.0 - done.astype(jnp.float32)
            delta = reward + g * v_next * nonterm - value
            adv = delta + g * lam * nonterm * adv_next
            return (adv, value), adv

        (_, _), advs = jax.lax.scan(
            body, (jnp.zeros_like(last_value), last_value),
            (traj["reward"], traj["value"], traj["done"]), reverse=True,
        )
        return advs, advs + traj["value"]

    def _loss(self, params, batch):
        logits, value = jax.vmap(self._forward, in_axes=(None, 0))(
            params, batch["obs"]
        )
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["action"][..., None], axis=-1
        )[..., 0].sum(axis=-1)
        ratio = jnp.exp(logp - batch["logp"])
        adv = batch["adv"]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        clip_eps, ent_coef = self._loss_hyper()
        unclipped = ratio * adv
        clipped = jnp.clip(ratio, 1 - clip_eps, 1 + clip_eps) * adv
        policy_loss = -jnp.mean(jnp.minimum(unclipped, clipped))
        value_loss = 0.5 * jnp.mean((value - batch["ret"]) ** 2)
        entropy = -jnp.mean(
            jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1).sum(axis=-1)
        )
        total = (
            policy_loss + self.pcfg.vf_coef * value_loss
            - ent_coef * entropy
        )
        return total, dict(policy_loss=policy_loss, value_loss=value_loss,
                           entropy=entropy)

    def _loss_hyper(self):
        """(clip_eps, ent_coef) for the loss — static here; the PBT core
        overrides with per-member traced values (train/pbt.py)."""
        return self.pcfg.clip_eps, self.pcfg.ent_coef

    def _rollout_phase(self, state: PortfolioTrainState, data=None):
        """Phase 1 of the train step (see train/ppo.py _rollout_phase:
        the split exists for bench phase attribution and is pinned to
        compose bitwise into ``_train_step_impl``)."""
        env_states, obs_vec, rng, traj, bootstrap = self._rollout(
            state.params, state.env_states, state.obs_vec, state.rng, data
        )
        inter = PortfolioTrainState(
            state.params, state.opt_state, env_states, obs_vec, rng
        )
        return inter, (traj, bootstrap)

    def _update_phase(self, state: PortfolioTrainState, rollout_out):
        """Phase 2: GAE + minibatched epochs on a collected trajectory."""
        pcfg = self.pcfg
        traj, bootstrap = rollout_out
        env_states, obs_vec, rng = state.env_states, state.obs_vec, state.rng
        advs, returns = self._gae(traj, bootstrap)
        fields = {
            "obs": traj["obs"],
            "action": traj["action"],
            "logp": traj["logp"],
            "adv": advs,
            "ret": returns,
        }
        from gymfx_tpu.train.common import minibatch_plan

        n_perm, mb, take = minibatch_plan(
            fields, scheme=pcfg.minibatch_scheme, n_envs=pcfg.n_envs,
            horizon=pcfg.horizon, minibatches=pcfg.minibatches,
        )
        params, opt_state = state.params, state.opt_state

        def epoch_body(carry, k):
            params, opt_state = carry
            perm = jax.random.permutation(k, n_perm)

            def mb_body(carry, i):
                params, opt_state = carry
                idx = jax.lax.dynamic_slice_in_dim(perm, i * mb, mb)
                batch = take(idx)
                (loss, aux), grads = jax.value_and_grad(
                    self._loss, has_aux=True
                )(params, batch)
                updates, opt_state = self.optimizer.update(
                    grads, opt_state, params
                )
                params = optax.apply_updates(params, updates)
                return (params, opt_state), (loss, aux)

            (params, opt_state), outs = jax.lax.scan(
                mb_body, (params, opt_state), jnp.arange(pcfg.minibatches)
            )
            return (params, opt_state), outs

        rng, *ks = jax.random.split(rng, pcfg.epochs + 1)
        (params, opt_state), (losses, auxes) = jax.lax.scan(
            epoch_body, (params, opt_state), jnp.stack(ks)
        )
        metrics = dict(
            loss=losses.mean(),
            policy_loss=auxes["policy_loss"].mean(),
            value_loss=auxes["value_loss"].mean(),
            entropy=auxes["entropy"].mean(),
            mean_reward=traj["reward"].mean(),
        )
        return PortfolioTrainState(params, opt_state, env_states, obs_vec, rng), metrics

    def _train_step_impl(self, state: PortfolioTrainState, data=None):
        inter, rollout_out = self._rollout_phase(state, data)
        return self._update_phase(inter, rollout_out)

    def train_step(self, state):
        return self._train_step(state)

    def train(self, total_env_steps: int, seed: int = 0,
              initial_params=None, initial_state=None,
              *, checkpoint_dir: Optional[str] = None,
              checkpoint_every: int = 0, step_offset: int = 0,
              checkpoint_metadata: Optional[Dict[str, Any]] = None,
              preempt_at: Optional[int] = None,
              telemetry=None,
              mesh_faults=(),
              checkpoint_keep: int = 0):
        """``initial_state`` continues a checkpointed run exactly (full
        PortfolioTrainState: params + opt state + env batch + RNG);
        ``initial_params`` is a params-only warm start — the same
        contract as the single-pair trainers (train/ppo.py).

        The resilience hooks carry the same contract as PPOTrainer.train
        (resilience/loop.py): periodic full-state checkpoints with
        retention (``checkpoint_keep``), scripted ``mesh_faults`` and
        mesh health supervision, simulated preemption, and ledger rows —
        with every kwarg unset this loop is the exact pre-elastic one."""
        if initial_state is not None:
            state = initial_state
            if self.runtime is not None:
                state = self.runtime.place_state(state, self.STATE_PLAN)
        else:
            state = self.init_state(seed)
        if initial_params is not None:
            state = state._replace(params=initial_params)
            if self.runtime is not None:
                # restored host arrays must re-enter the mesh placement
                # (model-axis tensor sharding), like the full-state path
                state = self.runtime.place_state(state, self.STATE_PLAN)
        per_iter = self.pcfg.n_envs * self.pcfg.horizon
        iters = max(1, int(total_env_steps) // per_iter)
        from gymfx_tpu.resilience.loop import ResilientLoop

        supervisor = None
        if self.runtime is not None and (mesh_faults or telemetry is not None):
            from gymfx_tpu.parallel.elastic import MeshSupervisor

            supervisor = MeshSupervisor(self.runtime.mesh)
        hooks = ResilientLoop(
            steps_per_iter=per_iter,
            checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every,
            step_offset=step_offset,
            checkpoint_metadata=checkpoint_metadata,
            max_consecutive_skips=0,
            preempt_at=preempt_at,
            ledger=telemetry.ledger if telemetry is not None else None,
            recorder=telemetry.recorder if telemetry is not None else None,
            mesh_faults=tuple(mesh_faults or ()),
            supervisor=supervisor,
            checkpoint_keep=int(checkpoint_keep or 0),
        )
        if telemetry is not None and supervisor is not None:
            from gymfx_tpu.telemetry import register_mesh_health

            register_mesh_health(
                telemetry.registry, supervisor, name="portfolio_ppo"
            )
        t0 = time.perf_counter()
        metrics: Dict[str, Any] = {}
        for it in range(iters):
            hooks.begin_superstep(it, 1)
            if self.curriculum is not None:
                _ti, _label, tape = self.curriculum.pick(it)
                state, metrics = self._train_step_data(state, tape)
            else:
                state, metrics = self.train_step(state)
            hooks.after_superstep(
                it, 1, metrics, lambda: (state._asdict(), state.params)
            )
        hooks.finish(lambda: (state._asdict(), state.params))
        jax.block_until_ready(state.params)
        out = {k: float(v) for k, v in metrics.items()}
        out["env_steps_per_sec"] = per_iter * iters / (time.perf_counter() - t0)
        out["iterations"] = iters
        out["total_env_steps"] = per_iter * iters
        if hooks.last_checkpoint_step is not None:
            out["last_checkpoint_step"] = hooks.last_checkpoint_step
        return state, out


def evaluate(trainer: "PortfolioPPOTrainer", params,
             steps: Optional[int] = None, chunk: int = 128) -> Dict[str, Any]:
    """Greedy (per-pair argmax) portfolio episode -> reference-style
    trading metrics on the ACCOUNT ledger, trade statistics pooled over
    pairs.  Chunked scan (fixed-size jitted chunks) so long episodes
    compile once — the portfolio twin of train/ppo.py evaluate."""
    import math
    import types

    from gymfx_tpu.metrics import compute_analyzers, summarize_trading
    from gymfx_tpu.train.ppo import _step_sharpe

    env = trainer.env
    cfg, eparams, data = env.cfg, env.params, env.data
    steps = int(steps or cfg.n_bars - 1)
    state0, obs0 = P.reset(cfg, eparams, data)
    vec0 = trainer._encode(obs0)

    @jax.jit
    def run_chunk(params, st, vec):
        def body(carry, _):
            st, vec = carry
            logits, _v = trainer._forward(params, vec)
            action = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            st2, obs2, _r, done, info = P.step(cfg, eparams, data, st, action)
            return (st2, trainer._encode(obs2)), (info["equity"], done)

        (st, vec), outs = jax.lax.scan(body, (st, vec), None, length=chunk)
        return st, vec, outs

    state, vec = state0, vec0
    eqs, dones = [], []
    for _ in range(max(1, math.ceil(steps / chunk))):
        state, vec, (eq, dn) = run_chunk(params, state, vec)
        eqs.append(np.asarray(eq, np.float64))
        dones.append(np.asarray(dn, bool))
    equity = np.concatenate(eqs)[:steps]
    done = np.concatenate(dones)[:steps]

    pairs, acct = jax.device_get((state.pairs, state.acct))
    agg = types.SimpleNamespace(
        trade_count=int(np.sum(pairs.trade_count)),
        trades_won=int(np.sum(pairs.trades_won)),
        trades_lost=int(np.sum(pairs.trades_lost)),
        trade_pnl_sum=float(np.sum(pairs.trade_pnl_sum)),
        trade_pnl_sumsq=float(np.sum(pairs.trade_pnl_sumsq)),
        max_drawdown_pct=float(acct.max_drawdown_pct),
        max_drawdown_money=float(acct.max_drawdown_money),
    )
    ts = env.timestamps[1 : steps + 1]
    analyzers = compute_analyzers(equity=equity, done=done, state=agg,
                                  timestamps=ts)
    final_eq = float(equity[int(np.argmax(done))] if done.any() else equity[-1])
    summary = summarize_trading(
        initial_cash=float(eparams.acct.initial_cash),
        final_equity=final_eq,
        analyzers=analyzers,
        config=env.config,
    )
    tf_hours = env.timeframe_hours or (1.0 / 60.0)
    summary["sharpe_ratio_steps"] = _step_sharpe(equity, tf_hours)
    summary["pairs"] = list(env.pairs)
    return summary


def eval_portfolio_policy_from_config(config: Dict[str, Any]) -> Dict[str, Any]:
    """CLI ``driver_mode=policy`` with ``portfolio_files``: greedy
    evaluation of a checkpointed portfolio policy via the shared
    skeleton (train/common.py eval_checkpointed_policy), with the
    pair-set checked against the checkpoint (positional heads)."""
    from gymfx_tpu.train.common import (
        build_portfolio_train_eval_envs,
        eval_checkpointed_policy,
    )

    def resolve(meta, cfg):
        stored = str(meta.get("policy") or "")
        if not cfg.get("policy") and stored.startswith("portfolio_"):
            cfg["policy"] = stored[len("portfolio_"):]

    def validate(meta, env):
        if meta.get("pairs") and list(meta["pairs"]) != list(env.pairs):
            raise ValueError(
                f"checkpoint was trained on pairs {meta['pairs']}, config "
                f"loads {env.pairs} — the per-pair heads are positional"
            )

    return eval_checkpointed_policy(
        config,
        build_envs=build_portfolio_train_eval_envs,
        make_trainer=lambda env, cfg: PortfolioPPOTrainer(
            env, PortfolioPPOConfig(policy=str(cfg.get("policy") or "mlp"))
        ),
        evaluate_fn=lambda tr, params, steps: evaluate(tr, params, steps=steps),
        resolve_policy=resolve,
        validate=validate,
    )


def train_portfolio_from_config(config: Dict[str, Any]) -> Dict[str, Any]:
    """CLI entry; with ``elastic_resume`` set the run routes through the
    elastic auto-resume controller (parallel/elastic.py, see
    train/ppo.py train_from_config)."""
    from gymfx_tpu.parallel.elastic import elastic_entry

    return elastic_entry(
        _train_portfolio_from_config, config,
        must_divide=(int(config.get("num_envs", 64) or 64),),
    )


def _train_portfolio_from_config(config: Dict[str, Any]) -> Dict[str, Any]:
    from gymfx_tpu.train.common import (
        build_portfolio_train_eval_envs,
        labeled_eval_summary,
    )

    env, eval_env = build_portfolio_train_eval_envs(config)
    from gymfx_tpu.train.common import resolve_minibatch_scheme
    from gymfx_tpu.train.ppo import (
        resolve_collect_dtype,
        resolve_optimizer_state_dtype,
    )

    n_envs = int(config.get("num_envs", 64) or 64)
    resolve_minibatch_scheme(
        config, n_envs, int(config.get("ppo_minibatches", 4))
    )
    pdt = {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[
        str(config.get("policy_dtype", "float32"))
    ]
    pcfg = PortfolioPPOConfig(
        n_envs=n_envs,
        horizon=int(config.get("ppo_horizon", 64)),
        epochs=int(config.get("ppo_epochs", 2)),
        minibatches=int(config.get("ppo_minibatches", 4)),
        lr=float(config.get("learning_rate", 3e-4)),
        policy=str(config.get("policy") or "mlp"),
        minibatch_scheme=str(
            config.get("ppo_minibatch_scheme", "env_permute")
        ),
        policy_dtype=pdt,
        collect_dtype=resolve_collect_dtype(config, pdt),
        opt_state_dtype=resolve_optimizer_state_dtype(config),
    )
    from gymfx_tpu.parallel import mesh_from_config, validate_batch_axis

    mesh = mesh_from_config(config)
    validate_batch_axis(mesh, pcfg.n_envs, "num_envs")
    trainer = PortfolioPPOTrainer(env, pcfg, mesh=mesh)
    from gymfx_tpu.train.checkpoint import resume_from_config

    # full-state checkpoints continue the exact trajectory (opt moments,
    # env batch, RNG); legacy params-only ones warm-start — the same
    # resume contract as PPO/IMPALA (r4 closes the portfolio gap)
    resume_state, resume_params, resume_step = resume_from_config(
        config, trainer, PortfolioTrainState
    )
    # the elastic/resilience wiring rides the inherited PPOTrainer.train
    # loop: scripted mesh faults, periodic checkpoints, retention, and
    # the mesh_resume ledger row on an elastic re-entry
    from gymfx_tpu.resilience.faults import parse_fault_profile
    from gymfx_tpu.telemetry import telemetry_from_config

    profile = parse_fault_profile(config.get("fault_profile"))
    telemetry = telemetry_from_config(config)
    if telemetry is not None and telemetry.ledger is not None and (
            resume_state is not None or resume_params is not None):
        telemetry.ledger.record("checkpoint_restore", step=int(resume_step))
        if config.get("elastic_attempt"):
            telemetry.ledger.record(
                "mesh_resume", step=int(resume_step),
                attempt=int(config["elastic_attempt"]), verified=True,
                mesh_shape=dict(mesh.shape) if mesh is not None else None,
            )
    try:
        state, metrics = trainer.train(
            int(config.get("train_total_steps", 1_000_000)),
            seed=int(config.get("seed", 0) or 0),
            initial_params=resume_params, initial_state=resume_state,
            checkpoint_dir=config.get("checkpoint_dir"),
            checkpoint_every=int(config.get("checkpoint_every", 0) or 0),
            step_offset=resume_step,
            checkpoint_metadata={"policy": f"portfolio_{pcfg.policy}",
                                 "pairs": env.pairs},
            preempt_at=profile.get("preempt_at"),
            telemetry=telemetry,
            mesh_faults=profile.get("mesh") or (),
            checkpoint_keep=int(config.get("checkpoint_keep", 0) or 0),
        )
    except BaseException:
        if telemetry is not None:
            telemetry.close()
        raise
    if telemetry is not None:
        telemetry.close()
    # held-out evaluation (VERDICT r4 item #3): greedy episode on the
    # aligned bars the agent never trained on, in-sample riding along
    summary = labeled_eval_summary(
        lambda e: evaluate(
            trainer if e is None else PortfolioPPOTrainer(e, pcfg),
            state.params,
        ),
        env, eval_env,
    )
    summary.update({"mode": "training", "trainer": "portfolio_ppo",
                    "pairs": env.pairs, "train_metrics": metrics})
    if mesh is not None:
        summary["mesh_shape"] = dict(mesh.shape)
    ckpt_dir = config.get("checkpoint_dir")
    if ckpt_dir:
        from gymfx_tpu.train.checkpoint import save_checkpoint

        # composite format: the FULL train state for exact resume plus a
        # standalone params item for cheap evaluation restores; the step
        # is cumulative so a resumed run advances past the loaded step
        final_step = resume_step + metrics["total_env_steps"]
        if metrics.get("last_checkpoint_step") != final_step:
            save_checkpoint(
                ckpt_dir, state._asdict(),
                step=final_step,
                metadata={"policy": f"portfolio_{pcfg.policy}",
                          "pairs": env.pairs},
                params=state.params,
                keep=int(config.get("checkpoint_keep", 0) or 0),
                protect=(int(resume_step),),
            )
        summary["checkpoint_dir"] = str(ckpt_dir)
    return summary
