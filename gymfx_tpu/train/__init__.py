"""Training subsystem — new capability mandated by the north star.

The reference is env-only ("env-only, agent-agnostic", reference
app/cli.py:6); agents attach externally through reset/step.  Here the
actor-learner is part of the framework: rollout collection is fused
into the env scan on-device, and gradients all-reduce over the mesh
(ICI) instead of leaving the chip.
"""
from gymfx_tpu.train import impala, pbt, policies, portfolio_ppo, ppo  # noqa: F401
