"""Policy networks (flax.linen): MLP, LSTM, Transformer.

Model families follow BASELINE.json's config ladder: 3-layer MLP
(config 3), recurrent LSTM (config 4), Transformer (config 5).  All
are actor-critic heads over the Dict observation; observations are
flattened in a fixed key order so the same policies drive any obs
layout (price windows, feature windows, stage-B/calendar blocks).

TPU notes: matmul-heavy bodies sized for the MXU; parameters can be
sharded over a 'model' mesh axis (see train/ppo.py shardings);
compute dtype is configurable (bfloat16 on TPU, f32 reference path).
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp


def flatten_obs(obs: Dict[str, Any]) -> Any:
    """Dict obs -> flat feature vector (sorted key order, stable)."""
    parts = [jnp.ravel(obs[k]).astype(jnp.float32) for k in sorted(obs.keys())]
    return jnp.concatenate(parts, axis=0)


def obs_size(obs: Dict[str, Any]) -> int:
    return int(sum(int(jnp.size(v)) for v in obs.values()))


class MLPPolicy(nn.Module):
    """3-layer MLP actor-critic (BASELINE config 3)."""

    n_actions: int = 3
    hidden: Sequence[int] = (256, 256, 256)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        for width in self.hidden:
            x = nn.Dense(width, dtype=self.dtype)(x)
            x = nn.tanh(x)
        logits = nn.Dense(self.n_actions, dtype=jnp.float32)(x)
        value = nn.Dense(1, dtype=jnp.float32)(x)
        return logits, jnp.squeeze(value, axis=-1)

    def initial_carry(self, batch_shape=()):
        return ()

    def apply_seq(self, params, x, carry):
        logits, value = self.apply(params, x)
        return logits, value, carry


class LSTMPolicy(nn.Module):
    """Recurrent actor-critic; the cell carry threads through the env
    scan (BASELINE config 4)."""

    n_actions: int = 3
    hidden: int = 256
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, carry):
        x = x.astype(self.dtype)
        x = nn.tanh(nn.Dense(self.hidden, dtype=self.dtype)(x))
        cell = nn.OptimizedLSTMCell(self.hidden, dtype=self.dtype)
        carry, x = cell(carry, x)
        logits = nn.Dense(self.n_actions, dtype=jnp.float32)(x)
        value = nn.Dense(1, dtype=jnp.float32)(x)
        return logits, jnp.squeeze(value, axis=-1), carry

    def initial_carry(self, batch_shape=()):
        # (c, h) zeros — what LSTMCell.initialize_carry returns, built
        # directly (flax modules cannot be instantiated outside a scope).
        # Two distinct buffers: aliased leaves break jit donation.
        return (
            jnp.zeros((*batch_shape, self.hidden), dtype=self.dtype),
            jnp.zeros((*batch_shape, self.hidden), dtype=self.dtype),
        )

    def apply_seq(self, params, x, carry):
        return self.apply(params, x, carry)


class TransformerPolicy(nn.Module):
    """Attention over the observation window (BASELINE config 5).

    Expects the obs dict to contain at least one (window, k) block
    ('features') or (window,) blocks ('prices'/'returns'); scalar
    blocks are broadcast as extra tokens.  Attention heads and MLP
    widths are chosen to tile the MXU (dims multiples of 128).
    """

    n_actions: int = 3
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tokens):
        # tokens: (window, token_dim)
        x = nn.Dense(self.d_model, dtype=self.dtype)(tokens.astype(self.dtype))
        n = x.shape[-2]
        pos = self.param(
            "pos_embed", nn.initializers.normal(0.02), (n, self.d_model), jnp.float32
        )
        x = x + pos.astype(self.dtype)
        for _ in range(self.n_layers):
            y = nn.LayerNorm(dtype=self.dtype)(x)
            y = nn.MultiHeadDotProductAttention(
                num_heads=self.n_heads, dtype=self.dtype
            )(y, y)
            x = x + y
            y = nn.LayerNorm(dtype=self.dtype)(x)
            y = nn.Dense(self.d_model * 4, dtype=self.dtype)(y)
            y = nn.gelu(y)
            y = nn.Dense(self.d_model, dtype=self.dtype)(y)
            x = x + y
        x = nn.LayerNorm(dtype=self.dtype)(x)
        pooled = jnp.mean(x, axis=-2)
        logits = nn.Dense(self.n_actions, dtype=jnp.float32)(pooled)
        value = nn.Dense(1, dtype=jnp.float32)(pooled)
        return logits, jnp.squeeze(value, axis=-1)

    def initial_carry(self, batch_shape=()):
        return ()

    def apply_seq(self, params, tokens, carry):
        logits, value = self.apply(params, tokens)
        return logits, value, carry


def tokens_from_obs(obs: Dict[str, Any], window: int) -> Any:
    """Obs dict -> (window, token_dim) token sequence for the
    TransformerPolicy: window-aligned blocks become per-bar token
    features; scalar blocks broadcast along the window."""
    cols = []
    for k in sorted(obs.keys()):
        v = obs[k]
        if v.ndim >= 1 and v.shape[0] == window:
            cols.append(v.reshape(window, -1).astype(jnp.float32))
        else:
            flat = jnp.ravel(v).astype(jnp.float32)
            cols.append(jnp.broadcast_to(flat[None, :], (window, flat.shape[0])))
    return jnp.concatenate(cols, axis=-1)


class ContinuousMLPPolicy(nn.Module):
    """Gaussian actor-critic for action_space_mode=continuous: emits the
    mean of a Normal over the Box(-1,1,(1,)) action (state-independent
    learned log-std); the env thresholds the sampled value into
    hold/long/short (reference app/env.py:343-355)."""

    hidden: Sequence[int] = (256, 256, 256)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        for width in self.hidden:
            x = nn.tanh(nn.Dense(width, dtype=self.dtype)(x))
        mu = nn.tanh(nn.Dense(1, dtype=jnp.float32)(x))
        log_std = self.param("log_std", nn.initializers.constant(-0.5), (1,))
        value = nn.Dense(1, dtype=jnp.float32)(x)
        return (jnp.squeeze(mu, -1), jnp.broadcast_to(log_std[0], mu.shape[:-1])), jnp.squeeze(value, -1)

    def initial_carry(self, batch_shape=()):
        return ()

    def apply_seq(self, params, x, carry):
        dist, value = self.apply(params, x)
        return dist, value, carry


def make_policy(name: str, n_actions: int = 3, dtype: Any = jnp.float32, **kw):
    if name == "mlp_continuous":
        return ContinuousMLPPolicy(dtype=dtype, **kw)
    if name == "mlp":
        return MLPPolicy(n_actions=n_actions, dtype=dtype, **kw)
    if name == "lstm":
        return LSTMPolicy(n_actions=n_actions, dtype=dtype, **kw)
    if name == "transformer":
        return TransformerPolicy(n_actions=n_actions, dtype=dtype, **kw)
    raise ValueError(f"unknown policy {name!r}")
